"""L2: (p,c) backtracking-attractor combinatorics and factor tensors.

The reference evaluates the attractor-closure / trajectory-consistency /
endpoint indicators (`atr_condition`/`traj_condition`/`attr_fix`,
`HPR_pytorch_RRG.py:14-39`, `ER_BDCM_entropy.ipynb:66-111`) one scalar combo
at a time inside its DP loops, except for the notebook's precomputed ``A[d]`` /
``Ai[d]`` tensors (`ipynb:339-345,360-367`). Here *everything* is table-driven
(the notebook's strategy, taken to its conclusion): the full factor tensors
are built host-side, vectorized over all ``(x_i, x_j, ρ)`` combos at once, for
**any** (rule, tie) pair — the conditions are expressed through the same
closed-form ``R·sign(2·total + C·prev)`` update as the dynamics kernel
(:mod:`graphdyn.ops.dynamics`), so the swappable-dynamics axis of the design
(`HPR_pytorch_RRG.py:22,25`) extends to the cavity method for free.

Conventions (all matching the reference):

- Trajectories live in {1, 0} with 1 ↔ spin +1; the enumeration order is
  ``itertools.product([1, 0], repeat=T)`` — index 0 is the all-ones
  trajectory, exactly the reference's ``order`` encoding
  (`HPR_pytorch_RRG.py:66-76`: ``num_combs−1−int(binary)``).
- ρ-lattices store *counts of +1 neighbors* ``0..d``; the signed sum of ``d``
  {±1} trajectories is ``2ρ − d`` (`ipynb:291`, `HPR_pytorch_RRG.py:212`).
- λ-tilt ``exp(−λ·x_i(0))`` is applied at contraction time, not baked into the
  tensors (comment at `ipynb:285`: built once at λ=0).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from graphdyn.ops.dynamics import rule_coefficients


@lru_cache(maxsize=None)
def trajectories01(T: int) -> np.ndarray:
    """All {1,0} trajectories, shape [2^T, T], in product([1,0]) order
    (index 0 = all-ones)."""
    K = 2**T
    out = np.empty((K, T), dtype=np.int64)
    for t in range(T):
        period = 2 ** (T - 1 - t)
        out[:, t] = 1 - (np.arange(K) // period) % 2
    return out


def order_index(xi01: np.ndarray, xj01: np.ndarray) -> int:
    """Combo index of a trajectory pair in the reference's column encoding
    (`HPR_pytorch_RRG.py:66-74`): position of (xi, xj) in the double
    product([1,0]) enumeration."""
    bits = 1 - np.concatenate([np.asarray(xi01), np.asarray(xj01)])
    return int("".join(map(str, bits)), 2)


@lru_cache(maxsize=None)
def rho_lattice(n_msgs: int, T: int) -> np.ndarray:
    """Mixed-radix enumeration of ρ ∈ {0..n_msgs}^T, shape [(n_msgs+1)^T, T].

    Index r = Σ_t ρ_t·(n_msgs+1)^(T−1−t); axis t of the *tensor-shaped* DP
    state corresponds to ρ_t, matching the notebook's trailing-axes layout
    (`ipynb:91-93` cell comments).
    """
    base = n_msgs + 1
    M = base**T
    out = np.empty((M, T), dtype=np.int64)
    for t in range(T):
        out[:, t] = (np.arange(M) // base ** (T - 1 - t)) % base
    return out


def _step_out(total_pm, prev_pm, R_coef, C_coef):
    """Closed-form synchronous update (see ops.dynamics): what x(t+1) must be
    given the inclusive neighbor sum ``total_pm`` and x(t)=``prev_pm``."""
    return R_coef * np.sign(2 * total_pm + C_coef * prev_pm)


def condition_tensors(
    n_msgs: int,
    p: int,
    c: int,
    *,
    include_xj: bool,
    rule: str = "majority",
    tie: str = "stay",
):
    """Vectorized atr/traj indicators over the full (xi, xj, ρ) grid.

    Returns (atr, traj) with shape [K, K, M] when ``include_xj`` (edge
    variant: total = ρ + x_j, `ipynb:66-81`) else [K, M] (node variant:
    total = ρ, `ipynb:83-98`). ρ counts exclude x_j in the edge variant.
    """
    T = p + c
    R_coef, C_coef = rule_coefficients(rule, tie)
    X = 2 * trajectories01(T) - 1          # [K, T] in ±1
    Rho = 2 * rho_lattice(n_msgs, T) - n_msgs  # [M, T] signed sums

    if include_xj:
        xi = X[:, None, None, :]
        xj = X[None, :, None, :]
        rho = Rho[None, None, :, :]
        total = rho + xj
    else:
        xi = X[:, None, :]
        rho = Rho[None, :, :]
        total = rho

    shape = np.broadcast_shapes(total.shape[:-1], xi.shape[:-1])
    traj = np.ones(shape, dtype=bool)
    for t in range(T - 1):
        out_t = _step_out(total[..., t], xi[..., t], R_coef, C_coef)
        traj = traj & (xi[..., t + 1] == out_t)
    out_T = _step_out(total[..., T - 1], xi[..., T - 1], R_coef, C_coef)
    atr = xi[..., p] == out_T
    return atr, traj


def attr_mask(T: int, attr_value: int) -> np.ndarray:
    """bool[K]: trajectory endpoint pinned to the attractor value
    (`attr_fix`, `HPR_pytorch_RRG.py:34-36`)."""
    X = 2 * trajectories01(T) - 1
    return X[:, T - 1] == attr_value


def x0_pm(T: int) -> np.ndarray:
    """±1 initial value of each trajectory, [K] — the λ-tilt couples to this."""
    return 2 * trajectories01(T)[:, 0] - 1


def edge_factor_tensor(
    n_msgs: int,
    p: int,
    c: int,
    attr_value: int = 1,
    rule: str = "majority",
    tie: str = "stay",
) -> np.ndarray:
    """λ=0 edge factor A[x_i, x_j, ρ], shape [K, K, (n_msgs+1)^T]
    (= the notebook's ``A[d]``, `ipynb:285-291`; HPR's inline ``A_i_sums``,
    `HPR_pytorch_RRG.py:38-39` with the λ term factored out)."""
    T = p + c
    atr, traj = condition_tensors(n_msgs, p, c, include_xj=True, rule=rule, tie=tie)
    fix = attr_mask(T, attr_value)
    # host-built factor tensors stay f64 like the reference; BDCMData
    # casts to the message dtype at transfer time
    return (atr & traj & fix[:, None, None]).astype(np.float64)  # graftlint: disable=GD004  host staging


def node_factor_tensor(
    n_msgs: int,
    p: int,
    c: int,
    attr_value: int = 1,
    rule: str = "majority",
    tie: str = "stay",
) -> np.ndarray:
    """λ=0 node factor Ai[x_i, ρ] over all-neighbor sums, [K, (n_msgs+1)^T]
    (= the notebook's ``Ai[d]``, `ipynb:309-313`)."""
    T = p + c
    atr, traj = condition_tensors(n_msgs, p, c, include_xj=False, rule=rule, tie=tie)
    fix = attr_mask(T, attr_value)
    # graftlint: disable-next-line=GD004  host staging (cast at transfer)
    return (atr & traj & fix[:, None]).astype(np.float64)


def leaf_factor_tensor(
    p: int,
    c: int,
    attr_value: int = 1,
    rule: str = "majority",
    tie: str = "stay",
) -> np.ndarray:
    """λ=0 message from a leaf node i to its unique neighbor j: the edge
    factor with an empty ρ (zero signed sum), [K, K]
    (`ipynb:403-417`: d=0 edges get the normalized bare factor)."""
    A = edge_factor_tensor(0, p, c, attr_value, rule, tie)
    return A[:, :, 0]
