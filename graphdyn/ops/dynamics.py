"""Synchronous spin dynamics on graphs — the L3 hot kernel.

The reference implements the majority/always-stay step three times
(`SA_RRG.py:18-20`, `HPR_pytorch_RRG.py:169-171`, degree-grouped as
``np.sign(2*sums + s)`` at `ER_BDCM_entropy.ipynb:113-117`) and sketches
minority / always-change variants in comments (`HPR_pytorch_RRG.py:22,25`,
`ipynb:70,74`). Here the rule axis is explicit and closed-form:

    out = R * sign(2 * Σ_{j∈∂i} s_j + C * s_i)

with ``R = -1`` for minority dynamics (else ``+1``) and
``C = R * (+1 for tie→stay, -1 for tie→change)``. The ``2Σ + C·s`` trick folds
tie-breaking into a single integer sign, so one fused gather→sum→sign XLA
program covers every (rule, tie) pair and every degree sequence (ghost-padded
neighbor rows contribute 0). Equivalence with the reference's
``(1-|sign Σ|)·s + sign Σ`` form is covered by tests.

Spins are int8 on device (HBM-bandwidth-bound workload: 1 byte/spin), neighbor
sums int32. All functions are jit/vmap-friendly: static shapes, `lax` control
flow only.
"""

from __future__ import annotations

import enum
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.analysis.contracts import contract


class Rule(str, enum.Enum):
    MAJORITY = "majority"
    MINORITY = "minority"


class TieBreak(str, enum.Enum):
    STAY = "stay"
    CHANGE = "change"


def rule_coefficients(rule: Rule | str, tie: TieBreak | str) -> tuple[int, int]:
    """(R, C) such that one step is ``R * sign(2*sums + C*s)``."""
    rule = Rule(rule)
    tie = TieBreak(tie)
    R = -1 if rule == Rule.MINORITY else 1
    C = R * (1 if tie == TieBreak.STAY else -1)
    return R, C


def neighbor_sums(nbr: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """Σ_{j∈∂i} s_j via the ghost-padded gather. ``s``: int8[n] (±1),
    ``nbr``: int32[n, dmax] padded with n. Returns int32[n]."""
    s_ext = jnp.concatenate([s.astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
    return jnp.sum(jnp.take(s_ext, nbr, axis=0), axis=1)


def step_spins(
    nbr: jnp.ndarray,
    s: jnp.ndarray,
    rule: Rule | str = Rule.MAJORITY,
    tie: TieBreak | str = TieBreak.STAY,
) -> jnp.ndarray:
    """One synchronous update. Exact integer arithmetic, any degree."""
    R, C = rule_coefficients(rule, tie)
    sums = neighbor_sums(nbr, s)
    t = 2 * sums + C * s.astype(jnp.int32)
    return (R * jnp.sign(t)).astype(s.dtype)


def batched_rollout_impl(nbr, s, steps: int, R_coef: int, C_coef: int,
                         gather: str = "fused"):
    """Roll a batch ``s: int8[R, n]`` for ``steps`` synchronous updates.

    The framework's single hot kernel, shared by the SA solver and the
    benchmark so BASELINE numbers measure the shipped code path. Call inside
    jit; for a standalone jitted version use :func:`batched_rollout`.

    ``gather`` selects the HBM schedule (identical results — integer sums
    are order-exact):

    - ``"fused"`` (default): one gather producing ``[R, n, dmax]``, widened
      int32, then row-summed. Wins on CPU (cache-backed; measured ~1.3× vs
      per_slot at the smoke shape) and is the historical schedule.
    - ``"per_slot"``: one **int8** ``[R, n]`` gather per neighbor slot
      accumulated straight into the int32 sum — no ``[R, n, dmax]`` buffer,
      and the gathered bytes stay 1/4 the size (the packed kernel's
      ``per_slot`` reasoning, ARCHITECTURE.md roofline). Candidate TPU
      default, pending on-chip A/B (scripts/tpu_bench_session.sh).
    """
    dmax = nbr.shape[-1]
    n = s.shape[-1]
    if steps <= 0:
        return s

    # the ghost column rides IN the loop carry (see ops.packed.packed_rollout:
    # an in-body concatenate costs an extra full read+write of the state per
    # step). Ghost column n is self-neighbored: its sums and spin are 0, so
    # sign keeps it 0 under every (rule, tie) — no per-step forcing needed.
    nbr_ext = jnp.concatenate([nbr, jnp.full((1, dmax), n, nbr.dtype)], axis=0)

    if gather == "per_slot":
        def neighbor_sums(sb_ext):
            sums = jnp.zeros(sb_ext.shape, jnp.int32)
            for j in range(dmax):
                sums = sums + jnp.take(
                    sb_ext, nbr_ext[:, j], axis=1
                ).astype(jnp.int32)
            return sums
    elif gather == "fused":
        flat_nbr = nbr_ext.reshape(-1)

        def neighbor_sums(sb_ext):
            g = jnp.take(sb_ext.astype(jnp.int32), flat_nbr, axis=1).reshape(
                sb_ext.shape[0], n + 1, dmax
            )
            return g.sum(axis=2)
    else:
        raise ValueError(f"gather must be 'fused' or 'per_slot', got {gather!r}")

    def body(_, sb_ext):
        sums = neighbor_sums(sb_ext)
        return (
            R_coef * jnp.sign(2 * sums + C_coef * sb_ext.astype(jnp.int32))
        ).astype(jnp.int8)

    s_ext0 = jnp.concatenate(
        [s, jnp.zeros((s.shape[0], 1), s.dtype)], axis=1
    )
    return lax.fori_loop(0, steps, body, s_ext0)[:, :n]


@partial(jax.jit, static_argnames=("steps", "rule", "tie", "gather"))
@contract(nbr="int32[n,d]", s="int8[r,n]", ret="int8[r,n]")
# the fused/per_slot A/B path and the numpy-parity tests roll the SAME s
# through multiple calls; donating s would invalidate their input buffer
# graftlint: disable-next-line=GD006  A/B callers reuse the input state
def batched_rollout(nbr, s, steps: int, rule: str = "majority",
                    tie: str = "stay", gather: str = "fused"):
    R_coef, C_coef = rule_coefficients(rule, tie)
    return batched_rollout_impl(nbr, s, steps, R_coef, C_coef, gather)


@partial(jax.jit, static_argnames=("steps", "rule", "tie"))
@contract(nbr="int32[n,d]", s0="int8[n]", ret="int8[n]")
# run_dynamics passes the caller's (asarray-identity) spins; oracles then
# replay the same buffer — donation would invalidate it under them
# graftlint: disable-next-line=GD006  callers replay the input spins
def _run_jax(nbr, s0, steps: int, rule: str, tie: str):
    if steps <= 0:
        return s0

    def body(_, s):
        return step_spins(nbr, s, rule, tie)

    return lax.fori_loop(0, steps, body, s0)


def _run_numpy(nbr, s0, steps, rule, tie):
    R, C = rule_coefficients(rule, tie)
    nbr = np.asarray(nbr)
    s = np.asarray(s0).astype(np.int64)
    s_ext = np.zeros(nbr.shape[0] + 1, dtype=np.int64)
    for _ in range(steps):
        s_ext[:-1] = s
        sums = s_ext[nbr].sum(axis=1)
        s = R * np.sign(2 * sums + C * s)
    return s.astype(np.asarray(s0).dtype)


def _run_torch(nbr, s0, steps, rule, tie):
    import torch

    R, C = rule_coefficients(rule, tie)
    nbr_t = torch.as_tensor(np.asarray(nbr), dtype=torch.long)
    s = torch.as_tensor(np.asarray(s0), dtype=torch.long)
    s_ext = torch.zeros(nbr_t.shape[0] + 1, dtype=torch.long)
    for _ in range(steps):
        s_ext[:-1] = s
        sums = s_ext[nbr_t].sum(dim=1)
        s = R * torch.sign(2 * sums + C * s)
    return s.numpy().astype(np.asarray(s0).dtype)


def run_dynamics(
    graph,
    init_spins,
    steps: int,
    rule: Rule | str = Rule.MAJORITY,
    tie: TieBreak | str = TieBreak.STAY,
    backend: str = "jax_tpu",
):
    """The BASELINE.json entry point: roll ``steps`` synchronous updates.

    ``graph`` is a ``graphdyn.Graph`` or a raw neighbor table; ``backend`` is
    one of ``{'cpu', 'torch', 'jax_tpu', 'jax'}`` — 'cpu' is the numpy parity
    oracle, 'torch' the torch oracle; both JAX names dispatch to the jitted
    path on whatever devices JAX sees.
    """
    nbr = graph.nbr if hasattr(graph, "nbr") else graph
    rule, tie = Rule(rule).value, TieBreak(tie).value
    if backend == "cpu":
        return _run_numpy(nbr, init_spins, steps, rule, tie)
    if backend == "torch":
        return _run_torch(nbr, init_spins, steps, rule, tie)
    if backend in ("jax", "jax_tpu"):
        s = jnp.asarray(init_spins)
        if s.ndim == 2:  # replica batch -> the shared batched hot kernel
            return batched_rollout(jnp.asarray(nbr), s, steps, rule, tie)
        return _run_jax(jnp.asarray(nbr), s, steps, rule, tie)
    raise ValueError(f"unknown backend {backend!r}")


def end_state(
    graph,
    s0,
    p: int,
    c: int,
    rule: Rule | str = Rule.MAJORITY,
    tie: TieBreak | str = TieBreak.STAY,
    backend: str = "jax_tpu",
):
    """``s_endstate``: p+c-1 synchronous steps (`SA_RRG.py:23-26`)."""
    return run_dynamics(graph, s0, p + c - 1, rule, tie, backend)
