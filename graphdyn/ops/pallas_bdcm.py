"""Pallas TPU kernel: fused BDCM neighbor-DP + factor contraction.

The XLA sweep (:func:`graphdyn.ops.bdcm._neighbor_dp`) materializes the
ρ-lattice DP state ``LL[E, K, M]`` in HBM once per neighbor-slot ``D`` (d
round trips of an array ``M/K`` times larger than chi itself), then runs the
``A``-tensor contraction as a separate einsum. Here the whole per-edge
pipeline — DP, contraction, ε-clamp, normalization, damping — fuses into one
VMEM-resident kernel: HBM is touched exactly once for the gathered messages
in and once for the updated messages out.

Layout: **edges are the lane axis** (last, 128-multiple). All per-edge work
is elementwise across edges with *identical* control flow, so one vector op
serves a whole tile; K = 2^T and M = (d+1)^T ride the sublane axis.

Group axis: the batched executors (``graphdyn.pipeline`` — HPr ensembles and
entropy λ-ladder cell groups) carry a leading group axis ``G``. The grouped
variant (:func:`dp_contract_grouped`) makes that axis a **grid dimension**
``grid = (G, n_tiles)`` — NOT a ``vmap`` of the serial kernel, which would
lower to a serial Python loop of G kernel launches (graftlint GD009). The
serial :func:`dp_contract` is the G=1 instance of the grouped kernel, so
"grouped == serial within the same kernel" is structural, not maintained.

The ``A_tilted`` rows come in two variants:

- **shared** (``a_tilted[K, K, M]``): every group contracts against the same
  rows — the HPr ensembles' shape (one λ, congruent reps). The block is
  grid-invariant; Pallas fetches it once.
- **group-resident** (``a_tilted[G, K, K, M]``): each group carries its own
  rows — the entropy cell groups' shape (per-cell λ-tilt). The whole stack
  sits VMEM-resident with a constant index map (one up-front DMA; the block
  never revolves, so the byte model charges it singly) and the kernel
  selects its group's rows by ``pl.program_id(0)``. ``vmem_block_edges(d, T,
  G=G)`` models this residency; 0 means the stack cannot fit and the caller
  must keep that class on the XLA path.

The ρ-lattice shift-convolution uses a *flat* mixed-radix shift: trajectory
``k`` with bits ``b_t`` advances the flat index by
``off_k = Σ_t b_t·(d+1)^{T−1−t}``. This equals the per-axis rolls of the XLA
path (`ops/bdcm.py`) because after ``D`` accumulated neighbors every axis
coordinate is ≤ D < d+1 — no radix carry can occur, so flat-index addition
never crosses an axis boundary. The shifts are static Python slices, fully
unrolled at trace time (d·K slice-FMAs of shape [≤M, Eb] per tile).

The λ-tilt ``exp(−λ·x_i(0))`` couples only to the destination trajectory's
initial value, so it is folded into the A tensor *outside* the kernel
(``A_tilted[x_i, x_j, m] = A[x_i, x_j, m]·tilt[x_i]``) — λ stays traced and
one compiled kernel serves the whole λ-ladder.

Reference semantics covered (capability parity, not translation):
`HPR_pytorch_RRG.py:183-218` (HPr_dp) and `ER_BDCM_entropy.ipynb:133-198`
(BDCM_ER) — see `SURVEY.md` §2.2/§2.3.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from graphdyn.attractors import trajectories01

LANE = 128

# Per-core VMEM is ~16 MiB on v4/v5e-class chips. The byte model below
# underestimates the compiler's scoped-vmem demand by up to ~33% (measured:
# a modeled 12.5 MiB kernel was charged 16.55 MiB by the v5e AOT compiler),
# so the budget leaves that margin. Pipelined in/out blocks are
# double-buffered (×2); the two DP scratch buffers are not.
VMEM_BUDGET = 10 * 1024 * 1024
MAX_BLOCK_EDGES = 8192  # wider tiles add nothing once the VPU is saturated


def vmem_bytes(d: int, T: int, edges: int, G: int = 0) -> int:
    """VMEM working-set byte model of the DP-contract kernel at an
    ``edges``-wide tile (f32 = 4 B) — the public formula both
    :func:`vmem_block_edges` and the graftcost hand-model adapter
    (``graphdyn.analysis.graftcost.HAND_MODELS``) evaluate, so the tiling
    decision and the GB102 gate can never disagree about what the kernel
    is believed to hold resident.

    - ``G=0`` — the serial / shared-A kernel: the broadcast A rows
      ``[K², M]`` ride the grid pipeline double-buffered → fixed
      ``8·K²·M``.
    - ``G>=1`` — the group-resident variant (per-group ``A_tilted``): the
      whole ``[G, K², M]`` stack sits resident under a constant index map —
      fetched once before the grid sweep, never revolved, so it is charged
      SINGLY → fixed ``4·G·K²·M``. At G=1 this coincides with half the
      shared fixed term, so a grouped G=1 program never tiles narrower
      than the serial program.

    Per edge lane: the pipelined chi_in/chi_old/out blocks
    (``(d+2)·K²`` values, ×2 buffers) plus the two un-pipelined DP scratch
    buffers (``K·M`` each) → ``8·(K²·(d+2) + K·M)`` bytes.
    """
    K = 2**T
    M = (d + 1) ** T
    if G:
        fixed = 4 * G * K * K * M                # resident A stack, single
    else:
        fixed = 8 * K * K * M                    # a_rows, double-buffered
    per_edge = 8 * (K * K * (d + 2) + K * M)     # blocks ×2 + scratch ×2
    return fixed + per_edge * edges


def vmem_block_edges(d: int, T: int, budget: int = VMEM_BUDGET,
                     G: int = 0) -> int:
    """Largest lane-multiple edge-tile width whose VMEM working set
    (:func:`vmem_bytes`) fits ``budget``, capped at ``MAX_BLOCK_EDGES``.
    Returns 0 when even a single lane-width tile does not fit (callers
    keep that class on the XLA path)."""
    fixed = vmem_bytes(d, T, 0, G)
    per_edge = vmem_bytes(d, T, 1, G) - fixed
    eb = (budget - fixed) // per_edge
    return int(min(MAX_BLOCK_EDGES, max(0, eb // LANE) * LANE))


def _flat_offsets(d: int, T: int) -> np.ndarray:
    """off_k for every trajectory k: mixed-radix flat shift on the (d+1)^T
    lattice."""
    X01 = trajectories01(T)                       # [K, T]
    radix = (d + 1) ** np.arange(T - 1, -1, -1)   # [T]
    return (X01 * radix).sum(axis=1).astype(np.int64)


def _dp_contract_kernel(
    chi_in_ref,   # [1, d, K, K, Eb] gathered incoming messages (this group)
    a_ref,        # [K*K, M, 1] shared | [G, K*K, M, 1] group-resident rows
    chi_old_ref,  # [1, K, K, Eb]  current messages of this tile (damping)
    out_ref,      # [1, K, K, Eb]
    ll_ref,       # scratch [K, M, Eb]
    acc_ref,      # scratch [K, M, Eb]
    *,
    d: int,
    K: int,
    M: int,
    offsets: tuple,
    damp: float,
    eps_clamp: float,
    per_group_a: bool,
):
    # DP base case: δ(ρ = 0) for every destination trajectory x_i
    ll_ref[:] = jnp.zeros_like(ll_ref)
    ll_ref[:, 0, :] = jnp.ones_like(ll_ref[:, 0, :])

    # induction over neighbor slots; ping-pong LL <-> acc
    for D in range(d):
        src, dst = (ll_ref, acc_ref) if D % 2 == 0 else (acc_ref, ll_ref)
        dst[:] = jnp.zeros_like(dst)
        for k in range(K):
            off = offsets[k]
            for xi in range(K):
                w = chi_in_ref[0, D, k, xi, :]    # [Eb]
                if off == 0:
                    dst[xi, :, :] += src[xi, :, :] * w[None, :]
                else:
                    dst[xi, off:M, :] += src[xi, 0 : M - off, :] * w[None, :]
    final = ll_ref if d % 2 == 0 else acc_ref

    if per_group_a:
        # group-resident rows: the whole [G, K*K, M, 1] stack is in VMEM;
        # this program instance reads its own group's slab
        g = pl.program_id(0)

        def a_row(row):
            return a_ref[g, row, :, :]
    else:

        def a_row(row):
            return a_ref[row, :, :]

    # contraction chi2[xi, xj, :] = Σ_m A_tilted[xi, xj, m]·LL[xi, m, :],
    # then ε-clamp, tile-local normalization, damping — all in VMEM
    z = jnp.zeros_like(out_ref[0, 0, 0, :])
    for xi in range(K):
        for xj in range(K):
            row = jnp.maximum(
                jnp.sum(a_row(xi * K + xj) * final[xi, :, :], axis=0),
                eps_clamp,
            )
            out_ref[0, xi, xj, :] = row
            z = z + row
    inv = 1.0 / jnp.maximum(z, jnp.finfo(jnp.float32).tiny)
    for xi in range(K):
        for xj in range(K):
            out_ref[0, xi, xj, :] = (
                damp * out_ref[0, xi, xj, :] * inv
                + (1.0 - damp) * chi_old_ref[0, xi, xj, :]
            )


@functools.partial(
    jax.jit,
    static_argnames=("d", "T", "damp", "eps_clamp", "block_edges", "interpret"),
)
def dp_contract_grouped(
    chi_in,      # f32[G, Ed, d, K, K]  (gathered, bias/mask already applied)
    a_tilted,    # f32[K, K, M] shared | f32[G, K, K, M] per-group
    chi_old,     # f32[G, Ed, K, K]
    *,
    d: int,
    T: int,
    damp: float,
    eps_clamp: float = 0.0,
    block_edges: int | None = None,
    interpret: bool = False,
):
    """Fused DP + contraction + normalize + damp for one edge-degree class
    of a GROUP of independent instances — group axis as the leading Pallas
    grid dimension (``grid = (G, n_tiles)``), never a ``vmap`` over kernel
    launches.

    ``a_tilted``'s rank selects the A variant: rank 3 is one shared row set
    (HPr ensembles — same λ across reps), rank 4 carries per-group rows
    VMEM-resident (entropy cell groups — per-cell λ-tilt; gated by
    ``vmem_block_edges(d, T, G=G)``). ``block_edges=None`` picks the widest
    lane-multiple tile that fits the VMEM budget; tile width never changes
    numerics (all per-lane work is elementwise across lanes). Returns
    f32[G, Ed, K, K].
    """
    K = 2**T
    M = (d + 1) ** T
    G, Ed = chi_in.shape[0], chi_in.shape[1]
    per_group_a = a_tilted.ndim == 4
    # trace-time kernel constants from static (d, T) — no device value
    # graftlint: disable-next-line=GD003  static ints for the kernel spec
    offsets = tuple(int(o) for o in _flat_offsets(d, T))

    budget_eb = vmem_block_edges(d, T, G=G if per_group_a else 0)
    if budget_eb == 0 and not interpret:
        raise ValueError(
            f"dp_contract_grouped(d={d}, T={T}, G={G}, per_group_a="
            f"{per_group_a}): no lane-multiple edge tile fits the "
            f"{VMEM_BUDGET >> 20} MiB VMEM budget (K·M = {K * M}); use the "
            "XLA path (pallas_group_supported() gates this automatically)"
        )
    vmem_eb = max(LANE, budget_eb)               # interpret mode has no VMEM
    Eb = min(
        block_edges if block_edges is not None else vmem_eb,
        vmem_eb,
        max(LANE, ((Ed + LANE - 1) // LANE) * LANE),
    )
    pad = (-Ed) % Eb
    n_tiles = (Ed + pad) // Eb

    # edges -> lane axis; pad lanes carry zeros (z=0 -> tiny denominator,
    # outputs on pad lanes are discarded by the final slice)
    chi_in_t = jnp.pad(
        jnp.transpose(chi_in, (0, 2, 3, 4, 1)), ((0, 0),) * 4 + ((0, pad),)
    )
    chi_old_t = jnp.pad(
        jnp.transpose(chi_old, (0, 2, 3, 1)), ((0, 0),) * 3 + ((0, pad),)
    )
    if per_group_a:
        a_rows = a_tilted.reshape(G, K * K, M, 1).astype(jnp.float32)
        a_spec = pl.BlockSpec(
            (G, K * K, M, 1), lambda g, i: (0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        )
    else:
        a_rows = a_tilted.reshape(K * K, M, 1).astype(jnp.float32)
        a_spec = pl.BlockSpec(
            (K * K, M, 1), lambda g, i: (0, 0, 0), memory_space=pltpu.VMEM
        )

    kernel = functools.partial(
        _dp_contract_kernel,
        d=d,
        K=K,
        M=M,
        offsets=offsets,
        damp=float(damp),
        eps_clamp=float(eps_clamp),
        per_group_a=per_group_a,
    )
    out_t = pl.pallas_call(
        kernel,
        grid=(G, n_tiles),
        in_specs=[
            pl.BlockSpec(
                (1, d, K, K, Eb), lambda g, i: (g, 0, 0, 0, i),
                memory_space=pltpu.VMEM,
            ),
            a_spec,
            pl.BlockSpec(
                (1, K, K, Eb), lambda g, i: (g, 0, 0, i),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, K, K, Eb), lambda g, i: (g, 0, 0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((G, K, K, Ed + pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((K, M, Eb), jnp.float32),
            pltpu.VMEM((K, M, Eb), jnp.float32),
        ],
        interpret=interpret,
    )(chi_in_t.astype(jnp.float32), a_rows, chi_old_t.astype(jnp.float32))
    return jnp.transpose(out_t[:, :, :, :Ed], (0, 3, 1, 2))


def dp_contract(
    chi_in,      # f32[Ed, d, K, K]  (gathered, bias/mask already applied)
    a_tilted,    # f32[K, K, M]
    chi_old,     # f32[Ed, K, K]
    *,
    d: int,
    T: int,
    damp: float,
    eps_clamp: float = 0.0,
    block_edges: int | None = None,
    interpret: bool = False,
):
    """Fused DP + contraction + normalize + damp for one edge-degree class —
    the G=1 instance of :func:`dp_contract_grouped` (shared-A variant), so
    the serial Pallas path and the grouped Pallas path run the SAME kernel
    body: grouped-vs-serial parity is one-kernel parity, bit-exact by
    construction (per-lane work is elementwise across lanes and tile
    widths; tested). Returns f32[Ed, K, K]."""
    return dp_contract_grouped(
        chi_in[None], a_tilted, chi_old[None],
        d=d, T=T, damp=damp, eps_clamp=eps_clamp,
        block_edges=block_edges, interpret=interpret,
    )[0]


def pallas_supported(d: int, T: int, Ed: int) -> bool:
    """Gate for the fused kernel (serial / shared-A). Bounds validated on a
    real v5e chip (see PALLAS_TPU.md): the unrolled body scales as d·K²
    slice-FMAs, so we keep the reference regime (T ≤ 4, d ≤ 8), require at
    least one full lane tile of edges, and require a lane-multiple tile to
    fit the VMEM budget (:func:`vmem_block_edges` — replaces the earlier
    K·M heuristic that admitted >2×16 MiB scratch at its own upper end)."""
    return T <= 4 and d <= 8 and Ed >= LANE and vmem_block_edges(d, T) >= LANE


def pallas_group_supported(
    d: int, T: int, Ed: int, G: int, *, per_group_a: bool
) -> bool:
    """Gate for the grouped kernel: the serial regime bounds plus the
    grouped VMEM model — with ``per_group_a`` the resident ``[G, K², M]``
    A stack joins the working set (:func:`vmem_block_edges` with ``G``), so
    a group too large for VMEM degrades that class to the XLA path instead
    of erroring (the executors re-check per call via the
    ``pallas_fallback_spec`` machinery for anything the model misses)."""
    return (
        T <= 4 and d <= 8 and Ed >= LANE and G >= 1
        and vmem_block_edges(d, T, G=G if per_group_a else 0) >= LANE
    )
