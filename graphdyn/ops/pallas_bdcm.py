"""Pallas TPU kernel: fused BDCM neighbor-DP + factor contraction.

The XLA sweep (:func:`graphdyn.ops.bdcm._neighbor_dp`) materializes the
ρ-lattice DP state ``LL[E, K, M]`` in HBM once per neighbor-slot ``D`` (d
round trips of an array ``M/K`` times larger than chi itself), then runs the
``A``-tensor contraction as a separate einsum. Here the whole per-edge
pipeline — DP, contraction, ε-clamp, normalization, damping — fuses into one
VMEM-resident kernel: HBM is touched exactly once for the gathered messages
in and once for the updated messages out.

Layout: **edges are the lane axis** (last, 128-multiple). All per-edge work
is elementwise across edges with *identical* control flow, so one vector op
serves a whole tile; K = 2^T and M = (d+1)^T ride the sublane axis.

The ρ-lattice shift-convolution uses a *flat* mixed-radix shift: trajectory
``k`` with bits ``b_t`` advances the flat index by
``off_k = Σ_t b_t·(d+1)^{T−1−t}``. This equals the per-axis rolls of the XLA
path (`ops/bdcm.py`) because after ``D`` accumulated neighbors every axis
coordinate is ≤ D < d+1 — no radix carry can occur, so flat-index addition
never crosses an axis boundary. The shifts are static Python slices, fully
unrolled at trace time (d·K slice-FMAs of shape [≤M, Eb] per tile).

The λ-tilt ``exp(−λ·x_i(0))`` couples only to the destination trajectory's
initial value, so it is folded into the A tensor *outside* the kernel
(``A_tilted[x_i, x_j, m] = A[x_i, x_j, m]·tilt[x_i]``) — λ stays traced and
one compiled kernel serves the whole λ-ladder.

Reference semantics covered (capability parity, not translation):
`HPR_pytorch_RRG.py:183-218` (HPr_dp) and `ER_BDCM_entropy.ipynb:133-198`
(BDCM_ER) — see `SURVEY.md` §2.2/§2.3.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from graphdyn.attractors import trajectories01

LANE = 128

# Per-core VMEM is ~16 MiB on v4/v5e-class chips. The byte model below
# underestimates the compiler's scoped-vmem demand by up to ~33% (measured:
# a modeled 12.5 MiB kernel was charged 16.55 MiB by the v5e AOT compiler),
# so the budget leaves that margin. Pipelined in/out blocks are
# double-buffered (×2); the two DP scratch buffers are not.
VMEM_BUDGET = 10 * 1024 * 1024
MAX_BLOCK_EDGES = 8192  # wider tiles add nothing once the VPU is saturated


def vmem_block_edges(d: int, T: int, budget: int = VMEM_BUDGET) -> int:
    """Largest lane-multiple edge-tile width whose VMEM working set fits
    ``budget``: 2×(chi_in + chi_old + out) pipelined blocks, the broadcast A
    rows, and the two [K, M, Eb] DP scratch buffers — capped at
    ``MAX_BLOCK_EDGES``. Returns 0 when even a single lane-width tile does
    not fit."""
    K = 2**T
    M = (d + 1) ** T
    fixed = 8 * K * K * M                        # a_rows, double-buffered
    per_edge = 8 * (K * K * (d + 2) + K * M)     # blocks ×2 + scratch ×2
    eb = (budget - fixed) // per_edge
    return int(min(MAX_BLOCK_EDGES, max(0, eb // LANE) * LANE))


def _flat_offsets(d: int, T: int) -> np.ndarray:
    """off_k for every trajectory k: mixed-radix flat shift on the (d+1)^T
    lattice."""
    X01 = trajectories01(T)                       # [K, T]
    radix = (d + 1) ** np.arange(T - 1, -1, -1)   # [T]
    return (X01 * radix).sum(axis=1).astype(np.int64)


def _dp_contract_kernel(
    chi_in_ref,   # [d, K, K, Eb]  gathered incoming messages (src-traj major)
    a_ref,        # [K*K, M, 1]    tilted factor tensor rows (x_i*K + x_j)
    chi_old_ref,  # [K, K, Eb]     current messages of this tile (for damping)
    out_ref,      # [K, K, Eb]
    ll_ref,       # scratch [K, M, Eb]
    acc_ref,      # scratch [K, M, Eb]
    *,
    d: int,
    K: int,
    M: int,
    offsets: tuple,
    damp: float,
    eps_clamp: float,
):
    # DP base case: δ(ρ = 0) for every destination trajectory x_i
    ll_ref[:] = jnp.zeros_like(ll_ref)
    ll_ref[:, 0, :] = jnp.ones_like(ll_ref[:, 0, :])

    # induction over neighbor slots; ping-pong LL <-> acc
    for D in range(d):
        src, dst = (ll_ref, acc_ref) if D % 2 == 0 else (acc_ref, ll_ref)
        dst[:] = jnp.zeros_like(dst)
        for k in range(K):
            off = offsets[k]
            for xi in range(K):
                w = chi_in_ref[D, k, xi, :]       # [Eb]
                if off == 0:
                    dst[xi, :, :] += src[xi, :, :] * w[None, :]
                else:
                    dst[xi, off:M, :] += src[xi, 0 : M - off, :] * w[None, :]
    final = ll_ref if d % 2 == 0 else acc_ref

    # contraction chi2[xi, xj, :] = Σ_m A_tilted[xi, xj, m]·LL[xi, m, :],
    # then ε-clamp, tile-local normalization, damping — all in VMEM
    z = jnp.zeros_like(out_ref[0, 0, :])
    for xi in range(K):
        for xj in range(K):
            row = jnp.maximum(
                jnp.sum(a_ref[xi * K + xj, :, :] * final[xi, :, :], axis=0),
                eps_clamp,
            )
            out_ref[xi, xj, :] = row
            z = z + row
    inv = 1.0 / jnp.maximum(z, jnp.finfo(jnp.float32).tiny)
    for xi in range(K):
        for xj in range(K):
            out_ref[xi, xj, :] = (
                damp * out_ref[xi, xj, :] * inv
                + (1.0 - damp) * chi_old_ref[xi, xj, :]
            )


@functools.partial(
    jax.jit,
    static_argnames=("d", "T", "damp", "eps_clamp", "block_edges", "interpret"),
)
def dp_contract(
    chi_in,      # f32[Ed, d, K, K]  (gathered, bias/mask already applied)
    a_tilted,    # f32[K, K, M]
    chi_old,     # f32[Ed, K, K]
    *,
    d: int,
    T: int,
    damp: float,
    eps_clamp: float = 0.0,
    block_edges: int | None = None,
    interpret: bool = False,
):
    """Fused DP + contraction + normalize + damp for one edge-degree class.

    ``block_edges=None`` picks the widest lane-multiple tile that fits the
    VMEM budget (:func:`vmem_block_edges`); an explicit value is still
    clamped to that budget. Returns f32[Ed, K, K] — the damped updated
    messages for these edges.
    """
    K = 2**T
    M = (d + 1) ** T
    Ed = chi_in.shape[0]
    # trace-time kernel constants from static (d, T) — no device value
    # graftlint: disable-next-line=GD003  static ints for the kernel spec
    offsets = tuple(int(o) for o in _flat_offsets(d, T))

    budget_eb = vmem_block_edges(d, T)
    if budget_eb == 0 and not interpret:
        raise ValueError(
            f"dp_contract(d={d}, T={T}): no lane-multiple edge tile fits the "
            f"{VMEM_BUDGET >> 20} MiB VMEM budget (K·M = {K * M}); use the "
            "XLA path (pallas_supported() gates this automatically)"
        )
    vmem_eb = max(LANE, budget_eb)               # interpret mode has no VMEM
    Eb = min(
        block_edges if block_edges is not None else vmem_eb,
        vmem_eb,
        max(LANE, ((Ed + LANE - 1) // LANE) * LANE),
    )
    pad = (-Ed) % Eb
    n_tiles = (Ed + pad) // Eb

    # edges -> lane axis; pad lanes carry zeros (z=0 -> tiny denominator,
    # outputs on pad lanes are discarded by the final slice)
    chi_in_t = jnp.pad(
        jnp.transpose(chi_in, (1, 2, 3, 0)), ((0, 0),) * 3 + ((0, pad),)
    )
    chi_old_t = jnp.pad(
        jnp.transpose(chi_old, (1, 2, 0)), ((0, 0),) * 2 + ((0, pad),)
    )
    a_rows = a_tilted.reshape(K * K, M, 1).astype(jnp.float32)

    kernel = functools.partial(
        _dp_contract_kernel,
        d=d,
        K=K,
        M=M,
        offsets=offsets,
        damp=float(damp),
        eps_clamp=float(eps_clamp),
    )
    out_t = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (d, K, K, Eb), lambda i: (0, 0, 0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((K * K, M, 1), lambda i: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, K, Eb), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (K, K, Eb), lambda i: (0, 0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((K, K, Ed + pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((K, M, Eb), jnp.float32),
            pltpu.VMEM((K, M, Eb), jnp.float32),
        ],
        interpret=interpret,
    )(chi_in_t.astype(jnp.float32), a_rows, chi_old_t.astype(jnp.float32))
    return jnp.transpose(out_t[:, :, :Ed], (2, 0, 1))


def pallas_supported(d: int, T: int, Ed: int) -> bool:
    """Gate for the fused kernel. Bounds validated on a real v5e chip
    (see PALLAS_TPU.md): the unrolled body scales as d·K² slice-FMAs, so we
    keep the reference regime (T ≤ 4, d ≤ 8), require at least one full lane
    tile of edges, and require a lane-multiple tile to fit the VMEM budget
    (:func:`vmem_block_edges` — replaces the earlier K·M heuristic that
    admitted >2×16 MiB scratch at its own upper end)."""
    return T <= 4 and d <= 8 and Ed >= LANE and vmem_block_edges(d, T) >= LANE
