"""Per-degree update LUTs — the rule axis compiled to popcount tables.

The p-bit annealers in PAPERS.md (arXiv:2602.16143's dual-BRAM LUT engine,
arXiv:2110.02481's sparse Ising machines) precompute the spin update as a
small table indexed by the neighbor popcount instead of re-deriving it from
arithmetic every tick. This module is that idea for the graphdyn rule axis:

- :func:`update_lut` compiles ONE (rule, tie) pair of
  :mod:`graphdyn.ops.dynamics` into a ``uint8[dmax+1, dmax+1, 2]`` table —
  next spin bit for every (degree, +1-neighbor count, current bit) triple.
  The generator is exhaustively oracle-tested against
  :func:`graphdyn.ops.dynamics.step_spins` on star graphs (a genuinely
  independent oracle: the reference's ``R·sign(2Σ + C·s)`` integer form,
  not the LUT formula itself).
- :func:`lut_node_masks` broadcasts a table against a graph's degree
  sequence into per-count packed word masks, and :func:`lut_one_step`
  applies them to the packed state: the carry-save bit-plane counter
  (:mod:`graphdyn.ops.packed`) produces the popcount, a plane comparator
  selects the count's mask, and the masked table entry IS the next bit —
  ``O(dmax·log dmax)`` word ops per step, the same order as the dedicated
  majority comparator, but now ANY f(degree, count, spin) rule ships as a
  table instead of hand-derived word logic (ROADMAP item 4's compilation
  point; the fused annealer :mod:`graphdyn.ops.pallas_anneal` is its first
  consumer).

Exactness: for the four shipped (rule, tie) pairs ``lut_one_step`` is
bit-identical to the comparator step of ``ops.packed`` (tested on RRG and
ragged ER degree sequences).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from graphdyn.ops.dynamics import Rule, TieBreak, rule_coefficients
from graphdyn.ops.packed import _FULL, _csa_add_one


def update_lut_rows(degs, max_cnt: int,
                    rule: Rule | str = Rule.MAJORITY,
                    tie: TieBreak | str = TieBreak.STAY) -> np.ndarray:
    """``uint8[len(degs), max_cnt+1, 2]``: the :func:`update_lut` rows for
    an EXPLICIT degree list (vectorized host NumPy). This is the bucketed
    kernel's per-bucket table build (:mod:`graphdyn.ops.bucketed`): a
    power-law hub pushes ``dmax`` into the thousands, where materializing
    the full ``[dmax+1, dmax+1, 2]`` square costs O(dmax²) for rows no
    node in the bucket has — the row build is the same formula, degree
    sequence in, so :func:`update_lut` and the bucketed masks cannot
    drift (update_lut IS this function over ``arange(dmax+1)``)."""
    degs = np.asarray(degs, np.int64).reshape(-1)
    R, C = rule_coefficients(rule, tie)
    deg = degs[:, None, None]
    cnt = np.arange(max_cnt + 1, dtype=np.int64)[None, :, None]
    b = np.arange(2, dtype=np.int64)[None, None, :]
    # R·sign(2Σ + C·s) with Σ = 2·cnt − deg, s = 2b − 1 (see update_lut)
    val = R * np.sign(2 * (2 * cnt - deg) + C * (2 * b - 1))
    return ((val == 1) & (cnt <= deg)).astype(np.uint8)


def update_lut(dmax: int, rule: Rule | str = Rule.MAJORITY,
               tie: TieBreak | str = TieBreak.STAY) -> np.ndarray:
    """``uint8[dmax+1, dmax+1, 2]``: next spin bit for (degree ``deg``,
    +1-neighbor count ``cnt``, current bit ``b``). Entries with
    ``cnt > deg`` are unreachable (a node's popcount cannot exceed its
    degree) and filled with 0.

    Derivation: with spin ``s = 2b − 1`` and neighbor sum
    ``Σ = 2·cnt − deg``, one synchronous step is ``R·sign(2Σ + C·s)``
    (:func:`graphdyn.ops.dynamics.rule_coefficients`); the next bit is 1
    iff that value is +1. ``sign`` never returns 0 here: ``2Σ`` is even and
    ``C·s = ±1`` breaks every tie.
    """
    if dmax < 0:
        raise ValueError(f"dmax must be >= 0, got {dmax}")
    return update_lut_rows(np.arange(dmax + 1), dmax, rule, tie)


def lut_node_masks(deg_ext: np.ndarray, lut: np.ndarray) -> np.ndarray:
    """Broadcast a ``[dmax+1, dmax+1, 2]`` table against the ghost-extended
    degree sequence ``deg_ext: int[n+1]`` into packed word masks
    ``uint32[dmax+1, 2, n+1]``: entry ``[cnt, b, i]`` is all-ones when
    ``lut[deg_i, cnt, b]`` else all-zeros. The ghost row's update is
    irrelevant (its word is forced back to zero every step), so its masks
    are zero regardless of the table's degree-0 column."""
    deg_ext = np.asarray(deg_ext)
    dmax = lut.shape[0] - 1
    if int(deg_ext[:-1].max(initial=0)) > dmax:
        raise ValueError(
            f"degree sequence exceeds the table's dmax={dmax} "
            f"(max degree {int(deg_ext.max())})"
        )
    n1 = deg_ext.shape[0]
    masks = np.zeros((dmax + 1, 2, n1), np.uint32)
    for cnt in range(dmax + 1):
        for b in (0, 1):
            on = lut[np.minimum(deg_ext, dmax), cnt, b].astype(bool)
            masks[cnt, b, on] = np.uint32(0xFFFFFFFF)
    masks[:, :, n1 - 1] = 0          # ghost row: forced to zero anyway
    return masks


def _count_eq_masks(planes, dmax: int):
    """Packed equality masks ``eq[c]`` (c = 0..dmax) of the bit-plane
    counter against each constant count — all-ones words where the
    per-replica popcount equals ``c``."""
    out = []
    full = jnp.uint32(_FULL)
    zero = jnp.uint32(0)
    for c in range(dmax + 1):
        eq = jnp.full_like(planes[0], _FULL)
        for k, pl in enumerate(planes):
            bit = full if (c >> k) & 1 else zero
            eq = eq & ~(pl ^ bit)
        out.append(eq)
    return out


def lut_one_step(sp_ext, nbr_ext, lut_masks, *, n: int, dmax: int):
    """One synchronous packed update of the ghost-extended state via the
    LUT masks (``lut_masks: uint32[dmax+1, 2, n+1]`` — from
    :func:`lut_node_masks`, as a device array): carry-save popcount over
    the neighbor gather, then ``out = Σ_c eq_c & (prev ? m[c,1] : m[c,0])``.
    Bit-identical to the hand-derived comparator step for the four shipped
    (rule, tie) pairs (tested); the ghost word is forced back to zero."""
    n_planes = max(int(dmax).bit_length(), 1)
    planes = [jnp.zeros_like(sp_ext) for _ in range(n_planes)]
    for j in range(dmax):
        _csa_add_one(planes, jnp.take(sp_ext, nbr_ext[:, j], axis=0))
    eqs = _count_eq_masks(planes, dmax)
    out = jnp.zeros_like(sp_ext)
    for c in range(dmax + 1):
        m0 = lut_masks[c, 0][:, None]
        m1 = lut_masks[c, 1][:, None]
        out = out | (eqs[c] & ((sp_ext & m1) | (~sp_ext & m0)))
    return out.at[n].set(jnp.uint32(0))
