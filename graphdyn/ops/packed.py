"""Bit-packed multi-replica dynamics — the HBM-bandwidth kernel.

The synchronous-dynamics workload is memory-bound: the int8 path reads one
byte per (replica, neighbor) per step. Here 32 replicas pack into each uint32
word (spin +1 ↔ bit 1), so one neighbor-table gather serves 32 replicas and
per-step HBM traffic drops ~8× vs int8. The per-node neighbor count is
accumulated **bitwise** with a carry-save adder over bit-planes, and the
rule/tie decision becomes a bitwise comparator of the packed counter against
the per-node degree threshold — pure VPU word ops, no per-replica arithmetic
anywhere.

Derivation: with ``cnt`` = number of +1 neighbors and ``deg`` the true degree
(ghost-padded slots contribute 0 bits and are excluded from ``deg``), the
signed neighbor sum is ``2·cnt − deg``, so with T = deg//2:

- strictly positive  ⇔ cnt > T            (odd deg) / cnt > T   (even deg)
- tie (sum == 0)     ⇔ deg even ∧ cnt == T
- strictly negative  ⇔ otherwise

and the update ``R·sign(2Σ + C·s)`` (see ops.dynamics) maps to
``win | (tie & tie_bit)`` with the appropriate complements for
minority/change. Exactness vs the int8 kernel is covered by tests over all
(rule, tie) pairs on ragged ER degree sequences.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.analysis.contracts import contract
from graphdyn.ops.dynamics import Rule, TieBreak

WORD = 32
_FULL = np.uint32(0xFFFFFFFF)


def pack_spins(s: np.ndarray) -> np.ndarray:
    """int8[R, n] (±1) -> uint32[n, W] with W = ceil(R/32); replica r lives in
    word r//32, bit r%32; +1 ↔ 1. Pad replicas read as spin −1 and are
    sliced away by :func:`unpack_spins`."""
    s = np.asarray(s)
    R, n = s.shape
    W = -(-R // WORD)
    bits = (s.T == 1).astype(np.uint32)          # [n, R]
    padded = np.zeros((n, W * WORD), np.uint32)
    padded[:, :R] = bits
    words = padded.reshape(n, W, WORD)
    shifts = np.arange(WORD, dtype=np.uint32)
    return (words << shifts).sum(axis=2).astype(np.uint32)


def unpack_spins(p: np.ndarray, R: int) -> np.ndarray:
    """uint32[n, W] -> int8[R, n]."""
    p = np.asarray(p)
    n, W = p.shape
    shifts = np.arange(WORD, dtype=np.uint32)
    bits = (p[:, :, None] >> shifts) & np.uint32(1)   # [n, W, 32]
    bits = bits.reshape(n, W * WORD)[:, :R]
    return (2 * bits.astype(np.int8) - 1).T


def _csa_add_one(planes, carry):
    """Ripple one 1-bit addend (a packed word) into the bit-plane counter —
    the shared inner step of both gather schedules. Mutates ``planes``. The
    final carry out of the top plane is discarded: ``n_planes =
    ceil(log2(dmax+1))`` makes overflow impossible."""
    for k in range(len(planes)):
        new_carry = planes[k] & carry
        planes[k] = planes[k] ^ carry
        carry = new_carry


def _csa_planes(gathered, d: int, n_planes: int):
    """Carry-save accumulate ``d`` one-bit addends (packed words) into
    ``n_planes`` bit-planes of a per-replica counter. ``gathered``:
    [n, d, W] — addends indexed on axis 1 so no transpose of the gather
    output is needed."""
    planes = [jnp.zeros_like(gathered[:, 0, :]) for _ in range(n_planes)]
    for j in range(d):
        _csa_add_one(planes, gathered[:, j, :])
    return planes


def _compare_planes(planes, thr_bits):
    """Bitwise comparator: (gt, eq) of the packed counter vs a broadcast
    per-node threshold given as bit-plane masks (all-ones/all-zeros words)."""
    gt = jnp.zeros_like(planes[0])
    eq = jnp.full_like(planes[0], _FULL)
    for k in reversed(range(len(planes))):
        tk = thr_bits[k]
        gt = gt | (eq & planes[k] & ~tk)
        eq = eq & ~(planes[k] ^ tk)
    return gt, eq


def _rule_tie_combine(win, tie_mask, prev, rule: Rule, tie: TieBreak):
    """Combine the comparator outputs into next-step spin bits — the ONE
    implementation of the packed rule/tie word logic (``win`` = strictly
    positive sum, ``tie_mask`` = sum == 0, ``prev`` = current bits; loss =
    ``~(win | tie_mask)`` implicitly). The unsharded body and the halo
    kernel (:mod:`graphdyn.parallel.halo`) both call this, so a semantics
    fix propagates to every node-sharding mode and the bit-exactness
    contract stays structural."""
    tie_bit = prev if tie == TieBreak.STAY else ~prev
    out = win | (tie_mask & tie_bit)
    if rule == Rule.MINORITY:
        # minority: +1 iff sum<0, tie -> (stay: s, change: ~s)
        loss = ~(win | tie_mask)
        out = loss | (tie_mask & tie_bit)
    return out


@partial(jax.jit, static_argnames=("rule", "tie", "steps", "gather"))
@contract(nbr="int32[n,d]", deg="int32[n]", sp="uint32[n,w]",
          ret="uint32[n,w]")
# the per_slot/fused A/B tests and benchmarks roll the SAME sp through both
# schedules; donating it would invalidate their input buffer
# graftlint: disable-next-line=GD006  A/B callers reuse the input state
def _packed_rollout_device(nbr, deg, sp, steps: int, rule: str = "majority",
                           tie: str = "stay", gather: str = "per_slot"):
    """The single-device packed rollout program (the P=1 instance of the
    partitioned path below; graftcheck fingerprints THIS program as the
    ``packed_rollout`` ledger entry, so the dispatcher wrapper cannot
    perturb the committed P=1 fingerprint).

    ``gather`` selects the HBM access pattern (bit-identical results):

    - ``"per_slot"`` (default): one ``[n, W]`` gather per neighbor slot,
      consumed immediately by the carry-save accumulation — XLA fuses each
      gather into the CSA elementwise ops, so no ``[n, dmax, W]`` gather
      buffer ever exists in HBM. Per-step traffic approaches the streaming
      minimum ``n·W·4·(d reads + 1 write)`` bytes.
    - ``"fused"``: one big gather materializing ``[n, dmax, W]`` before the
      CSA (the round-2 formulation; kept for A/B measurement —
      ARCHITECTURE.md roofline notes).
    """
    rule = Rule(rule)
    tie = TieBreak(tie)
    if gather not in ("per_slot", "fused"):
        raise ValueError(f"gather must be 'per_slot' or 'fused', got {gather!r}")
    n, dmax = nbr.shape
    if steps <= 0:
        return sp
    # bits needed to count up to dmax: bit_length(dmax) == ceil(log2(dmax+1))
    # exactly, in integer arithmetic (no host float math at trace time)
    n_planes = max(dmax.bit_length(), 1)

    # the ghost row rides IN the loop carry: re-building the ghost-extended
    # state with a concatenate inside the body costs a full extra read+write
    # of the [n, W] state per step (~33% of the streaming traffic at d=3 —
    # the headline shape). The tables extend once: ghost row n is
    # self-neighbored with degree 0, and its word is forced back to zero
    # each step (tie->change would flip it; everything else preserves it).
    nbr_ext = jnp.concatenate([nbr, jnp.full((1, dmax), n, nbr.dtype)], axis=0)
    deg_ext = jnp.concatenate([deg, jnp.zeros((1,), deg.dtype)])
    flat_nbr = nbr_ext.reshape(-1)

    thr = (deg_ext // 2).astype(jnp.uint32)
    deg_even = (deg_ext % 2 == 0)
    even_mask = jnp.where(deg_even, _FULL, jnp.uint32(0))[:, None]
    thr_bits = [
        jnp.where((thr >> k) & 1 == 1, _FULL, jnp.uint32(0))[:, None]
        for k in range(n_planes)
    ]

    def body(_, sp_ext):
        if gather == "per_slot":
            planes = [jnp.zeros_like(sp_ext) for _ in range(n_planes)]
            for j in range(dmax):
                _csa_add_one(planes, jnp.take(sp_ext, nbr_ext[:, j], axis=0))
        else:
            g = jnp.take(sp_ext, flat_nbr, axis=0).reshape(
                n + 1, dmax, sp_ext.shape[1]
            )
            planes = _csa_planes(g, dmax, n_planes)
        gt, eq = _compare_planes(planes, thr_bits)
        out = _rule_tie_combine(
            gt, eq & even_mask, sp_ext, rule, tie    # 2cnt > / == deg
        )
        return out.at[n].set(jnp.uint32(0))          # ghost word stays zero

    sp_ext0 = jnp.concatenate(
        [sp, jnp.zeros((1, sp.shape[1]), sp.dtype)], axis=0
    )
    return lax.fori_loop(0, steps, body, sp_ext0)[:n]


def packed_rollout(nbr, deg, sp, steps: int, rule: str = "majority",
                   tie: str = "stay", gather: str = "per_slot",
                   partition=None, mesh=None):
    """Roll packed spins ``sp: uint32[n, W]`` for ``steps`` synchronous
    updates. ``nbr: int32[n, dmax]`` ghost-padded with n; ``deg: int32[n]``.

    ``partition=None`` (or a P=1 :class:`graphdyn.graphs.Partition`) runs
    the single-device program (:func:`_packed_rollout_device` — the
    dispatcher adds nothing, so the P=1 instance IS the existing program,
    per the grouped-executor identity precedent). A P>=2 partition routes
    through the halo-exchange node sharding
    (:func:`graphdyn.parallel.halo.halo_rollout`): per-shard packed state,
    boundary-word ``ppermute`` per step, bit-exact to the P=1 program.
    ``mesh`` (optional, P>=2 only) overrides the default 1-D node mesh.
    See ``_packed_rollout_device`` for the ``gather`` schedule knob.
    """
    if partition is None or partition.P == 1:
        return _packed_rollout_device(nbr, deg, sp, steps, rule, tie, gather)
    if gather != "per_slot":
        raise ValueError(
            "the partitioned rollout implements only the per_slot gather "
            f"schedule (got gather={gather!r})"
        )
    from graphdyn.parallel.halo import halo_rollout

    return halo_rollout(
        nbr, deg, sp, steps, partition=partition, rule=rule, tie=tie,
        mesh=mesh,
    )


# the canonical lowering surface stays reachable through the public name
# (graftcheck's ledger entry + the roofline smoke builder lower the P=1
# program via `packed_rollout.lower`)
packed_rollout.lower = _packed_rollout_device.lower


@partial(jax.jit, static_argnames=("target",))
def packed_consensus_mask(sp: jnp.ndarray, target: int = 1) -> jnp.ndarray:
    """Per-replica consensus flags straight from the packed domain.

    Replica r sits at the homogeneous ``target`` state iff its bit column is
    all-ones (target +1) / all-zeros (target −1) across every node — one
    AND/OR word-reduction over the node axis, no unpacking. Returns
    uint32[W] bit-flags (replica r of word w = bit r%32 of entry r//32).
    """
    if target == 1:
        return jax.lax.reduce(
            sp, np.uint32(0xFFFFFFFF), jax.lax.bitwise_and, dimensions=(0,)
        )
    return ~jax.lax.reduce(sp, np.uint32(0), jax.lax.bitwise_or, dimensions=(0,))


def packed_consensus_fraction(sp, n_replicas: int, target: int = 1) -> float:
    """Fraction of replicas at the homogeneous ``target`` consensus
    (`observe.consensus_fraction` in the packed domain). Pad replicas
    (unpack reads them as −1) are excluded via ``n_replicas``."""
    sp = jnp.asarray(sp)
    if n_replicas > sp.shape[1] * WORD:
        raise ValueError(
            f"n_replicas={n_replicas} exceeds packed capacity "
            f"{sp.shape[1] * WORD} (W={sp.shape[1]} words)"
        )
    flags = np.asarray(packed_consensus_mask(sp, target))
    bits = (flags[:, None] >> np.arange(WORD, dtype=np.uint32)) & np.uint32(1)
    return float(bits.reshape(-1)[:n_replicas].sum()) / n_replicas


def draw_packed_biased(seed: int, n: int, W: int, m0: float,
                       out_shardings=None) -> jnp.ndarray:
    """uint32[n, W] packed spins drawn ON DEVICE with initial magnetization
    bias: each bit is +1 (set) independently with probability (1+m0)/2, so
    E[m(0)] = m0 per replica — the biased-initialization axis of the thesis
    question (`ER_BDCM_entropy.ipynb:113-123`: which m(0) flow to consensus).
    Device-resident for the same reason as ``benchmarks.common.draw_u32``:
    host→device state uploads are what the tunneled TPU link cannot sustain.
    ``out_shardings`` lands the state directly in a word-axis sharding for
    the multi-device scan (the draw is deterministic in ``seed`` regardless,
    so sharded and unsharded states are bit-identical)."""
    def f():
        bits = jax.random.bernoulli(
            jax.random.key(seed), (1.0 + m0) / 2.0, (n, W, WORD)
        )
        shifts = jnp.arange(WORD, dtype=jnp.uint32)
        return (bits.astype(jnp.uint32) << shifts).sum(axis=2).astype(jnp.uint32)

    return jax.jit(f, out_shardings=out_shardings)()


def _consensus_bits(sp: jnp.ndarray, R: int) -> jnp.ndarray:
    """bool[R]: replica at EITHER homogeneous state (+1 all-ones column or
    −1 all-zeros column), straight from the packed domain."""
    up = lax.reduce(sp, np.uint32(_FULL), lax.bitwise_and, dimensions=(0,))
    down = ~lax.reduce(sp, np.uint32(0), lax.bitwise_or, dimensions=(0,))
    flags = up | down                                   # uint32[W]
    bits = (flags[:, None] >> jnp.arange(WORD, dtype=jnp.uint32)) & 1
    return bits.reshape(-1)[:R].astype(bool)


def _replica_magnetization(sp: jnp.ndarray, R: int) -> jnp.ndarray:
    """float32[R]: per-replica magnetization m_r = (2·cnt_r − n)/n where
    cnt_r counts +1 spins down replica r's bit column. The [n, W, 32]
    bit expansion fuses into the sum — no unpacked state in HBM."""
    n = sp.shape[0]
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    cnt = ((sp[:, :, None] >> shifts) & 1).astype(jnp.int32).sum(axis=0)
    cnt = cnt.reshape(-1)[:R]
    return (2.0 * cnt - n) / n


@partial(jax.jit, static_argnames=(
    "R", "max_steps", "chunk", "near_eps", "rule", "tie"),
         donate_argnames=("sp",))
@contract(nbr="int32[n,d]", deg="int32[n]", sp="uint32[n,w]")
def packed_consensus_scan(nbr, deg, sp, R: int, max_steps: int,
                          chunk: int = 10, near_eps: float = 0.01,
                          rule: str = "majority", tie: str = "stay"):
    """Roll packed replicas until every one has (near-)reached consensus or
    ``max_steps`` is spent, recording per-replica first-passage steps — the
    opinion-consensus observable (`SURVEY.md` §0.3: which initializations
    flow to consensus) in one device program, no host round-trips.

    Runs in ``chunk``-step slabs (first-passage resolution = chunk); after
    each slab two per-replica flags update:

    - ``strict``: bit column homogeneous (AND/OR word reductions), i.e. the
      absorbing all-+1/all-−1 state;
    - ``near``: |m_r| ≥ 1 − near_eps — robust to the O(1) frozen/blinking
      small components of a sparse ER graph, which block strict consensus
      at a rate set by component statistics rather than by the dynamics
      under study.

    The loop exits early once every replica is near-consensus (strict
    implies near). Returns a dict of final state and per-replica
    ``(strict, strict_step, near, near_step, m_final)``; unreached
    first-passage steps are −1.

    ``chunk`` must divide ``max_steps``: the loop advances in whole slabs,
    so a non-dividing pair would silently run past the budget while
    downstream artifacts record the requested ``max_steps`` — refused here
    instead.
    """
    if max_steps % chunk:
        raise ValueError(
            f"chunk={chunk} must divide max_steps={max_steps} (the scan "
            "advances in whole chunks; a remainder would overshoot the "
            "recorded budget)"
        )
    def slab(carry):
        sp, t, strict, strict_t, near, near_t = carry
        sp = packed_rollout(nbr, deg, sp, chunk, rule, tie)
        t = t + chunk
        s_now = _consensus_bits(sp, R)
        m = _replica_magnetization(sp, R)
        n_now = jnp.abs(m) >= 1.0 - near_eps
        strict_t = jnp.where(s_now & ~strict, t, strict_t)
        near_t = jnp.where(n_now & ~near, t, near_t)
        return sp, t, strict | s_now, strict_t, near | n_now, near_t

    def cond(carry):
        _, t, _, _, near, _ = carry
        return (t < max_steps) & ~jnp.all(near)

    init = (
        sp, jnp.int32(0),
        jnp.zeros((R,), bool), jnp.full((R,), -1, jnp.int32),
        jnp.zeros((R,), bool), jnp.full((R,), -1, jnp.int32),
    )
    sp, t, strict, strict_t, near, near_t = lax.while_loop(cond, slab, init)
    return {
        "sp": sp, "steps_run": t,
        "strict": strict, "strict_step": strict_t,
        "near": near, "near_step": near_t,
        "m_final": _replica_magnetization(sp, R),
    }


def packed_end_state(graph, s, steps, rule="majority", tie="stay"):
    """Convenience wrapper: int8[R, n] in/out through the packed kernel."""
    sp = pack_spins(s)
    out = packed_rollout(
        jnp.asarray(graph.nbr),
        jnp.asarray(graph.deg),
        jnp.asarray(sp),
        steps,
        rule,
        tie,
    )
    return unpack_spins(np.asarray(out), s.shape[0])
