"""Pallas TPU packed-dynamics kernel: explicit per-row HBM→VMEM DMA.

The XLA packed kernel (`graphdyn.ops.packed.packed_rollout`) is bound by the
random-row gather of neighbor spin words (`ARCHITECTURE.md` roofline: the
measured headline sits well below the HBM streaming bound, and
`scripts/pallas_gather_probe.py` measures whether explicitly pipelined
per-row DMAs beat XLA's gather at the same shape). This module is the
gather probe's pattern graduated into the full dynamics step: for each node
the kernel DMAs its ``d`` neighbor rows ``[1, W]`` from HBM into a VMEM
ring buffer (depth-``depth`` double buffering, the guide's sparse-gather
recipe), folds them with the carry-save bit-plane adder, and writes the
packed update — no ``[n, d, W]`` gather intermediate, and the access
stream is software-pipelined ``depth`` rows ahead.

Scope (v1, deliberately narrow — the BASELINE headline shapes): uniform
ODD degree (d=3 / d=5 regular graphs ⇒ no ties, so the tie-break never
needs the node's own spin row), majority or minority rule. Everything else
falls back to the XLA kernel. Correctness off-chip is interpret-mode
tested bit-for-bit against `packed_rollout` (tests/test_pallas_packed.py);
whether it *wins* on chip is exactly what `scripts/pallas_gather_probe.py`
and the session A/B measure — if XLA's gather already saturates the
random-access limit, this kernel is the written answer to why the roofline
gap is irreducible (VERDICT r3 task 8).

Reference anchor: the hot update `SA_RRG.py:18-20` / the ensemble dynamics
this accelerates, `SURVEY.md` §2.1.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from graphdyn.ops.packed import _FULL, _compare_planes, _csa_add_one
from graphdyn.ops.dynamics import Rule, TieBreak


def _row_dma_pipeline(sp_ref, scratch, sems, idx_fn, total: int, depth: int):
    """The shared software pipeline of both kernels: per-row HBM→VMEM async
    copies through a depth-``depth`` ring buffer. Returns ``(warm,
    consume)``: call ``warm()`` once, then ``consume(k)`` for k = 0..total-1
    in order — it waits row k, returns its VMEM view, and starts the
    prefetch of row ``k+depth`` (slot k's refill must wait until row k is
    consumed; ``depth-1`` lookahead DMAs stay in flight)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def dma(k):
        slot = jax.lax.rem(k, depth)
        return pltpu.make_async_copy(
            sp_ref.at[pl.ds(idx_fn(k), 1), :],
            scratch.at[pl.ds(slot, 1), :],
            sems.at[slot],
        )

    def warm():
        def start(k, _):
            dma(k).start()
            return 0

        jax.lax.fori_loop(0, min(depth, total), start, 0)

    def consume(k):
        dma(k).wait()
        row = scratch[pl.ds(jax.lax.rem(k, depth), 1), :]

        @pl.when(k + depth < total)
        def _():
            dma(k + depth).start()

        return row

    return warm, consume


def pallas_packed_supported(deg: np.ndarray, rule: str, tie: str) -> bool:
    """v1 applicability: uniform odd degree (tie-break unreachable), and a
    rule whose no-tie update is a pure comparator (majority/minority)."""
    deg = np.asarray(deg)
    if deg.size == 0 or (deg != deg.flat[0]).any():
        return False
    return int(deg.flat[0]) % 2 == 1 and rule in ("majority", "minority")


def _maj_planes(rows, d: int, thr: int):
    """planes-of-count comparator for uniform degree: cnt > thr (bitwise,
    per replica-lane) — the XLA kernel's `_compare_planes` with the
    threshold as broadcast scalar constants. Returns the packed win mask."""
    n_planes = max(int(np.ceil(np.log2(d + 1))), 1)
    planes = [jnp.zeros_like(rows[0]) for _ in range(n_planes)]
    for r in rows:
        _csa_add_one(planes, r)
    thr_bits = [
        jnp.uint32(0xFFFFFFFF) if (thr >> k) & 1 else jnp.uint32(0)
        for k in range(n_planes)
    ]
    gt, _ = _compare_planes(planes, thr_bits)
    return gt


def _make_kernel(B: int, d: int, depth: int, minority: bool):
    from jax.experimental import pallas as pl

    thr = d // 2

    def kernel(nbr_ref, sp_ref, out_ref, scratch, sems):
        warm, consume = _row_dma_pipeline(
            sp_ref, scratch, sems,
            lambda k: nbr_ref[k // d, k % d], B * d, depth,
        )
        warm()

        def body(b, _):
            rows = [consume(b * d + j) for j in range(d)]   # d static
            win = _maj_planes(rows, d, thr)                 # cnt > d//2
            out_ref[pl.ds(b, 1), :] = ~win if minority else win
            return 0

        jax.lax.fori_loop(0, B, body, 0)

    return kernel


@partial(jax.jit, static_argnames=("rule", "block", "depth", "interpret"))
def pallas_packed_step(nbr, sp, *, rule: str = "majority", block: int = 256,
                       depth: int = 8, interpret: bool = False):
    """One synchronous packed update ``sp: uint32[n, W] -> uint32[n, W]``
    for a UNIFORM-ODD-degree graph (``nbr: int32[n, d]``, no ghost slots in
    real rows — callers gate on :func:`pallas_packed_supported`).

    The node axis is padded to ``block`` internally; pad rows gather row 0
    (a real row — harmless, their output is sliced off).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rule = Rule(rule)
    n, d = nbr.shape
    W = sp.shape[1]
    pad = (-n) % block
    n_pad = n + pad
    if pad:
        nbr = jnp.concatenate(
            [nbr, jnp.zeros((pad, d), nbr.dtype)], axis=0
        )
    out = pl.pallas_call(
        _make_kernel(block, d, depth, rule == Rule.MINORITY),
        grid=(n_pad // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((block, W), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, W), sp.dtype),
        scratch_shapes=[
            pltpu.VMEM((depth, W), sp.dtype),
            pltpu.SemaphoreType.DMA((depth,)),
        ],
        interpret=interpret,
    )(nbr, sp)
    return out[:n]


def _make_general_kernel(B: int, dmax: int, depth: int, rule: Rule,
                         tie: str, n_real: int):
    """General-degree packed step: per-node thresholds/even masks from SMEM
    scalars, ghost neighbor slots fetch the all-zero ghost row, the node's
    own spin row arrives as a pipelined input block (rows are contiguous per
    grid block) for the tie-break. Rows at or past ``n_real`` (the ghost row
    and block padding) are forced to zero so the ghost-extended state can be
    carried across steps unchanged."""
    from jax.experimental import pallas as pl

    n_planes = max(int(np.ceil(np.log2(dmax + 1))), 1)
    full = _FULL

    def kernel(nbr_ref, deg_ref, sp_ref, own_ref, out_ref, scratch, sems):
        blk = pl.program_id(0)
        warm, consume = _row_dma_pipeline(
            sp_ref, scratch, sems,
            lambda k: nbr_ref[k // dmax, k % dmax], B * dmax, depth,
        )
        warm()

        def body(b, _):
            rows = [consume(b * dmax + j) for j in range(dmax)]  # static dmax
            planes = [jnp.zeros_like(rows[0]) for _ in range(n_planes)]
            for r in rows:
                _csa_add_one(planes, r)
            deg_b = deg_ref[b]
            thr = deg_b // 2
            thr_bits = [
                jnp.where((thr >> k) & 1 == 1, full, jnp.uint32(0))
                for k in range(n_planes)
            ]
            gt, eq = _compare_planes(planes, thr_bits)
            even_mask = jnp.where(deg_b % 2 == 0, full, jnp.uint32(0))
            win = gt
            tie_mask = eq & even_mask
            own = own_ref[pl.ds(b, 1), :]
            tie_bit = own if tie == "stay" else ~own
            out = win | (tie_mask & tie_bit)
            if rule == Rule.MINORITY:
                loss = ~(win | tie_mask)
                out = loss | (tie_mask & tie_bit)
            # ghost + pad rows stay zero so the carry is reusable
            beyond = (blk * B + b) >= n_real
            out_ref[pl.ds(b, 1), :] = jnp.where(beyond, jnp.uint32(0), out)
            return 0

        jax.lax.fori_loop(0, B, body, 0)

    return kernel


@partial(
    jax.jit,
    static_argnames=("rule", "tie", "n_real", "block", "depth", "interpret"),
)
def _general_step_ext(nbr_pad, deg_pad, sp_ext, *, rule, tie, n_real,
                      block, depth, interpret):
    """One general packed step on the ghost-extended padded state
    ``sp_ext: uint32[n_pad, W]`` (row ``n_real`` = ghost zeros, further rows
    = block padding). Returns the same-shape updated state."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_pad, dmax = nbr_pad.shape
    W = sp_ext.shape[1]
    return pl.pallas_call(
        _make_general_kernel(block, dmax, depth, Rule(rule), tie, n_real),
        grid=(n_pad // block,),
        in_specs=[
            pl.BlockSpec((block, dmax), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((block,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block, W), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, W), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, W), sp_ext.dtype),
        scratch_shapes=[
            pltpu.VMEM((depth, W), sp_ext.dtype),
            pltpu.SemaphoreType.DMA((depth,)),
        ],
        interpret=interpret,
    )(nbr_pad, deg_pad, sp_ext, sp_ext)


@partial(
    jax.jit,
    static_argnames=("steps", "rule", "tie", "block", "depth", "interpret"),
)
# bit-parity tests roll the SAME sp through this kernel and packed_rollout;
# donating sp would invalidate their input buffer
# graftlint: disable-next-line=GD006  parity callers reuse the input state
def pallas_packed_rollout_general(nbr, deg, sp, steps: int,
                                  rule: str = "majority", tie: str = "stay",
                                  *, block: int = 256, depth: int = 8,
                                  interpret: bool = False):
    """General-degree packed rollout (ragged/even degrees, ghost padding,
    all four (rule, tie) pairs) with the same per-row-DMA pipeline as the
    uniform-odd v1 kernel. The ghost-extended state is built once and
    carried across steps (the XLA kernel's ghost-carry design); each node
    costs ``dmax`` row DMAs plus its own-row block read for the tie-break.
    Bit-parity with `packed_rollout` is interpret-mode tested."""
    tie = str(TieBreak(tie).value)
    n, dmax = nbr.shape
    W = sp.shape[1]
    n_pad = -((-(n + 1)) // block) * block        # room for the ghost row
    pad = n_pad - n
    nbr_pad = jnp.concatenate(
        [nbr, jnp.full((pad, dmax), n, nbr.dtype)], axis=0
    )
    deg_pad = jnp.concatenate([deg, jnp.zeros((pad,), deg.dtype)])
    sp_ext = jnp.concatenate(
        [sp, jnp.zeros((pad, W), sp.dtype)], axis=0
    )
    step = partial(
        _general_step_ext, rule=Rule(rule).value, tie=tie, n_real=n,
        block=block, depth=depth, interpret=interpret,
    )
    out = jax.lax.fori_loop(
        0, steps, lambda _, s: step(nbr_pad, deg_pad, s), sp_ext
    )
    return out[:n]


@partial(
    jax.jit, static_argnames=("steps", "rule", "block", "depth", "interpret")
)
# graftlint: disable-next-line=GD006  parity callers reuse the input state
def _rollout_jit(nbr, sp, *, steps, rule, block, depth, interpret):
    step = partial(
        pallas_packed_step, rule=rule, block=block, depth=depth,
        interpret=interpret,
    )
    return jax.lax.fori_loop(0, steps, lambda _, s: step(nbr, s), sp)


def pallas_packed_rollout(nbr, deg, sp, steps: int, rule: str = "majority",
                          tie: str = "stay", *, block: int = 256,
                          depth: int = 8, interpret: bool = False):
    """Drop-in variant of `packed_rollout` for supported shapes (uniform odd
    degree, majority/minority — ``tie`` accepted for signature parity but
    unreachable at odd degree). Raises ValueError when unsupported; callers
    A/B against the XLA kernel explicitly (benchmarks), so silent fallback
    would defeat the measurement. The loop itself is jitted (same caching
    as `packed_rollout`, so rate A/Bs compare kernels, not dispatch)."""
    if not pallas_packed_supported(np.asarray(deg), Rule(rule).value, tie):
        raise ValueError(
            "pallas_packed_rollout v1 requires uniform odd degree and "
            "majority/minority rule"
        )
    return _rollout_jit(
        nbr, sp, steps=steps, rule=Rule(rule).value, block=block,
        depth=depth, interpret=interpret,
    )
