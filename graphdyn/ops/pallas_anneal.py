"""One-kernel annealing: fused LUT-popcount SA with the schedule on device.

The chromatic annealer (:mod:`graphdyn.ops.chromatic`) already runs a whole
distance-2 color class per device step, but its chunk program re-derives
the update from the hand-written comparator, draws uniforms through
``jax.random`` host-key plumbing, and its drive loop polls a stop flag at
every chunk boundary. The p-bit annealers in PAPERS.md (arXiv:2602.16143's
dual-BRAM LUT engine, arXiv:2110.02481's sparse Ising machines) show the
fully fused shape; this module ports it:

- **LUT update** (:mod:`graphdyn.ops.lut`): the dynamics rule is a
  ``[dmax+1, dmax+1, 2]`` popcount table compiled to packed word masks —
  the end-state evaluations of the SA objective run through the table, so
  ANY f(degree, count, spin) rule ships without new word logic (ROADMAP
  item 4's compilation point).
- **Counter-based RNG**: proposal/acceptance uniforms come from an
  explicit Threefry-2x32 with counter ``(step, site)`` — no host key
  stream, no state to carry; the SAME function body generates the bits in
  the Pallas kernel, the XLA twin, and the numpy test oracle, so the
  stream is pinned deterministic per (seed, site, step) and bit-identical
  across execution modes and process restarts.
- **Metropolis acceptance with exact per-site ΔE** via the additive
  end-sum trick the chromatic kernel proved (two LUT one-step evals, CSA
  ball popcounts, disjoint radius-1 balls ⇒ whole-class flip ≡ per-site
  single flips).
- **Device-resident schedule**: the geometric anneal (per-class-step
  ``a·par_a^|class|`` with cap-before-multiply, per replica) advances
  INSIDE the one while loop, so an entire fixed-budget SA run executes
  with zero host transfers between snapshot boundaries.

Two implementations of ONE chain law share :func:`_fused_class_step`
verbatim:

- :func:`fused_chunk_xla` — the jitted XLA program (ONE while loop over
  class steps, donated carry; graftcheck pins its structure as the
  ``fused_anneal`` ledger row). This is the CPU-container contract and the
  fallback.
- :func:`fused_chunk_pallas` — the same loop inside ONE ``pallas_call``:
  state, tables and LUT masks VMEM-resident, uniforms generated in-kernel.
  Interpret mode makes it tier-1-testable off-chip; whether the in-kernel
  gathers beat XLA's is a chip-round question
  (``scripts/pallas_tpu_validate.py`` checklist item 6). A runtime
  lowering failure degrades to the XLA twin through the established
  :func:`graphdyn.ops.bdcm.pallas_fallback_spec` / ``resilient_exec``
  machinery (bit-parity is tested, so the fallback changes throughput,
  not results).

VMEM gate: :func:`fused_vmem_bytes` models the kernel's resident set (the
``vmem_block_edges`` precedent); :func:`fused_kernel_supported` returns
False when the state + tables + per-replica expansion do not fit — the
fused Pallas kernel targets the search regime (the model admits
n ≲ 1.1e4 at W=1 / d=3, ~4e3 at W=4, where time-to-target lives);
larger graphs keep the XLA twin, which still never leaves the chip
between snapshot boundaries.

Replica lanes: the K-lane drive ladder (ROADMAP item 3's composition)
rides the packed replica axis — per-replica ``(a, b, caps)`` vectors, so a
β-scaled drive ladder is one broadcast, 32 lanes per uint32 word. A grid
axis would buy one lane per grid step; the bit-parallel replica axis buys
32 per word, so the ladder shares the kernel rather than the grid.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.config import SAConfig
from graphdyn.ops.chromatic import (
    ChromaticTables,
    accept_apply,
    build_chromatic_tables,
)
from graphdyn.ops.lut import lut_node_masks, lut_one_step, update_lut
from graphdyn.ops.packed import WORD

# key word 1 of the fused proposal stream (key word 0 is the run seed):
# a fixed tag so the fused stream can never collide with jax.random keys
# derived from the same seed
FUSED_STREAM_TAG = 0x464C5554  # b"FLUT"

#: per-core VMEM budget for the fused kernel's resident set — same margin
#: reasoning as ops.pallas_bdcm.VMEM_BUDGET (the model underestimates the
#: compiler's scoped-vmem demand by up to ~33%)
FUSED_VMEM_BUDGET = 10 * 1024 * 1024


# ---------------------------------------------------------------------------
# counter-based RNG (Threefry-2x32) — one body for kernel, XLA and numpy
# ---------------------------------------------------------------------------


def _rotl32(x, r: int):
    """32-bit rotate-left via operators only, so the same body runs on
    numpy uint32 arrays (the test oracle) and traced jnp values (the XLA
    twin and the Pallas kernel)."""
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def threefry2x32(k0, k1, c0, c1):
    """Threefry-2x32 (20 rounds, the jax.random stream cipher): keys
    ``(k0, k1)``, counters ``(c0, c1)`` — uint32 arrays or scalars,
    broadcastable. Returns two uint32 blocks. Operator-only arithmetic so
    numpy and jnp share the body bit-for-bit."""
    ks2 = k0 ^ k1 ^ np.uint32(0x1BD11BDA)
    ks = (k0, k1, ks2)
    x0 = c0 + k0
    x1 = c1 + k1
    rotations = ((13, 15, 26, 6), (17, 29, 16, 24))
    for d in range(5):
        for r in rotations[d % 2]:
            x0 = x0 + x1
            x1 = _rotl32(x1, r) ^ x0
        x0 = x0 + ks[(d + 1) % 3]
        x1 = x1 + ks[(d + 2) % 3] + np.uint32(d + 1)
    return x0, x1


def _bits_to_uniform(bits):
    """uint32 bits → f32 uniforms in [0, 1): the top 24 bits scaled by
    2^-24 — exact in f32 for numpy and XLA alike, so the host oracle and
    both device paths see identical floats."""
    return (bits >> np.uint32(8)).astype(np.float32) * np.float32(
        1.0 / (1 << 24)
    )


def counter_uniforms(seed, step, n: int, Rp: int):
    """The fused proposal stream: f32 uniforms ``[n, Rp]`` for class step
    ``step``, deterministic per ``(seed, site, step)`` where site =
    (node, replica). Layout: key ``(seed, FUSED_STREAM_TAG + replica-pair
    index)``, counter ``(step, node)``; each Threefry block yields the
    uniforms of replicas ``(2j, 2j+1)`` of its node. Independence across
    sites and steps is key/counter distinctness; there is no sequential
    state, so streams are reproducible from (seed, step) alone —
    resume-invariant across chunk boundaries and process restarts — and
    keying (not counting) the replica pair makes them invariant under
    replica-count growth: replicas 0..R−1 of a wider run see the SAME
    stream (pair granularity; the chromatic driver's word-granularity
    contract, sharpened)."""
    pairs = Rp // 2
    node = lax.broadcasted_iota(jnp.uint32, (n, pairs), 0)
    pair = lax.broadcasted_iota(jnp.uint32, (n, pairs), 1)
    k0 = jnp.asarray(seed, jnp.uint32)
    k1 = jnp.uint32(FUSED_STREAM_TAG) + pair
    c0 = jnp.full((n, pairs), 1, jnp.uint32) * jnp.asarray(step, jnp.uint32)
    y0, y1 = threefry2x32(k0, k1, c0, node)
    u = jnp.stack([y0, y1], axis=2).reshape(n, Rp)
    return _bits_to_uniform(u)


def counter_uniforms_np(seed, step, n: int, Rp: int) -> np.ndarray:
    """The numpy mirror of :func:`counter_uniforms` — same Threefry body,
    same key/counter layout, bit-identical floats; the test oracle's
    stream."""
    pairs = Rp // 2
    node = np.broadcast_to(
        np.arange(n, dtype=np.uint32)[:, None], (n, pairs)
    )
    k1 = (np.uint32(FUSED_STREAM_TAG)
          + np.arange(pairs, dtype=np.uint32)[None, :])
    c0 = np.full((n, pairs), np.uint32(step), np.uint32)
    with np.errstate(over="ignore"):
        y0, y1 = threefry2x32(np.uint32(seed), k1, c0, node)
    u = np.stack([y0, y1], axis=2).reshape(n, Rp)
    return _bits_to_uniform(u)


# ---------------------------------------------------------------------------
# tables + VMEM model
# ---------------------------------------------------------------------------


class FusedTables(NamedTuple):
    """Host-side setup of the fused annealer (numpy arrays): the chromatic
    distance-2 machinery plus the LUT word masks and the per-class anneal
    factors (``par**|class|`` — the schedule advances at class
    granularity, mirroring the chromatic chain)."""

    chrom: ChromaticTables
    masks_ext: np.ndarray   # uint32[χ, n+1] — ghost column 0
    lut_masks: np.ndarray   # uint32[dmax+1, 2, n+1]
    fac_a: np.ndarray       # f32[χ]
    fac_b: np.ndarray       # f32[χ]

    @property
    def chi(self) -> int:
        return self.chrom.chi

    @property
    def n(self) -> int:
        return self.chrom.n

    @property
    def dmax(self) -> int:
        return self.chrom.dmax


def build_fused_tables(graph, config: SAConfig | None = None, *,
                       seed: int = 0) -> FusedTables:
    """Distance-2 coloring + LUT masks + anneal factors for ``graph``
    (deterministic per ``seed``; the coloring validity refusal lives in
    :func:`graphdyn.ops.chromatic.build_chromatic_tables`)."""
    config = config or SAConfig()
    dyn = config.dynamics
    chrom = build_chromatic_tables(graph, seed=seed)
    masks_ext = np.concatenate(
        [chrom.masks, np.zeros((chrom.chi, 1), np.uint32)], axis=1
    )
    lut = update_lut(chrom.dmax, dyn.rule, dyn.tie)
    lm = lut_node_masks(chrom.deg_ext, lut)
    sizes = chrom.class_sizes.astype(np.float64)  # graftlint: disable=GD004  host staging; fac cast to f32 below
    fac_a = (config.par_a ** sizes).astype(np.float32)
    fac_b = (config.par_b ** sizes).astype(np.float32)
    return FusedTables(chrom=chrom, masks_ext=masks_ext, lut_masks=lm,
                       fac_a=fac_a, fac_b=fac_b)


def fused_vmem_bytes(n: int, W: int, chi: int, dmax: int) -> int:
    """Resident-set byte model of the fused Pallas kernel (f32/int32 =
    4 B; ``Rp = 32·W`` expanded replica lanes):

    - packed state carry, double-buffered across loop iterations:
      ``2·4·(n+1)·W``
    - CSA planes + count-equality masks: ``(⌈log₂(dmax+1)⌉ + dmax+1)·
      4·(n+1)·W``
    - tables: class masks ``4·χ·(n+1)``, LUT masks ``8·(dmax+1)·(n+1)``,
      neighbor + ball gather tables ``4·(n+1)·(2·dmax+1)``
    - the per-replica expansion (uniforms, ball counts ×2, unpacked
      spins, ΔE, accept mask): ``6·4·(n+1)·Rp`` — the dominant term; the
      32× unpack is what caps the kernel at search-regime n.
    """
    Rp = WORD * W
    n1 = n + 1
    n_planes = max(int(dmax).bit_length(), 1)
    return 4 * n1 * (
        W * (2 + n_planes + dmax + 1)
        + chi
        + 2 * (dmax + 1)
        + (2 * dmax + 1)
        + 6 * Rp
    )


def fused_kernel_supported(n: int, W: int, chi: int, dmax: int,
                           budget: int = FUSED_VMEM_BUDGET) -> bool:
    """Static admission of the fused Pallas kernel: the modeled resident
    set fits the VMEM budget. A False keeps the chain on the XLA twin
    (same chain law — the choice moves throughput, never results)."""
    return fused_vmem_bytes(n, W, chi, dmax) <= budget


# ---------------------------------------------------------------------------
# the chain law: ONE class-step body shared by XLA twin and Pallas kernel
# ---------------------------------------------------------------------------


def _fused_class_step(
    sp_ext, u, mask_row_ext, fa, fb,
    sum_end, a, b, t_target, active, steps, accepted,
    nbr_ext, nbr_self, lut_masks_dev, a_caps, b_caps,
    *, n: int, dmax: int, target_sum: int,
):
    """One fused class step on the ghost-extended packed state: LUT
    end-state evals, exact per-site ΔE from disjoint-ball popcounts,
    Metropolis accept against the caller's uniforms, additive ``Σs_end``,
    per-replica anneal (cap checked before the multiply), first-passage
    record + freeze. Pure function of its inputs — the XLA while body, the
    Pallas kernel loop and the oracle test all call THIS, so the chain law
    cannot drift between execution modes."""
    end = lut_one_step(sp_ext, nbr_ext, lut_masks_dev, n=n, dmax=dmax)
    end_all = lut_one_step(
        sp_ext ^ mask_row_ext[:, None], nbr_ext, lut_masks_dev,
        n=n, dmax=dmax,
    )
    sp_new, acc, dsend_tot = accept_apply(
        sp_ext, end, end_all, u, mask_row_ext[:n], a, b, active,
        nbr_self, n=n,
    )
    sum_end = sum_end + dsend_tot
    a_new = jnp.where(active & (a < a_caps), a * fa, a)
    b_new = jnp.where(active & (b < b_caps), b * fb, b)
    steps = steps + 1
    hit = active & (sum_end >= target_sum)
    t_target = jnp.where(hit, steps, t_target)
    active = active & ~hit
    accepted = accepted + jnp.sum(acc.astype(jnp.int32))
    return (sp_new, sum_end, a_new, b_new, t_target, active, steps,
            accepted)


class FusedState(NamedTuple):
    """Device carry of the fused annealer. The packed state is carried
    ghost-EXTENDED (``[n+1, W]``, ghost word pinned 0) so no per-step
    concatenate re-reads the state (the ``packed_rollout`` ghost-carry
    lesson). Replica axis padded to ``Rp = 32·W``; pad lanes frozen by
    ``active``."""

    sp_ext: jnp.ndarray     # uint32[n+1, W]
    sum_end: jnp.ndarray    # int32[Rp]
    a: jnp.ndarray          # f32[Rp]
    b: jnp.ndarray          # f32[Rp]
    t_target: jnp.ndarray   # int32[Rp] — first-passage class step, −1
    active: jnp.ndarray     # bool[Rp]
    steps: jnp.ndarray      # int32[] — global class-step index (the RNG
    #                         counter, so chunk splits cannot change the
    #                         chain)
    accepted: jnp.ndarray   # int32[]


def _fused_cond_body(masks_ext, facs, nbr_ext, nbr_self, lut_masks_dev,
                     a_caps, b_caps, seed, *, n, dmax, chi, target_sum,
                     chunk_steps, stop_on_first, steps0):
    """The (cond, body) pair of the ONE fused while loop — over flat class
    steps (class index = steps % χ), shared verbatim by the XLA twin and
    the Pallas kernel so GC106's while-count band pins both."""

    def cond(carry):
        st: FusedState = carry
        go = jnp.any(st.active) & (st.steps - steps0 < chunk_steps)
        if stop_on_first:
            go = go & ~jnp.any(st.t_target >= 0)
        return go

    def body(carry):
        st: FusedState = carry
        c_idx = lax.rem(st.steps, jnp.int32(chi))
        mask_row_ext = lax.dynamic_index_in_dim(
            masks_ext, c_idx, 0, keepdims=False
        )
        fa = lax.dynamic_index_in_dim(facs[:, 0], c_idx, 0, keepdims=False)
        fb = lax.dynamic_index_in_dim(facs[:, 1], c_idx, 0, keepdims=False)
        u = counter_uniforms(seed, st.steps.astype(jnp.uint32), n,
                             st.sum_end.shape[0])
        (sp_new, sum_end, a_new, b_new, t_target, active, steps,
         accepted) = _fused_class_step(
            st.sp_ext, u, mask_row_ext, fa, fb,
            st.sum_end, st.a, st.b, st.t_target, st.active, st.steps,
            st.accepted, nbr_ext, nbr_self, lut_masks_dev,
            a_caps, b_caps, n=n, dmax=dmax, target_sum=target_sum,
        )
        return FusedState(sp_new, sum_end, a_new, b_new, t_target, active,
                          steps, accepted)

    return cond, body


@partial(
    jax.jit,
    static_argnames=("n", "dmax", "chi", "target_sum",
                     "chunk_steps", "stop_on_first"),
    donate_argnames=("state",),
)
def fused_chunk_xla(
    state: FusedState,
    seed,
    masks_ext, facs, nbr_ext, nbr_self, lut_masks_dev, a_caps, b_caps,
    *,
    n: int, dmax: int, chi: int, target_sum: int,
    chunk_steps: int, stop_on_first: bool = False,
):
    """Advance up to ``chunk_steps`` class steps as ONE device program —
    one while loop, donated carry (graftcheck's ``fused_anneal`` ledger
    row pins exactly this structure: GC106 while-count 1 per band, GC001
    donation, no baked host constants — every table arrives as an
    argument)."""
    cond, body = _fused_cond_body(
        masks_ext, facs, nbr_ext, nbr_self, lut_masks_dev,
        a_caps, b_caps, jnp.asarray(seed, jnp.uint32),
        n=n, dmax=dmax, chi=chi,
        target_sum=target_sum, chunk_steps=chunk_steps,
        stop_on_first=stop_on_first, steps0=state.steps,
    )
    return lax.while_loop(cond, body, state)


# ---------------------------------------------------------------------------
# the Pallas kernel: the same loop inside one pallas_call
# ---------------------------------------------------------------------------


def _make_fused_kernel(*, n, dmax, chi, target_sum,
                       chunk_steps, stop_on_first):
    def kernel(
        seed_ref, cnt_ref,                       # SMEM scalars
        sp_ref, se_ref, a_ref, b_ref, tt_ref, act_ref,   # state (aliased)
        masks_ref, facs_ref, nbr_ref, nbrs_ref, lutm_ref,  # tables
        acap_ref, bcap_ref,
        sp_out, se_out, a_out, b_out, tt_out, act_out, cnt_out,
    ):
        state = FusedState(
            sp_ext=sp_ref[:],
            sum_end=se_ref[0, :],
            a=a_ref[0, :],
            b=b_ref[0, :],
            t_target=tt_ref[0, :],
            active=act_ref[0, :] != 0,
            steps=cnt_ref[0],
            accepted=cnt_ref[1],
        )
        cond, body = _fused_cond_body(
            masks_ref[:], facs_ref[:], nbr_ref[:], nbrs_ref[:], lutm_ref[:],
            acap_ref[0, :], bcap_ref[0, :], seed_ref[0],
            n=n, dmax=dmax, chi=chi,
            target_sum=target_sum, chunk_steps=chunk_steps,
            stop_on_first=stop_on_first, steps0=cnt_ref[0],
        )
        st = lax.while_loop(cond, body, state)
        sp_out[:] = st.sp_ext
        se_out[0, :] = st.sum_end
        a_out[0, :] = st.a
        b_out[0, :] = st.b
        tt_out[0, :] = st.t_target
        act_out[0, :] = st.active.astype(jnp.int32)
        cnt_out[0] = st.steps
        cnt_out[1] = st.accepted

    return kernel


@partial(
    jax.jit,
    static_argnames=("n", "dmax", "chi", "target_sum",
                     "chunk_steps", "stop_on_first", "interpret"),
    donate_argnames=("state",),
)
def fused_chunk_pallas(
    state: FusedState,
    seed,
    masks_ext, facs, nbr_ext, nbr_self, lut_masks_dev, a_caps, b_caps,
    *,
    n: int, dmax: int, chi: int, target_sum: int,
    chunk_steps: int, stop_on_first: bool = False,
    interpret: bool = False,
):
    """The fused chunk as ONE ``pallas_call``: the whole state + tables
    sit VMEM-resident (gate via :func:`fused_kernel_supported`), the while
    loop runs inside the kernel, uniforms are generated in-kernel from the
    counter RNG, and the state buffers are input/output-aliased (the
    donation contract). Bit-identical to :func:`fused_chunk_xla` — the
    loop body IS :func:`_fused_class_step` in both (tested, interpret
    mode)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    W = state.sp_ext.shape[1]
    Rp = state.sum_end.shape[0]
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    kernel = _make_fused_kernel(
        n=n, dmax=dmax, chi=chi,
        target_sum=target_sum, chunk_steps=chunk_steps,
        stop_on_first=stop_on_first,
    )
    out = pl.pallas_call(
        kernel,
        in_specs=[smem, smem] + [vmem] * 13,
        out_specs=(vmem, vmem, vmem, vmem, vmem, vmem, smem),
        out_shape=(
            jax.ShapeDtypeStruct((n + 1, W), jnp.uint32),    # sp_ext
            jax.ShapeDtypeStruct((1, Rp), jnp.int32),        # sum_end
            jax.ShapeDtypeStruct((1, Rp), jnp.float32),      # a
            jax.ShapeDtypeStruct((1, Rp), jnp.float32),      # b
            jax.ShapeDtypeStruct((1, Rp), jnp.int32),        # t_target
            jax.ShapeDtypeStruct((1, Rp), jnp.int32),        # active
            jax.ShapeDtypeStruct((2,), jnp.int32),           # counters
        ),
        # state buffers update in place chunk-to-chunk: inputs 2..7 alias
        # outputs 0..5, the counter scalar pair aliases output 6
        input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3, 6: 4, 7: 5, 1: 6},
        interpret=interpret,
    )(
        jnp.asarray(seed, jnp.uint32).reshape(1),
        jnp.stack([state.steps.astype(jnp.int32),
                   state.accepted.astype(jnp.int32)]),
        state.sp_ext,
        state.sum_end.reshape(1, Rp),
        state.a.reshape(1, Rp),
        state.b.reshape(1, Rp),
        state.t_target.reshape(1, Rp),
        state.active.astype(jnp.int32).reshape(1, Rp),
        masks_ext, facs, nbr_ext, nbr_self, lut_masks_dev,
        a_caps.reshape(1, Rp), b_caps.reshape(1, Rp),
    )
    sp_ext, se, a, b, tt, act, cnt = out
    return FusedState(
        sp_ext=sp_ext,
        sum_end=se[0],
        a=a[0],
        b=b[0],
        t_target=tt[0],
        active=act[0] != 0,
        steps=cnt[0],
        accepted=cnt[1],
    )


# ---------------------------------------------------------------------------
# mode resolution + runtime fallback (the shared bdcm machinery)
# ---------------------------------------------------------------------------


class _FusedSpec(NamedTuple):
    """Kernel-mode holder duck-typed for
    :func:`graphdyn.ops.bdcm.pallas_fallback_spec` (the ``pallas`` tuple
    protocol): ``('tpu',)`` compiled kernel, ``('interpret',)`` interpret
    mode (off-chip tests), ``('',)`` the XLA twin."""

    pallas: tuple


def resolve_fused_mode(kernel: str, *, n: int, W: int, chi: int,
                       dmax: int) -> _FusedSpec:
    """Static kernel choice: ``'auto'`` takes the Pallas kernel on TPU
    backends when the VMEM model admits the shape; ``'pallas'`` forces it
    (interpret mode off-TPU — a test mode, not a throughput mode);
    ``'xla'`` keeps the twin. Runtime lowering failures degrade through
    :func:`graphdyn.ops.bdcm.resilient_exec`."""
    if kernel not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"kernel must be 'auto', 'xla' or 'pallas', got {kernel!r}"
        )
    # the tunneled plugin reports "tpu"; hedge "axon" like every other
    # chip-backend allowlist (bdcm._pallas_class_modes, bench.on_chip)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    fits = fused_kernel_supported(n, W, chi, dmax)
    if kernel == "xla":
        return _FusedSpec(("",))
    if kernel == "pallas":
        return _FusedSpec(("tpu",) if on_tpu else ("interpret",))
    return _FusedSpec(("tpu",) if (on_tpu and fits) else ("",))


def fused_chunk(state: FusedState, seed, tables_dev, spec: _FusedSpec,
                **kwargs) -> FusedState:
    """Dispatch one fused chunk under ``spec``'s mode. ``tables_dev`` is
    the 7-tuple of device tables ``(masks_ext, facs, nbr_ext, nbr_self,
    lut_masks, a_caps, b_caps)`` — the order of
    ``fused_chunk_xla``/``fused_chunk_pallas``'s positional table args,
    as ``search.fused._assemble_fused`` builds it."""
    mode = spec.pallas[0]
    if mode:
        return fused_chunk_pallas(
            state, seed, *tables_dev,
            interpret=(mode == "interpret"), **kwargs,
        )
    return fused_chunk_xla(state, seed, *tables_dev, **kwargs)
