"""L3 kernels: synchronous spin dynamics, BDCM message passing, Pallas TPU
kernels."""

from graphdyn.ops.dynamics import (  # noqa: F401
    Rule,
    TieBreak,
    step_spins,
    run_dynamics,
    end_state,
)
