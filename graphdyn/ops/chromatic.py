"""Chromatic block Metropolis — a whole independent set per device step.

The reference SA chain (`SA_RRG.py:58-88`) proposes ONE site per MCMC step;
even the light-cone path (PR `ops/lightcone`) evaluates one radius-(p+c−1)
ball per device step. The massively parallel sparse Ising machines
(PAPERS.md arXiv:2110.02481) instead update an entire independent set per
tick. This module is that idea for the SA search objective
``E(s) = (a·Σs(0) − b·Σs_end)/n`` at ``p = c = 1`` (one-step rollout):

- A **distance-2 coloring** (:func:`graphdyn.graphs.greedy_coloring` over
  :func:`graphdyn.graphs.power_graph`\\ ``(g, 2)``) puts same-color sites at
  pairwise distance ≥ 3, so their radius-1 update balls are disjoint: per-
  site ΔE of a single flip stays EXACT when the whole class flips together,
  and the per-site Metropolis accepts are a product of independent kernels
  on non-interacting coordinates — detailed balance per class, a valid
  chain per sweep (the standard chromatic Gibbs decomposition).
- One device step proposes and accepts **every site of one color class at
  once** via the packed popcount helpers (:mod:`graphdyn.ops.packed`:
  carry-save bit-plane counters + the word comparator): ΔΣs_end of site
  ``i`` is read off two packed one-step evaluations — ``end(s)`` and
  ``end(s ⊕ class)`` — because each node ``j`` has at most ONE class member
  in ``N(j) ∪ {j}``, so the all-class flip restricted to ``ball(i)`` IS the
  single flip of ``i``. Disjoint balls also make the per-replica
  ``Σs_end`` update additive, so the target-magnetization test costs one
  masked reduction, not a re-evaluation.
- A full sweep is **O(χ) device steps** instead of n: greedy coloring of
  ``G²`` gives χ ≤ dmax²+1 (measured χ(G²)=7–11 on the d=3 RRG), replacing
  one-light-cone-per-step serialism with ~n/χ proposals per device step.

Annealing follows the reference schedule per proposal-equivalent: one class
step of ``|class c|`` proposals multiplies ``a``/``b`` by ``par^|c|`` (cap
checked once per class step, before the multiply, mirroring
`SA_RRG.py:80-81` at class granularity). The chromatic chain is a different
(parallel) Markov chain from the serial reference — sweeps are
seed-deterministic and bit-reproducible, but not bit-equal to the serial
walk; the A/B contract is the ``tta_*`` bench rows, not bit parity.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.ops.dynamics import Rule, TieBreak
from graphdyn.ops.packed import (
    WORD,
    _FULL,
    _compare_planes,
    _csa_add_one,
    _rule_tie_combine,
)


class ChromaticTables(NamedTuple):
    """Host-side setup for the chromatic kernel (numpy arrays).

    Attributes:
      colors:      int32[n] distance-2 color per node (proper on ``G²``).
      masks:       uint32[χ, n] word masks — all-ones where ``colors == c``.
      class_sizes: int64[χ] proposals per class step (the anneal exponents).
      nbr_self:    int32[n+1, dmax+1] ghost-extended ``{i} ∪ N(i)`` gather
                   table (slot 0 = self), ghost row all-ghost.
      nbr_ext:     int32[n+1, dmax] ghost-extended neighbor table.
      deg_ext:     int32[n+1] degrees with the 0-degree ghost row.
    """

    colors: np.ndarray
    masks: np.ndarray
    class_sizes: np.ndarray
    nbr_self: np.ndarray
    nbr_ext: np.ndarray
    deg_ext: np.ndarray

    @property
    def chi(self) -> int:
        return self.masks.shape[0]

    @property
    def n(self) -> int:
        return self.masks.shape[1]

    @property
    def dmax(self) -> int:
        return self.nbr_ext.shape[1]


def build_chromatic_tables(graph, *, seed: int = 0) -> ChromaticTables:
    """Distance-2 coloring + gather tables for ``graph`` (deterministic per
    ``seed``). Refuses an invalid coloring loudly: a monochromatic ``G²``
    edge would make the whole-class update silently wrong."""
    from graphdyn.graphs import (
        greedy_coloring, power_graph, validate_coloring,
    )

    n = graph.n
    g2 = power_graph(graph, 2)
    colors = greedy_coloring(g2, seed=seed)
    problems = validate_coloring(g2, colors)
    if problems:
        raise ValueError(
            f"distance-2 coloring invalid for the chromatic kernel: "
            f"{problems} (greedy_coloring(power_graph(g, 2)) is the "
            f"supported construction)"
        )
    chi = int(colors.max(initial=-1)) + 1
    masks = np.zeros((chi, n), np.uint32)
    for c in range(chi):
        masks[c, colors == c] = np.uint32(0xFFFFFFFF)
    class_sizes = np.bincount(colors, minlength=chi).astype(np.int64)
    nbr_ext = np.concatenate(
        [graph.nbr.astype(np.int64),
         np.full((1, graph.dmax), n, np.int64)], axis=0,
    )
    self_col = np.concatenate([np.arange(n, dtype=np.int64), [n]])[:, None]
    nbr_self = np.concatenate([self_col, nbr_ext], axis=1)
    deg_ext = np.concatenate([graph.deg.astype(np.int64), [0]])
    return ChromaticTables(
        colors=colors.astype(np.int32),
        masks=masks,
        class_sizes=class_sizes,
        nbr_self=nbr_self.astype(np.int32),
        nbr_ext=nbr_ext.astype(np.int32),
        deg_ext=deg_ext.astype(np.int32),
    )


def _threshold_words(deg_ext, n_planes: int):
    """Per-node comparator constants of the packed update (the same
    derivation as ``ops.packed._packed_rollout_device``): threshold
    bit-plane masks + the even-degree tie mask."""
    thr = (deg_ext // 2).astype(jnp.uint32)
    even_mask = jnp.where(deg_ext % 2 == 0, _FULL, jnp.uint32(0))[:, None]
    thr_bits = [
        jnp.where((thr >> k) & 1 == 1, _FULL, jnp.uint32(0))[:, None]
        for k in range(n_planes)
    ]
    return thr_bits, even_mask


def _one_step(sp_ext, nbr_ext, thr_bits, even_mask, n: int, dmax: int,
              rule: Rule, tie: TieBreak):
    """One synchronous packed update on the ghost-extended state — the
    ``end(s)`` evaluation (p=c=1 rollout) built from the shared carry-save
    + comparator helpers; the ghost word is forced back to zero."""
    n_planes = len(thr_bits)
    planes = [jnp.zeros_like(sp_ext) for _ in range(n_planes)]
    for j in range(dmax):
        _csa_add_one(planes, jnp.take(sp_ext, nbr_ext[:, j], axis=0))
    gt, eq = _compare_planes(planes, thr_bits)
    out = _rule_tie_combine(gt, eq & even_mask, sp_ext, rule, tie)
    return out.at[n].set(jnp.uint32(0))


def _ball_counts(bits_ext, nbr_self):
    """Per-(node, replica) popcount of ``bits`` over ``{i} ∪ N(i)``:
    carry-save planes over the self+neighbor gather, expanded to int32
    ``[n+1, W·32]`` replica counts (counts ≤ dmax+1)."""
    slots = nbr_self.shape[1]
    n_planes = max(int(slots).bit_length(), 1)
    planes = [jnp.zeros_like(bits_ext) for _ in range(n_planes)]
    for j in range(slots):
        _csa_add_one(planes, jnp.take(bits_ext, nbr_self[:, j], axis=0))
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    rows = bits_ext.shape[0]
    tot = jnp.zeros((rows, bits_ext.shape[1] * WORD), jnp.int32)
    for k, pl in enumerate(planes):
        b = ((pl[:, :, None] >> shifts) & jnp.uint32(1)).astype(jnp.int32)
        tot = tot + (b.reshape(rows, -1) << k)
    return tot


def _unpack_pm1(sp):
    """uint32[n, W] -> int32[n, W·32] spins (±1) per replica column."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = ((sp[:, :, None] >> shifts) & jnp.uint32(1)).astype(jnp.int32)
    return 2 * bits.reshape(sp.shape[0], -1) - 1


def _pack_bool(acc, W: int):
    """bool[n, W·32] -> uint32[n, W] (bit r%32 of word r//32)."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    b = acc.reshape(acc.shape[0], W, WORD).astype(jnp.uint32) << shifts
    return b.sum(axis=2).astype(jnp.uint32)


def accept_apply(sp_ext, end, end_all, u, class_mask, a, b, active,
                 nbr_self, *, n: int):
    """The exact-single-flip accept-and-apply core — ONE implementation
    shared by the chromatic kernel (:func:`class_update`) and the fused
    annealer (:func:`graphdyn.ops.pallas_anneal._fused_class_step`), so
    the acceptance arithmetic cannot drift between the two chains that
    both claim it: ΔΣ of every class site read off the two one-step
    evaluations via disjoint-ball popcounts, per-(site, replica)
    Metropolis accepts against the caller's uniforms, accepted flips
    XORed back into the packed words, additive per-replica ΔΣ total.
    ``class_mask`` is the UNextended ``uint32[n]`` class word mask.
    Returns ``(sp_ext_new, acc, dsend_tot)``."""
    dt = a.dtype
    up = end_all & ~end                    # j: end −1 → +1 under the flip
    dn = end & ~end_all
    dsend = 2 * (_ball_counts(up, nbr_self)[:n]
                 - _ball_counts(dn, nbr_self)[:n])      # int32 [n, Rp]
    s_pm = _unpack_pm1(sp_ext[:n])                       # int32 [n, Rp]
    delta_e = (
        -2.0 * a[None, :] * s_pm.astype(dt)
        - b[None, :] * dsend.astype(dt)
    ) / n
    in_class = (class_mask != 0)[:, None]
    acc = (u < jnp.exp(-delta_e)) & in_class & active[None, :]
    flips = _pack_bool(acc, sp_ext.shape[1])
    sp_new = sp_ext.at[:n].set(sp_ext[:n] ^ flips)
    dsend_tot = jnp.sum(dsend * acc.astype(jnp.int32), axis=0)
    return sp_new, acc, dsend_tot


def class_update(sp_ext, u, mask_row, anneal_pow, a, b, active,
                 nbr_ext, nbr_self, thr_bits, even_mask, *,
                 n: int, dmax: int, rule: Rule, tie: TieBreak,
                 par_a: float, par_b: float, a_cap: float, b_cap: float):
    """One chromatic class step: propose flipping EVERY site of the class,
    accept per site with the exact single-flip ΔE, then anneal by the
    class's proposal count. Pure function of its inputs (the jitted sweep
    scans it; the oracle test calls it directly with injected ``u``).

    Returns ``(sp_ext_new, dsend_tot, a_new, b_new, n_accepted)`` where
    ``dsend_tot[r]`` is the exact per-replica change of ``Σs_end`` (the
    disjoint-ball additivity the distance-2 coloring guarantees).
    """
    dt = a.dtype
    end = _one_step(sp_ext, nbr_ext, thr_bits, even_mask, n, dmax, rule, tie)
    flip_all = jnp.concatenate([mask_row, jnp.zeros((1,), jnp.uint32)])
    end_all = _one_step(sp_ext ^ flip_all[:, None], nbr_ext, thr_bits,
                        even_mask, n, dmax, rule, tie)
    sp_new, acc, dsend_tot = accept_apply(
        sp_ext, end, end_all, u, mask_row, a, b, active, nbr_self, n=n,
    )
    # per-proposal-equivalent anneal at class granularity (cap checked
    # before the multiply, as the reference does per step)
    fac_a = jnp.asarray(par_a, dt) ** anneal_pow.astype(dt)
    fac_b = jnp.asarray(par_b, dt) ** anneal_pow.astype(dt)
    a_new = jnp.where(active & (a < a_cap), a * fac_a, a)
    b_new = jnp.where(active & (b < b_cap), b * fac_b, b)
    n_acc = jnp.sum(acc.astype(jnp.int32))
    return sp_new, dsend_tot, a_new, b_new, n_acc


class ChromState(NamedTuple):
    """Device carry of the chromatic annealer (replica axis padded to
    ``W·32``; pad replicas are frozen by ``active``)."""

    sp: jnp.ndarray         # uint32[n, W]
    sum_end: jnp.ndarray    # int32[Rp] — Σ s_end per replica (additive)
    a: jnp.ndarray          # f32[Rp]
    b: jnp.ndarray          # f32[Rp]
    steps: jnp.ndarray      # int32[] — class (device) steps taken
    sweeps: jnp.ndarray     # int32[] — full sweeps taken
    t_target: jnp.ndarray   # int32[Rp] — first-passage class step, −1
    active: jnp.ndarray     # bool[Rp]
    accepted: jnp.ndarray   # int32[] — cumulative accepted flips
    chunk_s: jnp.ndarray    # int32[] — sweeps advanced this chunk


@partial(
    jax.jit,
    static_argnames=("n", "dmax", "rule", "tie", "par_a", "par_b",
                     "a_cap", "b_cap", "target_sum", "chunk_sweeps",
                     "stop_on_first"),
    donate_argnames=("state",),
)
def chromatic_chunk(
    state: ChromState,
    key,
    masks,          # uint32[χ, n]
    class_sizes,    # int32[χ]
    nbr_ext, nbr_self, deg_ext,
    *,
    n: int, dmax: int, rule: str, tie: str,
    par_a: float, par_b: float, a_cap: float, b_cap: float,
    target_sum: int, chunk_sweeps: int, stop_on_first: bool = False,
):
    """Advance up to ``chunk_sweeps`` full sweeps (each = one scanned pass
    over the χ color classes) in ONE device program: uniforms derive from
    ``fold_in(key, global class-step index)`` so sweeps are bit-reproducible
    per seed and resume-invariant across chunk boundaries. A replica whose
    ``Σs_end`` reaches ``target_sum`` records its first-passage step and
    freezes; with ``stop_on_first`` the chunk exits once any replica has."""
    rule_e, tie_e = Rule(rule), TieBreak(tie)
    n_planes = max(int(dmax).bit_length(), 1)
    thr_bits, even_mask = _threshold_words(deg_ext, n_planes)

    def class_body(carry, xs):
        sp, sum_end, a, b, steps, t_tgt, active, accepted = carry
        mask_row, n_c = xs
        # one uniform block per 32-replica WORD, keyed (step, word): replica
        # r's proposal stream depends only on its word index, so growing
        # the replica set (more words) leaves existing replicas' sweeps
        # bit-identical — reproducibility across replica counts at word
        # granularity (tested)
        step_key = jax.random.fold_in(key, steps.astype(jnp.uint32))
        u = jnp.concatenate(
            [jax.random.uniform(jax.random.fold_in(step_key, jnp.uint32(w)),
                                (n, WORD), a.dtype)
             for w in range(sp.shape[1])], axis=1,
        )
        sp_ext = jnp.concatenate(
            [sp, jnp.zeros((1, sp.shape[1]), sp.dtype)], axis=0
        )
        sp_ext, dsend_tot, a, b, n_acc = class_update(
            sp_ext, u, mask_row, n_c, a, b, active,
            nbr_ext, nbr_self, thr_bits, even_mask,
            n=n, dmax=dmax, rule=rule_e, tie=tie_e,
            par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
        )
        sum_end = sum_end + dsend_tot
        steps = steps + 1
        hit = active & (sum_end >= target_sum)
        t_tgt = jnp.where(hit, steps, t_tgt)
        active = active & ~hit
        return (sp_ext[:n], sum_end, a, b, steps, t_tgt, active,
                accepted + n_acc), None

    def sweep_body(st: ChromState):
        carry = (st.sp, st.sum_end, st.a, st.b, st.steps, st.t_target,
                 st.active, st.accepted)
        carry, _ = lax.scan(class_body, carry, (masks, class_sizes))
        sp, sum_end, a, b, steps, t_tgt, active, accepted = carry
        return ChromState(sp, sum_end, a, b, steps, st.sweeps + 1, t_tgt,
                          active, accepted, st.chunk_s + 1)

    def cond(st: ChromState):
        go = jnp.any(st.active) & (st.chunk_s < chunk_sweeps)
        if stop_on_first:
            go = go & ~jnp.any(st.t_target >= 0)
        return go

    return lax.while_loop(cond, sweep_body, state)


def replica_end_sums(sp, nbr_ext, deg_ext, n: int, dmax: int,
                     rule: str, tie: str):
    """int32 per-replica ``Σ s_end`` of the packed state (one synchronous
    step, then a column popcount) — the ``sum_end`` initializer."""
    n_planes = max(int(dmax).bit_length(), 1)
    thr_bits, even_mask = _threshold_words(jnp.asarray(deg_ext), n_planes)
    sp_ext = jnp.concatenate(
        [jnp.asarray(sp), jnp.zeros((1, np.shape(sp)[1]), jnp.uint32)],
        axis=0,
    )
    end = _one_step(sp_ext, jnp.asarray(nbr_ext), thr_bits, even_mask,
                    n, dmax, Rule(rule), TieBreak(tie))[:n]
    bits = _unpack_pm1(end)          # ±1 per (node, replica)
    return jnp.sum(bits, axis=0).astype(jnp.int32)
