"""Out-of-core streamed rollout — larger-than-HBM graphs + live churn.

Every resident kernel (:mod:`graphdyn.ops.packed`,
:mod:`graphdyn.ops.bucketed`) holds the FULL neighbor table and state in
device memory, so the largest graph the system can run is the largest
table that fits — serve admission simply refuses anything bigger
(ROADMAP item 3, the last structural memory cliff). Here the node axis
is partitioned into host-resident **chunks**: only the active chunk's
state slab + neighbor table live on device, and while the device steps
chunk ``c`` a :class:`graphdyn.pipeline.prefetch.HostPrefetcher` lane
gathers + uploads chunk ``c+1``'s slab in the background — the boundary-
overlap discipline of the TPU Ising kernels (PAPERS.md arXiv:1903.11714)
applied to the host↔device seam instead of the core↔core seam. ``obs``
spans attribute the h2d/d2h bytes per step and the driver emits the
measured ``stream.overlap_util`` gauge, so the overlap is evidence, not
assumption.

Exactness is structural: every chunk applies the SAME carry-save
bit-plane popcount / bitwise comparator as the resident kernels — the
shared helpers imported from :mod:`graphdyn.ops.packed` and
:mod:`graphdyn.ops.bucketed` — and integer popcounts are exact and
order-independent, so a node's update is identical whether its neighbor
state arrives from a resident table or a streamed slab. The rollout is
**bit-exact** to ``packed_rollout`` / ``bucketed_rollout_global`` on any
graph small enough to run both (tested across the rule × tie ×
RRG/power-law matrix).

On top of the chunk boundaries rides the **mutation stream**: batches of
edges arriving/expiring mid-rollout (:class:`ChurnBatch`), applied at
the synchronous step boundary with an incremental table rebuild of only
the touched chunks — the evolving-adjacency workload the sparse Ising
machines treat as first-class (PAPERS.md arXiv:2110.02481). Every
applied batch is journaled (``stream.churn`` op) next to the checkpoint,
so a preempted run replays the identical churn sequence bit-exactly
through the PR-9/10 requeue machinery **from the journal alone** — the
schedule is never consulted for steps the journal already covers.
"""

from __future__ import annotations

from functools import partial
from typing import Iterable, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from graphdyn import obs
from graphdyn.graphs import Graph, degree_buckets
from graphdyn.ops.bucketed import (
    UNROLL_MAX,
    _csa_bucket,
    _pack_lanes,
    _wide_bucket_counts,
)
from graphdyn.ops.dynamics import Rule, TieBreak
from graphdyn.ops.packed import _FULL, _compare_planes, _rule_tie_combine
from graphdyn.obs.memband import streamed_chunk_bytes

__all__ = [
    "StreamChunk", "StreamPlan", "ChurnBatch", "build_stream_plan",
    "chunk_device_bytes", "plan_device_bytes", "streamed_rollout",
    "seeded_churn", "lower_streamed_chunk",
]


def _pow2_width(dmax: int) -> int:
    """The padded slot width for a chunk of max degree ``dmax`` — the
    :func:`graphdyn.graphs.degree_buckets` power-of-two convention
    (degrees 0/1 share width 1; wide widths ≥ 64 are automatically
    multiples of :data:`~graphdyn.ops.bucketed.UNROLL_MAX`, the segment
    requirement of the wide CSA path)."""
    return 1 << int(max(int(dmax) - 1, 0)).bit_length()


class StreamChunk(NamedTuple):
    """One host-resident chunk of the node axis (host numpy).

    The chunk OWNS ``nodes`` (the global ids it updates); its device
    working set is the **slab** — the packed state rows of ``gids``
    (owned nodes ∪ their neighbors, sorted global ids) plus one ghost
    zero row at local index ``len(gids)``. ``nbr_loc`` indexes the slab
    (ghost-padded), ``self_loc`` maps each owned node to its slab row.

    Attributes:
      nodes:   int64[C] owned global node ids.
      gids:    int64[M] global ids whose state the slab carries (sorted;
               a superset of ``nodes``).
      nbr_loc: int32[C, w] slab-local neighbor table, ghost = M, with
               ``w`` the chunk's power-of-two padded width.
      deg:     int32[C] true degrees of the owned nodes.
      self_loc: int32[C] slab row of each owned node.
    """

    nodes: np.ndarray
    gids: np.ndarray
    nbr_loc: np.ndarray
    deg: np.ndarray
    self_loc: np.ndarray

    @property
    def C(self) -> int:
        return self.nodes.size

    @property
    def M(self) -> int:
        return self.gids.size

    @property
    def width(self) -> int:
        return self.nbr_loc.shape[1]


class StreamPlan(NamedTuple):
    """The chunked layout of one graph: every node owned by exactly one
    chunk (``chunk_of[i]``), chunks walked in order each synchronous
    step. Built by :func:`build_stream_plan`; rebuilt incrementally per
    touched chunk when churn mutates the adjacency."""

    n: int
    chunks: tuple
    chunk_of: np.ndarray

    @property
    def K(self) -> int:
        return len(self.chunks)


def chunk_device_bytes(C: int, M: int, width: int, W: int) -> int:
    """Device-resident bytes of ONE chunk's step at ``W`` state words:
    slab ``4·(M+1)·W`` (+ ghost row) + neighbor table ``4·C·w`` + degree
    and self-row vectors ``8·C`` + output block ``4·C·W``. The quantity
    the ``streamed_state_bytes`` memband model charges per chunk and
    :func:`build_stream_plan`'s budget mode packs against. The formula
    itself lives in :func:`graphdyn.obs.memband.streamed_chunk_bytes`
    (a registered graftcost HAND_MODELS adapter, gated against the
    HLO-derived model); this is the ops-side alias."""
    return streamed_chunk_bytes(C, M, width, W)


def plan_device_bytes(plan: StreamPlan, W: int) -> int:
    """Peak modeled device bytes of the plan: the two largest chunks
    resident at once (active + prefetched) under double-buffering."""
    per = sorted(
        (chunk_device_bytes(c.C, c.M, c.width, W) for c in plan.chunks),
        reverse=True,
    )
    return sum(per[:2]) if len(per) > 1 else (per[0] if per else 0)


def _adjacency_lists(graph: Graph) -> list[np.ndarray]:
    """Per-node neighbor id arrays (sorted) from the padded table."""
    return [
        np.sort(graph.nbr[i, : graph.deg[i]].astype(np.int64))
        for i in range(graph.n)
    ]


def _build_chunk(nodes: np.ndarray, adj: list[np.ndarray]) -> StreamChunk:
    """Materialize one chunk's slab-local tables from the adjacency."""
    nodes = np.asarray(nodes, np.int64)
    degs = np.array([adj[i].size for i in nodes], np.int64)
    width = _pow2_width(int(degs.max()) if nodes.size else 0)
    nbr_cat = (np.concatenate([adj[i] for i in nodes])
               if nodes.size else np.empty(0, np.int64))
    gids = np.unique(np.concatenate([nodes, nbr_cat]))
    M = gids.size
    # global -> slab row (gids is sorted, so searchsorted is the inverse)
    self_loc = np.searchsorted(gids, nodes)
    nbr_loc = np.full((nodes.size, width), M, np.int64)
    if nbr_cat.size:
        loc_cat = np.searchsorted(gids, nbr_cat)
        pos = 0
        for r, d in enumerate(degs):
            nbr_loc[r, :d] = loc_cat[pos:pos + d]
            pos += d
    return StreamChunk(
        nodes=nodes, gids=gids,
        nbr_loc=nbr_loc.astype(np.int32),
        deg=degs.astype(np.int32),
        self_loc=self_loc.astype(np.int32),
    )


def _split_stream_groups(order: np.ndarray, adj: list[np.ndarray], *,
                         W: int, n_chunks: int | None = None,
                         device_budget_bytes: int | None = None,
                         n_total: int | None = None) -> list[np.ndarray]:
    """The chunk-grouping walk shared by the single-device plan and the
    per-shard runs of the sharded plan: split ``order`` (degree-ascending
    node ids) into contiguous groups, either ``n_chunks`` equal slices or
    greedily packed against half of ``device_budget_bytes`` (two chunks
    resident at once under double-buffered prefetch). ``n_total`` only
    shapes the ``n_chunks`` range error message."""
    if (n_chunks is None) == (device_budget_bytes is None):
        raise ValueError(
            "pass exactly one of n_chunks or device_budget_bytes"
        )
    order = np.asarray(order, np.int64)
    if n_total is None:
        n_total = order.size
    groups: list[np.ndarray] = []
    if n_chunks is not None:
        if not 1 <= n_chunks <= max(n_total, 1):
            raise ValueError(
                f"n_chunks must be in [1, {n_total}], got {n_chunks}"
            )
        parts = min(n_chunks, max(order.size, 1))
        groups = [g for g in np.array_split(order, parts) if g.size]
    else:
        half = device_budget_bytes // 2
        cur: list[int] = []
        c = deg_sum = 0
        for i in order:
            d = adj[i].size
            # degrees ascend along the walk, so the newest node's
            # power-of-two width bounds the whole candidate block
            w = _pow2_width(d)
            est = chunk_device_bytes(
                c + 1, (c + 1) + deg_sum + d, w, W)
            if cur and est > half:
                groups.append(np.asarray(cur, np.int64))
                cur, c, deg_sum = [], 0, 0
                est = chunk_device_bytes(1, 1 + d, w, W)
            if est > half:
                raise ValueError(
                    f"node {int(i)} (degree {d}) alone needs {est} B — "
                    f"over half the {device_budget_bytes} B device "
                    f"budget; the graph cannot be streamed at W={W}"
                )
            cur.append(int(i))
            c += 1
            deg_sum += d
        if cur:
            groups.append(np.asarray(cur, np.int64))
    return groups


def build_stream_plan(graph: Graph, *, W: int, n_chunks: int | None = None,
                      device_budget_bytes: int | None = None,
                      adj: list[np.ndarray] | None = None,
                      partition=None):
    """Partition the node axis into host-resident chunks.

    Nodes are walked in :func:`graphdyn.graphs.degree_buckets` order
    (degree-ascending) so each chunk's power-of-two padded width is tight
    — the same layout economics as the bucketed kernel, per chunk.

    Exactly one of ``n_chunks`` (fixed chunk count, contiguous equal
    slices) or ``device_budget_bytes`` must be given. Budget mode packs
    greedily: a chunk closes when its modeled bytes
    (:func:`chunk_device_bytes`, using the conservative slab bound
    ``M ≤ C + Σdeg``) would exceed **half** the budget — two chunks are
    resident at once under double-buffered prefetch. Raises
    ``ValueError`` when even a single node cannot fit (admission performs
    the same feasibility check up front).

    ``partition=`` (a :class:`graphdyn.graphs.Partition`) switches to the
    SHARDED plan: each of P shards owns a part-major contiguous run of
    chunks (its owned non-hub nodes, degree-ascending; hubs stay
    vertex-cut replicated) and ``n_chunks``/``device_budget_bytes`` apply
    PER SHARD. Returns a
    :class:`graphdyn.parallel.stream.ShardStreamPlan` — the layout
    :func:`graphdyn.parallel.stream.sharded_streamed_rollout` walks.
    """
    if partition is not None:
        from graphdyn.parallel.stream import build_shard_stream_plan

        return build_shard_stream_plan(
            graph, W=W, partition=partition, n_chunks=n_chunks,
            device_budget_bytes=device_budget_bytes, adj=adj,
        )
    if adj is None:
        adj = _adjacency_lists(graph)
    order = degree_buckets(graph).order
    groups = _split_stream_groups(
        order, adj, W=W, n_chunks=n_chunks,
        device_budget_bytes=device_budget_bytes, n_total=graph.n,
    )
    chunks = tuple(_build_chunk(g, adj) for g in groups)
    chunk_of = np.empty(graph.n, np.int32)
    for k, ch in enumerate(chunks):
        chunk_of[ch.nodes] = k
    return StreamPlan(n=graph.n, chunks=chunks, chunk_of=chunk_of)


# ---------------------------------------------------------------------------
# device step of one chunk — the graftcheck-fingerprinted program
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("rule", "tie"))
# graftlint: disable-next-line=GD006  the [M+1,W] slab can never alias the [C,W] output — donation would only emit XLA "not usable" noise
def _stream_chunk_device(nbr_loc, deg, self_loc, slab, rule: str = "majority",
                         tie: str = "stay"):
    """One synchronous update of one chunk's owned nodes from its state
    slab (graftcheck fingerprints THIS program as the
    ``streamed_rollout`` ledger entry). ``slab: uint32[M+1, W]`` — the
    gathered packed state with the ghost zero row last (not donated: the
    output shape ``[C, W]`` can never alias it); returns ``uint32[C, W]``.
    Narrow chunks run the unrolled CSA + bitwise comparator, wide (hub)
    chunks the segmented CSA + integer comparator — the exact arithmetic
    of the resident bucketed kernel, shared helpers."""
    rule = Rule(rule)
    tie = TieBreak(tie)
    width = nbr_loc.shape[1]
    prev = jnp.take(slab, self_loc, axis=0)
    if width > UNROLL_MAX:
        cnt = _wide_bucket_counts(slab, nbr_loc)
        two = 2 * cnt
        deg_col = deg.astype(jnp.int32)[:, None, None]
        return _rule_tie_combine(
            _pack_lanes(two > deg_col), _pack_lanes(two == deg_col),
            prev, rule, tie)
    n_planes = max(width.bit_length(), 1)
    planes = _csa_bucket(slab, nbr_loc, n_planes)
    thr = (deg // 2).astype(jnp.uint32)
    even = jnp.where(deg % 2 == 0, _FULL, jnp.uint32(0))[:, None]
    thr_bits = [
        jnp.where((thr >> k) & 1 == 1, _FULL, jnp.uint32(0))[:, None]
        for k in range(n_planes)
    ]
    gt, eq = _compare_planes(planes, thr_bits)
    return _rule_tie_combine(gt, eq & even, prev, rule, tie)


def lower_streamed_chunk(chunk: StreamChunk, *, W: int,
                         rule: str = "majority", tie: str = "stay"):
    """Lower (without executing) the streamed chunk step at this chunk's
    shapes — the program :mod:`graphdyn.analysis.graftcheck` fingerprints
    for the ``streamed_rollout`` ledger entry. Kept next to the kernel so
    a refactor updates the fingerprinted surface in place."""
    nbr = jnp.asarray(chunk.nbr_loc)
    deg = jnp.asarray(chunk.deg)
    self_loc = jnp.asarray(chunk.self_loc)
    slab = jax.ShapeDtypeStruct((chunk.M + 1, W), jnp.uint32)
    return _stream_chunk_device.lower(nbr, deg, self_loc, slab, rule, tie)


# ---------------------------------------------------------------------------
# the mutation stream — live edge churn at chunk boundaries
# ---------------------------------------------------------------------------


class ChurnBatch(NamedTuple):
    """One batch of edge mutations applied at the boundary BEFORE step
    ``step`` (0-based): ``drops`` leave first, then ``adds`` arrive.
    Both are int ``[k, 2]`` endpoint arrays; application is idempotent —
    drops of absent edges and adds of present edges or self-loops are
    filtered, and only the surviving mutations are journaled."""

    step: int
    adds: np.ndarray
    drops: np.ndarray


def seeded_churn(n: int, steps: int, *, rate: float,
                 seed: int) -> list[ChurnBatch]:
    """A deterministic churn schedule: per step, ``Poisson(rate/2)``
    candidate arrivals and departures over uniform node pairs (pure in
    ``(n, steps, rate, seed)`` — the prerequisite for journal replay
    equivalence tests). Departure candidates are drawn blind to the live
    adjacency; the idempotent filters in application make that exact."""
    rng = np.random.default_rng(seed)
    out = []
    for t in range(steps):
        ka = int(rng.poisson(rate / 2.0))
        kd = int(rng.poisson(rate / 2.0))
        adds = rng.integers(0, n, size=(ka, 2), dtype=np.int64)
        drops = rng.integers(0, n, size=(kd, 2), dtype=np.int64)
        if ka or kd:
            out.append(ChurnBatch(step=t, adds=adds, drops=drops))
    return out


class _Adjacency:
    """Mutable per-node neighbor sets over a base graph — the live
    adjacency the churn stream edits. ``apply`` filters a batch down to
    the mutations that actually change the graph (drops of absent edges,
    duplicate/self-loop adds are dropped) and returns them with the
    touched node set, so the caller journals exactly what happened and
    rebuilds exactly the chunks whose tables changed."""

    def __init__(self, graph: Graph):
        self.n = graph.n
        self._sets = [
            set(graph.nbr[i, : graph.deg[i]].astype(int).tolist())
            for i in range(graph.n)
        ]

    def apply(self, adds, drops):
        applied_drops, applied_adds = [], []
        touched: set[int] = set()
        for u, v in np.asarray(drops, np.int64).reshape(-1, 2):
            u, v = int(u), int(v)
            if u == v or v not in self._sets[u]:
                continue
            self._sets[u].discard(v)
            self._sets[v].discard(u)
            applied_drops.append((min(u, v), max(u, v)))
            touched.update((u, v))
        for u, v in np.asarray(adds, np.int64).reshape(-1, 2):
            u, v = int(u), int(v)
            if u == v or v in self._sets[u]:
                continue
            self._sets[u].add(v)
            self._sets[v].add(u)
            applied_adds.append((min(u, v), max(u, v)))
            touched.update((u, v))
        return applied_adds, applied_drops, touched

    def neighbor_lists(self) -> list[np.ndarray]:
        return [
            np.fromiter(sorted(s), np.int64, len(s)) for s in self._sets
        ]

    def neighbors_of(self, i: int) -> np.ndarray:
        return np.fromiter(sorted(self._sets[i]), np.int64,
                           len(self._sets[i]))


def _rebuild_touched(plan: StreamPlan, adj_lists: list[np.ndarray],
                     touched: set[int]) -> StreamPlan:
    """Rebuild ONLY the chunks owning a touched node — chunk membership
    is stable under churn (ownership never moves), so the rebuild cost is
    proportional to the churn locality, not the graph."""
    dirty = {int(plan.chunk_of[i]) for i in touched}
    chunks = tuple(
        _build_chunk(ch.nodes, adj_lists) if k in dirty else ch
        for k, ch in enumerate(plan.chunks)
    )
    return StreamPlan(n=plan.n, chunks=chunks, chunk_of=plan.chunk_of)


# ---------------------------------------------------------------------------
# the streamed rollout driver
# ---------------------------------------------------------------------------


class _StreamState(NamedTuple):
    sp: np.ndarray       # uint32[n, W] packed state, GLOBAL node order
    t: int               # completed synchronous steps
    seq: int             # applied churn batches so far (journal cursor)


def _one_step(state: _StreamState, plan_ref: list, adj, schedule,
              journal, rule: str, tie: str, depth: int,
              totals: dict) -> _StreamState:
    """Advance one synchronous step: apply due churn at the boundary,
    then sweep every chunk with the prefetch lane one chunk ahead."""
    plan: StreamPlan = plan_ref[0]
    t, seq = state.t, state.seq
    # -- churn boundary: drops then adds, journal what was applied -------
    while seq < len(schedule) and schedule[seq].step <= t:
        batch = schedule[seq]
        adds, drops, touched = adj.apply(batch.adds, batch.drops)
        if touched:
            plan = _rebuild_touched(plan, adj.neighbor_lists(), touched)
            plan_ref[0] = plan
        if journal is not None:
            journal(step=int(batch.step), seq=int(seq),
                    adds=[list(e) for e in adds],
                    drops=[list(e) for e in drops],
                    n_adds=len(adds), n_drops=len(drops))
        totals["mutations"] += len(adds) + len(drops)
        seq += 1
    # -- chunk sweep: prefetch gathers chunk c+1 while c steps -----------
    from graphdyn.pipeline.prefetch import HostPrefetcher

    sp, W = state.sp, state.sp.shape[1]
    new = np.empty_like(sp)

    def build(c: int):
        ch = plan.chunks[c]
        slab = np.concatenate(
            [sp[ch.gids], np.zeros((1, W), np.uint32)], axis=0)
        dev = (jnp.asarray(ch.nbr_loc), jnp.asarray(ch.deg),
               jnp.asarray(ch.self_loc), jnp.asarray(slab))
        # graftlint: disable-next-line=GD016  measured H2D traffic gauge over the arrays actually staged, not a predictive byte model — the model is streamed_chunk_bytes in obs/memband
        nbytes = sum(int(a.nbytes) for a in dev)
        return dev, nbytes

    h2d = d2h = 0
    pf = HostPrefetcher(build, range(plan.K), depth=depth)
    try:
        with obs.span("stream.step", step=t, chunks=plan.K):
            for c in range(plan.K):
                (nbr, deg, self_loc, slab), nbytes = pf.get(c)
                out = _stream_chunk_device(
                    nbr, deg, self_loc, slab, rule, tie)
                out_np = np.asarray(out)
                new[plan.chunks[c].nodes] = out_np
                h2d += nbytes
                d2h += int(out_np.nbytes)
    finally:
        totals["build_s"] += pf._build_s
        totals["wait_s"] += pf._wait_s
        pf.close()
    totals["h2d_bytes"] += h2d
    totals["d2h_bytes"] += d2h
    if obs.enabled():
        obs.gauge("stream.h2d_bytes", h2d, step=t, chunks=plan.K)
        obs.gauge("stream.d2h_bytes", d2h, step=t, chunks=plan.K)
    return _StreamState(sp=new, t=t + 1, seq=seq)


def _replay_churn_from_journal(jpath: str, t0: int, adj: _Adjacency,
                               plan_ref: list):
    """Re-apply every journaled ``stream.churn`` batch with ``step <
    t0`` — the resumed adjacency comes from the journal ALONE (the
    schedule may disagree about the past; the journal is the record of
    what this run actually applied). Returns the dedup set of applied
    ``(step, seq)`` pairs and the resume journal cursor."""
    from graphdyn.obs.recorder import read_ledger

    try:
        events, _ = read_ledger(jpath)
    except (OSError, ValueError):
        events = []
    seen: set[tuple[int, int]] = set()
    batches = []
    for ev in events:
        if ev.get("ev") != "journal" or ev.get("op") != "stream.churn":
            continue
        key = (int(ev.get("step", -1)), int(ev.get("seq", -1)))
        if key in seen:
            continue            # a requeued run re-journals nothing, but
        seen.add(key)           # dedup keeps replay idempotent anyway
        batches.append((key, ev.get("adds") or [], ev.get("drops") or []))
    touched_all: set[int] = set()
    applied = 0
    for (step, _), adds, drops in sorted(batches, key=lambda b: b[0]):
        if step >= t0:
            continue            # boundary not yet crossed by the resumed
        a = np.asarray(adds, np.int64).reshape(-1, 2)
        d = np.asarray(drops, np.int64).reshape(-1, 2)
        _, _, touched = adj.apply(a, d)
        touched_all |= touched
        applied += 1
    if touched_all:
        plan_ref[0] = _rebuild_touched(
            plan_ref[0], adj.neighbor_lists(), touched_all)
    return applied


def streamed_rollout(graph: Graph, sp, steps: int, *,
                     rule: str = "majority", tie: str = "stay",
                     n_chunks: int | None = None,
                     device_budget_bytes: int | None = None,
                     plan: StreamPlan | None = None,
                     prefetch_depth: int = 2,
                     churn: Iterable[ChurnBatch] | None = None,
                     checkpoint_path: str | None = None,
                     checkpoint_interval_s: float = 30.0,
                     seed: int = 0,
                     stats_out: dict | None = None) -> np.ndarray:
    """Roll packed spins ``sp: uint32[n, W]`` (GLOBAL node order) for
    ``steps`` synchronous updates with only one chunk (plus the
    prefetched next) resident on device. Bit-exact to
    :func:`graphdyn.ops.packed.packed_rollout` /
    :func:`graphdyn.ops.bucketed.bucketed_rollout_global` on the same
    graph (no permutation: chunks address global ids).

    ``churn``: optional :class:`ChurnBatch` schedule (sorted by step),
    applied at boundaries with incremental rebuild of touched chunks and
    journaled under the ``stream.churn`` op when checkpointing.
    ``prefetch_depth=0`` is the forced-synchronous A/B leg (gathers
    serialize with compute — the overlap baseline). ``stats_out`` (dict)
    receives the measured totals: ``build_s``, ``wait_s``,
    ``overlap_frac``, ``h2d_bytes``, ``d2h_bytes``, ``mutations``,
    ``steps``, ``chunks``.

    With ``checkpoint_path``, preemption resume is exact: the snapshot
    carries ``(sp, t, seq)`` and the resumed run replays the journaled
    churn for ``step < t`` from the journal ALONE before consulting the
    schedule for the remaining boundaries.
    """
    sp = np.ascontiguousarray(np.asarray(sp, np.uint32))
    if sp.ndim != 2 or sp.shape[0] != graph.n:
        raise ValueError(
            f"sp must be uint32[n={graph.n}, W], got {sp.shape}"
        )
    W = sp.shape[1]
    schedule = sorted(churn, key=lambda b: (b.step,)) if churn else []
    adj = _Adjacency(graph)
    if plan is None:
        plan = build_stream_plan(
            graph, W=W, n_chunks=n_chunks,
            device_budget_bytes=device_budget_bytes,
            adj=adj.neighbor_lists(),
        )
    plan_ref = [plan]
    totals = {"build_s": 0.0, "wait_s": 0.0, "h2d_bytes": 0,
              "d2h_bytes": 0, "mutations": 0}

    journal = None
    ckpt = None
    state = _StreamState(sp=sp, t=0, seq=0)
    if checkpoint_path:
        from graphdyn.resilience.store import (
            journal_event, journal_path_for,
        )
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        jpath = journal_path_for(checkpoint_path)

        def journal(**fields):
            journal_event(jpath, "stream.churn", **fields)

        # identity EXCLUDES the churn schedule: the journal (not the
        # schedule argument) is authoritative for boundaries already
        # crossed, so a resume with a tampered past schedule must still
        # validate — that is the journal-alone replay contract
        fp = run_fingerprint(
            graph.edges, np.int64(graph.n), np.int64(steps), str(rule),
            str(tie), np.int64(W),
        )
        ckpt = ChainCheckpointer(
            checkpoint_path, kind="streamed_rollout", seed=seed, fp=fp,
            interval_s=checkpoint_interval_s,
            extra_meta={"W": int(W)},
        )
        loaded = ckpt.load_state(
            check=lambda a: a["sp"].shape == sp.shape)
        if loaded is not None:
            t0 = int(loaded["t"])
            seq0 = int(loaded["seq"])
            replayed = _replay_churn_from_journal(jpath, t0, adj, plan_ref)
            state = _StreamState(
                sp=np.ascontiguousarray(loaded["sp"].astype(np.uint32)),
                t=t0, seq=seq0)
            if obs.enabled():
                obs.counter("stream.resume", t=t0, seq=seq0,
                            replayed=replayed)

    def advance(s: _StreamState) -> _StreamState:
        return _one_step(s, plan_ref, adj, schedule, journal, rule, tie,
                         prefetch_depth, totals)

    def active(s: _StreamState) -> bool:
        return s.t < steps

    if ckpt is not None:
        state = ckpt.drive(
            state, advance=advance, active=active,
            payload=lambda s: {"sp": s.sp, "t": np.int64(s.t),
                               "seq": np.int64(s.seq)},
        )
    else:
        while active(state):
            state = advance(state)

    build_s, wait_s = totals["build_s"], totals["wait_s"]
    overlap = max(0.0, 1.0 - wait_s / build_s) if build_s > 0 else 0.0
    if obs.enabled() and build_s > 0:
        obs.gauge(
            "stream.overlap_util", overlap,
            build_s=round(build_s, 6), wait_s=round(wait_s, 6),
            depth=prefetch_depth, steps=int(state.t),
            chunks=plan_ref[0].K,
            h2d_bytes=totals["h2d_bytes"], d2h_bytes=totals["d2h_bytes"],
        )
    if stats_out is not None:
        stats_out.update(
            totals, overlap_frac=overlap, steps=int(state.t),
            chunks=plan_ref[0].K,
        )
    return state.sp
