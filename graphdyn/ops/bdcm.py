"""BDCM message passing — the L3/L4 cavity-method hot path, jitted.

Generalizes the reference's two sweep implementations — `HPr_dp`
(`HPR_pytorch_RRG.py:183-218`, RRG, flat-column chi, host round-trips per
combo) and `BDCM_ER` (`ER_BDCM_entropy.ipynb:133-198`, degree-grouped,
slice-shift ρ-convolution) — into one table-driven jitted sweep:

- chi lives as ``f32[2E, K, K]`` (``chi[e, x_src, x_dst]``, K = 2^T), the
  notebook's tensor layout with the two T-axis groups flattened.
- The neighbor DP is a product of shift-convolutions on the ρ-lattice: start
  from δ(ρ=0) and, per incoming message, add the K trajectory-shifted copies
  weighted by that message — the notebook's slice-arithmetic trick
  (`ipynb:108-128` cell) expressed as ``jnp.roll`` over the T trailing axes
  (rolls never wrap nonzero mass: after D steps the lattice support is ≤ D
  per axis, and the lattice has d+1 ≥ D+1 slots).
- The final contraction against the precomputed factor tensor ``A[d]`` is one
  einsum (MXU-friendly batched matmul), with the λ-tilt ``exp(−λ·x_i(0))``
  applied as a rank-1 scale at call time — λ stays a traced argument, so a
  λ-ladder sweep reuses one compiled program.
- Degree classes are unrolled at trace time (static shapes per class, one
  compiled program for the whole sweep), updated Gauss-Seidel style in class
  order exactly like the notebook's in-place ``chi[...] = ...`` loop.

The HPr variant differs from the entropy variant in two reference-faithful
ways (SURVEY.md §2.2 vs §2.3): incoming messages are weighted by per-node
reinforcement biases, and invalid-endpoint source trajectories are *not*
masked out of the DP (HPr relies on those chi entries decaying under damping);
``mask_invalid_src`` selects the behavior.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from graphdyn.analysis.contracts import contract
from graphdyn.resilience import faults as _faults
from graphdyn.attractors import (
    attr_mask,
    edge_factor_tensor,
    leaf_factor_tensor,
    node_factor_tensor,
    rho_lattice,
    trajectories01,
    x0_pm,
)
from graphdyn.graphs import EdgeTables, Graph, build_edge_tables, degree_classes

log = logging.getLogger("graphdyn.ops")


class _EdgeClass(NamedTuple):
    d: int
    idx: np.ndarray        # [Ed] directed edge ids
    in_edges: np.ndarray   # [Ed, d] incoming directed edge ids
    A: np.ndarray          # [K, K, (d+1)^T] λ=0 factor


class _NodeClass(NamedTuple):
    d: int
    idx: np.ndarray        # [Nd] node ids
    in_edges: np.ndarray   # [Nd, d]
    Ai: np.ndarray         # [K, (d+1)^T]


def _pad_class(idx: np.ndarray, in_edges: np.ndarray, bucket: int, ghost_idx: int, ghost_in: int):
    """Pad a degree class to the next multiple of ``bucket``: padded members
    scatter to the ghost slot ``ghost_idx`` and gather from the ghost message
    row ``ghost_in`` (both sliced away by the executors)."""
    pad = (-idx.shape[0]) % bucket
    if pad == 0:
        return idx, in_edges
    idx = np.concatenate([idx, np.full(pad, ghost_idx, idx.dtype)])
    in_edges = np.concatenate(
        [in_edges, np.full((pad, in_edges.shape[1]), ghost_in, in_edges.dtype)]
    )
    return idx, in_edges


class BDCMData:
    """Per-graph static data for the BDCM sweep (host-built).

    ``class_bucket``: round every degree-class size up to a multiple of this
    (padding with ghost edges/nodes). Bucketed instances of the same ensemble
    usually land on identical shapes, so the module-level jitted executors
    (:func:`_sweep_exec` etc.) reuse one compiled program across graphs —
    XLA recompilation, not math, dominates multi-instance ER sweeps.
    """

    def __init__(
        self,
        graph: Graph,
        tables: EdgeTables | None = None,
        *,
        p: int = 1,
        c: int = 1,
        attr_value: int = 1,
        rule: str = "majority",
        tie: str = "stay",
        class_bucket: int | None = None,
        dtype=jnp.float32,
    ):
        # the reference's entropy/HPr paths run float64
        # (`HPR_pytorch_RRG.py:11`, numpy default in the notebook); dtype
        # threads through messages, factor casts, and observables. float64
        # requires jax_enable_x64 (and disables the f32 Pallas kernel).
        self.dtype = jnp.dtype(dtype)
        # graftlint: disable-next-line=GD004  dtype *guard*, no f64 created
        if self.dtype == jnp.float64 and not jax.config.jax_enable_x64:
            raise ValueError(
                "BDCMData(dtype=float64) requires jax.config.update"
                "('jax_enable_x64', True) before tracing"
            )
        tables = tables or build_edge_tables(graph)
        self.graph = graph
        self.tables = tables
        self.p, self.c = p, c
        self.T = p + c
        self.K = 2**self.T
        self.attr_value = attr_value
        self.rule, self.tie = rule, tie
        self.padded = class_bucket is not None

        self.valid = attr_mask(self.T, attr_value)          # bool[K]
        self.x0 = x0_pm(self.T)                             # ±1[K]
        self.leaf01 = leaf_factor_tensor(p, c, attr_value, rule, tie)  # [K,K]

        ghost_edge = tables.num_directed                    # row 2E of chi_ext

        eclasses = degree_classes(tables.edge_deg)
        self.leaf_idx = eclasses.get(0, np.empty(0, np.int32))
        self.edge_classes: list[_EdgeClass] = []
        for d, idx in sorted(eclasses.items()):
            if d == 0:
                continue
            in_edges = tables.in_edges[idx, :d]
            if self.padded:
                idx, in_edges = _pad_class(
                    idx, in_edges, class_bucket, ghost_edge, ghost_edge
                )
            self.edge_classes.append(
                _EdgeClass(
                    d=int(d),
                    idx=idx,
                    in_edges=in_edges,
                    A=edge_factor_tensor(d, p, c, attr_value, rule, tie),
                )
            )

        nclasses = degree_classes(graph.deg)
        self.node_classes: list[_NodeClass] = []
        for d, idx in sorted(nclasses.items()):
            if d == 0:
                continue
            in_edges = tables.node_in_edges[idx, :d]
            if self.padded:
                idx, in_edges = _pad_class(
                    idx, in_edges, class_bucket, graph.n, ghost_edge
                )
            self.node_classes.append(
                _NodeClass(
                    d=int(d),
                    idx=idx,
                    in_edges=in_edges,
                    Ai=node_factor_tensor(d, p, c, attr_value, rule, tie),
                )
            )

        self.num_directed = tables.num_directed
        self.num_edges = tables.num_edges
        self.n = graph.n

    def init_messages(self, seed=0) -> jnp.ndarray:
        """Random row-normalized chi (`ipynb:509-511`, `HPR:101-103`).
        ``seed`` may be an int or a ``np.random.Generator`` (shared stream)."""
        rng = np.random.default_rng(seed)
        chi = rng.random((self.num_directed, self.K, self.K))
        chi /= chi.sum(axis=(1, 2), keepdims=True)
        return jnp.asarray(chi, self.dtype)

    def init_messages_device(self, seed: int = 0) -> jnp.ndarray:
        """Random row-normalized chi drawn ON DEVICE (different stream from
        :meth:`init_messages` — both are valid random inits; this one never
        ships a [2E, K, K] host buffer over the device link)."""
        return draw_chi_device(
            jax.random.key(seed), self.num_directed, self.K, self.dtype
        )


def draw_chi_device(key, rows: int, K: int, dtype, out_shardings=None):
    """Row-normalized random chi ``[rows, K, K]`` drawn ON DEVICE, optionally
    straight into a sharding — the one draw behind
    :meth:`BDCMData.init_messages_device` and the solvers'/benchmarks'
    device-resident init paths (the per-row normalization is elementwise
    over the row axis, so any 1-D row sharding is valid)."""

    def f():
        u = jax.random.uniform(key, (rows, K, K), dtype)
        return u / u.sum(axis=(1, 2), keepdims=True)

    return jax.jit(f, out_shardings=out_shardings)()


def replicate_bdcm_device(base: BDCMData, R: int) -> BDCMData:
    """R-replica disjoint-union ``BDCMData`` in the replica-major layout
    (:func:`graphdyn.graphs.replicate_edge_tables`), with every union-sized
    table computed ON DEVICE from the base graph's host tables.

    Rationale: the host builders materialize ~4 GB of union tables at
    BASELINE config-2 scale (n=1e5, R=256) that must then cross the
    host→device link — which over the tunneled TPU transport is the
    round-4 session's measured failure mode. Here the link carries only the
    base tables (~10 MB); the union's classes/tables are offset-tiled jnp
    arrays. The degree-class structure of a disjoint union of R copies is
    exactly the base structure tiled, so no host ``degree_classes`` pass is
    needed. Layout equality with the host path is tested
    (tests/test_hpr.py)."""
    import copy

    from graphdyn.graphs import (
        _rep_ids_device,
        replicate_disjoint_device,
        replicate_edge_tables_device,
    )

    g, t = base.graph, base.tables
    n, twoE = g.n, t.num_directed
    ghost, ghost_u = twoE, R * twoE

    # shallow-copy the base, then override every union-sized field: scalar
    # config and the [K]-shaped factor data (valid/x0/leaf01, per-class A/Ai)
    # are edge-count independent and carry over; a future BDCMData attribute
    # is inherited rather than silently missing
    u = copy.copy(base)
    u.graph = replicate_disjoint_device(g, R)
    u.tables = replicate_edge_tables_device(t, R, n)
    u.leaf_idx = _rep_ids_device(base.leaf_idx, R, twoE, ghost, ghost_u)
    u.edge_classes = [
        _EdgeClass(
            d=cls.d,
            idx=_rep_ids_device(cls.idx, R, twoE, ghost, ghost_u),
            in_edges=_rep_ids_device(cls.in_edges, R, twoE, ghost, ghost_u),
            A=cls.A,
        )
        for cls in base.edge_classes
    ]
    u.node_classes = [
        _NodeClass(
            d=cls.d,
            idx=_rep_ids_device(cls.idx, R, n, g.n, R * g.n),
            in_edges=_rep_ids_device(cls.in_edges, R, twoE, ghost, ghost_u),
            Ai=cls.Ai,
        )
        for cls in base.node_classes
    ]
    u.num_directed = R * twoE
    u.num_edges = R * t.num_edges
    u.n = R * n
    return u


def _neighbor_dp(chi_in, d: int, T: int, K: int):
    """ρ-lattice DP: LL[e, x_i, ρ] = Σ over assignments of the d incoming
    source trajectories of Π_D chi_in[e, D, x_k(D), x_i] with ρ = Σ x_k.

    ``chi_in``: [E, d, K, K] indexed [edge, slot, x_src, x_dst].
    Returns [E, K, (d+1)^T] (flattened lattice, mixed-radix row-major).
    """
    X01 = trajectories01(T)
    Ed = chi_in.shape[0]
    lat_axes = tuple(range(2, 2 + T))
    LL = (
        jnp.zeros((Ed, K) + (d + 1,) * T, chi_in.dtype)
        .at[(slice(None), slice(None)) + (0,) * T]
        .set(1.0)
    )
    for D in range(d):
        acc = jnp.zeros_like(LL)
        for k_idx in range(K):
            shift = tuple(int(b) for b in X01[k_idx])
            shifted = jnp.roll(LL, shift, lat_axes) if any(shift) else LL
            w = chi_in[:, D, k_idx, :]
            acc = acc + shifted * w[(...,) + (None,) * T]
        LL = acc
    return LL.reshape(Ed, K, -1)


def class_update(chi_in, A, tilt, chi_old, *, d, T, K, damp, eps_clamp):
    """XLA per-degree-class message update: neighbor DP, factor contraction,
    ε-clamp, normalization, damping. The single numerical core shared by the
    local sweep (:func:`make_sweep`) and the edge-sharded sweep
    (:func:`graphdyn.parallel.sharded.make_sharded_sweep`), so the
    sharded-vs-unsharded equivalence is structural, not maintained by hand."""
    LL = _neighbor_dp(chi_in, d, T, K)                  # [Ed, K, M]
    chi2 = jnp.einsum("xym,exm->exy", A, LL) * tilt[None, :, None]
    chi2 = jnp.maximum(chi2, eps_clamp)
    # safe denominator: an empty attractor set (all factors zero, e.g.
    # minority dynamics with a c=1 homogeneous endpoint) yields all-zero
    # messages and φ → −inf downstream instead of NaNs
    z = chi2.sum(axis=(1, 2), keepdims=True)
    chi2 = chi2 / jnp.maximum(z, jnp.finfo(chi2.dtype).tiny)
    return damp * chi2 + (1.0 - damp) * chi_old


class _SweepSpec(NamedTuple):
    """Hashable static configuration of one sweep program. Everything traced
    (chi, λ, bias, index tables, factor tensors) is an argument of the
    module-level executor instead of a closure constant, so graphs whose
    table *shapes* coincide (same degree-class signature — automatic for RRG
    ensembles, arranged for ER via ``BDCMData(class_bucket=...)``) share ONE
    compiled program instead of compiling per instance."""

    T: int
    K: int
    damp: float
    eps_clamp: float
    mask_invalid_src: bool
    with_bias: bool
    padded: bool
    class_ds: tuple          # per-class neighbor count d
    pallas: tuple            # per-class: '' (XLA) | 'tpu' | 'interpret'


def _sweep_core(chi, lmbd, bias_edge, valid, x0, tables, spec: _SweepSpec):
    """The sweep body (call inside jit). ``tables``: tuple per class of
    (idx, in_edges, A)."""
    T, K = spec.T, spec.K
    tilt = jnp.exp(-lmbd * x0)  # [K]
    n_real = chi.shape[0]
    if spec.padded:
        # ghost row 2E: gathered by padded class members only (never by real
        # ones); their garbage updates scatter back to this row and are
        # sliced off at the end
        ghost = jnp.full((1,) + chi.shape[1:], 1.0 / (K * K), chi.dtype)
        chi = jnp.concatenate([chi, ghost], axis=0)
        if spec.with_bias:
            bias_edge = jnp.concatenate(
                [bias_edge, jnp.ones((1, K), bias_edge.dtype)], axis=0
            )
    for (d, mode), (idx, in_edges, A) in zip(
        zip(spec.class_ds, spec.pallas), tables
    ):
        chi_in = chi[in_edges]                      # [Ed, d, K, K]
        if spec.with_bias:
            chi_in = chi_in * bias_edge[in_edges][:, :, :, None]
        if spec.mask_invalid_src:
            chi_in = chi_in * valid[None, None, :, None]
        if mode:
            from graphdyn.ops.pallas_bdcm import dp_contract

            # trace-time site: a firing plan here stands in for a real
            # kernel lowering/compile failure on this backend
            _faults.maybe_fail("pallas.lower", key=f"d={d}")
            upd = dp_contract(
                chi_in,
                A * tilt[:, None, None],
                chi[idx],
                d=d,
                T=T,
                damp=spec.damp,
                eps_clamp=spec.eps_clamp,
                interpret=mode == "interpret",
            )
        else:
            upd = class_update(
                chi_in, A, tilt, chi[idx], d=d, T=T, K=K,
                damp=spec.damp, eps_clamp=spec.eps_clamp,
            )
        chi = chi.at[idx].set(upd)
    return chi[:n_real]


@partial(jax.jit, static_argnames=("spec",))
@contract(chi="float32|float64[e,k,k]", lmbd="float32|float64[]",
          ret="float32|float64[e,k,k]")
def _sweep_exec(chi, lmbd, bias_edge, valid, x0, tables, spec: _SweepSpec):
    return _sweep_core(chi, lmbd, bias_edge, valid, x0, tables, spec)


def _pallas_class_modes(choice: str, dtype, gates, *, force_err: str) -> tuple:
    """The ONE mode-resolution core behind the serial
    (:func:`_resolve_pallas_modes`) and grouped
    (:func:`resolve_group_pallas_modes`) resolvers: the f32-only dtype
    guard (forcing the kernel under f64 is refused loudly — never silently
    comparing XLA to itself in a parity test), the backend→mode mapping,
    and the per-class degrade loop. ``choice`` is ``'auto'``/``'force'``/
    ``'off'``; ``gates`` holds one zero-arg support predicate per class.

    Chip backends: the tunneled TPU plugin reports ``"tpu"``; ``"axon"``
    is hedged like every other chip-backend allowlist in the repo
    (bench.py ``on_chip``, ``CHIP_BACKENDS``) — on either, ``'auto'``
    selects the compiled kernel and ``'force'`` compiles too; off-chip a
    force means interpret mode (tests, not throughput)."""
    # graftlint: disable-next-line=GD004  dtype *guard*, no f64 created
    if jnp.dtype(dtype) == jnp.float64:
        if choice == "force":
            raise ValueError(force_err)
        return ("",) * len(gates)
    on_chip = jax.default_backend() in ("tpu", "axon")
    if choice == "auto":
        mode = "tpu" if on_chip else "off"
    elif choice == "force":
        mode = "tpu" if on_chip else "interpret"
    else:
        mode = "off"
    return tuple(
        mode if (mode != "off" and gate()) else "" for gate in gates
    )


def _resolve_pallas_modes(data: BDCMData, use_pallas) -> tuple:
    from graphdyn.ops.pallas_bdcm import pallas_supported

    gates = [
        lambda d=cls.d, Ed=int(cls.idx.shape[0]): pallas_supported(
            d, data.T, Ed
        )
        for cls in data.edge_classes
    ]
    choice = (
        "auto" if use_pallas == "auto" else ("force" if use_pallas else "off")
    )
    return _pallas_class_modes(
        choice, data.dtype, gates,
        force_err=(
            "use_pallas=True is incompatible with BDCMData(dtype=float64) "
            "— the Pallas kernel is f32-only; use dtype=float32 or "
            "use_pallas='auto'/False"
        ),
    )


def resolve_group_pallas_modes(
    class_ds, class_eds, *, T: int, dtype, kernel: str, G: int,
    per_group_a: bool,
) -> tuple:
    """Per-class kernel modes (``''`` XLA | ``'tpu'`` | ``'interpret'``) for
    the GROUPED executors — the grouped mirror of
    :func:`_resolve_pallas_modes`, with the group-aware VMEM gate
    (:func:`graphdyn.ops.pallas_bdcm.pallas_group_supported`).

    ``kernel``: ``'auto'`` selects the fused grouped kernel on chip
    backends for every class whose spec fits; ``'pallas'`` forces it
    (interpret mode off-chip, for tests); ``'xla'`` keeps the pure-XLA
    path. A class whose group-resident VMEM model returns 0 degrades to
    XLA per call rather than erroring (the static half of the contract;
    runtime lowering failures go through :func:`pallas_fallback_spec`)."""
    if kernel not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"kernel must be 'auto', 'xla' or 'pallas', got {kernel!r}"
        )
    from graphdyn.ops.pallas_bdcm import pallas_group_supported

    gates = [
        lambda d=int(d), Ed=int(Ed): pallas_group_supported(
            d, T, Ed, int(G), per_group_a=per_group_a
        )
        for d, Ed in zip(class_ds, class_eds)
    ]
    choice = {"auto": "auto", "xla": "off", "pallas": "force"}[kernel]
    return _pallas_class_modes(
        choice, dtype, gates,
        force_err=(
            "kernel='pallas' is incompatible with dtype=float64 — the "
            "Pallas kernel is f32-only; use float32 or kernel='auto'/'xla'"
        ),
    )


def pallas_fallback_spec(spec: _SweepSpec, exc: BaseException) -> _SweepSpec:
    """Runtime Pallas→lax degradation: when a sweep program with active
    Pallas modes dies in kernel lowering/compilation, return the same spec
    with every class forced onto the XLA path (bit-parity is tested, so the
    fallback changes throughput, not results); any other failure — or a
    failure with no Pallas mode to blame — re-raises. Callers swap their
    spec for the returned one, so the rebuild happens once per program
    (``_resolve_pallas_modes`` alone only makes the *static* dtype/backend
    choice and cannot see a lowering failure). Duck-typed on the spec's
    ``pallas`` tuple, so the grouped executors' specs
    (``pipeline.hpr_group._HPRGroupSpec``,
    ``pipeline.entropy_group._CellSpec``) ride the same machinery."""
    if not any(spec.pallas) or not _faults.is_lowering_failure(exc):
        raise exc
    log.warning(
        "Pallas kernel failed to lower/compile on backend %r — rebuilding "
        "the sweep with use_pallas=False and continuing: %s",
        jax.default_backend(), exc,
    )
    return spec._replace(pallas=("",) * len(spec.pallas))


def poison_nan(x: jnp.ndarray) -> jnp.ndarray:
    """Seed one NaN into a float carry (the ``sweep.nan`` fault payload)."""
    return x.at[(0,) * x.ndim].set(jnp.nan)


def resilient_exec(state: dict, run):
    """Execute ``run(spec)`` with the runtime Pallas→lax fallback — the ONE
    implementation shared by :func:`make_sweep` and
    :func:`graphdyn.models.entropy.make_fixed_point`, so the fallback
    protocol cannot drift between them. ``state`` is a mutable
    ``{"spec": _SweepSpec}`` holder: a lowering failure swaps in the XLA
    spec (via :func:`pallas_fallback_spec`, which re-raises anything it
    cannot blame on Pallas) and the rebuilt program sticks for all later
    calls."""
    try:
        return run(state["spec"])
    except Exception as e:
        state["spec"] = pallas_fallback_spec(state["spec"], e)
        return run(state["spec"])


def _sweep_args(data: BDCMData, *, damp, eps_clamp, mask_invalid_src, with_bias, use_pallas):
    valid = jnp.asarray(data.valid)
    x0 = jnp.asarray(data.x0, data.dtype)
    tables = tuple(
        (
            jnp.asarray(cls.idx),
            jnp.asarray(cls.in_edges),
            jnp.asarray(cls.A, data.dtype),
        )
        for cls in data.edge_classes
    )
    spec = _SweepSpec(
        T=data.T,
        K=data.K,
        damp=float(damp),
        eps_clamp=float(eps_clamp),
        mask_invalid_src=bool(mask_invalid_src),
        with_bias=bool(with_bias),
        padded=data.padded,
        class_ds=tuple(cls.d for cls in data.edge_classes),
        pallas=_resolve_pallas_modes(data, use_pallas),
    )
    return valid, x0, tables, spec


def make_sweep(
    data: BDCMData,
    *,
    damp: float,
    eps_clamp: float = 0.0,
    mask_invalid_src: bool = True,
    with_bias: bool = False,
    use_pallas: bool | str = "auto",
):
    """Build the jitted BDCM sweep ``(chi, lmbd[, bias_edge]) -> chi'``.

    ``bias_edge``: [2E, K] multiplicative weight on each message *when
    consumed* (the HPr reinforcement bias ``b_k(x_k(0))`` gathered to edge
    shape, cf. `HPR_pytorch_RRG.py:128-133,188`).

    ``use_pallas``: ``'auto'`` fuses the per-class DP + contraction into the
    Pallas TPU kernel (:mod:`graphdyn.ops.pallas_bdcm`) on TPU backends when
    the class shape qualifies; ``True`` forces it (interpret mode off-TPU,
    for tests); ``False`` keeps the pure-XLA path.

    The returned callable dispatches to a module-level jitted executor —
    graphs with identical class-table shapes share its compile cache (see
    ``BDCMData(class_bucket=...)`` for arranging that on ER ensembles).

    Resilience: a Pallas lowering/compile failure at first execution
    degrades the program to the pure-XLA path (:func:`pallas_fallback_spec`
    — logged, results unchanged) instead of aborting the run; fault site
    ``sweep.nan`` can poison the returned messages for NaN-path tests.
    """
    valid, x0, tables, spec = _sweep_args(
        data, damp=damp, eps_clamp=eps_clamp,
        mask_invalid_src=mask_invalid_src, with_bias=with_bias,
        use_pallas=use_pallas,
    )
    state = {"spec": spec}

    def call(chi, lmbd, bias_edge):
        out = resilient_exec(state, lambda sp: _sweep_exec(
            chi, lmbd, bias_edge, valid, x0, tables, sp
        ))
        if _faults.transform_spec("sweep.nan", "nan") is not None:
            out = poison_nan(out)
        return out

    if with_bias:
        return lambda chi, lmbd, bias_edge: call(chi, lmbd, bias_edge)
    return lambda chi, lmbd: call(chi, lmbd, None)


def lower_sweep(
    data: BDCMData,
    *,
    damp: float,
    eps_clamp: float = 0.0,
    mask_invalid_src: bool = True,
    lmbd: float = 0.1,
    seed: int = 0,
):
    """Lower (without executing) the pure-XLA sweep program for ``data`` at
    its own shapes — the program-structure surface
    :mod:`graphdyn.analysis.graftcheck` fingerprints for the
    ``dp_contract``-equivalent XLA core. Lives next to :func:`make_sweep` so
    a sweep refactor updates the fingerprinted surface in the same place;
    always the ``use_pallas=False`` spec (the fingerprint ledger is the
    hardware-free structural contract — kernel mode is orthogonal to it).
    Returns a ``jax.stages.Lowered``."""
    valid, x0, tables, spec = _sweep_args(
        data, damp=damp, eps_clamp=eps_clamp,
        mask_invalid_src=mask_invalid_src, with_bias=False, use_pallas=False,
    )
    chi = data.init_messages(seed)
    return _sweep_exec.lower(
        chi, jnp.asarray(lmbd, data.dtype), None, valid, x0, tables, spec
    )


class EnsembleBDCM:
    """Stacked BDCM data for an ensemble of *structurally congruent* graphs
    (same n, same degree-class signature — e.g. RRG(n, d) instances, where
    every directed edge is one class of size 2E).

    The reference runs its graph ensemble as a host ``for`` loop
    (`HPR_pytorch_RRG.py:259`, `ipynb:496-497`), recompiling nothing because
    it never compiles; a jitted per-graph loop would recompile or at best
    re-dispatch per instance. Here the ensemble axis is a *batch* axis:
    per-class index tables stack to ``[G, Ed, ...]`` and one ``vmap``-ed
    program sweeps every instance at once — the BASELINE config-4 shape
    (64 graphs × λ ladder) as a single device program.
    """

    def __init__(self, datas: list[BDCMData]):
        if not datas:
            raise ValueError("empty ensemble")
        for dd in datas:
            _require_halved_layout(dd, "EnsembleBDCM")   # chi[:E]/chi[E:]
        d0 = datas[0]
        sig = [(c.d, c.idx.shape[0]) for c in d0.edge_classes]
        nsig = [(c.d, c.idx.shape[0]) for c in d0.node_classes]
        for dd in datas[1:]:
            if (
                dd.p != d0.p
                or dd.c != d0.c
                or dd.attr_value != d0.attr_value
                or dd.rule != d0.rule
                or dd.tie != d0.tie
            ):
                raise ValueError(
                    "ensemble members must share dynamics parameters "
                    "(p, c, attr_value, rule, tie) — factor tensors are shared"
                )
            if (
                dd.n != d0.n
                or dd.T != d0.T
                or [(c.d, c.idx.shape[0]) for c in dd.edge_classes] != sig
                or [(c.d, c.idx.shape[0]) for c in dd.node_classes] != nsig
                or dd.leaf_idx.size != d0.leaf_idx.size
            ):
                raise ValueError(
                    "ensemble graphs must be structurally congruent "
                    "(same n and degree-class signature)"
                )
        self.datas = datas
        self.G = len(datas)
        self.T, self.K = d0.T, d0.K
        self.n = d0.n
        self.num_edges = d0.num_edges
        self.num_directed = d0.num_directed
        self.valid = d0.valid
        self.x0 = d0.x0
        # stacked per-class tables: (d, idx[G, Ed], in_edges[G, Ed, d], A)
        self.edge_classes = [
            (
                cls.d,
                np.stack([dd.edge_classes[k].idx for dd in datas]),
                np.stack([dd.edge_classes[k].in_edges for dd in datas]),
                cls.A,
            )
            for k, cls in enumerate(d0.edge_classes)
        ]
        self.node_classes = [
            (
                cls.d,
                np.stack([dd.node_classes[k].idx for dd in datas]),
                np.stack([dd.node_classes[k].in_edges for dd in datas]),
                cls.Ai,
            )
            for k, cls in enumerate(d0.node_classes)
        ]
        self.edges = np.stack([dd.graph.edges.astype(np.int64) for dd in datas])
        self.deg = np.stack([dd.graph.deg for dd in datas])
        self.leaf_idx = np.stack([dd.leaf_idx for dd in datas])   # [G, L]
        self.leaf01 = d0.leaf01
        self.dtype = d0.dtype

    def init_messages(self, seed=0) -> jnp.ndarray:
        """[G, 2E, K, K] random row-normalized chi, one stream per graph."""
        rng = np.random.default_rng(seed)
        chi = rng.random((self.G, self.num_directed, self.K, self.K))
        chi /= chi.sum(axis=(2, 3), keepdims=True)
        return jnp.asarray(chi, self.dtype)


class StackedBDCM:
    """Stacked per-cell BDCM edge tables for a RAGGED ensemble — graphs that
    need NOT be congruent (different edge counts, different degree-class
    signatures: the entropy grid's ER cells across a whole deg × rep plane).

    Where :class:`EnsembleBDCM` demands one shared class signature,
    :func:`stack_bdcm` takes the UNION of the cells' degree classes and pads
    every class table to the class's maximum population ``Ed_max`` across
    cells: padded members gather from the ghost message row ``2E_max`` and
    scatter their garbage updates back to it (the exact ghost mechanism
    :func:`_pad_class` already uses per graph, lifted to the cell axis), so
    a cell that lacks a class entirely just runs that class as all-ghost
    rows. chi stacks to ``[G, 2E_max, K, K]`` with rows past a cell's own
    ``2E`` held constant (they are never indexed, so they contribute 0 to
    the per-cell convergence delta).

    Only the SWEEP tables are stacked — observables (φ, m_init) run per
    cell through the serial executors on the cell's own ``chi[:2E]`` slice,
    which is what keeps grouped observables bit-identical to the serial
    ladder by construction (see ``graphdyn.pipeline.entropy_group``).
    """

    def __init__(self, datas: list[BDCMData]):
        if not datas:
            raise ValueError("empty cell stack")
        d0 = datas[0]
        for dd in datas[1:]:
            if (
                dd.p != d0.p
                or dd.c != d0.c
                or dd.attr_value != d0.attr_value
                or dd.rule != d0.rule
                or dd.tie != d0.tie
                or dd.dtype != d0.dtype
            ):
                raise ValueError(
                    "stacked cells must share dynamics parameters and dtype "
                    "(p, c, attr_value, rule, tie, dtype) — factor tensors "
                    "are shared"
                )
        self.datas = datas
        self.G = len(datas)
        self.T, self.K = d0.T, d0.K
        self.dtype = d0.dtype
        self.valid = d0.valid
        self.x0 = d0.x0
        self.leaf01 = d0.leaf01
        self.twoE = np.asarray([dd.num_directed for dd in datas])
        self.num_edges = np.asarray([dd.num_edges for dd in datas])
        self.twoE_max = int(self.twoE.max())
        ghost = self.twoE_max                 # row 2E_max of the extended chi

        def remap(arr, dd):
            # per-cell ghost references (class_bucket padding points at the
            # CELL's own ghost row 2E_g) move to the stacked ghost row
            out = np.asarray(arr, np.int64)
            return np.where(out == dd.num_directed, ghost, out)

        ds = sorted({cls.d for dd in datas for cls in dd.edge_classes})
        self.edge_classes = []
        for d in ds:
            percell = [
                next((c for c in dd.edge_classes if c.d == d), None)
                for dd in datas
            ]
            Ed = max(c.idx.shape[0] for c in percell if c is not None)
            idx = np.full((self.G, Ed), ghost, np.int64)
            in_edges = np.full((self.G, Ed, d), ghost, np.int64)
            A = next(c for c in percell if c is not None).A
            for g, (dd, c) in enumerate(zip(datas, percell)):
                if c is None:
                    continue
                m = c.idx.shape[0]
                idx[g, :m] = remap(c.idx, dd)
                in_edges[g, :m] = remap(c.in_edges, dd)
            self.edge_classes.append((d, idx, in_edges, A))

        L = max(dd.leaf_idx.size for dd in datas)
        self.leaf_idx = np.full((self.G, L), ghost, np.int64)
        for g, dd in enumerate(datas):
            self.leaf_idx[g, :dd.leaf_idx.size] = remap(dd.leaf_idx, dd)

    def stack_chi(self, chi_list) -> jnp.ndarray:
        """Stack per-cell chi arrays ``[2E_g, K, K]`` to ``[G, 2E_max, K,
        K]``; pad rows hold the uniform message (constant — never indexed
        by any cell's tables, so they stay fixed through every sweep)."""
        if len(chi_list) != self.G:
            raise ValueError(f"need {self.G} chi arrays, got {len(chi_list)}")
        K = self.K
        out = np.full(
            (self.G, self.twoE_max, K, K), 1.0 / (K * K),
            dtype=np.dtype(self.dtype),
        )
        for g, (chi, e2) in enumerate(zip(chi_list, self.twoE)):
            chi = np.asarray(chi)
            if chi.shape != (e2, K, K):
                raise ValueError(
                    f"cell {g}: chi shape {chi.shape} != {(int(e2), K, K)}"
                )
            out[g, :e2] = chi
        return jnp.asarray(out)


def stack_bdcm(data_list: list[BDCMData]) -> StackedBDCM:
    """Stack ragged per-cell BDCM tables into the ``[G, Ed_max, …]`` layout
    of :class:`StackedBDCM` (padding with the existing ghost-row
    machinery). The table half of the cell-parallel entropy pipeline."""
    return StackedBDCM(data_list)


def make_ensemble_sweep(
    ens: EnsembleBDCM,
    *,
    damp: float,
    eps_clamp: float = 0.0,
    mask_invalid_src: bool = True,
):
    """Jitted ``(chi[G, 2E, K, K], lmbd) -> chi'``: the BDCM sweep vmapped
    over the ensemble axis (λ shared across graphs)."""
    T, K = ens.T, ens.K
    valid = jnp.asarray(ens.valid)
    x0 = jnp.asarray(ens.x0, ens.dtype)
    classes = [
        (d, jnp.asarray(idx), jnp.asarray(ie), jnp.asarray(A, ens.dtype))
        for d, idx, ie, A in ens.edge_classes
    ]

    def sweep_one(chi, lmbd, *tables):
        tilt = jnp.exp(-lmbd * x0)
        for (d, _, _, A), (idx, in_edges) in zip(classes, zip(*[iter(tables)] * 2)):
            chi_in = chi[in_edges]
            if mask_invalid_src:
                chi_in = chi_in * valid[None, None, :, None]
            upd = class_update(
                chi_in, A, tilt, chi[idx], d=d, T=T, K=K,
                damp=damp, eps_clamp=eps_clamp,
            )
            chi = chi.at[idx].set(upd)
        return chi

    flat_tables = [t for _, idx, ie, _ in classes for t in (idx, ie)]
    vsweep = jax.vmap(sweep_one, in_axes=(0, None) + (0,) * len(flat_tables))

    @jax.jit
    def sweep(chi, lmbd):
        return vsweep(chi, lmbd, *flat_tables)

    return sweep


def make_ensemble_free_entropy(
    ens: EnsembleBDCM, *, n_total: int | None = None, eps_clamp: float = 0.0
):
    """Jitted ``(chi, lmbd) -> φ[G]`` for a congruent isolate-free ensemble."""
    T, K, n = ens.T, ens.K, ens.n
    n_total = n_total or n
    E = ens.num_edges
    valid = jnp.asarray(ens.valid)
    validf = jnp.asarray(ens.valid, ens.dtype)
    mask2 = validf[:, None] * validf[None, :]
    x0 = jnp.asarray(ens.x0, ens.dtype)
    nclasses = [
        (d, jnp.asarray(idx), jnp.asarray(ie), jnp.asarray(Ai, ens.dtype))
        for d, idx, ie, Ai in ens.node_classes
    ]

    def phi_one(chi, lmbd, *tables):
        tilt = jnp.exp(-lmbd * x0)
        zi = jnp.zeros((n,), chi.dtype)
        for (d, _, _, Ai), (idx, in_edges) in zip(nclasses, zip(*[iter(tables)] * 2)):
            chi_in = chi[in_edges] * valid[None, None, :, None]
            LL = _neighbor_dp(chi_in, d, T, K)
            z = jnp.einsum("xm,nxm,x->n", Ai, LL, tilt)
            zi = zi.at[idx].set(z)
        zi = jnp.maximum(zi, eps_clamp)
        P = chi[:E] * jnp.swapaxes(chi[E:], 1, 2) * mask2[None]
        zij = jnp.maximum(P.sum(axis=(1, 2)), eps_clamp)
        phi = (jnp.sum(jnp.log(zi)) - jnp.sum(jnp.log(zij))) / n_total
        # empty attractor set: φ=−inf, not (−inf)−(−inf)=NaN; vanished Z
        # sits AT the clamp floor (see _phi_exec)
        return jnp.where(jnp.any(zi <= eps_clamp), -jnp.inf, phi)

    flat_tables = [t for _, idx, ie, _ in nclasses for t in (idx, ie)]
    vphi = jax.vmap(phi_one, in_axes=(0, None) + (0,) * len(flat_tables))

    @jax.jit
    def phi(chi, lmbd):
        return vphi(chi, lmbd, *flat_tables)

    return phi


def make_ensemble_m_init(ens: EnsembleBDCM, *, n_total: int | None = None, eps_clamp: float = 0.0):
    """Jitted ``chi -> m_init[G]`` for a congruent isolate-free ensemble."""
    E = ens.num_edges
    n_total = n_total or ens.n
    validf = jnp.asarray(ens.valid, ens.dtype)
    mask2 = validf[:, None] * validf[None, :]
    x0 = jnp.asarray(ens.x0, ens.dtype)
    edges = jnp.asarray(ens.edges)
    deg = jnp.asarray(ens.deg, ens.dtype)

    def m_one(chi, edges_g, deg_g):
        P = chi[:E] * jnp.swapaxes(chi[E:], 1, 2) * mask2[None]
        Zij = jnp.maximum(P.sum(axis=(1, 2)), eps_clamp)
        wu = x0[:, None] / deg_g[edges_g[:, 0]][:, None, None]
        wv = x0[None, :] / deg_g[edges_g[:, 1]][:, None, None]
        s = ((wu + wv) * P).sum(axis=(1, 2))
        # Z_ij = 0 (empty attractor set): 0, not 0/0 = NaN — same guard as
        # _minit_edge_terms_exec, so ent1 degrades to −inf and the
        # entropy-floor exit still fires on ensemble members
        s = jnp.where(
            Zij > eps_clamp, s / jnp.maximum(Zij, jnp.finfo(chi.dtype).tiny), 0.0
        )
        return s.sum() / n_total

    vm = jax.vmap(m_one, in_axes=(0, 0, 0))

    @jax.jit
    def m_init(chi):
        return vm(chi, edges, deg)

    return m_init


def make_ensemble_leaf_setter(ens: EnsembleBDCM):
    """Jitted ``(chi[G,...], lmbd) -> chi``: closed-form leaf messages per
    graph (no-op when the ensemble has no degree-0 edges)."""
    has_leaves = ens.leaf_idx.shape[1] > 0
    leaf01 = jnp.asarray(ens.leaf01, ens.dtype)
    x0 = jnp.asarray(ens.x0, ens.dtype)
    leaf_idx = jnp.asarray(ens.leaf_idx)

    @jax.jit
    def set_leaves(chi, lmbd):
        if not has_leaves:
            return chi
        t = leaf01 * jnp.exp(-lmbd * x0)[:, None]
        t = t / t.sum()
        return jax.vmap(lambda c, li: c.at[li].set(t[None]))(chi, leaf_idx)

    return set_leaves


def make_leaf_setter(data: BDCMData):
    """Jitted ``(chi, lmbd) -> chi`` writing the closed-form leaf messages
    (d=0 edges): normalized λ-tilted bare factor (`ipynb:403-417`)."""
    leaf01 = jnp.asarray(data.leaf01, data.dtype)
    x0 = jnp.asarray(data.x0, data.dtype)
    leaf_idx = jnp.asarray(data.leaf_idx)
    has_leaves = data.leaf_idx.size > 0

    @jax.jit
    def set_leaves(chi, lmbd):
        if not has_leaves:
            return chi
        t = leaf01 * jnp.exp(-lmbd * x0)[:, None]
        t = t / t.sum()
        return chi.at[leaf_idx].set(t[None])

    return set_leaves


def _require_halved_layout(data: BDCMData, what: str) -> None:
    """The Z_ij/φ/m_init observables pair forward and reverse messages by
    slicing chi into halves (``chi[:E]``/``chi[E:]``); a permuted edge layout
    (``EdgeTables.rev_map`` set, e.g. the replica-major union tables of
    :func:`graphdyn.graphs.replicate_edge_tables`) breaks that pairing."""
    if getattr(data.tables, "rev_map", None) is not None:
        raise ValueError(
            f"{what} requires the canonical [forward | reverse] directed-edge "
            "layout; got permuted tables (rev_map set). Build BDCMData from "
            "build_edge_tables(...) for partition-function observables."
        )


def make_edge_partition(data: BDCMData, eps_clamp: float = 0.0):
    """Jitted ``chi -> Z_ij[E]``: per-undirected-edge partition function with
    endpoint-valid trajectories only (`ipynb:146-155`)."""
    _require_halved_layout(data, "make_edge_partition")
    valid = jnp.asarray(data.valid, data.dtype)
    mask2 = valid[:, None] * valid[None, :]
    return lambda chi: _zij_exec(chi, mask2, float(eps_clamp))


class _ZiSpec(NamedTuple):
    T: int
    K: int
    n: int
    eps_clamp: float
    padded: bool
    class_ds: tuple


@partial(jax.jit, static_argnames=("spec",))
def _zi_exec(chi, lmbd, valid, x0, ntables, spec: _ZiSpec):
    """Module-level Z_i executor (compile-shared across graphs with the same
    node-class shapes). Padded class members gather from the ghost message
    row 2E and scatter into a ghost node slot n, both sliced away."""
    T, K, n = spec.T, spec.K, spec.n
    tilt = jnp.exp(-lmbd * x0)
    if spec.padded:
        ghost = jnp.full((1,) + chi.shape[1:], 1.0 / (K * K), chi.dtype)
        chi = jnp.concatenate([chi, ghost], axis=0)
    out = jnp.zeros((n + 1 if spec.padded else n,), chi.dtype)
    for d, (idx, in_edges, Ai) in zip(spec.class_ds, ntables):
        chi_in = chi[in_edges] * valid[None, None, :, None]
        LL = _neighbor_dp(chi_in, d, T, K)          # [Nd, K, M]
        # einsum over (xi, rho); tilt couples to xi only
        z = jnp.einsum("xm,nxm,x->n", Ai, LL, tilt)
        out = out.at[idx].set(z)
    return jnp.maximum(out[:n], spec.eps_clamp)


def _zi_args(data: BDCMData, eps_clamp: float):
    valid = jnp.asarray(data.valid)
    x0 = jnp.asarray(data.x0, data.dtype)
    ntables = tuple(
        (
            jnp.asarray(cls.idx),
            jnp.asarray(cls.in_edges),
            jnp.asarray(cls.Ai, data.dtype),
        )
        for cls in data.node_classes
    )
    spec = _ZiSpec(
        T=data.T, K=data.K, n=data.n, eps_clamp=float(eps_clamp),
        padded=data.padded, class_ds=tuple(cls.d for cls in data.node_classes),
    )
    return valid, x0, ntables, spec


def make_node_partition(data: BDCMData, eps_clamp: float = 0.0):
    """Jitted ``(chi, lmbd) -> Z_i[n]``: per-node partition function via the
    all-neighbor DP against ``Ai`` (`ipynb:157-222`). Nodes of degree 0 get
    Z=eps_clamp — the entropy pipeline removes isolates first
    (`ipynb:283-291`)."""
    valid, x0, ntables, spec = _zi_args(data, eps_clamp)
    return lambda chi, lmbd: _zi_exec(chi, lmbd, valid, x0, ntables, spec)


@partial(jax.jit, static_argnames=("eps_clamp",))
def _zij_exec(chi, mask2, eps_clamp: float):
    E = chi.shape[0] // 2
    P = chi[:E] * jnp.swapaxes(chi[E:], 1, 2) * mask2[None]
    return jnp.maximum(P.sum(axis=(1, 2)), eps_clamp)


@partial(jax.jit, static_argnames=("spec", "eps_clamp"))
def _phi_exec(chi, lmbd, valid, x0, ntables, mask2, n_iso, n_total, spec, eps_clamp):
    zi = _zi_exec(chi, lmbd, valid, x0, ntables, spec)
    zij = _zij_exec(chi, mask2, eps_clamp)
    phi = (
        jnp.sum(jnp.log(zi)) - jnp.sum(jnp.log(zij)) - lmbd * n_iso
    ) / n_total
    # empty attractor set (some Z_i = 0, e.g. minority dynamics with a c=1
    # homogeneous endpoint): no valid configuration exists — report φ=−inf
    # rather than the NaN that (−inf) − (−inf) would produce when Z_ij
    # vanishes too. _zi_exec clamps zi at spec.eps_clamp, so a vanished Z
    # sits AT the floor — compare against it, not against 0
    return jnp.where(jnp.any(zi <= spec.eps_clamp), -jnp.inf, phi)


def make_free_entropy(data: BDCMData, *, n_total: int, n_iso: int, eps_clamp: float = 0.0):
    """Jitted ``(chi, lmbd) -> φ``: Bethe free entropy density
    ``(Σ ln Z_i − Σ ln Z_ij − λ·n_iso)/n_total`` (`ipynb:318-322`), with the
    analytic isolated-node term. The isolate counts are traced scalars, so
    the compiled program is shared across graphs of the same shape."""
    _require_halved_layout(data, "make_free_entropy")
    valid, x0, ntables, spec = _zi_args(data, eps_clamp)
    validf = jnp.asarray(data.valid, data.dtype)
    mask2 = validf[:, None] * validf[None, :]
    n_iso_t = jnp.asarray(n_iso, data.dtype)
    n_total_t = jnp.asarray(n_total, data.dtype)
    return lambda chi, lmbd: _phi_exec(
        chi, lmbd, valid, x0, ntables, mask2, n_iso_t, n_total_t,
        spec, float(eps_clamp),
    )


@partial(jax.jit, static_argnames=("eps_clamp",))
def _minit_edge_terms_exec(chi, mask2, x0, edges, deg, eps_clamp: float):
    E = chi.shape[0] // 2
    P = chi[:E] * jnp.swapaxes(chi[E:], 1, 2) * mask2[None]
    Zij = jnp.maximum(P.sum(axis=(1, 2)), eps_clamp)
    wu = x0[:, None] / deg[edges[:, 0]][:, None, None]
    wv = x0[None, :] / deg[edges[:, 1]][:, None, None]
    s = ((wu + wv) * P).sum(axis=(1, 2))
    # Z_ij = 0 (empty attractor set): the edge carries no admissible
    # configurations — report 0, not 0/0 = NaN. φ is −inf there
    # (see _phi_exec), so ent1 = −inf + λ·m stays well-defined and the
    # entropy-floor early exit still fires. A vanished Z sits AT the clamp
    # floor when eps_clamp > 0, so compare against the floor.
    return jnp.where(
        Zij > eps_clamp, s / jnp.maximum(Zij, jnp.finfo(chi.dtype).tiny), 0.0
    )


def make_m_init_edge_terms(data: BDCMData, eps_clamp: float = 0.0):
    """Jitted ``chi -> s[E]``: each undirected edge's contribution to the BP
    mean initial magnetization (the summand of `ipynb:325-338`, before the
    edge sum). Lets callers aggregate per graph-ensemble member via segment
    sums (the union-ensemble entropy path)."""
    _require_halved_layout(data, "make_m_init_edge_terms")
    validf = jnp.asarray(data.valid, data.dtype)
    mask2 = validf[:, None] * validf[None, :]
    x0 = jnp.asarray(data.x0, data.dtype)
    edges = jnp.asarray(data.graph.edges.astype(np.int64))
    deg = jnp.asarray(data.graph.deg, data.dtype)
    return lambda chi: _minit_edge_terms_exec(
        chi, mask2, x0, edges, deg, float(eps_clamp)
    )


def make_mean_m_init(data: BDCMData, *, n_total: int, n_iso: int, eps_clamp: float = 0.0):
    """Jitted ``chi -> m_init``: BP mean initial magnetization
    (`ipynb:325-338`); each isolated node contributes +1 (it must sit at the
    attractor value). Shares the per-edge summand with
    :func:`make_m_init_edge_terms` (one implementation of the magnetization
    term)."""
    terms = make_m_init_edge_terms(data, eps_clamp)
    n_iso_t = jnp.asarray(n_iso, data.dtype)
    n_total_t = jnp.asarray(n_total, data.dtype)
    return lambda chi: (terms(chi).sum() + n_iso_t) / n_total_t


def make_marginals(data: BDCMData, eps: float = 1e-15):
    """Jitted ``chi -> marg[n, 2]``: per-node probabilities of x_i(0)=+1
    (col 0) / −1 (col 1), the HPr marginal computation
    (`HPR_pytorch_RRG.py:147-167`): per-directed-edge pair sums split by the
    source trajectory's initial value, ε-clamped, normalized, then multiplied
    over the node's outgoing edges. No endpoint-validity mask (faithful to the
    reference)."""
    E = data.num_edges
    sel_plus = jnp.asarray(data.x0 == 1, data.dtype)
    rev = jnp.asarray(data.tables.rev(np.arange(2 * E)))
    out_edges = data.tables.node_out_edges
    out_edges = jnp.asarray(
        out_edges.astype(np.int64) if isinstance(out_edges, np.ndarray)
        else out_edges              # device tables are int32 (range-guarded)
    )

    @jax.jit
    def marginals(chi):
        P = chi * jnp.swapaxes(chi[rev], 1, 2)          # [2E, K, K]
        Zp = (P * sel_plus[None, :, None]).sum(axis=(1, 2))
        Zm = (P * (1.0 - sel_plus)[None, :, None]).sum(axis=(1, 2))
        Zp = jnp.maximum(Zp, eps)
        Zm = jnp.maximum(Zm, eps)
        tot = Zp + Zm
        Zp, Zm = Zp / tot, Zm / tot
        # ghost slot multiplies by 1 (ragged node degrees)
        Zp_ext = jnp.concatenate([Zp, jnp.ones((1,), Zp.dtype)])
        Zm_ext = jnp.concatenate([Zm, jnp.ones((1,), Zm.dtype)])
        mp = jnp.prod(Zp_ext[out_edges], axis=1)
        mm = jnp.prod(Zm_ext[out_edges], axis=1)
        marg = jnp.stack([mp, mm], axis=1)
        return marg / marg.sum(axis=1, keepdims=True)

    return marginals
