"""Light-cone SA proposal evaluation — O(ball) instead of O(n) per flip.

The reference evaluates every Metropolis candidate by re-rolling the FULL
graph for ``p+c−1`` synchronous steps (`SA_RRG.py:32-37`: two rollouts per
``E_delta``, a third for the stop test — SURVEY.md §3.1 calls this the
single biggest performance lever). But synchronous dynamics has a finite
propagation speed of one hop per step: flipping spin i at t=0 can only
change the trajectory inside the radius-t ball around i ("light cone"), so
after ``R = p+c−1`` steps the end-state delta lives entirely inside
``B_R(i)`` — ~``1 + d·((d−1)^R − 1)/(d−2)`` nodes on a d-regular graph
(53 at d=4, R=3) versus n = 10⁴..10⁶ for the full rollout.

Mechanism: the solver carries the full cached trajectory ``S[t], t=0..R``
of the *current* configuration. A candidate flip rolls only the ball,
gathering neighbor values from the updated ball slots when the neighbor is
inside the ball and from the cached trajectory when outside (nodes at
distance > t are provably unchanged at step t). The end-sum delta is the
masked sum of (new − cached) over the ball; an accepted flip scatters the
ball columns back into the cache. All arithmetic is small-integer exact, so
the chain is bit-identical to the full-rollout solver (tested under
injected common-random-number streams).

Tables are host-precomputed per graph (`build_lightcone_tables`):
``ball[n, B]`` (BFS-ordered ball node ids, self at slot 0, padded with the
ghost id n), ``nbr_slot[n, B, dmax]`` (each ball node's neighbors as ball
slots, −1 when outside), ``nbr_glob[n, B, dmax]`` (the same neighbors as
global ids for the cached gather; ghost-padded with n). The trajectory
cache stores an extra ghost column that is always 0, so ghost gathers are
neutral and ghost scatters are no-ops.

Known ceiling (measured, CPU backend): the accept-time scatter into the
carried cache is NOT aliased in place by XLA:CPU even with the
read-free trash-column formulation — each step copies the O(R·T·n) buffer,
which caps very-large-n throughput (delta-only: 14k steps/s at n=2e4;
with accept: ~2k). The mode still wins 8.5×/15× at n=1e4/2e4 overall
because the full rollout pays O(n) arithmetic AND the copy. On TPU the
in-place carry-scatter pattern (the KV-cache update shape) is expected to
alias; measure via benchmarks/config1_sa_rrg.py when a chip is reachable.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp


class LightconeTables(NamedTuple):
    ball: jnp.ndarray       # int32[n, B] — ball node ids, self at slot 0
    nbr_slot: jnp.ndarray   # int32[n, B, dmax] — ball slot of each neighbor, -1 outside
    nbr_glob: jnp.ndarray   # int32[n, B, dmax] — global id of each neighbor (n = ghost)
    radius: int
    ball_max: int


def _adjacency_checksums(nbr) -> tuple[int, int]:
    """Two independent position-weighted 32-bit checksums of a neighbor
    table, computed WHERE THE ARRAY LIVES (numpy on host, XLA on device —
    only two scalars ever cross the link). Weights are a fixed odd-multiplier
    mix of the flat position, so swapped/permuted/mismatched adjacencies
    collide only with ~2^-64 probability."""
    xp = jnp if isinstance(nbr, jnp.ndarray) else np
    flat = xp.asarray(nbr, dtype=xp.uint32).reshape(-1)
    pos = xp.arange(flat.shape[0], dtype=xp.uint32)
    w1 = pos * xp.uint32(2654435761) + xp.uint32(0x9E3779B9)
    w2 = (pos ^ xp.uint32(0x85EBCA6B)) * xp.uint32(2246822519) + xp.uint32(1)
    c1 = ((flat + xp.uint32(1)) * w1).sum(dtype=xp.uint32)
    c2 = ((flat + xp.uint32(1)) * w2).sum(dtype=xp.uint32)
    return int(c1), int(c2)


def resolve_lightcone_tables(graph, radius: int, lc_tables=None) -> LightconeTables:
    """Build tables for ``graph``/``radius``, or validate caller-supplied
    ones. Slot 0 of every ball is the node itself, so ``nbr_glob[:, 0, :]``
    IS the adjacency the tables were built from — a full graph identity
    check, not just a shape check. A mismatched table would make the chain
    silently diverge (JAX gathers clamp instead of erroring), so refuse up
    front. One guard shared by the unsharded and mesh SA solvers.

    The identity check compares position-weighted checksums rather than the
    raw arrays: device-built tables at n=1e6 would otherwise pull 12 MB to
    the host on EVERY solver call — tens of seconds over the tunneled TPU
    link, inside callers' timed regions."""
    if lc_tables is None:
        return build_lightcone_tables(graph, radius)
    if (
        lc_tables.radius != radius
        or lc_tables.ball.shape[0] != graph.n
        or lc_tables.nbr_glob.shape[2] != graph.nbr.shape[1]
        or _adjacency_checksums(lc_tables.nbr_glob[:, 0, :])
        != _adjacency_checksums(graph.nbr)
    ):
        raise ValueError(
            f"lc_tables were built for a different graph or radius "
            f"(tables: radius={lc_tables.radius}, "
            f"n={lc_tables.ball.shape[0]}; run: radius={radius} "
            f"(p+c-1), n={graph.n}); rebuild with build_lightcone_tables"
        )
    return lc_tables


def build_lightcone_tables(graph, radius: int) -> LightconeTables:
    """Host-side BFS ball tables for every node. O(n · ball) time/memory —
    intended for the SA regimes (n ≲ 1e5); the full-rollout mode remains
    for giant graphs where n·B tables would dominate HBM.

    Pure-Python int loops over ``nbr.tolist()`` with a timestamp visited
    array (no per-node dict churn, no numpy-scalar hashing) — ~1 s at
    n=1e4, d=4, R=3."""
    n = graph.n
    nbr = np.asarray(graph.nbr)
    dmax = nbr.shape[1]
    nbr_list = nbr.tolist()
    visited = [-1] * (n + 1)
    visited[n] = n + 1          # ghost: never admitted
    balls = []
    for i in range(n):
        visited[i] = i
        order = [i]
        frontier = [i]
        for _ in range(radius):
            nxt = []
            for j in frontier:
                for k in nbr_list[j]:
                    if visited[k] != i and k != n:
                        visited[k] = i
                        nxt.append(k)
            nxt.sort()
            order.extend(nxt)
            frontier = nxt
        balls.append(order)
    B = max(len(b) for b in balls)

    # graftlint: disable-next-line=GD017  radius-bounded ball tables (B ≈ d^r slots, not a dmax-padded node layout); host build, parity-tested vs the full rollout
    ball = np.full((n, B), n, np.int32)
    nbr_slot = np.full((n, B, dmax), -1, np.int32)
    # graftlint: disable-next-line=GD017  same ball-table build: ghost id fills the radius-bounded slots, not a padded nbr[n, dmax] layout
    nbr_glob = np.full((n, B, dmax), n, np.int32)
    slot_lookup = np.full(n + 1, -1, np.int32)    # ghost row n stays -1
    for i, order in enumerate(balls):
        L = len(order)
        ball[i, :L] = order
        nbr_glob[i, :L] = nbr[order]
        slot_lookup[order] = np.arange(L, dtype=np.int32)
        nbr_slot[i, :L] = slot_lookup[nbr_glob[i, :L]]
        slot_lookup[order] = -1                   # O(ball) reset
    return LightconeTables(
        ball=jnp.asarray(ball),
        nbr_slot=jnp.asarray(nbr_slot),
        nbr_glob=jnp.asarray(nbr_glob),
        radius=radius,
        ball_max=B,
    )


def ball_bound(dmax: int, radius: int) -> int:
    """Tree upper bound on the radius-``radius`` ball size at max degree
    ``dmax``: 1 + Σ_{k=1..r} dmax·(dmax−1)^{k−1}. Exact on trees; an
    overestimate wherever short cycles merge branches (padding absorbs)."""
    return 1 + sum(dmax * max(dmax - 1, 1) ** (k - 1)
                   for k in range(1, radius + 1))


def build_lightcone_tables_device(graph, radius: int) -> LightconeTables:
    """The ball tables built ON DEVICE — gathers, sorts and searchsorted
    instead of the host BFS of :func:`build_lightcone_tables`.

    Motivation: at n=1e6 the host builder spends ~100 s of Python BFS and
    then uploads ~600 MB of tables over the tunneled TPU link (the r04
    session measured ~0.3 MB/s host→device — half an hour of transfer for
    one benchmark rung). Here only the [n, dmax] neighbor table crosses the
    link; everything else is computed where it will be used.

    Construction per node i (vectorized over all nodes at once):

    1. candidate list = radius-fold repeated neighbor gather starting from
       [i] (ghost id n maps to itself, so padding propagates inertly);
    2. self-occurrences masked to ghost, then sort + first-occurrence
       compaction → the ball as {i} followed by the remaining members in
       ascending id order, ghost-padded to the static tree bound B;
    3. ``nbr_glob = nbr_ext[ball]``; ``nbr_slot`` by binary search of each
       global neighbor id in the sorted tail (slot 0 = self handled
       separately, ghost/out-of-ball → −1).

    Slot ORDER differs from the host builder (BFS level order there,
    sorted-id here), but the kernel contract only requires membership,
    self-at-slot-0, and table self-consistency — the per-slot DP is
    order-independent, so chains stay bit-identical (tested against the
    host tables and the full rollout).
    """
    n = graph.n
    nbr = jnp.asarray(graph.nbr)
    dmax = int(nbr.shape[1])
    B = ball_bound(dmax, radius)
    # the static tree bound pads every row to the WORST-degree ball — fine
    # for (near-)regular graphs (d=3, r=3 ⇒ B=22 ⇒ ~620 MB of tables at
    # n=1e6), hopeless for ragged ones (ER dmax≈20, r=3 ⇒ B=7621 ⇒
    # tens of GB at n=1e5). Refuse on projected TABLE memory, not on B
    # alone (a big B on a tiny graph is fine); the host builder sizes B to
    # the largest ACTUAL ball instead.
    # peak BUILD memory, not just the three output tables: the jitted build
    # also materializes q/pos/hit/slot, each [n, B·dmax] int32 — ~4 extra
    # table-sized buffers. ≈ 4·n·B·(1+2·dmax) output + 16·n·B·dmax temps.
    # pre-build refusal estimate, not a gated cost model: it bounds a
    # build we refuse to RUN, so there is no lowered HLO for graftcost
    # to derive a model from
    # graftlint: disable-next-line=GD016 refusal guard, no HLO to derive against
    build_bytes = 4 * n * B * (1 + 6 * dmax)
    if build_bytes > 8e9:
        raise ValueError(
            f"device ball-table build would peak at ~{build_bytes / 1e9:.0f}"
            f" GB (tree bound B={B} at dmax={dmax}, radius={radius}, n={n})"
            " — too ragged for the device builder's static padding; use "
            "build_lightcone_tables (host BFS, actual-ball-sized tables)"
        )

    @jax.jit
    def build(nbr):
        nbr_ext = jnp.concatenate(
            [nbr, jnp.full((1, dmax), n, nbr.dtype)], axis=0
        )
        ids = jnp.arange(n, dtype=jnp.int32)
        cand = ids[:, None]                           # [n, 1]
        frontier = cand
        for _ in range(radius):
            frontier = jnp.take(
                nbr_ext, frontier, axis=0
            ).reshape(n, -1)                          # [n, d^k]
            cand = jnp.concatenate([cand, frontier], axis=1)
        # self never re-enters (cycles through i) — mask to ghost, re-add
        # as slot 0 below
        cand = jnp.where(cand == ids[:, None], n, cand)
        srt = jnp.sort(cand, axis=1)                  # ghosts (n) sort last
        first = jnp.concatenate(
            [jnp.ones((n, 1), bool), srt[:, 1:] != srt[:, :-1]], axis=1
        )
        uniq = jnp.sort(jnp.where(first & (srt < n), srt, n), axis=1)
        tail = uniq[:, : B - 1]                       # ascending, ghost-padded
        ball = jnp.concatenate([ids[:, None], tail], axis=1)     # [n, B]
        nbr_glob = jnp.take(nbr_ext, ball, axis=0)               # [n, B, d]
        # ghost ball slots must gather ghost neighbors (the host builder
        # leaves them at the ghost fill): nbr_ext[n] = n already does.
        q = nbr_glob.reshape(n, -1)                   # [n, B*d]
        pos = jax.vmap(
            lambda t, qr: jnp.searchsorted(t, qr)
        )(tail, q)                                    # [n, B*d]
        hit = (q < n) & (pos < B - 1) & (
            jnp.take_along_axis(tail, jnp.minimum(pos, B - 2), axis=1) == q
        )
        slot = jnp.where(hit, pos + 1, -1)            # tail slots start at 1
        slot = jnp.where(q == ids[:, None], 0, slot)  # self -> slot 0
        nbr_slot = slot.reshape(n, B, dmax).astype(jnp.int32)
        return ball, nbr_slot, nbr_glob

    ball, nbr_slot, nbr_glob = build(nbr)
    return LightconeTables(
        ball=ball, nbr_slot=nbr_slot, nbr_glob=nbr_glob,
        radius=radius, ball_max=B,
    )


def batched_trajectory(nbr, s, steps: int, R_coef: int, C_coef: int):
    """Full trajectory cache ``int8[R, steps+1, n+2]`` of the batched
    rollout — the light-cone solver's carried state. Column ``n`` is the
    ghost (always 0, read by out-of-ball/ragged gathers); column ``n+1`` is
    the trash target rejected flips scatter into (so the accept scatter
    never has to READ the cache, which lets XLA alias it in-place inside
    the solver's while-loop instead of copying O(n) per step). Same
    per-step arithmetic as :func:`graphdyn.ops.dynamics
    .batched_rollout_impl`."""
    from graphdyn.ops.dynamics import batched_rollout_impl

    Rr, n = s.shape
    frames = [s]
    cur = s
    for _ in range(steps):
        cur = batched_rollout_impl(nbr, cur, 1, R_coef, C_coef)
        frames.append(cur)
    traj = jnp.stack(frames, axis=1)                         # [R, T+1, n]
    pad = jnp.zeros((Rr, steps + 1, 2), s.dtype)             # ghost + trash
    return jnp.concatenate([traj, pad], axis=2)              # [R, T+1, n+2]


@partial(jax.jit, static_argnames=("R_coef", "C_coef", "radius"))
def lightcone_flip_delta(tables: LightconeTables, traj, i,
                         R_coef: int, C_coef: int, radius: int):
    """Per-replica candidate evaluation: roll only the ball of each
    replica's proposal ``i`` against its cached trajectory.

    ``traj: int8[R, T+1, n+2]``, ``i: int32[R]``. Returns
    ``(delta int32[R], vstack int8[R, T+1, B])`` where ``vstack`` holds the
    flipped-ball trajectory for the accept-time scatter (slot 0 is i)."""
    n = traj.shape[2] - 2

    def one(traj_r, i_r):
        ball = tables.ball[i_r]                      # [B]
        slots = tables.nbr_slot[i_r]                 # [B, d]
        globs = tables.nbr_glob[i_r]                 # [B, d]
        mask = ball < n                              # [B]
        v = traj_r[0][ball].astype(jnp.int32) * mask # padded slots -> 0
        v = v.at[0].set(-v[0])                       # the candidate flip
        frames = [v]
        for t in range(radius):
            cache_t = traj_r[t].astype(jnp.int32)    # [n+2], ghost col n = 0
            inside = slots >= 0
            nbvals = jnp.where(
                inside,
                v[jnp.clip(slots, 0)],
                cache_t[globs],
            )                                        # [B, d]
            sums = nbvals.sum(axis=1)
            v = jnp.where(
                mask, R_coef * jnp.sign(2 * sums + C_coef * v), 0
            )
            frames.append(v)
        end_cached = traj_r[radius][ball].astype(jnp.int32) * mask
        delta = jnp.where(mask, frames[-1] - end_cached, 0).sum()
        return delta.astype(jnp.int32), jnp.stack(frames).astype(jnp.int8)

    return jax.vmap(one)(traj, i)


@jax.jit
def lightcone_accept(tables: LightconeTables, traj, i, vstack, do):
    """Scatter accepted flips' ball trajectories into the cache.

    ``do: bool[R]`` masks accepted replicas. Rejected replicas redirect the
    whole scatter into the trash column ``n+1`` instead of masking against
    the current values — the scatter then never READS the cache, so XLA can
    update the while-loop carry in place rather than copying the O(n)
    buffer every step. Accepted ghost ball slots write 0 into the ghost
    column — a no-op by the ghost invariant."""
    n = traj.shape[2] - 2

    def one(traj_r, i_r, v_r, do_r):
        ball = tables.ball[i_r]                      # [B]
        tgt = jnp.where(do_r, ball, n + 1)           # reject -> trash column
        return traj_r.at[:, tgt].set(v_r, mode="promise_in_bounds")  # [T+1, B]

    return jax.vmap(one)(traj, i, vstack, do)
