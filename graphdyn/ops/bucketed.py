"""Degree-bucketed packed dynamics — the power-law fast-path kernel.

The padded kernel (:mod:`graphdyn.ops.packed`) charges every node
``dmax`` gather slots per step, so on a power-law graph ONE degree-1e5
hub multiplies both the neighbor-table bytes and the per-step work of
all ``n`` nodes by the hub factor (ROADMAP item 3). Here the graph is
laid out bucket-major (:func:`graphdyn.graphs.degree_buckets` — nodes
permuted into O(log dmax) power-of-two degree buckets, each with a tight
``nbr[n_b, d_b]`` block), and ONE jitted program runs the carry-save /
comparator update per bucket over the static bucket schedule: total
per-step work is ``Σ_b n_b·d_b ≤ 4E + n`` gather slots — edge-count
proportional, the degree-aware layout of the sparse Ising machines
(PAPERS.md arXiv:2110.02481) on the XLA/TPU substrate.

Exactness: every bucket applies the SAME carry-save bit-plane popcount
and bitwise comparator as the padded kernel (shared helpers), and a
node's popcount is identical whether accumulated over ``dmax`` padded
slots or its bucket's ``d_b`` tight slots (ghost slots contribute 0
bits), so the bucketed rollout is **bit-exact** to
:func:`graphdyn.ops.packed.packed_rollout` on the same graph modulo the
bucket permutation (tested across the rule/tie matrix on ragged ER and
seeded power-law graphs). Wide (hub) buckets reshape their slab into
32-slot *segments*, run the same unrolled CSA per segment, and dense-sum
the per-segment integer counts — exact order-independent addition, so
the segment schedule cannot perturb bits while keeping the program size
O(log dmax), not O(dmax), with no data-dependent inner loop.

Routes: ``route='comparator'`` is the hand-derived majority/minority
word logic; ``route='lut'`` compiles ANY (rule, tie) pair through the
:mod:`graphdyn.ops.lut` popcount tables (per-bucket rows via
:func:`graphdyn.ops.lut.update_lut_rows`, so a hub bucket never
materializes the O(dmax²) table square).

Layout routing: :func:`auto_layout` picks ``'bucketed'`` when the degree
coefficient of variation crosses :data:`BUCKETED_CV_THRESHOLD` — ~0 for
an RRG, ``1/sqrt(c)`` for ER(c), diverging for a power-law tail — the
knob the ``sa``/``fused`` drivers and serve admission consult.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from graphdyn.graphs import DegreeBuckets, degree_buckets, degree_cv
from graphdyn.ops.dynamics import Rule, TieBreak
from graphdyn.ops.packed import (
    _FULL,
    _compare_planes,
    _rule_tie_combine,
)

#: degree-CV above which the drivers route to the bucketed layout: an RRG
#: sits at 0, ER(c) at 1/sqrt(c) (< 0.71 for every c >= 2), a power-law
#: tail diverges with n (measured 6.8 at n=2e4, gamma=2.5)
BUCKETED_CV_THRESHOLD = 1.0

#: widest bucket whose slot loop unrolls in the trace; wider (hub)
#: buckets split into UNROLL_MAX-slot segments whose integer counts
#: dense-sum, so program size stays O(log dmax)
UNROLL_MAX = 32


def auto_layout(deg, *, threshold: float = BUCKETED_CV_THRESHOLD) -> str:
    """``'bucketed'`` when the degree CV crosses ``threshold``, else
    ``'padded'`` — the one routing predicate shared by the drivers and
    serve admission (a single knob, so they cannot disagree)."""
    return "bucketed" if degree_cv(deg) >= threshold else "padded"


def _csa_add(planes, carry):
    """One carry-save addition: fold a packed neighbor word into the
    bit-plane accumulator (the padded kernel's per-slot arithmetic)."""
    nxt = []
    for k in range(len(planes)):
        nxt.append(planes[k] ^ carry)
        carry = planes[k] & carry
    return tuple(nxt)


def _csa_bucket(sp_ext, nbr_b, n_planes: int):
    """Carry-save popcount planes of one NARROW bucket (``d_b ≤``
    :data:`UNROLL_MAX`): accumulate the bucket's ``d_b`` neighbor gathers
    (from the ghost-extended bucketed state) into ``n_planes`` bit-planes,
    slot loop unrolled in the trace — the same per-slot arithmetic as the
    padded kernel. Wide (hub) buckets take :func:`_wide_bucket_counts`."""
    d_b = nbr_b.shape[1]
    zero = jnp.zeros((nbr_b.shape[0], sp_ext.shape[1]), sp_ext.dtype)
    planes = (zero,) * n_planes
    for j in range(d_b):
        planes = _csa_add(planes, jnp.take(sp_ext, nbr_b[:, j], axis=0))
    return list(planes)


_SHIFTS = tuple(range(32))


def _wide_bucket_counts(sp_ext, nbr_b):
    """Integer neighbor counts of one WIDE (hub) bucket. The slab is
    reshaped into :data:`UNROLL_MAX`-slot *segments* (``(n_b·k, 32)``
    with ``k = d_b/32`` — exact, wide widths are powers of two), each
    segment runs the SAME unrolled CSA as a narrow bucket, and the
    per-segment integer counts dense-sum over the segment axis —
    ``int32[n_b, W, 32]``. Program size stays O(1) per bucket with **no
    inner loop**: a slot-at-a-time ``fori_loop`` here is XLA:CPU
    loop-overhead-bound (~8 µs/iteration of tiny work — measured ~20×
    slower at hub degree ~3e3), and an arithmetic lane-sum over the whole
    slab pays the 32× unpack blowup at slab size (~45× slower). Integer
    count addition is exact and order-independent, so the segment
    schedule cannot perturb bits; ghost slots gather row ``n`` (all-zero)
    and add 0."""
    n_b, d_b = nbr_b.shape
    k = d_b // UNROLL_MAX
    seg = nbr_b.reshape(n_b * k, UNROLL_MAX)
    planes = _csa_bucket(sp_ext, seg, UNROLL_MAX.bit_length())
    cnt = _planes_to_counts(planes)                  # (n_b·k, W, 32)
    return cnt.reshape(n_b, k, cnt.shape[1], 32).sum(
        axis=1, dtype=jnp.int32)


def _planes_to_counts(planes):
    """Integer neighbor counts from the CSA bit-planes: unpack each
    plane's 32 replica lanes and weight by the plane's bit value —
    ``int32[n_b, W, 32]`` (lane k of word w is replica ``32·w + k``)."""
    shifts = jnp.asarray(_SHIFTS, jnp.uint32)
    one = jnp.uint32(1)
    cnt = None
    for k, pl in enumerate(planes):
        bit = ((pl[..., None] >> shifts) & one).astype(jnp.int32) << k
        cnt = bit if cnt is None else cnt + bit
    return cnt


def _pack_lanes(bits):
    """Repack boolean replica lanes ``[n_b, W, 32]`` into packed words
    ``uint32[n_b, W]`` (lane k of word w is replica ``32·w + k`` — the
    :func:`graphdyn.ops.packed.pack_spins` convention)."""
    shifts = jnp.asarray(_SHIFTS, jnp.uint32)
    return (bits.astype(jnp.uint32) << shifts).sum(
        axis=-1, dtype=jnp.uint32)


def _lut_bucket_out(planes, masks_b, prev, n_planes: int, d_b: int):
    """LUT-route combine for one NARROW bucket: select each count's mask
    row and OR the table entries (``out = Σ_c eq_c & (prev ? m[c,1] :
    m[c,0])``, the :func:`graphdyn.ops.lut.lut_one_step` formula per
    bucket), count loop unrolled."""
    full = jnp.uint32(_FULL)
    zero = jnp.uint32(0)
    out = jnp.zeros_like(prev)
    for c in range(d_b + 1):
        eq = jnp.full_like(prev, _FULL)
        for k, pl in enumerate(planes):
            bit = full if (c >> k) & 1 else zero
            eq = eq & ~(pl ^ bit)
        m0 = masks_b[c, 0][:, None]
        m1 = masks_b[c, 1][:, None]
        out = out | (eq & ((prev & m1) | (~prev & m0)))
    return out


def _lut_bucket_out_counts(cnt, rows_b, prev):
    """LUT-route combine for one WIDE bucket from the integer counts:
    every (node, replica) lane reads its truth-table entry
    ``rows[i, cnt, prev_bit]`` directly (the same
    :func:`graphdyn.ops.lut.update_lut_rows` table the narrow masks
    encode) — one vectorized gather, no per-count loop."""
    shifts = jnp.asarray(_SHIFTS, jnp.uint32)
    prev_bits = ((prev[..., None] >> shifts) & jnp.uint32(1)).astype(
        jnp.int32)
    idx = jnp.arange(rows_b.shape[0], dtype=jnp.int32)[:, None, None]
    return _pack_lanes(rows_b[idx, cnt, prev_bits].astype(bool))


@partial(jax.jit, static_argnames=("steps", "rule", "tie", "route"),
         donate_argnames=("sp",))
def _bucketed_rollout_device(nbr_t, deg_t, lut_t, sp, steps: int,
                             rule: str = "majority", tie: str = "stay",
                             route: str = "comparator"):
    """The single-device bucketed rollout program (graftcheck fingerprints
    THIS program as the ``bucketed_rollout`` ledger entry). ``nbr_t`` /
    ``deg_t``: the :class:`graphdyn.graphs.DegreeBuckets` block tuples
    (neighbor ids index the ghost-extended BUCKETED state, ghost = n);
    ``sp: uint32[n, W]`` in bucketed node order, donated; ``lut_t``: per-
    bucket mask arrays for ``route='lut'`` (empty tuple otherwise). The
    bucket loop is unrolled over the static bucket schedule — one
    program, O(log dmax) bucket bodies."""
    rule = Rule(rule)
    tie = TieBreak(tie)
    if route not in ("comparator", "lut"):
        raise ValueError(
            f"route must be 'comparator' or 'lut', got {route!r}"
        )
    n = sp.shape[0]
    if steps <= 0:
        return sp
    widths = tuple(t.shape[1] for t in nbr_t)
    offsets = [0]
    # graftlint: disable-next-line=GD002  nbr_t is a static tuple of bucket blocks; the bucket schedule unrolls at trace time by design
    for t in nbr_t:
        offsets.append(offsets[-1] + t.shape[0])

    # per-bucket comparator constants for the narrow (CSA) buckets
    # (trace-time, from the degree blocks); wide buckets compare their
    # integer counts directly and need none of this
    thr_bits_t, even_t, n_planes_t = [], [], []
    for b, deg_b in enumerate(deg_t):
        if widths[b] > UNROLL_MAX:
            thr_bits_t.append(None)
            even_t.append(None)
            n_planes_t.append(0)
            continue
        n_planes = max(widths[b].bit_length(), 1)
        thr = (deg_b // 2).astype(jnp.uint32)
        even_t.append(
            jnp.where(deg_b % 2 == 0, _FULL, jnp.uint32(0))[:, None]
        )
        thr_bits_t.append([
            jnp.where((thr >> k) & 1 == 1, _FULL, jnp.uint32(0))[:, None]
            for k in range(n_planes)
        ])
        n_planes_t.append(n_planes)

    def body(_, sp_ext):
        outs = []
        for b, nbr_b in enumerate(nbr_t):
            prev = sp_ext[offsets[b]:offsets[b + 1]]
            if widths[b] > UNROLL_MAX:
                cnt = _wide_bucket_counts(sp_ext, nbr_b)
                if route == "comparator":
                    two = 2 * cnt
                    deg_col = deg_t[b].astype(jnp.int32)[:, None, None]
                    # 2·cnt > deg ⇔ cnt > ⌊deg/2⌋; 2·cnt == deg is the
                    # even-degree tie — the comparator's (gt, eq & even)
                    out = _rule_tie_combine(
                        _pack_lanes(two > deg_col),
                        _pack_lanes(two == deg_col), prev, rule, tie)
                else:
                    out = _lut_bucket_out_counts(cnt, lut_t[b], prev)
            else:
                planes = _csa_bucket(sp_ext, nbr_b, n_planes_t[b])
                if route == "comparator":
                    gt, eq = _compare_planes(planes, thr_bits_t[b])
                    out = _rule_tie_combine(
                        gt, eq & even_t[b], prev, rule, tie)
                else:
                    out = _lut_bucket_out(
                        planes, lut_t[b], prev, n_planes_t[b], widths[b]
                    )
            outs.append(out)
        # synchronous: every bucket read the OLD state; ghost row re-zeroed
        outs.append(jnp.zeros((1, sp_ext.shape[1]), sp_ext.dtype))
        return jnp.concatenate(outs, axis=0)

    sp_ext0 = jnp.concatenate(
        [sp, jnp.zeros((1, sp.shape[1]), sp.dtype)], axis=0
    )
    return lax.fori_loop(0, steps, body, sp_ext0)[:n]


def _bucket_lut_masks(buckets: DegreeBuckets, rule, tie) -> tuple:
    """Per-bucket LUT tables via the vectorized
    :func:`graphdyn.ops.lut.update_lut_rows` — rows for the bucket's
    actual degree sequence only, never the O(dmax²) square. Narrow
    buckets get packed word masks ``uint32[d_b+1, 2, n_b]`` (the unrolled
    eq-mask select); wide buckets keep the raw truth-table rows
    ``uint8[n_b, d_b+1, 2]`` (indexed directly by the integer counts)."""
    from graphdyn.ops.lut import update_lut_rows

    out = []
    for b, deg_b in enumerate(buckets.deg):
        rows = update_lut_rows(deg_b, buckets.widths[b], rule, tie)
        if buckets.widths[b] > UNROLL_MAX:
            out.append(np.ascontiguousarray(rows))
            continue
        masks = np.where(
            rows.transpose(1, 2, 0).astype(bool),
            np.uint32(_FULL), np.uint32(0),
        )
        out.append(masks)
    return tuple(out)


def bucketed_rollout(buckets: DegreeBuckets, sp, steps: int,
                     rule: str = "majority", tie: str = "stay",
                     route: str = "comparator"):
    """Roll packed spins ``sp: uint32[n, W]`` (BUCKETED node order — old
    node ``buckets.order[k]`` in row ``k``) for ``steps`` synchronous
    updates. Bit-exact to :func:`graphdyn.ops.packed.packed_rollout` on
    the same graph modulo the bucket permutation; see
    :func:`bucketed_rollout_global` for the order-preserving wrapper.
    ``sp`` is donated — rebind the result."""
    if route == "lut":
        lut_t = tuple(
            jnp.asarray(m) for m in _bucket_lut_masks(buckets, rule, tie)
        )
    elif route == "comparator":
        lut_t = ()
    else:
        raise ValueError(
            f"route must be 'comparator' or 'lut', got {route!r}"
        )
    nbr_t = tuple(jnp.asarray(t) for t in buckets.nbr)
    deg_t = tuple(jnp.asarray(d) for d in buckets.deg)
    return _bucketed_rollout_device(
        nbr_t, deg_t, lut_t, jnp.asarray(sp), steps, rule, tie, route
    )


def bucketed_rollout_global(graph, sp, steps: int, rule: str = "majority",
                            tie: str = "stay", route: str = "comparator",
                            buckets: DegreeBuckets | None = None):
    """Convenience parity surface: GLOBAL node order in and out (permute
    into the bucketed layout, run, permute back) — what the bit-parity
    oracle holds against ``packed_rollout`` directly. Pass ``buckets`` to
    amortize the layout build across calls."""
    b = buckets if buckets is not None else degree_buckets(graph)
    spb = np.asarray(sp)[b.order]
    out = np.asarray(bucketed_rollout(b, spb, steps, rule, tie, route))
    return out[b.inv]


def lower_bucketed_rollout(buckets: DegreeBuckets, *, W: int, steps: int,
                           rule: str = "majority", tie: str = "stay",
                           route: str = "comparator"):
    """Lower (without executing) the bucketed rollout at this layout's
    shapes — the program :mod:`graphdyn.analysis.graftcheck` fingerprints
    for the ``bucketed_rollout`` ledger entry (pinning the one-program
    contract: a single fused loop over the static bucket schedule, no
    per-bucket dispatch). Kept next to the kernel so a refactor updates
    the fingerprinted surface in place."""
    if route == "lut":
        lut_t = tuple(
            jnp.asarray(m) for m in _bucket_lut_masks(buckets, rule, tie)
        )
    else:
        lut_t = ()
    nbr_t = tuple(jnp.asarray(t) for t in buckets.nbr)
    deg_t = tuple(jnp.asarray(d) for d in buckets.deg)
    sp = jax.ShapeDtypeStruct((buckets.n, W), jnp.uint32)
    return _bucketed_rollout_device.lower(
        nbr_t, deg_t, lut_t, sp, steps, rule, tie, route
    )
