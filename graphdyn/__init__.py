"""graphdyn — a TPU-native framework for graph dynamics, strategic
initialization search, and backtracking-dynamical-cavity (BDCM) inference.

Re-designed from scratch for JAX/XLA/Pallas on TPU, with the capabilities of
the reference thesis codebase (simulated-annealing initialization search,
History-Passing reinforcement, BDCM entropy curves — see SURVEY.md):

- ``graphdyn.graphs``      — graph ensembles (RRG, Erdős–Rényi) and the padded
  neighbor-table / directed-edge-table representation (L1 of SURVEY.md §1).
- ``graphdyn.ops``         — jitted dynamics kernels (majority/minority ×
  stay/change tie-breaking), the BDCM message-passing sweep, and Pallas TPU
  kernels (L3).
- ``graphdyn.attractors``  — (p,c) backtracking-attractor combinatorics and
  factor-tensor precomputation (L2).
- ``graphdyn.observe``     — observables: magnetization, consensus fraction,
  Bethe free entropy, tilted entropy (L4).
- ``graphdyn.models``      — solvers: SA-MCMC, HPr reinforced BP, BDCM entropy
  λ-sweep (L5).
- ``graphdyn.parallel``    — device-mesh sharding, psum ensemble reductions,
  node-sharded dynamics for giant graphs.
- ``graphdyn.utils``       — PRNG, IO (npz + orbax checkpoints), profiling.
- ``graphdyn.analysis``    — static guarantees: the graftlint AST linter
  (GD001–GD007) and trace-time shape/dtype contracts.
- ``graphdyn.resilience``  — runtime guarantees: deterministic fault
  injection, retry/degrade policies, preemption-safe shutdown (exit 75).
"""

from graphdyn.graphs import (  # noqa: F401
    Graph,
    EdgeTables,
    DegreeBuckets,
    random_regular_graph,
    erdos_renyi_graph,
    powerlaw_graph,
    from_edgelist,
    graph_from_edges,
    build_edge_tables,
    bfs_order,
    degree_buckets,
    degree_cv,
    permute_nodes,
    replicate_disjoint,
    disjoint_union,
)
from graphdyn.ops.dynamics import (  # noqa: F401
    Rule,
    TieBreak,
    step_spins,
    run_dynamics,
    end_state,
)
from graphdyn.observe import magnetization, consensus_fraction  # noqa: F401
from graphdyn.config import DynamicsConfig, SAConfig, HPRConfig, EntropyConfig  # noqa: F401

__version__ = "0.1.0"
