"""``python -m graphdyn`` — see :mod:`graphdyn.cli`."""

import sys

from graphdyn.cli import main

sys.exit(main())
