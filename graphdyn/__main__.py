"""``python -m graphdyn`` — see :mod:`graphdyn.cli`."""

import sys

from graphdyn.cli import main

if __name__ == "__main__":
    sys.exit(main())
