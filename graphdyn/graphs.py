"""L1 graph layer: ensembles and index tables (TPU-first representation).

The reference stores graphs three ways (dense RRG neighbor table, directed-edge
tables for BP, degree-grouped dicts for irregular graphs — SURVEY.md §1,
reference `SA_RRG.py:9-16`, `HPR_pytorch_RRG.py:81-118`, notebook
`ER_BDCM_entropy.ipynb:278-369`). Here all graphs use ONE padded representation
that XLA can tile statically:

- ``Graph.nbr``: ``int32[n, dmax]`` neighbor table, rows padded with the ghost
  node index ``n`` (spin vectors are gathered through a zero-extended copy, so
  ghosts contribute 0 to neighbor sums — this makes the single gather+sum
  kernel exact for *any* degree sequence, subsuming the reference's per-degree
  kernel launches at `ipynb:113-117`).
- ``EdgeTables``: directed-edge tables for message passing. Directed edge ``e``
  for ``e < E`` is ``(u_e, v_e)`` in edge order; ``e + E`` is its reverse —
  the same convention as `HPR_pytorch_RRG.py:277-287`. ``in_edges[e]`` lists
  the directed edges ``(k, src[e])`` with ``k ≠ dst[e]`` (the BP-incoming
  messages, cf. `HPR_pytorch_RRG.py:81-97`), padded with the ghost edge ``2E``.

Graph construction is host-side numpy (optionally the C++ native builder in
``graphdyn._native``), seeded, and networkx-free by default; a ``networkx``
method is kept for sampling-parity experiments with the reference.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Graph(NamedTuple):
    """A simple undirected graph in padded-table form (host numpy arrays).

    Attributes:
      nbr:   int32[n, dmax] neighbor table padded with ghost index ``n``.
      deg:   int32[n] degrees.
      edges: int32[E, 2] undirected edge list (u < v not required; order is
             the canonical edge order used for the directed-edge tables).
    """

    nbr: np.ndarray
    deg: np.ndarray
    edges: np.ndarray

    @property
    def n(self) -> int:
        return self.nbr.shape[0]

    @property
    def dmax(self) -> int:
        return self.nbr.shape[1]

    @property
    def num_edges(self) -> int:
        return self.edges.shape[0]


class EdgeTables(NamedTuple):
    """Directed-edge tables for message passing (host numpy arrays).

    Directed edge ``e < E`` is ``(src[e], dst[e]) = edges[e]``; ``e + E`` is
    the reversed edge. ``ghost_edge == 2E`` pads ragged rows; messages are
    gathered through a ghost-extended message array whose ghost row is the
    multiplicative identity (ones) so padding is a no-op in products.

    Attributes:
      src, dst:        int32[2E].
      edge_deg:        int32[2E], number of BP-incoming messages = deg(src)-1.
      in_edges:        int32[2E, dmax-1], incoming directed edges (k, src[e]),
                       k ∈ ∂src[e] \\ {dst[e]}, padded with 2E.
      node_in_edges:   int32[n, dmax], directed edges (k, i) into node i.
      node_out_edges:  int32[n, dmax], directed edges (i, k) out of node i.
      rev_map:         int32[2E] or None. None means the canonical halved
                       layout (reverse of e is (e+E) mod 2E). A permuted
                       layout (e.g. the replica-major union of
                       :func:`replicate_edge_tables`) carries the reversal
                       explicitly; the halves-slicing observables (Z_ij, φ,
                       m_init) require the canonical layout and refuse
                       tables with a rev_map.
    """

    src: np.ndarray
    dst: np.ndarray
    edge_deg: np.ndarray
    in_edges: np.ndarray
    node_in_edges: np.ndarray
    node_out_edges: np.ndarray
    rev_map: np.ndarray | None = None

    @property
    def num_directed(self) -> int:
        return self.src.shape[0]

    @property
    def num_edges(self) -> int:
        return self.src.shape[0] // 2

    def rev(self, e: np.ndarray) -> np.ndarray:
        if self.rev_map is not None:
            return self.rev_map[np.asarray(e)]
        E = self.num_edges
        if E == 0:
            return np.asarray(e)
        return (e + E) % (2 * E)


# ---------------------------------------------------------------------------
# Construction from an edge list
# ---------------------------------------------------------------------------


def _directed_endpoints(n: int, edges: np.ndarray):
    if edges.size and (edges.min() < 0 or edges.max() >= n):
        raise ValueError(
            f"edge endpoints must be in [0, {n}); got range "
            f"[{edges.min()}, {edges.max()}]"
        )
    u, v = edges[:, 0], edges[:, 1]
    src = np.concatenate([u, v]).astype(np.int64)
    dst = np.concatenate([v, u]).astype(np.int64)
    return src, dst


def _padded_slots(n: int, keys: np.ndarray, values: np.ndarray, width: int, fill):
    """Scatter ``values`` into an ``[n, width]`` table grouped by ``keys``.

    Stable within each group (original order preserved). Rows padded with
    ``fill``.
    """
    order = np.argsort(keys, kind="stable")
    k_sorted = keys[order]
    v_sorted = values[order]
    counts = np.bincount(k_sorted, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank = np.arange(k_sorted.size) - starts[k_sorted]
    table = np.full((n, width), fill, dtype=np.int64)
    table[k_sorted, rank] = v_sorted
    return table


def graph_from_edges(n: int, edges: np.ndarray, dmax: int | None = None) -> Graph:
    """Build the padded neighbor-table Graph from an undirected edge list."""
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    src, dst = _directed_endpoints(n, edges)
    deg = np.bincount(src, minlength=n)
    actual_max = max(int(deg.max(initial=0)), 1)
    if dmax is None:
        dmax = actual_max
    elif dmax < actual_max:
        raise ValueError(f"dmax={dmax} < max degree {actual_max}")
    nbr = _padded_slots(n, src, dst, dmax, fill=n)
    return Graph(
        nbr=nbr.astype(np.int32),
        deg=deg.astype(np.int32),
        edges=edges.astype(np.int32),
    )


def build_edge_tables(graph: Graph) -> EdgeTables:
    """Build directed-edge message-passing tables for a Graph."""
    n, dmax = graph.n, graph.dmax
    edges = graph.edges.astype(np.int64)
    E = edges.shape[0]
    ghost_edge = 2 * E
    src, dst = _directed_endpoints(n, edges)
    eid = np.arange(2 * E, dtype=np.int64)

    node_in = _padded_slots(n, dst, eid, dmax, fill=ghost_edge)
    node_out = _padded_slots(n, src, eid, dmax, fill=ghost_edge)

    # Incoming messages of edge e: directed edges into src[e], minus rev(e).
    rev = (eid + E) % (2 * E)
    rows = node_in[src]                       # [2E, dmax]
    drop = (rows == rev[:, None]) | (rows == ghost_edge)
    order = np.argsort(drop, axis=1, kind="stable")  # keep (False) first
    kept = np.take_along_axis(rows, order, axis=1)
    kept_mask = np.take_along_axis(drop, order, axis=1)
    width = max(dmax - 1, 1)
    in_edges = np.where(kept_mask, ghost_edge, kept)[:, :width]

    edge_deg = graph.deg[src] - 1

    return EdgeTables(
        src=src.astype(np.int32),
        dst=dst.astype(np.int32),
        edge_deg=edge_deg.astype(np.int32),
        in_edges=in_edges.astype(np.int32),
        node_in_edges=node_in.astype(np.int32),
        node_out_edges=node_out.astype(np.int32),
    )


def degree_classes(values: np.ndarray) -> dict[int, np.ndarray]:
    """Host-side grouping {degree: indices} (the notebook's degree classes,
    `ER_BDCM_entropy.ipynb:276-295`), used to pick static DP depths at trace
    time."""
    out: dict[int, np.ndarray] = {}
    for d in np.unique(values):
        out[int(d)] = np.where(values == d)[0].astype(np.int32)
    return out


def remove_isolates(graph: Graph) -> tuple[Graph, int]:
    """Drop isolated nodes, relabel to 0..n'-1; returns (subgraph, n_iso).

    Mirrors the analytic treatment of isolates in the BDCM entropy sweep
    (`ER_BDCM_entropy.ipynb:283-291`): isolates contribute ``-λ·n_iso/n`` to φ
    and ``+1`` each to m_init, handled by the entropy solver, not the graph.
    """
    keep = graph.deg > 0
    n_iso = int((~keep).sum())
    if n_iso == 0:
        return graph, 0
    relabel = np.cumsum(keep) - 1
    edges = relabel[graph.edges.astype(np.int64)]
    return graph_from_edges(int(keep.sum()), edges), n_iso


# ---------------------------------------------------------------------------
# Ensembles
# ---------------------------------------------------------------------------


def _as_rng(seed) -> np.random.Generator:
    if isinstance(seed, np.random.Generator):
        return seed
    return np.random.default_rng(seed)


def random_regular_graph(
    n: int,
    d: int,
    *,
    seed=None,
    method: str = "pairing",
    max_repair_rounds: int = 200,
) -> Graph:
    """Sample a d-regular simple graph on n nodes.

    ``method='pairing'`` (default): configuration-model stub pairing with
    vectorized conflict repair — asymptotically uniform like the reference's
    `nx.random_regular_graph` (`SA_RRG.py:59-60`) but numpy-native and fast at
    N=10⁶. ``method='networkx'`` defers to networkx for sampling-parity runs.
    """
    if n * d % 2 != 0:
        raise ValueError("n*d must be even")
    if d >= n:
        raise ValueError("need d < n")
    if method == "networkx":
        import networkx as nx

        G = nx.random_regular_graph(d, n, seed=seed)
        return graph_from_edges(n, np.array(G.edges, dtype=np.int64))
    if method == "native":
        from graphdyn._native import native_random_regular

        edges = native_random_regular(n, d, seed)
        return graph_from_edges(n, edges)

    rng = _as_rng(seed)
    if d > (n - 1) // 2:
        # Dense degrees: stub re-pairing almost never finds a simple pairing.
        # Sample the (n-1-d)-regular complement instead (complement of a
        # simple regular graph is simple and regular).
        comp = random_regular_graph(n, n - 1 - d, seed=rng, method="pairing") \
            if n - 1 - d > 0 else None
        i, j = np.triu_indices(n, k=1)
        all_codes = i * n + j
        if comp is None:
            edges = np.stack([i, j], axis=1)
        else:
            ce = comp.edges.astype(np.int64)
            lo, hi = np.minimum(ce[:, 0], ce[:, 1]), np.maximum(ce[:, 0], ce[:, 1])
            keep = ~np.isin(all_codes, lo * n + hi)
            edges = np.stack([i[keep], j[keep]], axis=1)
        return graph_from_edges(n, edges, dmax=d)

    stubs = np.repeat(np.arange(n, dtype=np.int64), d)
    rng.shuffle(stubs)
    u, v = stubs[0::2].copy(), stubs[1::2].copy()
    E = u.size

    for _ in range(max_repair_rounds):
        lo, hi = np.minimum(u, v), np.maximum(u, v)
        code = lo * n + hi
        selfloop = u == v
        # mark extra copies of duplicated edges (keep the first of each)
        order = np.argsort(code, kind="stable")
        sorted_code = code[order]
        dup_sorted = np.zeros(E, dtype=bool)
        dup_sorted[1:] = sorted_code[1:] == sorted_code[:-1]
        dup = np.zeros(E, dtype=bool)
        dup[order] = dup_sorted
        bad = selfloop | dup
        nbad = int(bad.sum())
        if nbad == 0:
            break
        # re-pair the bad stubs together with an equal number of good edges
        # (breaking up good edges avoids parity deadlocks)
        idx_bad = np.where(bad)[0]
        idx_good = np.where(~bad)[0]
        take = min(idx_good.size, max(nbad, 8))
        idx_pool = np.concatenate(
            [idx_bad, rng.choice(idx_good, size=take, replace=False)]
        )
        pool_stubs = np.concatenate([u[idx_pool], v[idx_pool]])
        rng.shuffle(pool_stubs)
        half = idx_pool.size
        u[idx_pool] = pool_stubs[:half]
        v[idx_pool] = pool_stubs[half:]
    else:
        raise RuntimeError("RRG repair did not converge; try another seed")

    return graph_from_edges(n, np.stack([u, v], axis=1), dmax=d)


def _decode_triu(code: np.ndarray, n: int):
    """Decode linear upper-triangle index k -> (i, j), i < j (vectorized)."""
    # f64 host math is load-bearing: sqrt on f32 loses the exact integer
    # decode above ~2^24 edges — never crosses the device link
    code = code.astype(np.float64)  # graftlint: disable=GD004  exact host decode
    nn = 2 * n - 1
    i = np.floor((nn - np.sqrt(nn * nn - 8.0 * code)) / 2.0).astype(np.int64)
    # float guard: correct i by at most one in either direction
    for _ in range(2):
        start = i * (2 * n - i - 1) // 2
        i = np.where(start > code.astype(np.int64), i - 1, i)
        start = i * (2 * n - i - 1) // 2
        nexts = (i + 1) * (2 * n - i - 2) // 2
        i = np.where(code.astype(np.int64) >= nexts, i + 1, i)
    start = i * (2 * n - i - 1) // 2
    j = code.astype(np.int64) - start + i + 1
    return i, j


def erdos_renyi_graph(
    n: int,
    p: float,
    *,
    seed=None,
    method: str = "numpy",
) -> Graph:
    """Sample G(n, p). ``method='networkx'`` mirrors the reference's
    `nx.fast_gnp_random_graph` (`ER_BDCM_entropy.ipynb:280`)."""
    if method == "networkx":
        import networkx as nx

        G = nx.fast_gnp_random_graph(n, p, seed=seed)
        edges = np.array(G.edges, dtype=np.int64).reshape(-1, 2)
        return graph_from_edges(n, edges)
    if method == "native":
        from graphdyn._native import native_erdos_renyi

        return graph_from_edges(n, native_erdos_renyi(n, p, seed))

    rng = _as_rng(seed)
    M = n * (n - 1) // 2
    m = int(rng.binomial(M, p)) if p < 1.0 else M
    if m == 0:
        return graph_from_edges(n, np.empty((0, 2), dtype=np.int64))
    if m > M // 4 or M <= (1 << 22):
        # Dense (or small) regime: rejection sampling degrades to
        # coupon-collecting; draw an exact m-subset instead. O(M) memory,
        # which a dense edge list costs anyway.
        codes = rng.choice(M, size=m, replace=False)
    else:
        # Sparse regime: rejection-sample distinct pair codes from [0, M).
        codes = np.array([], dtype=np.int64)
        while codes.size < m:
            extra = rng.integers(0, M, size=int((m - codes.size) * 1.2) + 8)
            codes = np.unique(np.concatenate([codes, extra]))
        codes = rng.permutation(codes)[:m]
    i, j = _decode_triu(np.sort(codes), n)
    return graph_from_edges(n, np.stack([i, j], axis=1))


def from_edgelist(
    edges,
    *,
    n: int | None = None,
    dmax: int | None = None,
    strict: bool = False,
) -> Graph:
    """Ingest an EXTERNAL undirected edge list into the padded-table
    :class:`Graph` — the entry point for real (social/web) graphs that
    arrive as pair dumps rather than from the seeded generators.

    Accepts an ``[E, 2]`` array or any iterable of ``(u, v)`` pairs.
    Unlike :func:`graph_from_edges` (which trusts its caller), this
    sanitizes: self-loops are dropped and duplicate undirected edges
    (either orientation) are deduplicated keeping the FIRST occurrence in
    input order, so the result is a simple graph and the edge order is
    deterministic in the input order. ``strict=True`` REJECTS instead of
    sanitizing — a pointed :class:`ValueError` naming the first offending
    input rows, for pipelines where a dirty dump means upstream corruption
    rather than expected noise. Endpoints outside ``[0, n)`` are always an
    error (never silently re-labeled). ``n`` defaults to ``max id + 1``
    (it must be given explicitly for an empty list). Round-trip contract:
    ``from_edgelist(g.edges, n=g.n)`` reproduces ``g``'s tables for any
    simple :class:`Graph` (tested) — a simple graph passes ``strict``.
    """
    if isinstance(edges, np.ndarray):
        e = edges.astype(np.int64).reshape(-1, 2)
    else:
        e = np.array(list(edges), dtype=np.int64).reshape(-1, 2)
    if n is None:
        if e.size == 0:
            raise ValueError("empty edge list: pass n explicitly")
        if e.min() < 0:
            raise ValueError(
                "negative node id(s) in edge list: first offending rows "
                f"{e[(e < 0).any(axis=1)][:5].tolist()}"
            )
        n = int(e.max()) + 1
    if e.size:
        bad = (e < 0).any(axis=1) | (e >= n).any(axis=1)
        if bad.any():
            rows = np.flatnonzero(bad)
            raise ValueError(
                f"{rows.size} edge endpoint(s) outside [0, {n}): first at "
                f"input row(s) {rows[:5].tolist()} = "
                f"{e[rows[:5]].tolist()}; fix the ids or pass a larger n"
            )
    loops = e[:, 0] == e[:, 1] if e.size else np.zeros(0, bool)
    if strict and loops.any():
        rows = np.flatnonzero(loops)
        raise ValueError(
            f"strict edge list has {rows.size} self-loop(s): first at "
            f"input row(s) {rows[:5].tolist()} = "
            f"{e[rows[:5]].tolist()}; drop them upstream or call with "
            "strict=False to sanitize"
        )
    e = e[~loops]                                  # self-loops dropped
    if e.size:
        lo = np.minimum(e[:, 0], e[:, 1])
        hi = np.maximum(e[:, 0], e[:, 1])
        key = lo * max(n, 1) + hi
        uniq, first, counts = np.unique(
            key, return_index=True, return_counts=True)
        if strict and (counts > 1).any():
            dup_keys = uniq[counts > 1]
            order = np.argsort(first[counts > 1])
            ex = [[int(k) // max(n, 1), int(k) % max(n, 1)]
                  for k in dup_keys[order][:5]]
            raise ValueError(
                f"strict edge list has {dup_keys.size} duplicate "
                f"undirected edge(s) (counting either orientation): first "
                f"duplicated pair(s) {ex}; dedup upstream or call with "
                "strict=False to keep each pair's first occurrence"
            )
        e = e[np.sort(first)]                      # first occurrence kept
    return graph_from_edges(n, e, dmax=dmax)


def powerlaw_graph(
    n: int,
    *,
    gamma: float = 2.5,
    dmin: int = 2,
    dmax: int | None = None,
    seed=None,
    method: str = "configuration",
) -> Graph:
    """Sample a power-law (scale-free) graph on ``n`` nodes — the degree
    regime the thesis's own motivation lives in (opinion consensus on
    social networks), where one hub can have ``~n^(1/(γ−1))`` neighbors
    and the padded ``nbr[n, dmax]`` table explodes (ROADMAP item 3; the
    degree-bucketed layout of :func:`degree_buckets` is the fast path).

    ``method='configuration'`` (default): degrees drawn from the discrete
    power law ``P(k) ∝ k^−γ`` on ``[dmin, dmax]`` (``dmax`` defaults to
    ``n−1``, the natural cutoff), stubs paired uniformly, then the
    **erased** configuration model — self-loops and duplicate edges
    dropped — so realized degrees can undershoot drawn degrees slightly
    at the hubs (standard; the degree SEQUENCE law is what matters here).
    ``method='ba'``: Barabási–Albert preferential attachment with
    ``dmin`` edges per arriving node (γ → 3 tail), a Python loop — use it
    for small sampling-parity graphs, the configuration model at scale.
    Host NumPy, deterministic per ``seed``.
    """
    if n < 2:
        raise ValueError(f"need n >= 2, got {n}")
    if dmin < 1:
        raise ValueError(f"dmin must be >= 1, got {dmin}")
    if gamma <= 1.0:
        raise ValueError(f"gamma must be > 1, got {gamma}")
    if dmax is None:
        dmax = n - 1
    if not dmin <= dmax <= n - 1:
        raise ValueError(f"need dmin <= dmax <= n-1, got [{dmin}, {dmax}]")
    rng = _as_rng(seed)
    if method == "ba":
        m = dmin
        if m >= n:
            raise ValueError(f"BA needs dmin < n, got dmin={dmin}, n={n}")
        # repeated-nodes preferential attachment: sampling uniformly from
        # the endpoint multiset IS degree-proportional sampling
        repeated: list[int] = list(range(m))
        edges = []
        for v in range(m, n):
            chosen: set[int] = set()
            guard = 0
            while len(chosen) < m:
                guard += 1
                if guard > 64 * m:
                    # degenerate early multiset: fall back to uniform
                    pool = [u for u in range(v) if u not in chosen]
                    chosen.update(
                        int(u) for u in rng.choice(
                            pool, size=m - len(chosen), replace=False)
                    )
                    break
                chosen.add(int(repeated[int(rng.integers(len(repeated)))]))
            for u in chosen:
                edges.append((u, v))
                repeated.extend((u, v))
        return from_edgelist(np.array(edges, dtype=np.int64), n=n)
    if method != "configuration":
        raise ValueError(
            f"method must be 'configuration' or 'ba', got {method!r}"
        )
    ks = np.arange(dmin, dmax + 1, dtype=np.int64)
    w = ks ** (-gamma)
    deg = rng.choice(ks, size=n, p=w / w.sum())
    if deg.sum() % 2:                               # stub parity
        i = int(rng.integers(n))
        if (deg < dmax).any():
            while deg[i] >= dmax:                   # keep support [dmin, dmax]
                i = int(rng.integers(n))
            deg[i] += 1
        else:
            deg[i] -= 1                # dmin == dmax == every draw: shed one
    stubs = np.repeat(np.arange(n, dtype=np.int64), deg)
    rng.shuffle(stubs)
    u, v = stubs[0::2], stubs[1::2]
    keep = u != v                                   # erased: no self-loops
    u, v = u[keep], v[keep]
    lo, hi = np.minimum(u, v), np.maximum(u, v)
    _, first = np.unique(lo * n + hi, return_index=True)
    first = np.sort(first)                          # erased: dedup, stable
    return graph_from_edges(n, np.stack([lo[first], hi[first]], axis=1))


def bfs_order(graph: Graph) -> np.ndarray:
    """Breadth-first node ordering (frontier-vectorized; spans all
    components). Returns ``order`` with ``order[k]`` = old id of the node
    assigned new id ``k``.

    Purpose: HBM gather locality. The packed/int8 dynamics kernels gather a
    row of spin words per neighbor; under a random labeling those rows are
    uniform over the array, while BFS labeling keeps a node's neighbors
    within a few frontier widths — the same rows land near each other in
    HBM, which prefetch and DMA batching reward (roofline notes in
    ARCHITECTURE.md). Dynamics are label-equivariant, so results only
    permute (tested).
    """
    n = graph.n
    nbr = graph.nbr
    visited = np.zeros(n + 1, bool)
    visited[n] = True                      # ghost slot
    order = np.empty(n, np.int64)
    pos = 0
    scan = 0                               # pointer to next unvisited seed
    while pos < n:
        while scan < n and visited[scan]:
            scan += 1
        frontier = np.array([scan], np.int64)
        visited[scan] = True
        while frontier.size:
            order[pos : pos + frontier.size] = frontier
            pos += frontier.size
            nxt = np.unique(nbr[frontier].reshape(-1))
            nxt = nxt[~visited[nxt]]
            visited[nxt] = True
            frontier = nxt
    return order


def degree_cv(deg) -> float:
    """Coefficient of variation of a degree sequence (std/mean, host
    float) — the layout-routing statistic: ~0 for an RRG, ``1/sqrt(c)``
    for ER(c), diverging with n for a power-law tail. The ``sa``/``fused``
    drivers and serve admission switch to the degree-bucketed layout when
    this crosses :data:`graphdyn.ops.bucketed.BUCKETED_CV_THRESHOLD`."""
    deg = np.asarray(deg)
    if deg.size == 0:
        return 0.0
    mean = float(deg.mean())
    if mean <= 0.0:
        return 0.0
    return float(deg.std()) / mean


def _bit_length(v: np.ndarray) -> np.ndarray:
    """Vectorized ``int.bit_length`` (host int math — no float log2)."""
    v = np.asarray(v, dtype=np.int64)
    out = np.zeros(v.shape, np.int64)
    for k in range(63):
        bit = np.int64(1) << k
        out += v >= bit
        if not (v >= bit).any():
            break
    return out


class DegreeBuckets(NamedTuple):
    """Degree-bucketed node layout (host numpy) — the power-law fast path.

    Nodes are permuted bucket-major into ``O(log dmax)`` power-of-two
    degree buckets: node ``i`` lands in bucket ``ceil(log2(deg_i))``
    (degrees 0 and 1 in bucket 0), so every node in a width-``2^b``
    bucket has degree in ``(2^(b-1), 2^b]`` and the tight per-bucket
    neighbor block ``nbr[b]: int32[n_b, 2^b]`` pads each row by at most
    2x over its true degree. Total table entries are therefore
    ``<= 4E + n_0`` — edge-count-proportional — vs the padded table's
    ``n·dmax``, which one degree-1e5 hub inflates for ALL n nodes (the
    generalization of the BDCM ``class_bucket`` ghost-row machinery from
    entropy solvers to the dynamics kernels; consumed by
    :mod:`graphdyn.ops.bucketed`).

    Neighbor entries are PERMUTED node ids indexing the bucketed state
    order, ghost-padded with ``n`` (the same zero-contribution slot as
    the padded kernel). Only non-empty buckets are materialized.

    Attributes:
      n:       global node count.
      order:   int64[n] old id of the node in permuted slot k.
      inv:     int64[n] permuted slot of old node i.
      offsets: int64[B+1] bucket boundaries in the permuted order.
      widths:  tuple[int, ...] static per-bucket padded width (powers of
               two, strictly increasing).
      nbr:     tuple of int32[n_b, width_b] per-bucket neighbor blocks.
      deg:     tuple of int32[n_b] per-bucket true degrees.
    """

    n: int
    order: np.ndarray
    inv: np.ndarray
    offsets: np.ndarray
    widths: tuple
    nbr: tuple
    deg: tuple

    @property
    def B(self) -> int:
        return len(self.widths)

    @property
    def table_entries(self) -> int:
        """Σ_b n_b · width_b — the bucketed analogue of ``n·dmax``."""
        return int(sum(t.shape[0] * t.shape[1] for t in self.nbr))


def degree_buckets(graph: Graph, *, seed: int | None = None) -> DegreeBuckets:
    """Build the :class:`DegreeBuckets` layout for ``graph`` (host NumPy,
    one-time cost; deterministic — ``seed=None`` keeps the stable
    original order within each bucket, preserving whatever locality the
    input labeling already has, an int seed applies a deterministic
    within-bucket shuffle instead)."""
    n = graph.n
    deg = graph.deg.astype(np.int64)
    bucket = _bit_length(np.maximum(deg - 1, 0))    # deg<=1 -> 0, else ceil(log2)
    if seed is None:
        order = np.argsort(bucket, kind="stable").astype(np.int64)
    else:
        jitter = np.random.default_rng(seed).random(n)
        order = np.lexsort((jitter, bucket)).astype(np.int64)
    inv = np.empty(n, np.int64)
    inv[order] = np.arange(n)
    # ghost index n maps to itself: bucket blocks gather the ghost-
    # extended permuted state exactly like the padded kernel
    inv_ext = np.concatenate([inv, [n]])

    present = np.unique(bucket)
    counts = np.array([(bucket == b).sum() for b in present], np.int64)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    widths, nbrs, degs = [], [], []
    for k, b in enumerate(present):
        ids = order[offsets[k]:offsets[k + 1]]
        w = 1 << int(b)
        take = min(w, graph.dmax)
        blk = inv_ext[graph.nbr[ids, :take].astype(np.int64)]
        if take < w:
            blk = np.concatenate(
                [blk, np.full((ids.size, w - take), n, np.int64)], axis=1
            )
        widths.append(w)
        nbrs.append(blk.astype(np.int32))
        degs.append(graph.deg[ids].astype(np.int32))
    return DegreeBuckets(
        n=n,
        order=order,
        inv=inv,
        offsets=offsets,
        widths=tuple(widths),
        nbr=tuple(nbrs),
        deg=tuple(degs),
    )


class Partition(NamedTuple):
    """An edge-cut node partition for node-axis sharding (host numpy).

    Part ``p`` owns the nodes ``order[offsets[p]:offsets[p+1]]`` — its
    **interior** nodes (no neighbor outside ``p``) first, **boundary**
    nodes (at least one cut edge) after, each in BFS-relative order so the
    per-shard gather locality the BFS reorder buys survives partitioning.
    The halo-exchange layout (:mod:`graphdyn.parallel.halo`) ships exactly
    the boundary nodes' spin words per synchronous step, so ``edge_cut``
    (equivalently the boundary counts) IS the per-step DCN/ICI byte bill.

    **Hub splitting** (``hubs`` non-empty): vertices above the
    ``hub_threshold`` degree are owned by NO part (``part[hub] = -1``,
    excluded from ``order``/``offsets``) and vertex-cut REPLICATED
    instead — every shard holds the hub's spin words and contributes a
    partial popcount of its locally-owned hub neighbors, combined by a
    ring allreduce over the existing halo exchange
    (:mod:`graphdyn.parallel.halo`). Without splitting, a degree-1e5 hub
    makes every partition cut-dominated: the hub is boundary to every
    part and its whole neighborhood ships each step; ``edge_cut`` here
    counts only NON-hub edges (hub traffic is the bounded
    ``O(P·hubs·log dmax)`` allreduce instead).

    Attributes:
      part:     int32[n] part id of each original node (-1 = hub).
      order:    int64[n - hubs] non-hub node ids in part-major order.
      offsets:  int64[P+1] part boundaries into ``order``.
      interior: int64[P] interior-node count per part (the first
                ``interior[p]`` rows of part ``p``'s segment).
      edge_cut: number of undirected NON-hub edges crossing parts.
      hubs:     int64[h] vertex-cut replicated hub node ids (sorted),
                or None (no hub splitting — the default layout).
    """

    part: np.ndarray
    order: np.ndarray
    offsets: np.ndarray
    interior: np.ndarray
    edge_cut: int
    hubs: np.ndarray | None = None

    @property
    def P(self) -> int:
        return len(self.offsets) - 1

    @property
    def counts(self) -> np.ndarray:
        """int64[P] nodes owned per part."""
        return np.diff(self.offsets)

    @property
    def boundary(self) -> np.ndarray:
        """int64[P] boundary-node count per part."""
        return self.counts - self.interior


def edge_cut(graph: Graph, part: np.ndarray) -> int:
    """Undirected edges of ``graph`` whose endpoints lie in different parts."""
    e = graph.edges.astype(np.int64)
    if e.size == 0:
        return 0
    return int((part[e[:, 0]] != part[e[:, 1]]).sum())


def partition_graph(
    graph: Graph,
    n_parts: int,
    *,
    seed: int = 0,
    refine_rounds: int = 8,
    balance_slack: float = 0.1,
    hub_threshold: int | None = None,
) -> Partition:
    """Edge-cut-minimizing partition into ``n_parts`` balanced parts.

    Extends :func:`bfs_order` into a partitioner (ROADMAP item 1): (1)
    **BFS-grow** — the BFS ordering is cut into ``n_parts`` contiguous
    segments (each part a union of consecutive BFS frontiers, so a part is
    a ball-like region rather than a random node sample; the same locality
    argument as the +6%-measured BFS reorder, applied to shard ownership);
    (2) a **greedy boundary refinement** pass — each round moves boundary
    nodes whose cut-edge count strictly drops to their best-connected
    neighbor part, highest gain first, under a ±``balance_slack`` part-size
    cap, until no improving move remains or ``refine_rounds`` is spent.

    Pure host NumPy and deterministic for a given ``seed`` (the seed only
    jitters the order equal-gain moves are attempted in — the irregular-
    graph analogue of arXiv:1903.11714's fixed checkerboard tiling, which
    needs no search because the lattice is regular). Returns the part-major
    node permutation with the interior/boundary split per part
    (:class:`Partition`); the ghost tables the halo exchange needs are
    derived from it by :func:`partition_ghosts`.

    ``hub_threshold`` enables **hub splitting**: nodes with degree >=
    threshold are pulled out as vertex-cut replicated hubs (see
    :class:`Partition`), their incident edges removed from the working
    graph BEFORE partitioning — so hubs neither drag the edge cut nor
    skew the balance, and the remaining bounded-degree residual
    partitions as well as an RRG/ER graph would.
    """
    n = graph.n
    if n_parts < 1:
        raise ValueError(f"n_parts must be >= 1, got {n_parts}")
    if n_parts > n:
        raise ValueError(f"n_parts={n_parts} > n={n}")
    hubs = None
    work = graph
    if hub_threshold is not None:
        if hub_threshold < 1:
            raise ValueError(
                f"hub_threshold must be >= 1, got {hub_threshold}"
            )
        hub_mask = graph.deg >= hub_threshold
        hubs = np.where(hub_mask)[0].astype(np.int64)
        if hubs.size:
            if n_parts > n - hubs.size:
                raise ValueError(
                    f"n_parts={n_parts} > non-hub nodes {n - hubs.size}"
                )
            e_all = graph.edges.astype(np.int64)
            if e_all.size:
                keep = ~(hub_mask[e_all[:, 0]] | hub_mask[e_all[:, 1]])
                e_all = e_all[keep]
            # hubs stay as ISOLATED nodes of the working graph: owned by
            # no part, never boundary, replicated by the halo layer
            work = graph_from_edges(n, e_all, dmax=graph.dmax)
        else:
            hubs = None
    graph = work
    order0 = bfs_order(graph)
    pos = np.empty(n, np.int64)
    pos[order0] = np.arange(n)

    # BFS-grow: contiguous chop of the BFS order into balanced segments
    base, rem = divmod(n, n_parts)
    sizes0 = np.full(n_parts, base, np.int64)
    sizes0[:rem] += 1
    bounds = np.concatenate([[0], np.cumsum(sizes0)])
    part = np.empty(n, np.int32)
    for p in range(n_parts):
        part[order0[bounds[p]:bounds[p + 1]]] = p

    if n_parts > 1:
        e = graph.edges.astype(np.int64)
        src = np.concatenate([e[:, 0], e[:, 1]])
        dst = np.concatenate([e[:, 1], e[:, 0]])
        rng = np.random.default_rng(seed)
        jitter = rng.random(n)            # deterministic equal-gain tiebreak
        lo = max(1, int(np.floor(base * (1.0 - balance_slack))))
        hi = int(np.ceil((base + 1) * (1.0 + balance_slack)))
        for _ in range(refine_rounds):
            # only BOUNDARY nodes (an endpoint of a cut edge) can have a
            # strictly improving move, so the per-node/per-part edge-count
            # table is sized to the cut, not to n — at the pod-scale target
            # (n=1e8+) a dense [n, P] table would cost multi-GB transients
            # per round for rows that are all gain <= 0 by construction
            cross = part[src] != part[dst]
            bdy = np.unique(src[cross])
            if bdy.size == 0:
                break
            on_bdy = np.zeros(n, bool)
            on_bdy[bdy] = True
            bdy_row = np.full(n, -1, np.int64)
            bdy_row[bdy] = np.arange(bdy.size)
            sel = on_bdy[src]
            cnt = np.zeros((bdy.size, n_parts), np.int32)
            np.add.at(cnt, (bdy_row[src[sel]], part[dst[sel]]), 1)
            own = cnt[np.arange(bdy.size), part[bdy]]
            masked = cnt.copy()
            masked[np.arange(bdy.size), part[bdy]] = -1
            best = masked.argmax(axis=1).astype(np.int32)
            gain = masked[np.arange(bdy.size), best] - own
            cand = np.where(gain > 0)[0]
            if cand.size == 0:
                break
            # highest gain first; seeded jitter orders equal gains
            cand = cand[np.lexsort((jitter[bdy[cand]], -gain[cand]))]
            sizes = np.bincount(part, minlength=n_parts).astype(np.int64)
            moved = 0
            for k in cand:
                i = bdy[k]
                p_from, p_to = part[i], best[k]
                if sizes[p_from] > lo and sizes[p_to] < hi:
                    part[i] = p_to
                    sizes[p_from] -= 1
                    sizes[p_to] += 1
                    moved += 1
            if moved == 0:
                break

    # boundary detection + part-major, interior-first, BFS-relative order
    e = graph.edges.astype(np.int64)
    is_boundary = np.zeros(n, bool)
    if e.size:
        cross = part[e[:, 0]] != part[e[:, 1]]
        is_boundary[e[cross, 0]] = True
        is_boundary[e[cross, 1]] = True
    if hubs is not None:
        # hubs are owned by no part; part=-1 sorts them to the head of
        # the lexsort, where the slice strips them from `order`
        part[hubs] = -1
    order = np.lexsort((pos, is_boundary, part)).astype(np.int64)
    if hubs is not None:
        order = order[hubs.size:]
    counts = np.bincount(
        part[part >= 0], minlength=n_parts
    ).astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    interior = counts - np.bincount(
        part[is_boundary], minlength=n_parts
    ).astype(np.int64)
    return Partition(
        part=part,
        order=order,
        offsets=offsets,
        interior=interior,
        edge_cut=edge_cut(graph, part),
        hubs=hubs,
    )


def partition_ghosts(graph: Graph, partition: Partition) -> list[np.ndarray]:
    """Per-part ghost tables: for each part ``p``, the sorted global ids of
    the remote nodes ``p``'s owned rows gather from (boundary nodes of
    OTHER parts adjacent to ``p``) — the rows the halo exchange refreshes
    each synchronous step. Sorted-by-global-id so sender and receiver
    derive the identical transfer order independently."""
    e = graph.edges.astype(np.int64)
    part = partition.part
    out: list[np.ndarray] = []
    if e.size == 0:
        return [np.empty(0, np.int64) for _ in range(partition.P)]
    src = np.concatenate([e[:, 0], e[:, 1]])
    dst = np.concatenate([e[:, 1], e[:, 0]])
    # hub endpoints (part -1, vertex-cut replicated on every shard) are
    # never ghosts: their rows are locally resident by construction
    cross = (part[src] != part[dst]) & (part[src] >= 0) & (part[dst] >= 0)
    src, dst = src[cross], dst[cross]
    for p in range(partition.P):
        out.append(np.unique(dst[part[src] == p]))
    return out


def power_graph(graph: Graph, radius: int) -> Graph:
    """The graph ``G^radius``: an edge between every pair of distinct nodes
    at distance ≤ ``radius`` in ``graph`` (host numpy, BFS-free — repeated
    neighbor-table expansion, O(n·dmax^radius) memory at build time).

    Purpose: **distance-r colorings** for the chromatic Metropolis kernel
    (:mod:`graphdyn.ops.chromatic`). A proper coloring of ``G²`` puts
    same-color nodes at pairwise distance ≥ 3, so their radius-1 update
    balls are disjoint and a whole color class updates in one device step
    with exact per-site ΔE (the dense analogue of the p-bit machines'
    independent-set ticks, arXiv:2110.02481).
    """
    if radius < 1:
        raise ValueError(f"radius must be >= 1, got {radius}")
    n = graph.n
    if radius == 1:
        return graph
    # frontier expansion over the ghost-extended table: ball[k] holds every
    # node at distance <= k (dense [n, width] with ghost padding)
    nbr = graph.nbr.astype(np.int64)
    ball = nbr
    for _ in range(radius - 1):
        nbr_ext = np.concatenate(
            [nbr, np.full((1, graph.dmax), n, np.int64)], axis=0
        )
        grown = nbr_ext[ball.reshape(-1)].reshape(n, -1)
        ball = np.concatenate([ball, grown], axis=1)
    src = np.repeat(np.arange(n, dtype=np.int64), ball.shape[1])
    dst = ball.reshape(-1)
    keep = (dst != n) & (src != dst)
    src, dst = src[keep], dst[keep]
    lo, hi = np.minimum(src, dst), np.maximum(src, dst)
    codes = np.unique(lo * n + hi)
    edges = np.stack([codes // n, codes % n], axis=1)
    return graph_from_edges(n, edges)


def greedy_coloring(graph: Graph, *, seed: int = 0) -> np.ndarray:
    """Greedy proper node coloring, **host NumPy and deterministic per
    seed**: nodes are visited highest-degree-first with a seeded jitter
    ordering equal degrees (the same determinism discipline as
    :func:`partition_graph`), each taking the smallest color absent from
    its already-colored neighbors. Guarantees **no monochromatic edge**
    and **χ ≤ dmax + 1** (a node has at most ``dmax`` colored neighbors
    when visited) — the contract the ``colorcheck`` lint step and the
    chromatic kernel's setup validation both assert.

    Returns ``int32[n]`` colors in ``[0, χ)``; ``χ = colors.max() + 1``.
    Distance-2 colorings (the chromatic kernel's requirement) come from
    ``greedy_coloring(power_graph(g, 2))``, bounded by ``dmax² + 1``.
    """
    n = graph.n
    rng = np.random.default_rng(seed)
    jitter = rng.random(n)
    order = np.lexsort((jitter, -graph.deg.astype(np.int64)))
    colors = np.full(n, -1, np.int64)
    nbr = graph.nbr
    # smallest-free-color scan: used[] sized dmax+2 so argmin always finds
    # a free slot within the chi <= dmax+1 bound
    width = graph.dmax + 2
    used = np.zeros(width, bool)
    for i in order:
        used[:] = False
        cs = colors[nbr[i][nbr[i] != n]]
        used[cs[cs >= 0]] = True
        colors[i] = int(np.argmin(used))
    return colors.astype(np.int32)


def validate_coloring(graph: Graph, colors: np.ndarray) -> list[str]:
    """Validity problems of a coloring for ``graph`` (empty list = valid):
    monochromatic edges, the χ ≤ dmax+1 greedy bound, out-of-range or
    non-contiguous color ids. The ``colorcheck`` gate and the chromatic
    kernel setup both call this — an invalid coloring would make the
    "whole independent set per device step" update silently wrong, so it
    must fail loudly before any device code runs."""
    problems = []
    colors = np.asarray(colors)
    if colors.shape != (graph.n,):
        return [f"colors shape {colors.shape} != ({graph.n},)"]
    e = graph.edges.astype(np.int64)
    if e.size:
        mono = int((colors[e[:, 0]] == colors[e[:, 1]]).sum())
        if mono:
            problems.append(f"{mono} monochromatic edge(s)")
    if colors.min(initial=0) < 0:
        problems.append("negative color id")
    chi = int(colors.max(initial=-1)) + 1
    if chi > graph.dmax + 1:
        problems.append(f"chi={chi} exceeds dmax+1={graph.dmax + 1}")
    if chi and len(np.unique(colors)) != chi:
        problems.append(f"non-contiguous color ids (chi={chi})")
    return problems


def permute_nodes(graph: Graph, order: np.ndarray) -> tuple[Graph, np.ndarray]:
    """Relabel nodes so old node ``order[k]`` becomes new node ``k``.

    Returns ``(relabeled_graph, inv)`` with ``inv[old] = new``; a spin vector
    follows via ``s_new[..., inv] = s_old`` i.e. ``s_new = s_old[..., order]``.
    """
    inv = np.empty_like(order)
    inv[order] = np.arange(order.size)
    new_edges = inv[graph.edges.astype(np.int64)]
    return graph_from_edges(graph.n, new_edges, dmax=graph.dmax), inv


def replicate_disjoint(graph: Graph, R: int) -> Graph:
    """Disjoint union of ``R`` copies of ``graph`` (copy r occupies nodes
    ``[r*n, (r+1)*n)``).

    TPU-first replica batching for message passing: a ``vmap`` over a
    replica axis of chi ``[R, 2E, K, K]`` makes XLA pick the replica axis as
    the 128-lane dim, so every ``R < 128`` pads to 128 (8× HBM blowup at
    R=16, measured — the padded buffer size is R-independent). The disjoint
    union instead keeps ONE big edge axis ``[R·2E]`` as the lane dim — the
    layout the unbatched sweep already uses — so memory scales linearly in
    R. Per-replica observables are reshapes ``[R·n] -> [R, n]``.

    Built by direct tiling of the base tables — identical to
    ``graph_from_edges`` over the shifted edge list (the stable grouped
    scatter preserves each node's incident order under the block shift;
    tested), without its O(R·E log(R·E)) sort: at config-2 scale (n=1e5,
    R=256) that sort costs ~30 s of host time per solver call.
    """
    n = graph.n
    E = graph.num_edges
    dmax = graph.dmax
    noff = np.arange(R, dtype=np.int64) * n
    edges = (
        graph.edges.astype(np.int64)[None] + noff[:, None, None]
    ).reshape(R * E, 2)
    nbr = graph.nbr.astype(np.int64)
    # ghost slot n -> union ghost R*n; real neighbors shift per replica
    nbr_u = np.where(
        nbr[None] == n, R * n, nbr[None] + noff[:, None, None]
    ).reshape(R * n, dmax)
    return Graph(
        nbr=nbr_u.astype(np.int32),
        deg=np.tile(graph.deg, R).astype(np.int32),
        edges=edges.astype(np.int32),
    )


def replicate_edge_tables(tables: EdgeTables, R: int, n: int) -> EdgeTables:
    """Directed-edge tables for ``replicate_disjoint(g, R)`` in REPLICA-MAJOR
    edge layout: replica ``r``'s directed edges occupy rows
    ``[r·2E, (r+1)·2E)`` — copy ``r`` of the base tables with edge ids offset
    by ``r·2E`` and node ids by ``r·n``.

    ``build_edge_tables(replicate_disjoint(g, R))`` instead orders directed
    edges ``[all R forward blocks | all R reverse blocks]``, which puts each
    replica's two blocks ``R·E`` rows apart: under a 1-D sharding of chi over
    the directed-edge axis every BP gather (``in_edges``, the marginals'
    reverse-edge read) then crosses shards, and GSPMD falls back to
    all-gathering chi each sweep (the measured 17× per-combo collapse of the
    round-3 replica benchmark). In the replica-major layout every index table
    entry of replica ``r`` stays inside ``[r·2E, (r+1)·2E)``, so a replica
    sharding with ``R % n_shards == 0`` is communication-free and the solver
    can run each shard's block under ``shard_map`` with purely local gathers.

    The ``[forward | reverse]`` halves convention no longer holds, so the
    reversal is carried explicitly in ``rev_map`` (see ``EdgeTables.rev``).
    """
    twoE = tables.num_directed
    E = tables.num_edges
    ghost, ghost_u = twoE, R * twoE
    eoff = np.arange(R, dtype=np.int64) * twoE
    noff = np.arange(R, dtype=np.int64) * n

    def rep_edge_ids(t: np.ndarray) -> np.ndarray:
        """Tile a table of (ghost-padded) directed-edge ids across replicas."""
        t = t.astype(np.int64)
        off = eoff.reshape((R,) + (1,) * t.ndim)
        out = np.where(t[None] == ghost, ghost_u, t[None] + off)
        return out.reshape((R * t.shape[0],) + t.shape[1:]).astype(np.int32)

    src = (tables.src.astype(np.int64)[None] + noff[:, None]).reshape(-1)
    dst = (tables.dst.astype(np.int64)[None] + noff[:, None]).reshape(-1)
    base_rev = (np.arange(twoE, dtype=np.int64) + E) % max(twoE, 1)
    rev_map = (base_rev[None] + eoff[:, None]).reshape(-1)
    return EdgeTables(
        src=src.astype(np.int32),
        dst=dst.astype(np.int32),
        edge_deg=np.tile(tables.edge_deg, R),
        in_edges=rep_edge_ids(tables.in_edges),
        node_in_edges=rep_edge_ids(tables.node_in_edges),
        node_out_edges=rep_edge_ids(tables.node_out_edges),
        rev_map=rev_map.astype(np.int32),
    )


def replicate_disjoint_device(graph: Graph, R: int) -> Graph:
    """:func:`replicate_disjoint` computed ON DEVICE: the returned ``Graph``
    holds jnp arrays built by offset arithmetic from the base graph's (small)
    host tables. Purpose: over a tunneled/remote device link the union's
    ``[R·n, dmax]`` neighbor table (~300 MB at config-2 scale) never crosses
    host→device — only the base tables do. Same layout contract as the host
    builder (tested equal)."""
    import jax.numpy as jnp

    n, E, dmax = graph.n, graph.num_edges, graph.dmax
    _check_i32(R, n)                    # ids here are node ids, ghost = R*n
    noff = (jnp.arange(R, dtype=jnp.int32) * n)[:, None, None]
    nbr = jnp.asarray(graph.nbr)
    nbr_u = jnp.where(nbr[None] == n, R * n, nbr[None] + noff)
    edges_u = jnp.asarray(graph.edges)[None] + noff
    return Graph(
        nbr=nbr_u.reshape(R * n, dmax).astype(jnp.int32),
        deg=jnp.tile(jnp.asarray(graph.deg), R),
        edges=edges_u.reshape(R * E, 2).astype(jnp.int32),
    )


def _check_i32(R: int, period: int):
    if R * period >= 2**31:
        raise ValueError(
            f"union ids exceed int32 (R={R} x period={period}); split the "
            "replicas across several smaller unions"
        )


def _rep_ids_device(t: np.ndarray, R: int, period: int, ghost: int, ghost_u: int):
    """Tile a table of (ghost-padded) ids across R replicas on device:
    replica r's copy is offset by ``r·period``; ``ghost`` maps to
    ``ghost_u`` unshifted. int32 throughout (range-guarded) so the helpers
    behave identically with and without x64."""
    import jax.numpy as jnp

    _check_i32(R, period)
    t = jnp.asarray(np.asarray(t).astype(np.int32))
    off = (jnp.arange(R, dtype=jnp.int32) * period).reshape((R,) + (1,) * t.ndim)
    out = jnp.where(t == ghost, ghost_u, t + off)
    return out.reshape((R * t.shape[0],) + t.shape[1:])


def replicate_edge_tables_device(tables: EdgeTables, R: int, n: int) -> EdgeTables:
    """:func:`replicate_edge_tables` computed ON DEVICE (same replica-major
    layout; jnp members). See :func:`replicate_disjoint_device` for why."""
    import jax.numpy as jnp

    twoE = tables.num_directed
    E = tables.num_edges
    ghost, ghost_u = twoE, R * twoE
    base_rev = (np.arange(twoE, dtype=np.int64) + E) % max(twoE, 1)
    return EdgeTables(
        src=_rep_ids_device(tables.src, R, n, -1, -1),      # no ghost nodes
        dst=_rep_ids_device(tables.dst, R, n, -1, -1),
        edge_deg=jnp.tile(jnp.asarray(tables.edge_deg), R),
        in_edges=_rep_ids_device(tables.in_edges, R, twoE, ghost, ghost_u),
        node_in_edges=_rep_ids_device(tables.node_in_edges, R, twoE, ghost, ghost_u),
        node_out_edges=_rep_ids_device(tables.node_out_edges, R, twoE, ghost, ghost_u),
        rev_map=_rep_ids_device(base_rev, R, twoE, -1, -1),
    )


class GraphStack(NamedTuple):
    """``G`` same-size graphs as one batched table set (host numpy arrays) —
    the ensemble-pipeline layout (ARCHITECTURE.md "Ensemble pipeline"):
    member ``g``'s neighbor row block is ``nbr[g]``, ghost-padded to the
    stack-wide ``dmax`` with each member's OWN ghost index ``n`` (ghost rows
    contribute 0 to neighbor sums, so padding a member to a wider ``dmax``
    cannot change its dynamics — the vmapped rollout is exact for every
    member degree sequence)."""

    nbr: np.ndarray   # int32[G, n, dmax]
    deg: np.ndarray   # int32[G, n]

    @property
    def G(self) -> int:
        return self.nbr.shape[0]

    @property
    def n(self) -> int:
        return self.nbr.shape[1]

    @property
    def dmax(self) -> int:
        return self.nbr.shape[2]


def stack_graphs(graphs, dmax: int | None = None) -> GraphStack:
    """Stack same-``n`` graphs into the batched ``nbr[G, n, dmax]`` layout
    consumed by the vmapped multi-graph solvers (one device-resident table
    set for a whole disorder ensemble, instead of one host→device transfer
    per repetition). Members with a smaller ``dmax`` are re-padded with
    their ghost index; a member wider than ``dmax`` is refused."""
    if not graphs:
        raise ValueError("empty graph stack")
    ns = {g.n for g in graphs}
    if len(ns) != 1:
        raise ValueError(f"stacked graphs must share n, got {sorted(ns)}")
    n = ns.pop()
    width = max(g.dmax for g in graphs)
    if dmax is None:
        dmax = width
    elif dmax < width:
        raise ValueError(f"dmax={dmax} < stack max degree width {width}")
    nbr = np.full((len(graphs), n, dmax), n, np.int32)
    for k, g in enumerate(graphs):
        nbr[k, :, : g.dmax] = g.nbr
    return GraphStack(
        nbr=nbr, deg=np.stack([g.deg for g in graphs]).astype(np.int32)
    )


def disjoint_union(graphs) -> tuple[Graph, np.ndarray, np.ndarray]:
    """Disjoint union of arbitrary graphs (graph k's nodes shifted by the
    cumulative node count).

    Returns ``(union, node_gid, edge_gid)`` where ``node_gid[i]`` /
    ``edge_gid[e]`` give the member-graph index of union node i / undirected
    union edge e (edges keep per-graph order, concatenated). The same
    layout rationale as :func:`replicate_disjoint` — one big edge/node axis
    instead of a padded batch axis — but for *heterogeneous* members: the
    union's degree classes are simply the merged classes of all members, so
    message passing over e.g. a whole ER ensemble with different degree
    signatures compiles as ONE program.
    """
    G = len(graphs)
    if G == 0:
        raise ValueError("empty union")
    ns = [g.n for g in graphs]
    offs = np.cumsum([0] + ns)
    edges = [
        g.edges.astype(np.int64) + offs[k] for k, g in enumerate(graphs)
        if g.num_edges
    ]
    edges = (
        np.concatenate(edges) if edges else np.empty((0, 2), np.int64)
    )
    node_gid = np.repeat(np.arange(G), ns)
    edge_gid = np.repeat(np.arange(G), [g.num_edges for g in graphs])
    return graph_from_edges(int(offs[-1]), edges), node_gid, edge_gid
