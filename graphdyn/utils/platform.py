"""Platform forcing shared by every entry point (CLI, bench, benchmarks).

Environment plugins can pin ``jax_platforms`` at interpreter startup, which a
plain ``JAX_PLATFORMS`` environment variable cannot override; the
``GRAPHDYN_FORCE_PLATFORM`` knob forces the platform from inside the process
before first jax use — e.g. ``GRAPHDYN_FORCE_PLATFORM=cpu`` runs any entry
point with the TPU unreachable. One implementation here so the contract
cannot drift between entry points.
"""

from __future__ import annotations

import os


def apply_force_platform(env_var: str = "GRAPHDYN_FORCE_PLATFORM") -> str | None:
    """Apply the force-platform knob if set; returns the forced platform.

    Must run before the first operation that initializes a jax backend
    (importing jax alone is fine)."""
    force = os.environ.get(env_var)
    if force:
        import jax

        jax.config.update("jax_platforms", force)
    return force or None


def apply_compile_cache(
    path: str | None = None, env_var: str = "GRAPHDYN_COMPILE_CACHE"
) -> str | None:
    """Opt-in persistent XLA compile cache (``jax_compilation_cache_dir``).

    A resumed or re-run ensemble job pays the multi-second XLA compile of
    its group program again for nothing — the program is identical, only
    the process is new. Pointing ``GRAPHDYN_COMPILE_CACHE`` (or the CLI's
    ``--compile-cache``) at a directory makes re-runs load the compiled
    executable from disk instead. Opt-in because the cache directory must
    be a real, writable path the operator owns (scratch volumes, not
    containers' ephemeral overlay).

    An explicit ``path`` wins over the environment variable; returns the
    directory applied, or None when the knob is unset. Cache-eligibility
    thresholds are lowered so even the smoke-sized programs qualify —
    the whole point is skipping *every* recompile on resume, not only the
    giant ones. Silently tolerates jax versions without the tuning knobs
    (the cache dir itself is supported by every jax this repo targets).
    """
    target = path or os.environ.get(env_var)
    if not target:
        return None
    import jax

    os.makedirs(target, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", target)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):  # older jax: knob absent
            pass
    return target
