"""Platform forcing shared by every entry point (CLI, bench, benchmarks).

Environment plugins can pin ``jax_platforms`` at interpreter startup, which a
plain ``JAX_PLATFORMS`` environment variable cannot override; the
``GRAPHDYN_FORCE_PLATFORM`` knob forces the platform from inside the process
before first jax use — e.g. ``GRAPHDYN_FORCE_PLATFORM=cpu`` runs any entry
point with the TPU unreachable. One implementation here so the contract
cannot drift between entry points.
"""

from __future__ import annotations

import os


def apply_force_platform(env_var: str = "GRAPHDYN_FORCE_PLATFORM") -> str | None:
    """Apply the force-platform knob if set; returns the forced platform.

    Must run before the first operation that initializes a jax backend
    (importing jax alone is fine)."""
    force = os.environ.get(env_var)
    if force:
        import jax

        jax.config.update("jax_platforms", force)
    return force or None
