"""Numerical sanitizers (SURVEY.md §5.2).

The reference has no sanitizers (single-threaded Python; its only guards are
ε-clamps at `HPR_pytorch_RRG.py:157-158`, `ER_BDCM_entropy.ipynb:209,276`).
The TPU-native analogues: a ``debug_nans`` context that makes XLA raise at
the op that produced a NaN/Inf, and a ``checkify`` wrapper that compiles
float checks *into* the jitted program (works under jit/vmap/scan where
Python-level assertions cannot run). Determinism over shardings — the psum
order-independence concern — is covered by the sharded-vs-unsharded and
mesh-layout-invariance tests in ``tests/test_parallel.py``.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """``with debug_nans():`` — any NaN produced inside re-runs the offending
    op un-jitted and raises with its location (jax's debug_nans mode)."""
    import jax

    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def checked(fn):
    """Compile float-error checks into ``fn``: returns a callable with the
    same signature that raises ``JaxRuntimeError`` on NaN/Inf/div-by-zero
    produced anywhere inside, including under jit/scan/while_loop."""
    import functools

    from jax.experimental import checkify

    cfn = checkify.checkify(fn, errors=checkify.float_checks)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        err, out = cfn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper
