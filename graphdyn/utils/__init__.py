"""Utilities: PRNG helpers, IO (npz + checkpoints), profiling hooks."""
