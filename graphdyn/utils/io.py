"""Result persistence and checkpoint/resume.

The reference persists end-of-run result arrays with ``np.savez``
(`HPR_pytorch_RRG.py:377` live; `SA_RRG.py:92`, `ER_BDCM_entropy.ipynb:515`
commented) and sketches a time-triggered intermediate save
(`ipynb:439-445,475-476`). Here both are first-class: npz-compatible result
files with the reference's key names, plus checkpoints of solver state
(chi, biases, spins, rng seed, λ index, sweep count) so SA chains and λ
sweeps resume exactly (SURVEY.md §5.4). Orbax is used when available for
async checkpointing of jax pytrees; the portable npz path is the default.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
import zipfile
import zlib
from typing import Any

import numpy as np

from graphdyn.resilience import faults as _faults
from graphdyn.resilience.retry import SAVE_RETRY, retry as _retry_call
from graphdyn.resilience.shutdown import raise_if_requested, shutdown_requested
from graphdyn.resilience.supervisor import beat as _beat

log = logging.getLogger("graphdyn.io")


def _fingerprint_repr(p) -> str:
    """``repr`` for fingerprinting. For config dataclasses, fields named in
    the class's ``_fingerprint_optional`` tuple are omitted when they hold
    their default value — so ADDING an opt-in field (at a
    semantics-preserving default) does not invalidate checkpoints written
    before the field existed. At the defaults the produced string is
    byte-identical to the pre-field dataclass repr."""
    import dataclasses

    if dataclasses.is_dataclass(p) and not isinstance(p, type):
        skip = getattr(p, "_fingerprint_optional", ())
        parts = []
        for f in dataclasses.fields(p):
            v = getattr(p, f.name)
            if f.name in skip:
                if f.default is not dataclasses.MISSING and v == f.default:
                    continue
                if (f.default_factory is not dataclasses.MISSING
                        and v == f.default_factory()):
                    continue
            parts.append(f"{f.name}={_fingerprint_repr(v)}")
        return f"{type(p).__name__}({', '.join(parts)})"
    return repr(p)


def run_fingerprint(*parts) -> str:
    """Stable hex digest identifying a solver run's full identity — graph
    arrays hash by bytes+shape, everything else by ``repr`` (config
    dataclasses have stable field reprs; opt-in fields at their defaults
    are excluded, see :func:`_fingerprint_repr`). Stored in
    chain-checkpoint metadata so a resume under a different graph, config,
    dtype, or budget is refused instead of silently producing a chimera
    chain."""
    h = hashlib.sha1()
    for p in parts:
        if isinstance(p, np.ndarray):
            h.update(str(p.shape).encode())
            h.update(np.ascontiguousarray(p).tobytes())
        else:
            h.update(_fingerprint_repr(p).encode())
        h.update(b"|")
    return h.hexdigest()


def _atomic_savez(path: str, payload: dict) -> str:
    """``np.savez`` with the temp-file + ``os.replace`` discipline: a reader
    (or a preemption mid-write) sees either the old file or the new one,
    never a torn npz. Preserves ``np.savez``'s append-``.npz`` semantics;
    returns the final path. The one savez both :func:`save_results_npz` and
    :class:`Checkpoint` go through (graftlint GD007 flags any other write
    path in the package)."""
    final = path if path.endswith(".npz") else path + ".npz"
    tmp = final[:-len(".npz")] + ".tmp.npz"
    np.savez(tmp, **payload)
    os.replace(tmp, final)
    return final


def save_results_npz(path: str, **arrays) -> None:
    """Reference-compatible result file (e.g. ``mag_reached=..., conf=...,
    num_steps=..., graphs=..., time=...`` as in `HPR_pytorch_RRG.py:377`),
    written atomically — a preemption during the end-of-run save cannot
    leave a torn results file."""
    _atomic_savez(path, {k: np.asarray(v) for k, v in arrays.items()})


def load_results_npz(path: str) -> dict[str, np.ndarray]:
    with np.load(path) as f:
        return {k: f[k] for k in f.files}


def write_json_atomic(path: str, doc, **dump_kwargs) -> None:
    """JSON result file via temp + ``os.replace`` — same torn-write
    discipline as the npz writers (GD007 flags direct ``open(…, "w")``
    persistence elsewhere in the package)."""
    write_text_atomic(path, json.dumps(doc, **dump_kwargs))


def write_text_atomic(path: str, text: str) -> None:
    """Whole-file text write via temp + ``os.replace`` — one copy of the
    atomic-write idiom: the JSON writer above delegates here, and the
    flight recorder's post-mortem JSONL dump goes through this so a crash
    *during the crash dump* can never leave a torn ledger."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)


class Checkpoint:
    """Minimal atomic checkpoint of a solver-state dict of arrays + metadata.

    Layout: one ``<path>.npz`` holding the arrays plus the metadata as a
    JSON-encoded ``__meta__`` entry. The single file goes through a temp
    file + ``os.replace``, so arrays and metadata can never be torn apart by
    a preemption — a reader sees either the old checkpoint or the new one.

    The durable store (:class:`graphdyn.resilience.store.DurableCheckpoint`,
    reached through :func:`open_checkpoint`) subclasses this with checksums,
    retention and mirroring — same file format, same fault sites.
    """

    _META_KEY = "__meta__"

    #: structural-corruption exceptions (vs transient OSError, which must
    #: propagate): what quarantine-and-fall-back is allowed to swallow
    _STRUCTURAL = (zipfile.BadZipFile, zlib.error, EOFError, ValueError)

    #: quarantined corruption evidence retained per checkpoint path (oldest
    #: cleaned first) — bounded so an unattended requeue loop cannot fill
    #: the disk with .corrupt files
    _QUARANTINE_KEEP = 5

    def __init__(self, path: str):
        self.path = path

    def _payload(self, arrays: dict[str, Any],
                 meta: dict[str, Any]) -> dict[str, np.ndarray]:
        """Validate + assemble the npz payload (arrays + JSON meta entry)."""
        if self._META_KEY in arrays:
            raise ValueError(f"array key {self._META_KEY!r} is reserved")
        payload = {k: np.asarray(v) for k, v in arrays.items()}
        for k, v in payload.items():
            if v.dtype == object:
                # savez would pickle it and SUCCEED, but the default
                # allow_pickle=False load then raises ValueError — which the
                # corruption handler would read as a corrupt file and
                # quarantine on every resume. Fail at write time instead.
                raise TypeError(
                    f"checkpoint array {k!r} has dtype=object (ragged or "
                    f"mixed) — not loadable without pickle; use a "
                    f"fixed-width dtype"
                )
        payload[self._META_KEY] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8
        )
        return payload

    def _write_fault_gate(self) -> None:
        """The ``checkpoint.write`` fault site (raise-ENOSPC / torn temp
        file / preempt), shared by the plain and durable save paths."""
        spec = _faults.check_fault("checkpoint.write", key=self.path)
        if spec is not None and spec.action != "signal":
            if spec.action == "preempt":
                raise _faults.InjectedPreemption(
                    f"injected preempt during checkpoint write ({self.path})"
                )
            if spec.action == "torn":
                # what a real preemption mid-savez leaves behind: a partial
                # temp file (never the published .npz — os.replace is atomic)
                with open(self.path + ".tmp.npz", "wb") as f:
                    f.write(b"PK\x03\x04 torn by injected preemption")
            raise _faults.InjectedWriteError(self.path)

    def save(self, arrays: dict[str, Any], meta: dict[str, Any]) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        payload = self._payload(arrays, meta)
        self._write_fault_gate()
        self._persist(payload, meta)

    def _persist(self, payload: dict[str, np.ndarray],
                 meta: dict[str, Any]) -> None:
        """One complete persistence of the assembled payload — the subclass
        hook. The durable store overrides THIS, not :meth:`save`, so every
        checkpoint write (plain or durable) flows through the one ``save``
        entry point — wrappers patched onto ``Checkpoint.save`` (the test
        suite's abort-after-save preemption fixture, retry shims) observe
        durable saves too."""
        from graphdyn import obs

        with obs.current().span("io.ckpt.write", path=self.path) as sp:
            _atomic_savez(self.path + ".npz", payload)
            if obs.enabled():
                sp.set(bytes=int(os.path.getsize(self.path + ".npz")))

    def remove(self) -> None:
        """Delete the checkpoint file if present (end-of-run cleanup), plus
        any stale temp file a preemption between ``np.savez`` and
        ``os.replace`` may have left behind."""
        for p in (self.path + ".npz", self.path + ".tmp.npz"):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    def _read_npz(self, path: str) -> tuple[dict[str, np.ndarray], dict]:
        """One structural npz read (arrays + decoded meta); raises the
        :data:`_STRUCTURAL` exceptions on corruption, ``OSError`` on
        transient trouble — classification is the caller's policy."""
        with np.load(path) as f:
            arrays = {k: f[k] for k in f.files if k != self._META_KEY}
            if self._META_KEY in f.files:
                meta = json.loads(f[self._META_KEY].tobytes().decode())
            else:
                # foreign/legacy npz (e.g. a reference-style results
                # file): still loadable, just with empty metadata
                meta = {}
        return arrays, meta

    def _quarantine_file(self, path: str) -> str:
        """Move ``path`` aside as corruption evidence with a MONOTONIC
        suffix (``.corrupt.1.npz``, ``.corrupt.2.npz``, …) so a second
        corruption can never overwrite the first's evidence; at most
        :data:`_QUARANTINE_KEEP` are retained (oldest removed first)."""
        import glob as _glob
        import re as _re

        pat = _re.compile(_re.escape(self.path) + r"\.corrupt\.(\d+)\.npz$")
        existing = sorted(
            (int(m.group(1)), f)
            for f in _glob.glob(_glob.escape(self.path) + ".corrupt.*.npz")
            if (m := pat.match(f))
        )
        nxt = (existing[-1][0] + 1) if existing else 1
        quarantine = f"{self.path}.corrupt.{nxt}.npz"
        try:
            os.replace(path, quarantine)
        except OSError:
            return "<unquarantined: rename failed>"
        retained = existing + [(nxt, quarantine)]
        for _, f in retained[:-self._QUARANTINE_KEEP]:
            try:
                os.remove(f)
            except OSError:
                pass
        return quarantine

    def load(self) -> tuple[dict[str, np.ndarray], dict[str, Any]] | None:
        path = self.path + ".npz"
        if not os.path.exists(path):
            return None
        spec = _faults.transform_spec("checkpoint.read", "truncate",
                                      key=self.path)
        if spec is not None:
            _faults.truncate_file(path)          # torn flush / partial copy
        from graphdyn import obs

        try:
            with obs.current().span("io.ckpt.read", path=self.path):
                arrays, meta = self._read_npz(path)
        # structural corruption ONLY — a transient read error (plain
        # OSError: EIO, EACCES, network blip) must propagate, not destroy a
        # perfectly good checkpoint by quarantining it
        except self._STRUCTURAL as e:
            # a corrupted/truncated checkpoint is a first-class condition
            # (torn write on a dying node, partial object-store copy), not
            # a crash: quarantine it for post-mortem and start fresh. The
            # quarantine file is deliberately NOT cleaned by remove().
            quarantine = self._quarantine_file(path)
            log.warning(
                "checkpoint at %s is corrupt (%s: %s) — quarantined to %s, "
                "starting fresh", path, type(e).__name__, e, quarantine,
            )
            obs.counter("io.ckpt.quarantine", path=self.path,
                        quarantine=quarantine,
                        error=f"{type(e).__name__}: {e}"[:200])
            return None
        return arrays, meta


def open_checkpoint(path: str) -> Checkpoint:
    """The checkpoint factory every consumer goes through
    (:class:`ChainCheckpointer`, :class:`PeriodicCheckpointer`,
    :func:`load_validated`, the grouped drivers): returns the durable store
    (:class:`graphdyn.resilience.store.DurableCheckpoint` — checksum-verified
    loads, keep-last-K retention, optional ``--ckpt-mirror`` replication,
    run journal) wrapping the same on-disk snapshot format. Plain
    :class:`Checkpoint` remains available for format-level tests."""
    from graphdyn.resilience.store import DurableCheckpoint

    return DurableCheckpoint(path)


def load_resume_prefix(ck: Checkpoint, expect: dict[str, Any]):
    """Load an ensemble-driver resume snapshot and validate its identity.

    The shared half of the driver resume protocol (used by ``sa_ensemble``
    and ``hpr_ensemble``): returns ``(arrays, next_rep)``, or ``None`` when
    no checkpoint exists; raises ``ValueError`` when any ``expect`` key
    disagrees with the stored metadata — a checkpoint from a different run
    must be refused, never silently mixed in."""
    loaded = ck.load()
    if loaded is None:
        return None
    arrays, meta = loaded
    bad = {k: (meta.get(k), v) for k, v in expect.items() if meta.get(k) != v}
    if bad:
        raise ValueError(
            f"checkpoint at {ck.path!r} is from a different run "
            f"(stored vs expected: {bad}); refusing to resume"
        )
    return arrays, int(meta["next_rep"])


def load_validated(path: str, id_key: str, id_value, what: str):
    """Load a checkpoint and refuse it unless ``meta[id_key]`` equals the
    caller's run identity — the shared load-or-refuse half of the λ-driver
    resume protocol (``entropy_grid``, ``entropy_ensemble_union``). Returns
    ``(arrays, meta)`` or None when no checkpoint exists."""
    loaded = open_checkpoint(path).load()
    if loaded is None:
        return None
    arrays, meta = loaded
    if meta.get(id_key) != id_value:
        raise ValueError(
            f"checkpoint at {path!r} is from a different {what} run "
            f"(meta {meta}); refusing to resume"
        )
    return arrays, meta


class ChainCheckpointer:
    """The chain-level exact-resume protocol shared by the solvers
    (``simulated_annealing``, ``sa_sharded``, ``hpr_solve``,
    ``hpr_solve_batch``): a fingerprint-validated load that refuses foreign
    snapshots, a due-gated periodic save stamping identical metadata, and
    remove-on-completion. One implementation so the protocol cannot drift
    between solvers.

    ``extra_meta``: additional identity fields (e.g. replica count) checked
    for equality on load and stamped on save alongside kind/seed/fp.
    """

    def __init__(self, path: str, *, kind: str, seed: int, fp: str,
                 interval_s: float, extra_meta: dict | None = None):
        self.path = path
        self._meta = {"kind": kind, "seed": int(seed), "fp": fp,
                      **(extra_meta or {})}
        self.ckpt = open_checkpoint(path)
        self._pc = PeriodicCheckpointer(path, interval_s=interval_s)

    def load_state(self, check=None) -> dict | None:
        """Load and validate; returns the arrays dict, or None when no
        checkpoint exists. ``check(arrays) -> bool`` adds shape/content
        validation. Raises ValueError on any identity mismatch."""
        loaded = self.ckpt.load()
        if loaded is None:
            return None
        arrays, meta = loaded
        ok = all(meta.get(k) == v for k, v in self._meta.items())
        if ok and check is not None:
            ok = bool(check(arrays))
        if not ok:
            raise ValueError(
                f"checkpoint at {self.path!r} is not a matching "
                f"{self._meta['kind']} snapshot for this graph/config/seed "
                f"(meta {meta}); refusing to resume"
            )
        return arrays

    def due(self) -> bool:
        return self._pc.due()

    def maybe_save(self, arrays: dict) -> bool:
        return self._pc.maybe_save(arrays, self._meta)

    def save_now(self, arrays: dict) -> bool:
        """Immediate save bypassing the interval gate — the shutdown
        snapshot. Same retry/degrade policy as periodic saves."""
        return self._pc.save_now(arrays, self._meta)

    def remove(self) -> None:
        self._pc.remove()

    def drive(self, state, *, advance, active, payload):
        """The shared chunk loop: run ``advance(state)`` until
        ``active(state)`` is False, saving a due snapshot between chunks —
        never of a finished state, so an abort in the final window cannot
        leave a stale done-snapshot — then remove the file. ``payload`` is
        only called when a save is actually due (snapshots can be large
        device-to-host copies). Returns the final state.

        Preemption-safe: when a graceful shutdown is pending (SIGTERM under
        :func:`graphdyn.resilience.graceful_shutdown`), the chunk boundary
        forces an immediate snapshot and raises
        :class:`~graphdyn.resilience.ShutdownRequested` — so the on-disk
        checkpoint is never older than one chunk when the CLI exits 75.
        Fault site ``chunk.boundary`` simulates a hard preemption here."""
        k = 0
        while active(state):
            state = advance(state)
            k += 1
            _beat("chunk")
            _faults.maybe_fail("chunk.boundary", key=f"{self.path}#{k}")
            if active(state):
                if shutdown_requested():
                    if not self.save_now(payload(state)):
                        log.warning(
                            "shutdown snapshot for %s could not be written "
                            "— resume will fall back to the last periodic "
                            "checkpoint (if any)", self.path,
                        )
                    raise_if_requested(where="chunk")
                elif self.due():
                    self.maybe_save(payload(state))
        self.remove()
        return state


def save_with_retry(ckpt: Checkpoint, arrays: dict, meta: dict) -> bool:
    """``ckpt.save`` under the process-wide retry budget
    (:data:`graphdyn.resilience.retry.SAVE_RETRY`, CLI
    ``--max-save-retries``), degrading to **skip-save** when retries are
    exhausted: a transient (or even persistent) write failure must not kill
    an hours-long chain — the snapshot is insurance, the chain is the
    value. Returns False (with a logged warning) on the degrade path."""
    try:
        # the pid in the site key seeds SAVE_RETRY's full-jitter: N hosts
        # retrying a save to the same shared-filesystem path must draw
        # DE-correlated backoff schedules (path alone would give every
        # rank the identical seed — the lockstep stampede the jitter
        # exists to prevent)
        _retry_call(
            lambda: ckpt.save(arrays, meta),
            policy=SAVE_RETRY,
            retry_on=(OSError,),
            what=f"checkpoint save ({ckpt.path}, pid {os.getpid()})",
        )
        return True
    except OSError as e:
        log.warning(
            "checkpoint save to %s failed after %d attempt(s) — SKIPPING "
            "this snapshot and continuing the run: %s",
            ckpt.path, SAVE_RETRY.tries, e,
        )
        from graphdyn import obs

        obs.counter("resilience.retry.degrade", site=f"checkpoint save "
                    f"({ckpt.path})", attempts=SAVE_RETRY.tries,
                    decision="skip-save",
                    error=f"{type(e).__name__}: {e}"[:200])
        return False


class PeriodicCheckpointer:
    """Time-triggered checkpointing (the notebook's ``saving_time`` sketch,
    `ipynb:439-445`): call ``maybe_save`` inside the solver loop; it writes at
    most every ``interval_s`` seconds. Writes go through
    :func:`save_with_retry` — after the retry budget, the snapshot is
    skipped (logged) and the next one is attempted an interval later."""

    def __init__(self, path: str, interval_s: float = 30.0, max_saves: int | None = None):
        self.ckpt = open_checkpoint(path)
        self.interval_s = interval_s
        self.max_saves = max_saves
        self._last = time.monotonic()
        self._count = 0

    def due(self) -> bool:
        """True when the next ``maybe_save`` would actually write — callers
        with expensive payloads (device-to-host copies) should gate payload
        construction on this."""
        if self.max_saves is not None and self._count >= self.max_saves:
            return False
        return time.monotonic() - self._last >= self.interval_s

    def maybe_save(self, arrays: dict[str, Any], meta: dict[str, Any]) -> bool:
        if not self.due():
            return False
        return self.save_now(arrays, meta)

    def save_now(self, arrays: dict[str, Any], meta: dict[str, Any]) -> bool:
        """Immediate save bypassing the interval gate — the graceful-
        shutdown snapshot (same retry/degrade policy). On the degrade path
        the clock still resets: retry next interval, don't hammer a full
        disk on every chunk."""
        ok = save_with_retry(self.ckpt, arrays, meta)
        self._last = time.monotonic()
        if ok:
            self._count += 1
        return ok

    def remove(self) -> None:
        self.ckpt.remove()


def save_pytree_orbax(path: str, pytree) -> bool:
    """Orbax checkpoint of a jax pytree; returns False if orbax is absent."""
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        return False
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(path), pytree, force=True)
    return True


def load_pytree_orbax(path: str):
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
