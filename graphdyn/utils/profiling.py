"""Tracing/profiling hooks (SURVEY.md §5.1) — now thin shims.

The reference's observability was a wall-clock bracket (`HPR:257,364`) and
per-λ prints (`ipynb:433`); this module's ``StepTimer``/``wall_clock``
reproduced that idiom. Since the obs subsystem landed (ARCHITECTURE.md
"Runtime telemetry") the ONE timing idiom is :func:`graphdyn.obs.timed` —
an always-measuring span whose event also lands in the JSONL ledger when a
recorder is active — and graftlint GD011 keeps bare ``time.time()``/
``time.perf_counter()`` brackets out of the driver modules. ``StepTimer``
and ``wall_clock`` remain as **deprecated shims over that API** so old call
sites keep working and their measurements now reach the ledger too.
``device_trace`` is now the same kind of shim over
:func:`graphdyn.obs.trace.profiling` — the aligned capture additionally
names the device timeline with the ledger's span paths, and graftlint
GD012 keeps bare ``jax.profiler`` calls out of everything but the obs
layer."""

from __future__ import annotations

import contextlib
import warnings
from dataclasses import dataclass, field

from graphdyn import obs


def _deprecated(name: str, replacement: str) -> None:
    warnings.warn(
        f"graphdyn.utils.profiling.{name} is deprecated — use {replacement} "
        f"(the one timing idiom; ARCHITECTURE.md 'Runtime telemetry')",
        DeprecationWarning, stacklevel=3,
    )


@dataclass
class StepTimer:
    """Deprecated shim: accumulates wall time and work counts via
    :func:`graphdyn.obs.timed` spans (``profiling.step_timer`` events when
    recording); reports updates/sec. New code should hold an
    ``obs.timed(...)`` span and compute its own rate."""

    seconds: float = 0.0
    updates: int = 0
    _warned: bool = field(default=False, repr=False)

    @contextlib.contextmanager
    def measure(self, n_updates: int):
        if not self._warned:
            _deprecated("StepTimer", "graphdyn.obs.timed")
            self._warned = True
        with obs.timed("profiling.step_timer", n_updates=n_updates) as sw:
            yield
        self.seconds += sw.wall_s
        self.updates += n_updates

    @property
    def updates_per_sec(self) -> float:
        return self.updates / self.seconds if self.seconds else 0.0


@contextlib.contextmanager
def device_trace(logdir: str):
    """Deprecated shim over :func:`graphdyn.obs.trace.profiling`:
    ``with device_trace('/tmp/trace'):`` still captures a jax.profiler
    trace of the block (TensorBoard profile tab / Perfetto), and now any
    obs span inside the block also opens a ledger-named TraceAnnotation —
    the aligned-capture contract new code gets from
    ``obs.trace.profiling`` / the CLI ``--profile`` flag directly."""
    _deprecated("device_trace", "graphdyn.obs.trace.profiling")
    from graphdyn.obs import trace

    with trace.profiling(logdir):
        yield


@contextlib.contextmanager
def wall_clock():
    """Deprecated shim over :func:`graphdyn.obs.timed` (reference-style
    bracket, `HPR:257,364`): yields a dict filled with ``seconds`` on exit.
    The span event (``profiling.wall_clock``) reaches the ledger when a
    recorder is active."""
    _deprecated("wall_clock", "graphdyn.obs.timed")
    out = {}
    sw = obs.timed("profiling.wall_clock").start()
    try:
        yield out
    finally:
        out["seconds"] = sw.stop().wall_s
