"""Tracing/profiling hooks (SURVEY.md §5.1).

The reference's observability is a wall-clock bracket (`HPR:257,364`) and
per-λ prints (`ipynb:433`). Here: a timing context that reports the headline
spin-updates/sec metric, and a thin wrapper over ``jax.profiler`` traces for
inspecting XLA/TPU execution in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field


@dataclass
class StepTimer:
    """Accumulates wall time and work counts; reports updates/sec."""

    seconds: float = 0.0
    updates: int = 0
    _t0: float = field(default=0.0, repr=False)

    @contextlib.contextmanager
    def measure(self, n_updates: int):
        t0 = time.perf_counter()
        yield
        self.seconds += time.perf_counter() - t0
        self.updates += n_updates

    @property
    def updates_per_sec(self) -> float:
        return self.updates / self.seconds if self.seconds else 0.0


@contextlib.contextmanager
def device_trace(logdir: str):
    """``with device_trace('/tmp/trace'):`` → jax.profiler trace of the block
    (view in TensorBoard's profile tab or Perfetto)."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def wall_clock():
    """Reference-style bracket (`HPR:257,364`): yields a dict filled with
    ``seconds`` on exit."""
    out = {}
    t0 = time.time()
    try:
        yield out
    finally:
        out["seconds"] = time.time() - t0
