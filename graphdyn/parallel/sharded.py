"""Sharded execution: replica-parallel + node-parallel dynamics over a Mesh.

The reference's "replica axis" is a host for-loop (`SA_RRG.py:58`,
`HPR_pytorch_RRG.py:259`); its graphs never leave one device. Here the
ensemble axes (replicas × temperatures) shard over the mesh's ``'replica'``
axis (embarrassingly parallel, psum/pmean for ensemble observables), and for
giant single graphs (N=10⁶, BASELINE config 5) the **node axis** shards too:
each device owns a contiguous node block plus that block's neighbor-table
rows; one ``all_gather`` of the int8 spin vector (1 MB at N=10⁶ — cheap on
ICI) per synchronous step replaces any halo bookkeeping.

All collectives are XLA (`all_gather`/`pmean` over named mesh axes inside
``shard_map``), so the same code runs on a real TPU pod slice or a CPU
simulated mesh.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from graphdyn.analysis.contracts import contract
from graphdyn.ops.dynamics import rule_coefficients
from graphdyn.parallel.mesh import shard_map


def pad_nodes(graph, n_shards: int):
    """Pad the node axis to a multiple of ``n_shards``.

    Returns (nbr_padded, n_padded). Padding rows are all-ghost (degree 0), so
    padded nodes are isolated spins that never change under tie→stay rules and
    never influence real nodes (no edges point at them).
    """
    n, dmax = graph.n, graph.dmax
    n_pad = (-n) % n_shards
    nbr = graph.nbr.astype(np.int32)
    if n_pad:
        ghost_rows = np.full((n_pad, dmax), n, dtype=np.int32)
        nbr = np.concatenate([nbr, ghost_rows], axis=0)
        # remap the ghost slot: `Graph.nbr` pads ragged rows with index n,
        # but the zero slot of the gathered spin vector now sits at index
        # n + n_pad (appended after the pad columns) — without the remap,
        # ghost gathers would read pad-column spins instead of 0
        nbr = np.where(nbr == n, n + n_pad, nbr)
    return nbr, n + n_pad


def _real_mask(node_axis: str, n_block: int, n_real: int):
    """bool[n_block]: which rows of this shard's node block are real nodes
    (contiguous blocks ⇒ global index = shard_idx·n_block + row)."""
    node_idx = lax.axis_index(node_axis)
    gidx = node_idx * n_block + jnp.arange(n_block, dtype=jnp.int32)
    return gidx < n_real


def _local_step(nbr_local, s_full, s_local, real_mask, R_coef, C_coef):
    """One synchronous update of a local node block given the fully gathered
    spin vector. Padded rows are frozen (they have no edges, but under
    tie→change they would otherwise oscillate — the mask keeps the pad
    invariant for every rule). ``s_full``: int8[R, n_pad]; ``nbr_local``:
    rows for this block with *global* neighbor indices; the ghost slot is
    appended here."""
    Rb = s_full.shape[0]
    s_ext = jnp.concatenate(
        [s_full.astype(jnp.int32), jnp.zeros((Rb, 1), jnp.int32)], axis=1
    )
    g = jnp.take(s_ext, nbr_local.reshape(-1), axis=1).reshape(
        Rb, nbr_local.shape[0], nbr_local.shape[1]
    )
    sums = g.sum(axis=2)
    out = (R_coef * jnp.sign(2 * sums + C_coef * s_local.astype(jnp.int32))).astype(
        jnp.int8
    )
    return jnp.where(real_mask[None, :], out, s_local)


def _masked_block_sum(s_local, real_mask):
    """Pad-free Σ over this shard's block (padded rows excluded)."""
    return jnp.where(real_mask[None, :], s_local.astype(jnp.int32), 0).sum(axis=1)


def make_sharded_rollout(
    mesh: Mesh,
    n_real: int,
    steps: int,
    rule: str = "majority",
    tie: str = "stay",
    replica_axis: str = "replica",
    node_axis: str = "node",
):
    """Build a jitted rollout ``f(nbr, s) -> s_end`` with replicas sharded over
    ``replica_axis`` and nodes over ``node_axis``.

    ``s``: int8[R, n_pad] with R divisible by the replica-axis size and n_pad
    by the node-axis size; rows with global index ≥ ``n_real`` are padding and
    stay frozen. The ghost slot for the spin gather is appended *after* the
    all_gather inside each shard.
    """
    R_coef, C_coef = rule_coefficients(rule, tie)

    @contract(nbr_local="int32[nb,d]", s_local="int8[r,nb]",
              ret="int8[r,nb]")
    def rollout(nbr_local, s_local):
        # nbr_local: int32[n_pad/P, dmax]; s_local: int8[R/Q, n_pad/P]
        mask = _real_mask(node_axis, s_local.shape[1], n_real)

        def body(_, s_loc):
            # graftlint: disable-next-line=GD013  legacy gather mode: the parity baseline the halo path (parallel/halo.py) is tested against, and the small-graph fallback where one ICI gather beats halo bookkeeping
            s_full = lax.all_gather(s_loc, node_axis, axis=1, tiled=True)
            return _local_step(nbr_local, s_full, s_loc, mask, R_coef, C_coef)

        return lax.fori_loop(0, steps, body, s_local)

    f = shard_map(
        rollout,
        mesh=mesh,
        in_specs=(P(node_axis, None), P(replica_axis, node_axis)),
        out_specs=P(replica_axis, node_axis),
        check_vma=False,
    )
    return jax.jit(f)


def lower_sharded_rollout(
    mesh: Mesh,
    graph,
    R: int,
    *,
    steps: int,
    rule: str = "majority",
    tie: str = "stay",
    replica_axis: str = "replica",
    node_axis: str = "node",
):
    """Lower (without executing) the sharded rollout at this graph's padded
    shapes with canonically placed arguments — the program
    :mod:`graphdyn.analysis.graftcheck` fingerprints for the mesh path.
    Kept next to :func:`make_sharded_rollout` so a rollout refactor updates
    the fingerprinted surface in the same place. The spin values are
    placeholders (a lowering sees only shapes/dtypes/shardings). Returns a
    ``jax.stages.Lowered``."""
    nbr_pad, n_pad = pad_nodes(graph, int(mesh.shape[node_axis]))
    nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P(node_axis, None))
    s_d = place_sharded(
        mesh, jnp.ones((R, n_pad), jnp.int8), P(replica_axis, node_axis)
    )
    f = make_sharded_rollout(
        mesh, n_real=graph.n, steps=steps, rule=rule, tie=tie,
        replica_axis=replica_axis, node_axis=node_axis,
    )
    return f.lower(nbr_d, s_d)


def make_sharded_sa_step(
    mesh: Mesh,
    rollout_steps: int,
    n_real: int,
    rule: str = "majority",
    tie: str = "stay",
    replica_axis: str = "replica",
    node_axis: str = "node",
):
    """Build the full SA training step over the mesh: per-replica proposal,
    candidate rollout, Metropolis acceptance, annealing, plus a pmean'd
    ensemble consensus fraction — BASELINE config 5's multi-chip psum path.

    Returns jitted ``step(nbr, s, sum_end, a, b, key, t) ->
    (s', sum_end', a', b', key', t', consensus_frac)`` with ``s`` sharded
    ``P(replica, node)`` and scalars-per-replica sharded ``P(replica)``.
    """
    R_coef, C_coef = rule_coefficients(rule, tie)

    def step(nbr_local, s_local, sum_end, a, b, key, t,
             par_a, par_b, a_cap, b_cap):
        from graphdyn.models.sa import draw_sa_proposal, metropolis_anneal_update

        Rl, n_block = s_local.shape
        node_idx = lax.axis_index(node_axis)
        mask = _real_mask(node_axis, n_block, n_real)

        # one proposal per replica (global node index), same on every node
        # shard — the shared draw used by both full solvers
        i, u = draw_sa_proposal(
            key, t, None, None, injected=False, stream_len=1,
            n=n_real, dt=a.dtype,
        )

        # flip spin i on the owning shard
        local_i = i - node_idx * n_block
        owned = (local_i >= 0) & (local_i < n_block)
        li = jnp.clip(local_i, 0, n_block - 1)
        ridx = jnp.arange(Rl, dtype=jnp.int32)
        s_i_local = s_local[ridx, li].astype(jnp.int32)
        flipped = s_local.at[ridx, li].set((-s_i_local).astype(jnp.int8))
        s_flip = jnp.where(owned[:, None], flipped, s_local)
        # s_i of the proposed spin, broadcast to all shards
        s_i = lax.psum(jnp.where(owned, s_i_local, 0), node_axis)

        # candidate rollout (the single rollout per MCMC step; SURVEY §3.1)
        def body(_, s_loc):
            # graftlint: disable-next-line=GD013  legacy gather mode (see make_sharded_rollout): parity baseline + small-graph fallback
            s_full = lax.all_gather(s_loc, node_axis, axis=1, tiled=True)
            return _local_step(nbr_local, s_full, s_loc, mask, R_coef, C_coef)

        s_end_flip = lax.fori_loop(0, rollout_steps, body, s_flip)
        # pad-free sum: same basis as the caller-seeded sum_end and the
        # `>= n_real` consensus test below
        sum_end_flip = lax.psum(_masked_block_sum(s_end_flip, mask), node_axis)

        # every replica is live in the single-step primitive: no freeze mask,
        # no timeout (the full solver `sa_sharded` owns those semantics)
        always = jnp.ones(a.shape, bool)
        do, sum_end_new, a_new, b_new, _, _, _ = metropolis_anneal_update(
            always, a, b, t, jnp.zeros(a.shape, a.dtype),
            sum_end, sum_end_flip, s_i, u,
            par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
            max_steps=2**31 - 2, n=n_real,
        )
        s_new = jnp.where(do[:, None], s_flip, s_local)

        # ensemble observable over the whole mesh (ICI collective)
        local_consensus = jnp.mean(
            (sum_end_new >= n_real).astype(jnp.float32)
        )
        consensus = lax.pmean(lax.pmean(local_consensus, replica_axis), node_axis)

        return s_new, sum_end_new, a_new, b_new, key, t + 1, consensus

    f = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P(node_axis, None),            # nbr
            P(replica_axis, node_axis),    # s
            P(replica_axis),               # sum_end
            P(replica_axis),               # a
            P(replica_axis),               # b
            P(replica_axis),               # key
            P(replica_axis),               # t
            P(), P(), P(), P(),            # scalars
        ),
        out_specs=(
            P(replica_axis, node_axis),
            P(replica_axis),
            P(replica_axis),
            P(replica_axis),
            P(replica_axis),
            P(replica_axis),
            P(),
        ),
        check_vma=False,
    )
    return jax.jit(f)


def place_sharded(mesh: Mesh, x, spec: P):
    return jax.device_put(x, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# Edge-sharded BDCM sweep (giant-graph message passing over the mesh)
# ---------------------------------------------------------------------------


def _sharded_sweep_body(
    data,
    mesh: Mesh,
    *,
    damp: float,
    eps_clamp: float = 0.0,
    mask_invalid_src: bool = True,
    edge_axis: str = "edge",
):
    """Shared core of :func:`make_sharded_sweep` and
    :func:`make_sharded_fixed_point`: builds the padded per-class tables and
    returns ``(sweep_body(chi, lmbd) -> chi', replicated_sharding)`` for the
    callers to jit (standalone or inside a while_loop)."""
    import jax.numpy as jnp

    from graphdyn.ops.bdcm import class_update

    T, K = data.T, data.K
    valid = jnp.asarray(data.valid)
    x0 = jnp.asarray(data.x0, data.dtype)
    n_shards = int(mesh.shape[edge_axis])
    classes = []
    for cls in data.edge_classes:
        Ed = cls.idx.shape[0]
        pad = (-Ed) % n_shards
        # pad class members by repeating the first edge; padded lanes compute
        # a duplicate update that lands on the same index via the scatter —
        # `.at[idx].set` with duplicate indices writes the same value, so the
        # result is unchanged
        idx = np.concatenate([cls.idx, np.repeat(cls.idx[:1], pad)])
        in_edges = np.concatenate(
            [cls.in_edges, np.repeat(cls.in_edges[:1], pad, axis=0)]
        )
        classes.append(
            (
                cls.d,
                jnp.asarray(idx),
                jnp.asarray(in_edges),
                jnp.asarray(cls.A, data.dtype),
            )
        )

    shard = NamedSharding(mesh, P(edge_axis))
    replicated = NamedSharding(mesh, P())

    def sweep_body(chi, lmbd):
        tilt = jnp.exp(-lmbd * x0)
        for d, idx, in_edges, A in classes:
            chi_in = jax.lax.with_sharding_constraint(
                chi[in_edges], NamedSharding(mesh, P(edge_axis, None, None, None))
            )
            if mask_invalid_src:
                chi_in = chi_in * valid[None, None, :, None]
            upd = class_update(
                chi_in, A, tilt, chi[idx], d=d, T=T, K=K,
                damp=damp, eps_clamp=eps_clamp,
            )
            chi = chi.at[idx].set(upd)
        return chi

    return sweep_body, replicated


def make_sharded_sweep(
    data,
    mesh: Mesh,
    *,
    damp: float,
    eps_clamp: float = 0.0,
    mask_invalid_src: bool = True,
    edge_axis: str = "edge",
):
    """Edge-parallel BDCM sweep ``(chi, lmbd) -> chi'`` over ``mesh``.

    The reference's BP sweeps are single-device (`HPR_pytorch_RRG.py:348`,
    `ER_BDCM_entropy.ipynb:424`). For giant single graphs the per-class DP
    tensors (``[Ed, K, (d+1)^T]`` — the memory hot spot, SURVEY.md §7 "hard
    parts") dominate; here they shard over the mesh's ``edge_axis`` via GSPMD
    sharding constraints: the message array stays replicated (it is small —
    the DP state is what explodes), each device computes the DP + contraction
    for its slice of every degree class, and XLA inserts the (all_gather /
    scatter) collectives over ICI. Numerically identical to
    :func:`graphdyn.ops.bdcm.make_sweep` — covered by the sharded-vs-unsharded
    equivalence test on the simulated CPU mesh (SURVEY.md §4.4).
    """
    sweep_body, replicated = _sharded_sweep_body(
        data, mesh, damp=damp, eps_clamp=eps_clamp,
        mask_invalid_src=mask_invalid_src, edge_axis=edge_axis,
    )
    return jax.jit(sweep_body, out_shardings=replicated)


def make_sharded_fixed_point(
    data,
    mesh: Mesh,
    *,
    damp: float,
    eps: float,
    max_sweeps: int,
    eps_clamp: float = 0.0,
    edge_axis: str = "edge",
):
    """Edge-sharded BP fixed point ``(chi, lmbd) -> (chi*, sweeps, delta)``:
    the entropy solvers' hot loop (`ipynb:420-432` — one fixed point per λ,
    ~10²–10³ sweeps each) with every sweep's per-class DP sharded over
    ``edge_axis`` exactly as :func:`make_sharded_sweep` (same padded class
    tables, same arithmetic per edge — results match the unsharded
    :func:`graphdyn.models.entropy.make_fixed_point` to roundoff; tested).
    The convergence test ``max|Δchi|`` is a global reduction XLA lowers to
    one small all-reduce per sweep."""
    sweep_body, replicated = _sharded_sweep_body(
        data, mesh, damp=damp, eps_clamp=eps_clamp,
        mask_invalid_src=True, edge_axis=edge_axis,
    )

    @partial(jax.jit, out_shardings=(replicated, replicated, replicated))
    # graftlint: disable-next-line=GD006  parity tests replay the same chi
    def fixed_point(chi, lmbd):
        def cond(st):
            _, delta, t = st
            return (delta > eps) & (t < max_sweeps)

        def body(st):
            chi, _, t = st
            new = sweep_body(chi, lmbd)
            return new, jnp.abs(new - chi).max(), t + 1

        chi_out, delta, t = lax.while_loop(
            cond, body, (chi, jnp.asarray(jnp.inf, chi.dtype), 0)
        )
        return chi_out, t, delta

    return fixed_point
