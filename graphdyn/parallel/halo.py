"""Halo-exchange node-axis sharding of the packed dynamics kernel.

Every axis the framework sharded before this module — replicas, groups,
packed words, grid cells — is an *ensemble* axis: it grows with how many
chains you run, not with how big a graph you can hold. The node axis is the
one that grows with graph size, and the legacy node sharding
(:mod:`graphdyn.parallel.sharded`) pays for it with a full-state
``all_gather`` per synchronous step: every device receives every spin word
whether or not it reads them. This module ships only what the partition
says a shard actually reads — the **boundary** nodes' packed words — the
irregular-graph analogue of the boundary tiles in the TPU-cluster Ising
design of PAPERS.md arXiv:1903.11714 (its checkerboard halo generalizes to
ghost ROWS once the partition is irregular, machinery ``stack_bdcm``'s
ghost-row layout already prototypes; the sparse Ising machines of
arXiv:2110.02481 run exactly such irregular master graphs natively).

Layout (host-built once by :func:`build_halo_tables` from a
:class:`graphdyn.graphs.Partition`): per shard ``p`` the packed state is
``uint32[n_rows, W]`` with

- rows ``[0, n_local_max)`` — the nodes ``p`` owns, **interior first**
  (no cut edge) then boundary, padded with inert rows (degree 0, frozen);
- rows ``[n_local_max, n_local_max + n_ghost_max)`` — **ghost rows**: the
  remote boundary nodes ``p`` gathers from, refreshed each step by the
  exchange; padded;
- one **trash** row (the scatter target of pad recv lanes) and one
  always-**zero** row (the gather target of ghost-padded neighbor slots —
  the same zero-contribution trick as the unsharded kernel's ghost word).

The synchronous step updates every owned row from purely local gathers
(the same carry-save-adder / bitwise-comparator arithmetic as
:func:`graphdyn.ops.packed.packed_rollout` — elementwise per node, so the
sharded program is **bit-exact** to the unsharded one by construction),
then exchanges only the boundary words over a **static shard-neighbor
schedule**: one ``lax.ppermute`` per distinct shard offset ``δ``, every
shard sending its ``[m_δ, W]`` boundary slab to shard ``(p+δ) mod P``.
Send and receive tables list the same nodes in the same (global-id) order,
so both sides derive the transfer layout independently; the carry is
donated and no full-state ``all_gather`` ever exists (graftlint GD013
polices exactly that regression class).

Per-step USEFUL traffic is ``4·W·Σ_p n_ghost(p)`` bytes — the
partitioner's edge cut, priced in words; the wire actually carries the
padded uniform slabs, ``4·W·P·Σ_δ m_δ`` (``HaloTables.n_slab_words`` — the
``parallel.halo.bytes_per_step`` gauge reports this honest number, and the
slab/useful ratio measures partition imbalance). The ``halo_shard``
residency model in :mod:`graphdyn.obs.memband` charges the ghost term.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from graphdyn.graphs import Graph, Partition, partition_ghosts
from graphdyn.ops.bucketed import UNROLL_MAX as _UNROLL_MAX
from graphdyn.ops.dynamics import Rule, TieBreak
from graphdyn.parallel.mesh import device_pool, make_mesh, shard_map

_FULL = np.uint32(0xFFFFFFFF)


class HaloTables(NamedTuple):
    """Host tables of the per-shard halo layout (see module docstring).

    ``schedule`` is the static exchange plan: one ``(delta, send_idx,
    recv_idx)`` triple per distinct shard offset, ``send_idx/recv_idx``
    int32[P, m_delta] local row indices (pad send lanes gather the zero
    row; pad recv lanes scatter into the trash row). ``n_halo_words``
    counts the USEFUL rows exchanged per step (= Σ ghosts — the edge-cut
    floor); ``n_slab_words`` counts what the collectives actually ship:
    every shard sends the PADDED ``m_delta`` slab at every offset
    (``P · Σ_δ m_δ`` — a uniform collective cannot send ragged rows), so
    the honest wire bill is ``4 · W · n_slab_words`` and the pad overhead
    ``n_slab_words / n_halo_words`` is a partition-balance figure of
    merit (measured 1.26× at P=4, 1.56× at P=8 on the d=3 RRG smoke).
    """

    n: int                    # global node count
    n_local_max: int          # owned rows per shard (padded)
    n_ghost_max: int          # ghost rows per shard (padded)
    dmax: int
    counts: np.ndarray        # int64[P] real owned nodes per shard
    ghost_counts: np.ndarray  # int64[P] real ghost rows per shard
    nbr_loc: np.ndarray       # int32[P, n_local_max, dmax] local row indices
    deg_loc: np.ndarray       # int32[P, n_local_max]
    real: np.ndarray          # bool[P, n_local_max] owned-and-real mask
    owned_global: np.ndarray  # int64[P, n_local_max] global id per row (-1 pad)
    ghost_global: np.ndarray  # int64[P, n_ghost_max] global id per ghost (-1)
    loc_of: np.ndarray        # int32[n]: owner shard * n_local_max + row
    schedule: tuple           # ((delta, send_idx[P, m], recv_idx[P, m]), ...)
    n_halo_words: int         # useful boundary rows per step (Σ ghosts)
    n_slab_words: int         # shipped rows per step (P · Σ_δ m_δ, pads incl.)
    # hub-split vertex cut (None/empty on hubless partitions — the layout
    # and every fingerprinted program are then byte-identical to before):
    hub_global: np.ndarray | None = None  # int64[H] global hub ids
    hub_deg: np.ndarray | None = None     # int32[H] ORIGINAL hub degrees
    hub_nbr_loc: np.ndarray | None = None  # int32[P, H, hd_max] local rows
    hub_ring_words: int = 0   # rows shipped per step by the hub ring

    @property
    def P(self) -> int:
        return self.nbr_loc.shape[0]

    @property
    def n_hubs(self) -> int:
        return 0 if self.hub_global is None else int(self.hub_global.size)

    @property
    def n_rows(self) -> int:
        # owned + ghosts + replicated hubs + trash + zero
        return self.n_local_max + self.n_ghost_max + self.n_hubs + 2

    @property
    def hub_row0(self) -> int:
        """First replicated-hub row (hubs occupy ``[hub_row0, trash_row)``)."""
        return self.n_local_max + self.n_ghost_max

    @property
    def trash_row(self) -> int:
        return self.n_local_max + self.n_ghost_max + self.n_hubs

    @property
    def zero_row(self) -> int:
        return self.n_local_max + self.n_ghost_max + self.n_hubs + 1

    def halo_bytes_per_step(self, W: int) -> int:
        """ACTUAL exchange traffic of one synchronous step at ``W`` spin
        words per node — the padded slabs the collectives ship
        (``4·W·n_slab_words``) plus the hub partial-popcount ring
        (``4·W·hub_ring_words``), not the useful-words floor
        (``4·W·n_halo_words``). The number the weak-scaling bench row and
        the obs gauge report; the slab/useful ratio is pad overhead from
        partition imbalance."""
        return 4 * W * (self.n_slab_words + self.hub_ring_words)


def build_halo_tables(graph: Graph, partition: Partition) -> HaloTables:
    """Build the per-shard layout + static exchange schedule for
    ``partition`` (pure host NumPy; one-time cost per graph).

    Hub-split partitions (``partition.hubs`` non-empty — see
    :func:`graphdyn.graphs.partition_graph` ``hub_threshold``) get the
    vertex-cut layout: every shard carries a replicated row per hub, the
    owned-row ``dmax`` shrinks to the max NON-hub degree (the whole point
    — one degree-1e5 hub no longer pads every owned row), and
    ``hub_nbr_loc[p, i]`` lists hub ``i``'s neighbors OWNED BY shard p
    (hub–hub neighbors charged to shard 0 so each edge counts once): the
    per-shard partial popcounts those rows produce are ring-combined each
    step (see :func:`make_halo_rollout`)."""
    n = graph.n
    Pn = partition.P
    counts = partition.counts
    hubs = (
        partition.hubs if partition.hubs is not None
        else np.empty(0, np.int64)
    ).astype(np.int64)
    H = int(hubs.size)
    # hub-split shrinks the owned-row gather width to the non-hub max
    # degree; hubless tables keep graph.dmax so the layout (and the
    # committed halo_rollout fingerprint) is unchanged
    if H:
        hub_mask = np.zeros(n, bool)
        hub_mask[hubs] = True
        dmax = int(graph.deg[~hub_mask].max(initial=1))
    else:
        dmax = graph.dmax
    n_local_max = int(counts.max())
    ghosts = partition_ghosts(graph, partition)
    ghost_counts = np.array([g.size for g in ghosts], np.int64)
    n_ghost_max = int(ghost_counts.max(initial=0))
    n_rows = n_local_max + n_ghost_max + H + 2
    trash_row, zero_row = n_rows - 2, n_rows - 1
    hub_row0 = n_local_max + n_ghost_max

    nbr_loc = np.full((Pn, n_local_max, dmax), zero_row, np.int32)
    deg_loc = np.zeros((Pn, n_local_max), np.int32)
    real = np.zeros((Pn, n_local_max), bool)
    owned_global = np.full((Pn, n_local_max), -1, np.int64)
    ghost_global = np.full((Pn, n_ghost_max), -1, np.int64)
    row_of = np.empty(n, np.int64)          # local row within the owner shard
    ghost_pos = []                          # per shard: global -> ghost slot
    for p in range(Pn):
        seg = partition.order[partition.offsets[p]:partition.offsets[p + 1]]
        row_of[seg] = np.arange(seg.size)
        gl = ghosts[p]
        # global -> local row lut for this shard; the graph's own ghost
        # index n (ragged-degree padding) maps to the zero row, exactly the
        # unsharded kernel's zero-contribution slot
        lut = np.full(n + 1, zero_row, np.int64)
        lut[seg] = np.arange(seg.size)
        lut[gl] = n_local_max + np.arange(gl.size)
        if H:
            lut[hubs] = hub_row0 + np.arange(H)
        nbr_loc[p, :seg.size] = lut[graph.nbr[seg, :dmax].astype(np.int64)]
        deg_loc[p, :seg.size] = graph.deg[seg]
        real[p, :seg.size] = True
        owned_global[p, :seg.size] = seg
        ghost_global[p, :gl.size] = gl
        gpos = np.full(n, -1, np.int64)
        gpos[gl] = np.arange(gl.size)
        ghost_pos.append(gpos)
    if H:
        row_of[hubs] = 0
    loc_of = (
        partition.part.astype(np.int64) * n_local_max + row_of
    ).astype(np.int32)
    if H:
        loc_of[hubs] = -1        # hubs live on every shard, not one row

    # hub neighbor slices: shard p accumulates hub i's popcount over the
    # neighbors p OWNS; hub–hub neighbors ride on shard 0 only, so every
    # edge contributes to exactly one partial count and the ring-combined
    # total equals the unsharded popcount bit-for-bit
    hub_nbr_loc = None
    hub_ring_words = 0
    if H:
        slices: list[list[np.ndarray]] = [[] for _ in range(Pn)]
        hub_lut = np.full(n, -1, np.int64)
        hub_lut[hubs] = hub_row0 + np.arange(H)
        for i, h in enumerate(hubs):
            nbrs = graph.nbr[h, :graph.deg[h]].astype(np.int64)
            owners = partition.part[nbrs]
            for p in range(Pn):
                mine = nbrs[owners == p]
                rows = row_of[mine]
                if p == 0:
                    rows = np.concatenate(
                        [rows, hub_lut[nbrs[owners < 0]]]
                    )
                slices[p].append(rows)
        hd_max = max(
            (r.size for per_p in slices for r in per_p), default=1
        )
        hd_max = max(hd_max, 1)
        if hd_max > _UNROLL_MAX:
            # wide hubs take the segment-reshape popcount (see
            # make_halo_rollout), which needs UNROLL_MAX | hd_max; the pad
            # slots gather the zero row and contribute 0
            hd_max += -hd_max % _UNROLL_MAX
        hub_nbr_loc = np.full((Pn, H, hd_max), zero_row, np.int32)
        for p in range(Pn):
            for i, rows in enumerate(slices[p]):
                hub_nbr_loc[p, i, :rows.size] = rows
        n_planes_hub = max(int(graph.deg[hubs].max()).bit_length(), 1)
        hub_ring_words = Pn * (Pn - 1) * H * n_planes_hub

    # static exchange schedule, grouped by shard offset delta = (p - q) % P:
    # sender q ships the boundary nodes that shard p = (q + delta) % P
    # ghosts; both sides list them sorted by global id (partition_ghosts),
    # so send_idx[q] and recv_idx[p] describe the same slab independently
    by_delta: dict[int, dict[int, np.ndarray]] = {}
    for p in range(Pn):
        gl = ghosts[p]
        owners = partition.part[gl]
        for q in np.unique(owners):
            delta = int((p - q) % Pn)
            by_delta.setdefault(delta, {})[int(q)] = gl[owners == q]
    schedule = []
    for delta in sorted(by_delta):
        per_q = by_delta[delta]
        m = max(nodes.size for nodes in per_q.values())
        send_idx = np.full((Pn, m), zero_row, np.int32)
        recv_idx = np.full((Pn, m), trash_row, np.int32)
        for q, nodes in per_q.items():
            p = (q + delta) % Pn
            send_idx[q, :nodes.size] = row_of[nodes]
            recv_idx[p, :nodes.size] = n_local_max + ghost_pos[p][nodes]
        schedule.append((delta, send_idx, recv_idx))

    return HaloTables(
        n=n,
        n_local_max=n_local_max,
        n_ghost_max=n_ghost_max,
        dmax=dmax,
        counts=counts,
        ghost_counts=ghost_counts,
        nbr_loc=nbr_loc,
        deg_loc=deg_loc,
        real=real,
        owned_global=owned_global,
        ghost_global=ghost_global,
        loc_of=loc_of,
        schedule=tuple(schedule),
        n_halo_words=int(ghost_counts.sum()),
        n_slab_words=Pn * sum(s.shape[1] for (_, s, _) in schedule),
        hub_global=hubs if H else None,
        hub_deg=graph.deg[hubs].astype(np.int32) if H else None,
        hub_nbr_loc=hub_nbr_loc,
        hub_ring_words=hub_ring_words,
    )


def exchange_perms(tables: HaloTables) -> tuple:
    """The static ``ppermute`` permutation per schedule offset."""
    Pn = tables.P
    return tuple(
        tuple((q, (q + delta) % Pn) for q in range(Pn))
        for (delta, _, _) in tables.schedule
    )


# ---------------------------------------------------------------------------
# packed (uint32 word) halo rollout
# ---------------------------------------------------------------------------


def make_halo_rollout(
    mesh: Mesh,
    tables: HaloTables,
    *,
    steps: int,
    rule: str = "majority",
    tie: str = "stay",
    node_axis: str = "node",
):
    """Build the jitted halo rollout ``f(nbr_loc, deg_loc, real, sends,
    recvs, sp) -> sp'`` over ``mesh``'s ``node_axis`` (size = tables.P).

    ``sp``: uint32[P, n_rows, W] per-shard packed state (donated — the
    carry updates in place, group-to-group). The per-node update is the
    carry-save-adder / comparator program of
    :func:`graphdyn.ops.packed.packed_rollout` verbatim (shared helpers),
    so results are bit-exact to the unsharded kernel; the only
    collectives are the schedule's boundary ``ppermute`` slabs.
    """
    from graphdyn.ops.bucketed import (
        _pack_lanes,
        _wide_bucket_counts,
    )
    from graphdyn.ops.packed import (
        _compare_planes,
        _csa_add_one,
        _rule_tie_combine,
    )

    rule = Rule(rule)
    tie = TieBreak(tie)
    nm = tables.n_local_max
    dmax = tables.dmax
    n_planes = max(int(dmax).bit_length(), 1)
    perms = exchange_perms(tables)
    Pn = tables.P
    H = tables.n_hubs
    hub0 = tables.hub_row0
    if H:
        # replicated-hub constants (host data -> jaxpr constants): the
        # comparator thresholds come from the ORIGINAL hub degrees, so a
        # hub's update is the unsharded rule applied to the ring-combined
        # total popcount
        hd_max = tables.hub_nbr_loc.shape[2]
        hd = tables.hub_deg.astype(np.int64)
        n_planes_hub = max(int(hd.max()).bit_length(), 1)
        thr_h = (hd // 2).astype(np.uint32)
        even_h = np.where(hd % 2 == 0, _FULL, np.uint32(0))[:, None]
        thr_bits_h = [
            np.where((thr_h >> k) & 1 == 1, _FULL, np.uint32(0))[:, None]
            for k in range(n_planes_hub)
        ]
        ring_perm = tuple((q, (q + 1) % Pn) for q in range(Pn))

    def rollout(nbr_l, deg_l, real_l, send_l, recv_l, sp_l, *hub_l):
        nbr = nbr_l[0]
        deg = deg_l[0]
        real = real_l[0]
        sends = [s[0] for s in send_l]
        recvs = [r[0] for r in recv_l]
        sp0 = sp_l[0]
        hub_nbr = hub_l[0][0][0] if H else None

        thr = (deg // 2).astype(jnp.uint32)
        even_mask = jnp.where(deg % 2 == 0, _FULL, jnp.uint32(0))[:, None]
        thr_bits = [
            jnp.where((thr >> k) & 1 == 1, _FULL, jnp.uint32(0))[:, None]
            for k in range(n_planes)
        ]

        def body(_, sp):
            planes = [jnp.zeros_like(sp[:nm]) for _ in range(n_planes)]
            for j in range(dmax):
                _csa_add_one(planes, jnp.take(sp, nbr[:, j], axis=0))
            gt, eq = _compare_planes(planes, thr_bits)
            out = _rule_tie_combine(gt, eq & even_mask, sp[:nm], rule, tie)
            # pad rows stay inert under every rule (cf. the unsharded
            # kernel's forced ghost word)
            out = jnp.where(real[:, None], out, sp[:nm])
            if H:
                # partial popcount of every hub over the neighbors THIS
                # shard owns, from the same pre-update state as `out`
                if hd_max <= _UNROLL_MAX:
                    # narrow slices: unrolled CSA, one gather+add per slot
                    hpl = [
                        jnp.zeros((H, sp.shape[1]), sp.dtype)
                        for _ in range(n_planes_hub)
                    ]
                    for j in range(hd_max):
                        _csa_add_one(
                            hpl, jnp.take(sp, hub_nbr[:, j], axis=0)
                        )
                else:
                    # wide slices: the ops/bucketed segment scheme —
                    # UNROLL_MAX-slot segments CSA'd then dense-summed as
                    # integer counts (exact), so program size stays
                    # O(log d_hub) instead of O(d_hub/P) unrolled adds;
                    # repack the counts into the ring's bit-planes
                    cnt = _wide_bucket_counts(sp, hub_nbr)
                    hpl = [
                        _pack_lanes((cnt >> p) & 1)
                        for p in range(n_planes_hub)
                    ]
                prev_h = lax.dynamic_slice_in_dim(sp, hub0, H, axis=0)
            sp = lax.dynamic_update_slice(sp, out, (0, 0))
            if H:
                # ring-allreduce the partial counts: (P-1) ppermute
                # rounds; bit-plane ripple-carry addition is exact, and
                # n_planes_hub bounds the total (= the hub degree), so no
                # carry ever leaves the top plane. Every shard computes
                # the identical total -> hub rows stay replicated.
                acc, buf = hpl, hpl
                for _ in range(Pn - 1):
                    buf = [
                        lax.ppermute(pl, node_axis, ring_perm) for pl in buf
                    ]
                    carry = jnp.zeros_like(acc[0])
                    nxt = []
                    for a, b in zip(acc, buf):
                        nxt.append(a ^ b ^ carry)
                        carry = (a & b) | (carry & (a ^ b))
                    acc = nxt
                gt_h, eq_h = _compare_planes(acc, thr_bits_h)
                out_h = _rule_tie_combine(
                    gt_h, eq_h & even_h, prev_h, rule, tie
                )
                sp = lax.dynamic_update_slice(sp, out_h, (hub0, 0))
            # halo exchange: boundary words only, one slab per offset
            for perm, s_idx, r_idx in zip(perms, sends, recvs):
                buf = jnp.take(sp, s_idx, axis=0)
                buf = lax.ppermute(buf, node_axis, perm)
                sp = sp.at[r_idx].set(buf)
            return sp

        return lax.fori_loop(0, steps, body, sp0)[None]

    k = len(tables.schedule)
    spec2 = P(node_axis, None)
    spec3 = P(node_axis, None, None)
    in_specs = (spec3, spec2, spec2, [spec2] * k, [spec2] * k, spec3)
    if H:
        # hub tables ride AFTER sp so the donated-carry position (and the
        # hubless flat jaxpr graftcheck fingerprints) never moves
        in_specs = in_specs + ([spec3],)
    f = shard_map(
        rollout,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec3,
        check_vma=False,
    )
    return jax.jit(f, donate_argnums=(5,))


def scatter_state(tables: HaloTables, sp: np.ndarray) -> np.ndarray:
    """Global packed state ``uint32[n, W]`` -> per-shard halo layout
    ``uint32[P, n_rows, W]`` (owned rows + CONSISTENT ghost rows, pads and
    the trash/zero rows zeroed)."""
    sp = np.asarray(sp)
    W = sp.shape[1]
    out = np.zeros((tables.P, tables.n_rows, W), np.uint32)
    nm = tables.n_local_max
    h0 = tables.hub_row0
    for p in range(tables.P):
        cnt = int(tables.counts[p])
        out[p, :cnt] = sp[tables.owned_global[p, :cnt]]
        gcnt = int(tables.ghost_counts[p])
        if gcnt:
            out[p, nm:nm + gcnt] = sp[tables.ghost_global[p, :gcnt]]
        if tables.n_hubs:
            out[p, h0:h0 + tables.n_hubs] = sp[tables.hub_global]
    return out


def gather_state(tables: HaloTables, sp_loc: np.ndarray) -> np.ndarray:
    """Per-shard halo layout back to the global ``uint32[n, W]`` order."""
    sp_loc = np.asarray(sp_loc)
    out = np.empty((tables.n, sp_loc.shape[2]), np.uint32)
    for p in range(tables.P):
        cnt = int(tables.counts[p])
        out[tables.owned_global[p, :cnt]] = sp_loc[p, :cnt]
    if tables.n_hubs:
        # hub rows are replicated and updated identically on every shard
        h0 = tables.hub_row0
        out[tables.hub_global] = sp_loc[0, h0:h0 + tables.n_hubs]
    return out


class HaloProgram:
    """A compiled halo rollout bound to one (graph, partition, mesh): the
    tables are placed once, repeated calls reuse the jitted program (the
    bench chaining pattern). ``mesh=None`` builds a 1-D ``node`` mesh over
    ``partition.P`` devices (default platform, CPU host-platform
    fallback)."""

    def __init__(
        self,
        graph: Graph,
        partition: Partition,
        *,
        steps: int,
        rule: str = "majority",
        tie: str = "stay",
        mesh: Mesh | None = None,
        node_axis: str = "node",
        tables: HaloTables | None = None,
    ):
        self.tables = tables if tables is not None else build_halo_tables(
            graph, partition
        )
        if mesh is None:
            mesh = make_mesh(
                (self.tables.P,), (node_axis,),
                devices=device_pool(self.tables.P),
            )
        if int(mesh.shape[node_axis]) != self.tables.P:
            raise ValueError(
                f"mesh {node_axis!r} axis size {mesh.shape[node_axis]} != "
                f"partition P {self.tables.P}"
            )
        self.mesh = mesh
        self.node_axis = node_axis
        self.steps = steps
        self._fn = make_halo_rollout(
            mesh, self.tables, steps=steps, rule=rule, tie=tie,
            node_axis=node_axis,
        )
        t = self.tables
        spec2 = NamedSharding(mesh, P(node_axis, None))
        spec3 = NamedSharding(mesh, P(node_axis, None, None))
        self._spec3 = spec3
        self._consts = (
            jax.device_put(jnp.asarray(t.nbr_loc), spec3),
            jax.device_put(jnp.asarray(t.deg_loc), spec2),
            jax.device_put(jnp.asarray(t.real), spec2),
            [jax.device_put(jnp.asarray(s), spec2) for (_, s, _) in t.schedule],
            [jax.device_put(jnp.asarray(r), spec2) for (_, _, r) in t.schedule],
        )
        # hub tables ride after sp (see make_halo_rollout); empty for
        # hubless partitions so the call signature is unchanged
        self._hub_consts = (
            ([jax.device_put(jnp.asarray(t.hub_nbr_loc), spec3)],)
            if t.n_hubs else ()
        )

    def place(self, sp) -> jax.Array:
        """Scatter + place a global ``uint32[n, W]`` state onto the mesh."""
        return jax.device_put(
            jnp.asarray(scatter_state(self.tables, sp)), self._spec3
        )

    def advance(self, sp_loc: jax.Array) -> jax.Array:
        """Run ``steps`` synchronous updates on a placed state (donated —
        rebind the result). Emits the per-step halo-traffic gauge while an
        obs recorder is active."""
        from graphdyn import obs

        if obs.enabled():
            W = int(sp_loc.shape[2])
            obs.gauge(
                "parallel.halo.bytes_per_step",
                self.tables.halo_bytes_per_step(W),
                P=self.tables.P, W=W, steps=self.steps,
            )
        return self._fn(*self._consts, sp_loc, *self._hub_consts)

    def fetch(self, sp_loc: jax.Array) -> np.ndarray:
        """Placed state back to the global ``uint32[n, W]`` order."""
        return gather_state(self.tables, np.asarray(sp_loc))

    def __call__(self, sp) -> jnp.ndarray:
        """One-shot: global state in, global state out (bit-exact to the
        unsharded :func:`graphdyn.ops.packed.packed_rollout`)."""
        return jnp.asarray(self.fetch(self.advance(self.place(sp))))


# ---------------------------------------------------------------------------
# int8 (SA spin vector) halo primitives — the node axis of the sharded SA
# solver rides the SAME tables, with columns instead of rows
# ---------------------------------------------------------------------------


def sa_halo_local_step(nbr_l, s, real_l, R_coef: int, C_coef: int):
    """One synchronous int8 update of the OWNED columns of a per-shard SA
    state ``s: int8[Rl, n_rows]`` (columns laid out as the halo rows:
    owned, ghosts, trash, zero). Same arithmetic as
    :func:`graphdyn.parallel.sharded._local_step` — ghost-padded neighbor
    slots read the zero column, pad columns stay frozen — so chains remain
    bit-identical to the full-gather solver."""
    nm, dmax = nbr_l.shape
    Rl = s.shape[0]
    s32 = s.astype(jnp.int32)
    g = jnp.take(s32, nbr_l.reshape(-1), axis=1).reshape(Rl, nm, dmax)
    sums = g.sum(axis=2)
    out = (R_coef * jnp.sign(2 * sums + C_coef * s32[:, :nm])).astype(jnp.int8)
    out = jnp.where(real_l[None, :], out, s[:, :nm])
    return lax.dynamic_update_slice(s, out, (0, 0))


def sa_halo_exchange(s, sends, recvs, perms, node_axis: str):
    """Refresh the ghost COLUMNS of a per-shard SA state from the owners'
    boundary columns — one ``ppermute`` slab per schedule offset, exactly
    the packed rollout's exchange with the word axis leading."""
    for perm, s_idx, r_idx in zip(perms, sends, recvs):
        buf = jnp.take(s, s_idx, axis=1)
        buf = lax.ppermute(buf, node_axis, perm)
        s = s.at[:, r_idx].set(buf)
    return s


def sa_halo_cols(tables: HaloTables, s: np.ndarray) -> np.ndarray:
    """Global int8 spins ``[R, n]`` -> halo column layout
    ``[R, P * n_rows]`` (owned + consistent ghosts; trash/zero columns 0,
    so ghost-padded neighbor slots contribute 0 to neighbor sums).
    Hub-split tables additionally replicate every hub's spin into the hub
    columns ``[hub_row0, trash_row)`` of EVERY shard — the vertex-cut
    invariant the SA solver maintains step to step (identical hub updates
    on all shards) and re-establishes on every accepted hub flip."""
    s = np.asarray(s, np.int8)
    R = s.shape[0]
    nm = tables.n_local_max
    out = np.zeros((R, tables.P * tables.n_rows), np.int8)
    view = out.reshape(R, tables.P, tables.n_rows)
    h0 = tables.hub_row0
    for p in range(tables.P):
        cnt = int(tables.counts[p])
        view[:, p, :cnt] = s[:, tables.owned_global[p, :cnt]]
        gcnt = int(tables.ghost_counts[p])
        if gcnt:
            view[:, p, nm:nm + gcnt] = s[:, tables.ghost_global[p, :gcnt]]
        if tables.n_hubs:
            view[:, p, h0:h0 + tables.n_hubs] = s[:, tables.hub_global]
    return out


def sa_halo_uncols(tables: HaloTables, s_cols: np.ndarray) -> np.ndarray:
    """Halo column layout back to global int8 spins ``[R, n]`` (hub spins
    read from shard 0's replicated columns — every shard carries the same
    values by the vertex-cut invariant)."""
    s_cols = np.asarray(s_cols)
    R = s_cols.shape[0]
    view = s_cols.reshape(R, tables.P, tables.n_rows)
    out = np.empty((R, tables.n), np.int8)
    for p in range(tables.P):
        cnt = int(tables.counts[p])
        out[:, tables.owned_global[p, :cnt]] = view[:, p, :cnt]
    if tables.n_hubs:
        h0 = tables.hub_row0
        out[:, tables.hub_global] = view[:, 0, h0:h0 + tables.n_hubs]
    return out


def graph_from_tables(nbr, deg) -> Graph:
    """Reconstruct a host :class:`Graph` from the padded device tables (the
    ``packed_rollout(partition=...)`` entry has only ``nbr``/``deg`` in
    hand). Each undirected edge appears twice in ``nbr``; the ``u < v``
    filter dedups."""
    nbr_h = np.asarray(nbr).astype(np.int32)
    deg_h = np.asarray(deg).astype(np.int32)
    n, dmax = nbr_h.shape
    u = np.repeat(np.arange(n, dtype=np.int64), dmax)
    v = nbr_h.reshape(-1).astype(np.int64)
    keep = (v != n) & (u < v)
    edges = np.stack([u[keep], v[keep]], axis=1).astype(np.int32)
    return Graph(nbr=nbr_h, deg=deg_h, edges=edges)


def halo_rollout(
    nbr,
    deg,
    sp,
    steps: int,
    *,
    partition: Partition,
    rule: str = "majority",
    tie: str = "stay",
    mesh: Mesh | None = None,
):
    """One-shot partitioned rollout — the ``partition=`` path of
    :func:`graphdyn.ops.packed.packed_rollout` (which handles the P=1
    identity itself; this function requires P >= 2)."""
    if partition.P < 2:
        raise ValueError(
            "halo_rollout needs a partition with P >= 2 "
            "(P=1 is packed_rollout itself)"
        )
    prog = HaloProgram(
        graph_from_tables(nbr, deg), partition,
        steps=steps, rule=rule, tie=tie, mesh=mesh,
    )
    return prog(sp)


def lower_halo_rollout(
    mesh: Mesh,
    graph: Graph,
    partition: Partition,
    *,
    W: int,
    steps: int,
    rule: str = "majority",
    tie: str = "stay",
    node_axis: str = "node",
):
    """Lower (without executing) the halo rollout at this partition's
    padded shapes with canonically placed arguments — the program
    :mod:`graphdyn.analysis.graftcheck` fingerprints for the halo path
    (the fingerprint pins the collective structure: one ``ppermute`` slab
    per schedule offset and NO all-gather — the exchange cannot silently
    deoptimize into a full-state gather). Kept next to
    :func:`make_halo_rollout` so a refactor updates the fingerprinted
    surface in place. Returns a ``jax.stages.Lowered``."""
    prog = HaloProgram(
        graph, partition, steps=steps, rule=rule, tie=tie, mesh=mesh,
        node_axis=node_axis,
    )
    sp_loc = prog.place(np.zeros((graph.n, W), np.uint32))
    return prog._fn.lower(*prog._consts, sp_loc, *prog._hub_consts)
