"""Full multi-chip SA solver: the consensus-stop loop over a device mesh.

Round-2 shipped only the sharded loop *body*
(:func:`graphdyn.parallel.sharded.make_sharded_sa_step`); this module wraps
it into the reference's complete solver semantics (`SA_RRG.py:58-88`): the
Metropolis accept, per-step annealing with caps, the stop-when-consensus
test, the ``2n³``-step timeout sentinel ``m_final=2`` (`SA_RRG.py:84`), and
per-replica freezing — all inside ONE jitted ``lax.while_loop`` under
``shard_map``, with replicas (× the temperature ladder) sharded over the
mesh's ``replica`` axis and the node axis of giant graphs sharded over
``node`` (one tiled int8 ``all_gather`` per synchronous rollout step; psum
for the pad-free Σs_end).

Semantics are *identical* to the unsharded solver (`graphdyn.models.sa`):
the same PRNG derivation (fold_in by step count, split, randint/uniform) and
the same injected-stream mode, so the CPU-mesh equivalence test can require
bit-equal spins/steps/sentinels, not just statistical agreement.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from graphdyn.config import SAConfig
from graphdyn.parallel.mesh import shard_map
from graphdyn.models.sa import (
    SAResult,
    draw_sa_proposal,
    metropolis_anneal_update,
    prepare_sa_inputs,
)
from graphdyn.ops.dynamics import rule_coefficients
from graphdyn.parallel.sharded import (
    _local_step,
    _masked_block_sum,
    _real_mask,
    pad_nodes,
    place_sharded,
)


class _State(NamedTuple):
    s: jnp.ndarray         # int8[Rl, n_block] — this shard's spin block
    sum_end: jnp.ndarray   # int32[Rl] — Σ s_end of current config (global)
    a: jnp.ndarray         # f[Rl]
    b: jnp.ndarray         # f[Rl]
    t: jnp.ndarray         # int[Rl]
    m_final: jnp.ndarray   # f[Rl]
    active: jnp.ndarray    # bool[Rl]
    key: jnp.ndarray       # per-replica PRNG key
    live: jnp.ndarray      # int32 scalar — mesh-wide count of active shards
    chunk_t: jnp.ndarray   # int32 scalar — steps taken in the current chunk


@functools.lru_cache(maxsize=64)
def make_sharded_sa_solver(
    mesh: Mesh,
    *,
    n_real: int,
    rollout_steps: int,
    max_steps: int,
    rule: str = "majority",
    tie: str = "stay",
    injected: bool = False,
    stream_len: int = 1,
    replica_axis: str = "replica",
    node_axis: str = "node",
    chunk_steps: int | None = None,
    lightcone: bool = False,
):
    """Build the jitted sharded solver pair ``(init_fn, chunk_fn)``.

    ``init_fn(nbr, s0) -> sum_end0`` computes the rolled-out end sum of the
    starting configuration (the cached quantity the 1-rollout-per-step
    redesign carries). ``chunk_fn(nbr, s, key, a, b, t, m_final, active,
    sum_end, par_a, par_b, a_cap, b_cap, proposals, uniforms) -> (s, mag,
    key, a, b, t, m_final, active, sum_end)`` advances every chain until all
    stop — or, with ``chunk_steps``, for at most that many more steps, which
    makes the returned state an exact-resume point (the loop body is
    step-index-driven, so splitting it across calls cannot change the
    chain). ``s0``/``s`` are sharded ``P(replica, node)``, per-replica
    vectors ``P(replica)``.

    The caller builds the initial ``active`` mask — shard-padding replicas
    must start inactive so they cannot keep the mesh loop alive (an all-+1
    pad row is at consensus under majority dynamics, but not under e.g.
    ``rule='minority'``).

    ``lightcone=True`` (replica-only meshes: node axis size 1, enforced by
    the caller) evaluates candidates O(ball) against a per-replica cached
    trajectory instead of the full sharded rollout — the same
    :mod:`graphdyn.ops.lightcone` ops as the unsharded solver, so chains
    stay bit-identical across all three solvers under injected streams. The
    signatures change: ``init_fn(nbr, s0) -> (traj, sum_end)`` and
    ``chunk_fn`` carries ``traj`` (int8[Rl, T+1, n+2]) instead of ``s``,
    with the three light-cone tables appended as replicated args."""
    R_coef, C_coef = rule_coefficients(rule, tie)
    if lightcone:
        return _make_lightcone_solver(
            mesh, n_real=n_real, rollout_steps=rollout_steps,
            max_steps=max_steps, R_coef=R_coef, C_coef=C_coef,
            injected=injected, stream_len=stream_len,
            replica_axis=replica_axis, node_axis=node_axis,
            chunk_steps=chunk_steps,
        )

    def _rollout_tools(nbr_local, n_block):
        mask = _real_mask(node_axis, n_block, n_real)

        def rollout(s_loc):
            def rbody(_, s):
                # graftlint: disable-next-line=GD013  node_mode='gather': the parity baseline the halo mode is tested against, and the small-graph fallback
                s_full = lax.all_gather(s, node_axis, axis=1, tiled=True)
                return _local_step(nbr_local, s_full, s, mask, R_coef, C_coef)

            return lax.fori_loop(0, rollout_steps, rbody, s_loc)

        def end_sum(s_loc):
            return lax.psum(_masked_block_sum(rollout(s_loc), mask), node_axis)

        return mask, end_sum

    def init(nbr_local, s0_local):
        _, end_sum = _rollout_tools(nbr_local, s0_local.shape[1])
        return end_sum(s0_local)

    def chunk(nbr_local, s_local, key, a, b, t, m_final_in, active_in,
              sum_end_in, par_a, par_b, a_cap, b_cap, proposals, uniforms):
        Rl, n_block = s_local.shape
        dt = a.dtype
        node_idx = lax.axis_index(node_axis)
        mask, end_sum = _rollout_tools(nbr_local, n_block)

        def cond(st: _State):
            go = st.live > 0
            if chunk_steps is not None:
                go = go & (st.chunk_t < chunk_steps)
            return go

        def body(st: _State):
            # identical draw to the unsharded `_sa_loop` (shared helper):
            # replicated keys make every node shard draw the same (i, u)
            i, u = draw_sa_proposal(
                st.key, st.t, proposals, uniforms,
                injected=injected, stream_len=stream_len, n=n_real, dt=dt,
            )

            # flip proposal i on its owning node shard
            local_i = i - node_idx * n_block
            owned = (local_i >= 0) & (local_i < n_block)
            li = jnp.clip(local_i, 0, n_block - 1)
            ridx = jnp.arange(Rl, dtype=jnp.int32)
            s_i_local = st.s[ridx, li].astype(jnp.int32)
            flipped = st.s.at[ridx, li].set((-s_i_local).astype(jnp.int8))
            s_flip = jnp.where(owned[:, None], flipped, st.s)
            s_i = lax.psum(jnp.where(owned, s_i_local, 0), node_axis)

            sum_end_flip = end_sum(s_flip)

            do, sum_end_new, a_new, b_new, t_new, m_final, active = (
                metropolis_anneal_update(
                    st.active, st.a, st.b, st.t, st.m_final,
                    st.sum_end, sum_end_flip, s_i, u,
                    par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
                    max_steps=max_steps, n=n_real,
                )
            )
            s_new = jnp.where(do[:, None], s_flip, st.s)
            live = lax.psum(jnp.any(active).astype(jnp.int32), replica_axis)
            return _State(
                s_new, sum_end_new, a_new, b_new, t_new, m_final, active,
                st.key, live, st.chunk_t + 1,
            )

        live0 = lax.psum(jnp.any(active_in).astype(jnp.int32), replica_axis)
        state0 = _State(
            s_local, sum_end_in, a, b, t, m_final_in, active_in, key,
            live0, jnp.zeros((), jnp.int32),
        )
        out = lax.while_loop(cond, body, state0)
        mag = lax.psum(_masked_block_sum(out.s, mask), node_axis).astype(dt) / n_real
        return (out.s, mag, out.key, out.a, out.b, out.t, out.m_final,
                out.active, out.sum_end)

    rep = P(replica_axis)
    init_fn = jax.jit(shard_map(
        init,
        mesh=mesh,
        in_specs=(P(node_axis, None), P(replica_axis, node_axis)),
        out_specs=rep,
        check_vma=False,
    ))
    chunk_fn = jax.jit(shard_map(
        chunk,
        mesh=mesh,
        in_specs=(
            P(node_axis, None),            # nbr
            P(replica_axis, node_axis),    # s
            rep, rep, rep, rep, rep, rep, rep,  # key a b t m_final active sum_end
            P(), P(), P(), P(),            # par_a, par_b, a_cap, b_cap
            P(replica_axis, None),         # proposals
            P(replica_axis, None),         # uniforms
        ),
        out_specs=(
            P(replica_axis, node_axis),
            rep, rep, rep, rep, rep, rep, rep, rep,
        ),
        check_vma=False,
    ))
    return init_fn, chunk_fn


def make_halo_sa_solver(
    mesh: Mesh,
    tables,
    *,
    n_real: int,
    rollout_steps: int,
    max_steps: int,
    rule: str = "majority",
    tie: str = "stay",
    injected: bool = False,
    stream_len: int = 1,
    replica_axis: str = "replica",
    node_axis: str = "node",
    chunk_steps: int | None = None,
):
    """The halo-exchange node-sharding solver pair (``node_mode='halo'`` of
    :func:`sa_sharded`): same chain semantics and signatures as the full
    mode of :func:`make_sharded_sa_solver`, but the candidate rollout
    updates each shard's owned spin columns from purely local gathers and
    ships only the partition's boundary columns per synchronous step
    (:mod:`graphdyn.parallel.halo` — one ``ppermute`` slab per schedule
    offset, never a full-state ``all_gather``), so per-step collective
    traffic scales with the edge CUT instead of ``n``. ``tables`` is a
    :class:`graphdyn.parallel.halo.HaloTables`; the extra leading args of
    ``init_fn``/``chunk_fn`` are the placed layout tables, and ``chunk_fn``
    takes the replicated ``loc_of`` owner map and ``hub_of`` hub-slot map
    as its final arguments (the proposal flip must find node ``i``'s shard
    and column — or, for a vertex-cut hub, its replicated hub column on
    EVERY shard). Hub-split tables are first-class: each shard gathers its
    local partial neighbor sum for every hub from ``hub_nbr_loc`` and a
    ``psum`` over the node axis yields the exact total (hub–hub terms live
    on shard 0 only, so nothing is double-counted), every shard then writes
    the identical sign update into its hub columns — the replication
    invariant needs no extra collective beyond that one integer psum.
    Not lru-cached: the host tables are unhashable — one build per driver
    call, which the chunked drive loop amortizes exactly like the jit
    cache would."""
    from graphdyn.parallel.halo import (
        exchange_perms,
        sa_halo_exchange,
        sa_halo_local_step,
    )

    R_coef, C_coef = rule_coefficients(rule, tie)
    nm = tables.n_local_max
    perms = exchange_perms(tables)
    k = len(tables.schedule)
    H = int(tables.n_hubs)
    hub_row0 = tables.hub_row0

    def _tools(nbr_l, real_l, sends, recvs, hub_nbr_l):
        if H:
            hd = hub_nbr_l.shape[-1]

            def hub_step(s):
                # partial hub neighbor sums from THIS shard's owned rows
                # (+ hub–hub terms on shard 0; zero-column pads read 0),
                # psum -> exact totals, replicated on every shard
                Rl_ = s.shape[0]
                g = jnp.take(
                    s.astype(jnp.int32), hub_nbr_l.reshape(-1), axis=1
                ).reshape(Rl_, H, hd)
                tot = lax.psum(g.sum(axis=2), node_axis)
                s_hub = s[:, hub_row0:hub_row0 + H].astype(jnp.int32)
                return (
                    R_coef * jnp.sign(2 * tot + C_coef * s_hub)
                ).astype(jnp.int8)

        def rollout(s_loc):
            def rbody(_, s):
                if H:
                    hub_new = hub_step(s)   # from the OLD state, like owned
                s = sa_halo_local_step(nbr_l, s, real_l, R_coef, C_coef)
                if H:
                    s = lax.dynamic_update_slice(s, hub_new, (0, hub_row0))
                return sa_halo_exchange(s, sends, recvs, perms, node_axis)

            return lax.fori_loop(0, rollout_steps, rbody, s_loc)

        def block_sum(s_loc):
            # pad-free Σ over this shard's OWNED real columns (ghosts and
            # pads excluded — each node is counted once, on its owner);
            # replicated hub columns are counted once, on shard 0
            out = jnp.where(
                real_l[None, :], s_loc[:, :nm].astype(jnp.int32), 0
            ).sum(axis=1)
            if H:
                hub_sum = s_loc[:, hub_row0:hub_row0 + H].astype(
                    jnp.int32).sum(axis=1)
                out = out + jnp.where(
                    lax.axis_index(node_axis) == 0, hub_sum, 0)
            return out

        def end_sum(s_loc):
            return lax.psum(block_sum(rollout(s_loc)), node_axis)

        return rollout, block_sum, end_sum

    def init(nbr_l, real_l, send_l, recv_l, hub_nbr_l, s0):
        sends = [x[0] for x in send_l]
        recvs = [x[0] for x in recv_l]
        _, _, end_sum = _tools(nbr_l, real_l, sends, recvs, hub_nbr_l)
        return end_sum(s0)

    def chunk(nbr_l, real_l, send_l, recv_l, hub_nbr_l, s_local, key, a, b,
              t, m_final_in, active_in, sum_end_in, par_a, par_b, a_cap,
              b_cap, proposals, uniforms, loc_of, hub_of):
        sends = [x[0] for x in send_l]
        recvs = [x[0] for x in recv_l]
        Rl = s_local.shape[0]
        dt = a.dtype
        node_idx = lax.axis_index(node_axis)
        _, block_sum, end_sum = _tools(nbr_l, real_l, sends, recvs,
                                       hub_nbr_l)

        def cond(st: _State):
            go = st.live > 0
            if chunk_steps is not None:
                go = go & (st.chunk_t < chunk_steps)
            return go

        def body(st: _State):
            i, u = draw_sa_proposal(
                st.key, st.t, proposals, uniforms,
                injected=injected, stream_len=stream_len, n=n_real, dt=dt,
            )
            # flip proposal i on its owning shard's column (loc_of maps the
            # global id to owner * n_local_max + row). A hub has NO owner
            # (loc_of == -1): its spin lives replicated in the hub columns
            # of every shard, so the flip is applied on ALL shards — that
            # is the propagation the vertex cut requires before the
            # candidate rollout reads any replica
            lg = jnp.take(loc_of, i)
            if H:
                hu = jnp.take(hub_of, i)
                is_hub = hu >= 0
                col = jnp.where(is_hub, hub_row0 + jnp.maximum(hu, 0),
                                lg % nm)
                owned = ((lg // nm) == node_idx) | is_hub
                count_here = jnp.where(is_hub, node_idx == 0,
                                       (lg // nm) == node_idx)
            else:
                col = lg % nm
                owned = (lg // nm) == node_idx
                count_here = owned
            ridx = jnp.arange(Rl, dtype=jnp.int32)
            s_i_local = st.s[ridx, col].astype(jnp.int32)
            flipped = st.s.at[ridx, col].set((-s_i_local).astype(jnp.int8))
            s_flip = jnp.where(owned[:, None], flipped, st.s)
            # propagate the flip into its ghost copies BEFORE the rollout:
            # the all_gather solver re-gathers the full state every step,
            # here the exchanged boundary columns are the only remote view
            # (hub flips need no exchange — already applied on every shard)
            s_flip = sa_halo_exchange(s_flip, sends, recvs, perms, node_axis)
            s_i = lax.psum(jnp.where(count_here, s_i_local, 0), node_axis)

            sum_end_flip = end_sum(s_flip)

            do, sum_end_new, a_new, b_new, t_new, m_final, active = (
                metropolis_anneal_update(
                    st.active, st.a, st.b, st.t, st.m_final,
                    st.sum_end, sum_end_flip, s_i, u,
                    par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
                    max_steps=max_steps, n=n_real,
                )
            )
            s_new = jnp.where(do[:, None], s_flip, st.s)
            live = lax.psum(jnp.any(active).astype(jnp.int32), replica_axis)
            return _State(
                s_new, sum_end_new, a_new, b_new, t_new, m_final, active,
                st.key, live, st.chunk_t + 1,
            )

        live0 = lax.psum(jnp.any(active_in).astype(jnp.int32), replica_axis)
        state0 = _State(
            s_local, sum_end_in, a, b, t, m_final_in, active_in, key,
            live0, jnp.zeros((), jnp.int32),
        )
        out = lax.while_loop(cond, body, state0)
        mag = lax.psum(block_sum(out.s), node_axis).astype(dt) / n_real
        return (out.s, mag, out.key, out.a, out.b, out.t, out.m_final,
                out.active, out.sum_end)

    rep = P(replica_axis)
    tab_specs = (
        P(node_axis, None),                # nbr_loc [P*nm, dmax]
        P(node_axis),                      # real    [P*nm]
        [P(node_axis, None)] * k,          # send_idx per offset [P, m]
        [P(node_axis, None)] * k,          # recv_idx per offset [P, m]
        P(node_axis, None, None),          # hub_nbr_loc [P, H, hd_max]
    )
    init_fn = jax.jit(shard_map(
        init,
        mesh=mesh,
        in_specs=(*tab_specs, P(replica_axis, node_axis)),
        out_specs=rep,
        check_vma=False,
    ))
    chunk_fn = jax.jit(shard_map(
        chunk,
        mesh=mesh,
        in_specs=(
            *tab_specs,
            P(replica_axis, node_axis),    # s (halo column layout)
            rep, rep, rep, rep, rep, rep, rep,  # key a b t m_final active sum_end
            P(), P(), P(), P(),            # par_a, par_b, a_cap, b_cap
            P(replica_axis, None),         # proposals
            P(replica_axis, None),         # uniforms
            P(),                           # loc_of
            P(),                           # hub_of
        ),
        out_specs=(
            P(replica_axis, node_axis),
            rep, rep, rep, rep, rep, rep, rep, rep,
        ),
        check_vma=False,
    ))
    return init_fn, chunk_fn


def _make_lightcone_solver(
    mesh: Mesh,
    *,
    n_real: int,
    rollout_steps: int,
    max_steps: int,
    R_coef: int,
    C_coef: int,
    injected: bool,
    stream_len: int,
    replica_axis: str,
    node_axis: str,
    chunk_steps: int | None,
):
    """The replica-only-mesh light-cone solver pair (see
    :func:`make_sharded_sa_solver`). Each device owns whole replicas (node
    axis size 1), so the unsharded O(ball) candidate evaluation runs
    per-shard verbatim; the only collective is the one-scalar live count
    keeping the mesh loop in lockstep."""
    from graphdyn.ops.lightcone import (
        LightconeTables,
        batched_trajectory,
        lightcone_accept,
        lightcone_flip_delta,
    )

    def init(nbr_local, s0_local):
        traj = batched_trajectory(
            nbr_local, s0_local, rollout_steps, R_coef, C_coef
        )
        sum_end = (
            traj[:, rollout_steps, :n_real].astype(jnp.int32).sum(axis=1)
        )
        return traj, sum_end

    def chunk(nbr_local, traj_in, key, a, b, t, m_final_in, active_in,
              sum_end_in, par_a, par_b, a_cap, b_cap, proposals, uniforms,
              ball, nbr_slot, nbr_glob):
        tables = LightconeTables(
            ball, nbr_slot, nbr_glob, rollout_steps, ball.shape[1]
        )
        Rl = traj_in.shape[0]
        dt = a.dtype

        def cond(st):
            go = st[9] > 0
            if chunk_steps is not None:
                go = go & (st[8] < chunk_steps)
            return go

        def body(st):
            traj, key, a, b, t, m_final, active, sum_end, chunk_t, _ = st
            i, u = draw_sa_proposal(
                key, t, proposals, uniforms,
                injected=injected, stream_len=stream_len, n=n_real, dt=dt,
            )
            ridx = jnp.arange(Rl, dtype=jnp.int32)
            # current spins live in traj[:, 0] (the carried cache); see
            # models.sa._sa_loop — identical step arithmetic
            s_i = traj[ridx, 0, i].astype(jnp.int32)
            delta, vstack = lightcone_flip_delta(
                tables, traj, i, R_coef, C_coef, rollout_steps
            )
            do, sum_end_new, a_new, b_new, t_new, m_final_new, active_new = (
                metropolis_anneal_update(
                    active, a, b, t, m_final, sum_end, sum_end + delta,
                    s_i, u,
                    par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
                    max_steps=max_steps, n=n_real,
                )
            )
            traj_new = lightcone_accept(tables, traj, i, vstack, do)
            live = lax.psum(
                jnp.any(active_new).astype(jnp.int32), replica_axis
            )
            return (traj_new, key, a_new, b_new, t_new, m_final_new,
                    active_new, sum_end_new, chunk_t + 1, live)

        live0 = lax.psum(jnp.any(active_in).astype(jnp.int32), replica_axis)
        out = lax.while_loop(cond, body, (
            traj_in, key, a, b, t, m_final_in, active_in, sum_end_in,
            jnp.zeros((), jnp.int32), live0,
        ))
        traj = out[0]
        mag = (
            traj[:, 0, :n_real].astype(jnp.int32).sum(axis=1).astype(dt)
            / n_real
        )
        return (traj, mag, out[1], out[2], out[3], out[4], out[5], out[6],
                out[7])

    rep = P(replica_axis)
    init_fn = jax.jit(shard_map(
        init,
        mesh=mesh,
        in_specs=(P(node_axis, None), P(replica_axis, node_axis)),
        out_specs=(rep, rep),
        check_vma=False,
    ))
    chunk_fn = jax.jit(shard_map(
        chunk,
        mesh=mesh,
        in_specs=(
            P(node_axis, None),            # nbr
            rep,                           # traj
            rep, rep, rep, rep, rep, rep, rep,  # key a b t m_final active sum_end
            P(), P(), P(), P(),            # par_a, par_b, a_cap, b_cap
            P(replica_axis, None),         # proposals
            P(replica_axis, None),         # uniforms
            P(), P(), P(),                 # ball, nbr_slot, nbr_glob
        ),
        out_specs=(rep,) * 9,
        check_vma=False,
    ))
    return init_fn, chunk_fn


def sa_sharded(
    graph,
    config: SAConfig | None = None,
    *,
    mesh: Mesh,
    n_replicas: int | None = None,
    seed: int | None = None,
    s0: np.ndarray | None = None,
    a0: np.ndarray | float | None = None,
    b0: np.ndarray | float | None = None,
    proposals: np.ndarray | None = None,
    uniforms: np.ndarray | None = None,
    max_steps: int | None = None,
    dtype=jnp.float32,
    replica_axis: str = "replica",
    node_axis: str = "node",
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    chunk_steps: int = 100_000,
    rollout_mode: str = "full",
    lc_tables=None,
    node_mode: str = "gather",
    partition=None,
    layout: str = "padded",
    stream_chunks: int = 4,
    hub_threshold: int | None = None,
) -> SAResult:
    """Run batched SA chains to completion over a device mesh.

    The multi-chip counterpart of
    :func:`graphdyn.models.sa.simulated_annealing` (same API axes:
    per-replica ``a0``/``b0`` carry the temperature ladder, injected
    ``proposals``/``uniforms`` enable bitwise parity testing; the same
    ``checkpoint_path`` exact-resume contract — state is saved UNPADDED, so
    a run may resume on a different mesh shape — or under a different
    ``rollout_mode`` (the snapshot is mode-agnostic: spins + chain
    scalars) — bit-exactly when the collective reduction order matches).
    Replicas pad up to the replica-axis size with already-converged all-+1
    dummies; the node axis pads via :func:`pad_nodes`. Results are sliced
    back to the caller's shapes.

    ``rollout_mode='lightcone'`` (replica-only meshes: the mesh's node axis
    must have size 1) evaluates candidates O(ball) per step against a
    per-replica trajectory cache instead of the O(n·d) sharded rollout —
    the BASELINE config-5 shape (giant graph × many replicas) where memory
    allows each device a whole-graph cache. Chains are bit-identical to
    both full-rollout solvers (tested under injected streams). Pass
    ``lc_tables`` (:func:`graphdyn.ops.lightcone.build_lightcone_tables`)
    to amortize table construction across calls.

    ``node_mode='halo'`` (full rollout mode, node axis >= 2) replaces the
    per-step full-state ``all_gather`` with the halo exchange of
    :mod:`graphdyn.parallel.halo`: the graph is partitioned
    (``partition``, a :class:`graphdyn.graphs.Partition` with ``P`` equal
    to the mesh's node-axis size; built with
    :func:`graphdyn.graphs.partition_graph` when None), each shard owns
    its part's spin columns plus ghost copies of remote boundary nodes,
    and every synchronous step ships only boundary columns over the static
    ``ppermute`` schedule — per-step collective bytes scale with the edge
    cut, not with ``n`` (the pod-scale path; calls
    :func:`graphdyn.parallel.mesh.init_multihost` up front, so the
    ``multihost.init`` fault site and its coordinator-retry policy ride
    this path). Chains, snapshots, and the resume contract are identical
    to the gather mode (snapshots store the unpadded GLOBAL state, so runs
    resume across node modes, mesh shapes, and shard counts — tested).

    ``layout='streamed'`` (ISSUE 20) is the out-of-core composition: the
    chain is host-stepped exactly like the unsharded ``layout='streamed'``
    route, with every candidate end-sum computed by
    :func:`graphdyn.parallel.stream.sharded_streamed_rollout` — each of
    the mesh's ``node_axis`` shards walks its own part-major chunk run
    (``stream_chunks`` per shard) while boundary words + hub partials
    (``hub_threshold``) ride the halo collectives. Bit-identical chains
    to ``layout='padded'`` under injected streams; no chunked-chain
    resume (refuses ``checkpoint_path``).
    """
    config = config or SAConfig()
    n = graph.n
    dyn = config.dynamics
    prep = prepare_sa_inputs(
        graph, config, n_replicas=n_replicas, seed=seed, s0=s0, a0=a0, b0=b0,
        proposals=proposals, uniforms=uniforms, max_steps=max_steps,
    )
    (R, seed, s0, a0, b0, proposals, uniforms,
     max_steps, stream_len, injected) = prep

    rep_shards = int(mesh.shape[replica_axis])
    node_shards = int(mesh.shape[node_axis])
    np_dt = np.float32 if dtype == jnp.float32 else np.float64  # graftlint: disable=GD004  dtype mirror for host results
    t_dt = np.int64 if jax.config.jax_enable_x64 else np.int32

    if layout not in ("padded", "streamed"):
        raise ValueError(
            f"layout must be 'padded' or 'streamed', got {layout!r} "
            "(degree-bucketed layouts relabel nodes — use the unsharded "
            "solver's layout='bucketed')"
        )
    if layout == "streamed":
        # the out-of-core composition (ISSUE 20): the chain is
        # host-stepped exactly like the unsharded layout='streamed'
        # route, with every candidate end-sum computed by the SHARDED
        # streamed engine — P prefetch lanes walking part-major chunk
        # runs, boundary words + hub partials on the halo collectives
        if rollout_mode != "full":
            raise ValueError(
                "layout='streamed' pages state through host RAM; "
                "rollout_mode='lightcone' caches device-resident "
                "trajectories — use rollout_mode='full'"
            )
        if checkpoint_path is not None:
            raise ValueError(
                "layout='streamed' has no chunked-chain resume (the "
                "chain is host-stepped; the streamed rollout's own "
                "checkpoints cover serve jobs, not this chain) — use "
                "layout='padded' for checkpointed SA chains"
            )
        if lc_tables is not None:
            raise ValueError("lc_tables requires rollout_mode='lightcone'")
        if node_mode != "gather":
            raise ValueError(
                "layout='streamed' runs its own halo composition inside "
                "the streamed engine — drop node_mode='halo'"
            )
        if partition is not None and partition.P != node_shards:
            raise ValueError(
                f"partition has P={partition.P} parts but the mesh "
                f"{node_axis!r} axis has size {node_shards}"
            )
        return _sa_sharded_streamed(
            graph, config, prep, mesh=mesh, node_axis=node_axis,
            node_shards=node_shards, dtype=dtype, np_dt=np_dt,
            stream_chunks=stream_chunks, hub_threshold=hub_threshold,
            partition=partition,
        )

    if rollout_mode not in ("full", "lightcone"):
        raise ValueError(
            f"rollout_mode must be 'full' or 'lightcone', got {rollout_mode!r}"
        )
    if node_mode not in ("gather", "halo"):
        raise ValueError(
            f"node_mode must be 'gather' or 'halo', got {node_mode!r}"
        )
    halo = node_mode == "halo"
    if halo and rollout_mode != "full":
        raise ValueError(
            "node_mode='halo' shards the full-rollout node axis; "
            "rollout_mode='lightcone' keeps whole replicas per device and "
            "has no node axis to exchange"
        )
    if halo and node_shards < 2:
        raise ValueError(
            f"node_mode='halo' needs a node axis of size >= 2 (got "
            f"{node_shards}): with one shard there is no halo to exchange "
            "— use node_mode='gather'"
        )
    tables = None
    if halo:
        from graphdyn.graphs import partition_graph
        from graphdyn.parallel.halo import build_halo_tables
        from graphdyn.parallel.mesh import init_multihost

        # the pod-scale path: bring up the multi-host runtime first (an
        # idempotent no-op single-process) — a not-yet-up coordinator at
        # requeue time retries with jittered backoff via the
        # `multihost.init` fault site's policy instead of crashing the job
        init_multihost()
        if partition is None:
            partition = partition_graph(graph, node_shards, seed=seed or 0)
        if partition.P != node_shards:
            raise ValueError(
                f"partition has P={partition.P} parts but the mesh "
                f"{node_axis!r} axis has size {node_shards}"
            )
        tables = build_halo_tables(graph, partition)
    elif partition is not None:
        raise ValueError("partition= requires node_mode='halo'")
    lightcone = rollout_mode == "lightcone"
    rollout = dyn.p + dyn.c - 1
    if lightcone:
        if node_shards != 1:
            raise ValueError(
                "rollout_mode='lightcone' needs a replica-only mesh (node "
                f"axis size 1, got {node_shards}): each device holds whole "
                "replicas and their trajectory caches"
            )
        from graphdyn.ops.lightcone import resolve_lightcone_tables

        lc_tables = resolve_lightcone_tables(graph, rollout, lc_tables)
    elif lc_tables is not None:
        raise ValueError("lc_tables given but rollout_mode is 'full'")

    ckpt = None
    restored = None
    if checkpoint_path is not None:
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        ckpt = ChainCheckpointer(
            checkpoint_path, kind="sa_sharded_chain", seed=seed,
            # run identity deliberately excludes the mesh shape: state is
            # saved unpadded/global, so resuming on a different mesh works.
            # Injected streams ARE identity (resuming under different
            # streams would splice a chimera chain)
            fp=run_fingerprint(
                graph.edges, config, int(max_steps), bool(injected),
                np_dt, bool(jax.config.jax_enable_x64),
                *((np.asarray(proposals), np.asarray(uniforms))
                  if injected else ()),
            ),
            interval_s=checkpoint_interval_s,
            extra_meta={"R": int(R)},
        )
        restored = ckpt.load_state(check=lambda a: a["s"].shape == (R, n))

    # replica padding: all-+1 rows are at consensus (m0 == 1) and freeze on
    # entry (active=False below) — they do no work and are sliced off at exit
    R_pad = (-R) % rep_shards
    Rtot = R + R_pad

    def pad_rep(x, fill):
        x = np.asarray(x)
        if not R_pad:
            return x
        pad = np.full((R_pad,) + x.shape[1:], fill, x.dtype)
        return np.concatenate([x, pad])

    proposals = pad_rep(proposals, 0)
    uniforms = pad_rep(uniforms, 0.0)

    if not halo:
        nbr_pad, n_pad = pad_nodes(graph, node_shards)

    if restored is None:
        s_h = np.asarray(s0, np.int8)
        a_h = a0.astype(np_dt)
        b_h = b0.astype(np_dt)
        t_h = np.zeros(R, t_dt)
        key_h = np.asarray(jax.vmap(jax.random.PRNGKey)(
            np.arange(R, dtype=np.uint32) + np.uint32(seed)
        ))
        sum_end_h = None           # computed by init_fn below
        m_final_h = None
        active_h = None
    else:
        s_h = restored["s"].astype(np.int8)
        a_h = restored["a"].astype(np_dt)
        b_h = restored["b"].astype(np_dt)
        t_h = restored["t"].astype(t_dt)
        key_h = restored["key"]
        sum_end_h = restored["sum_end"].astype(np.int32)
        m_final_h = restored["m_final"].astype(np_dt)
        active_h = restored["active"].astype(bool)

    def place_state():
        """Pad the host state to mesh shapes and place it."""
        s_full = (
            np.concatenate([s_h, np.ones((R_pad, n), np.int8)])
            if R_pad else s_h
        )
        if halo:
            # the halo column layout: owned + consistent ghost columns per
            # shard; the all-+1 replica pad rows stay at consensus in any
            # layout, and the zero column reads as spin 0 for ghost-padded
            # neighbor slots
            from graphdyn.parallel.halo import sa_halo_cols

            s_pad = sa_halo_cols(tables, s_full)
        else:
            s_pad = np.concatenate(       # frozen node pad columns
                [s_full, np.ones((Rtot, n_pad - n), np.int8)], axis=1,
            )
        key_pad = np.concatenate(
            [key_h, np.asarray(jax.vmap(jax.random.PRNGKey)(
                np.zeros(R_pad, np.uint32)))]
        ) if R_pad else key_h
        return (
            place_sharded(mesh, jnp.asarray(s_pad), P(replica_axis, node_axis)),
            place_sharded(mesh, jnp.asarray(key_pad), P(replica_axis)),
            place_sharded(mesh, jnp.asarray(pad_rep(a_h, 1.0)), P(replica_axis)),
            place_sharded(mesh, jnp.asarray(pad_rep(b_h, 1.0)), P(replica_axis)),
            place_sharded(mesh, jnp.asarray(pad_rep(t_h, 0)), P(replica_axis)),
        )

    if halo:
        init_fn, chunk_fn = make_halo_sa_solver(
            mesh, tables,
            n_real=n,
            rollout_steps=dyn.p + dyn.c - 1,
            max_steps=max_steps,
            rule=dyn.rule,
            tie=dyn.tie,
            injected=injected,
            stream_len=stream_len,
            replica_axis=replica_axis,
            node_axis=node_axis,
            chunk_steps=int(chunk_steps) if ckpt is not None else None,
        )
        spec2 = P(node_axis, None)
        # hub-split tables: the per-shard hub partial-sum gather rows and
        # the global-id -> hub-slot map (solver statics; a hub-free
        # partition ships 1-element dummies the solver never traces)
        hub_nbr_h = (
            tables.hub_nbr_loc if tables.n_hubs
            else np.full((node_shards, 1, 1), tables.zero_row, np.int32)
        )
        hub_of_h = np.full(n, -1, np.int32)
        if tables.n_hubs:
            hub_of_h[tables.hub_global] = np.arange(
                tables.n_hubs, dtype=np.int32)
        lead = (
            place_sharded(
                mesh,
                jnp.asarray(tables.nbr_loc.reshape(-1, tables.dmax)),
                spec2,
            ),
            place_sharded(mesh, jnp.asarray(tables.real.reshape(-1)),
                          P(node_axis)),
            [place_sharded(mesh, jnp.asarray(s), spec2)
             for (_, s, _) in tables.schedule],
            [place_sharded(mesh, jnp.asarray(r), spec2)
             for (_, _, r) in tables.schedule],
            place_sharded(mesh, jnp.asarray(hub_nbr_h),
                          P(node_axis, None, None)),
        )
    else:
        init_fn, chunk_fn = make_sharded_sa_solver(
            mesh,
            n_real=n,
            rollout_steps=dyn.p + dyn.c - 1,
            max_steps=max_steps,
            rule=dyn.rule,
            tie=dyn.tie,
            injected=injected,
            stream_len=stream_len,
            replica_axis=replica_axis,
            node_axis=node_axis,
            chunk_steps=int(chunk_steps) if ckpt is not None else None,
            lightcone=lightcone,
        )
        lead = (
            place_sharded(mesh, jnp.asarray(nbr_pad), P(node_axis, None)),
        )
    s_dev, key_dev, a_dev, b_dev, t_dev = place_state()

    if lightcone:
        # traj is a pure function of s — recomputed, never persisted (same
        # as the unsharded solver's resume); sum_end from the cache's last
        # frame equals the restored value by construction
        traj_dev, sum_end_dev = init_fn(*lead, s_dev)
        if sum_end_h is None:
            sum_end_h = np.asarray(sum_end_dev)[:R]
            m_final_h = (sum_end_h.astype(np_dt) / np_dt(n)).astype(np_dt)
            active_h = m_final_h < 1.0
        carried0 = traj_dev
    else:
        if sum_end_h is None:
            sum_end_h = np.asarray(init_fn(*lead, s_dev))[:R]
            m_final_h = (sum_end_h.astype(np_dt) / np_dt(n)).astype(np_dt)
            active_h = m_final_h < 1.0
        carried0 = s_dev

    def place_rep(x, fill):
        return place_sharded(mesh, jnp.asarray(pad_rep(x, fill)), P(replica_axis))

    state = (
        carried0, key_dev, a_dev, b_dev, t_dev,
        place_rep(m_final_h, 1.0),                 # pad rows: at consensus
        place_rep(active_h, False),                # pad rows: frozen
        place_rep(sum_end_h, n),
    )
    consts = (
        jnp.asarray(np_dt(config.par_a)),
        jnp.asarray(np_dt(config.par_b)),
        jnp.asarray(np_dt(config.a_cap_frac * n)),
        jnp.asarray(np_dt(config.b_cap_frac * n)),
        place_sharded(mesh, jnp.asarray(proposals), P(replica_axis, None)),
        place_sharded(mesh, jnp.asarray(uniforms.astype(np_dt)), P(replica_axis, None)),
    )
    if lightcone:
        repl = P()
        consts = consts + (
            place_sharded(mesh, lc_tables.ball, repl),
            place_sharded(mesh, lc_tables.nbr_slot, repl),
            place_sharded(mesh, lc_tables.nbr_glob, repl),
        )
    if halo:
        consts = consts + (
            place_sharded(mesh, jnp.asarray(tables.loc_of), P()),
            place_sharded(mesh, jnp.asarray(hub_of_h), P()),
        )

    fields = ("s", "key", "a", "b", "t", "m_final", "active", "sum_end")

    def extract_s(carried):
        """Current spins from the carried state, in the caller's GLOBAL
        node order — traj frame 0 in lightcone mode (the cache IS the live
        state; `models.sa._sa_loop`), the un-partitioned owned columns in
        halo mode (snapshots are layout-agnostic, so runs resume across
        node modes and shard counts). Slices on DEVICE first: the full
        traj cache is [Rtot, T+1, n+2] int8 and a checkpoint only needs
        the [R, n] spin frame on the host."""
        if halo:
            from graphdyn.parallel.halo import sa_halo_uncols

            return sa_halo_uncols(tables, np.asarray(carried[:R]))
        sl = carried[:R, 0, :n] if lightcone else carried[:R, :n]
        return np.asarray(sl)

    def advance(st):
        out = chunk_fn(*lead, *st, *consts)     # (s|traj, mag, key, a, b, ...)
        from graphdyn import obs

        if obs.enabled():
            # per-chunk device-memory gauges for the sharded rollout
            # (obs.mem.*; explicit unavailable+reason on stats-less
            # backends) — the mesh path's HBM occupancy row. Fenced like
            # the grouped loops' sites: stats sampled while the chunk is
            # still in flight would attribute residency one chunk late
            import jax

            jax.block_until_ready(out)
            obs.memband.emit_memory_gauges(loop="sa_sharded.chunk")
        return (out[0], *out[2:])

    def still_active(st):
        return bool(np.asarray(st[6])[:R].any())

    def snapshot(st):
        full = {k: np.asarray(v)[:R] for k, v in zip(fields[1:], st[1:])}
        full["s"] = extract_s(st[0])            # unpadded/global state
        return full

    if ckpt is None:
        while still_active(state):              # one chunk runs to completion
            state = advance(state)
    else:
        state = ckpt.drive(
            state, advance=advance, active=still_active, payload=snapshot
        )

    s_final = extract_s(state[0])
    # same arithmetic as the unsharded solver's mag_reached
    # graftlint: disable-next-line=GD004  host observable, exact sum
    mag = (s_final.astype(np.float64).sum(axis=1) / n).astype(np_dt)
    return SAResult(
        s=s_final,
        mag_reached=mag,
        num_steps=np.asarray(state[4])[:R],
        m_final=np.asarray(state[5])[:R],
    )


def _sa_sharded_streamed(
    graph, config, prep, *, mesh, node_axis, node_shards, dtype, np_dt,
    stream_chunks, hub_threshold, partition,
):
    """``layout='streamed'`` under ``sa_sharded``: the SAME serial
    Metropolis chain law as :func:`graphdyn.models.sa._sa_streamed`, with
    every candidate end-sum computed by the SHARDED out-of-core engine
    (:func:`graphdyn.parallel.stream.sharded_streamed_rollout`) — P
    prefetch lanes walking part-major chunk runs, boundary words + hub
    partials riding the halo collectives. Bit-parity with
    ``layout='padded'`` (sharded or not) is structural: the sharded
    streamed engine is bit-exact to the packed kernel, and the proposal
    draw + Metropolis/anneal arithmetic are literally the same shared
    helpers on the same dtype. Node labeling is the caller's throughout."""
    from graphdyn.graphs import partition_graph
    from graphdyn.models.sa import (
        draw_sa_proposal as _draw,
        metropolis_anneal_update as _update,
    )
    from graphdyn.ops.packed import WORD, pack_spins, unpack_spins
    from graphdyn.parallel.stream import sharded_streamed_rollout

    n = graph.n
    dyn = config.dynamics
    rollout = dyn.p + dyn.c - 1
    (R, seed, s0, a0, b0, proposals, uniforms,
     max_steps, stream_len, injected) = prep
    W = -(-R // WORD)
    if partition is None:
        partition = partition_graph(
            graph, node_shards, seed=seed or 0, hub_threshold=hub_threshold,
        )

    def end_sums(s_batch):
        out = sharded_streamed_rollout(
            graph, pack_spins(np.asarray(s_batch)), rollout,
            n_shards=node_shards, rule=dyn.rule, tie=dyn.tie,
            n_chunks=stream_chunks, hub_threshold=hub_threshold,
            partition=partition, mesh=mesh, node_axis=node_axis,
        )
        return jnp.asarray(unpack_spins(out, R).astype(np.int32).sum(axis=1))

    s = jnp.asarray(s0)
    a_v = jnp.asarray(a0.astype(np_dt))
    b_v = jnp.asarray(b0.astype(np_dt))
    dt = a_v.dtype
    key = jax.vmap(jax.random.PRNGKey)(
        np.arange(R, dtype=np.uint32) + np.uint32(seed))
    sum_end = end_sums(s0)
    m0 = sum_end.astype(dt) / n
    t = jnp.zeros((R,), jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    m_final = m0
    active = m0 < 1.0
    par_a = jnp.asarray(np_dt(config.par_a))
    par_b = jnp.asarray(np_dt(config.par_b))
    a_cap = jnp.asarray(np_dt(config.a_cap_frac * n))
    b_cap = jnp.asarray(np_dt(config.b_cap_frac * n))
    prop_j = jnp.asarray(proposals)
    unif_j = jnp.asarray(uniforms.astype(np_dt))
    ridx = jnp.arange(R, dtype=jnp.int32)
    # graftlint: disable-next-line=GD015  streamed layout: state pages through host RAM between proposals, so the chain is host-stepped by design — the per-step readback IS the chunk boundary; layout='padded' keeps the fused on-device annealer
    while bool(jnp.any(active)):
        i, u = _draw(
            key, t, prop_j, unif_j,
            injected=injected, stream_len=stream_len, n=n, dt=dt,
        )
        s_i = s[ridx, i].astype(jnp.int32)
        s_flip = s.at[ridx, i].set((-s_i).astype(jnp.int8))
        sum_end_flip = end_sums(s_flip)
        do, sum_end, a_v, b_v, t, m_final, active = _update(
            active, a_v, b_v, t, m_final, sum_end, sum_end_flip, s_i, u,
            par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
            max_steps=max_steps, n=n,
        )
        s = jnp.where(do[:, None], s_flip, s)
    s_final = np.asarray(s)
    mag = s_final.astype(np.float64).sum(axis=1) / n  # graftlint: disable=GD004  host observable, exact sum
    return SAResult(
        s=s_final,
        mag_reached=mag.astype(np_dt),
        num_steps=np.asarray(t),
        m_final=np.asarray(m_final),
    )
