"""Full multi-chip SA solver: the consensus-stop loop over a device mesh.

Round-2 shipped only the sharded loop *body*
(:func:`graphdyn.parallel.sharded.make_sharded_sa_step`); this module wraps
it into the reference's complete solver semantics (`SA_RRG.py:58-88`): the
Metropolis accept, per-step annealing with caps, the stop-when-consensus
test, the ``2n³``-step timeout sentinel ``m_final=2`` (`SA_RRG.py:84`), and
per-replica freezing — all inside ONE jitted ``lax.while_loop`` under
``shard_map``, with replicas (× the temperature ladder) sharded over the
mesh's ``replica`` axis and the node axis of giant graphs sharded over
``node`` (one tiled int8 ``all_gather`` per synchronous rollout step; psum
for the pad-free Σs_end).

Semantics are *identical* to the unsharded solver (`graphdyn.models.sa`):
the same PRNG derivation (fold_in by step count, split, randint/uniform) and
the same injected-stream mode, so the CPU-mesh equivalence test can require
bit-equal spins/steps/sentinels, not just statistical agreement.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from graphdyn.config import SAConfig
from graphdyn.models.sa import (
    SAResult,
    draw_sa_proposal,
    metropolis_anneal_update,
    prepare_sa_inputs,
)
from graphdyn.ops.dynamics import rule_coefficients
from graphdyn.parallel.sharded import (
    _local_step,
    _masked_block_sum,
    _real_mask,
    pad_nodes,
    place_sharded,
)


class _State(NamedTuple):
    s: jnp.ndarray         # int8[Rl, n_block] — this shard's spin block
    sum_end: jnp.ndarray   # int32[Rl] — Σ s_end of current config (global)
    a: jnp.ndarray         # f[Rl]
    b: jnp.ndarray         # f[Rl]
    t: jnp.ndarray         # int[Rl]
    m_final: jnp.ndarray   # f[Rl]
    active: jnp.ndarray    # bool[Rl]
    key: jnp.ndarray       # per-replica PRNG key
    live: jnp.ndarray      # int32 scalar — mesh-wide count of active shards


@functools.lru_cache(maxsize=64)
def make_sharded_sa_solver(
    mesh: Mesh,
    *,
    n_real: int,
    rollout_steps: int,
    max_steps: int,
    rule: str = "majority",
    tie: str = "stay",
    injected: bool = False,
    stream_len: int = 1,
    n_real_replicas: int | None = None,
    replica_axis: str = "replica",
    node_axis: str = "node",
):
    """Build the jitted sharded solver
    ``f(nbr, s0, key, a0, b0, par_a, par_b, a_cap, b_cap, proposals,
    uniforms) -> (s, mag, num_steps, m_final)`` with ``s0`` sharded
    ``P(replica, node)`` and per-replica vectors ``P(replica)``.

    ``n_real_replicas``: replicas with global index ≥ this are shard padding
    and start inactive — they must not keep the mesh loop alive (an all-+1
    pad row is at consensus under majority dynamics, but not under e.g.
    ``rule='minority'``)."""
    R_coef, C_coef = rule_coefficients(rule, tie)

    def solve(nbr_local, s0_local, key0, a0, b0,
              par_a, par_b, a_cap, b_cap, proposals, uniforms):
        Rl, n_block = s0_local.shape
        dt = a0.dtype
        node_idx = lax.axis_index(node_axis)
        mask = _real_mask(node_axis, n_block, n_real)
        rep_gidx = lax.axis_index(replica_axis) * Rl + jnp.arange(Rl)
        real_replica = (
            rep_gidx < n_real_replicas
            if n_real_replicas is not None
            else jnp.ones((Rl,), bool)
        )

        def rollout(s_loc):
            def rbody(_, s):
                s_full = lax.all_gather(s, node_axis, axis=1, tiled=True)
                return _local_step(nbr_local, s_full, s, mask, R_coef, C_coef)

            return lax.fori_loop(0, rollout_steps, rbody, s_loc)

        def end_sum(s_loc):
            return lax.psum(_masked_block_sum(rollout(s_loc), mask), node_axis)

        sum_end0 = end_sum(s0_local)
        m0 = sum_end0.astype(dt) / n_real
        active0 = (m0 < 1.0) & real_replica
        live0 = lax.psum(jnp.any(active0).astype(jnp.int32), replica_axis)

        def cond(st: _State):
            return st.live > 0

        def body(st: _State):
            # identical draw to the unsharded `_sa_run` (shared helper):
            # replicated keys make every node shard draw the same (i, u)
            i, u = draw_sa_proposal(
                st.key, st.t, proposals, uniforms,
                injected=injected, stream_len=stream_len, n=n_real, dt=dt,
            )

            # flip proposal i on its owning node shard
            local_i = i - node_idx * n_block
            owned = (local_i >= 0) & (local_i < n_block)
            li = jnp.clip(local_i, 0, n_block - 1)
            ridx = jnp.arange(Rl)
            s_i_local = st.s[ridx, li].astype(jnp.int32)
            flipped = st.s.at[ridx, li].set((-s_i_local).astype(jnp.int8))
            s_flip = jnp.where(owned[:, None], flipped, st.s)
            s_i = lax.psum(jnp.where(owned, s_i_local, 0), node_axis)

            sum_end_flip = end_sum(s_flip)

            do, sum_end_new, a_new, b_new, t_new, m_final, active = (
                metropolis_anneal_update(
                    st.active, st.a, st.b, st.t, st.m_final,
                    st.sum_end, sum_end_flip, s_i, u,
                    par_a=par_a, par_b=par_b, a_cap=a_cap, b_cap=b_cap,
                    max_steps=max_steps, n=n_real,
                )
            )
            s_new = jnp.where(do[:, None], s_flip, st.s)
            live = lax.psum(jnp.any(active).astype(jnp.int32), replica_axis)
            return _State(
                s_new, sum_end_new, a_new, b_new, t_new, m_final, active,
                st.key, live,
            )

        state0 = _State(
            s0_local, sum_end0, a0, b0,
            jnp.zeros(
                a0.shape, jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
            ),
            m0, active0, key0, live0,
        )
        out = lax.while_loop(cond, body, state0)
        mag = lax.psum(_masked_block_sum(out.s, mask), node_axis).astype(dt) / n_real
        return out.s, mag, out.t, out.m_final

    f = shard_map(
        solve,
        mesh=mesh,
        in_specs=(
            P(node_axis, None),            # nbr
            P(replica_axis, node_axis),    # s0
            P(replica_axis),               # key
            P(replica_axis),               # a0
            P(replica_axis),               # b0
            P(), P(), P(), P(),            # par_a, par_b, a_cap, b_cap
            P(replica_axis, None),         # proposals
            P(replica_axis, None),         # uniforms
        ),
        out_specs=(
            P(replica_axis, node_axis),
            P(replica_axis),
            P(replica_axis),
            P(replica_axis),
        ),
        check_rep=False,
    )
    return jax.jit(f)


def sa_sharded(
    graph,
    config: SAConfig | None = None,
    *,
    mesh: Mesh,
    n_replicas: int | None = None,
    seed: int | None = None,
    s0: np.ndarray | None = None,
    a0: np.ndarray | float | None = None,
    b0: np.ndarray | float | None = None,
    proposals: np.ndarray | None = None,
    uniforms: np.ndarray | None = None,
    max_steps: int | None = None,
    dtype=jnp.float32,
    replica_axis: str = "replica",
    node_axis: str = "node",
) -> SAResult:
    """Run batched SA chains to completion over a device mesh.

    The multi-chip counterpart of
    :func:`graphdyn.models.sa.simulated_annealing` (same API axes:
    per-replica ``a0``/``b0`` carry the temperature ladder, injected
    ``proposals``/``uniforms`` enable bitwise parity testing). Replicas pad
    up to the replica-axis size with already-converged all-+1 dummies; the
    node axis pads via :func:`pad_nodes`. Results are sliced back to the
    caller's shapes.
    """
    config = config or SAConfig()
    n = graph.n
    dyn = config.dynamics
    prep = prepare_sa_inputs(
        graph, config, n_replicas=n_replicas, seed=seed, s0=s0, a0=a0, b0=b0,
        proposals=proposals, uniforms=uniforms, max_steps=max_steps,
    )
    (R, seed, s0, a0, b0, proposals, uniforms,
     max_steps, stream_len, injected) = prep

    rep_shards = int(mesh.shape[replica_axis])
    node_shards = int(mesh.shape[node_axis])

    # replica padding: all-+1 rows are at consensus (m0 == 1) and freeze on
    # entry — they do no work and are sliced off below
    R_pad = (-R) % rep_shards
    if R_pad:
        s0 = np.concatenate([s0, np.ones((R_pad, n), np.int8)])
        a0 = np.concatenate([a0, np.ones(R_pad)])
        b0 = np.concatenate([b0, np.ones(R_pad)])
        proposals = np.concatenate([proposals, np.zeros((R_pad, stream_len), np.int32)])
        uniforms = np.concatenate([uniforms, np.zeros((R_pad, stream_len))])
    Rtot = R + R_pad

    nbr_pad, n_pad = pad_nodes(graph, node_shards)
    # padded node columns: frozen +1 spins, excluded from all masked sums
    s0_pad = np.concatenate(
        [s0, np.ones((Rtot, n_pad - n), np.int8)], axis=1
    )

    np_dt = np.float32 if dtype == jnp.float32 else np.float64
    keys = jax.vmap(jax.random.PRNGKey)(
        np.arange(Rtot, dtype=np.uint32) + np.uint32(seed)
    )

    solver = make_sharded_sa_solver(
        mesh,
        n_real=n,
        rollout_steps=dyn.p + dyn.c - 1,
        max_steps=max_steps,
        rule=dyn.rule,
        tie=dyn.tie,
        injected=injected,
        stream_len=stream_len,
        n_real_replicas=R,
        replica_axis=replica_axis,
        node_axis=node_axis,
    )
    s, mag, t, m_final = solver(
        place_sharded(mesh, jnp.asarray(nbr_pad), P(node_axis, None)),
        place_sharded(mesh, jnp.asarray(s0_pad), P(replica_axis, node_axis)),
        place_sharded(mesh, keys, P(replica_axis)),
        place_sharded(mesh, jnp.asarray(a0.astype(np_dt)), P(replica_axis)),
        place_sharded(mesh, jnp.asarray(b0.astype(np_dt)), P(replica_axis)),
        jnp.asarray(np_dt(config.par_a)),
        jnp.asarray(np_dt(config.par_b)),
        jnp.asarray(np_dt(config.a_cap_frac * n)),
        jnp.asarray(np_dt(config.b_cap_frac * n)),
        place_sharded(mesh, jnp.asarray(proposals), P(replica_axis, None)),
        place_sharded(mesh, jnp.asarray(uniforms.astype(np_dt)), P(replica_axis, None)),
    )
    return SAResult(
        s=np.asarray(s)[:R, :n],
        mag_reached=np.asarray(mag)[:R],
        num_steps=np.asarray(t)[:R],
        m_final=np.asarray(m_final)[:R],
    )
