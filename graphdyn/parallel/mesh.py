"""Mesh construction and sharding helpers.

The reference is single-process single-device (SURVEY.md §5.8); here the
replica × temperature ensemble axes shard over a ``jax.sharding.Mesh`` and
observables reduce over ICI with psum/pmean. Works identically on real TPU
meshes and on CPU-simulated meshes (``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(shape: tuple[int, ...] | None = None, axis_names: tuple[str, ...] = ("replica",)) -> Mesh:
    """Build a mesh over all visible devices. Default: 1-D 'replica' axis."""
    devices = np.array(jax.devices())
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), axis_names)


def shard_batch(mesh: Mesh, x, axis: str = "replica"):
    """Place array with its leading axis sharded over ``axis``."""
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, x):
    """Place array fully replicated over the mesh."""
    spec = P(*([None] * np.ndim(x)))
    return jax.device_put(x, NamedSharding(mesh, spec))
