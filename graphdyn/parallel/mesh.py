"""Mesh construction and sharding helpers.

The reference is single-process single-device (SURVEY.md §5.8); here the
replica × temperature ensemble axes shard over a ``jax.sharding.Mesh`` and
observables reduce over ICI with psum/pmean. Works identically on real TPU
meshes and on CPU-simulated meshes (``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("replica",),
    devices=None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all visible). 1-D 'replica'
    axis by default."""
    devices = np.array(jax.devices() if devices is None else devices)
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    need = int(np.prod(shape))
    return Mesh(devices[:need].reshape(shape), axis_names)


def device_pool(n_devices: int):
    """Return at least ``n_devices`` devices, preferring the default platform
    and falling back to the (possibly simulated) CPU host platform — covers
    environments where a plugin pins the default platform while multi-chip
    tests run on ``--xla_force_host_platform_device_count`` CPU meshes."""
    devices = jax.devices()
    if len(devices) < n_devices:
        try:
            devices = jax.devices("cpu")
        except RuntimeError:
            pass
    if len(devices) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have {len(devices)}")
    return devices[:n_devices]


def shard_batch(mesh: Mesh, x, axis: str = "replica"):
    """Place array with its leading axis sharded over ``axis``."""
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, x):
    """Place array fully replicated over the mesh."""
    spec = P(*([None] * np.ndim(x)))
    return jax.device_put(x, NamedSharding(mesh, spec))
