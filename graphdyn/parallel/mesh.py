"""Mesh construction and sharding helpers.

The reference is single-process single-device (SURVEY.md §5.8); here the
replica × temperature ensemble axes shard over a ``jax.sharding.Mesh`` and
observables reduce over ICI with psum/pmean. Works identically on real TPU
meshes and on CPU-simulated meshes (``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                  # jax ≥ 0.6: top-level export, check_vma
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:                   # jax 0.4.x: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-portable ``shard_map``: the new-API surface (``check_vma``)
    mapped onto whichever implementation this jax ships (the 0.4.x
    experimental one calls the same flag ``check_rep``). Every shard_map in
    graphdyn goes through here so an API move breaks one line, not five
    call sites."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("replica",),
    devices=None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all visible). 1-D 'replica'
    axis by default."""
    devices = np.array(jax.devices() if devices is None else devices)
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    need = int(np.prod(shape))
    return Mesh(devices[:need].reshape(shape), axis_names)


def init_multihost(retry_deadline_s: float = 60.0, **kwargs) -> int:
    """Initialize JAX's multi-host runtime (one controller process per host)
    and return ``jax.process_count()``.

    This is the TPU-native analogue of an NCCL/MPI world setup: on TPU pods
    the coordinator/rank/world-size resolve automatically from the
    environment, so a bare ``init_multihost()`` works under any standard
    launcher; pass ``coordinator_address=/num_processes=/process_id=`` to
    override (forwarded to ``jax.distributed.initialize``). Idempotent and a
    no-op for single-process runs, so drivers can call it unconditionally.
    After it returns, ``jax.devices()`` is the GLOBAL device set and
    :func:`make_mesh` spans every host.

    Axis placement guidance (ARCHITECTURE.md "Parallelism model"): keep
    node/edge-sharded axes inside one host (their all_gather/psum ride ICI);
    put replica/ensemble axes across hosts — replica sharding is
    communication-free in the solvers (replica-major unions, per-device SA
    chains), so DCN only ever carries the scalar per-sweep stop-test psum.
    :func:`make_hybrid_mesh` builds exactly that layout.

    Resilience: with multi-host intent (explicit kwargs, or a coordinator
    detectable in the environment), a coordinator that is not up yet is a
    *race*, not an error — the connection retries with exponential backoff
    until ``retry_deadline_s`` (fault site ``multihost.init`` simulates the
    not-yet-up coordinator) before the failure surfaces.
    """
    import jax.distributed

    import os

    from graphdyn.resilience import RetryPolicy, retry
    from graphdyn.resilience import faults as _faults

    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is None:
        # jax 0.4.x has no public probe; the live client sits on the
        # private distributed state (None until initialize() succeeds)
        from jax._src import distributed as _dist

        def is_init():
            state = getattr(_dist, "global_state", None)
            return getattr(state, "client", None) is not None

    if not is_init():
        # Benign single-process cases: no coordinator config to form a
        # world from (ValueError), or the XLA backend is already up —
        # e.g. a driver that used jax before opting into multi-host
        # (RuntimeError). Swallowing either on a REAL pod would make N
        # hosts silently run N duplicate single-host jobs, so surface
        # the failure whenever multi-host intent is stated (kwargs) or
        # a multi-host environment is detectable.
        detected = any(
            os.environ.get(v)
            for v in (
                "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS",
            )
        # single-host TPU VMs also set TPU_WORKER_HOSTNAMES (one
        # entry); only a multi-worker list signals a pod
        ) or ("," in os.environ.get("TPU_WORKER_HOSTNAMES", ""))

        def connect():
            _faults.maybe_fail("multihost.init")
            jax.distributed.initialize(**kwargs)

        def transient(e: BaseException) -> bool:
            # only unavailability is worth waiting out; a deterministic
            # RuntimeError (e.g. "initialize must be called before any JAX
            # computations") must surface on the FIRST attempt
            if isinstance(e, _faults.InjectedUnavailable):
                return True
            msg = str(e).lower()
            return any(t in msg for t in (
                "unavailable", "connection refused", "failed to connect",
                "deadline", "timed out", "timeout",
            ))

        if kwargs or detected:
            # multi-host intent: a not-yet-listening coordinator at job
            # start is the common race on preemptible slices — retry with
            # a deadline instead of crashing the whole pod job at t=0.
            # tries=64 is a non-binding ceiling; retry()'s deadline_s stops
            # as soon as the next backoff sleep would cross the deadline,
            # so retry_deadline_s is the single binding limit.
            # jitter=True + the rank in the site key: every rank of the pod
            # hits the same not-yet-up coordinator, and synchronized
            # exponential backoff would re-stampede it at t=0.5, 1, 2, …;
            # seeded full-jitter de-correlates the ranks deterministically
            rank = kwargs.get("process_id",
                              os.environ.get("JAX_PROCESS_ID", os.getpid()))
            retry(
                connect,
                policy=RetryPolicy(tries=64, base_delay_s=0.5,
                                   max_delay_s=8.0, jitter=True),
                retry_on=(RuntimeError,),
                retry_if=transient,
                what=f"jax.distributed.initialize(rank {rank})",
                deadline_s=retry_deadline_s,
            )
        else:
            try:
                connect()
            except (ValueError, RuntimeError):
                pass
    return jax.process_count()


def make_hybrid_mesh(
    ici_shape: tuple[int, ...],
    axis_names: tuple[str, ...],
    *,
    dcn_axis: str | None = None,
    devices=None,
) -> Mesh:
    """Mesh spanning all hosts with ``dcn_axis`` (default: the first axis)
    split across hosts over DCN and the remaining axes inside each host over
    ICI. ``ici_shape`` gives the per-host shape of the non-DCN axes; the DCN
    axis size is ``jax.process_count()``.

    Single-process runs degrade to an ordinary :func:`make_mesh` with a
    size-1 DCN axis, so the same program text runs on a laptop, one TPU
    host, or a multi-host pod slice. ``devices`` overrides the local device
    pool in that single-process fallback (e.g. ``device_pool(8)`` on a
    plugin-pinned machine whose simulated mesh lives on the CPU platform);
    multi-process, device placement is topology-driven
    (``mesh_utils.create_hybrid_device_mesh``) and ``devices`` must be None.

    Scope note: the *jitted chunk programs* of the solvers are SPMD-correct
    on such a mesh, but the convenience drivers (`sa_sharded`,
    `hpr_solve_batch(mesh=...)`) do host-side fetch/persist between chunks
    and are single-controller today — on a pod, drive the chunk programs
    directly (or gather results with `jax.experimental.multihost_utils`).
    """
    if dcn_axis is None:
        dcn_axis = axis_names[0]
    if dcn_axis not in axis_names:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in axis_names {axis_names}")
    k = axis_names.index(dcn_axis)
    ici_axes = [a for a in axis_names if a != dcn_axis]
    if len(ici_shape) != len(ici_axes):
        raise ValueError(
            f"ici_shape {ici_shape} must give one size per non-DCN axis "
            f"{tuple(ici_axes)}"
        )
    n_proc = jax.process_count()
    need = int(np.prod(ici_shape))
    if devices is not None:
        if n_proc > 1:
            raise ValueError(
                "devices= override is single-process only (multi-process "
                "placement is topology-driven)"
            )
        pool = list(devices)
    elif n_proc > 1:
        pool = jax.local_devices()
    else:
        pool = jax.local_devices()
        if len(pool) != need:
            # same platform fallback as device_pool (plugin-pinned default
            # platform vs a simulated CPU mesh) — but never a slice: the
            # exact-fit rule below stays meaningful
            try:
                cpu = jax.devices("cpu")
            except RuntimeError:
                cpu = []
            if len(cpu) == need:
                pool = cpu
    n_local = len(pool)
    # the multi-process path (create_hybrid_device_mesh) requires the
    # per-host ICI shape to cover the local devices exactly; enforcing the
    # same fit single-process keeps 'validated on a laptop' meaning 'runs
    # on the pod' instead of failing only at deployment
    if need != n_local:
        raise ValueError(
            f"prod(ici_shape)={need} must equal the "
            f"per-host device count {n_local}"
        )
    full_shape = list(ici_shape)
    full_shape.insert(k, n_proc)
    if n_proc == 1:
        return make_mesh(tuple(full_shape), axis_names, devices=pool)
    from jax.experimental import mesh_utils

    mesh_shape = list(ici_shape)
    mesh_shape.insert(k, 1)                      # per-host granule: ICI only
    dcn_shape = [1] * len(axis_names)
    dcn_shape[k] = n_proc
    devices = mesh_utils.create_hybrid_device_mesh(
        tuple(mesh_shape), tuple(dcn_shape)
    )
    return Mesh(devices, axis_names)


def device_pool(n_devices: int):
    """Return at least ``n_devices`` devices, preferring the default platform
    and falling back to the (possibly simulated) CPU host platform — covers
    environments where a plugin pins the default platform while multi-chip
    tests run on ``--xla_force_host_platform_device_count`` CPU meshes."""
    devices = jax.devices()
    if len(devices) < n_devices:
        try:
            devices = jax.devices("cpu")
        except RuntimeError:
            pass
    if len(devices) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have {len(devices)}")
    return devices[:n_devices]


def shard_batch(mesh: Mesh, x, axis: str = "replica"):
    """Place array with its leading axis sharded over ``axis``."""
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, x):
    """Place array fully replicated over the mesh."""
    spec = P(*([None] * np.ndim(x)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def shard_stack(mesh: Mesh, x, axis: str = "group"):
    """Place one array of an ensemble-pipeline stack: leading ``[G, ...]``
    axes shard over ``axis`` (repetitions — SA/HPr groups — and entropy
    grid CELLS are independent, so the partitioned group program is
    communication-free except its stop test); scalars replicate. The
    placement helper the grouped solvers use to consume the stacked layout
    on a mesh — ``run_sa_group(mesh=...)`` shards repetitions,
    ``EntropyCellExec(mesh=..., cell_axis=...)`` shards the entropy cell
    axis — results are bit-identical to the unsharded program (tested)."""
    return shard_batch(mesh, x, axis) if np.ndim(x) else replicate(mesh, x)
