"""Sharded out-of-core streaming — the chunk walk × halo exchange engine.

PR 19's streamed rollout (:mod:`graphdyn.ops.streamed`) made
larger-than-HBM graphs runnable on ONE device: host-resident chunks page
through a double-buffered prefetch lane while the device steps the
active chunk. PR 11/18's halo shard (:mod:`graphdyn.parallel.halo`) made
resident graphs P-way wide: each shard owns a node segment and ships
only boundary words per step. This module composes the two (ROADMAP
item 3's open remainder): each of P shards owns a **part-major
contiguous run of chunks** — its owned non-hub nodes in degree-ascending
order, split exactly like the single-device plan — and walks them with
its OWN :class:`graphdyn.pipeline.prefetch.HostPrefetcher` lane, so both
aggregate HBM and aggregate host→device gather bandwidth scale with the
mesh. The per-step cross-shard traffic rides the halo machinery
unchanged: ghost boundary words travel as one ``ppermute`` slab per
schedule offset and hub partial popcounts ride the bit-plane ring
allreduce — the same O(P·hubs) discipline the sparse Ising layouts of
PAPERS.md arXiv:2110.02481 motivate, and the same boundary-overlap move
arXiv:1903.11714's checkerboard halo makes when the lattice outgrows one
core.

Exactness is structural and **layout-independent**: every owned node
steps through :func:`graphdyn.ops.streamed._stream_chunk_device` (the
fingerprinted single-device chunk program) against pre-update neighbor
state, and every hub through the exact ring-combined integer popcount of
:func:`graphdyn.parallel.halo.make_halo_rollout` — so results are
bit-exact to the single-device streamed kernel, to the resident halo
kernel, and across ANY shard count or partition. That layout
independence is what makes cross-shard-count resume trivial to prove:
the checkpoint payload is the GLOBAL packed state, so a preempted
sharded run requeued onto a different P replays bit-exactly.

On top rides **churn-driven repartition**: when a
:class:`~graphdyn.ops.streamed.ChurnBatch` crosses a node's degree over
the ``hub_threshold``, the node is promoted to a vertex-cut replicated
hub at the chunk boundary (fallen hubs are demoted to the part owning
most of their neighbors), only the touched chunks are rebuilt (a chunk
whose support rows map to the same local rows under the new tables is
reused as-is), and the decision is journaled (``stream.repartition``
next to ``stream.churn``) so a preempted run — even requeued onto a
different shard count — replays the churn + repartition sequence
bit-exactly from the journal alone.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from graphdyn import obs
from graphdyn.graphs import Graph, Partition, graph_from_edges, partition_graph
from graphdyn.ops.bucketed import (
    UNROLL_MAX,
    _pack_lanes,
    _wide_bucket_counts,
)
from graphdyn.ops.dynamics import Rule, TieBreak
from graphdyn.ops.packed import (
    _FULL,
    _compare_planes,
    _csa_add_one,
    _rule_tie_combine,
)
from graphdyn.ops.streamed import (
    ChurnBatch,
    _Adjacency,
    _adjacency_lists,
    _pow2_width,
    _split_stream_groups,
    _stream_chunk_device,
    chunk_device_bytes,
)
from graphdyn.parallel.halo import (
    HaloTables,
    build_halo_tables,
    exchange_perms,
    gather_state,
    scatter_state,
)
from graphdyn.parallel.mesh import device_pool, make_mesh, shard_map

__all__ = [
    "ShardChunk", "ShardStreamPlan", "build_shard_stream_plan",
    "make_stream_exchange", "lower_stream_exchange",
    "sharded_streamed_rollout", "shard_plan_device_bytes",
]


class ShardChunk(NamedTuple):
    """One host-resident chunk of ONE shard's owned (non-hub) nodes.

    The same slab discipline as :class:`graphdyn.ops.streamed.StreamChunk`
    with LOCAL halo-layout rows in place of global ids: the slab gathers
    the shard-local state rows ``gids`` (owned rows ∪ neighbor
    owned/ghost/hub rows, sorted) plus one appended zero row at slab
    index ``M``; ``nbr_loc`` indexes the slab. ``sup_global``/``sup_rows``
    record which global id each referenced local row belonged to when the
    chunk was built — the reuse test after a table rebuild (a chunk whose
    support maps to the identical local rows needs no rebuild).

    Attributes:
      nodes:      int64[C] owned global node ids.
      rows:       int64[C] owned local rows in the shard's halo layout.
      sup_global: int64[M] global ids the slab reads (sorted by id).
      sup_rows:   int64[M] local row of each support id at build time.
      gids:       int64[M] slab gather rows (= sorted ``sup_rows``).
      nbr_loc:    int32[C, w] slab-local neighbor table, ghost = M.
      deg:        int32[C] true degrees of the owned nodes.
      self_loc:   int32[C] slab row of each owned node.
    """

    nodes: np.ndarray
    rows: np.ndarray
    sup_global: np.ndarray
    sup_rows: np.ndarray
    gids: np.ndarray
    nbr_loc: np.ndarray
    deg: np.ndarray
    self_loc: np.ndarray

    @property
    def C(self) -> int:
        return self.nodes.size

    @property
    def M(self) -> int:
        return self.gids.size

    @property
    def width(self) -> int:
        return self.nbr_loc.shape[1]


class ShardStreamPlan(NamedTuple):
    """The sharded chunked layout: shard ``p`` owns the part-major
    contiguous chunk run ``shard_chunks[p]`` over the halo layout of
    ``tables``. Built by :func:`build_shard_stream_plan` (or
    ``build_stream_plan(partition=...)``); chunks are rebuilt
    incrementally when churn mutates the adjacency."""

    n: int
    tables: HaloTables
    shard_chunks: tuple

    @property
    def P(self) -> int:
        return len(self.shard_chunks)

    @property
    def K(self) -> int:
        """Total chunks across all shards."""
        return sum(len(cs) for cs in self.shard_chunks)


def _shard_lut(tables: HaloTables, p: int) -> np.ndarray:
    """Global id -> local state row of shard ``p`` (the halo layout's
    owned/ghost/hub rows; unreachable ids map to the zero row, which no
    built chunk ever references because every neighbor of an owned node
    is owned, ghost, or hub by construction of the tables)."""
    lut = np.full(tables.n + 1, tables.zero_row, np.int64)
    cnt = int(tables.counts[p])
    lut[tables.owned_global[p, :cnt]] = np.arange(cnt)
    gcnt = int(tables.ghost_counts[p])
    if gcnt:
        lut[tables.ghost_global[p, :gcnt]] = (
            tables.n_local_max + np.arange(gcnt)
        )
    if tables.n_hubs:
        lut[tables.hub_global] = tables.hub_row0 + np.arange(tables.n_hubs)
    return lut


def _build_shard_chunk(nodes: np.ndarray, adj: list[np.ndarray],
                       lut: np.ndarray) -> ShardChunk:
    """Materialize one shard chunk's slab-local tables from the adjacency
    and the shard's global->local row lut."""
    nodes = np.asarray(nodes, np.int64)
    degs = np.array([adj[i].size for i in nodes], np.int64)
    width = _pow2_width(int(degs.max()) if nodes.size else 0)
    nbr_cat = (np.concatenate([adj[i] for i in nodes])
               if nodes.size else np.empty(0, np.int64))
    sup_global = np.unique(np.concatenate([nodes, nbr_cat]))
    sup_rows = lut[sup_global]
    gids = np.sort(sup_rows)
    M = gids.size
    rows = lut[nodes]
    self_loc = np.searchsorted(gids, rows)
    nbr_loc = np.full((nodes.size, width), M, np.int64)
    if nbr_cat.size:
        loc_cat = np.searchsorted(gids, lut[nbr_cat])
        pos = 0
        for r, d in enumerate(degs):
            nbr_loc[r, :d] = loc_cat[pos:pos + d]
            pos += d
    return ShardChunk(
        nodes=nodes, rows=rows,
        sup_global=sup_global, sup_rows=sup_rows, gids=gids,
        nbr_loc=nbr_loc.astype(np.int32),
        deg=degs.astype(np.int32),
        self_loc=self_loc.astype(np.int32),
    )


def _shard_orders(graph_deg: np.ndarray, partition: Partition) -> list:
    """Per-shard owned nodes, degree-ascending (stable) — the per-shard
    restriction of the single-device plan's degree_buckets walk, so each
    chunk's power-of-two padded width stays tight."""
    out = []
    for p in range(partition.P):
        seg = partition.order[
            partition.offsets[p]:partition.offsets[p + 1]
        ]
        out.append(seg[np.argsort(graph_deg[seg], kind="stable")])
    return out


def build_shard_stream_plan(graph: Graph, *, W: int, partition: Partition,
                            n_chunks: int | None = None,
                            device_budget_bytes: int | None = None,
                            adj: list[np.ndarray] | None = None,
                            tables: HaloTables | None = None
                            ) -> ShardStreamPlan:
    """Build the sharded streamed plan: shard ``p`` owns a part-major
    contiguous run of chunks over its owned non-hub nodes
    (degree-ascending), hubs stay vertex-cut replicated in the halo
    layout. ``n_chunks`` / ``device_budget_bytes`` apply PER SHARD — the
    budget is each device's, and the shards stream concurrently."""
    if adj is None:
        adj = _adjacency_lists(graph)
    if tables is None:
        tables = build_halo_tables(graph, partition)
    shard_chunks = []
    for p, order in enumerate(_shard_orders(graph.deg, partition)):
        nc = (min(n_chunks, max(order.size, 1))
              if n_chunks is not None else None)
        groups = _split_stream_groups(
            order, adj, W=W, n_chunks=nc,
            device_budget_bytes=device_budget_bytes,
        )
        lut = _shard_lut(tables, p)
        shard_chunks.append(tuple(
            _build_shard_chunk(g, adj, lut) for g in groups
        ))
    return ShardStreamPlan(
        n=graph.n, tables=tables, shard_chunks=tuple(shard_chunks),
    )


def shard_plan_device_bytes(plan: ShardStreamPlan, W: int) -> int:
    """Peak modeled device bytes of the WORST shard: its two largest
    chunks resident at once under double-buffered prefetch — the number
    the per-shard ``streamed_state_bytes`` admission model prices."""
    worst = 0
    for chunks in plan.shard_chunks:
        per = sorted(
            (chunk_device_bytes(c.C, c.M, c.width, W) for c in chunks),
            reverse=True,
        )
        mine = sum(per[:2]) if len(per) > 1 else (per[0] if per else 0)
        worst = max(worst, mine)
    return worst


# ---------------------------------------------------------------------------
# the per-step exchange program — the graftcheck-fingerprinted composition
# ---------------------------------------------------------------------------


def make_stream_exchange(mesh: Mesh, tables: HaloTables, *,
                         rule: str = "majority", tie: str = "stay",
                         node_axis: str = "node"):
    """Build the jitted per-step exchange program of the composed engine:
    ``f(hub_slab, prev_h, *send_slabs) -> (out_h, *recv_slabs)`` over
    ``mesh``'s ``node_axis`` (size = tables.P); hubless tables drop the
    leading pair on both sides.

    The host chunk walk stays out-of-core — only the boundary slabs and
    the gathered hub neighbor slab ever reach the device. The body is
    the halo kernel's collective schedule verbatim: each shard's hub
    partial popcounts (CSA bit-planes for narrow hub slices, the
    segmented integer counts of the wide bucketed path otherwise) ride
    the (P-1)-round bit-plane ripple-carry ring, the comparator
    thresholds come from the ORIGINAL hub degrees, and each boundary
    slab ships as one ``lax.ppermute`` per schedule offset — no
    ``all_gather`` exists in the shard-mapped body (graftlint GD013);
    ``prev_h`` (the hub carry) is donated."""
    rule = Rule(rule)
    tie = TieBreak(tie)
    Pn = tables.P
    H = tables.n_hubs
    k = len(tables.schedule)
    if H == 0 and k == 0:
        raise ValueError(
            "tables have no hubs and an empty exchange schedule — "
            "nothing to exchange (P=1, hubless: skip the program)"
        )
    perms = exchange_perms(tables)
    if H:
        hd_max = tables.hub_nbr_loc.shape[2]
        hd = tables.hub_deg.astype(np.int64)
        n_planes_hub = max(int(hd.max()).bit_length(), 1)
        thr_h = (hd // 2).astype(np.uint32)
        even_h = np.where(hd % 2 == 0, _FULL, np.uint32(0))[:, None]
        thr_bits_h = [
            np.where((thr_h >> b) & 1 == 1, _FULL, np.uint32(0))[:, None]
            for b in range(n_planes_hub)
        ]
        ring_perm = tuple((q, (q + 1) % Pn) for q in range(Pn))
        # the host pre-gathered the hub neighbor rows in hub_nbr_loc
        # order (pad slots carry the zero row's zeros), so the device
        # popcount runs the shared bucketed helpers over the identity
        # index — the same arithmetic as the resident halo kernel
        seg_idx = jnp.asarray(
            np.arange(H * hd_max, dtype=np.int32).reshape(H, hd_max)
        )

    def exch(*args):
        outs = []
        if H:
            hub_slab = args[0][0]           # [H*hd_max, W]
            prev_h = args[1][0]             # [H, W]
            sends = [a[0] for a in args[2:]]
            if hd_max <= UNROLL_MAX:
                slab3 = hub_slab.reshape(H, hd_max, hub_slab.shape[1])
                hpl = [
                    jnp.zeros((H, hub_slab.shape[1]), hub_slab.dtype)
                    for _ in range(n_planes_hub)
                ]
                for j in range(hd_max):
                    _csa_add_one(hpl, slab3[:, j, :])
            else:
                cnt = _wide_bucket_counts(hub_slab, seg_idx)
                hpl = [
                    _pack_lanes((cnt >> b) & 1)
                    for b in range(n_planes_hub)
                ]
            # ring-allreduce the partial counts: (P-1) ppermute rounds of
            # exact bit-plane ripple-carry addition (n_planes_hub bounds
            # the total, so no carry leaves the top plane); every shard
            # computes the identical total -> hub rows stay replicated
            acc, buf = hpl, hpl
            for _ in range(Pn - 1):
                buf = [
                    lax.ppermute(pl, node_axis, ring_perm) for pl in buf
                ]
                carry = jnp.zeros_like(acc[0])
                nxt = []
                for a, b in zip(acc, buf):
                    nxt.append(a ^ b ^ carry)
                    carry = (a & b) | (carry & (a ^ b))
                acc = nxt
            gt_h, eq_h = _compare_planes(acc, thr_bits_h)
            out_h = _rule_tie_combine(gt_h, eq_h & even_h, prev_h, rule, tie)
            outs.append(out_h[None])
        else:
            sends = [a[0] for a in args]
        for perm, s in zip(perms, sends):
            outs.append(lax.ppermute(s, node_axis, perm)[None])
        return tuple(outs)

    spec3 = P(node_axis, None, None)
    n_in = (2 if H else 0) + k
    n_out = (1 if H else 0) + k
    f = shard_map(
        exch,
        mesh=mesh,
        in_specs=(spec3,) * n_in,
        out_specs=(spec3,) * n_out,
        check_vma=False,
    )
    donate = (1,) if H else ()
    return jax.jit(f, donate_argnums=donate)


def lower_stream_exchange(mesh: Mesh, graph: Graph, partition: Partition, *,
                          W: int, rule: str = "majority", tie: str = "stay",
                          node_axis: str = "node"):
    """Lower (without executing) the composed engine's exchange program
    at this partition's shapes — the program
    :mod:`graphdyn.analysis.graftcheck` fingerprints for the
    ``streamed_halo`` ledger entry (the fingerprint pins the collective
    structure: the hub bit-plane ring + one ``ppermute`` slab per
    schedule offset, donated hub carry, and NO all-gather). Kept next to
    :func:`make_stream_exchange` so a refactor updates the fingerprinted
    surface in place. Returns a ``jax.stages.Lowered``."""
    tables = build_halo_tables(graph, partition)
    fn = make_stream_exchange(
        mesh, tables, rule=rule, tie=tie, node_axis=node_axis,
    )
    spec3 = NamedSharding(mesh, P(node_axis, None, None))
    Pn, H = tables.P, tables.n_hubs
    args = []
    if H:
        hd_max = tables.hub_nbr_loc.shape[2]
        args.append(jax.device_put(
            jnp.zeros((Pn, H * hd_max, W), jnp.uint32), spec3))
        args.append(jax.device_put(
            jnp.zeros((Pn, H, W), jnp.uint32), spec3))
    for (_, s_idx, _) in tables.schedule:
        args.append(jax.device_put(
            jnp.zeros((Pn, s_idx.shape[1], W), jnp.uint32), spec3))
    return fn.lower(*args)


# ---------------------------------------------------------------------------
# churn-driven repartition
# ---------------------------------------------------------------------------


def _graph_from_adj(adj: _Adjacency) -> Graph:
    """The current churned graph as a padded-table Graph (host)."""
    lists = adj.neighbor_lists()
    src = np.concatenate(
        [np.full(l.size, i, np.int64) for i, l in enumerate(lists)]
        or [np.empty(0, np.int64)]
    )
    dst = (np.concatenate(lists) if src.size
           else np.empty(0, np.int64))
    keep = src < dst
    edges = np.stack([src[keep], dst[keep]], axis=1)
    return graph_from_edges(adj.n, edges)


def _partition_from_part(cur: Graph, part_vec: np.ndarray,
                         hubs: np.ndarray, n_parts: int) -> Partition:
    """Rebuild a :class:`Partition` from an explicit part vector + hub
    set — the incremental-repartition path (promotions/demotions edit
    ``part_vec`` in place; non-hub ownership never moves, so the
    boundary/interior split is the only thing recomputed)."""
    n = cur.n
    e = cur.edges.astype(np.int64)
    is_boundary = np.zeros(n, bool)
    cut = 0
    if e.size:
        pu, pv = part_vec[e[:, 0]], part_vec[e[:, 1]]
        cross = (pu != pv) & (pu >= 0) & (pv >= 0)
        is_boundary[e[cross, 0]] = True
        is_boundary[e[cross, 1]] = True
        cut = int(cross.sum())
    pos = np.arange(n, dtype=np.int64)
    order = np.lexsort((pos, is_boundary, part_vec)).astype(np.int64)
    order = order[hubs.size:]
    counts = np.bincount(
        part_vec[part_vec >= 0], minlength=n_parts
    ).astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    bmask = is_boundary & (part_vec >= 0)
    interior = counts - np.bincount(
        part_vec[bmask], minlength=n_parts
    ).astype(np.int64)
    return Partition(
        part=part_vec.astype(np.int32),
        order=order,
        offsets=offsets,
        interior=interior,
        edge_cut=cut,
        hubs=np.sort(hubs).astype(np.int64) if hubs.size else None,
    )


def _demote_target(adj: _Adjacency, part_vec: np.ndarray, v: int) -> int:
    """The part a fallen hub lands on: the owner of most of its
    neighbors (ties -> lowest part id; isolated -> part 0) — a
    deterministic function of the journaled churn sequence, so replay
    re-derives the identical assignment."""
    owners = [int(part_vec[u]) for u in adj.neighbors_of(v)
              if part_vec[u] >= 0]
    if not owners:
        return 0
    cnt = Counter(owners)
    best = max(cnt.values())
    return min(p for p, c in cnt.items() if c == best)


def _replay_churn(jpath: str, t0: int, adj: _Adjacency) -> int:
    """Re-apply every journaled ``stream.churn`` batch with ``step < t0``
    to the adjacency — the sharded twin of the single-device
    journal-alone replay (:func:`graphdyn.ops.streamed
    ._replay_churn_from_journal`), without the chunk rebuild: the caller
    rebuilds the whole sharded plan from the replayed adjacency (the
    requeued shard count may differ — layout independence makes any
    partition of the replayed graph bit-exact)."""
    from graphdyn.obs.recorder import read_ledger

    try:
        events, _ = read_ledger(jpath)
    except (OSError, ValueError):
        events = []
    seen: set[tuple[int, int]] = set()
    batches = []
    for ev in events:
        if ev.get("ev") != "journal" or ev.get("op") != "stream.churn":
            continue
        key = (int(ev.get("step", -1)), int(ev.get("seq", -1)))
        if key in seen:
            continue
        seen.add(key)
        batches.append((key, ev.get("adds") or [], ev.get("drops") or []))
    applied = 0
    for (step, _), adds, drops in sorted(batches, key=lambda b: b[0]):
        if step >= t0:
            continue
        adj.apply(np.asarray(adds, np.int64).reshape(-1, 2),
                  np.asarray(drops, np.int64).reshape(-1, 2))
        applied += 1
    return applied


# ---------------------------------------------------------------------------
# the sharded streamed rollout driver
# ---------------------------------------------------------------------------


class _ShardStreamState(NamedTuple):
    loc: np.ndarray      # uint32[P, n_rows, W] per-shard halo layout
    t: int               # completed synchronous steps
    seq: int             # applied churn batches so far (journal cursor)


class _ShardEngine:
    """The mutable composed-engine environment: halo tables, per-shard
    chunk runs, the compiled exchange program (cached on the tables'
    content signature), and the incremental rebuild machinery."""

    def __init__(self, graph: Graph, adj: _Adjacency,
                 partition: Partition, *, W: int, rule: str, tie: str,
                 n_chunks: int | None, device_budget_bytes: int | None,
                 mesh: Mesh, node_axis: str):
        self.adj = adj
        self.n = graph.n
        self.W = W
        self.rule, self.tie = rule, tie
        self.n_chunks = n_chunks
        self.device_budget_bytes = device_budget_bytes
        self.mesh = mesh
        self.node_axis = node_axis
        self.Pn = partition.P
        self._exch_cache: dict = {}
        self.repartitions = 0
        self.chunks_rebuilt = 0
        # per-shard device of the node axis — each shard's chunk walk
        # stages its slabs onto ITS device, so the P prefetch lanes use
        # P independent host->device paths
        ax = list(mesh.axis_names).index(node_axis)
        devs = np.moveaxis(np.asarray(mesh.devices), ax, 0)
        devs = devs.reshape(devs.shape[0], -1)
        self.devices = [devs[p, 0] for p in range(devs.shape[0])]
        part_vec = partition.part.astype(np.int64).copy()
        hubs = (partition.hubs if partition.hubs is not None
                else np.empty(0, np.int64)).astype(np.int64)
        self.part_vec = part_vec
        self.hubset: set[int] = set(int(h) for h in hubs)
        self.tables = build_halo_tables(graph, partition)
        # mutable per-shard chunk membership (global ids): stable under
        # churn; promotions remove a node, demotions append to the
        # target shard's last chunk
        adj_lists = adj.neighbor_lists()
        self.chunk_nodes: list[list[np.ndarray]] = []
        for order in _shard_orders(graph.deg, partition):
            nc = (min(n_chunks, max(order.size, 1))
                  if n_chunks is not None else None)
            groups = _split_stream_groups(
                order, adj_lists, W=W, n_chunks=nc,
                device_budget_bytes=device_budget_bytes,
            )
            self.chunk_nodes.append([np.asarray(g, np.int64)
                                     for g in groups] or
                                    [np.empty(0, np.int64)])
        self.shard_chunks: list[list[ShardChunk]] = []
        for p in range(self.Pn):
            lut = _shard_lut(self.tables, p)
            self.shard_chunks.append([
                _build_shard_chunk(g, adj_lists, lut)
                for g in self.chunk_nodes[p]
            ])
            self.chunks_rebuilt += len(self.chunk_nodes[p])

    # -- exchange program -------------------------------------------------

    def exchange_fn(self):
        """The compiled exchange program for the CURRENT tables (None
        when there is nothing to exchange: P=1, hubless). Cached on the
        tables' content signature — repartitions that leave the hub set
        and schedule unchanged reuse the compiled program."""
        t = self.tables
        if t.n_hubs == 0 and len(t.schedule) == 0:
            return None
        key = (
            t.P, t.n_hubs,
            t.hub_deg.tobytes() if t.n_hubs else b"",
            t.hub_nbr_loc.shape if t.n_hubs else (),
            tuple((int(d), s.shape[1]) for (d, s, _) in t.schedule),
        )
        fn = self._exch_cache.get(key)
        if fn is None:
            fn = make_stream_exchange(
                self.mesh, t, rule=self.rule, tie=self.tie,
                node_axis=self.node_axis,
            )
            self._exch_cache[key] = fn
        return fn

    # -- churn + repartition at a step boundary ---------------------------

    def apply_churn(self, touched: set[int], promotes: list[int],
                    demotes: list[int], loc: np.ndarray) -> np.ndarray:
        """Rebuild after a churn boundary: update hub membership,
        rebuild the halo tables + exchange schedule, remap the state
        (exact — at a boundary ghosts and hub rows are consistent), and
        rebuild ONLY the chunks whose adjacency or support-row mapping
        changed. Returns the remapped per-shard state."""
        for v in promotes:
            self.hubset.add(v)
            self.part_vec[v] = -1
        for v in demotes:
            self.hubset.discard(v)
            self.part_vec[v] = _demote_target(self.adj, self.part_vec, v)
        cur = _graph_from_adj(self.adj)
        hubs = np.fromiter(sorted(self.hubset), np.int64,
                           len(self.hubset))
        partition = _partition_from_part(cur, self.part_vec, hubs, self.Pn)
        old_tables = self.tables
        self.tables = build_halo_tables(cur, partition)
        glob = gather_state(old_tables, loc)
        loc = scatter_state(self.tables, glob)
        if promotes or demotes:
            self.repartitions += 1
            # membership edits: a promoted node leaves its chunk, a
            # demoted hub joins the tail chunk of its new owner
            if promotes:
                gone = set(promotes)
                for per_p in self.chunk_nodes:
                    for k, g in enumerate(per_p):
                        if gone.intersection(g.tolist()):
                            per_p[k] = g[~np.isin(g, promotes)]
            for v in demotes:
                p_to = int(self.part_vec[v])
                self.chunk_nodes[p_to][-1] = np.concatenate(
                    [self.chunk_nodes[p_to][-1], [v]]
                )
        adj_lists = self.adj.neighbor_lists()
        moved = set(promotes) | set(demotes)
        for p in range(self.Pn):
            lut = _shard_lut(self.tables, p)
            rebuilt = []
            for g, old in zip(self.chunk_nodes[p], self.shard_chunks[p]):
                clean = (
                    old.nodes.size == g.size
                    and np.array_equal(old.nodes, g)
                    and not touched.intersection(g.tolist())
                    and not moved.intersection(g.tolist())
                    and np.array_equal(lut[old.sup_global], old.sup_rows)
                )
                if clean:
                    rebuilt.append(old)
                else:
                    rebuilt.append(_build_shard_chunk(g, adj_lists, lut))
                    self.chunks_rebuilt += 1
            # a demotion may have appended a chunk-less node after the
            # zip ran short (shard had more groups than chunks never
            # happens: groups and chunks stay 1:1)
            self.shard_chunks[p] = rebuilt
        return loc

    # -- one synchronous step ---------------------------------------------

    def step(self, loc: np.ndarray, t: int, depth: int,
             totals: dict) -> np.ndarray:
        """One synchronous update of every shard: per-shard prefetched
        chunk walks (buffered owned writes), then the exchange program
        refreshes ghost rows and ring-combines the hub update."""
        from graphdyn.pipeline.prefetch import HostPrefetcher

        tables = self.tables
        W = self.W
        Pn = self.Pn
        H = tables.n_hubs
        hub0 = tables.hub_row0
        h2d = d2h = 0
        hub_src = (np.empty((Pn, H * tables.hub_nbr_loc.shape[2], W),
                            np.uint32) if H else None)
        with obs.span("stream.step", step=t, shards=Pn):
            for p in range(Pn):
                dev = self.devices[p]
                loc_p = loc[p]
                chunks = [c for c in self.shard_chunks[p] if c.C]

                def build(c: int):
                    ch = chunks[c]
                    slab = np.concatenate(
                        [loc_p[ch.gids], np.zeros((1, W), np.uint32)],
                        axis=0)
                    staged = jax.device_put(
                        (ch.nbr_loc, ch.deg, ch.self_loc, slab), dev)
                    # graftlint: disable-next-line=GD016  measured H2D traffic over the arrays actually staged; the predictive model is streamed_chunk_bytes in obs/memband
                    nbytes = sum(int(a.nbytes) for a in staged)
                    return staged, nbytes

                outs = []
                pf = HostPrefetcher(build, range(len(chunks)), depth=depth)
                try:
                    for c in range(len(chunks)):
                        (nbr, deg, self_loc, slab), nbytes = pf.get(c)
                        out = _stream_chunk_device(
                            nbr, deg, self_loc, slab, self.rule, self.tie)
                        out_np = np.asarray(out)
                        outs.append((chunks[c], out_np))
                        h2d += nbytes
                        d2h += int(out_np.nbytes)
                finally:
                    totals["shard_build_s"][p] += pf._build_s
                    totals["shard_wait_s"][p] += pf._wait_s
                    pf.close()
                if H:
                    # hub partial inputs gather PRE-update state (the
                    # halo kernel's ordering), so before the owned write
                    hub_src[p] = loc_p[
                        tables.hub_nbr_loc[p].reshape(-1)]
                for ch, out_np in outs:
                    loc_p[ch.rows] = out_np
            fn = self.exchange_fn()
            if fn is not None:
                spec3 = NamedSharding(
                    self.mesh, P(self.node_axis, None, None))
                args = []
                if H:
                    prev_h = np.ascontiguousarray(
                        loc[:, hub0:hub0 + H, :])
                    args.append(jax.device_put(
                        jnp.asarray(hub_src), spec3))
                    args.append(jax.device_put(
                        jnp.asarray(prev_h), spec3))
                rows = np.arange(Pn)[:, None]
                for (_, s_idx, _) in tables.schedule:
                    args.append(jax.device_put(
                        jnp.asarray(loc[rows, s_idx, :]), spec3))
                # graftlint: disable-next-line=GD016  measured H2D traffic gauge over the exchange operands actually staged, not a predictive byte model — the model is halo_bytes_per_step/streamed_state_bytes in memband
                ex_bytes = sum(int(np.asarray(a).nbytes) for a in args)
                outs = fn(*args)
                outs = [np.asarray(o) for o in outs]
                if H:
                    loc[:, hub0:hub0 + H, :] = outs[0]
                    outs = outs[1:]
                for (_, _, r_idx), rv in zip(tables.schedule, outs):
                    loc[rows, r_idx, :] = rv
                h2d += ex_bytes
                # graftlint: disable-next-line=GD016  measured D2H readback gauge, same contract as the H2D one above
                d2h += sum(int(o.nbytes) for o in outs)
        totals["h2d_bytes"] += h2d
        totals["d2h_bytes"] += d2h
        if obs.enabled():
            obs.gauge("stream.h2d_bytes", h2d, step=t, shards=Pn)
            obs.gauge("stream.d2h_bytes", d2h, step=t, shards=Pn)
            obs.gauge(
                "stream.exchange_bytes",
                tables.halo_bytes_per_step(W), step=t, shards=Pn,
            )
        return loc


def sharded_streamed_rollout(
    graph: Graph, sp, steps: int, *,
    n_shards: int,
    rule: str = "majority", tie: str = "stay",
    n_chunks: int | None = None,
    device_budget_bytes: int | None = None,
    hub_threshold: int | None = None,
    partition: Partition | None = None,
    partition_seed: int = 0,
    mesh: Mesh | None = None,
    node_axis: str = "node",
    prefetch_depth: int = 2,
    churn: Iterable[ChurnBatch] | None = None,
    checkpoint_path: str | None = None,
    checkpoint_interval_s: float = 30.0,
    seed: int = 0,
    stats_out: dict | None = None,
) -> np.ndarray:
    """Roll packed spins ``sp: uint32[n, W]`` (GLOBAL node order) for
    ``steps`` synchronous updates over ``n_shards`` halo shards, each
    walking its own out-of-core chunk run — bit-exact to the
    single-device :func:`graphdyn.ops.streamed.streamed_rollout`, to the
    resident halo kernel, and to itself at any other shard count.

    ``n_chunks`` / ``device_budget_bytes`` (exactly one) size the chunk
    run PER SHARD. ``hub_threshold`` enables hub-split partitioning AND
    churn-driven repartition: a churned node crossing the threshold is
    promoted to a vertex-cut hub at the chunk boundary (fallen hubs
    demote to the part owning most of their neighbors), with the
    decision journaled (``stream.repartition``) next to the
    ``stream.churn`` record. With ``checkpoint_path``, the snapshot is
    the GLOBAL state under the same identity as the single-device
    streamed engine, so a preempted run resumes bit-exactly on ANY shard
    count — the churn + repartition history replays from the journal
    alone. ``stats_out`` receives totals: ``build_s``, ``wait_s``,
    ``overlap_frac``, ``per_shard_overlap``, ``h2d_bytes``,
    ``d2h_bytes``, ``mutations``, ``repartitions``, ``chunks_rebuilt``,
    ``steps``, ``chunks``, ``shards``.
    """
    sp = np.ascontiguousarray(np.asarray(sp, np.uint32))
    if sp.ndim != 2 or sp.shape[0] != graph.n:
        raise ValueError(
            f"sp must be uint32[n={graph.n}, W], got {sp.shape}"
        )
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    W = sp.shape[1]
    schedule = sorted(churn, key=lambda b: (b.step,)) if churn else []
    adj = _Adjacency(graph)
    if partition is not None and partition.P != n_shards:
        raise ValueError(
            f"partition has P={partition.P} parts but n_shards="
            f"{n_shards}"
        )
    if mesh is None:
        mesh = make_mesh(
            (n_shards,), (node_axis,), devices=device_pool(n_shards),
        )
    if int(mesh.shape[node_axis]) != n_shards:
        raise ValueError(
            f"mesh {node_axis!r} axis size {mesh.shape[node_axis]} != "
            f"n_shards {n_shards}"
        )

    journal = journal_repart = None
    ckpt = None
    t0, seq0 = 0, 0
    if checkpoint_path:
        from graphdyn.resilience.store import (
            journal_event, journal_path_for,
        )
        from graphdyn.utils.io import ChainCheckpointer, run_fingerprint

        jpath = journal_path_for(checkpoint_path)

        def journal(**fields):
            journal_event(jpath, "stream.churn", **fields)

        def journal_repart(**fields):
            journal_event(jpath, "stream.repartition", **fields)

        # the IDENTICAL identity as the single-device streamed engine —
        # it excludes the churn schedule AND the shard count/partition,
        # so a preempted run resumes across engines and shard counts;
        # the journal (not the schedule) is authoritative for
        # boundaries already crossed
        fp = run_fingerprint(
            graph.edges, np.int64(graph.n), np.int64(steps), str(rule),
            str(tie), np.int64(W),
        )
        ckpt = ChainCheckpointer(
            checkpoint_path, kind="streamed_rollout", seed=seed, fp=fp,
            interval_s=checkpoint_interval_s,
            extra_meta={"W": int(W)},
        )
        loaded = ckpt.load_state(
            check=lambda a: a["sp"].shape == sp.shape)
        if loaded is not None:
            t0 = int(loaded["t"])
            seq0 = int(loaded["seq"])
            replayed = _replay_churn(jpath, t0, adj)
            sp = np.ascontiguousarray(loaded["sp"].astype(np.uint32))
            if obs.enabled():
                obs.counter("stream.resume", t=t0, seq=seq0,
                            replayed=replayed, shards=n_shards)
            # the journaled history moved the adjacency: any partition
            # of the REPLAYED graph is bit-exact (layout independence),
            # so a requeue onto a different shard count re-partitions
            # fresh instead of trusting a stale layout
            partition = None

    if partition is None:
        cur = _graph_from_adj(adj)
        partition = partition_graph(
            cur, n_shards, seed=partition_seed,
            hub_threshold=hub_threshold,
        )
        base = cur
    else:
        base = graph
    eng = _ShardEngine(
        base, adj, partition, W=W, rule=rule, tie=tie,
        n_chunks=n_chunks, device_budget_bytes=device_budget_bytes,
        mesh=mesh, node_axis=node_axis,
    )
    loc = scatter_state(eng.tables, sp)
    state = _ShardStreamState(loc=loc, t=t0, seq=seq0)
    totals = {
        "h2d_bytes": 0, "d2h_bytes": 0, "mutations": 0,
        "shard_build_s": [0.0] * n_shards,
        "shard_wait_s": [0.0] * n_shards,
    }

    def advance(s: _ShardStreamState) -> _ShardStreamState:
        t, seq = s.t, s.seq
        loc = s.loc
        touched_all: set[int] = set()
        promotes_all: list[int] = []
        demotes_all: list[int] = []
        dirty = False
        while seq < len(schedule) and schedule[seq].step <= t:
            batch = schedule[seq]
            adds, drops, touched = adj.apply(batch.adds, batch.drops)
            if journal is not None:
                journal(step=int(batch.step), seq=int(seq),
                        adds=[list(e) for e in adds],
                        drops=[list(e) for e in drops],
                        n_adds=len(adds), n_drops=len(drops))
            totals["mutations"] += len(adds) + len(drops)
            touched_all |= touched
            dirty = dirty or bool(touched)
            if hub_threshold is not None and touched:
                promotes = sorted(
                    v for v in touched
                    if v not in eng.hubset
                    and len(adj._sets[v]) >= hub_threshold
                )
                demotes = sorted(
                    v for v in touched
                    if v in eng.hubset
                    and len(adj._sets[v]) < hub_threshold
                )
                if promotes or demotes:
                    if journal_repart is not None:
                        journal_repart(
                            step=int(batch.step), seq=int(seq),
                            promotes=promotes, demotes=demotes,
                            n_promotes=len(promotes),
                            n_demotes=len(demotes),
                        )
                    promotes_all += promotes
                    demotes_all += demotes
            seq += 1
        if dirty:
            loc = eng.apply_churn(
                touched_all, promotes_all, demotes_all, loc)
        loc = eng.step(loc, t, prefetch_depth, totals)
        return _ShardStreamState(loc=loc, t=t + 1, seq=seq)

    def active(s: _ShardStreamState) -> bool:
        return s.t < steps

    if ckpt is not None:
        state = ckpt.drive(
            state, advance=advance, active=active,
            payload=lambda s: {
                "sp": gather_state(eng.tables, s.loc),
                "t": np.int64(s.t), "seq": np.int64(s.seq),
            },
        )
    else:
        while active(state):
            state = advance(state)

    build_s = float(sum(totals["shard_build_s"]))
    wait_s = float(sum(totals["shard_wait_s"]))
    overlap = max(0.0, 1.0 - wait_s / build_s) if build_s > 0 else 0.0
    per_shard = []
    for p in range(n_shards):
        b, w = totals["shard_build_s"][p], totals["shard_wait_s"][p]
        o = max(0.0, 1.0 - w / b) if b > 0 else 0.0
        per_shard.append(o)
        if obs.enabled() and b > 0:
            obs.gauge(
                "stream.overlap_util", o, shard=p,
                build_s=round(b, 6), wait_s=round(w, 6),
                depth=prefetch_depth, steps=int(state.t),
                chunks=len(eng.shard_chunks[p]),
            )
    if stats_out is not None:
        stats_out.update(
            build_s=build_s, wait_s=wait_s, overlap_frac=overlap,
            per_shard_overlap=per_shard,
            h2d_bytes=totals["h2d_bytes"],
            d2h_bytes=totals["d2h_bytes"],
            mutations=totals["mutations"],
            repartitions=eng.repartitions,
            chunks_rebuilt=eng.chunks_rebuilt,
            steps=int(state.t), shards=n_shards,
            chunks=sum(len(cs) for cs in eng.shard_chunks),
        )
    return gather_state(eng.tables, state.loc)
