"""Device-mesh parallelism: replica/temperature sharding, psum ensemble
reductions, node-sharded dynamics for giant graphs."""

from graphdyn.parallel.mesh import make_mesh, device_pool, replicate, shard_batch  # noqa: F401
from graphdyn.parallel.sa_sharded import make_sharded_sa_solver, sa_sharded  # noqa: F401
