"""Device-mesh parallelism: replica/temperature sharding, psum ensemble
reductions, node-sharded dynamics for giant graphs."""

from graphdyn.parallel.mesh import (  # noqa: F401
    device_pool,
    init_multihost,
    make_hybrid_mesh,
    make_mesh,
    replicate,
    shard_batch,
)
from graphdyn.parallel.halo import (  # noqa: F401
    HaloProgram,
    HaloTables,
    build_halo_tables,
    halo_rollout,
    make_halo_rollout,
)
from graphdyn.parallel.sa_sharded import make_sharded_sa_solver, sa_sharded  # noqa: F401
from graphdyn.parallel.stream import (  # noqa: F401
    ShardChunk,
    ShardStreamPlan,
    build_shard_stream_plan,
    make_stream_exchange,
    sharded_streamed_rollout,
)
