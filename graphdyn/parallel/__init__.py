"""Device-mesh parallelism: replica/temperature sharding, psum ensemble
reductions, node-sharded dynamics for giant graphs."""

from graphdyn.parallel.mesh import make_mesh, replicate, shard_batch  # noqa: F401
