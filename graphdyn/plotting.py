"""BDCM entropy plots — the notebook's end artifact as a library call.

The reference notebook exists to compute "BDCM entropy plots"
(`code/README.md:1`); it stores result grids and the author plots the tilted
entropy ``s(m_init) = φ + λ·m_init`` against the BP mean initial
magnetization, one curve per mean degree. These helpers render exactly that
from the solver results, headless (Agg backend) so they work on TPU hosts
with no display. matplotlib is imported lazily — the rest of the framework
has no hard dependency on it.
"""

from __future__ import annotations

import numpy as np


def _mpl():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def plot_entropy_curve(result, *, ax=None, label=None, save_path=None):
    """Plot one tilted-entropy curve s(m_init) from an
    :class:`~graphdyn.models.entropy.EntropyResult` (or any object with
    ``m_init``/``ent1`` arrays over the visited λ ladder).

    Points where the entropy degraded to −inf (empty attractor set) are
    dropped. Returns the matplotlib Axes."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 3.6), dpi=120)
    m = np.asarray(result.m_init, float).reshape(-1)
    s = np.asarray(result.ent1, float).reshape(-1)
    keep = np.isfinite(m) & np.isfinite(s)
    ax.plot(m[keep], s[keep], marker="o", ms=3, lw=1.2, label=label)
    ax.set_xlabel(r"$m_{\mathrm{init}}$")
    ax.set_ylabel(r"$s(m_{\mathrm{init}}) = \phi + \lambda\, m_{\mathrm{init}}$")
    ax.axhline(0.0, color="0.7", lw=0.8, zorder=0)
    if label:
        ax.legend(frameon=False, fontsize=8)
    if save_path:
        ax.figure.tight_layout()
        ax.figure.savefig(save_path)
    return ax


def plot_entropy_grid(grid, *, rep: int | str = "mean", save_path=None):
    """Plot the deg-grid family of s(m_init) curves from an
    :class:`~graphdyn.models.entropy.EntropyGridResult` — the notebook
    driver's deg × rep × λ grids (`ipynb:484-492`), one curve per mean
    degree.

    ``rep``: a repetition index, or ``"mean"`` to average the grids over
    repetitions (zero entries from early-exited λ points are masked out).
    Returns the matplotlib Axes."""
    plt = _mpl()
    _, ax = plt.subplots(figsize=(5.5, 4), dpi=120)
    deg = np.asarray(grid.deg, float)
    for di in range(deg.size):
        m = np.asarray(grid.m_init[di], float)     # [rep, λ]
        s = np.asarray(grid.ent1[di], float)
        if rep == "mean":
            # untouched entries stay 0; −inf/NaN (degraded reps) must not
            # poison the mean of the finite reps at the same λ
            visited = ((m != 0) | (s != 0)) & np.isfinite(m) & np.isfinite(s)
            with np.errstate(invalid="ignore"):
                cnt = np.maximum(visited.sum(axis=0), 1)
                m_v = np.where(visited, m, 0.0).sum(axis=0) / cnt
                s_v = np.where(visited, s, 0.0).sum(axis=0) / cnt
            keep = visited.any(axis=0)
            m_v, s_v = m_v[keep], s_v[keep]
        else:
            m_v, s_v = m[int(rep)], s[int(rep)]
        finite = np.isfinite(m_v) & np.isfinite(s_v)
        ax.plot(m_v[finite], s_v[finite], marker="o", ms=3, lw=1.2,
                label=f"deg={deg[di]:g}")
    ax.set_xlabel(r"$m_{\mathrm{init}}$")
    ax.set_ylabel(r"$s(m_{\mathrm{init}})$")
    ax.axhline(0.0, color="0.7", lw=0.8, zorder=0)
    ax.legend(frameon=False, fontsize=8)
    if save_path:
        ax.figure.tight_layout()
        ax.figure.savefig(save_path)
    return ax
