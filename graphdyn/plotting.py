"""BDCM entropy plots — the notebook's end artifact as a library call.

The reference notebook exists to compute "BDCM entropy plots"
(`code/README.md:1`); it stores result grids and the author plots the tilted
entropy ``s(m_init) = φ + λ·m_init`` against the BP mean initial
magnetization, one curve per mean degree. These helpers render exactly that
from the solver results, headless (Agg backend) so they work on TPU hosts
with no display. matplotlib is imported lazily — the rest of the framework
has no hard dependency on it.
"""

from __future__ import annotations

import numpy as np


def _mpl():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def masked_mean(values, visited=None, axis: int = 0) -> np.ndarray:
    """Mean of ``values`` over ``axis`` restricted to ``visited`` AND finite
    entries; positions with no contributing entries give NaN (so downstream
    finite-masking drops them). The one masking rule shared by the grid
    'mean' plot and the CLI's union-ensemble member mean — degraded −inf/NaN
    members must not poison the mean of the finite ones at the same λ."""
    v = np.asarray(values, float)
    ok = np.isfinite(v)
    if visited is not None:
        ok &= visited
    cnt = ok.sum(axis=axis)
    mean = np.where(ok, v, 0.0).sum(axis=axis) / np.maximum(cnt, 1)
    return np.where(cnt == 0, np.nan, mean)


def plot_entropy_curve(result, *, ax=None, label=None, save_path=None):
    """Plot one tilted-entropy curve s(m_init) from an
    :class:`~graphdyn.models.entropy.EntropyResult` (or any object with
    ``m_init``/``ent1`` arrays over the visited λ ladder).

    Points where the entropy degraded to −inf (empty attractor set) are
    dropped. Returns the matplotlib Axes."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 3.6), dpi=120)
    m = np.asarray(result.m_init, float).reshape(-1)
    s = np.asarray(result.ent1, float).reshape(-1)
    keep = np.isfinite(m) & np.isfinite(s)
    ax.plot(m[keep], s[keep], marker="o", ms=3, lw=1.2, label=label)
    ax.set_xlabel(r"$m_{\mathrm{init}}$")
    ax.set_ylabel(r"$s(m_{\mathrm{init}}) = \phi + \lambda\, m_{\mathrm{init}}$")
    ax.axhline(0.0, color="0.7", lw=0.8, zorder=0)
    if label:
        ax.legend(frameon=False, fontsize=8)
    if save_path:
        ax.figure.tight_layout()
        ax.figure.savefig(save_path)
    return ax


def plot_entropy_grid(grid, *, rep: int | str = "mean", save_path=None):
    """Plot the deg-grid family of s(m_init) curves from an
    :class:`~graphdyn.models.entropy.EntropyGridResult` — the notebook
    driver's deg × rep × λ grids (`ipynb:484-492`), one curve per mean
    degree.

    ``rep``: a repetition index, or ``"mean"`` to average the grids over
    repetitions (zero entries from early-exited λ points are masked out).
    Returns the matplotlib Axes."""
    plt = _mpl()
    _, ax = plt.subplots(figsize=(5.5, 4), dpi=120)
    deg = np.asarray(grid.deg, float)
    for di in range(deg.size):
        m = np.asarray(grid.m_init[di], float)     # [rep, λ]
        s = np.asarray(grid.ent1[di], float)
        if rep == "mean":
            # visited λ cells come from the explicit per-rep count when the
            # grid carries it; legacy grids (or cells restored from old
            # checkpoints) fall back to the zero-value sentinel, OR-ed in so
            # a legitimately-(0, 0) visited point is kept when counted
            lam_count = getattr(grid, "n_lambda", None)
            visited = (m != 0) | (s != 0)
            if lam_count is not None:
                counted = (
                    np.arange(m.shape[1])[None, :]
                    < np.asarray(lam_count)[di][:, None]
                )
                visited |= counted
            # joint finiteness: a rep degraded in EITHER grid drops out of
            # BOTH means, so each plotted (m, s) point averages one
            # population
            visited &= np.isfinite(m) & np.isfinite(s)
            m_v = masked_mean(m, visited)
            s_v = masked_mean(s, visited)
        else:
            m_v, s_v = m[int(rep)], s[int(rep)]
        finite = np.isfinite(m_v) & np.isfinite(s_v)
        ax.plot(m_v[finite], s_v[finite], marker="o", ms=3, lw=1.2,
                label=f"deg={deg[di]:g}")
    ax.set_xlabel(r"$m_{\mathrm{init}}$")
    ax.set_ylabel(r"$s(m_{\mathrm{init}})$")
    ax.axhline(0.0, color="0.7", lw=0.8, zorder=0)
    ax.legend(frameon=False, fontsize=8)
    if save_path:
        ax.figure.tight_layout()
        ax.figure.savefig(save_path)
    return ax

def plot_consensus_curve(rows, *, title=None, save_path=None):
    """Render the m(0)→consensus curve family from
    :func:`graphdyn.models.consensus.consensus_curve` rows: consensus
    fraction (near + strict) vs m(0) on the left, mean first-passage steps
    on the right. Returns the (ax_fraction, ax_steps) pair."""
    plt = _mpl()
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9.2, 3.6), dpi=120)
    m0s = [r["m0"] for r in rows]
    frac = [r["consensus_fraction"] for r in rows]
    yerr = [r.get("consensus_fraction_std") for r in rows]
    if any(e is not None for e in yerr):
        # ensemble rows: instance spread as error bars
        ax1.errorbar(m0s, frac, yerr=[e or 0.0 for e in yerr],
                     fmt="o-", ms=4, lw=1.2, capsize=2.5,
                     label="near (|m| ≥ 1−ε), ±σ over instances")
    else:
        ax1.plot(m0s, frac, "o-", ms=4, lw=1.2, label="near (|m| ≥ 1−ε)")
    if "strict_fraction" in rows[0]:
        ax1.plot(m0s, [r["strict_fraction"] for r in rows],
                 "s--", ms=4, lw=1.0, label="strict (all equal)")
    ax1.set_xlabel("initial magnetization m(0)")
    ax1.set_ylabel("consensus fraction")
    ax1.set_ylim(-0.05, 1.05)
    ax1.legend(frameon=False, fontsize=8)
    if title:
        ax1.set_title(title, fontsize=9)
    steps = [(r["m0"], r["mean_steps_to_consensus"]) for r in rows
             if r["mean_steps_to_consensus"] is not None]
    if steps:
        ax2.plot(*zip(*steps), "o-", ms=4, lw=1.2)
    ax2.set_xlabel("initial magnetization m(0)")
    ax2.set_ylabel("mean steps to consensus")
    ax2.set_title("first-passage time", fontsize=9)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path)
    return ax1, ax2
