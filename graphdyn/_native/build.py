"""Build/load shim for the C++ graph builder (filled in by milestone M9)."""

from __future__ import annotations


def native_available() -> bool:
    return False


def native_random_regular(n: int, d: int, seed):
    raise NotImplementedError("native graph builder not built yet; use method='pairing'")


def native_erdos_renyi(n: int, p: float, seed):
    raise NotImplementedError("native graph builder not built yet; use method='numpy'")
