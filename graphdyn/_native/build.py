"""Build/load shim for the C++ graph builder (``graphgen.cpp``).

Compiles with g++ on first use (cached as ``_graphgen.so`` next to the
source, keyed by source mtime) and binds via ctypes. Falls back cleanly —
``native_available()`` is False — when no toolchain is present, so the pure
numpy samplers in :mod:`graphdyn.graphs` remain the default everywhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "graphgen.cpp")
_SO = os.path.join(_HERE, "_graphgen.so")

_lib = None
_load_error: str | None = None


def _ensure_built():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return
    try:
        if (not os.path.exists(_SO)) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            # unique temp name so concurrent first-use builds can't corrupt
            # each other; os.replace makes the install atomic
            tmp = f"{_SO}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _SO)
        lib = ctypes.CDLL(_SO)
        lib.rrg_edges.restype = ctypes.c_int
        lib.rrg_edges.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.er_edges.restype = ctypes.c_int64
        lib.er_edges.argtypes = [
            ctypes.c_int64,
            ctypes.c_double,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
        ]
        _lib = lib
    except (subprocess.CalledProcessError, OSError) as e:
        _load_error = str(e)
        stderr = getattr(e, "stderr", None)
        if stderr:
            _load_error += "\n" + stderr.decode(errors="replace")


def native_available() -> bool:
    _ensure_built()
    return _lib is not None


def _as_seed(seed) -> int:
    if seed is None:
        return int.from_bytes(os.urandom(8), "little")
    if isinstance(seed, np.random.Generator):
        return int(seed.integers(0, 2**63))
    return int(seed) & (2**64 - 1)


def native_random_regular(n: int, d: int, seed) -> np.ndarray:
    """Sample a simple d-regular edge list, shape [n*d/2, 2]."""
    _ensure_built()
    if _lib is None:
        raise RuntimeError(f"native builder unavailable: {_load_error}")
    E = n * d // 2
    u = np.empty(E, np.int32)
    v = np.empty(E, np.int32)
    rc = _lib.rrg_edges(
        n,
        d,
        _as_seed(seed),
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise RuntimeError(f"rrg_edges failed (rc={rc})")
    return np.stack([u, v], axis=1).astype(np.int64)


def native_erdos_renyi(n: int, p: float, seed) -> np.ndarray:
    """Sample G(n,p) edge list, shape [m, 2]."""
    _ensure_built()
    if _lib is None:
        raise RuntimeError(f"native builder unavailable: {_load_error}")
    mean = n * (n - 1) / 2 * p
    cap = int(mean + 8 * np.sqrt(mean + 1) + 64)
    while True:
        u = np.empty(cap, np.int32)
        v = np.empty(cap, np.int32)
        m = _lib.er_edges(
            n,
            float(p),
            _as_seed(seed),
            u.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cap,
        )
        if m >= 0:
            return np.stack([u[:m], v[:m]], axis=1).astype(np.int64)
        cap *= 2
