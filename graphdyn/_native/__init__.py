"""Native (C++) host-side components, loaded via ctypes.

The reference has no native code (SURVEY.md §2: "Native components: NONE"),
but graph construction at N=10⁶ is a real host-side bottleneck for the TPU
pipeline, so the builder is implemented in C++ (``graphgen.cpp``) with a
transparent numpy fallback when no toolchain is available.
"""

from graphdyn._native.build import native_available, native_random_regular, native_erdos_renyi  # noqa: F401
