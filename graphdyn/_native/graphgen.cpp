// Native graph builders for the host-side pipeline.
//
// The reference builds graphs through networkx Python loops
// (SA_RRG.py:59, HPR_pytorch_RRG.py:261, ER_BDCM_entropy.ipynb:280); at the
// framework's target scale (N=1e6 nodes feeding a TPU) graph construction is
// a real host bottleneck, so the ensemble samplers are implemented natively:
//
//  - rrg_edges: configuration-model stub pairing with conflict repair
//    (asymptotically uniform simple d-regular graphs, same scheme as the
//    numpy fallback in graphdyn/graphs.py).
//  - er_edges: G(n,p) via Batagelj–Brandes geometric skipping, O(E).
//
// Exposed through ctypes (see build.py); all buffers are caller-allocated
// numpy arrays. Returns <0 on error.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <random>
#include <unordered_set>
#include <vector>

extern "C" {

// Sample a simple d-regular graph on n nodes. out_u/out_v must hold n*d/2
// entries. Returns 0 on success, -1 if repair failed, -2 on bad args.
int rrg_edges(int64_t n, int32_t d, uint64_t seed, int32_t* out_u,
              int32_t* out_v) {
  if (n <= 0 || d <= 0 || d >= n || (n * (int64_t)d) % 2 != 0) return -2;
  const int64_t E = n * (int64_t)d / 2;
  std::mt19937_64 rng(seed);

  std::vector<int32_t> stubs(2 * E);
  int64_t pos = 0;
  for (int64_t i = 0; i < n; ++i)
    for (int32_t k = 0; k < d; ++k) stubs[pos++] = (int32_t)i;
  std::shuffle(stubs.begin(), stubs.end(), rng);

  std::vector<int32_t> u(E), v(E);
  for (int64_t e = 0; e < E; ++e) {
    u[e] = stubs[2 * e];
    v[e] = stubs[2 * e + 1];
  }

  std::vector<int64_t> pool;
  std::vector<char> bad(E);
  std::unordered_set<int64_t> seen;
  seen.reserve(2 * E);

  for (int round = 0; round < 400; ++round) {
    // mark self-loops and duplicate copies (keep first occurrence)
    seen.clear();
    int64_t nbad = 0;
    for (int64_t e = 0; e < E; ++e) {
      int64_t a = std::min(u[e], v[e]), b = std::max(u[e], v[e]);
      int64_t code = a * n + b;
      bool is_bad = (u[e] == v[e]) || !seen.insert(code).second;
      bad[e] = is_bad;
      nbad += is_bad;
    }
    if (nbad == 0) {
      std::copy(u.begin(), u.end(), out_u);
      std::copy(v.begin(), v.end(), out_v);
      return 0;
    }

    // re-pair the bad stubs together with an equal number of good edges
    pool.clear();
    for (int64_t e = 0; e < E; ++e)
      if (bad[e]) pool.push_back(e);
    int64_t want_good = std::min<int64_t>(std::max<int64_t>(nbad, 8), E - nbad);
    std::uniform_int_distribution<int64_t> pick(0, E - 1);
    int64_t added = 0;
    while (added < want_good) {
      int64_t e = pick(rng);
      if (!bad[e]) {
        bad[e] = 1;  // marks as pooled so we don't add twice
        pool.push_back(e);
        ++added;
      }
    }
    std::vector<int32_t> ps;
    ps.reserve(2 * pool.size());
    for (int64_t e : pool) {
      ps.push_back(u[e]);
      ps.push_back(v[e]);
    }
    std::shuffle(ps.begin(), ps.end(), rng);
    for (size_t i = 0; i < pool.size(); ++i) {
      u[pool[i]] = ps[i];
      v[pool[i]] = ps[pool.size() + i];
    }
  }
  return -1;
}

// Sample G(n, p) edges by geometric skipping. Writes up to cap edges into
// out_u/out_v; returns the number of edges, or -1 if cap was too small.
int64_t er_edges(int64_t n, double p, uint64_t seed, int32_t* out_u,
                 int32_t* out_v, int64_t cap) {
  if (p <= 0.0 || n < 2) return 0;
  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> unif(0.0, 1.0);
  int64_t m = 0;
  if (p >= 1.0) {
    for (int64_t i = 1; i < n; ++i)
      for (int64_t j = 0; j < i; ++j) {
        if (m >= cap) return -1;
        out_u[m] = (int32_t)j;
        out_v[m] = (int32_t)i;
        ++m;
      }
    return m;
  }
  // Batagelj–Brandes: enumerate lower-triangle pairs (i, j), j < i, with
  // geometric skips of mean 1/p
  const double logq = std::log(1.0 - p);
  int64_t i = 1, j = -1;
  while (i < n) {
    double r = unif(rng);
    j += 1 + (int64_t)std::floor(std::log(1.0 - r) / logq);
    while (j >= i && i < n) {
      j -= i;
      ++i;
    }
    if (i < n) {
      if (m >= cap) return -1;
      out_u[m] = (int32_t)j;
      out_v[m] = (int32_t)i;
      ++m;
    }
  }
  return m;
}

}  // extern "C"
