"""Chaos soak harness: composed-fault schedules over real CLI workloads.

PR 2 proved each fault *site* is individually survivable; production
preemption delivers *sequences* — a torn write, then a preemption, then a
truncated read of the very snapshot the requeue needs, on a box whose
checkpoint directory may be gone entirely. This module runs seeded,
randomized compositions of the instrumented fault sites against the real
CLI drivers (``graphdyn.cli.main`` — the same entry a scheduler requeues)
through kill/requeue cycles, and holds every run to the durability
contract:

- the final results are **bit-exact** against a fault-free oracle run of
  the same command line (never "close", never silently truncated);
- the run journal (``run_journal.jsonl``, :func:`graphdyn.resilience.store
  .validate_journal`) is schema-valid and tells the whole story — saves
  with strictly increasing versions, the quarantine/failover the scenario
  forced, one manifest per (simulated) process;
- every preempted episode leaves a parseable flight-recorder post-mortem
  (``obs_postmortem.jsonl`` with an ``obs.crash`` event naming the site),
  and the final clean episode leaves none.

Scenario catalogue (each randomized per seed — fault positions, counts and
schedules come from the seed's RNG; ARCHITECTURE.md "Chaos soak"):

==================== ======================================================
scenario             composition
==================== ======================================================
``torn_write``       torn checkpoint temp file mid-run → preemption signal
                     → requeue resumes bit-exactly
``write_degrade``    a burst of save ENOSPC (retry → skip-save degrade) →
                     preemption → requeue
``truncated_read``   preempt → truncate the published snapshot (tears the
                     promote hard link too) → requeue falls back to a
                     retained version (quarantine + failover in journal)
``bitrot``           preempt → flip bytes inside the snapshot WITHOUT
                     breaking the zip container → the SHA-256 manifest
                     catches it (100% — a wrong resume is never accepted),
                     fallback to a retained version
``mirror_failover``  preempt → the primary checkpoint directory dies
                     entirely → requeue resumes from the ``--ckpt-mirror``
                     replica
``mirror_degraded``  mirror-path ENOSPC for the whole episode → primary
                     proceeds, journal records the degraded mirror →
                     preempt → requeue
``requeue_storm``    repeated preemption signals at randomized boundaries,
                     several requeues in a row, then a clean finish
``hang_detect``      injected mid-run ``stall`` (the run stops
                     heartbeating) → the ``--stall-timeout`` watchdog
                     requests a graceful shutdown (snapshot + exit 75) →
                     the SUPERVISOR (:mod:`graphdyn.resilience.supervisor`)
                     auto-restarts → bit-exact finish
``deadline_preempt`` ``--deadline`` expires mid-run → the same graceful
                     snapshot + exit-75 path → requeue without the
                     deadline finishes bit-exactly
``crash_loop_quarantine`` the run crashes at the SAME site on every
                     restart → the supervisor retries with seeded-jitter
                     backoff, then QUARANTINES after N same-site crashes
                     (journal ``supervise.quarantine``, bundled
                     post-mortems, exit 86) instead of restarting an
                     N+1-th time
``race_mirror_exit`` the graftrace schedule fuzzer
                     (``GRAPHDYN_RACECHECK=1`` + ``GRAPHDYN_RACEFUZZ``)
                     widens the mirror-flush-vs-process-exit race in a
                     real subprocess: seeded lock jitter + a ``stall`` at
                     the ``mirror.copy`` worker site while the child
                     saves and falls off the end. With the atexit
                     ``flush_mirror`` fix present the last replica is
                     ALWAYS mirrored (green across every seed); the
                     pinned-seed control leg re-runs with the fix
                     reverted (``atexit.unregister``) and must LOSE the
                     last replica — proving the fuzzer detects the
                     historical bug class
``race_prefetch_close`` prefetcher-close-vs-emit under the same seeded
                     fuzzer, in-process: close() mid-stream with the
                     worker mid-build/blocked on a full queue must not
                     deadlock or leak, the fuzzed threaded stream stays
                     bit-exact with the synchronous builds, and the
                     overlap gauge lands exactly once per prefetcher
``serve_kill_requeue`` the multi-tenant job service under the seeded
                     schedule fuzzer: tenants submit to a durable spool
                     (one oversized job the byte model must refuse, one
                     short-timeout job the deadline must checkpoint-evict),
                     the serving child is hard-killed mid-dispatch at a
                     seeded position (the claimed job left ``running`` on
                     disk), a restarted child recovers the orphan from
                     disk alone and drains the queue; every accepted job
                     must finish bit-exact to a fault-free in-harness
                     oracle, and the spool journal must carry the full
                     submit/refuse/requeue/evict/done story
==================== ======================================================

Run it: ``python -m graphdyn.resilience.soak [--bounded] [--seeds N]
[--scenarios a,b,…] [--format text|json]``. ``--bounded`` is the tier-1 /
``scripts/lint.sh`` soakcheck configuration (small workloads, 3 seeds,
every scenario; ``GRAPHDYN_SKIP_SOAKCHECK=1`` skips the lint step when the
same bounded soak already ran in the suite — ``tests/test_soak.py``).
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

from graphdyn.resilience import faults as _faults
from graphdyn.resilience import store as _store

#: exit codes the harness accepts from an episode
EX_OK = 0
EX_TEMPFAIL = 75

#: default seeds of the bounded (tier-1) configuration
BOUNDED_SEEDS = (0, 1, 2)


@dataclasses.dataclass
class Episode:
    """One kill/requeue cycle: optional pre-op mutating on-disk state (the
    "between processes" fault), a fault plan for the run, extra top-level
    CLI flags for just this episode (e.g. ``--deadline``), and the exit the
    contract demands."""

    specs: list
    expect: int = EX_TEMPFAIL
    pre: str | None = None          # "truncate_current" | "nuke_primary"
    extra_args: tuple = ()
    #: subcommand flags appended AFTER the workload's own (argparse is
    #: last-wins, so an episode can override a workload default — e.g.
    #: requeue onto a shrunk ``--shards``)
    post_args: tuple = ()


@dataclasses.dataclass
class Scenario:
    name: str
    workload: str                   # "sa" | "entropy"
    summary: str
    mirror: bool = False
    #: journal ops that MUST appear for the scenario to count as exercised
    require_ops: tuple = ()
    #: flight events (counter/gauge names) that MUST appear in at least one
    #: preempted episode's post-mortem — the watchdog/deadline detection
    #: evidence is asserted, not hoped
    require_flight: tuple = ()
    #: "episodes" = the scheduler-requeue chain; "hang" / "crash_loop" =
    #: the run goes through the supervisor's own restart loop
    mode: str = "episodes"


def _plan_episodes(name: str, rng: np.random.Generator) -> list[Episode]:
    """The seeded composition for one scenario run — fault positions and
    burst lengths are drawn from the seed's stream, so three seeds exercise
    three different schedules of the same failure mode."""
    sig = {"site": "rep.boundary", "action": "signal",
           "at": int(rng.integers(1, 3))}
    lam = {"site": "lambda.boundary", "action": "signal",
           "at": int(rng.integers(1, 3))}
    if name == "torn_write":
        return [
            Episode(specs=[
                {"site": "checkpoint.write", "action": "torn",
                 "at": int(rng.integers(1, 4))},
                sig,
            ]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "write_degrade":
        return [
            Episode(specs=[
                {"site": "checkpoint.write", "action": "raise",
                 "at": int(rng.integers(1, 3)),
                 "count": int(rng.integers(3, 7))},
                sig,
            ]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "truncated_read":
        return [
            Episode(specs=[sig]),
            Episode(specs=[], expect=EX_OK, pre="truncate_current"),
        ]
    if name == "bitrot":
        return [
            Episode(specs=[sig]),
            Episode(specs=[
                {"site": "checkpoint.bitrot", "action": "bitrot", "at": 1},
            ], expect=EX_OK),
        ]
    if name == "mirror_failover":
        return [
            Episode(specs=[lam]),
            Episode(specs=[], expect=EX_OK, pre="nuke_primary"),
        ]
    if name == "mirror_degraded":
        return [
            Episode(specs=[
                {"site": "mirror.write", "action": "raise", "at": 1,
                 "count": 99},
                lam,
            ]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "requeue_storm":
        eps = [
            Episode(specs=[{"site": "rep.boundary", "action": "signal",
                            "at": int(rng.integers(1, 3))}])
            for _ in range(int(rng.integers(2, 4)))
        ]
        return eps + [Episode(specs=[], expect=EX_OK)]
    if name == "stream_churn":
        # preempt the out-of-core streamed rollout at chunk boundaries
        # while its churn schedule is live — twice, so the second resume
        # must replay journaled mutations written across TWO processes —
        # then a clean finish. The signal action takes the graceful
        # checkpoint path (deterministic, race-free); the stream.churn
        # journal is the replay evidence _check_journal asserts on.
        return [
            Episode(specs=[{"site": "chunk.boundary", "action": "signal",
                            "at": int(rng.integers(2, 5))}]),
            Episode(specs=[{"site": "chunk.boundary", "action": "signal",
                            "at": int(rng.integers(2, 5))}]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "stream_shard_requeue":
        # kill the SHARDED streamed run at a chunk boundary mid-churn,
        # then requeue onto a SHRUNK shard count: the snapshot + journal
        # alone must reproduce the exact global state — the requeued
        # process replays the journaled mutations, re-partitions the
        # replayed graph fresh at the new shard count (layout
        # independence makes any partition bit-exact), and the surviving
        # journal keeps the full churn + repartition story across both
        # processes
        return [
            Episode(specs=[{"site": "chunk.boundary", "action": "signal",
                            "at": int(rng.integers(2, 5))}]),
            Episode(specs=[], expect=EX_OK,
                    post_args=("--shards", "2")),
        ]
    if name == "deadline_preempt":
        # the preemption is the --deadline timer taking the SIGTERM path
        # mid-run; the requeue runs without it. A side-effect-only `stall`
        # at the first lambda boundary (this is the ENTROPY workload) pins
        # the run PAST the deadline: the bounded workload warmed by an
        # in-suite run can finish in under 0.1 s wall on a fast container,
        # and a run that beats the timer exercises nothing (observed — the
        # scenario went red exactly that way). The stall only holds the
        # run alive while the timer fires; the preemption path under test
        # is untouched.
        return [
            Episode(specs=[{"site": "lambda.boundary", "action": "stall",
                            "secs": 0.3, "at": 1}],
                    extra_args=("--deadline", "0.1")),
            Episode(specs=[], expect=EX_OK),
        ]
    raise ValueError(f"unknown scenario {name!r}")


#: hang_detect tuning: the injected stall must dwarf the watchdog timeout
#: (detection happens mid-sleep) while the timeout stays far above any
#: legitimate inter-boundary gap of the warmed bounded workload
STALL_SECS = 2.0
STALL_TIMEOUT_S = 0.75


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in (
        Scenario("torn_write", "sa",
                 "torn save temp file, then preemption, then requeue",
                 require_ops=("save", "load")),
        Scenario("write_degrade", "sa",
                 "save ENOSPC burst (retry→skip-save), preemption, requeue",
                 require_ops=("save",)),
        Scenario("truncated_read", "sa",
                 "preempt, truncate the published snapshot, requeue falls "
                 "back to a retained version",
                 require_ops=("save", "quarantine", "failover")),
        Scenario("bitrot", "sa",
                 "preempt, silent byte flips in a valid container — the "
                 "checksum manifest must catch it 100% of the time",
                 require_ops=("save", "quarantine", "failover")),
        Scenario("mirror_failover", "entropy",
                 "preempt, primary checkpoint directory dies, requeue "
                 "resumes from the mirror", mirror=True,
                 require_ops=("save", "failover")),
        Scenario("mirror_degraded", "entropy",
                 "mirror ENOSPC: primary proceeds, journal records the "
                 "degraded mirror", mirror=True,
                 require_ops=("save", "mirror.degraded")),
        Scenario("requeue_storm", "sa",
                 "several preemptions at randomized boundaries in a row",
                 require_ops=("save", "load")),
        Scenario("hang_detect", "sa",
                 "injected mid-run stall: the watchdog detects the silent "
                 "heartbeat, preempts gracefully, the supervisor "
                 "auto-restarts, the finished run is bit-exact",
                 require_ops=("save", "load", "supervise.start",
                              "supervise.restart"),
                 require_flight=("supervise.stall_detected",),
                 mode="hang"),
        # require_ops carries no "load": a deadline firing before the first
        # λ completes leaves nothing resumable (cold starts re-derive — the
        # boundary hook's documented skip), and the requeue legitimately
        # starts fresh; the snapshot→load→resume proof under supervision is
        # hang_detect's job
        Scenario("deadline_preempt", "entropy",
                 "--deadline expires mid-ladder: graceful snapshot + exit "
                 "75 on a timer, requeue finishes bit-exactly",
                 require_ops=("save",),
                 require_flight=("supervise.deadline",)),
        Scenario("crash_loop_quarantine", "sa",
                 "same-site crash on every restart: the supervisor backs "
                 "off, then quarantines with bundled post-mortems instead "
                 "of restarting forever",
                 require_ops=("supervise.start", "supervise.restart",
                              "supervise.quarantine"),
                 mode="crash_loop"),
        Scenario("race_mirror_exit", "store",
                 "seeded schedule fuzz on the mirror-flush-vs-exit race: "
                 "the atexit flush must always deliver the last replica, "
                 "and the pinned-seed reverted-fix control leg must lose "
                 "it (the fuzzer detects the historical bug class)",
                 mirror=True, require_ops=("save", "mirror.save"),
                 mode="race_mirror"),
        Scenario("race_prefetch_close", "pipeline",
                 "seeded schedule fuzz on prefetcher close-vs-emit: no "
                 "deadlock or thread leak, fuzzed stream bit-exact with "
                 "synchronous builds, overlap gauge exactly once",
                 mode="race_prefetch"),
        Scenario("stream_churn", "stream",
                 "out-of-core streamed rollout with live edge churn: "
                 "preempted twice at chunk boundaries mid-churn, each "
                 "requeue replays the journaled mutations bit-exactly "
                 "from the journal alone (the schedule past the resume "
                 "point is never re-trusted)",
                 require_ops=("save", "load", "stream.churn")),
        Scenario("stream_shard_requeue", "stream_shard",
                 "sharded streamed rollout with churn-driven live "
                 "repartition: preempted at a chunk boundary mid-churn, "
                 "requeued onto a SHRUNK shard count — the journal alone "
                 "replays the mutations and the repartition story "
                 "bit-exactly at the new partition",
                 require_ops=("save", "load", "stream.churn",
                              "stream.repartition")),
        Scenario("serve_kill_requeue", "serve",
                 "multi-tenant serve spool under the schedule fuzzer: "
                 "hard kill mid-dispatch, restart recovers the orphaned "
                 "job from disk, oversized job refused by the byte "
                 "model, short-timeout job checkpoint-evicted, every "
                 "accepted job bit-exact after requeue",
                 require_ops=("serve.submit", "serve.refuse",
                              "serve.requeue", "serve.evict",
                              "serve.done"),
                 mode="serve"),
    )
}


# ---------------------------------------------------------------------------
# workloads (real CLI command lines)
# ---------------------------------------------------------------------------


def _workload_args(kind: str, out: str, ckpt: str | None,
                   mirror: str | None) -> list[str]:
    pre: list[str] = []
    if mirror:
        pre += ["--ckpt-mirror", mirror]
    if kind == "sa":
        args = ["sa", "--n", "40", "--d", "3", "--p", "1", "--c", "1",
                "--n-stat", "2", "--max-steps", "20000", "--seed", "0",
                "--out", out]
    elif kind == "entropy":
        args = ["entropy", "--n", "50", "--deg", "1.5", "--num-rep", "1",
                "--lmbd-max", "0.3", "--lmbd-step", "0.1",
                "--max-sweeps", "200", "--eps", "1e-5", "--seed", "1",
                "--out", out]
    elif kind == "stream":
        # bounded out-of-core run: 3 chunks, live churn every step — the
        # schedule is pure in its args, so the fault-free oracle and every
        # requeued episode chain derive the same mutations
        args = ["stream", "--n", "160", "--dmin", "2", "--steps", "10",
                "--churn-rate", "2.0", "--churn-seed", "3",
                "--chunks", "3", "--replicas", "32", "--seed", "0",
                "--out", out]
    elif kind == "stream_shard":
        # the SHARDED streamed run with churn-driven repartition live:
        # the (threshold, churn) pair is pinned where the seeded schedule
        # provably crosses the hub threshold in BOTH directions over the
        # full run (one promotion + two demotions at these exact args),
        # so the journal always carries stream.repartition next to
        # stream.churn whichever episode the decision lands in
        args = ["stream", "--n", "160", "--gamma", "2.3", "--dmin", "2",
                "--steps", "5", "--churn-rate", "12.0",
                "--churn-seed", "25", "--chunks", "2", "--replicas", "32",
                "--seed", "0", "--shards", "4", "--hub-threshold", "17",
                "--out", out]
    else:
        raise ValueError(f"unknown workload {kind!r}")
    if ckpt is not None:
        args += ["--checkpoint", ckpt, "--checkpoint-interval", "0"]
    return pre + args


def _silence_stdout():
    """The CLI prints a result JSON line per run; dozens of soak episodes
    must not flood the harness's own stdout contract."""
    # graftlint: disable-next-line=GD007  os.devnull is not persistence — nothing can tear
    return contextlib.redirect_stdout(open(os.devnull, "w"))


def _run_cli(args: list[str], cwd: str) -> int | str:
    """One episode process: run the real CLI entry in ``cwd`` (where the
    flight recorder drops its post-mortem). Returns the exit code, or
    ``"preempt"`` for an injected hard kill."""
    from graphdyn.cli import main as cli_main

    old = os.getcwd()
    os.makedirs(cwd, exist_ok=True)
    os.chdir(cwd)
    try:
        with _silence_stdout():
            try:
                return cli_main(args)
            except _faults.InjectedPreemption:
                return "preempt"
    finally:
        os.chdir(old)


def _oracle(kind: str, root: str, cache: dict) -> dict[str, np.ndarray]:
    """The fault-free reference run (no checkpointing, no faults), cached
    per workload kind — parity target for every episode chain."""
    if kind not in cache:
        from graphdyn.utils.io import load_results_npz

        odir = os.path.join(root, "oracle", kind)
        out = os.path.join(odir, "res.npz")
        rc = _run_cli(_workload_args(kind, out, None, None), odir)
        if rc != 0:
            raise RuntimeError(f"oracle run for {kind!r} failed: rc={rc}")
        cache[kind] = load_results_npz(out)
    return cache[kind]


# ---------------------------------------------------------------------------
# the soak loop
# ---------------------------------------------------------------------------


def _apply_pre(pre: str | None, primary_dir: str, ckpt: str) -> None:
    if pre is None:
        return
    if pre == "truncate_current":
        _faults.truncate_file(ckpt + ".npz", 0.4)
    elif pre == "nuke_primary":
        # the primary checkpoint directory dies wholesale — snapshots,
        # versions, manifests AND the journal (a dead disk keeps nothing)
        shutil.rmtree(primary_dir, ignore_errors=True)
    else:
        raise ValueError(f"unknown pre-op {pre!r}")


def _flight_names(cwd: str) -> set:
    """Counter/gauge event names carried by the episode's flight
    post-mortem (empty when none exists / unparseable) — the detection
    evidence ``Scenario.require_flight`` asserts on."""
    from graphdyn.obs.flight import POSTMORTEM_NAME
    from graphdyn.obs.recorder import read_ledger

    path = os.path.join(cwd, POSTMORTEM_NAME)
    if not os.path.exists(path):
        return set()
    try:
        events, _ = read_ledger(path)
    except ValueError:
        return set()
    return {e.get("name") for e in events
            if e.get("ev") in ("counter", "gauge")}


def _check_journal(journal: str, require_ops: tuple,
                   problems: list) -> list[str]:
    """Validate the surviving run journal and assert the scenario's
    required ops appeared; returns the op list (appends problems)."""
    ops: list[str] = []
    if os.path.exists(journal):
        events, jproblems = _store.validate_journal(journal)
        problems += [f"journal: {p}" for p in jproblems]
        ops = [e.get("op") for e in events if e.get("ev") == "journal"]
    else:
        problems.append("no run journal was written")
    for op in require_ops:
        if op not in ops:
            problems.append(
                f"journal never recorded the scenario's {op!r} op "
                f"(saw {sorted(set(ops))})"
            )
    return ops


def _check_parity(kind: str, out: str, root: str, oracle_cache: dict,
                  problems: list) -> None:
    """Bit-exact result parity against the fault-free oracle."""
    from graphdyn.utils.io import load_results_npz

    oracle = _oracle(kind, root, oracle_cache)
    got = load_results_npz(out)
    if set(got) != set(oracle):
        problems.append(
            f"result keys differ: {sorted(got)} vs {sorted(oracle)}")
    else:
        for k in oracle:
            if not np.array_equal(got[k], oracle[k]):
                problems.append(f"result array {k!r} is not bit-exact")


def _postmortem_story(cwd: str, preempted: bool) -> str | None:
    """The flight-recorder contract per episode: a preempted episode leaves
    a parseable post-mortem naming the crash, a clean one leaves none.
    Returns a problem string or None."""
    from graphdyn.obs.flight import POSTMORTEM_NAME
    from graphdyn.obs.recorder import read_ledger

    path = os.path.join(cwd, POSTMORTEM_NAME)
    if not preempted:
        if os.path.exists(path):
            return f"clean episode left a post-mortem at {path}"
        return None
    if not os.path.exists(path):
        return "preempted episode left no flight post-mortem"
    try:
        events, _ = read_ledger(path)
    except ValueError as e:
        return f"unparseable post-mortem: {e}"
    crash = [e for e in events
             if e.get("ev") == "counter" and e.get("name") == "obs.crash"]
    if not crash:
        return "post-mortem carries no obs.crash event"
    if not (crash[-1].get("attrs") or {}).get("site"):
        return "obs.crash names no site"
    return None


def run_scenario(name: str, seed: int, root: str,
                 oracle_cache: dict) -> dict:
    """One (scenario, seed) soak run: the episode chain, then the contract
    checks (oracle parity, journal validity + required ops, flight story +
    required detection events). Returns a report dict with ``ok`` +
    per-check details. Supervised scenarios (``mode`` = "hang" /
    "crash_loop") go through the supervisor's own restart loop instead of
    the scheduler-requeue episode chain."""
    scn = SCENARIOS[name]
    if scn.mode == "hang":
        return _run_hang_detect(scn, seed, root, oracle_cache)
    if scn.mode == "crash_loop":
        return _run_crash_loop(scn, seed, root, oracle_cache)
    if scn.mode == "race_mirror":
        return _run_race_mirror(scn, seed, root)
    if scn.mode == "race_prefetch":
        return _run_race_prefetch(scn, seed, root)
    if scn.mode == "serve":
        return _run_serve_kill_requeue(scn, seed, root)
    if scn.workload == "stream_shard":
        # the sharded workload needs a real multi-device mesh; a 1-device
        # process (standalone soak without the forced host platform —
        # main() forces it, a library caller may not) skips with a
        # visible reason instead of failing on an environment limit
        import jax

        if len(jax.devices()) < 2:
            return {
                "scenario": name, "seed": seed, "workload": scn.workload,
                "episodes": [], "journal_ops": [], "problems": [],
                "ok": True,
                "skipped": "needs >= 2 devices on one platform (force "
                           "XLA_FLAGS="
                           "--xla_force_host_platform_device_count=8)",
            }
    rng = np.random.default_rng(seed)
    episodes = _plan_episodes(name, rng)
    workdir = os.path.join(root, name, f"seed{seed}")
    primary_dir = os.path.join(workdir, "primary")
    mirror_dir = os.path.join(workdir, "mirror") if scn.mirror else None
    ckpt = os.path.join(primary_dir, "ck")
    out = os.path.join(workdir, "res.npz")
    args = _workload_args(scn.workload, out, ckpt, mirror_dir)

    problems: list[str] = []
    ep_log: list[dict] = []
    flight_seen: set = set()
    for i, ep in enumerate(episodes):
        _apply_pre(ep.pre, primary_dir, ckpt)
        # each episode simulates a fresh requeued process: the journal
        # stamps a new manifest line (the exactly-once seam)
        _store._reset_journal_state()
        cwd = os.path.join(workdir, f"ep{i}")
        plan_seed = int(rng.integers(0, 2**31 - 1))
        plan = (_faults.FaultPlan(
            [_faults.FaultSpec(**s) for s in ep.specs], seed=plan_seed)
            if ep.specs else contextlib.nullcontext())
        with plan:
            rc = _run_cli(list(ep.extra_args) + args + list(ep.post_args),
                          cwd)
        ep_log.append({"episode": i, "rc": rc, "specs": ep.specs,
                       "pre": ep.pre, "extra_args": list(ep.extra_args),
                       "post_args": list(ep.post_args)})
        early = rc == EX_OK and ep.expect == EX_TEMPFAIL
        if early:
            # a randomized schedule may plan its kill past the work that
            # remains after resume (e.g. the signal lands after the last
            # repetition) — completing early is a legitimate outcome of a
            # chaos chain, and the parity/journal checks below still hold
            # it to the full contract
            ep_log[-1]["early_finish"] = True
        elif rc != ep.expect:
            problems.append(
                f"episode {i}: exit {rc!r}, expected {ep.expect} "
                f"(specs {ep.specs}, pre {ep.pre})"
            )
            break
        story = _postmortem_story(cwd, preempted=(rc == EX_TEMPFAIL))
        if story:
            problems.append(f"episode {i}: {story}")
        if rc == EX_TEMPFAIL:
            flight_seen |= _flight_names(cwd)
        if early:
            break
    if not problems and not any(e["rc"] == EX_TEMPFAIL for e in ep_log):
        problems.append(
            "no episode was actually preempted — the scenario never "
            "exercised its fault composition"
        )
    # the detection evidence: e.g. deadline_preempt's post-mortem must
    # carry the watchdog's supervise.deadline event — the preemption being
    # CAUSED by the timer is asserted, not assumed
    for want in scn.require_flight:
        if not problems and want not in flight_seen:
            problems.append(
                f"no preempted episode's post-mortem carries the "
                f"{want!r} event (saw {sorted(flight_seen)})"
            )

    # 1. bit-exact parity with the fault-free oracle
    if not problems:
        _check_parity(scn.workload, out, root, oracle_cache, problems)

    # 2. the journal story (the one that survived — after a primary nuke
    # that is the post-failover journal)
    journal = os.path.join(primary_dir, _store.JOURNAL_NAME)
    ops = _check_journal(journal, scn.require_ops, problems)
    # bitrot acceptance: detection must be unconditional — the quarantine
    # reason names the checksum layer, never an accepted wrong resume
    if name == "bitrot" and not problems:
        qs = [e for e in _store.validate_journal(journal)[0]
              if e.get("op") == "quarantine"]
        if not any("Checksum" in (q.get("reason") or "") for q in qs):
            problems.append("bitrot was not caught by the checksum layer")

    return {"scenario": name, "seed": seed, "workload": scn.workload,
            "episodes": ep_log, "journal_ops": sorted(set(ops)),
            "problems": problems, "ok": not problems}


def _supervise_policy():
    """The bounded-soak restart policy: tiny seeded-jitter backoffs (the
    schedule's SHAPE is the contract; production uses the CLI defaults)."""
    from graphdyn.resilience.retry import RetryPolicy
    from graphdyn.resilience.supervisor import RestartPolicy

    return RestartPolicy(
        quarantine_after=3, max_crashes=6, max_episodes=10,
        backoff=RetryPolicy(tries=8, base_delay_s=0.01, max_delay_s=0.05,
                            jitter=True),
    )


def _run_hang_detect(scn: Scenario, seed: int, root: str,
                     oracle_cache: dict) -> dict:
    """The acceptance loop end to end: a mid-run ``stall`` fault stops the
    heartbeats → the ``--stall-timeout`` watchdog detects it mid-sleep and
    requests the graceful snapshot + exit-75 path → the SUPERVISOR
    auto-restarts from the durable snapshot → the finished run is bit-exact
    with the fault-free oracle, and journal + post-mortem tell the story."""
    from graphdyn.resilience import supervisor as _sup

    rng = np.random.default_rng(seed)
    workdir = os.path.join(root, scn.name, f"seed{seed}")
    primary_dir = os.path.join(workdir, "primary")
    ckpt = os.path.join(primary_dir, "ck")
    out = os.path.join(workdir, "res.npz")
    args = _workload_args(scn.workload, out, ckpt, None)
    problems: list[str] = []
    # warm the oracle FIRST: it doubles as the compile warm-up, so the
    # supervised run's watchdog times heartbeat gaps, never a cold trace
    _oracle(scn.workload, root, oracle_cache)

    plan = _faults.FaultPlan(
        [_faults.FaultSpec("rep.boundary", "stall",
                           at=int(rng.integers(1, 3)), secs=STALL_SECS)],
        seed=seed,
    )
    _store._reset_journal_state()
    with plan:
        report = _sup.supervise(
            args, workdir=workdir, policy=_supervise_policy(),
            runner=_sup.run_inprocess, stall_timeout_s=STALL_TIMEOUT_S,
            journal_dir=primary_dir,
        )
    eps = report["episodes"]
    if report["exit"] != 0:
        problems.append(
            f"supervised run did not finish: exit {report['exit']} "
            f"({report['reason']}; episodes {eps})"
        )
    if not eps or eps[0]["rc"] != EX_TEMPFAIL:
        problems.append(
            f"first episode was not preempted by the watchdog "
            f"(episodes {eps})"
        )
    if len(eps) < 2:
        problems.append("the supervisor never restarted the run")
    # detection evidence: the preempted episode's post-mortem must carry
    # the watchdog's stall_detected event, with the stall older than the
    # timeout (i.e. the watchdog measured a real heartbeat gap)
    flight_seen: set = set()
    detected_ok = False
    for ep in eps:
        if ep["rc"] != EX_TEMPFAIL:
            continue
        cwd = ep["cwd"]
        story = _postmortem_story(cwd, preempted=True)
        if story:
            problems.append(f"episode {ep['episode']}: {story}")
        flight_seen |= _flight_names(cwd)
        from graphdyn.obs.flight import POSTMORTEM_NAME
        from graphdyn.obs.recorder import read_ledger

        try:
            events, _ = read_ledger(os.path.join(cwd, POSTMORTEM_NAME))
        except (OSError, ValueError):
            events = []
        stalls = [e for e in events
                  if e.get("name") == "supervise.stall_detected"]
        if stalls and (stalls[-1].get("attrs") or {}).get(
                "age_s", 0) >= STALL_TIMEOUT_S:
            detected_ok = True
    for want in scn.require_flight:
        if want not in flight_seen:
            problems.append(
                f"no preempted episode's post-mortem carries the "
                f"{want!r} event (saw {sorted(flight_seen)})"
            )
    if not detected_ok and not problems:
        problems.append(
            "stall_detected event carries no heartbeat age >= the timeout"
        )
    if not problems:
        _check_parity(scn.workload, out, root, oracle_cache, problems)
    journal = os.path.join(primary_dir, _store.JOURNAL_NAME)
    ops = _check_journal(journal, scn.require_ops, problems)
    return {"scenario": scn.name, "seed": seed, "workload": scn.workload,
            "episodes": eps, "journal_ops": sorted(set(ops)),
            "problems": problems, "ok": not problems}


def _run_crash_loop(scn: Scenario, seed: int, root: str,
                    oracle_cache: dict) -> dict:
    """The quarantine half of the acceptance: a run crash-looping at ONE
    site is restarted with backoff exactly ``quarantine_after - 1`` times,
    then quarantined — journal ``supervise.quarantine`` + bundled
    post-mortems present, exit :data:`~graphdyn.resilience.supervisor
    .EX_QUARANTINE`, and NO results file (a quarantined run must not look
    completed)."""
    from graphdyn.resilience import supervisor as _sup

    workdir = os.path.join(root, scn.name, f"seed{seed}")
    primary_dir = os.path.join(workdir, "primary")
    ckpt = os.path.join(primary_dir, "ck")
    out = os.path.join(workdir, "res.npz")
    # --group-size 0: the serial per-rep chain drives through
    # ChainCheckpointer.drive, whose chunk.boundary fault site fires BEFORE
    # the chunk's snapshot — so every restart re-crashes at the very same
    # chunk with zero progress: the genuine crash-on-same-input loop
    # (rep.boundary would fire after the prefix snapshot and "progress"
    # its way out of the loop)
    args = _workload_args(scn.workload, out, ckpt, None) + \
        ["--group-size", "0"]
    problems: list[str] = []
    policy = _supervise_policy()
    # the same organic crash on EVERY restart: a huge count keeps the spec
    # firing at the first chunk boundary of each episode
    plan = _faults.FaultPlan(
        [_faults.FaultSpec("chunk.boundary", "raise", at=1, count=10_000)],
        seed=seed,
    )
    _store._reset_journal_state()
    with plan:
        report = _sup.supervise(
            args, workdir=workdir, policy=policy,
            runner=_sup.run_inprocess, journal_dir=primary_dir,
        )
    eps = report["episodes"]
    if report["exit"] != _sup.EX_QUARANTINE or not report.get("quarantined"):
        problems.append(
            f"run was not quarantined: exit {report['exit']} "
            f"({report['reason']}; episodes {eps})"
        )
    if len(eps) != policy.quarantine_after:
        problems.append(
            f"expected exactly {policy.quarantine_after} crash episodes "
            f"(no N+1-th restart), got {len(eps)}: {eps}"
        )
    sites = {ep.get("site") for ep in eps}
    if len(sites) != 1:
        problems.append(f"crash episodes disagree on the site: {sites}")
    bundle = report.get("bundle")
    if not bundle or not os.path.exists(bundle):
        problems.append(f"no quarantine bundle was written ({bundle})")
    else:
        with open(bundle, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("crashes") != policy.quarantine_after:
            problems.append(f"bundle crash count wrong: {doc.get('crashes')}")
        pms = doc.get("postmortems") or []
        if len(pms) != policy.quarantine_after:
            problems.append(
                f"bundle should carry {policy.quarantine_after} "
                f"post-mortems, has {len(pms)}"
            )
        for pm in pms:
            if not os.path.exists(pm):
                problems.append(f"bundled post-mortem missing: {pm}")
    if os.path.exists(out):
        problems.append(
            "a quarantined run must not leave a results file — it never "
            "completed"
        )
    journal = os.path.join(primary_dir, _store.JOURNAL_NAME)
    ops = _check_journal(journal, scn.require_ops, problems)
    restarts = ops.count("supervise.restart")
    if restarts != policy.quarantine_after - 1:
        problems.append(
            f"journal records {restarts} supervise.restart event(s), "
            f"expected {policy.quarantine_after - 1}"
        )
    return {"scenario": scn.name, "seed": seed, "workload": scn.workload,
            "episodes": eps, "journal_ops": sorted(set(ops)),
            "problems": problems, "ok": not problems}


# ---------------------------------------------------------------------------
# graftrace seeded-schedule race scenarios (ARCHITECTURE.md "Host
# concurrency model")
# ---------------------------------------------------------------------------

#: lock-jitter cap for the race scenarios (GRAPHDYN_RACEFUZZ_MAX_MS)
RACE_FUZZ_MAX_MS = 30.0
#: the mirror.copy stall (seconds) — must dwarf the child's whole
#: main-thread runtime (≤ ~0.25 s incl. worst-case jitter) so the
#: reverted-fix control leg loses the last replica DETERMINISTICALLY,
#: while the fixed path's atexit flush (timeout 10 s) always drains
RACE_STALL_SECS = 0.35
#: saves per child: enough that the write-behind queue is realistically
#: deep at exit
RACE_SAVES = 4
#: the control leg (fix reverted) runs at this seed only — pinned, so the
#: red outcome is one reproducible schedule, not a per-seed lottery
RACE_PIN_SEED = 0


def _race_mirror_child(primary: str, mirror: str, revert: bool) -> str:
    """The subprocess body of ``race_mirror_exit``: N durable saves with a
    write-behind mirror, then fall off the end — exit-vs-flush is the race
    under test. ``revert=True`` unregisters the atexit ``flush_mirror``
    (the historical bug, PR-10's fix undone) without touching shipped
    code."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    lines = [
        "import sys",
        f"sys.path.insert(0, {repo!r})",
        "import numpy as np",
        "from graphdyn.analysis.racecheck import maybe_install",
        "from graphdyn.resilience.store import (DurableCheckpoint, "
        "configure_store, flush_mirror)",
        "maybe_install()",
        f"configure_store(mirror={mirror!r}, keep={RACE_SAVES * 2})",
    ]
    if revert:
        lines += [
            "import atexit",
            "atexit.unregister(flush_mirror)   # the reverted fix",
        ]
    lines += [
        f"ck = DurableCheckpoint({os.path.join(primary, 'ck')!r})",
        f"for i in range({RACE_SAVES}):",
        "    ck.save({'state': np.arange(64) + i}, {'i': i})",
        "# fall off the end: interpreter teardown races the queue",
    ]
    return "\n".join(lines) + "\n"


def _race_mirror_env(seed: int) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "GRAPHDYN_RACECHECK": "1",
        "GRAPHDYN_RACEFUZZ": str(seed),
        "GRAPHDYN_RACEFUZZ_MAX_MS": str(RACE_FUZZ_MAX_MS),
        # thread-side delay the lock proxy cannot reach: stall every
        # write-behind copy on the worker (env plans are process-global,
        # so the WORKER thread polls this one)
        "GRAPHDYN_FAULT_PLAN": json.dumps([{
            "site": "mirror.copy", "action": "stall",
            "secs": RACE_STALL_SECS, "at": 1, "count": 999,
        }]),
    })
    env.pop("GRAPHDYN_CKPT_MIRROR", None)
    return env


def _mirror_last_replica(primary: str, mirror: str) -> str:
    """The mirror-side path of the LAST save's immutable version — derived
    through the store's OWN namespacing (`_mirror_base`), so a layout
    change there can never read as a lost-replica race here."""
    mbase = _store.DurableCheckpoint(
        os.path.join(primary, "ck"), mirror=mirror)._mirror_base()
    return f"{mbase}.v{RACE_SAVES}.npz"


def _run_race_mirror(scn: Scenario, seed: int, root: str) -> dict:
    """Mirror-flush-vs-exit under the seeded schedule fuzzer, end to end
    in real subprocesses. Green leg (every seed): with the atexit
    ``flush_mirror`` registration present, the last save's replica is in
    the mirror after exit despite per-copy stalls and lock jitter. Control
    leg (pinned seed only): the same child with the registration reverted
    must LOSE the last replica — the harness provably detects the
    historical bug class, so a future revert of the fix goes red here."""
    import subprocess

    workdir = os.path.join(root, scn.name, f"seed{seed}")
    problems: list[str] = []
    ep_log: list[dict] = []

    def episode(tag: str, revert: bool) -> tuple[str, str]:
        d = os.path.join(workdir, tag)
        primary = os.path.join(d, "primary")
        mirror = os.path.join(d, "mirror")
        os.makedirs(primary, exist_ok=True)
        script = _race_mirror_child(primary, mirror, revert)
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, timeout=120, env=_race_mirror_env(seed), cwd=d,
        )
        ep_log.append({"episode": tag, "rc": proc.returncode,
                       "revert": revert})
        if proc.returncode != 0:
            problems.append(
                f"{tag} child exited {proc.returncode}: "
                f"{proc.stderr[-500:]}")
        return primary, mirror

    # green leg: the shipped fix must hold under this seed's schedule
    primary, mirror = episode("green", revert=False)
    last = _mirror_last_replica(primary, mirror)
    if not problems and not os.path.exists(last):
        problems.append(
            f"atexit flush_mirror LOST the last write-behind replica "
            f"under fuzz seed {seed} (missing {last}) — the "
            f"flush-vs-exit race regressed")
    if not problems:
        pub = os.path.join(os.path.dirname(last), "ck.npz")
        got = np.load(pub)["state"][0] if os.path.exists(pub) else None
        if got != RACE_SAVES - 1:
            problems.append(
                f"published mirror replica is not the LAST save "
                f"(state[0]={got}, want {RACE_SAVES - 1})")
    journal = os.path.join(primary, _store.JOURNAL_NAME)
    ops = _check_journal(journal, scn.require_ops, problems)

    # control leg, pinned seed: reverting the fix must lose the race —
    # a detection harness that cannot see the bug it was built for is
    # not a harness
    if seed == RACE_PIN_SEED:
        primary_r, mirror_r = episode("reverted", revert=True)
        last_r = _mirror_last_replica(primary_r, mirror_r)
        if not problems and os.path.exists(last_r):
            problems.append(
                "control leg: with atexit flush_mirror REVERTED the last "
                "replica still reached the mirror — the fuzzer no longer "
                "detects the historical bug class (stall/jitter budget "
                "too small?)")

    return {"scenario": scn.name, "seed": seed, "workload": scn.workload,
            "episodes": ep_log, "journal_ops": sorted(set(ops)),
            "problems": problems, "ok": not problems}


def _run_race_prefetch(scn: Scenario, seed: int, root: str) -> dict:
    """Prefetcher close-vs-emit under the seeded schedule fuzzer,
    in-process: (a) close() mid-stream — worker mid-build or blocked on a
    full queue — returns without deadlock and releases the thread,
    idempotently; (b) the fuzzed threaded stream is bit-exact with the
    synchronous builds (determinism is structural, the module contract);
    (c) with a recorder, the overlap gauge lands exactly once per
    prefetcher. The inventoried locks the fuzzer jitters here are the
    flight ring's and the journal's — every obs emission the worker and
    the closer make is schedule-perturbed."""
    from graphdyn import obs
    from graphdyn.analysis import racecheck as _rc
    from graphdyn.pipeline.prefetch import HostPrefetcher

    workdir = os.path.join(root, scn.name, f"seed{seed}")
    os.makedirs(workdir, exist_ok=True)
    problems: list[str] = []
    rng = np.random.default_rng(seed)
    delays = rng.uniform(0.0, 0.004, size=16)

    def build(k: int):
        # seeded build latency widens the emit-vs-close window; the value
        # is a pure function of k (the module's determinism premise)
        # graftrace: disable-next-line=GT005  injected build latency for the race scenario, not synchronization
        time.sleep(float(delays[k]))
        return np.arange(32, dtype=np.int64) + k

    import threading

    from graphdyn.obs import flight

    def live_workers() -> list:
        return [t for t in threading.enumerate()
                if t.name == "graphdyn-prefetch" and t.is_alive()]

    was_installed = _rc.installed()
    if not was_installed:
        _rc.install(fuzz_seed=seed, fuzz_max_ms=8.0)
    pre_workers = len(live_workers())
    flight.clear()                      # so the hung-counter check is ours
    leg_problems: list[str] = []

    def legs() -> None:
        # (a) close mid-stream: depth-2 queue is full, worker blocked.
        # close() always clears _thread — even for a hung worker it merely
        # abandons — so the REAL leak checks are threading.enumerate()
        # (no surviving live worker) and the absence of the
        # pipeline.prefetch.hung counter in the flight ring, both run by
        # the supervising side after the join.
        pf = HostPrefetcher(build, list(range(12)), depth=2)
        first = pf.get(0)
        if not np.array_equal(first, np.arange(32, dtype=np.int64)):
            leg_problems.append("mid-stream get returned the wrong build")
        pf.close()
        pf.close()                      # idempotent under fuzz
        # (b) full fuzzed stream == synchronous builds, bit-exact
        pf2 = HostPrefetcher(build, list(range(12)), depth=3)
        got = [pf2.get(k) for k in range(12)]
        pf2.close()
        if not all(np.array_equal(g, np.arange(32, dtype=np.int64) + k)
                   for k, g in enumerate(got)):
            leg_problems.append(
                "fuzzed prefetch stream diverged from synchronous builds")
        # (c) overlap gauge exactly once per prefetcher
        if not obs.enabled():
            ledger = os.path.join(workdir, "obs.jsonl")
            with obs.recording(ledger):
                pf3 = HostPrefetcher(build, list(range(6)), depth=2)
                for k in range(3):
                    pf3.get(k)
                pf3.close()
                pf3.close()
            from graphdyn.obs.recorder import read_ledger

            events, _ = read_ledger(ledger)
            n = sum(1 for e in events
                    if e.get("name") == "pipeline.prefetch.overlap_util")
            if n != 1:
                leg_problems.append(
                    f"expected exactly one overlap gauge per closed "
                    f"prefetcher, got {n}")

    try:
        # the legs run on a bounded worker: HostPrefetcher.get() blocks on
        # an untimed Queue.get, so the regression class this scenario
        # exists to catch (worker wedged / close-vs-emit deadlock) would
        # otherwise hang the soak run and tier-1 forever instead of
        # failing — the join timeout IS the scenario's deadline
        runner = threading.Thread(target=legs, name="graphdyn-soak-race-legs",
                                  daemon=True)
        runner.start()
        runner.join(timeout=60.0)
        if runner.is_alive():
            problems.append(
                "scenario WEDGED: the prefetch legs did not finish within "
                "60 s — a get/close/emit path deadlocked under fuzz")
        else:
            problems.extend(leg_problems)
            # no worker was ever declared hung-and-abandoned: the legs
            # all closed cleanly, so a hung counter means a real wedge
            hung = [e for e in flight.snapshot()
                    if e.get("name") == "pipeline.prefetch.hung"]
            if hung:
                problems.append(
                    f"a prefetch worker wedged past close()'s join window "
                    f"under fuzz ({len(hung)} pipeline.prefetch.hung "
                    f"event(s))")
            if len(live_workers()) > pre_workers:
                problems.append(
                    "a live graphdyn-prefetch worker survived the scenario")
    finally:
        if not was_installed:
            _rc.uninstall()
    return {"scenario": scn.name, "seed": seed, "workload": scn.workload,
            "episodes": [{"episode": 0, "rc": 0}],
            "journal_ops": [], "problems": problems, "ok": not problems}


# ---------------------------------------------------------------------------
# serve_kill_requeue: the job service's kill/requeue soak
# ---------------------------------------------------------------------------

#: serve soak: HBM budget pinned in the child env so the oversized job's
#: refusal is deterministic regardless of what the host device reports
SERVE_HBM_BUDGET = 1 << 30

#: the short-timeout job's first slice — far below a cold compile, so the
#: deadline always fires during attempt 1 and the eviction ladder runs
SERVE_EVICT_TIMEOUT_S = 0.05

#: serve-specific schedule-fuzz jitter bound: the serve path heartbeats
#: at every chunk boundary, so its lock-acquisition rate is orders of
#: magnitude above the chain scenarios' — the chain bound
#: (RACE_FUZZ_MAX_MS) would turn pure fuzz sleep into the scenario's
#: whole budget. Permuting thread schedules only needs jitter above the
#: scheduler's switch granularity, not a large one
SERVE_FUZZ_MAX_MS = 3.0


def _serve_env(seed: int, compile_cache: str) -> dict:
    """The serving child's environment: schedule fuzzer on (seeded lock
    jitter over every inventoried lock the spool/worker/bucket cache
    take), CPU jax, a pinned admission budget, and a persistent compile
    cache shared across episodes AND seeds — the recovery child replays
    the same programs the killed child compiled, and paying the XLA
    compile six times over would be pure soak-budget waste (the cache
    changes wall time only, never bits)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "GRAPHDYN_RACECHECK": "1",
        "GRAPHDYN_RACEFUZZ": str(seed),
        "GRAPHDYN_RACEFUZZ_MAX_MS": str(SERVE_FUZZ_MAX_MS),
        "GRAPHDYN_SERVE_HBM_BUDGET": str(SERVE_HBM_BUDGET),
        "GRAPHDYN_COMPILE_CACHE": compile_cache,
    })
    env.pop("GRAPHDYN_FAULT_PLAN", None)
    return env


def _serve_child_script(spool: str) -> str:
    """The serving child: the real service loop under the fuzzer. An
    InjectedPreemption from the dispatch fault site is the hard kill —
    the child dies with the claimed job left ``running`` on disk, exactly
    what SIGKILL leaves, and exits 75 like a preempted scheduler slot."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return "\n".join([
        "import sys",
        f"sys.path.insert(0, {repo!r})",
        "from graphdyn.analysis.racecheck import maybe_install",
        "maybe_install()",
        "from graphdyn.utils.platform import apply_compile_cache",
        "apply_compile_cache()",
        "from graphdyn.resilience.faults import InjectedPreemption",
        "from graphdyn.serve.lifecycle import run_service",
        "try:",
        f"    rc = run_service({spool!r}, idle_exit_s=0.25)",
        "except InjectedPreemption:",
        "    sys.exit(75)",
        "sys.exit(rc)",
    ]) + "\n"


def _serve_oracle(spec: dict, kernel: str, oracle_cache: dict) -> dict:
    """Fault-free in-harness run of one job spec — the parity reference.
    The served result must be bit-exact: the requeue path is a full
    deterministic replay (counter RNG), so eviction/kill/requeue may cost
    time but never bits."""
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.graphs import random_regular_graph
    from graphdyn.ops.pallas_anneal import build_fused_tables
    from graphdyn.search.fused import fused_anneal

    key = ("serve", kernel) + tuple(sorted(spec.items()))
    if key not in oracle_cache:
        g = random_regular_graph(int(spec["n"]), int(spec["d"]),
                                 seed=int(spec["graph_seed"]))
        cfg = SAConfig(dynamics=DynamicsConfig(
            p=1, c=1, rule=str(spec["rule"]), tie=str(spec["tie"])))
        # the serve convention: the coloring is the GRAPH's (seeded by
        # graph_seed — graphdyn.serve.bucketing shares one table set per
        # graph), the counter-RNG chain is the job's (seed)
        tables = build_fused_tables(g, cfg, seed=int(spec["graph_seed"]))
        res = fused_anneal(
            g, cfg, n_replicas=int(spec["replicas"]),
            seed=int(spec["seed"]), m_target=float(spec["m_target"]),
            max_sweeps=int(spec["max_sweeps"]),
            chunk_sweeps=int(spec["chunk_sweeps"]), kernel=kernel,
            tables=tables,
        )
        oracle_cache[key] = {
            "conf": np.asarray(res.s),
            "mag_reached": np.asarray(res.mag_reached),
            "m_end": np.asarray(res.m_end),
            "steps_to_target": np.asarray(res.steps_to_target),
        }
    return oracle_cache[key]


def _run_serve_kill_requeue(scn: Scenario, seed: int, root: str,
                            oracle_cache: dict | None = None) -> dict:
    """The serve soak: multi-tenant submissions to a durable spool, a
    serving child hard-killed mid-dispatch at a seeded position, a second
    child that must recover the orphaned job from disk alone and drain
    the queue. Contracts: the oversized job is REFUSED by the byte model
    (journal reason, never a device allocation), the short-timeout job is
    checkpoint-EVICTED and still finishes, every accepted job ends
    ``done`` and bit-exact to the fault-free oracle, and the spool
    journal is schema-valid with the whole story."""
    import subprocess

    from graphdyn.serve.admission import admit
    from graphdyn.serve.spool import Spool
    from graphdyn.utils.io import load_results_npz

    oracle_cache = {} if oracle_cache is None else oracle_cache
    rng = np.random.default_rng(seed)
    workdir = os.path.join(root, scn.name, f"seed{seed}")
    spool_dir = os.path.join(workdir, "spool")
    problems: list[str] = []
    ep_log: list[dict] = []

    # -- tenants submit (no server alive yet: the spool IS the API) -------
    spool = Spool(spool_dir)
    accepted = []
    for tenant in ("alice", "bob"):
        for _ in range(2):
            accepted.append(spool.submit(
                {"n": 24, "d": 3, "graph_seed": int(rng.integers(0, 4)),
                 "seed": int(rng.integers(0, 2**31 - 1)),
                 "max_sweeps": 32, "chunk_sweeps": 8}, tenant))
    # the short-timeout job: MINORITY dynamics never freeze a lane at
    # m_target, so every chunk of the budget always executes — a
    # machine-speed-independent runtime floor (256 chunk dispatches)
    # that the first 0.05 s slice can never beat, warm compile cache or
    # not. Attempt 1 always evicts; the ×4 escalation finishes it
    accepted.append(spool.submit(
        {"n": 128, "d": 3, "rule": "minority",
         "seed": int(rng.integers(0, 2**31 - 1)),
         "max_sweeps": 512, "chunk_sweeps": 2},
        "tim", timeout_s=SERVE_EVICT_TIMEOUT_S))
    # the oversized job: ~20 GB modeled resident set vs the pinned 1 GiB
    # budget — must be refused at admission, never reach the device
    oversized = spool.submit(
        {"n": 200000, "d": 3, "replicas": 4096}, "carol")

    compile_cache = os.path.join(root, scn.name, "compile_cache")
    os.makedirs(compile_cache, exist_ok=True)

    def episode(tag: str, fault_plan: list | None) -> int:
        env = _serve_env(seed, compile_cache)
        if fault_plan:
            env["GRAPHDYN_FAULT_PLAN"] = json.dumps(fault_plan)
        proc = subprocess.run(
            [sys.executable, "-c", _serve_child_script(spool_dir)],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=workdir)
        ep_log.append({"episode": tag, "rc": proc.returncode,
                       "specs": fault_plan or []})
        return proc.returncode

    # episode 1: hard kill mid-dispatch at a seeded position (the 2nd-4th
    # dispatch — always mid-queue: five jobs pass admission)
    kill_at = int(rng.integers(2, 5))
    rc = episode("kill", [{"site": "serve.dispatch", "action": "preempt",
                           "at": kill_at}])
    if rc != EX_TEMPFAIL:
        problems.append(
            f"killed episode exited {rc}, expected {EX_TEMPFAIL} "
            f"(preempt at dispatch {kill_at})")
    orphans = [r["id"] for r in spool.jobs() if r["state"] == "running"]
    if not orphans:
        problems.append(
            "hard kill left no running orphan in the spool — the kill "
            "landed outside a claimed job")
    # episode 2: a fresh server against the same spool — recovery is
    # from disk alone
    rc = episode("requeue", None)
    if rc != EX_OK:
        problems.append(f"recovery episode exited {rc}, expected {EX_OK}")

    # -- contracts --------------------------------------------------------
    recs = {r["id"]: r for r in spool.jobs()}
    over = recs[oversized]
    if over["state"] != "refused":
        problems.append(
            f"oversized job is {over['state']!r}, want refused")
    elif "exceeds the device budget" not in (over["reason"] or ""):
        problems.append(
            f"oversized refusal reason carries no byte-model verdict: "
            f"{over['reason']!r}")
    for jid in accepted:
        if recs[jid]["state"] != "done":
            problems.append(
                f"accepted job {jid} ended {recs[jid]['state']!r} "
                f"(reason {recs[jid]['reason']!r}), want done")
    journal = os.path.join(spool_dir, _store.JOURNAL_NAME)
    ops = _check_journal(journal, scn.require_ops, problems)
    recovered = [r for r in spool.jobs()
                 if r["id"] in orphans and r["requeues"] >= 1]
    if orphans and not recovered:
        problems.append(
            "the orphaned running job was never requeued by recovery")
    # bit-exact parity for every accepted job, oracle run fault-free in
    # the harness with the same admission kernel decision
    for jid in accepted:
        rec = recs[jid]
        if rec["state"] != "done":
            continue
        want = _serve_oracle(rec["spec"], admit(rec["spec"]).kernel,
                             oracle_cache)
        got = load_results_npz(rec["result"])
        if set(got) != set(want):
            problems.append(
                f"{jid}: result keys {sorted(got)} vs {sorted(want)}")
            continue
        for k in want:
            if not np.array_equal(got[k], want[k]):
                problems.append(
                    f"{jid}: result array {k!r} is not bit-exact after "
                    f"kill/requeue (requeues={rec['requeues']})")
    return {"scenario": scn.name, "seed": seed, "workload": scn.workload,
            "episodes": ep_log, "journal_ops": sorted(set(ops)),
            "problems": problems, "ok": not problems}


def run_soak(scenarios=None, seeds=BOUNDED_SEEDS, root: str | None = None,
             diag=lambda s: None) -> dict:
    """The full soak matrix. Returns ``{"runs": [...], "ok": bool,
    "scenarios": N, "seeds": M, "failed": K}``."""
    names = list(scenarios or SCENARIOS)
    tmp = None
    if root is None:
        tmp = tempfile.mkdtemp(prefix="graphdyn_soak_")
        root = tmp
    oracle_cache: dict = {}
    runs = []
    try:
        for name in names:
            for seed in seeds:
                diag(f"soak: {name} seed={seed}")
                rep = run_scenario(name, int(seed), root, oracle_cache)
                diag(f"soak: {name} seed={seed} -> "
                     f"{'ok' if rep['ok'] else 'FAIL: ' + '; '.join(rep['problems'])}")
                runs.append(rep)
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
    failed = sum(1 for r in runs if not r["ok"])
    return {"runs": runs, "ok": failed == 0, "scenarios": len(names),
            "seeds": len(list(seeds)), "failed": failed}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.resilience.soak",
        description="chaos soak: composed-fault kill/requeue cycles over "
                    "real CLI workloads, bit-exact against a fault-free "
                    "oracle (ARCHITECTURE.md 'Chaos soak')",
    )
    ap.add_argument("--bounded", action="store_true",
                    help="the tier-1 / lint.sh soakcheck configuration "
                    "(all scenarios, 3 seeds, small workloads)")
    ap.add_argument("--seeds", type=int, default=None, metavar="N",
                    help="number of seeds per scenario (default: 3 bounded, "
                    "5 otherwise)")
    ap.add_argument("--scenarios", default=None,
                    help="comma-separated subset (default: all; see "
                    "--list)")
    ap.add_argument("--list", action="store_true",
                    help="list the scenario catalogue and exit")
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="keep the soak working tree here instead of a "
                    "deleted temp dir (post-mortem debugging)")
    ap.add_argument("--format", choices=["text", "json"], default="text")
    args = ap.parse_args(argv)

    if args.list:
        for s in SCENARIOS.values():
            print(f"{s.name:18s} [{s.workload}"
                  f"{', mirror' if s.mirror else ''}] {s.summary}")
        return 0
    # the sharded-stream scenario needs a multi-device mesh: force the
    # simulated host platform BEFORE jax initializes (main() runs before
    # any workload imports jax), so the standalone soakcheck exercises
    # the same matrix the 8-device test harness does
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    names = args.scenarios.split(",") if args.scenarios else None
    if names:
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            ap.error(f"unknown scenario(s): {unknown}; "
                     f"known: {sorted(SCENARIOS)}")
    n_seeds = args.seeds if args.seeds is not None else (
        len(BOUNDED_SEEDS) if args.bounded else 5)
    report = run_soak(
        scenarios=names, seeds=range(n_seeds), root=args.root,
        diag=lambda s: print(s, file=sys.stderr, flush=True),
    )
    if args.format == "json":
        print(json.dumps(report))
    else:
        for r in report["runs"]:
            status = "ok" if r["ok"] else "FAIL"
            print(f"{r['scenario']:18s} seed={r['seed']} "
                  f"episodes={len(r['episodes'])} {status}")
            if r.get("skipped"):
                print(f"    skipped: {r['skipped']}")
            for p in r["problems"]:
                print(f"    {p}")
        print(f"soak: {report['scenarios']} scenario(s) x "
              f"{report['seeds']} seed(s), {report['failed']} failed")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
