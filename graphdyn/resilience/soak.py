"""Chaos soak harness: composed-fault schedules over real CLI workloads.

PR 2 proved each fault *site* is individually survivable; production
preemption delivers *sequences* — a torn write, then a preemption, then a
truncated read of the very snapshot the requeue needs, on a box whose
checkpoint directory may be gone entirely. This module runs seeded,
randomized compositions of the instrumented fault sites against the real
CLI drivers (``graphdyn.cli.main`` — the same entry a scheduler requeues)
through kill/requeue cycles, and holds every run to the durability
contract:

- the final results are **bit-exact** against a fault-free oracle run of
  the same command line (never "close", never silently truncated);
- the run journal (``run_journal.jsonl``, :func:`graphdyn.resilience.store
  .validate_journal`) is schema-valid and tells the whole story — saves
  with strictly increasing versions, the quarantine/failover the scenario
  forced, one manifest per (simulated) process;
- every preempted episode leaves a parseable flight-recorder post-mortem
  (``obs_postmortem.jsonl`` with an ``obs.crash`` event naming the site),
  and the final clean episode leaves none.

Scenario catalogue (each randomized per seed — fault positions, counts and
schedules come from the seed's RNG; ARCHITECTURE.md "Chaos soak"):

==================== ======================================================
scenario             composition
==================== ======================================================
``torn_write``       torn checkpoint temp file mid-run → preemption signal
                     → requeue resumes bit-exactly
``write_degrade``    a burst of save ENOSPC (retry → skip-save degrade) →
                     preemption → requeue
``truncated_read``   preempt → truncate the published snapshot (tears the
                     promote hard link too) → requeue falls back to a
                     retained version (quarantine + failover in journal)
``bitrot``           preempt → flip bytes inside the snapshot WITHOUT
                     breaking the zip container → the SHA-256 manifest
                     catches it (100% — a wrong resume is never accepted),
                     fallback to a retained version
``mirror_failover``  preempt → the primary checkpoint directory dies
                     entirely → requeue resumes from the ``--ckpt-mirror``
                     replica
``mirror_degraded``  mirror-path ENOSPC for the whole episode → primary
                     proceeds, journal records the degraded mirror →
                     preempt → requeue
``requeue_storm``    repeated preemption signals at randomized boundaries,
                     several requeues in a row, then a clean finish
==================== ======================================================

Run it: ``python -m graphdyn.resilience.soak [--bounded] [--seeds N]
[--scenarios a,b,…] [--format text|json]``. ``--bounded`` is the tier-1 /
``scripts/lint.sh`` soakcheck configuration (small workloads, 3 seeds,
every scenario; ``GRAPHDYN_SKIP_SOAKCHECK=1`` skips the lint step when the
same bounded soak already ran in the suite — ``tests/test_soak.py``).
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import shutil
import sys
import tempfile

import numpy as np

from graphdyn.resilience import faults as _faults
from graphdyn.resilience import store as _store

#: exit codes the harness accepts from an episode
EX_OK = 0
EX_TEMPFAIL = 75

#: default seeds of the bounded (tier-1) configuration
BOUNDED_SEEDS = (0, 1, 2)


@dataclasses.dataclass
class Episode:
    """One kill/requeue cycle: optional pre-op mutating on-disk state (the
    "between processes" fault), a fault plan for the run, and the exit the
    contract demands."""

    specs: list
    expect: int = EX_TEMPFAIL
    pre: str | None = None          # "truncate_current" | "nuke_primary"


@dataclasses.dataclass
class Scenario:
    name: str
    workload: str                   # "sa" | "entropy"
    summary: str
    mirror: bool = False
    #: journal ops that MUST appear for the scenario to count as exercised
    require_ops: tuple = ()


def _plan_episodes(name: str, rng: np.random.Generator) -> list[Episode]:
    """The seeded composition for one scenario run — fault positions and
    burst lengths are drawn from the seed's stream, so three seeds exercise
    three different schedules of the same failure mode."""
    sig = {"site": "rep.boundary", "action": "signal",
           "at": int(rng.integers(1, 3))}
    lam = {"site": "lambda.boundary", "action": "signal",
           "at": int(rng.integers(1, 3))}
    if name == "torn_write":
        return [
            Episode(specs=[
                {"site": "checkpoint.write", "action": "torn",
                 "at": int(rng.integers(1, 4))},
                sig,
            ]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "write_degrade":
        return [
            Episode(specs=[
                {"site": "checkpoint.write", "action": "raise",
                 "at": int(rng.integers(1, 3)),
                 "count": int(rng.integers(3, 7))},
                sig,
            ]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "truncated_read":
        return [
            Episode(specs=[sig]),
            Episode(specs=[], expect=EX_OK, pre="truncate_current"),
        ]
    if name == "bitrot":
        return [
            Episode(specs=[sig]),
            Episode(specs=[
                {"site": "checkpoint.bitrot", "action": "bitrot", "at": 1},
            ], expect=EX_OK),
        ]
    if name == "mirror_failover":
        return [
            Episode(specs=[lam]),
            Episode(specs=[], expect=EX_OK, pre="nuke_primary"),
        ]
    if name == "mirror_degraded":
        return [
            Episode(specs=[
                {"site": "mirror.write", "action": "raise", "at": 1,
                 "count": 99},
                lam,
            ]),
            Episode(specs=[], expect=EX_OK),
        ]
    if name == "requeue_storm":
        eps = [
            Episode(specs=[{"site": "rep.boundary", "action": "signal",
                            "at": int(rng.integers(1, 3))}])
            for _ in range(int(rng.integers(2, 4)))
        ]
        return eps + [Episode(specs=[], expect=EX_OK)]
    raise ValueError(f"unknown scenario {name!r}")


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in (
        Scenario("torn_write", "sa",
                 "torn save temp file, then preemption, then requeue",
                 require_ops=("save", "load")),
        Scenario("write_degrade", "sa",
                 "save ENOSPC burst (retry→skip-save), preemption, requeue",
                 require_ops=("save",)),
        Scenario("truncated_read", "sa",
                 "preempt, truncate the published snapshot, requeue falls "
                 "back to a retained version",
                 require_ops=("save", "quarantine", "failover")),
        Scenario("bitrot", "sa",
                 "preempt, silent byte flips in a valid container — the "
                 "checksum manifest must catch it 100% of the time",
                 require_ops=("save", "quarantine", "failover")),
        Scenario("mirror_failover", "entropy",
                 "preempt, primary checkpoint directory dies, requeue "
                 "resumes from the mirror", mirror=True,
                 require_ops=("save", "failover")),
        Scenario("mirror_degraded", "entropy",
                 "mirror ENOSPC: primary proceeds, journal records the "
                 "degraded mirror", mirror=True,
                 require_ops=("save", "mirror.degraded")),
        Scenario("requeue_storm", "sa",
                 "several preemptions at randomized boundaries in a row",
                 require_ops=("save", "load")),
    )
}


# ---------------------------------------------------------------------------
# workloads (real CLI command lines)
# ---------------------------------------------------------------------------


def _workload_args(kind: str, out: str, ckpt: str | None,
                   mirror: str | None) -> list[str]:
    pre: list[str] = []
    if mirror:
        pre += ["--ckpt-mirror", mirror]
    if kind == "sa":
        args = ["sa", "--n", "40", "--d", "3", "--p", "1", "--c", "1",
                "--n-stat", "2", "--max-steps", "20000", "--seed", "0",
                "--out", out]
    elif kind == "entropy":
        args = ["entropy", "--n", "50", "--deg", "1.5", "--num-rep", "1",
                "--lmbd-max", "0.3", "--lmbd-step", "0.1",
                "--max-sweeps", "200", "--eps", "1e-5", "--seed", "1",
                "--out", out]
    else:
        raise ValueError(f"unknown workload {kind!r}")
    if ckpt is not None:
        args += ["--checkpoint", ckpt, "--checkpoint-interval", "0"]
    return pre + args


def _silence_stdout():
    """The CLI prints a result JSON line per run; dozens of soak episodes
    must not flood the harness's own stdout contract."""
    # graftlint: disable-next-line=GD007  os.devnull is not persistence — nothing can tear
    return contextlib.redirect_stdout(open(os.devnull, "w"))


def _run_cli(args: list[str], cwd: str) -> int | str:
    """One episode process: run the real CLI entry in ``cwd`` (where the
    flight recorder drops its post-mortem). Returns the exit code, or
    ``"preempt"`` for an injected hard kill."""
    from graphdyn.cli import main as cli_main

    old = os.getcwd()
    os.makedirs(cwd, exist_ok=True)
    os.chdir(cwd)
    try:
        with _silence_stdout():
            try:
                return cli_main(args)
            except _faults.InjectedPreemption:
                return "preempt"
    finally:
        os.chdir(old)


def _oracle(kind: str, root: str, cache: dict) -> dict[str, np.ndarray]:
    """The fault-free reference run (no checkpointing, no faults), cached
    per workload kind — parity target for every episode chain."""
    if kind not in cache:
        from graphdyn.utils.io import load_results_npz

        odir = os.path.join(root, "oracle", kind)
        out = os.path.join(odir, "res.npz")
        rc = _run_cli(_workload_args(kind, out, None, None), odir)
        if rc != 0:
            raise RuntimeError(f"oracle run for {kind!r} failed: rc={rc}")
        cache[kind] = load_results_npz(out)
    return cache[kind]


# ---------------------------------------------------------------------------
# the soak loop
# ---------------------------------------------------------------------------


def _apply_pre(pre: str | None, primary_dir: str, ckpt: str) -> None:
    if pre is None:
        return
    if pre == "truncate_current":
        _faults.truncate_file(ckpt + ".npz", 0.4)
    elif pre == "nuke_primary":
        # the primary checkpoint directory dies wholesale — snapshots,
        # versions, manifests AND the journal (a dead disk keeps nothing)
        shutil.rmtree(primary_dir, ignore_errors=True)
    else:
        raise ValueError(f"unknown pre-op {pre!r}")


def _postmortem_story(cwd: str, preempted: bool) -> str | None:
    """The flight-recorder contract per episode: a preempted episode leaves
    a parseable post-mortem naming the crash, a clean one leaves none.
    Returns a problem string or None."""
    from graphdyn.obs.flight import POSTMORTEM_NAME
    from graphdyn.obs.recorder import read_ledger

    path = os.path.join(cwd, POSTMORTEM_NAME)
    if not preempted:
        if os.path.exists(path):
            return f"clean episode left a post-mortem at {path}"
        return None
    if not os.path.exists(path):
        return "preempted episode left no flight post-mortem"
    try:
        events, _ = read_ledger(path)
    except ValueError as e:
        return f"unparseable post-mortem: {e}"
    crash = [e for e in events
             if e.get("ev") == "counter" and e.get("name") == "obs.crash"]
    if not crash:
        return "post-mortem carries no obs.crash event"
    if not (crash[-1].get("attrs") or {}).get("site"):
        return "obs.crash names no site"
    return None


def run_scenario(name: str, seed: int, root: str,
                 oracle_cache: dict) -> dict:
    """One (scenario, seed) soak run: the episode chain, then the three
    contract checks (oracle parity, journal validity + required ops, flight
    story). Returns a report dict with ``ok`` + per-check details."""
    scn = SCENARIOS[name]
    rng = np.random.default_rng(seed)
    episodes = _plan_episodes(name, rng)
    workdir = os.path.join(root, name, f"seed{seed}")
    primary_dir = os.path.join(workdir, "primary")
    mirror_dir = os.path.join(workdir, "mirror") if scn.mirror else None
    ckpt = os.path.join(primary_dir, "ck")
    out = os.path.join(workdir, "res.npz")
    args = _workload_args(scn.workload, out, ckpt, mirror_dir)

    problems: list[str] = []
    ep_log: list[dict] = []
    for i, ep in enumerate(episodes):
        _apply_pre(ep.pre, primary_dir, ckpt)
        # each episode simulates a fresh requeued process: the journal
        # stamps a new manifest line (the exactly-once seam)
        _store._reset_journal_state()
        cwd = os.path.join(workdir, f"ep{i}")
        plan_seed = int(rng.integers(0, 2**31 - 1))
        plan = (_faults.FaultPlan(
            [_faults.FaultSpec(**s) for s in ep.specs], seed=plan_seed)
            if ep.specs else contextlib.nullcontext())
        with plan:
            rc = _run_cli(args, cwd)
        ep_log.append({"episode": i, "rc": rc, "specs": ep.specs,
                       "pre": ep.pre})
        early = rc == EX_OK and ep.expect == EX_TEMPFAIL
        if early:
            # a randomized schedule may plan its kill past the work that
            # remains after resume (e.g. the signal lands after the last
            # repetition) — completing early is a legitimate outcome of a
            # chaos chain, and the parity/journal checks below still hold
            # it to the full contract
            ep_log[-1]["early_finish"] = True
        elif rc != ep.expect:
            problems.append(
                f"episode {i}: exit {rc!r}, expected {ep.expect} "
                f"(specs {ep.specs}, pre {ep.pre})"
            )
            break
        story = _postmortem_story(cwd, preempted=(rc == EX_TEMPFAIL))
        if story:
            problems.append(f"episode {i}: {story}")
        if early:
            break
    if not problems and not any(e["rc"] == EX_TEMPFAIL for e in ep_log):
        problems.append(
            "no episode was actually preempted — the scenario never "
            "exercised its fault composition"
        )

    # 1. bit-exact parity with the fault-free oracle
    if not problems:
        from graphdyn.utils.io import load_results_npz

        oracle = _oracle(scn.workload, root, oracle_cache)
        got = load_results_npz(out)
        if set(got) != set(oracle):
            problems.append(
                f"result keys differ: {sorted(got)} vs {sorted(oracle)}")
        else:
            for k in oracle:
                if not np.array_equal(got[k], oracle[k]):
                    problems.append(f"result array {k!r} is not bit-exact")

    # 2. the journal story (the one that survived — after a primary nuke
    # that is the post-failover journal)
    journal = os.path.join(primary_dir, _store.JOURNAL_NAME)
    ops: list[str] = []
    if os.path.exists(journal):
        events, jproblems = _store.validate_journal(journal)
        problems += [f"journal: {p}" for p in jproblems]
        ops = [e.get("op") for e in events if e.get("ev") == "journal"]
    else:
        problems.append("no run journal was written")
    for op in scn.require_ops:
        if op not in ops:
            problems.append(
                f"journal never recorded the scenario's {op!r} op "
                f"(saw {sorted(set(ops))})"
            )
    # bitrot acceptance: detection must be unconditional — the quarantine
    # reason names the checksum layer, never an accepted wrong resume
    if name == "bitrot" and not problems:
        qs = [e for e in _store.validate_journal(journal)[0]
              if e.get("op") == "quarantine"]
        if not any("Checksum" in (q.get("reason") or "") for q in qs):
            problems.append("bitrot was not caught by the checksum layer")

    return {"scenario": name, "seed": seed, "workload": scn.workload,
            "episodes": ep_log, "journal_ops": sorted(set(ops)),
            "problems": problems, "ok": not problems}


def run_soak(scenarios=None, seeds=BOUNDED_SEEDS, root: str | None = None,
             diag=lambda s: None) -> dict:
    """The full soak matrix. Returns ``{"runs": [...], "ok": bool,
    "scenarios": N, "seeds": M, "failed": K}``."""
    names = list(scenarios or SCENARIOS)
    tmp = None
    if root is None:
        tmp = tempfile.mkdtemp(prefix="graphdyn_soak_")
        root = tmp
    oracle_cache: dict = {}
    runs = []
    try:
        for name in names:
            for seed in seeds:
                diag(f"soak: {name} seed={seed}")
                rep = run_scenario(name, int(seed), root, oracle_cache)
                diag(f"soak: {name} seed={seed} -> "
                     f"{'ok' if rep['ok'] else 'FAIL: ' + '; '.join(rep['problems'])}")
                runs.append(rep)
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
    failed = sum(1 for r in runs if not r["ok"])
    return {"runs": runs, "ok": failed == 0, "scenarios": len(names),
            "seeds": len(list(seeds)), "failed": failed}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.resilience.soak",
        description="chaos soak: composed-fault kill/requeue cycles over "
                    "real CLI workloads, bit-exact against a fault-free "
                    "oracle (ARCHITECTURE.md 'Chaos soak')",
    )
    ap.add_argument("--bounded", action="store_true",
                    help="the tier-1 / lint.sh soakcheck configuration "
                    "(all scenarios, 3 seeds, small workloads)")
    ap.add_argument("--seeds", type=int, default=None, metavar="N",
                    help="number of seeds per scenario (default: 3 bounded, "
                    "5 otherwise)")
    ap.add_argument("--scenarios", default=None,
                    help="comma-separated subset (default: all; see "
                    "--list)")
    ap.add_argument("--list", action="store_true",
                    help="list the scenario catalogue and exit")
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="keep the soak working tree here instead of a "
                    "deleted temp dir (post-mortem debugging)")
    ap.add_argument("--format", choices=["text", "json"], default="text")
    args = ap.parse_args(argv)

    if args.list:
        for s in SCENARIOS.values():
            print(f"{s.name:18s} [{s.workload}"
                  f"{', mirror' if s.mirror else ''}] {s.summary}")
        return 0
    names = args.scenarios.split(",") if args.scenarios else None
    if names:
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            ap.error(f"unknown scenario(s): {unknown}; "
                     f"known: {sorted(SCENARIOS)}")
    n_seeds = args.seeds if args.seeds is not None else (
        len(BOUNDED_SEEDS) if args.bounded else 5)
    report = run_soak(
        scenarios=names, seeds=range(n_seeds), root=args.root,
        diag=lambda s: print(s, file=sys.stderr, flush=True),
    )
    if args.format == "json":
        print(json.dumps(report))
    else:
        for r in report["runs"]:
            status = "ok" if r["ok"] else "FAIL"
            print(f"{r['scenario']:18s} seed={r['seed']} "
                  f"episodes={len(r['episodes'])} {status}")
            for p in r["problems"]:
                print(f"    {p}")
        print(f"soak: {report['scenarios']} scenario(s) x "
              f"{report['seeds']} seed(s), {report['failed']} failed")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
