"""graphdyn.resilience — runtime fault tolerance for long-running solvers.

The runtime counterpart to :mod:`graphdyn.analysis` (which gives *static*
guarantees): this package makes the hours-long SA chains, HPr runs, and
λ-sweep grids survive the faults that preemptible TPU slices actually
deliver. Three cooperating pieces (ARCHITECTURE.md "Resilience"):

- :mod:`graphdyn.resilience.faults` — deterministic, seedable fault
  injection (:class:`FaultPlan`) at named sites instrumented through the
  io/solver/ops layers, plus the ``GRAPHDYN_FAULT_PLAN`` env hook for
  CLI-level tests. Every recovery path below ships with an injection test.
- :mod:`graphdyn.resilience.retry` — bounded exponential-backoff
  :func:`retry` and the process-wide checkpoint-save policy
  (:data:`SAVE_RETRY`, CLI ``--max-save-retries``): transient save failures
  retry, exhausted retries degrade to skip-save with a logged warning —
  the chain keeps computing.
- :mod:`graphdyn.resilience.shutdown` — :func:`graceful_shutdown` turns
  SIGTERM/SIGINT into "checkpoint at next chunk boundary, exit
  :data:`EX_TEMPFAIL` (75)", so schedulers can tell preemption from
  failure.
- :mod:`graphdyn.resilience.supervisor` — supervised execution: every
  driver boundary emits an ``obs.heartbeat`` (:func:`beat`), the
  :class:`Watchdog` escalates stalls along the shutdown ladder (graceful
  exit 75, then hard abort 130 with a flight post-mortem), ``--deadline``
  preempts on a timer, and the :func:`supervise` restart loop
  (``python -m graphdyn.resilience.supervisor`` /
  ``graphdyn run-supervised``) maps child exit codes to bounded
  auto-restart with crash-loop quarantine (exit :data:`EX_QUARANTINE`).
- :mod:`graphdyn.resilience.store` — the durable checkpoint store every
  consumer reaches via :func:`graphdyn.utils.io.open_checkpoint`:
  SHA-256-verified loads, keep-last-K versioned retention with atomic
  promote, write-behind mirror replication (``--ckpt-mirror``) with
  checksum-verified failover, and the ``run_journal.jsonl`` evidence
  trail. (Exported lazily below — the io↔resilience import order forbids
  importing it here eagerly.)
- :mod:`graphdyn.resilience.soak` — the chaos soak harness
  (``python -m graphdyn.resilience.soak``): seeded, composed-fault
  schedules over the instrumented sites driving real CLI workloads
  through kill/requeue cycles, asserting bit-exact parity with a
  fault-free oracle plus a clean journal story per episode.
"""

from graphdyn.resilience.faults import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedLoweringError,
    InjectedPreemption,
    InjectedUnavailable,
    InjectedWriteError,
    check_fault,
    current_plan,
    is_lowering_failure,
    maybe_fail,
    transform_spec,
    truncate_file,
)
from graphdyn.resilience.retry import (  # noqa: F401
    SAVE_RETRY,
    RetryPolicy,
    retry,
    set_save_retry,
)
from graphdyn.resilience.shutdown import (  # noqa: F401
    EX_ABORT,
    EX_TEMPFAIL,
    ShutdownRequested,
    clear_shutdown,
    graceful_shutdown,
    raise_if_requested,
    request_shutdown,
    shutdown_requested,
)
from graphdyn.resilience.supervisor import (  # noqa: F401
    EX_QUARANTINE,
    RestartPolicy,
    Watchdog,
    beat,
    last_beat,
    supervise,
    supervision,
)

# store.py imports graphdyn.utils.io at module level, and utils.io imports
# THIS package — so the store surface is re-exported lazily (PEP 562): by
# the time anyone asks for these attributes, utils.io is fully initialized.
_STORE_EXPORTS = (
    "ChecksumError",
    "DurableCheckpoint",
    "StoreConfig",
    "configure_store",
    "flush_mirror",
    "validate_journal",
)


def __getattr__(name: str):
    if name in _STORE_EXPORTS:
        from graphdyn.resilience import store as _store

        return getattr(_store, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
