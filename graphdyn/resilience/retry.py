"""Bounded exponential-backoff retry + graceful degradation policy.

The headline workloads are hours-long; the failure economics are asymmetric.
A checkpoint save that hits a transient ENOSPC/EIO must not kill the chain —
the chain IS the value, the snapshot is insurance. Conversely
``init_multihost`` racing a coordinator that is still booting should wait
out the race instead of crashing the whole pod job at t=0. Both are the
same primitive: :func:`retry` with a small bounded budget, then an explicit
policy decision (give up loudly, or degrade and keep computing).

The checkpoint-save budget is process-global (:data:`SAVE_RETRY`) so the CLI
``--max-save-retries`` flag reaches every solver without threading a
parameter through ten signatures.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

log = logging.getLogger("graphdyn.resilience")


@dataclass
class RetryPolicy:
    """``tries`` total attempts (1 = no retry), exponential backoff
    ``base_delay_s * 2**k`` capped at ``max_delay_s``."""

    tries: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0

    def delays(self):
        d = self.base_delay_s
        for _ in range(max(0, self.tries - 1)):
            yield min(d, self.max_delay_s)
            d *= 2.0


# the process-wide checkpoint-save budget (CLI: --max-save-retries). A
# mutable singleton, updated in place — importers hold the object, not a
# snapshot of it.
SAVE_RETRY = RetryPolicy()


def set_save_retry(tries: int) -> None:
    """Set the checkpoint-save retry budget (``tries`` retries after the
    first attempt): the ``--max-save-retries`` knob."""
    SAVE_RETRY.tries = max(1, int(tries) + 1)


def retry(
    fn,
    *,
    policy: RetryPolicy | None = None,
    retry_on: tuple = (OSError,),
    retry_if=None,
    what: str = "operation",
    deadline_s: float | None = None,
    sleep=time.sleep,
):
    """Call ``fn()`` with bounded exponential backoff.

    Retries on ``retry_on`` exceptions only — further narrowed by
    ``retry_if(exc) -> bool`` when given (a deterministic failure dressed
    in a retryable class must surface immediately, not after the whole
    backoff budget); the last failure re-raises. ``deadline_s`` caps the
    total time spent waiting (attempts stop early when the next sleep
    would cross it) — the ``init_multihost`` "retry with deadline"
    contract. Each retry logs a warning with the failure, so a run that
    survived transient trouble says so in its log."""
    from graphdyn import obs

    policy = policy or RetryPolicy()
    t0 = time.monotonic()
    delays = list(policy.delays()) + [None]     # None = no sleep after last
    backoff_total = 0.0
    for attempt, delay in enumerate(delays, start=1):
        try:
            return fn()
        except retry_on as e:
            if retry_if is not None and not retry_if(e):
                raise
            out_of_time = deadline_s is not None and delay is not None and (
                time.monotonic() - t0 + delay > deadline_s
            )
            if delay is None or out_of_time:
                raise
            backoff_total += delay
            # a degraded run must be diagnosable post-hoc: the SITE (what),
            # the attempt number, and the cumulative backoff ride in the
            # log record's fields AND in the obs counter, not only in the
            # formatted message
            log.warning(
                "%s failed (attempt %d/%d, cumulative backoff %.2gs): %s "
                "— retrying in %.2gs",
                what, attempt, len(delays), backoff_total, e, delay,
                extra={"retry_site": what, "retry_attempt": attempt,
                       "retry_backoff_s": delay,
                       "retry_cumulative_backoff_s": backoff_total},
            )
            obs.counter(
                "resilience.retry", site=what, attempt=attempt,
                backoff_s=delay, cumulative_backoff_s=round(backoff_total, 6),
                error=f"{type(e).__name__}: {e}"[:200],
            )
            sleep(delay)
    raise AssertionError("unreachable")         # pragma: no cover
