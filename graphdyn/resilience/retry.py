"""Bounded exponential-backoff retry + graceful degradation policy.

The headline workloads are hours-long; the failure economics are asymmetric.
A checkpoint save that hits a transient ENOSPC/EIO must not kill the chain —
the chain IS the value, the snapshot is insurance. Conversely
``init_multihost`` racing a coordinator that is still booting should wait
out the race instead of crashing the whole pod job at t=0. Both are the
same primitive: :func:`retry` with a small bounded budget, then an explicit
policy decision (give up loudly, or degrade and keep computing).

The checkpoint-save budget is process-global (:data:`SAVE_RETRY`) so the CLI
``--max-save-retries`` flag reaches every solver without threading a
parameter through ten signatures.
"""

from __future__ import annotations

import hashlib
import logging
import time
from dataclasses import dataclass

log = logging.getLogger("graphdyn.resilience")


@dataclass
class RetryPolicy:
    """``tries`` total attempts (1 = no retry), exponential backoff
    ``base_delay_s * 2**k`` capped at ``max_delay_s``.

    ``jitter=True`` switches to **seeded full-jitter**: each delay is drawn
    uniformly from ``(0, bound]`` where ``bound`` is the exponential value
    above, seeded from the retry-site ``key`` passed to :meth:`delays`.
    Multihost ranks retrying the same operation (``multihost.init``, a
    shared-filesystem save) carry distinct keys (rank/pid in the site
    string), so their retries DE-correlate instead of synchronizing into
    storms — while any one site's schedule stays deterministic for tests.
    """

    tries: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: bool = False

    def delays(self, key: str = ""):
        rng = None
        if self.jitter:
            import numpy as _np

            seed = int.from_bytes(
                hashlib.sha256(key.encode()).digest()[:8], "big"
            )
            rng = _np.random.default_rng(seed)
        d = self.base_delay_s
        for _ in range(max(0, self.tries - 1)):
            bound = min(d, self.max_delay_s)
            if rng is None:
                yield bound
            else:
                # full-jitter over (0, bound]: never exceeds the exponential
                # bound, never a 0 that would hammer the resource
                yield float(bound * (1.0 - rng.random()))
            d *= 2.0


# the process-wide checkpoint-save budget (CLI: --max-save-retries). Jittered:
# many hosts retrying a shared-filesystem save must not fire in lockstep. A
# mutable singleton, updated in place — importers hold the object, not a
# snapshot of it.
SAVE_RETRY = RetryPolicy(jitter=True)


def set_save_retry(tries: int) -> None:
    """Set the checkpoint-save retry budget (``tries`` retries after the
    first attempt): the ``--max-save-retries`` knob."""
    SAVE_RETRY.tries = max(1, int(tries) + 1)


def retry(
    fn,
    *,
    policy: RetryPolicy | None = None,
    retry_on: tuple = (OSError,),
    retry_if=None,
    what: str = "operation",
    deadline_s: float | None = None,
    sleep=time.sleep,
):
    """Call ``fn()`` with bounded exponential backoff.

    Retries on ``retry_on`` exceptions only — further narrowed by
    ``retry_if(exc) -> bool`` when given (a deterministic failure dressed
    in a retryable class must surface immediately, not after the whole
    backoff budget); the last failure re-raises. ``deadline_s`` caps the
    total time spent waiting (attempts stop early when the next sleep
    would cross it) — the ``init_multihost`` "retry with deadline"
    contract. Each retry logs a warning with the failure, so a run that
    survived transient trouble says so in its log."""
    from graphdyn import obs

    policy = policy or RetryPolicy()
    t0 = time.monotonic()
    # `what` doubles as the jitter seed key: distinct sites (and distinct
    # ranks, when the caller puts the rank in the site string) draw
    # de-correlated schedules
    delays = list(policy.delays(key=what)) + [None]  # None = no sleep after last
    backoff_total = 0.0
    for attempt, delay in enumerate(delays, start=1):
        try:
            return fn()
        except retry_on as e:
            if retry_if is not None and not retry_if(e):
                raise
            out_of_time = deadline_s is not None and delay is not None and (
                time.monotonic() - t0 + delay > deadline_s
            )
            if delay is None or out_of_time:
                raise
            backoff_total += delay
            # a degraded run must be diagnosable post-hoc: the SITE (what),
            # the attempt number, and the cumulative backoff ride in the
            # log record's fields AND in the obs counter, not only in the
            # formatted message
            log.warning(
                "%s failed (attempt %d/%d, cumulative backoff %.2gs): %s "
                "— retrying in %.2gs",
                what, attempt, len(delays), backoff_total, e, delay,
                extra={"retry_site": what, "retry_attempt": attempt,
                       "retry_backoff_s": delay,
                       "retry_cumulative_backoff_s": backoff_total},
            )
            obs.counter(
                "resilience.retry", site=what, attempt=attempt,
                backoff_s=delay, cumulative_backoff_s=round(backoff_total, 6),
                error=f"{type(e).__name__}: {e}"[:200],
            )
            sleep(delay)
    raise AssertionError("unreachable")         # pragma: no cover
