"""Supervised execution: heartbeat watchdog, run deadlines, bounded restart.

PR 9 made resume a proved durability contract — but nothing *noticed* when a
run needed resuming: a wedged device program, a stalled prefetch thread, or
a solver crash-looping on the same input would sit silently forever, exactly
the failure class that kills long pod-scale jobs (PAPERS.md arXiv:1903.11714
runs fleets where eviction and wedging are the steady state). This module
closes the detect → snapshot → restart → quarantine loop in three layers
(ARCHITECTURE.md "Supervised execution"):

- **Liveness.** Every chunk/rep/λ boundary a driver reaches is a *heartbeat*:
  :func:`beat` bumps a process-global monotonic counter and emits the
  ``obs.heartbeat`` gauge (value = beat count, ``where`` = the boundary name
  — the same ``where=`` vocabulary :class:`~graphdyn.resilience.shutdown
  .ShutdownRequested` carries), so the flight-recorder ring always knows the
  last boundary a run crossed. The :class:`Watchdog` thread watches the
  last-beat age and, past ``stall_timeout_s``, escalates along the PR-2
  ladder: first a graceful-shutdown request (the run snapshots at its next
  boundary and exits 75 — a transient stall costs one requeue, never a wrong
  result), then — if the program stays wedged past the grace window — a hard
  abort (exit :data:`~graphdyn.resilience.shutdown.EX_ABORT` = 130) with a
  flight-recorder post-mortem naming the stalled ``where=``.
- **Deadlines.** ``deadline_s`` triggers the same graceful snapshot +
  exit-75 path on a timer — preemption semantics without a scheduler, so a
  run can be given a time budget and trusted to requeue itself cleanly.
  Both knobs ride on every CLI command (``--stall-timeout`` /
  ``--deadline``, env ``GRAPHDYN_STALL_TIMEOUT`` / ``GRAPHDYN_DEADLINE``).
- **Bounded auto-restart.** :func:`supervise` (CLI: ``python -m
  graphdyn.resilience.supervisor`` / ``graphdyn run-supervised``) wraps any
  graphdyn CLI command and maps child exit codes to policy:

  ====== ==============================================================
  exit   policy
  ====== ==============================================================
  0      done — return success
  75     preemption (graceful snapshot on disk) → resume-restart
         immediately; NOT a failure, resets the crash streak
  130    operator abort / watchdog hard abort → stop, never restart
  other  crash → consecutive same-site counter + seeded full-jitter
         backoff (:class:`~graphdyn.resilience.retry.RetryPolicy`
         keyed by the crash site); after ``quarantine_after`` crashes
         at ONE site the run is **quarantined** — post-mortems bundled,
         journal ``supervise.quarantine``, exit :data:`EX_QUARANTINE` —
         instead of retried forever
  ====== ==============================================================

  The crash *site* comes from the episode's flight post-mortem
  (``obs_postmortem.jsonl`` → the ``obs.crash`` event's ``site``), the
  evidence PR 8 already produces; every episode is recorded in the PR-9
  ``run_journal.jsonl`` (``supervise.start`` / ``supervise.restart`` /
  ``supervise.quarantine`` — :func:`graphdyn.resilience.store
  .validate_journal` schema-checks them).

The watchdog never *decides* a result: its only moves are the two shutdown
codes the PR-2 exit-code contract already defines, so everything downstream
(schedulers, the soak harness, this module's own restart loop) composes.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import logging
import os
import shutil
import subprocess
import sys
import threading
import time

from graphdyn.resilience.retry import RetryPolicy
from graphdyn.resilience.shutdown import EX_ABORT, EX_TEMPFAIL, request_shutdown

log = logging.getLogger("graphdyn.resilience")

_MONO = time.monotonic

#: distinct "quarantined, do NOT requeue" exit code — a scheduler must treat
#: it like 130 (stop; operator attention), never like 75 (requeue): the run
#: has crash-looped at one site and retrying is proven useless
EX_QUARANTINE = 86

ENV_STALL = "GRAPHDYN_STALL_TIMEOUT"
ENV_DEADLINE = "GRAPHDYN_DEADLINE"


def env_float(name: str) -> float | None:
    """Lenient env-var float (the `_env_keep` convention: a typo'd value
    must not crash an otherwise-valid run before it starts)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        log.warning("ignoring unparseable %s=%r", name, raw)
        return None
    return v if v > 0 else None


# ---------------------------------------------------------------------------
# heartbeats (process-global, emitted at every driver boundary)
# ---------------------------------------------------------------------------

_beat_lock = threading.Lock()
_beat_n = 0
_beat_t = _MONO()           # import time: age is bounded before the first beat
_beat_where: str | None = None


def beat(where: str | None = None) -> int:
    """One liveness heartbeat: bump the monotonic counter and emit the
    ``obs.heartbeat`` gauge (value = count). Called at every chunk/rep/λ
    boundary — the same sites that poll the graceful-shutdown flag — so
    "the run reaches boundaries" and "the run is alive" are one fact.
    Near-free: a lock-guarded counter bump plus one gauge event (which the
    null recorder forwards to the bounded flight ring)."""
    global _beat_n, _beat_t, _beat_where
    with _beat_lock:
        _beat_n += 1
        _beat_t = _MONO()
        _beat_where = where
        n = _beat_n
    from graphdyn import obs

    if where is None:
        obs.gauge("obs.heartbeat", n)
    else:
        obs.gauge("obs.heartbeat", n, where=where)
    return n


def last_beat() -> tuple[int, float, str | None]:
    """``(count, monotonic_time, where)`` of the newest heartbeat (the
    watchdog's read side; ``count`` changing is how it tells a new beat from
    a stall that merely spans its poll)."""
    with _beat_lock:
        return _beat_n, _beat_t, _beat_where


# ---------------------------------------------------------------------------
# the watchdog thread (stall detection + deadline)
# ---------------------------------------------------------------------------


def _default_abort() -> None:           # pragma: no cover — kills the process
    os._exit(EX_ABORT)


class Watchdog:
    """A daemon thread enforcing liveness (``stall_timeout_s``) and a run
    time budget (``deadline_s``).

    Escalation ladder on a stall (no heartbeat for ``stall_timeout_s``):

    1. deliver a graceful-shutdown request (:func:`~graphdyn.resilience
       .shutdown.request_shutdown`) and emit ``supervise.stall_detected`` —
       if the program was merely slow, it snapshots at its next boundary
       and exits 75 (requeue-able; conservative by design: once a run has
       been stall-flagged it is preempted even if beats resume, because a
       program that stalls once mid-chain is a program the operator wants
       requeued onto healthier ground);
    2. if NO further heartbeat arrives for another ``grace_s``, the program
       is wedged (a hung device call never returns to a boundary): dump a
       flight post-mortem naming the stalled ``where=`` and hard-abort with
       exit 130 (``abort`` is injectable for tests; the default is
       ``os._exit(EX_ABORT)`` — a wedged program cannot run cleanup).

    A deadline fires the graceful request once, at ``deadline_s`` after
    :meth:`start` — the same snapshot + exit-75 path a SIGTERM takes.

    ``stall_timeout_s`` measures **inter-boundary** gaps; the run's cold
    start (interpreter + jax import + first compile, easily seconds to
    minutes) is not one. Until the first boundary beat of the scope, the
    effective timeout is ``startup_grace_s`` (default
    ``max(4 × stall_timeout, 60 s)``) — a wedged device *init* is still
    caught, but a legitimate cold start never false-preempts a run whose
    timeout was tuned to its steady-state boundary cadence (measured: a
    1.5 s timeout against subprocess episodes paying ~3 s of import cost
    preempted 13 times before finishing).
    """

    def __init__(self, *, stall_timeout_s: float | None = None,
                 deadline_s: float | None = None, grace_s: float | None = None,
                 startup_grace_s: float | None = None,
                 poll_s: float | None = None, abort=None):
        if stall_timeout_s is None and deadline_s is None:
            raise ValueError("watchdog needs a stall timeout or a deadline")
        self.stall_timeout_s = stall_timeout_s
        self.deadline_s = deadline_s
        # the grace window is generous by default: escalation 2 is for a
        # WEDGED program, and the graceful path (escalation 1) may still be
        # writing its shutdown snapshot — aborting mid-save would tear the
        # very state the ladder exists to protect
        self.grace_s = (grace_s if grace_s is not None
                        else max(4.0 * (stall_timeout_s or 0.0), 30.0))
        self.startup_grace_s = (
            startup_grace_s if startup_grace_s is not None
            else max(4.0 * (stall_timeout_s or 0.0), 60.0))
        if poll_s is None:
            cands = [t / 4.0 for t in (stall_timeout_s, deadline_s)
                     if t is not None]
            poll_s = min(1.0, max(0.01, min(cands)))
        self.poll_s = poll_s
        self._abort = abort or _default_abort
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Watchdog":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="graphdyn-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        from graphdyn import obs
        from graphdyn.obs import flight

        t_start = _MONO()
        n_entry = last_beat()[0]        # beats ≤ this are pre-scope
        deadline_fired = False
        stall_beat: int | None = None   # beat count when the stall was flagged
        stall_t = 0.0
        while not self._stop.wait(self.poll_s):
            now = _MONO()
            if (self.deadline_s is not None and not deadline_fired
                    and now - t_start >= self.deadline_s):
                deadline_fired = True
                log.warning(
                    "run deadline of %.3gs reached — requesting graceful "
                    "shutdown (snapshot at next boundary, exit %d)",
                    self.deadline_s, EX_TEMPFAIL,
                )
                obs.counter("supervise.deadline",
                            deadline_s=self.deadline_s,
                            elapsed_s=round(now - t_start, 3))
                request_shutdown()
            if self.stall_timeout_s is None:
                continue
            n, t, where = last_beat()
            age = now - t
            # the cold start is not an inter-boundary gap: until the first
            # boundary beat of this scope, only the startup grace applies
            timeout = (self.stall_timeout_s if n > n_entry
                       else max(self.stall_timeout_s, self.startup_grace_s))
            if age <= timeout:
                continue
            if stall_beat is None or n != stall_beat:
                # first escalation for THIS beat generation: the graceful
                # ladder rung (a new beat arriving later restarts the
                # grace clock via the n != stall_beat comparison)
                stall_beat, stall_t = n, now
                log.warning(
                    "no heartbeat for %.3gs (stall timeout %.3gs; last "
                    "boundary: %s) — requesting graceful shutdown; hard "
                    "abort in %.3gs if the run stays wedged",
                    age, self.stall_timeout_s, where or "<start>",
                    self.grace_s,
                )
                obs.counter("supervise.stall_detected",
                            where=where or "<start>",
                            age_s=round(age, 3),
                            timeout_s=self.stall_timeout_s)
                request_shutdown()
            elif now - stall_t >= self.grace_s:
                # the graceful request was ignored for a whole grace window
                # with zero heartbeats: the program is wedged, not slow
                site = (f"stalled past {where or '<start>'} boundary "
                        f"(no heartbeat for {age:.1f}s)")
                log.error("watchdog hard abort: %s — exiting %d",
                          site, EX_ABORT)
                obs.counter("supervise.stall_abort",
                            where=where or "<start>", age_s=round(age, 3))
                flight.dump("stall", site=site)
                self._abort()
                return


@contextlib.contextmanager
def supervision(stall_timeout_s: float | None = None,
                deadline_s: float | None = None, *,
                grace_s: float | None = None,
                startup_grace_s: float | None = None,
                poll_s: float | None = None, abort=None):
    """Run a scope under a :class:`Watchdog` (no-op when neither knob is
    set — an unsupervised run pays nothing). Emits one heartbeat at entry so
    the stall clock starts at the scope, not at module import."""
    if stall_timeout_s is None and deadline_s is None:
        yield None
        return
    beat("supervise.start")
    wd = Watchdog(stall_timeout_s=stall_timeout_s, deadline_s=deadline_s,
                  grace_s=grace_s, startup_grace_s=startup_grace_s,
                  poll_s=poll_s, abort=abort).start()
    try:
        yield wd
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# the supervisor restart loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RestartPolicy:
    """Exit-code → restart policy of :func:`supervise` (module docstring
    table). ``backoff`` is the PR-9 seeded full-jitter
    :class:`~graphdyn.resilience.retry.RetryPolicy`, keyed per crash site —
    deterministic per site for tests, de-correlated across sites."""

    quarantine_after: int = 3       # consecutive same-site crashes → quarantine
    max_crashes: int = 10           # total crash restarts across all sites
    #: consecutive preemption (exit-75) restarts before the supervisor
    #: gives the run back to the scheduler (exits 75 itself): legitimate
    #: eviction-heavy runs resume and make progress, but a misconfigured
    #: deadline/stall-timeout shorter than the cold start would otherwise
    #: spin forever — bounded auto-restart applies to preemptions too
    max_preempts: int = 100
    max_episodes: int = 1000        # backstop incl. preemption restarts
    backoff: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(
            tries=12, base_delay_s=0.5, max_delay_s=30.0, jitter=True))


def run_subprocess(args: list[str], cwd: str) -> int:
    """The default episode runner: one real ``python -m graphdyn`` child
    process in ``cwd`` (where its flight post-mortem lands). Signal deaths
    map to the 128+N shell convention so the policy table sees one code
    space."""
    os.makedirs(cwd, exist_ok=True)
    proc = subprocess.run([sys.executable, "-m", "graphdyn", *args], cwd=cwd)
    rc = proc.returncode
    return 128 - rc if rc < 0 else rc


def run_inprocess(args: list[str], cwd: str) -> int:
    """In-process episode runner (tests, the soak harness): calls the real
    CLI entry in ``cwd`` and simulates a fresh requeued process — journal
    manifest state and any pending shutdown flag are reset, an injected
    hard preemption maps to 137 (SIGKILL's shell code) and any other escape
    to 1, mirroring what a scheduler would observe."""
    from graphdyn.cli import main as cli_main
    from graphdyn.resilience import faults as _faults
    from graphdyn.resilience.shutdown import clear_shutdown
    from graphdyn.resilience.store import _reset_journal_state

    old = os.getcwd()
    os.makedirs(cwd, exist_ok=True)
    os.chdir(cwd)
    _reset_journal_state()
    clear_shutdown()
    try:
        # graftlint: disable-next-line=GD007  os.devnull is not persistence — nothing can tear
        with open(os.devnull, "w") as devnull, \
                contextlib.redirect_stdout(devnull):
            try:
                return cli_main(args)
            except SystemExit as e:
                return int(e.code) if isinstance(e.code, int) else 1
            except _faults.InjectedPreemption:
                return 137              # hard kill: what SIGKILL looks like
            except KeyboardInterrupt:
                return EX_ABORT
            except BaseException:       # noqa: BLE001 — a crash is exit != 0
                return 1
    finally:
        os.chdir(old)


def crash_site(epdir: str) -> str | None:
    """The failure site named by an episode's flight post-mortem (the last
    ``obs.crash`` event's ``site``), or None when no usable post-mortem
    exists — the supervisor's crash-loop key."""
    from graphdyn.obs.flight import POSTMORTEM_NAME
    from graphdyn.obs.recorder import read_ledger

    path = os.path.join(epdir, POSTMORTEM_NAME)
    if not os.path.exists(path):
        return None
    try:
        events, _ = read_ledger(path)
    except (OSError, ValueError):
        return None
    crashes = [e for e in events
               if e.get("ev") == "counter" and e.get("name") == "obs.crash"]
    if not crashes:
        return None
    return (crashes[-1].get("attrs") or {}).get("site")


#: path-valued CLI flags of the child command. Episodes run in per-episode
#: working directories (<workdir>/ep<N>), so a RELATIVE value would resolve
#: somewhere different every episode — the preempted episode's snapshot
#: would be invisible to the restarted one and every preemption would lose
#: all progress. supervise() rewrites these to absolute paths up front.
_PATH_FLAGS = frozenset((
    "--checkpoint", "--out", "--ckpt-mirror", "--obs-ledger", "--profile",
    "--compile-cache", "--plot",
))


def _absolutize_paths(args: list[str]) -> list[str]:
    """Rewrite the values of :data:`_PATH_FLAGS` (both ``--flag value`` and
    ``--flag=value`` forms) to absolute paths, anchored at the supervisor's
    own cwd — one location for snapshots/results/journal across every
    episode cwd."""
    out: list[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a in _PATH_FLAGS and i + 1 < len(args):
            out += [a, os.path.abspath(args[i + 1])]
            i += 2
            continue
        flag, eq, val = a.partition("=")
        if eq and flag in _PATH_FLAGS:
            out.append(f"{flag}={os.path.abspath(val)}")
            i += 1
            continue
        out.append(a)
        i += 1
    return out


def _checkpoint_dir(child_args: list[str]) -> str | None:
    """The child's checkpoint directory (where the PR-9 run journal lives),
    parsed from its ``--checkpoint`` flag when present."""
    for i, a in enumerate(child_args):
        if a == "--checkpoint" and i + 1 < len(child_args):
            return os.path.dirname(child_args[i + 1]) or "."
        if a.startswith("--checkpoint="):
            return os.path.dirname(a.split("=", 1)[1]) or "."
    return None


def supervise(child_args: list[str], *, workdir: str = ".",
              policy: RestartPolicy | None = None, runner=None,
              stall_timeout_s: float | None = None,
              deadline_s: float | None = None,
              journal_dir: str | None = None,
              sleep=time.sleep, diag=lambda s: None) -> dict:
    """Run a graphdyn CLI command under the restart policy until it
    finishes, is aborted, exhausts its crash budget, or is quarantined.

    Each episode runs in its own ``<workdir>/ep<N>`` directory (so flight
    post-mortems never overwrite each other); crash evidence is copied to
    ``<workdir>/supervise/`` as it happens, and a quarantine writes the
    bundle manifest ``quarantine.json`` there. Every episode transition is
    journaled (``supervise.start`` / ``supervise.restart`` /
    ``supervise.quarantine``) into the child's checkpoint-directory journal
    (fallback: ``workdir``) — the PR-9 evidence trail grows a supervision
    chapter. Returns the report dict ``{"exit", "episodes", "quarantined",
    ...}``; ``exit`` is what a wrapping scheduler should see.
    """
    from graphdyn import obs
    from graphdyn.obs.flight import POSTMORTEM_NAME
    from graphdyn.resilience.store import JOURNAL_NAME, journal_event

    policy = policy or RestartPolicy()
    runner = runner or run_subprocess
    child_args = _absolutize_paths(list(child_args))
    pre: list[str] = []
    if stall_timeout_s is not None:
        pre += ["--stall-timeout", str(stall_timeout_s)]
    if deadline_s is not None:
        pre += ["--deadline", str(deadline_s)]
    args = pre + child_args

    jdir = journal_dir or _checkpoint_dir(child_args) or workdir
    jpath = os.path.join(jdir, JOURNAL_NAME)
    evidence = os.path.join(workdir, "supervise")
    journal_event(jpath, "supervise.start", argv=args,
                  workdir=os.path.abspath(workdir),
                  policy={"quarantine_after": policy.quarantine_after,
                          "max_crashes": policy.max_crashes})

    episodes: list[dict] = []
    crashes = 0
    preempts = 0                    # consecutive 75s, reset by any crash
    streak = 0
    last_site: str | None = None
    delay_gen = None

    def _report(exit_code: int, reason: str, **extra) -> dict:
        return {"exit": exit_code, "reason": reason, "episodes": episodes,
                "quarantined": extra.pop("quarantined", False),
                "journal": jpath, **extra}

    for i in range(policy.max_episodes):
        epdir = os.path.join(workdir, f"ep{i}")
        diag(f"supervise: episode {i}: {' '.join(args)}")
        rc = runner(args, epdir)
        ep = {"episode": i, "rc": rc, "cwd": epdir}
        episodes.append(ep)
        if rc == 0:
            diag(f"supervise: episode {i} finished cleanly")
            return _report(0, "completed")
        if rc == EX_ABORT:
            # operator abort or watchdog hard abort: restarting would
            # override a human (or re-wedge a wedged device) — stop
            diag(f"supervise: episode {i} aborted (exit {rc}) — stopping")
            return _report(EX_ABORT, "aborted")
        if rc in (2, 64):
            # argparse's usage exit (2) / sysexits EX_USAGE (64): the
            # command line itself is wrong — deterministic, so every
            # restart would fail identically; stop NOW instead of burning
            # the crash budget discovering that
            diag(f"supervise: episode {i} exited {rc} (usage error) — a "
                 "misconfigured command cannot be restarted into working")
            return _report(rc, "usage error")
        if rc == EX_TEMPFAIL:
            # a graceful preemption left a snapshot: resume immediately;
            # not a failure, so the crash streak resets
            streak, last_site, delay_gen = 0, None, None
            preempts += 1
            ep["kind"] = "preempt"
            if preempts >= policy.max_preempts:
                # a preemption LOOP (deadline/stall-timeout shorter than
                # the run can make progress in): stop spinning locally and
                # hand the 75 to the wrapping scheduler — the snapshot is
                # on disk, another host may fare better
                diag(f"supervise: {preempts} consecutive preemptions — "
                     f"exiting {EX_TEMPFAIL} (requeue elsewhere)")
                return _report(EX_TEMPFAIL, "preemption budget exhausted")
            journal_event(jpath, "supervise.restart", episode=i, rc=rc,
                          kind="preempt")
            obs.counter("supervise.restart", episode=i, rc=rc,
                        kind="preempt")
            diag(f"supervise: episode {i} preempted (exit 75) — resuming")
            continue
        # a real crash: identify the site, preserve the evidence
        preempts = 0
        crashes += 1
        site = crash_site(epdir) or f"exit:{rc}"
        ep["kind"], ep["site"] = "crash", site
        pm = os.path.join(epdir, POSTMORTEM_NAME)
        if os.path.exists(pm):
            os.makedirs(evidence, exist_ok=True)
            dst = os.path.join(evidence, f"postmortem.ep{i}.jsonl")
            try:
                shutil.copyfile(pm, dst)
                ep["postmortem"] = dst
            except OSError as e:        # evidence is best-effort
                log.warning("could not preserve post-mortem %s: %s", pm, e)
        if site == last_site:
            streak += 1
        else:
            streak, last_site = 1, site
            delay_gen = policy.backoff.delays(key=f"supervise:{site}")
        if streak >= policy.quarantine_after:
            bundle = _quarantine(evidence, site, streak, episodes, args)
            journal_event(jpath, "supervise.quarantine", site=site,
                          crashes=streak, bundle=bundle)
            obs.counter("supervise.quarantine", site=site, crashes=streak)
            log.error(
                "run QUARANTINED after %d consecutive crashes at %s — "
                "refusing further restarts (bundle: %s); exiting %d",
                streak, site, bundle, EX_QUARANTINE,
            )
            diag(f"supervise: QUARANTINED after {streak} crashes at {site}")
            return _report(EX_QUARANTINE, "quarantined", quarantined=True,
                           site=site, bundle=bundle)
        if crashes >= policy.max_crashes:
            diag(f"supervise: crash budget ({policy.max_crashes}) "
                 f"exhausted — stopping with exit {rc}")
            return _report(rc, "crash budget exhausted", site=site)
        delay = next(delay_gen, policy.backoff.max_delay_s)
        ep["backoff_s"] = round(delay, 6)
        journal_event(jpath, "supervise.restart", episode=i, rc=rc,
                      kind="crash", site=site, backoff_s=round(delay, 6),
                      streak=streak)
        obs.counter("supervise.restart", episode=i, rc=rc, kind="crash",
                    site=site, backoff_s=round(delay, 6))
        log.warning(
            "episode %d crashed (exit %d) at %s — restart %d/%d for this "
            "site in %.2gs", i, rc, site, streak, policy.quarantine_after,
            delay,
        )
        sleep(delay)
    return _report(episodes[-1]["rc"] if episodes else 1,
                   "episode budget exhausted")


def _quarantine(evidence: str, site: str, streak: int,
                episodes: list[dict], argv: list[str]) -> str:
    """Write the quarantine bundle manifest next to the preserved
    post-mortems; returns its path (best-effort — quarantine must never
    fail because the evidence disk did)."""
    from graphdyn.utils.io import write_json_atomic

    os.makedirs(evidence, exist_ok=True)
    bundle = os.path.join(evidence, "quarantine.json")
    doc = {
        "site": site,
        "crashes": streak,
        "argv": argv,
        "time_unix": time.time(),
        "episodes": episodes,
        "postmortems": sorted(
            os.path.join(evidence, f)
            for f in os.listdir(evidence) if f.startswith("postmortem.")
        ),
    }
    try:
        write_json_atomic(bundle, doc, indent=1)
    except OSError as e:
        log.warning("could not write quarantine bundle %s: %s", bundle, e)
    return bundle


# ---------------------------------------------------------------------------
# CLI: python -m graphdyn.resilience.supervisor / graphdyn run-supervised
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.resilience.supervisor",
        description="run a graphdyn CLI command under the resilience "
                    "supervisor: heartbeat watchdog, run deadline, bounded "
                    "auto-restart with crash-loop quarantine "
                    "(ARCHITECTURE.md 'Supervised execution')",
        epilog="exit codes: 0 the workload completed; 75 episode budget "
               "exhausted while still preempting (requeue the supervisor); "
               "130 operator/watchdog abort; "
               f"{EX_QUARANTINE} quarantined crash loop (do NOT requeue); "
               "otherwise the child's final exit code",
    )
    ap.add_argument("--stall-timeout", type=float, default=None,
                    metavar="SECS",
                    help="forwarded to the child: its watchdog preempts "
                    "(snapshot + exit 75) when no chunk/rep/lambda boundary "
                    "heartbeat arrives for SECS, and hard-aborts (130) if "
                    "it stays wedged past the grace window")
    ap.add_argument("--deadline", type=float, default=None, metavar="SECS",
                    help="forwarded to the child: per-episode time budget — "
                    "graceful snapshot + exit 75 at SECS (a resumed episode "
                    "gets a fresh budget and continues from its snapshot)")
    ap.add_argument("--quarantine-after", type=int, default=3, metavar="N",
                    help="quarantine after N consecutive crashes at one "
                    "site (default: 3)")
    ap.add_argument("--max-crashes", type=int, default=10, metavar="N",
                    help="total crash-restart budget across sites "
                    "(default: 10)")
    ap.add_argument("--max-preempts", type=int, default=100, metavar="N",
                    help="consecutive preemption (exit-75) restarts before "
                    "the supervisor exits 75 itself — bounds the livelock "
                    "of a deadline/stall-timeout shorter than the run's "
                    "cold start (default: 100)")
    ap.add_argument("--backoff-base", type=float, default=0.5,
                    metavar="SECS", help="crash-restart backoff base "
                    "(seeded full-jitter exponential; default: 0.5)")
    ap.add_argument("--backoff-max", type=float, default=30.0,
                    metavar="SECS", help="crash-restart backoff cap "
                    "(default: 30)")
    ap.add_argument("--workdir", default=".", metavar="DIR",
                    help="episode working directories (ep<N>/) and the "
                    "supervise/ evidence directory live here (default: .)")
    ap.add_argument("--format", choices=["text", "json"], default="text")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="the graphdyn CLI command to supervise "
                    "(conventionally after a '--' separator)")
    args = ap.parse_args(argv)

    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command to supervise (append e.g. -- sa --n 1000 ...)")

    policy = RestartPolicy(
        quarantine_after=max(1, args.quarantine_after),
        max_crashes=max(1, args.max_crashes),
        max_preempts=max(1, args.max_preempts),
        backoff=RetryPolicy(tries=max(2, args.max_crashes + 1),
                            base_delay_s=args.backoff_base,
                            max_delay_s=args.backoff_max, jitter=True),
    )
    report = supervise(
        cmd, workdir=args.workdir, policy=policy,
        stall_timeout_s=args.stall_timeout, deadline_s=args.deadline,
        diag=lambda s: print(s, file=sys.stderr, flush=True),
    )
    if args.format == "json":
        print(json.dumps(report, default=str))
    else:
        for ep in report["episodes"]:
            extra = "".join(
                f" {k}={ep[k]}" for k in ("kind", "site", "backoff_s")
                if k in ep
            )
            print(f"episode {ep['episode']}: exit {ep['rc']}{extra}")
        print(f"supervise: {report['reason']} after "
              f"{len(report['episodes'])} episode(s), exit {report['exit']}")
    return report["exit"]


if __name__ == "__main__":
    sys.exit(main())
