"""Deterministic fault injection — the test harness for every recovery path.

The recovery code in this framework (checkpoint quarantine, retry/degrade
saves, preemption resume, the Pallas→lax fallback) is only trustworthy if it
can be *exercised*: a recovery path without a fault that triggers it is dead
code with a comforting name. This module provides the trigger.

A :class:`FaultPlan` is a context manager holding a list of
:class:`FaultSpec` entries, each naming an **injection site** (a stable
string like ``"checkpoint.write"``), an action, and a deterministic firing
schedule (the ``at``-th hit of that site, optionally ``count`` consecutive
hits, optionally a seeded probability). Production code calls
:func:`maybe_fail`/:func:`check_fault` at the instrumented sites; with no
active plan both are near-zero-cost no-ops (one global check), so the sites
cost nothing in real runs.

Site catalogue (kept in ARCHITECTURE.md "Resilience" in sync with the
instrumented code):

===================  =====================================================
site                 instrumented in
===================  =====================================================
``checkpoint.write`` ``utils.io.Checkpoint.save`` — ``raise`` (ENOSPC) or
                     ``torn`` (partial temp file left behind, then raise)
``checkpoint.read``  ``utils.io.Checkpoint.load`` — ``truncate`` corrupts
                     the on-disk npz before the real loader reads it
``checkpoint.bitrot`` ``resilience.store.DurableCheckpoint.load`` —
                     ``bitrot`` flips bytes inside a completed checkpoint
                     WITHOUT breaking the zip container (member CRCs are
                     recomputed): ``np.load`` succeeds, only the durable
                     store's SHA-256 manifest can catch it
``mirror.write``     ``resilience.store.DurableCheckpoint._mirror_save`` —
                     ``raise`` simulates mirror-path ENOSPC: the primary
                     save proceeds, the journal records the degraded mirror
``mirror.copy``      ``resilience.store.DurableCheckpoint._do_mirror_copy``
                     — polled on the write-behind WORKER thread (env-plan
                     injectable only); ``stall`` delays the replica copy,
                     the graftrace schedule fuzzer's primitive for the
                     flush-vs-exit race (``race_mirror_exit`` soak
                     scenario)
``chunk.boundary``   ``utils.io.ChainCheckpointer.drive`` — ``preempt``
                     raises at the ``at``-th chunk boundary
``rep.boundary``     ``models.sa.sa_ensemble`` / ``models.hpr.hpr_ensemble``
                     — ``preempt`` raises after the ``at``-th repetition
``lambda.boundary``  ``models.entropy._run_ladder`` — ``preempt`` raises
                     after the ``at``-th visited λ
``pallas.lower``     ``ops.bdcm._sweep_core`` (Pallas branch, trace time) —
                     ``raise`` simulates a kernel lowering/compile failure
``sweep.nan``        ``ops.bdcm.make_sweep`` / ``models.entropy
                     .make_fixed_point`` wrappers — ``nan`` poisons the
                     returned carry
``multihost.init``   ``parallel.mesh.init_multihost`` — ``raise`` simulates
                     a coordinator that is not up yet
``serve.admit``      ``serve.admission.admit`` — ``raise`` injects the
                     reject storm: admission stays up but refuses every
                     decision with an "injected" reason (the client-visible
                     failure mode of an overloaded admission tier)
``serve.dispatch``   ``serve.worker.Worker._dispatch`` — ``raise`` simulates
                     transient infrastructure failure in front of the
                     device (coordinator blip, compile-cache NFS hiccup):
                     retried with seeded backoff, then requeued; ``preempt``
                     is the soak harness's mid-stream worker kill
===================  =====================================================

CLI-level tests inject through the ``GRAPHDYN_FAULT_PLAN`` environment
variable (a JSON list of spec dicts); it is consulted only when no
in-process plan is active, so programmatic plans always win.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

log = logging.getLogger("graphdyn.resilience")

ENV_VAR = "GRAPHDYN_FAULT_PLAN"


class InjectedFault(Exception):
    """Base class of every injected failure (so tests and recovery code can
    tell injected faults from organic ones)."""


class InjectedWriteError(InjectedFault, OSError):
    """Simulated persistent-storage write failure (defaults to ENOSPC)."""

    def __init__(self, path: str = ""):
        OSError.__init__(self, errno.ENOSPC, "injected: no space left on device", path)


class InjectedPreemption(InjectedFault):
    """Simulated hard preemption: the process dies *here*, no cleanup."""


class InjectedLoweringError(InjectedFault):
    """Simulated Pallas kernel lowering/compile failure."""


class InjectedUnavailable(InjectedFault, RuntimeError):
    """Simulated transient service unavailability (e.g. coordinator not up)."""


@dataclass
class FaultSpec:
    """One fault: fire ``count`` times starting at the ``at``-th hit of
    ``site`` (1-based, counted per plan activation). ``p`` < 1 makes each
    eligible hit fire with that probability from the plan's seeded stream —
    deterministic given the plan seed. ``match`` restricts firing to hits
    whose ``key`` context value contains it (e.g. a checkpoint path).

    Actions: ``raise`` (site-specific exception), ``preempt`` (hard kill —
    :class:`InjectedPreemption`), ``torn``/``truncate``/``nan``/``bitrot``
    (data transformations applied by the site), ``signal`` (deliver a
    graceful-shutdown request exactly as a SIGTERM handler would — the
    deterministic, race-free way to test the preemption protocol), and
    ``stall`` (sleep ``secs`` at the site then continue — a deterministic
    injectable hang, the watchdog's test primitive: the site stops
    heartbeating for exactly ``secs``)."""

    site: str
    action: str = "raise"   # raise | preempt | torn | truncate | nan | bitrot | signal | stall
    at: int = 1
    count: int = 1
    p: float = 1.0
    match: str | None = None
    secs: float = 0.05      # stall only: how long the site sleeps
    hits: int = field(default=0, init=False)    # per-plan-activation counter
    fired: int = field(default=0, init=False)


class FaultPlan:
    """A deterministic, seedable schedule of injected faults.

    Use as a context manager::

        with FaultPlan([FaultSpec("chunk.boundary", "preempt", at=2)]):
            solver(...)        # raises InjectedPreemption at chunk 2

    Plans nest (a stack); the innermost active plan is consulted. Entering
    the same plan twice resets its hit counters, so one plan object can
    drive several independent runs in a test.
    """

    def __init__(self, specs, *, seed: int = 0):
        self.specs = [
            FaultSpec(**s) if isinstance(s, dict) else s for s in specs
        ]
        self.seed = seed
        self._rng = np.random.default_rng(seed)

    @classmethod
    def from_env(cls, env: str | None = None) -> "FaultPlan | None":
        """Plan from the ``GRAPHDYN_FAULT_PLAN`` JSON (or ``env`` override);
        None when unset/empty. Malformed JSON raises — a CLI test with a
        typo'd plan must fail loudly, not run fault-free and pass."""
        blob = os.environ.get(ENV_VAR, "") if env is None else env
        if not blob.strip():
            return None
        doc = json.loads(blob)
        specs = doc.get("specs", doc) if isinstance(doc, dict) else doc
        seed = doc.get("seed", 0) if isinstance(doc, dict) else 0
        return cls([FaultSpec(**s) for s in specs], seed=seed)

    def __enter__(self) -> "FaultPlan":
        for s in self.specs:
            s.hits = s.fired = 0
        self._rng = np.random.default_rng(self.seed)
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> None:
        _stack().remove(self)
        if any(s.action == "signal" and s.fired for s in self.specs):
            # a fired 'signal' spec set the process-global shutdown flag;
            # clear it on plan exit so the injected request cannot outlive
            # the plan and poison every later solver call in this process
            # (inside a graceful_shutdown scope the request has already
            # been consumed as ShutdownRequested by the time we get here)
            from graphdyn.resilience.shutdown import clear_shutdown

            clear_shutdown()

    def poll(self, site: str, key: str = "") -> FaultSpec | None:
        """The spec that fires on this hit of ``site``, or None. Counts the
        hit on every matching spec regardless of firing."""
        for s in self.specs:
            if s.site != site:
                continue
            if s.match is not None and s.match not in key:
                continue
            s.hits += 1
            in_window = s.at <= s.hits < s.at + s.count
            if in_window and s.fired < s.count:
                if s.p >= 1.0 or self._rng.random() < s.p:
                    s.fired += 1
                    return s
        return None


_local = threading.local()


def _stack() -> list:
    if not hasattr(_local, "plans"):
        _local.plans = []
    return _local.plans


_env_plan_cache: list = []      # [] = unparsed, [None] or [FaultPlan] = parsed
_env_plan_lock = threading.Lock()


def _env_plan() -> FaultPlan | None:
    if not _env_plan_cache:
        # env plans live for the process (never on the with-stack); their
        # hit counters run from the first consulted site onward. Parsed
        # under a lock: sites are polled from worker threads too (the
        # write-behind mirror's `mirror.copy`), and two first-pollers
        # racing the parse would each append a plan with its own hit
        # counters — split counters make `at=` schedules nondeterministic
        with _env_plan_lock:
            if not _env_plan_cache:
                _env_plan_cache.append(FaultPlan.from_env())
    return _env_plan_cache[0]


def current_plan() -> FaultPlan | None:
    """Innermost active plan: an explicit ``with FaultPlan(...)`` wins over
    the process-level ``GRAPHDYN_FAULT_PLAN`` env plan."""
    stack = _stack()
    if stack:
        return stack[-1]
    return _env_plan()


def check_fault(site: str, key: str = "") -> FaultSpec | None:
    """Poll ``site``: the firing :class:`FaultSpec` (for sites that apply a
    data transformation themselves — ``truncate``, ``torn``, ``nan``), or
    None. Near-free with no active plan."""
    plan = current_plan()
    if plan is None:
        return None
    spec = plan.poll(site, key)
    if spec is not None:
        log.warning("fault injected at %s: %s (hit %d)", site, spec.action,
                    spec.hits)
        # fault-site hits land in the event ledger too — a degraded run's
        # post-mortem should not require re-running with the plan
        from graphdyn import obs

        obs.counter("resilience.fault", site=site, action=spec.action,
                    hit=spec.hits, key=key)
        if spec.action == "signal":
            import signal as _signal

            from graphdyn.resilience.shutdown import request_shutdown

            request_shutdown(_signal.SIGTERM)
        elif spec.action == "stall":
            # an injectable hang: the site simply stops making progress (and
            # stops heartbeating) for spec.secs — what a wedged device call
            # or a dead NFS mount looks like from the watchdog's seat. The
            # sleep is the whole fault; execution then continues normally,
            # so an UNsupervised run is perturbed only in wall-clock time.
            # graftrace: disable-next-line=GT005  the injected fault primitive: this sleep IS the hang being simulated, not a synchronization idiom
            time.sleep(spec.secs)
    return spec


def maybe_fail(site: str, key: str = "") -> None:
    """Poll ``site`` and raise the configured exception when a spec fires:
    ``preempt`` → :class:`InjectedPreemption` (a hard kill is a hard kill at
    EVERY site — never downgraded to a site-specific retryable error),
    ``raise`` → the site's specialized exception. Transform-type actions at
    a raise-only site also raise (a misconfigured plan must not silently
    no-op); ``signal``'s and ``stall``'s side effects already happened in
    :func:`check_fault`."""
    spec = check_fault(site, key)
    if spec is None or spec.action in ("signal", "stall"):
        return
    if spec.action == "preempt":
        raise InjectedPreemption(
            f"injected preempt at {site} (hit {spec.hits})"
        )
    if spec.action == "raise":
        if site == "checkpoint.write":
            raise InjectedWriteError(key)
        if site == "pallas.lower":
            raise InjectedLoweringError(
                f"injected lowering failure at {key or site}"
            )
        if site == "multihost.init":
            raise InjectedUnavailable("injected: coordinator unavailable")
        if site == "serve.dispatch":
            raise InjectedUnavailable(
                "injected: dispatch transiently unavailable"
            )
    raise InjectedFault(f"injected {spec.action} at {site} (hit {spec.hits})")


def transform_spec(site: str, expected: str, key: str = "") -> FaultSpec | None:
    """:func:`check_fault` for sites whose firing spec applies a data
    transformation (``truncate``, ``torn``, ``nan``): returns the spec only
    when its action is ``expected``. ``preempt`` raises
    :class:`InjectedPreemption`, any other mismatched action raises
    :class:`InjectedFault` — a plan that names a site must never silently
    no-op; ``signal``/``stall`` return None (their side effects already
    happened)."""
    spec = check_fault(site, key)
    if spec is None or spec.action in ("signal", "stall"):
        return None
    if spec.action == expected:
        return spec
    if spec.action == "preempt":
        raise InjectedPreemption(f"injected preempt at {site} (hit {spec.hits})")
    raise InjectedFault(
        f"injected {spec.action} at {site} (hit {spec.hits}) — this site "
        f"only applies {expected!r}"
    )


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Corrupt an on-disk file by truncating it to ``frac`` of its size —
    the ``checkpoint.read`` fault's payload (a torn download / partial
    flush). A 0-byte result is valid too (frac=0)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, int(size * frac)))


def flip_npz_bytes(path: str, seed: int = 0) -> None:
    """SILENT bit rot: flip bytes inside the largest array member of an npz
    while keeping the zip container valid — the ``checkpoint.bitrot``
    fault's payload.

    The members are rewritten through ``zipfile.writestr``, which recomputes
    each member's CRC-32, so ``np.load`` succeeds and returns wrong data —
    the corruption class only a content checksum (the durable store's
    SHA-256 manifest) can catch. Flips land past the 128-byte npy header so
    the array parses; XOR 0xFF guarantees every flipped byte changes."""
    import zipfile as _zipfile

    rng = np.random.default_rng(seed)
    with _zipfile.ZipFile(path) as z:
        names = z.namelist()
        blobs = {nm: z.read(nm) for nm in names}
    arrays = [nm for nm in names if not nm.startswith("__")] or names
    target = max(arrays, key=lambda nm: len(blobs[nm]))
    b = bytearray(blobs[target])
    lo = min(128, max(0, len(b) - 1))
    for i in rng.integers(lo, len(b), size=min(8, max(1, len(b) - lo))):
        b[i] ^= 0xFF
    blobs[target] = bytes(b)
    tmp = path + ".tmp-bitrot"
    with _zipfile.ZipFile(tmp, "w", _zipfile.ZIP_STORED) as z:
        for nm in names:
            z.writestr(nm, blobs[nm])
    os.replace(tmp, path)


def is_lowering_failure(exc: BaseException) -> bool:
    """Heuristic: does this exception (or its cause/context chain) look like
    a Pallas/Mosaic kernel lowering or compile failure — the class of error
    the runtime lax fallback is allowed to swallow? Injected lowering faults
    count by construction."""
    seen: set[int] = set()
    e: BaseException | None = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, InjectedLoweringError):
            return True
        if isinstance(e, InjectedFault):
            # any OTHER injected fault is by construction not a lowering
            # failure — an InjectedPreemption at the pallas.lower site must
            # kill the run, not trigger the fallback (its message contains
            # "pallas", so the substring scan below would misfire)
            return False
        blob = f"{type(e).__module__}.{type(e).__name__}: {e}".lower()
        if any(tok in blob for tok in
               ("pallas", "mosaic", "triton", "lowering", "unimplemented")):
            return True
        e = e.__cause__ or e.__context__
    return False
