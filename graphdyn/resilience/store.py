"""Durable checkpoint store: checksums, rolling retention, mirror failover.

PR 2 made single faults survivable (atomic writes, quarantine, retry); this
module upgrades the checkpoint layer from "never torn" to a **durability
contract** strong enough for requeue-heavy TPU fleets (PAPERS.md
arXiv:1903.11714 runs pod-scale MC where eviction is the steady state):

- **Checksum-verified loads.** Every save writes a sidecar *manifest*
  (``<path>.manifest.json``) carrying per-array + metadata SHA-256 digests;
  every load recomputes and compares. Silent bit rot — flipped bytes inside
  a structurally valid zip container, which ``np.load`` happily returns —
  becomes a quarantine + fallback instead of a wrong resume. The snapshot
  ``.npz`` format itself is **unchanged** (plain :class:`~graphdyn.utils.io
  .Checkpoint` still reads it; a manifest-less legacy snapshot still loads,
  just unverified).
- **Versioned rolling retention.** Each save first lands as an immutable
  ``<path>.v<N>.npz`` (+ its manifest), then is *promoted* to the published
  ``<path>.npz`` by one hard-link + atomic rename. The last ``keep``
  versions are retained, so a corrupted current file falls back to the
  newest verifiable version — a torn write (or bit rot) can never destroy
  the only good state.
- **Mirror replication** (``--ckpt-mirror DIR`` / ``GRAPHDYN_CKPT_MIRROR``).
  Versions + manifests are copied to a second directory **write-behind** on
  a background worker — the hot path pays only the primary's extra atomic
  rename. When the primary directory is unreadable or every primary
  candidate fails verification, the load fails over to the mirror
  (checksum-verified there too). A mirror write failure degrades (journal +
  warning); the primary save already succeeded and the run proceeds.
- **Run journal** (``run_journal.jsonl`` next to the checkpoints, mirrored
  into the mirror directory). Every save / load / quarantine / failover /
  mirror event is one appended JSON line, following the obs ledger's
  torn-line contract (:func:`graphdyn.obs.recorder.read_ledger` parses it:
  torn tails are sealed on reopen, each process stamps a ``manifest``
  line) — so a requeued run proves exactly-once resume from the journal
  alone.

Load decision table (first verifiable candidate wins)::

    primary <path>.npz  ──verify──> resume            (fast path)
        │ structural corruption / checksum mismatch
        ▼  quarantine <path>.corrupt.<k>.npz
    primary <path>.v<N>.npz, newest first ──verify──> resume (journal: failover)
        │ none verifiable / primary directory unreadable
        ▼
    mirror  <mirror>/<base>.npz, then its versions ──verify──> resume (failover)
        │ none anywhere
        ▼
    None (fresh start) — or re-raise the first transient OSError when
    every candidate failed with one (a disk blip must not silently
    restart an hours-long run).

Every checkpoint consumer (``ChainCheckpointer``, ``PeriodicCheckpointer``,
``GroupDriver``, ``load_validated`` — i.e. the SA/HPr ensembles, the entropy
λ-ladder, sharded SA) routes here via :func:`graphdyn.utils.io
.open_checkpoint`. Fault sites ``checkpoint.bitrot`` (valid-container byte
flips) and ``mirror.write`` (mirror ENOSPC) exercise the two new layers;
:mod:`graphdyn.resilience.soak` composes them into end-to-end scenarios.
"""

from __future__ import annotations

import atexit
import dataclasses
import glob
import hashlib
import json
import logging
import os
import queue
import re
import shutil
import sys
import threading
import time

import numpy as np

from graphdyn.resilience import faults as _faults
# Safe despite the io→resilience package import: this module is only ever
# imported lazily (utils.io.open_checkpoint, the resilience.__getattr__
# export, soak/CLI/tests) — never while utils.io is itself half-initialized.
from graphdyn.utils.io import Checkpoint, _atomic_savez, write_json_atomic

log = logging.getLogger("graphdyn.resilience")

#: manifest schema version, stamped in every sidecar manifest
MANIFEST_SCHEMA = 1

#: journal file name, one per checkpoint directory
JOURNAL_NAME = "run_journal.jsonl"

#: journal event ops (the taxonomy ARCHITECTURE.md documents; validators
#: reject anything else). The ``supervise.*`` ops are appended by the
#: restart loop in :mod:`graphdyn.resilience.supervisor`; the ``serve.*``
#: ops by the job service's spool and worker (:mod:`graphdyn.serve`).
JOURNAL_OPS = (
    "save", "load", "quarantine", "reject", "failover", "read-error",
    "mirror.save", "mirror.degraded", "remove",
    "supervise.start", "supervise.restart", "supervise.quarantine",
    "serve.submit", "serve.done", "serve.refuse", "serve.requeue",
    "serve.evict", "serve.quarantine",
    "stream.churn", "stream.repartition",
)

_VERSION_RE = re.compile(r"\.v(\d+)\.npz$")


class ChecksumError(Exception):
    """A checkpoint's content disagrees with its manifest — silent bit rot
    or a stale/foreign manifest. Treated like structural corruption:
    quarantine + fall back, never resume the wrong state."""


# ---------------------------------------------------------------------------
# store configuration (process-wide, CLI --ckpt-mirror/--ckpt-keep)
# ---------------------------------------------------------------------------


def _env_keep() -> int:
    try:
        return max(1, int(os.environ.get("GRAPHDYN_CKPT_KEEP", "") or 2))
    except ValueError:
        return 2


@dataclasses.dataclass
class StoreConfig:
    """Process-wide durable-store knobs. A mutable singleton like
    :data:`graphdyn.resilience.retry.SAVE_RETRY` — the CLI flags reach every
    solver without threading parameters through ten signatures."""

    mirror: str | None = None   # second directory for write-behind replicas
    keep: int = 2               # retained versions per checkpoint (>= 1)


CONFIG = StoreConfig(
    mirror=os.environ.get("GRAPHDYN_CKPT_MIRROR") or None,
    keep=_env_keep(),
)

_UNSET = object()


def configure_store(mirror=_UNSET, keep=_UNSET) -> StoreConfig:
    """Set the process-wide store config (CLI ``--ckpt-mirror`` /
    ``--ckpt-keep``; omitted fields keep their current value). Returns the
    live singleton."""
    if mirror is not _UNSET:
        CONFIG.mirror = mirror or None
    if keep is not _UNSET:
        CONFIG.keep = max(1, int(keep))
    return CONFIG


# ---------------------------------------------------------------------------
# checksums + manifest
# ---------------------------------------------------------------------------


def array_sha256(a: np.ndarray) -> str:
    """SHA-256 over dtype + shape + bytes — the unit the manifest stores per
    array and every load recomputes."""
    a = np.ascontiguousarray(a)
    h = hashlib.sha256()
    h.update(a.dtype.str.encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def meta_sha256(meta: dict) -> str:
    return hashlib.sha256(
        json.dumps(meta, sort_keys=True, default=str).encode()
    ).hexdigest()


def _manifest_self_sha(doc: dict) -> str:
    body = {k: v for k, v in doc.items() if k != "manifest_sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()


def build_manifest(version: int, payload: dict, meta: dict,
                   meta_key: str) -> dict:
    """The sidecar manifest for one snapshot: per-array + metadata SHA-256
    plus a self-digest (so manifest bit rot is itself detectable)."""
    doc = {
        "schema": MANIFEST_SCHEMA,
        "version": int(version),
        "time_unix": time.time(),
        "arrays": {
            k: {"sha256": array_sha256(v), "dtype": v.dtype.str,
                "shape": list(v.shape)}
            for k, v in payload.items() if k != meta_key
        },
        "meta_sha256": meta_sha256(meta),
    }
    doc["manifest_sha256"] = _manifest_self_sha(doc)
    return doc


def verify_manifest(arrays: dict, meta: dict, manifest: dict) -> None:
    """Raise :class:`ChecksumError` unless ``arrays``/``meta`` match the
    manifest exactly — including the array *set* (a dropped or injected
    array is as wrong as a flipped byte)."""
    if manifest.get("manifest_sha256") != _manifest_self_sha(manifest):
        raise ChecksumError("manifest self-checksum mismatch (manifest rot)")
    want = manifest.get("arrays", {})
    if set(want) != set(arrays):
        raise ChecksumError(
            f"array set mismatch: manifest {sorted(want)} vs "
            f"snapshot {sorted(arrays)}"
        )
    for k, rec in want.items():
        got = array_sha256(arrays[k])
        if got != rec["sha256"]:
            raise ChecksumError(
                f"array {k!r} checksum mismatch "
                f"(stored {rec['sha256'][:12]}…, loaded {got[:12]}…)"
            )
    if meta_sha256(meta) != manifest.get("meta_sha256"):
        raise ChecksumError("metadata checksum mismatch")


def _read_manifest(path: str) -> dict | None:
    """The sidecar manifest, or None when absent/unparseable (an unreadable
    manifest downgrades the snapshot to unverifiable, it does not crash the
    load)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# ---------------------------------------------------------------------------
# run journal (append-only JSONL, obs read_ledger-compatible)
# ---------------------------------------------------------------------------

_journal_lock = threading.RLock()
_journal_manifested: set[str] = set()


def journal_path_for(ckpt_path: str) -> str:
    """The journal shared by every checkpoint in ``ckpt_path``'s directory."""
    return os.path.join(os.path.dirname(ckpt_path) or ".", JOURNAL_NAME)


def _reset_journal_state() -> None:
    """Forget which journals this process already stamped (tests simulating
    a requeued process)."""
    with _journal_lock:
        _journal_manifested.clear()


def journal_event(jpath: str, op: str, **fields) -> None:
    """Append one journal event; **never raises** — the journal is evidence,
    not the value. The first event a process appends to a given journal is
    preceded by sealing any torn tail (a hard-killed prior run may have died
    mid-line) and a ``manifest`` line, exactly the seam
    :func:`graphdyn.obs.recorder.read_ledger` tolerates."""
    try:
        with _journal_lock:
            os.makedirs(os.path.dirname(jpath) or ".", exist_ok=True)
            # re-stamp when the file vanished (the directory died and was
            # recreated mid-process): every journal FILE starts with a
            # manifest, not merely every process
            first = (jpath not in _journal_manifested
                     or not os.path.exists(jpath))
            sealed = False
            if first:
                try:
                    with open(jpath, "rb") as prev:
                        prev.seek(-1, os.SEEK_END)
                        sealed = prev.read(1) != b"\n"
                except (OSError, ValueError):
                    pass            # absent or empty: nothing to seal
            # graftlint: disable-next-line=GD007  append-only JSONL journal: one flushed line per event is the torn-line contract read_ledger tolerates — atomic-replace would destroy append-per-event
            with open(jpath, "a", encoding="utf-8") as f:
                if sealed:
                    f.write("\n")
                if first:
                    _journal_manifested.add(jpath)
                    f.write(json.dumps({
                        "ev": "manifest", "t": 0.0,
                        "run": {"schema": MANIFEST_SCHEMA, "journal": True,
                                "pid": os.getpid(),
                                "time_unix": time.time(),
                                "argv": sys.argv[:8]},
                    }, separators=(",", ":"), default=str) + "\n")
                f.write(json.dumps({
                    "ev": "journal", "t_unix": round(time.time(), 6),
                    "pid": os.getpid(), "op": op, **fields,
                }, separators=(",", ":"), default=str) + "\n")
                f.flush()
    except Exception as e:  # noqa: BLE001 — evidence must not kill the run
        log.warning("run journal append to %s failed: %s", jpath, e)


def validate_journal(path: str) -> tuple[list[dict], list[str]]:
    """Parse + schema-check a run journal. Returns ``(events, problems)`` —
    an empty ``problems`` list is the soak harness's "clean journal story".

    Checks: parseable under the obs torn-line contract, a ``manifest``
    first, every ``journal`` event carries a known ``op`` + its required
    fields, and per-checkpoint save versions are strictly increasing
    (exactly-once: a requeued run never re-publishes an old version)."""
    from graphdyn.obs.recorder import read_ledger

    problems: list[str] = []
    try:
        events, torn = read_ledger(path)
    except (OSError, ValueError) as e:
        return [], [f"unreadable journal: {e}"]
    if torn:
        problems.append(f"{torn} torn line(s) (sealed seams are tolerated)")
    if not events or events[0].get("ev") != "manifest":
        problems.append("journal does not start with a manifest event")
    last_version: dict[str, int] = {}
    required = {
        "save": ("path", "version"),
        "load": ("path", "source", "verified"),
        "quarantine": ("path", "to", "reason"),
        "reject": ("path", "file", "reason"),
        "failover": ("path", "source"),
        "read-error": ("path", "file", "error"),
        "mirror.save": ("path", "version"),
        "mirror.degraded": ("path", "error"),
        "remove": ("path",),
        # the supervisor's restart-loop chapter (no checkpoint path: a
        # supervised run may not checkpoint at all)
        "supervise.start": ("argv",),
        "supervise.restart": ("episode", "rc", "kind"),
        "supervise.quarantine": ("site", "crashes"),
        # the job service's lifecycle chapter (:mod:`graphdyn.serve`):
        # every spool transition is journalled, so "what happened to my
        # job" is answerable from the evidence trail alone
        "serve.submit": ("job", "tenant"),
        "serve.done": ("job", "tenant", "requeues"),
        "serve.refuse": ("job", "tenant", "reason"),
        "serve.requeue": ("job", "tenant", "requeues", "reason"),
        "serve.evict": ("job", "tenant", "requeues"),
        "serve.quarantine": ("job", "tenant", "site", "crashes"),
        # the streamed rollout's churn chapter (:mod:`graphdyn.ops
        # .streamed`): every APPLIED mutation batch is recorded, so a
        # requeued run replays the identical churn from the journal alone
        "stream.churn": ("step", "seq", "adds", "drops"),
        # the sharded streamed engine's churn-driven repartition
        # (:mod:`graphdyn.parallel.stream`): hub promotions/demotions
        # decided at a chunk boundary — deterministic given the churn
        # history, journaled so replay re-derives the identical layout
        # even on a different shard count
        "stream.repartition": ("step", "seq", "promotes", "demotes"),
    }
    for i, ev in enumerate(events):
        kind = ev.get("ev")
        if kind == "manifest":
            continue
        if kind != "journal":
            problems.append(f"event {i}: unknown ev kind {kind!r}")
            continue
        op = ev.get("op")
        if op not in JOURNAL_OPS:
            problems.append(f"event {i}: unknown op {op!r}")
            continue
        for field in required[op]:
            if field not in ev:
                problems.append(f"event {i} ({op}): missing field {field!r}")
        if op == "save":
            p, v = ev.get("path", ""), int(ev.get("version", 0))
            if v <= last_version.get(p, 0):
                problems.append(
                    f"event {i}: save version {v} for {p!r} not above "
                    f"{last_version.get(p, 0)} — re-published version"
                )
            last_version[p] = max(last_version.get(p, 0), v)
    return events, problems


# ---------------------------------------------------------------------------
# write-behind mirror worker
# ---------------------------------------------------------------------------

_mirror_q: queue.Queue = queue.Queue()
_mirror_thread: threading.Thread | None = None
_mirror_thread_lock = threading.Lock()


def _mirror_worker() -> None:
    while True:
        job = _mirror_q.get()
        try:
            job()
        except Exception as e:  # noqa: BLE001 — a mirror is best-effort
            log.warning("mirror job failed: %s", e)
        finally:
            _mirror_q.task_done()


def _ensure_mirror_worker() -> None:
    global _mirror_thread
    with _mirror_thread_lock:
        if _mirror_thread is None or not _mirror_thread.is_alive():
            # graftrace: disable-next-line=GT003  daemon LOOP thread, never joined by design — the bounded close path is flush_mirror(timeout_s): it drains the queue (the thread's whole observable effect) under a deadline, and the atexit hook calls it with timeout_s=10
            _mirror_thread = threading.Thread(
                target=_mirror_worker, name="graphdyn-ckpt-mirror",
                daemon=True,
            )
            _mirror_thread.start()


def flush_mirror(timeout_s: float | None = None) -> None:
    """Block until every enqueued mirror write has drained — called before
    any failover read, on remove, by tests that assert mirror state, and
    at interpreter exit (a run that saves and then returns must not drop
    its queued write-behind replicas on the floor — the whole point of the
    mirror is to survive exactly the runs that end abruptly).

    ``timeout_s`` bounds the wait (the atexit hook uses it: a mirror job
    wedged on a dead filesystem must not hang process shutdown forever —
    it is logged and abandoned instead)."""
    # gate on QUEUE state, not worker liveness (the graftrace GT audit:
    # the old worker-liveness read raced _ensure_mirror_worker's re-arm —
    # a save on another thread could enqueue between our check and our
    # return, and a liveness gate skips a queue with writes in flight).
    # unfinished_tasks only moves enqueue→drain, so a zero here means
    # every write that was enqueued before this call has drained.
    if not _mirror_q.unfinished_tasks:
        return
    # writes ARE in flight: make sure a live worker exists to drain them
    # (covers the enqueue-before-arm window, and a queue stranded by a
    # dead worker — re-arming is exactly what the next save would do)
    try:
        _ensure_mirror_worker()
    except RuntimeError:
        # interpreter shutdown can refuse new threads; nothing can drain
        log.warning(
            "mirror flush: cannot (re)start the worker with %d write(s) "
            "queued — abandoning them (mirror may be stale)",
            _mirror_q.unfinished_tasks,
        )
        return
    if timeout_s is None:
        _mirror_q.join()
        return
    deadline = time.monotonic() + timeout_s
    while _mirror_q.unfinished_tasks:
        if time.monotonic() >= deadline:
            log.warning(
                "mirror flush timed out after %.3gs with %d write(s) still "
                "queued — abandoning them (mirror may be stale)",
                timeout_s, _mirror_q.unfinished_tasks,
            )
            return
        # graftrace: disable-next-line=GT005  bounded drain poll, not synchronization: queue.Queue.join() has no timeout parameter, so the deadline-capped poll IS the bounded join the contract requires
        time.sleep(0.02)


# registered unconditionally at import: a no-op when no mirror worker ever
# started, and the difference between "the mirror has every published save"
# and "the last few replicas silently vanished" when a run exits right
# after saving (regression-tested end to end in tests/test_store.py)
atexit.register(flush_mirror, timeout_s=10.0)


# ---------------------------------------------------------------------------
# the durable checkpoint
# ---------------------------------------------------------------------------


class DurableCheckpoint(Checkpoint):
    """:class:`graphdyn.utils.io.Checkpoint` + the durability contract
    (module docstring): checksum-verified loads, keep-last-K retention with
    atomic promote, write-behind mirror failover, and the run journal.

    A ``Checkpoint`` subclass, so every call site — and every test that
    types ``Checkpoint`` — works unchanged; the published snapshot at
    ``<path>.npz`` keeps the exact PR-2 format (plain ``Checkpoint`` reads
    it, and a plain-written snapshot loads here, just unverified).
    """

    def __init__(self, path: str, *, mirror=_UNSET, keep: int | None = None,
                 journal: bool = True):
        super().__init__(path)
        self._mirror = mirror           # _UNSET → follow CONFIG at call time
        self._keep = keep
        self._journal_enabled = journal

    # -- configuration ---------------------------------------------------

    def _mirror_base(self) -> str | None:
        m = CONFIG.mirror if self._mirror is _UNSET else self._mirror
        if not m:
            return None
        # one subdirectory per primary DIRECTORY (short digest of its
        # absolute path — stable across requeues of the same job): two jobs
        # pointing same-named checkpoints (runA/ck, runB/ck) at one shared
        # mirror would otherwise interleave version sequences, have each
        # job's retention prune the other's newest copies, and offer job
        # B's snapshot to job A on failover. The subdir also gives every
        # job its own mirror run_journal.jsonl (journal_path_for walks up
        # to the dirname).
        d = hashlib.sha256(
            os.path.abspath(os.path.dirname(self.path)).encode()
        ).hexdigest()[:8]
        return os.path.join(m, d, os.path.basename(self.path))

    def _keep_n(self) -> int:
        return max(1, self._keep if self._keep is not None else CONFIG.keep)

    def _journal(self, op: str, **fields) -> None:
        if not self._journal_enabled:
            return
        journal_event(journal_path_for(self.path), op,
                      path=self.path, **fields)
        mbase = self._mirror_base()
        if mbase is not None:
            journal_event(journal_path_for(mbase), op,
                          path=self.path, **fields)

    # -- version bookkeeping --------------------------------------------

    def _versions(self, base: str | None = None) -> list[tuple[int, str]]:
        """Retained ``(version, file)`` pairs for ``base`` (default: the
        primary path), newest first."""
        base = self.path if base is None else base
        out = []
        for f in glob.glob(glob.escape(base) + ".v*.npz"):
            m = _VERSION_RE.search(f)
            if m:
                out.append((int(m.group(1)), f))
        return sorted(out, reverse=True)

    def _next_version(self) -> int:
        """One above the newest retained version — consulting the MIRROR
        too: after a primary-directory death the requeued process sees an
        empty primary, and restarting at v1 would (a) make the surviving
        mirror journal read as a version regression and (b) let mirror
        retention prune the *newest* copies as "oldest". The sequence stays
        monotonic as long as any replica survives, which is the failover
        premise."""
        vs = [v for v, _ in self._versions()]
        mbase = self._mirror_base()
        if mbase is not None:
            vs += [v for v, _ in self._versions(mbase)]
        return (max(vs) + 1) if vs else 1

    def _prune(self, base: str | None = None) -> None:
        for v, f in self._versions(base)[self._keep_n():]:
            for p in (f, f[:-len(".npz")] + ".manifest.json"):
                try:
                    os.remove(p)
                except OSError:
                    pass

    # -- save: version → manifest → promote → retention → mirror ---------

    def _persist(self, payload: dict, meta: dict) -> None:
        # the makedirs / payload validation / checkpoint.write fault gate
        # already ran in the shared Checkpoint.save entry point — overriding
        # _persist (not save) keeps save-patching wrappers (the test
        # suite's abort-after-save fixture) watching durable writes too
        from graphdyn import obs

        with obs.current().span("io.ckpt.write", path=self.path) as sp:
            version = self._next_version()
            vfile = f"{self.path}.v{version}.npz"
            _atomic_savez(vfile, payload)
            man = build_manifest(version, payload, meta, self._META_KEY)
            write_json_atomic(vfile[:-len(".npz")] + ".manifest.json", man)
            self._promote(vfile, man)
            self._prune()
            if obs.enabled():
                sp.set(bytes=int(os.path.getsize(vfile)), version=version)
        self._journal("save", version=version,
                      bytes=int(os.path.getsize(vfile)),
                      manifest_sha=man["manifest_sha256"][:16])
        self._mirror_save(version, vfile, man)

    def _promote(self, vfile: str, man: dict) -> None:
        """Publish ``vfile`` as the current ``<path>.npz``: one hard link +
        one atomic rename (the whole hot-path cost of retention), then the
        current manifest. A crash anywhere in between leaves the version
        file + its manifest intact — the load path's fallback scan finds
        it, so no window destroys the only good state."""
        tmp = self.path + ".promote.tmp.npz"
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
            os.link(vfile, tmp)
        except OSError:
            shutil.copyfile(vfile, tmp)     # filesystems without hard links
        os.replace(tmp, self.path + ".npz")
        write_json_atomic(self.path + ".manifest.json", man)

    def _mirror_save(self, version: int, vfile: str, man: dict) -> None:
        mbase = self._mirror_base()
        if mbase is None:
            return
        # the fault site is polled on the CALLER thread (fault plans are
        # thread-local) — an injected mirror ENOSPC degrades right here,
        # before anything is enqueued; the primary save above already
        # succeeded and the run proceeds
        spec = _faults.check_fault("mirror.write", key=self.path)
        if spec is not None:
            if spec.action == "preempt":
                raise _faults.InjectedPreemption(
                    f"injected preempt at mirror.write ({self.path})"
                )
            self._mirror_degraded(_faults.InjectedWriteError(mbase))
            return
        keep = self._keep_n()

        def job(vfile=vfile, man=man, mbase=mbase, version=version,
                keep=keep):
            try:
                self._do_mirror_copy(vfile, man, mbase, version, keep)
            except OSError as e:
                self._mirror_degraded(e)

        _ensure_mirror_worker()
        _mirror_q.put(job)

    def _do_mirror_copy(self, vfile: str, man: dict, mbase: str,
                        version: int, keep: int) -> None:
        # fault site on the WORKER thread (env-plan injectable: in-process
        # plans are thread-local and never reach here) — `stall` delays the
        # write-behind copy itself, the primitive the graftrace schedule
        # fuzzer uses to widen the flush-vs-exit race deterministically
        _faults.check_fault("mirror.copy", key=mbase)
        os.makedirs(os.path.dirname(mbase) or ".", exist_ok=True)
        mv = f"{mbase}.v{version}.npz"
        tmp = mv + ".tmp"
        shutil.copyfile(vfile, tmp)
        os.replace(tmp, mv)
        write_json_atomic(mv[:-len(".npz")] + ".manifest.json", man)
        ptmp = mbase + ".promote.tmp.npz"
        try:
            if os.path.exists(ptmp):
                os.remove(ptmp)
            os.link(mv, ptmp)
        except OSError:
            shutil.copyfile(mv, ptmp)
        os.replace(ptmp, mbase + ".npz")
        write_json_atomic(mbase + ".manifest.json", man)
        for v, f in self._versions(mbase)[keep:]:
            for p in (f, f[:-len(".npz")] + ".manifest.json"):
                try:
                    os.remove(p)
                except OSError:
                    pass
        self._journal("mirror.save", version=version)

    def _mirror_degraded(self, err: Exception) -> None:
        from graphdyn import obs

        log.warning(
            "mirror replication for %s DEGRADED (%s: %s) — primary "
            "checkpoint is intact, the run continues unmirrored",
            self.path, type(err).__name__, err,
        )
        obs.counter("io.ckpt.mirror.degrade", path=self.path,
                    error=f"{type(err).__name__}: {err}"[:200])
        self._journal("mirror.degraded",
                      error=f"{type(err).__name__}: {err}"[:200])

    # -- load: verify → fall back → fail over ----------------------------

    def load(self):
        flush_mirror()
        cur = self.path + ".npz"
        cur_exists = os.path.exists(cur)
        if cur_exists:
            if _faults.transform_spec("checkpoint.read", "truncate",
                                      key=self.path) is not None:
                _faults.truncate_file(cur)
            if _faults.transform_spec("checkpoint.bitrot", "bitrot",
                                      key=self.path) is not None:
                _faults.flip_npz_bytes(cur)
        candidates: list[tuple[str, str, str]] = []
        if cur_exists:
            candidates.append(("primary", cur, self.path + ".manifest.json"))
        for v, f in self._versions():
            candidates.append(
                ("version", f, f[:-len(".npz")] + ".manifest.json"))
        mbase = self._mirror_base()
        if mbase is not None:
            if os.path.exists(mbase + ".npz"):
                candidates.append(
                    ("mirror", mbase + ".npz", mbase + ".manifest.json"))
            for v, f in self._versions(mbase):
                candidates.append(
                    ("mirror", f, f[:-len(".npz")] + ".manifest.json"))
        if not candidates:
            return None
        from graphdyn import obs

        oserrors: list[OSError] = []
        structural = 0
        with obs.current().span("io.ckpt.read", path=self.path):
            for source, file, manfile in candidates:
                try:
                    arrays, meta = self._read_npz(file)
                    man = _read_manifest(manfile)
                    if man is not None:
                        verify_manifest(arrays, meta, man)
                        verified = True
                    elif source == "primary":
                        # manifest-less legacy/foreign snapshot: loadable,
                        # just unverified (format compatibility)
                        verified = False
                    else:
                        # a FALLBACK candidate exists to prevent a wrong
                        # resume — falling back to something unverifiable
                        # would defeat it
                        raise ChecksumError(
                            "fallback candidate has no manifest")
                except self._STRUCTURAL + (ChecksumError,) as e:
                    structural += 1
                    reason = f"{type(e).__name__}: {e}"[:200]
                    if source == "primary":
                        quarantine = self._quarantine_file(file)
                        log.warning(
                            "checkpoint at %s failed verification (%s) — "
                            "quarantined to %s, trying retained/mirror "
                            "fallbacks", file, reason, quarantine,
                        )
                        obs.counter("io.ckpt.quarantine", path=self.path,
                                    quarantine=quarantine, error=reason)
                        self._journal("quarantine", to=quarantine,
                                      reason=reason)
                    else:
                        log.warning(
                            "checkpoint fallback candidate %s rejected "
                            "(%s)", file, reason,
                        )
                        self._journal("reject", file=file, reason=reason)
                    continue
                except OSError as e:
                    oserrors.append(e)
                    self._journal("read-error", file=file,
                                  error=f"{type(e).__name__}: {e}"[:200])
                    continue
                self._journal("load", source=source, file=file,
                              verified=verified)
                if source != "primary":
                    log.warning(
                        "checkpoint FAILOVER for %s: resuming from %s "
                        "copy %s", self.path, source, file,
                    )
                    obs.counter("io.ckpt.failover", path=self.path,
                                source=source, file=file)
                    self._journal("failover", source=source, file=file)
                return arrays, meta
        if oserrors and not structural:
            # every candidate failed with a transient read error and none
            # was structurally bad: surface it — a disk blip must not
            # silently restart an hours-long run (PR-2 contract)
            raise oserrors[0]
        return None

    # -- cleanup ---------------------------------------------------------

    def remove(self) -> None:
        """End-of-run cleanup: the published snapshot, temp files, every
        retained version + manifest, and the mirror's copies. Quarantined
        evidence (``.corrupt.<k>.npz``) is deliberately kept."""
        flush_mirror()
        removed = False
        bases = [self.path]
        mbase = self._mirror_base()
        if mbase is not None:
            bases.append(mbase)
        for base in bases:
            targets = [base + ".npz", base + ".tmp.npz",
                       base + ".promote.tmp.npz", base + ".manifest.json"]
            for v, f in self._versions(base):
                targets += [f, f[:-len(".npz")] + ".manifest.json",
                            f + ".tmp"]
            for p in targets:
                try:
                    os.remove(p)
                    removed = True
                except FileNotFoundError:
                    pass
        if removed:
            self._journal("remove")
