"""Preemption-safe shutdown: SIGTERM → checkpoint → exit 75.

Preemptible TPU slices get a SIGTERM and a grace window. The difference
between "lost up to ``checkpoint_interval_s`` of work" and "lost nothing"
is whether the solver notices the signal and forces a snapshot at the next
chunk boundary. The difference between "the scheduler requeues the job" and
"the scheduler marks it failed" is the exit code: :data:`EX_TEMPFAIL` (75,
``sysexits.h``'s "temporary failure, retry later") tells any
exit-code-aware scheduler this was a preemption, not a bug.

Protocol:

- the CLI wraps its run/sweep commands in :func:`graceful_shutdown`, which
  converts the first SIGTERM/SIGINT into a *request flag* (no exception —
  signal handlers interrupting a ``np.savez`` would tear the very state we
  are trying to save);
- the chunked drivers (``ChainCheckpointer.drive``, the ensemble rep loops,
  the λ ladder) poll :func:`shutdown_requested` at their natural boundary,
  force an immediate checkpoint save (bypassing the interval gate), and
  raise :class:`ShutdownRequested`;
- the CLI catches it and exits :data:`EX_TEMPFAIL`. A second signal during
  the grace window raises ``KeyboardInterrupt`` immediately — the operator
  asking twice outranks the checkpoint.
"""

from __future__ import annotations

import contextlib
import logging
import signal
import threading

log = logging.getLogger("graphdyn.resilience")

#: sysexits.h EX_TEMPFAIL — "preempted, requeue me" (vs 1 = real failure)
EX_TEMPFAIL = 75

#: 128 + SIGINT, the shell convention for "killed by the operator": the
#: second-signal hard abort (nothing saved — asking twice outranks the
#: checkpoint). Distinct from 75 so schedulers do NOT requeue it.
EX_ABORT = 130


class ShutdownRequested(Exception):
    """Raised by a driver at its chunk boundary after the shutdown snapshot
    is on disk. Carries ``signum`` for logging and ``where`` (the boundary
    that honored the signal — chunk/rep/λ) so the flight recorder's
    post-mortem can name the preemption site; the CLI maps it to exit
    code :data:`EX_TEMPFAIL`."""

    def __init__(self, signum: int | None = None, where: str | None = None):
        self.signum = signum
        self.where = where
        name = signal.Signals(signum).name if signum else "request"
        super().__init__(
            f"graceful shutdown on {name}: checkpointed at "
            f"{where or 'chunk'} boundary"
        )


_flag = threading.Event()
_signum: list = [None]
_depth = 0


def shutdown_requested() -> bool:
    """True once a signal arrived inside a :func:`graceful_shutdown` scope
    (or after :func:`request_shutdown`). Drivers poll this at chunk/rep/λ
    boundaries."""
    return _flag.is_set()


def request_shutdown(signum: int | None = None) -> None:
    """Programmatic equivalent of receiving SIGTERM (used by tests and by
    embedding schedulers that deliver preemption notice out-of-band)."""
    _signum[0] = signum
    _flag.set()


def clear_shutdown() -> None:
    """Clear a pending shutdown request — used by fault plans on exit (an
    injected 'signal' must not outlive its plan) and by embedding
    schedulers that cancel a preemption notice."""
    _flag.clear()
    _signum[0] = None


def raise_if_requested(where: str | None = None) -> None:
    """Raise :class:`ShutdownRequested` if a shutdown is pending — for
    boundaries that have nothing to save (e.g. a driver whose in-flight
    chain already snapshotted). ``where`` names the boundary for the
    post-mortem (chunk/rep/lambda). Every call is also a liveness
    heartbeat (:func:`graphdyn.resilience.supervisor.beat`): any
    ``where=``-annotated boundary a driver reaches tells the watchdog the
    run is alive."""
    from graphdyn.resilience.supervisor import beat

    beat(where)
    if _flag.is_set():
        raise ShutdownRequested(_signum[0], where=where)


@contextlib.contextmanager
def graceful_shutdown(signals=(signal.SIGTERM, signal.SIGINT)):
    """Install handlers converting the first signal into the shutdown flag
    (second signal: immediate ``KeyboardInterrupt``). Re-entrant — nested
    scopes share one flag and only the outermost restores handlers — and a
    no-op off the main thread (Python only delivers signals there; worker
    threads simply inherit the flag)."""
    global _depth
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    prev = {}
    if _depth == 0:
        _flag.clear()
        _signum[0] = None

        def handler(signum, frame):
            if _flag.is_set():
                log.warning("second signal %d: aborting immediately", signum)
                raise KeyboardInterrupt
            log.warning(
                "signal %d: will checkpoint at next chunk boundary and "
                "exit %d", signum, EX_TEMPFAIL,
            )
            request_shutdown(signum)

        for s in signals:
            prev[s] = signal.signal(s, handler)
    _depth += 1
    try:
        yield
    finally:
        _depth -= 1
        if _depth == 0:
            for s, h in prev.items():
                signal.signal(s, h)
            _flag.clear()
            _signum[0] = None
