"""graftcost — HLO-derived byte/FLOP cost models with a committed ledger.

graftcheck (:mod:`graphdyn.analysis.graftcheck`) pins program *structure*;
this module pins program *cost*. The repo carries a family of hand-written
byte models — ``MEM_BANDS``/``packed_state_bytes`` in :mod:`graphdyn.obs.
memband`, the roofline traffic formulas in :mod:`graphdyn.obs.roofline`,
``fused_vmem_bytes`` in :mod:`graphdyn.ops.pallas_anneal`, the pallas_bdcm
VMEM model, ``HaloTables.halo_bytes_per_step`` — and ROADMAP items stake
real decisions on them (VMEM-margin re-centering in the chip round, serve
admission control). Nothing previously checked that those formulas still
describe the programs they model: a kernel rewrite that changes the fused
resident set leaves the hand model silently stale. The TPU Ising literature
(PAPERS.md arXiv:1903.11714) rests its headline on exactly this
bytes-per-update accounting, so the accounting must be *derived*, not
transcribed.

For every graftcheck-ledgered entry point, graftcost walks the compiled
HLO (reusing graftcheck's ``_OP_RE`` / ``_DTYPE_BYTES`` / ``_CATEGORY``
machinery) and derives, per canonical shape:

- **resident bytes** — argument / result / donated bytes parsed from the
  ``entry_computation_layout`` and the ``input_output_alias`` blob, plus
  XLA's temp-buffer size, combined into a peak-live estimate
  ``peak = arg + result − donated + temp``;
- **bytes moved per execution** — every op's output bytes, bucketed into
  graftcheck's traffic classes (gather / scatter / dot / reduce /
  elementwise / layout / collective / …), free plumbing ops and
  outer-loop/fusion wrappers excluded so bodies are counted once;
- **a FLOP estimate per op class** — output-element counts weighted per
  class (2× for dot/reduce, 0 for pure data movement).

Each entry point is evaluated at 2–3 calibration shapes (the size knobs
the graftcheck builders expose) and an affine model ``q(n) = a + b·n`` is
least-squares-fitted per quantity, so the derived models are *functions*
of the size feature, not point samples — ``bench.py`` and ``obs memcheck``
evaluate them at shapes never compiled here. Fits, samples and the
blessed hand-model ratios persist to the committed ``COST_LEDGER.json``
(backend- and jax-version-stamped; ``--update-ledger`` blessing path
exactly like graftcheck).

Rules (exit code = number of findings):

====== ====================================================================
GB101  a derived cost sample drifted from its ledger row beyond the
       per-field band (``_SAMPLE_BANDS``), or the program gained a traffic
       class the ledger never saw — the program's cost changed without a
       blessing
GB102  a registered hand model (``HAND_MODELS``) disagrees with the
       ledger's derived model beyond the committed tolerance at the
       calibration shapes — the hand formula went stale (or the program
       was re-blessed without updating the formula in the same PR)
GB103  an entry point in the graftcheck fingerprint ledger has no cost
       row (or there is no cost ledger at all) — coverage, not drift
GB104  a derived quantity's measured scaling exponent departs from its
       declared one (``CostEntrySpec.declared``), or the affine fit's
       relative residual exceeds the entry's tolerance — the model shape
       itself no longer describes the program
====== ====================================================================

The hand models register through a small adapter table
(:data:`HAND_MODELS`): one row per formula, naming the entry point and
derived quantity it must track and a callable evaluating the formula at
the entry's canonical configuration for a given size. GB102 compares the
*ratio* hand/derived against the ratio blessed at ``--update-ledger``
time: both sides are deterministic, so the shipped tree reproduces the
blessed ratio exactly, a hand-coefficient edit moves it immediately, and
a program re-bless (new derived coefficients) moves it until the hand
formula is updated in the same reviewed PR. Adapters resolve the hand
function at *call time* so a monkeypatched formula is seen (the
falsifiability tests rely on this).

CLI, mirroring graftlint/graftcheck/racecheck (one JSON document on
stdout, diagnostics on stderr, exit code = number of findings)::

    python -m graphdyn.analysis.graftcost [--format=text|json]
        [--update-ledger] [--ledger PATH] [--entries a,b,...]

The ledger records backend and jax version; the checker diffs only when
the live backend matches (the gate runs ``JAX_PLATFORMS=cpu``). The first
TPU round re-centers tolerances on measured ``memory_stats()`` — chip
checklist item in ``scripts/pallas_tpu_validate.py``.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from pathlib import Path
from typing import Callable, NamedTuple

from graphdyn.analysis import graftcheck
from graphdyn.analysis.graftcheck import (
    _CATEGORY,
    _DTYPE_BYTES,
    _OP_RE,
    Finding,
    UnsupportedEntry,
    _canon_rrg,
    _find_alias_blob,
)

RULES = {
    "GB101": "derived cost drifted from the ledger beyond the band",
    "GB102": "hand model disagrees with the derived model beyond tolerance",
    "GB103": "graftcheck-ledgered entry point has no cost row",
    "GB104": "measured scaling exponent departs from the declared one",
}

LEDGER_NAME = "COST_LEDGER.json"

#: |measured − declared| exponent tolerance (GB104). Wide enough for the
#: while-loop entries whose XLA programs carry size-independent terms,
#: tight enough that linear→quadratic (or linear→flat) cannot hide.
EXPONENT_TOL = 0.35

_SHAPE_TOKEN_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

#: ops whose "output" is free plumbing (no buffer written), plus the
#: loop/fusion wrappers whose bodies are printed — and therefore counted —
#: separately (counting the wrapper's carry again would double-charge the
#: whole body output per wrapper level)
_SKIP_OPS = frozenset({
    "parameter", "get-tuple-element", "tuple", "after-all", "bitcast",
    "constant", "while", "conditional", "call", "fusion",
    "optimization-barrier", "copy-start", "copy-done",
})

#: FLOPs per output element by traffic class: data movement computes
#: nothing; dot/reduce do a multiply-add per contribution (2× as the
#: conventional floor); everything arithmetic is 1 op/element
_FLOP_WEIGHT = {
    "elementwise": 1.0, "dot": 2.0, "reduce": 2.0, "rng": 1.0,
    "sort": 1.0, "custom-call": 1.0,
    "layout": 0.0, "gather": 0.0, "scatter": 0.0, "collective": 0.0,
    "hostio": 0.0, "control": 0.0, "constant": 0.0, "fusion": 0.0,
}

#: quantities fitted per entry (the derived models); ``collective_bytes``
#: is ``bytes_by_class["collective"]`` so the halo wire bill gets its own
#: symbolic model
FIT_QUANTITIES = (
    "peak_bytes", "arg_bytes", "result_bytes", "bytes_moved", "flops_est",
    "collective_bytes",
)

#: GB101 per-field bands: (relative, absolute floor). Live and ledger come
#: from the same deterministic compile on the stamped backend, so the
#: shipped tree diffs exactly; the bands exist to absorb jax patch-version
#: jitter, not real drift.
_SAMPLE_BANDS = {
    "arg_bytes": (0.10, 512),
    "result_bytes": (0.10, 512),
    "donated_bytes": (0.10, 512),
    "temp_bytes": (0.50, 4096),
    "peak_bytes": (0.25, 4096),
    "bytes_moved": (0.25, 4096),
    "flops_est": (0.25, 4096),
}


def default_ledger_path() -> Path:
    """The committed cost ledger at the repo root (next to the graftcheck
    fingerprint ledger)."""
    return Path(__file__).resolve().parents[2] / LEDGER_NAME


# ---------------------------------------------------------------------------
# derivation: compiled HLO -> cost facts
# ---------------------------------------------------------------------------


def _find_blob(txt: str, key: str) -> str | None:
    """Brace-balanced body of ``key{...}`` in the module header (the
    :func:`graftcheck._find_alias_blob` walk, generalized)."""
    start = txt.find(key)
    if start < 0:
        return None
    i = start + len(key)
    depth = 1
    while i < len(txt) and depth:
        if txt[i] == "{":
            depth += 1
        elif txt[i] == "}":
            depth -= 1
        i += 1
    return txt[start + len(key):i - 1]


def _token_bytes(dtype: str, dims: str) -> tuple[int, int]:
    """(bytes, elements) of one ``dtype[d0,d1,...]`` shape token."""
    elems = 1
    for d in dims.split(","):
        if d.strip():
            elems *= int(d)
    return _DTYPE_BYTES.get(dtype, 8) * elems, elems


def _shape_bytes(shape_text: str) -> tuple[int, int]:
    """(bytes, elements) of an HLO result type — an array type or a tuple
    type (every ``dtype[dims]`` token summed; layout braces carry no
    tokens)."""
    nbytes = elems = 0
    for m in _SHAPE_TOKEN_RE.finditer(shape_text):
        b, e = _token_bytes(m.group(1), m.group(2))
        nbytes += b
        elems += e
    return nbytes, elems


def derive_cost_text(hlo_text: str) -> dict:
    """The static half of the derivation, from compiled-HLO text alone:
    argument/result/donated bytes from the entry computation layout and
    the alias blob, per-class traffic and FLOP estimates from the op walk.
    ``temp_bytes``/``peak_bytes`` need the executable (see
    :func:`derive_cost`) and are absent here."""
    layout = _find_blob(hlo_text, "entry_computation_layout={")
    arg_list: list[int] = []
    result_bytes = 0
    if layout and "->" in layout:
        args_part, result_part = layout.split("->", 1)
        for m in _SHAPE_TOKEN_RE.finditer(args_part):
            arg_list.append(_token_bytes(m.group(1), m.group(2))[0])
        result_bytes = _shape_bytes(result_part)[0]

    alias = _find_alias_blob(hlo_text)
    donated = sorted(
        {int(p) for p in re.findall(r"\(\s*(\d+)\s*,", alias)}
    ) if alias else []
    donated_bytes = sum(
        arg_list[i] for i in donated if i < len(arg_list)
    )

    bytes_by_class: dict[str, int] = {}
    flops_by_class: dict[str, float] = {}
    for m in _OP_RE.finditer(hlo_text):
        shape, op = m.group(1), m.group(2)
        if op in _SKIP_OPS:
            continue
        nbytes, elems = _shape_bytes(shape)
        cat = _CATEGORY.get(op, "elementwise")
        bytes_by_class[cat] = bytes_by_class.get(cat, 0) + nbytes
        w = _FLOP_WEIGHT.get(cat, 1.0)
        if w:
            flops_by_class[cat] = flops_by_class.get(cat, 0.0) + w * elems

    return {
        "arg_bytes": sum(arg_list),
        "result_bytes": result_bytes,
        "donated_bytes": donated_bytes,
        "bytes_by_class": dict(sorted(bytes_by_class.items())),
        "bytes_moved": sum(bytes_by_class.values()),
        "flops_by_class": {
            k: int(v) for k, v in sorted(flops_by_class.items())
        },
        "flops_est": int(sum(flops_by_class.values())),
    }


def _xla_facts(compiled) -> dict:
    """XLA's own cost/memory analysis, recorded informationally (the
    derived fields above are what the ledger gates — XLA's numbers anchor
    the derivation to ground truth but jitter across versions)."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for key in ("flops", "bytes accessed", "transcendentals"):
            v = ca.get(key)
            if v is not None:
                out[key.replace(" ", "_")] = float(v)
    except Exception:  # noqa: BLE001 — informational; never kills the check
        pass
    try:
        ma = compiled.memory_analysis()
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "alias_size_in_bytes"):
            v = getattr(ma, attr, None)
            if v is not None:
                out[attr] = int(v)
    except Exception:  # noqa: BLE001
        pass
    return out


def derive_cost(lowered) -> dict:
    """Compile a ``jax.stages.Lowered`` and derive its cost facts: the
    text-walk fields plus XLA's temp-buffer size and the combined
    peak-live estimate ``arg + result − donated + temp``."""
    compiled = lowered.compile()
    facts = derive_cost_text(compiled.as_text())
    xla = _xla_facts(compiled)
    facts["temp_bytes"] = int(xla.get("temp_size_in_bytes", 0))
    facts["peak_bytes"] = (
        facts["arg_bytes"] + facts["result_bytes"]
        - facts["donated_bytes"] + facts["temp_bytes"]
    )
    facts["xla"] = xla
    return facts


# ---------------------------------------------------------------------------
# calibration specs + fits
# ---------------------------------------------------------------------------


class CostEntrySpec(NamedTuple):
    """Calibration plan for one graftcheck entry point: the sizes the
    affine models are fitted at (reached via ``lower_entry(name, n=...)``),
    a held-out size the scaling-law tests predict at (never fitted), the
    declared scaling exponents per quantity (GB104 gates the measured
    log-log exponent against these), and the entry's affine-fit residual
    tolerance (the while-loop entries carry size-independent program terms
    and instance-dependent class structure, so their residuals are honest
    but larger)."""

    points: tuple[int, ...]
    holdout: int
    declared: dict[str, float]
    residual_tol: float = 0.12


#: one spec per graftcheck entry point. Declared exponents are seeded from
#: the measured scaling at the calibration shapes (recorded in the ledger
#: per model as ``exponent``) rounded to the claim they support: 1.0 =
#: "dominated by size-linear terms", lower values are honest declarations
#: that the program carries large size-independent structure at these
#: shapes (the grouped while-loop drivers). GB104 fires when the live
#: exponent leaves the ±0.35 band around the declaration.
_LINEAR = {"peak_bytes": 1.0, "arg_bytes": 1.0, "bytes_moved": 1.0,
           "flops_est": 1.0}

COST_ENTRIES: dict[str, CostEntrySpec] = {
    "packed_rollout": CostEntrySpec((128, 256, 512), 384, dict(_LINEAR)),
    # the bucketed kernel's table bytes follow the *edge* count of the
    # seeded power-law family (E/n is near-constant for the canonical
    # gamma=2.5 dmin=2 configuration model), so traffic/flops are
    # size-linear; peak bytes carry the per-bucket scratch intercept at
    # these sizes (measured 0.75 — the honest declaration), and the
    # seeded-realization jitter across sizes earns a looser affine
    # residual band than the regular-graph entries
    "bucketed_rollout": CostEntrySpec(
        (128, 256, 512), 384, {**_LINEAR, "peak_bytes": 0.75},
        residual_tol=0.25),
    # the streamed entry fingerprints ONE chunk's device step (the last,
    # hub-heavy chunk of a K=3 plan over the seeded power-law family,
    # degree cutoff pinned at 64 so the padded hub width is the same
    # power of two at every size — uncapped the width grows ~n^(2/3) and
    # nothing here is affine): C and M scale linearly, with the same
    # seeded-realization jitter allowance as the resident bucketed kernel
    "streamed_rollout": CostEntrySpec(
        (128, 256, 512), 384, dict(_LINEAR), residual_tol=0.25),
    "bdcm_sweep": CostEntrySpec((32, 64, 96), 48, dict(_LINEAR)),
    "entropy_cell_chunk": CostEntrySpec((32, 48, 64), 40, dict(_LINEAR)),
    "hpr_group_loop": CostEntrySpec((16, 24, 32), 20, dict(_LINEAR)),
    # the grouped SA driver carries a large size-independent while-loop
    # program (schedule bookkeeping, swap machinery): at these shapes its
    # cost is intercept-dominated — sublinear measured exponents are the
    # honest declaration, and a silent slide to fully n-linear (or
    # quadratic) traffic still trips the ±0.35 band
    "sa_group_loop": CostEntrySpec(
        (24, 32, 48), 40,
        {"peak_bytes": 0.5, "arg_bytes": 0.9, "bytes_moved": 0.65,
         "flops_est": 0.65}),
    "sharded_rollout": CostEntrySpec(
        (48, 64, 96), 80, {**_LINEAR, "collective_bytes": 1.0}),
    "halo_rollout": CostEntrySpec(
        (96, 128, 192), 160, {**_LINEAR, "collective_bytes": 1.0}),
    # the composed streamed x sharded exchange step (PR 20): one chunk
    # boundary's ppermute slab + hub bit-plane ring over the seeded
    # power-law family (P=2, hub_threshold=12, W=4). Calibration starts
    # at n=192: below that the fixed threshold leaves almost no hubs and
    # the program is constant-dominated (96->128 is FLAT, then the hub
    # count knees) — from 192 up the slab/hub structure tracks n and the
    # measured exponents sit at 0.95..1.03, with the same seeded
    # realization jitter allowance as the bucketed / streamed families
    "streamed_halo": CostEntrySpec(
        (192, 256, 512), 384, {**_LINEAR, "collective_bytes": 1.0},
        residual_tol=0.25),
    # same intercept-dominated shape as sa_group_loop (the ladder's swap
    # machinery is K-, not n-, extensive)
    "tempering_ladder": CostEntrySpec(
        (32, 48, 64), 40,
        {"peak_bytes": 0.75, "arg_bytes": 0.8, "bytes_moved": 0.55,
         "flops_est": 0.55}),
    "fused_anneal": CostEntrySpec(
        (32, 48, 64), 40, {**_LINEAR, "arg_bytes": 0.9}),
}


def _fit_affine(xs, ys) -> tuple[float, float, float]:
    """Least-squares ``y = a + b·x`` over the calibration points →
    (intercept, slope, max relative residual)."""
    k = len(xs)
    sx, sy = sum(xs), sum(ys)
    sxx = sum(x * x for x in xs)
    sxy = sum(x * y for x, y in zip(xs, ys))
    den = k * sxx - sx * sx
    slope = (k * sxy - sx * sy) / den if den else 0.0
    intercept = (sy - slope * sx) / k
    residual = max(
        abs(intercept + slope * x - y) / max(abs(y), 1.0)
        for x, y in zip(xs, ys)
    )
    return intercept, slope, residual


def _scaling_exponent(xs, ys) -> float | None:
    """Measured log-log exponent between the first and last calibration
    point, or None when the quantity is zero at either end (nothing to
    scale — e.g. ``collective_bytes`` of a single-device program)."""
    if ys[0] <= 0 or ys[-1] <= 0:
        return None
    return math.log(ys[-1] / ys[0]) / math.log(xs[-1] / xs[0])


def _quantity(facts: dict, q: str) -> float:
    if q == "collective_bytes":
        return float(facts.get("bytes_by_class", {}).get("collective", 0))
    return float(facts.get(q, 0))


def fit_models(spec: CostEntrySpec, samples: dict[str, dict]) -> dict:
    """Affine models for every :data:`FIT_QUANTITIES` member, from the
    entry's calibration samples."""
    xs = [float(n) for n in spec.points]
    models = {}
    for q in FIT_QUANTITIES:
        ys = [_quantity(samples[str(n)], q) for n in spec.points]
        intercept, slope, residual = _fit_affine(xs, ys)
        models[q] = {
            "intercept": intercept,
            "slope": slope,
            "residual": residual,
            "exponent": _scaling_exponent(xs, ys),
            "declared_exponent": spec.declared.get(q),
        }
    return models


def predict(model: dict, n: float) -> float:
    """Evaluate one fitted model at size ``n``."""
    return float(model["intercept"] + model["slope"] * n)


# ---------------------------------------------------------------------------
# hand-model adapter table (GB102)
# ---------------------------------------------------------------------------


class HandModel(NamedTuple):
    """One registered hand-written byte model: the code location (for the
    ARCHITECTURE.md sync test), the derived quantity it must track, a
    human-readable formula (rendered into the doc table), and a callable
    evaluating the formula at the entry's canonical configuration for size
    ``n``. ``hand`` must resolve the underlying function at *call time*
    (module-attribute lookup, not a captured reference) so the
    falsifiability tests can monkeypatch it."""

    name: str
    module: str
    entry: str
    quantity: str
    formula: str
    hand: Callable[[int], float]
    tolerance: float = 0.05


def _hand_packed_state(n: int) -> float:
    from graphdyn.obs import memband

    return float(memband.packed_state_bytes(n, 3, 4))


def _hand_bucketed_state(n: int) -> float:
    from graphdyn.graphs import degree_buckets, powerlaw_graph
    from graphdyn.obs import memband

    b = degree_buckets(powerlaw_graph(n, gamma=2.5, dmin=2, seed=0))
    return float(memband.bucketed_state_bytes(n, 4, b.table_entries))


def _hand_packed_traffic(n: int) -> float:
    from graphdyn.obs import roofline

    # canonical program: R=128 replicas (W=4 words), steps=4
    # -> n·128·4 spin updates per execution
    return float(roofline.packed_bytes_per_update(3) * n * 128 * 4)


def _hand_bdcm_traffic(n: int) -> float:
    from graphdyn.obs import roofline
    from graphdyn.ops.bdcm import BDCMData

    data = BDCMData(_canon_rrg(n, 3, 1), p=1, c=1)
    return float(sum(
        len(ec.idx) * roofline.bdcm_bytes_per_edge_sweep(ec.d, data.T)
        for ec in data.edge_classes
    ))


def _entropy_stack(n: int):
    from graphdyn.ops.bdcm import BDCMData, stack_bdcm

    return stack_bdcm([
        BDCMData(_canon_rrg(n, 3, k), p=1, c=1) for k in range(2)
    ])


def _hand_stacked_bdcm(n: int) -> float:
    from graphdyn.obs import memband

    return float(memband.stacked_bdcm_bytes(_entropy_stack(n)))


def _hand_entropy_chunk(n: int) -> float:
    from graphdyn.obs import memband

    return float(memband.entropy_chunk_bytes(_entropy_stack(n)))


def _halo_tables(n: int):
    from graphdyn.graphs import partition_graph
    from graphdyn.parallel.halo import build_halo_tables

    g = _canon_rrg(n, 3, 0)
    return build_halo_tables(g, partition_graph(g, 2, seed=0))


def _hand_halo_shard(n: int) -> float:
    from graphdyn.obs import memband

    t = _halo_tables(n)
    return float(sum(
        memband.halo_shard_bytes(int(t.counts[p]), int(t.ghost_counts[p]), 4)
        for p in range(t.P)
    ))


def _hand_halo_wire(n: int) -> float:
    t = _halo_tables(n)
    return float(t.halo_bytes_per_step(4) * 2)   # canonical steps=2


def _hand_streamed_chunk(n: int) -> float:
    from graphdyn.graphs import powerlaw_graph
    from graphdyn.obs import memband
    from graphdyn.ops.streamed import build_stream_plan

    ch = build_stream_plan(
        powerlaw_graph(n, gamma=2.5, dmin=2, dmax=64, seed=0),
        W=4, n_chunks=3,
    ).chunks[-1]
    return float(memband.streamed_chunk_bytes(
        ch.C, ch.M, int(ch.nbr_loc.shape[1]), 4))


def _hand_fused_vmem(n: int) -> float:
    from graphdyn.ops import pallas_anneal

    t = pallas_anneal.build_fused_tables(
        _canon_rrg(n, 3, 0), graftcheck._temper_config()
    )
    return float(pallas_anneal.fused_vmem_bytes(n, 1, t.chi, t.dmax))


def _hand_pallas_bdcm_vmem(n: int) -> float:
    from graphdyn.ops import pallas_bdcm
    from graphdyn.ops.bdcm import BDCMData

    data = BDCMData(_canon_rrg(n, 3, 1), p=1, c=1)
    return float(pallas_bdcm.vmem_bytes(3, data.T, data.num_directed))


HAND_MODELS: tuple[HandModel, ...] = (
    HandModel(
        "packed_state_bytes", "graphdyn.obs.memband",
        "packed_rollout", "arg_bytes",
        "4·n·W + 4·n·d + 4·n  (d=3, W=4)", _hand_packed_state,
    ),
    HandModel(
        "bucketed_state_bytes", "graphdyn.obs.memband",
        "bucketed_rollout", "arg_bytes",
        "4·n·W + 4·T + 4·n  (power-law γ=2.5 dmin=2 seed=0, W=4)",
        _hand_bucketed_state,
    ),
    HandModel(
        "packed_bytes_per_update", "graphdyn.obs.roofline",
        "packed_rollout", "bytes_moved",
        "(d+1)/8 B per spin-update × n·R·steps  (d=3, R=128, steps=4)",
        _hand_packed_traffic,
    ),
    HandModel(
        "bdcm_bytes_per_edge_sweep", "graphdyn.obs.roofline",
        "bdcm_sweep", "bytes_moved",
        "Σ_d |E_d|·4·(d·(K+1)·K·M + K²·M + (d+2)·K²)  (p=c=1)",
        _hand_bdcm_traffic,
    ),
    HandModel(
        "stacked_bdcm_bytes", "graphdyn.obs.memband",
        "entropy_cell_chunk", "arg_bytes",
        "G·(2E+1)·K²·4 + Σ_d G·K²·M_d·4 + 8·index tables  (G=2)",
        _hand_stacked_bdcm,
    ),
    HandModel(
        "entropy_chunk_bytes", "graphdyn.obs.memband",
        "entropy_cell_chunk", "peak_bytes",
        "stacked_bdcm_bytes + chi double-buffer + max DP scratch  (G=2)",
        _hand_entropy_chunk,
    ),
    HandModel(
        "streamed_chunk_bytes", "graphdyn.obs.memband",
        "streamed_rollout", "arg_bytes",
        "4·(M+1)·W + 4·C·width + 8·C + 4·C·W  (last chunk of K=3, W=4)",
        _hand_streamed_chunk,
    ),
    HandModel(
        "halo_shard_bytes", "graphdyn.obs.memband",
        "halo_rollout", "peak_bytes",
        "Σ_shards 4·W·(n_local + n_ghost)  (P=2, W=4)", _hand_halo_shard,
    ),
    HandModel(
        "halo_bytes_per_step", "graphdyn.parallel.halo",
        "halo_rollout", "collective_bytes",
        "4·W·n_slab_words × steps  (W=4, steps=2)", _hand_halo_wire,
    ),
    HandModel(
        "fused_vmem_bytes", "graphdyn.ops.pallas_anneal",
        "fused_anneal", "peak_bytes",
        "4·(n+1)·(W·(2+planes+dmax+1) + χ + 2·(dmax+1) + (2·dmax+1) "
        "+ 6·4·W)  (W=1)", _hand_fused_vmem,
    ),
    HandModel(
        "pallas_bdcm.vmem_bytes", "graphdyn.ops.pallas_bdcm",
        "bdcm_sweep", "peak_bytes",
        "8·K²·M + 8·(K²·(d+2) + K·M)·edges  (p=c=1, shared-A)",
        _hand_pallas_bdcm_vmem,
    ),
)


def hand_model_ratios(entries: dict) -> dict:
    """The blessed-ratio table for the ledger: per registered hand model,
    ``hand(n) / derived_predict(n)`` at each calibration point (None when
    the derived prediction is non-positive at that point)."""
    out = {}
    for hm in HAND_MODELS:
        row = entries.get(hm.entry)
        if not row or "models" not in row:
            continue
        model = row["models"].get(hm.quantity)
        if model is None:
            continue
        ratios = {}
        for n in COST_ENTRIES[hm.entry].points:
            p = predict(model, n)
            ratios[str(n)] = (hm.hand(n) / p) if p > 0 else None
        out[hm.name] = {
            "entry": hm.entry,
            "quantity": hm.quantity,
            "formula": hm.formula,
            "tolerance": hm.tolerance,
            "ratios": ratios,
        }
    return out


def check_hand_models(ledger: dict, *, diag=None) -> list[Finding]:
    """GB102: every registered hand model's live ratio against the derived
    ledger model must match its blessed ratio within the committed
    tolerance. Needs no compilation — the derived side is the committed
    model, the hand side is host-table arithmetic."""
    findings = []
    blessed_all = ledger.get("hand_models", {})
    entries = ledger.get("entries", {})
    for hm in HAND_MODELS:
        row = entries.get(hm.entry)
        if not row or "unsupported" in row or "models" not in row:
            if diag:
                diag(f"graftcost: {hm.name}: no usable cost row for "
                     f"{hm.entry} — GB103 covers the gap")
            continue
        blessed = blessed_all.get(hm.name)
        if blessed is None:
            findings.append(Finding(
                hm.entry, "GB102",
                f"hand model {hm.name!r} ({hm.module}) is registered but "
                f"not blessed in {LEDGER_NAME} — run --update-ledger so "
                "its ratio against the derived model is committed",
            ))
            continue
        tol = float(blessed.get("tolerance", hm.tolerance))
        model = row["models"][hm.quantity]
        for n in COST_ENTRIES[hm.entry].points:
            want = blessed.get("ratios", {}).get(str(n))
            p = predict(model, n)
            if want is None or p <= 0:
                continue
            h = hm.hand(n)
            got = h / p
            if abs(got - want) / max(abs(want), 1e-9) > tol:
                findings.append(Finding(
                    hm.entry, "GB102",
                    f"hand model {hm.name!r} ({hm.module}) drifted from "
                    f"the derived {hm.quantity} model at n={n}: hand "
                    f"{h:.6g} B / derived {p:.6g} B = {got:.4f}, blessed "
                    f"ratio {want:.4f} (tol ±{tol:.0%}) — the formula "
                    "went stale (or a re-blessed program left it behind); "
                    "fix the formula and/or re-run --update-ledger in the "
                    "same reviewed PR",
                ))
    return findings


# ---------------------------------------------------------------------------
# collection + ledger
# ---------------------------------------------------------------------------


def collect_cost_samples(
    entries=None, *, diag=None
) -> dict[str, dict]:
    """Lower + compile every entry at its calibration sizes and derive the
    cost facts; ``{"unsupported": reason}`` rows mirror graftcheck's
    environment-skip contract (the halo entry on a 1-device host)."""
    out: dict[str, dict] = {}
    for name in entries or sorted(COST_ENTRIES):
        spec = COST_ENTRIES[name]
        samples: dict[str, dict] = {}
        try:
            for n in spec.points:
                if diag:
                    diag(f"graftcost: lowering + compiling {name} at n={n}")
                samples[str(n)] = derive_cost(
                    graftcheck.lower_entry(name, n=n)
                )
        except UnsupportedEntry as e:
            if diag:
                diag(f"graftcost: {name} unsupported here: {e}")
            out[name] = {"unsupported": str(e)}
            continue
        out[name] = samples
    return out


def build_ledger_entries(live: dict[str, dict]) -> dict:
    """Ledger rows (samples + fitted models) from live cost samples."""
    rows: dict[str, dict] = {}
    for name, samples in live.items():
        if "unsupported" in samples:
            rows[name] = dict(samples)
            continue
        spec = COST_ENTRIES[name]
        rows[name] = {
            "feature": "n",
            "points": list(spec.points),
            "holdout": spec.holdout,
            "samples": samples,
            "models": fit_models(spec, samples),
        }
    return rows


def load_ledger(path: Path | str | None = None) -> dict | None:
    p = Path(path) if path else default_ledger_path()
    if not p.exists():
        return None
    with open(p) as fh:
        return json.load(fh)


def write_ledger(live: dict[str, dict],
                 path: Path | str | None = None) -> Path:
    """Persist the cost ledger atomically (the GD007 discipline), stamped
    with backend + jax version like the graftcheck ledger."""
    import jax

    from graphdyn.utils.io import write_json_atomic

    rows = build_ledger_entries(live)
    p = Path(path) if path else default_ledger_path()
    write_json_atomic(str(p), {
        "version": 1,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "canon": {
            name: graftcheck.ENTRIES[name].canon
            for name in sorted(rows) if name in graftcheck.ENTRIES
        },
        "entries": rows,
        "hand_models": hand_model_ratios(rows),
    }, indent=2, sort_keys=True)
    return p


def diff_cost_samples(entry: str, ledger_row: dict,
                      live_samples: dict[str, dict]) -> list[Finding]:
    """GB101: per-calibration-point, per-field band diff of the live
    derivation against the ledger row."""
    findings = []
    want_samples = ledger_row.get("samples", {})
    for n_key in sorted(live_samples, key=int):
        live = live_samples[n_key]
        want = want_samples.get(n_key)
        if want is None:
            findings.append(Finding(
                entry, "GB101",
                f"calibration point n={n_key} has no sample in the ledger "
                "row — the calibration plan changed without --update-ledger",
            ))
            continue
        for field, (rel, floor) in _SAMPLE_BANDS.items():
            w = float(want.get(field, 0))
            g = float(live.get(field, 0))
            band = max(float(floor), rel * abs(w))
            if abs(g - w) > band:
                findings.append(Finding(
                    entry, "GB101",
                    f"{field} at n={n_key}: ledger {w:.6g} -> live "
                    f"{g:.6g} (band ±{band:.6g}) — the compiled program's "
                    "cost changed; if deliberate, re-run --update-ledger "
                    "and update the dependent hand models in the same PR",
                ))
        lcls = want.get("bytes_by_class", {})
        vcls = live.get("bytes_by_class", {})
        for cat, got in sorted(vcls.items()):
            if got and cat not in lcls:
                findings.append(Finding(
                    entry, "GB101",
                    f"new traffic class {cat!r} at n={n_key} "
                    f"({got} B) absent from the ledger — the program "
                    "gained a structurally new kind of memory traffic",
                ))
        for cat in sorted(lcls):
            w = float(lcls.get(cat, 0))
            g = float(vcls.get(cat, 0))
            band = max(2048.0, 0.5 * w)
            if abs(g - w) > band:
                findings.append(Finding(
                    entry, "GB101",
                    f"traffic class {cat!r} at n={n_key}: ledger "
                    f"{w:.6g} B -> live {g:.6g} B (band ±{band:.6g})",
                ))
    return findings


def check_exponents(entry: str, spec: CostEntrySpec,
                    live_samples: dict[str, dict]) -> list[Finding]:
    """GB104, in-suite on the live samples: measured log-log scaling
    exponent per declared quantity against the declaration, plus the
    affine-fit residual against the entry's tolerance."""
    findings = []
    xs = [float(n) for n in spec.points]
    for q, declared in sorted(spec.declared.items()):
        ys = [_quantity(live_samples[str(n)], q) for n in spec.points]
        alpha = _scaling_exponent(xs, ys)
        if alpha is None:
            findings.append(Finding(
                entry, "GB104",
                f"{q} declares scaling exponent {declared} but is "
                "non-positive at a calibration endpoint — the quantity "
                "vanished from the program (or the calibration plan broke)",
            ))
            continue
        if abs(alpha - declared) > EXPONENT_TOL:
            findings.append(Finding(
                entry, "GB104",
                f"{q}: measured scaling exponent {alpha:.3f} over "
                f"n={spec.points[0]}..{spec.points[-1]} departs from the "
                f"declared {declared} (tol ±{EXPONENT_TOL}) — the model "
                "shape no longer describes the program (quadratic blowup "
                "or lost size-dependence); update CostEntrySpec.declared "
                "deliberately if the new scaling is intended",
            ))
        _, _, residual = _fit_affine(xs, ys)
        if residual > spec.residual_tol:
            findings.append(Finding(
                entry, "GB104",
                f"{q}: affine-fit relative residual {residual:.3f} "
                f"exceeds the entry tolerance {spec.residual_tol} — "
                "q(n) = a + b·n no longer fits the measured samples "
                "(the program's cost is no longer affine in n at these "
                "shapes)",
            ))
    return findings


def check_coverage(cost_ledger: dict, *, diag=None) -> list[Finding]:
    """GB103: every entry point in the graftcheck fingerprint ledger must
    carry a cost row (coverage, not drift — the cost triad is only
    complete when every structurally-pinned program is also cost-pinned)."""
    gc_ledger = graftcheck.load_ledger()
    names = (
        set(gc_ledger.get("entries", {})) if gc_ledger
        else set(graftcheck.ENTRIES)
    )
    rows = cost_ledger.get("entries", {})
    findings = []
    for name in sorted(names):
        row = rows.get(name)
        if row is None:
            findings.append(Finding(
                name, "GB103",
                "entry point is in the graftcheck fingerprint ledger but "
                f"has no cost row in {LEDGER_NAME} — run `python -m "
                "graphdyn.analysis.graftcost --update-ledger` and commit "
                "the new row",
            ))
        elif "unsupported" in row and diag:
            diag(f"graftcost: ledger row for {name} is an environment "
                 f"skip: {row['unsupported']}")
    return findings


def check_ledger(
    live: dict[str, dict], ledger: dict | None, *, diag=None
) -> list[Finding]:
    """Diff live cost derivations against the committed ledger (GB101 /
    GB104 per entry, GB102 over the hand-model table, GB103 coverage). A
    missing ledger is a GB103 finding per live entry — the gate must fail
    until the contract is committed, never silently pass."""
    import jax

    if ledger is None:
        return [
            Finding(name, "GB103",
                    f"no cost ledger found ({LEDGER_NAME}) — run `python "
                    "-m graphdyn.analysis.graftcost --update-ledger` and "
                    "commit it")
            for name in sorted(live)
        ]
    backend = jax.default_backend()
    if ledger.get("backend") != backend:
        if diag:
            diag(
                f"graftcost: ledger was built on backend="
                f"{ledger.get('backend')!r}, live backend is {backend!r} — "
                "skipping the cost diff (costs are backend-specific; the "
                "gate runs on JAX_PLATFORMS=cpu). Chip rounds re-center "
                "the ledger per scripts/pallas_tpu_validate.py"
            )
        return []
    if ledger.get("jax") != jax.__version__ and diag:
        diag(
            f"graftcost: ledger jax={ledger.get('jax')} != live "
            f"jax={jax.__version__} — diffing anyway (bands absorb minor "
            "drift; re-run --update-ledger after a jax upgrade if needed)"
        )
    findings = check_coverage(ledger, diag=diag)
    flagged = {f.entry for f in findings}
    entries = ledger.get("entries", {})
    for name in sorted(live):
        if "unsupported" in live[name]:
            if diag:
                diag(f"graftcost: skipping {name} diff — "
                     f"{live[name]['unsupported']}")
            continue
        row = entries.get(name)
        if row is None or "unsupported" in row or "models" not in row:
            if name not in flagged:
                findings.append(Finding(
                    name, "GB103",
                    f"no usable cost row in {LEDGER_NAME} — run "
                    "--update-ledger and commit the new row",
                ))
            continue
        findings.extend(diff_cost_samples(name, row, live[name]))
        findings.extend(
            check_exponents(name, COST_ENTRIES[name], live[name])
        )
    findings.extend(check_hand_models(ledger, diag=diag))
    return findings


# ---------------------------------------------------------------------------
# consumers: memcheck cross-check + bench columns
# ---------------------------------------------------------------------------

#: peak-bytes / derived-model bands for the memcheck cross-check rows
#: (``derived:<entry>`` programs in :func:`graphdyn.obs.memband.
#: run_memcheck`). PROVISIONAL like MEM_BANDS: the measured peak includes
#: allocator slop and whatever ran first in the process; the first chip
#: round re-centers them (pallas_tpu_validate checklist).
DERIVED_MEM_BANDS: dict[str, tuple[float, float]] = {
    "derived:packed_rollout": (0.25, 16.0),
    "derived:bucketed_rollout": (0.25, 16.0),
    "derived:fused_anneal": (0.25, 16.0),
}


def derived_peak_bytes(
    entry: str, n: int, ledger: dict | None = None
) -> tuple[float | None, str | None]:
    """Evaluate the committed derived peak-bytes model of ``entry`` at
    size ``n`` — ``(bytes, None)`` or ``(None, reason)`` (the null+reason
    contract: no ledger, backend mismatch, no usable row)."""
    import jax

    ledger = ledger if ledger is not None else load_ledger()
    if ledger is None:
        return None, (
            f"no cost ledger ({LEDGER_NAME}) — run `python -m "
            "graphdyn.analysis.graftcost --update-ledger`"
        )
    backend = jax.default_backend()
    if ledger.get("backend") != backend:
        return None, (
            f"cost ledger was built on backend={ledger.get('backend')!r}, "
            f"live backend is {backend!r} — re-center the ledger on this "
            "backend first (pallas_tpu_validate checklist)"
        )
    row = ledger.get("entries", {}).get(entry)
    if not row or "unsupported" in row or "models" not in row:
        return None, f"no usable cost row for {entry!r} in {LEDGER_NAME}"
    v = predict(row["models"]["peak_bytes"], n)
    if v <= 0:
        return None, (
            f"derived peak model of {entry!r} is non-positive at n={n} "
            "(outside the model's useful range)"
        )
    return float(v), None


def bench_cost_columns(n: int, ledger: dict | None = None) -> dict:
    """The ``bench.py`` row columns: ``derived_bytes`` (the derived
    bytes-moved model of the canonical packed rollout evaluated at the
    bench size) and ``arithmetic_intensity`` (derived FLOP estimate per
    derived byte moved) — or explicit nulls + reasons when the ledger
    cannot speak for this process (missing, other backend). No
    compilation happens here: the committed models are evaluated as
    functions, which is the point of fitting them."""
    import jax

    reason = None
    ledger = ledger if ledger is not None else load_ledger()
    if ledger is None:
        reason = (
            f"no cost ledger ({LEDGER_NAME}) — run `python -m "
            "graphdyn.analysis.graftcost --update-ledger`"
        )
    elif ledger.get("backend") != jax.default_backend():
        reason = (
            f"cost ledger backend {ledger.get('backend')!r} != live "
            f"{jax.default_backend()!r} — derived models are "
            "backend-specific"
        )
    else:
        row = ledger.get("entries", {}).get("packed_rollout")
        if not row or "models" not in row:
            reason = f"no usable packed_rollout cost row in {LEDGER_NAME}"
        else:
            db = predict(row["models"]["bytes_moved"], n)
            fl = predict(row["models"]["flops_est"], n)
            if db <= 0 or fl <= 0:
                reason = (
                    f"derived packed_rollout model non-positive at n={n} "
                    "(outside the model's useful range)"
                )
            else:
                return {
                    "derived_bytes": float(db),
                    "arithmetic_intensity": float(fl / db),
                }
    return {
        "derived_bytes": None,
        "derived_bytes_skipped_reason": reason,
        "arithmetic_intensity": None,
        "arithmetic_intensity_skipped_reason": reason,
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _diag(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.analysis.graftcost",
        description="graftcost: HLO-derived byte/FLOP cost models over "
                    "the committed cost ledger (exit code = number of "
                    "findings)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--ledger", default=None,
                    help=f"ledger path (default: repo-root {LEDGER_NAME})")
    ap.add_argument("--update-ledger", action="store_true",
                    help="recompute every entry's samples + fits and "
                         "rewrite the ledger (incl. blessed hand ratios)")
    ap.add_argument("--entries", default=None,
                    help="comma-separated subset of entry points "
                         f"(default: all of {', '.join(sorted(COST_ENTRIES))})")
    args = ap.parse_args(argv)

    names = sorted(COST_ENTRIES)
    if args.entries:
        names = [e.strip() for e in args.entries.split(",") if e.strip()]
        unknown = [e for e in names if e not in COST_ENTRIES]
        if unknown:
            ap.error(f"unknown entries: {', '.join(unknown)} "
                     f"(known: {', '.join(sorted(COST_ENTRIES))})")

    live = collect_cost_samples(names, diag=_diag)
    findings: list[Finding] = []
    if args.update_ledger:
        if set(names) != set(COST_ENTRIES):
            ap.error("--update-ledger rewrites the WHOLE ledger; it cannot "
                     "be combined with --entries")
        unsupported = sorted(
            n for n, s in live.items() if "unsupported" in s
        )
        if unsupported:
            ap.error(
                "--update-ledger refuses to write a degraded ledger — "
                f"unsupported here: {', '.join(unsupported)} (run under "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
        path = write_ledger(live, args.ledger)
        _diag(f"graftcost: wrote {len(live)} cost row(s) + "
              f"{len(HAND_MODELS)} blessed hand ratio(s) to {path}")
    else:
        findings.extend(
            check_ledger(live, load_ledger(args.ledger), diag=_diag)
        )

    if args.format == "json":
        # exactly ONE JSON document on stdout; diagnostics live on stderr
        print(json.dumps({
            "findings": [f._asdict() for f in findings],
            "cost": {
                name: (
                    samples if "unsupported" in samples else {
                        "samples": samples,
                        "models": fit_models(COST_ENTRIES[name], samples),
                    }
                )
                for name, samples in live.items()
            },
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.entry}: {f.code} {f.message}")
    if findings:
        _diag(f"graftcost: {len(findings)} finding(s)")
    else:
        _diag(f"graftcost: {len(live)} entry point(s) clean")
    return min(len(findings), 125)


if __name__ == "__main__":
    sys.exit(main())
