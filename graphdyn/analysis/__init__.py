"""graphdyn.analysis — static analysis + trace-time contracts.

Two enforcement layers for the invariants the framework's throughput rests
on (ARCHITECTURE.md "Static analysis & contracts"):

- :mod:`graphdyn.analysis.graftlint` — an AST linter (stdlib-only) with
  JAX/TPU-specific rules GD001–GD006.  Run as
  ``python -m graphdyn.analysis graphdyn/ --format=text|json``; the exit
  code is the number of undisabled findings, so it slots straight into
  ``scripts/lint.sh`` and the tier-1 lint-gate test.
- :mod:`graphdyn.analysis.contracts` — the ``@contract`` decorator checking
  shapes/dtypes of jitted-function inputs/outputs at trace time (zero cost
  post-compile), applied to the public kernels in ``ops/`` and
  ``parallel/``.
- :mod:`graphdyn.analysis.graftcheck` — the jaxpr/HLO program auditor:
  fingerprints of the headline compiled programs diffed against the
  committed ``GRAFTCHECK_FINGERPRINTS.json`` ledger (structural regression
  detection without hardware), rules GC001–GC004, and the recompile guard.
  Run as ``python -m graphdyn.analysis.graftcheck [--update-ledger]``.
  NOT imported here: it builds canonical programs (jax + the pipeline
  stack), which would make the pure-AST graftlint CLI pay a device-init
  cost.
- :mod:`graphdyn.analysis.sanitize` — the runtime host-aliasing sanitizer
  (``GRAPHDYN_SANITIZE=alias``): host→device crossings digest their source
  buffers and a mutation during the alias window raises
  :class:`~graphdyn.analysis.sanitize.AliasRaceError` deterministically.
- :mod:`graphdyn.analysis.racecheck` — graftrace, the host-concurrency
  auditor: an AST inventory of the thread/lock/shared-global surface
  diffed against the committed ``CONCURRENCY_LEDGER.json`` (rules
  GT001–GT005), plus the opt-in ``GRAPHDYN_RACECHECK=1`` runtime lock
  proxy with ledger-asserted lock ordering and the ``GRAPHDYN_RACEFUZZ``
  seeded schedule fuzzer. Run as
  ``python -m graphdyn.analysis.racecheck [--update-ledger]``. NOT
  imported here, mirroring graftcheck: the CLI entry stays import-light.
"""

from graphdyn.analysis.contracts import ContractError, contract  # noqa: F401
from graphdyn.analysis.sanitize import (  # noqa: F401
    AliasRaceError,
    alias_sanitizer,
    maybe_alias_sanitizer,
)
from graphdyn.analysis.graftlint import (  # noqa: F401
    Finding,
    RULES,
    lint_paths,
    lint_sources,
    main,
)
