"""graphdyn.analysis — static analysis + trace-time contracts.

Two enforcement layers for the invariants the framework's throughput rests
on (ARCHITECTURE.md "Static analysis & contracts"):

- :mod:`graphdyn.analysis.graftlint` — an AST linter (stdlib-only) with
  JAX/TPU-specific rules GD001–GD006.  Run as
  ``python -m graphdyn.analysis graphdyn/ --format=text|json``; the exit
  code is the number of undisabled findings, so it slots straight into
  ``scripts/lint.sh`` and the tier-1 lint-gate test.
- :mod:`graphdyn.analysis.contracts` — the ``@contract`` decorator checking
  shapes/dtypes of jitted-function inputs/outputs at trace time (zero cost
  post-compile), applied to the public kernels in ``ops/`` and
  ``parallel/``.
"""

from graphdyn.analysis.contracts import ContractError, contract  # noqa: F401
from graphdyn.analysis.graftlint import (  # noqa: F401
    Finding,
    RULES,
    lint_paths,
    lint_sources,
    main,
)
