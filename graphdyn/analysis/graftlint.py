"""graftlint — an AST linter for the JAX/TPU invariants this framework's
performance story rests on (ARCHITECTURE.md "Static analysis & contracts").

The hot kernels must stay inside one fused XLA program: a stray host-NumPy
call, a Python branch on a traced value, or an implicit float64 promotion
inside a jitted body regresses throughput *silently* — results stay correct
while the program gains host round-trips or doubles its HBM traffic. ruff and
mypy cannot see any of this (it is all well-typed Python); these rules can.

Rules (stable codes; each can be silenced per line with
``# graftlint: disable=GDxxx`` plus a reason):

- **GD001** host-NumPy call (``np.*``/``numpy.*``) inside a jit context — a
  function decorated/wrapped with ``jax.jit`` (directly or via
  ``partial(jax.jit, ...)``) or passed as a body to ``lax.while_loop`` /
  ``lax.scan`` / ``lax.fori_loop``.  NumPy executes on the host at trace
  time; on traced values it either crashes or silently constant-folds.
  Dtype scalar constructors (``np.int32(…)`` etc.) are exempt — they are
  trace-time constants by construction.
- **GD002** Python ``if``/``while``/``for`` branching on a traced value
  (heuristic: the condition references a jit-function parameter that is not
  in ``static_argnums``/``static_argnames``).  Python control flow runs at
  trace time; on traced operands it raises ``TracerBoolConversionError`` —
  or worse, specializes the program to one branch.
- **GD003** host sync inside a hot path: ``.item()``, ``float(…)``,
  ``int(…)``, ``np.asarray(…)`` on device arrays inside jitted/loop bodies —
  each one is a device→host transfer that serializes the step loop.
- **GD004** dtype-contract violation: literal ``jnp.float64``/``np.float64``
  anywhere, and dtype-less ``jnp.ones``/``jnp.zeros``/``jnp.arange`` inside
  ``graphdyn/ops/`` and ``graphdyn/parallel/`` where the int8-spin /
  int32-sum / f32-message contract (ARCHITECTURE.md dtype table) is
  normative and the float default would double message HBM traffic.
- **GD005** jit hygiene: a string/enum/config-typed parameter of a jitted
  function not declared static (every distinct value retraces — or fails to
  hash), or a static parameter with an unhashable (list/dict/set) default.
- **GD006** a rollout-shaped jitted entry point (name matches
  ``rollout``/``scan``, or the body carries a ``lax`` loop) without
  ``donate_argnums``/``donate_argnames``: the large state buffer is
  double-buffered in HBM instead of updated in place.
- **GD007** non-atomic persistence: a direct ``np.savez``/
  ``np.savez_compressed`` or ``open(..., "w")`` write to a non-temp path
  anywhere except ``utils/io.py``.  A preemption mid-write leaves a torn
  file that poisons the next resume; every durable write must go through
  the atomic writers in :mod:`graphdyn.utils.io` (temp file +
  ``os.replace``).  Paths whose expression mentions ``tmp``/``temp`` are
  exempt — writing the temp half of the discipline is the point.
- **GD008** per-iteration host→device transfer: ``jnp.asarray``/
  ``jnp.array``/``jax.device_put`` of host-built arrays inside a Python
  ``for``-loop in a *driver module* (``graphdyn/models/``,
  ``graphdyn/pipeline/``, ``cli.py``).  Each iteration re-ships a fresh
  host buffer while the device idles — the serial-ensemble anti-pattern
  the pipeline exists to remove (stack the per-iteration tables once and
  run one vmapped program; overlap host builds with
  :class:`graphdyn.pipeline.prefetch.HostPrefetcher`).  ``for``-loops
  inside jit contexts are exempt (they unroll at trace time — no per-step
  transfer exists).
- **GD009** ``jax.vmap`` applied to a ``pallas_call``-backed callable
  (a function whose body — directly or through module-local calls —
  reaches ``pl.pallas_call``, a name bound to one, or a lambda/partial
  wrapping one).  ``vmap`` has no batching rule for a custom kernel: it
  lowers to a SERIAL loop of per-element kernel launches, silently
  forfeiting the batch parallelism the kernel was written for.  Make the
  batch axis a Pallas **grid dimension** instead (cf.
  ``ops/pallas_bdcm.dp_contract_grouped`` — the group axis is
  ``grid[0]``, never a vmap).
- **GD010** ``jnp.asarray`` on a *mutable host buffer* in a driver module
  (``graphdyn/models/``, ``graphdyn/pipeline/``, ``cli.py``): a name the
  same function also mutates in place (subscript assignment / ``.fill``
  etc.).  On the CPU backend ``asarray`` may ALIAS the numpy buffer for
  the device array's whole lifetime, so the later mutation races the
  asynchronous device reads — the PR-4 nondeterminism class.  Use
  ``jnp.array`` (explicit copy) at the crossing; the runtime half of this
  contract is the ``GRAPHDYN_SANITIZE=alias`` sanitizer
  (:mod:`graphdyn.analysis.sanitize`), which turns a surviving race into
  a deterministic failure.
- **GD011** bare wall-clock timing (``time.time()`` /
  ``time.perf_counter()``) in a driver module (``graphdyn/models/``,
  ``graphdyn/pipeline/``, ``cli.py``, ``bench.py``) outside the obs API.
  Ad-hoc brackets fragment the repo's timing into idioms the event ledger
  never sees — a rate measured with a private ``perf_counter`` pair is
  invisible to ``python -m graphdyn.obs report`` and to the bench trend
  gate.  Use :func:`graphdyn.obs.timed` (always measures; emits a span
  event when recording) or :func:`graphdyn.obs.span`; ``time.monotonic``
  stays allowed — it is the bookkeeping clock (queue waits, deadlines),
  not a measurement idiom.  ``graphdyn/obs/`` itself and
  ``utils/profiling.py`` (the deprecated shim) are the implementation and
  are out of scope by module.
- **GD012** bare ``jax.profiler`` capture/annotation calls
  (``start_trace``/``stop_trace``/``trace``/``TraceAnnotation``/
  ``StepTraceAnnotation``/``annotate_function``) anywhere outside
  ``graphdyn/obs/``.  A privately started trace misses the span-aligned
  ``TraceAnnotation`` names the obs layer adds (the device timeline and
  the JSONL ledger share one vocabulary — ARCHITECTURE.md "Runtime
  telemetry"), and a stray ``start_trace`` inside a run that is already
  profiling crashes the process-global profiler.  Use
  :func:`graphdyn.obs.trace.profiling` (CLI ``--profile`` /
  ``GRAPHDYN_PROFILE``); span annotations come for free from
  ``obs.span``/``obs.timed``.
- **GD013** full-node-axis data movement inside a shard-mapped body in
  ``graphdyn/parallel/``: a ``lax.all_gather`` call, or a ``jnp.take``
  whose operand was assigned from one.  The halo exchange
  (:mod:`graphdyn.parallel.halo`) exists so a node-sharded synchronous
  step moves only the partition's BOUNDARY spin words (one ``ppermute``
  slab per shard offset — per-step bytes scale with the edge cut); an
  ``all_gather`` of the state re-ships every shard's words to every
  device every step, the exact O(n) collective the node sharding is
  supposed to remove.  Scope: functions passed to ``shard_map`` and the
  module-local functions they call.  The legacy gather-mode solver keeps
  reasoned per-line disables (it is the parity baseline the halo mode is
  tested against, and the small-graph fallback).
- **GD014** host round-trip inside a search drive loop: ``np.asarray``
  (dotted or import-aliased), ``jax.device_get``, ``.item()``,
  ``.block_until_ready()``, or an ``int()``/``float()`` coercion of a
  non-literal, inside a host ``for``/``while`` loop of a
  ``graphdyn/search/`` module.  The tempering
  chunk+swap and the chromatic sweep are designed as ONE device program
  per chunk boundary — the only sanctioned per-chunk sync is the
  ``bool(jnp.any(…))`` stop test, and results read back ONCE after the
  loop.  A per-chunk ``np.asarray`` (materializing swap statistics or
  lane states every boundary) serializes the ladder on the host link
  exactly the way the pre-pipeline serial drivers did.  Loops inside jit
  contexts are exempt (they unroll at trace time); the checkpoint payload
  goes through ``ChainCheckpointer`` (``utils/io`` — out of scope), which
  only materializes when a snapshot is actually due.
- **GD015** per-temperature-step host sync in a ``graphdyn/models/``
  anneal drive loop: ``.item()``, ``.block_until_ready()``,
  ``jax.device_get``, or a ``bool()``/``int()``/``float()`` coercion of
  a ``jnp.``/``jax.``-rooted call, inside a host ``for``/``while`` loop.  Every solver's anneal schedule
  advances INSIDE its device loop (``metropolis_anneal_update``; the
  fused annealer ``ops/pallas_anneal`` keeps an entire run on device
  between snapshot boundaries), so a drive loop that reads a device
  value back per schedule step serializes the anneal on the host link —
  the exact round-trip class ROADMAP item 7 removes.  search/ chunk
  loops get the coarser GD014 with its sanctioned per-chunk stop test;
  models/ loops are per-rep/per-λ/per-step and get no such sanction.
- **GD016** hand-rolled byte-size arithmetic outside the sanctioned cost
  modules: a ``4``/``8`` itemsize literal multiplying two or more shape
  variables (``4 * n * W``), or ``.nbytes`` aggregated through ``sum()``
  or arithmetic, in a ``graphdyn/`` module that is NOT one of the
  registered cost-model homes (``obs/memband.py``, ``obs/roofline.py``,
  ``ops/pallas_*.py``, ``parallel/halo.py``, ``analysis/graftcost.py``).
  Every byte model the repo stakes decisions on is gated against the
  HLO-*derived* models by graftcost's GB102 (ARCHITECTURE.md "Cost-model
  contracts"); a byte formula floating free in ordinary code is exactly
  the hand transcription that goes silently stale — register it as a
  :data:`graphdyn.analysis.graftcost.HAND_MODELS` adapter or move it
  into a sanctioned module.
- **GD017** ghost-padded node-table construction outside ``graphs.py``:
  a ``np.full``/``jnp.full`` whose shape is a ≥2-element tuple and whose
  fill value is a non-constant expression that ALSO appears as one of
  the shape dimensions — the ``np.full((n, dmax), n)`` idiom that pads a
  per-node neighbor table with the dimension-sized ghost id.  The padded
  ``nbr[n, dmax]`` layout charges EVERY node the maximum degree, which a
  single power-law hub inflates by orders of magnitude (ROADMAP item 3);
  layouts therefore come from the ``graphs.py`` builders (which the
  degree-bucketed fast path, :func:`graphdyn.graphs.degree_buckets`, can
  replace wholesale), not from ad-hoc ``full`` constructions scattered
  through kernels.  The single-ghost-ROW extension ``full((1, dmax), n)``
  stays legal everywhere (the fill matches no dimension).

Escape hatches, all requiring an explicit code list (``all`` allowed):

- same line:      ``# graftlint: disable=GD001,GD003  <reason>``
- line before:    ``# graftlint: disable-next-line=GD004  <reason>``
- whole file:     ``# graftlint: disable-file=GD006  <reason>``

The linter is stdlib-only (``ast`` + ``tokenize``-free line scanning) so the
lint gate needs no third-party installs.  Heuristic by design: it resolves
names syntactically, not semantically — the escape hatch (with a written
reason) is the intended pressure valve, and every use of it documents a real
exception to the contract.
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from pathlib import Path
from typing import Iterable, NamedTuple

RULES = {
    "GD001": "host-NumPy call inside a jitted/loop body",
    "GD002": "Python control flow on a traced value",
    "GD003": "host sync (.item()/float()/int()/np.asarray) inside a hot path",
    "GD004": "dtype-contract violation (float64 literal / dtype-less creation)",
    "GD005": "jit hygiene (non-static string/enum/config param, unhashable static default)",
    "GD006": "rollout-shaped jitted entry point without donate_argnums",
    "GD007": "non-atomic persistence (direct np.savez / open-for-write outside utils/io.py)",
    "GD008": "per-iteration host->device transfer (jnp.asarray/device_put) in a driver-module for-loop",
    "GD009": "jax.vmap over a pallas_call-backed callable (serial kernel-launch loop, not a batched grid)",
    "GD010": "jnp.asarray of a host buffer this function mutates (CPU alias race with async device reads)",
    "GD011": "bare time.time()/time.perf_counter() timing in a driver module (use graphdyn.obs timed/span)",
    "GD012": "bare jax.profiler capture/annotation outside graphdyn/obs/ (use graphdyn.obs.trace profiling/span alignment)",
    "GD013": "full-node-axis all_gather/jnp.take in a parallel/ shard-mapped body (halo exchange moves boundary words only)",
    "GD014": "host round-trip (np.asarray/device_get/.item()/block_until_ready/int()/float() coercion) inside a search/ drive loop (swap/sweep chunks stay on device)",
    "GD015": "per-temperature-step host sync (.item()/device_get/block_until_ready/bool()/int()/float() of a jnp.- or jax.-rooted call) in a models/ anneal drive loop (advance the schedule on device — ops/pallas_anneal)",
    "GD016": "hand-rolled byte-size arithmetic (itemsize literal x shape variables, .nbytes aggregation) outside the sanctioned cost modules (register a graftcost HAND_MODELS adapter)",
    "GD017": "ghost-padded node-table construction (np.full with a dimension-sized ghost-id fill) outside graphs.py (build layouts through the graphs.py builders / degree_buckets)",
}

# device->host materializations GD014 watches inside search/ drive loops
# (the bool(jnp.any(...)) stop test is deliberately NOT in this set — it
# is the sanctioned one-scalar-per-chunk sync). The bare `asarray` name
# covers `from numpy import asarray` aliasing; int()/float() on
# non-literal args are flagged separately (a per-chunk int(state.sweeps)
# is the same blocking readback with different spelling).
_GD014_CALLS = {"np.asarray", "numpy.asarray", "asarray",
                "jax.device_get", "device_get"}
_GD014_METHODS = {"item", "block_until_ready"}

# GD015: the per-temperature-step sync surface in models/ anneal drive
# loops. Same method set as GD014; the coercion watched is bool() of a
# device-rooted call (`bool(jnp.any(x))` per schedule step — the classic
# slow-SA drive shape), resolved syntactically by the jnp./jax. root so
# host-side bool(meta["failed"]) bookkeeping stays out of scope. models/
# loops are per-rep/per-λ/per-step, so ANY device readback there
# serializes every schedule step on the host link; the chunk-granularity
# sync search/ drivers are allowed (GD014's sanction) has no models/
# analogue — the solvers' schedules advance inside their device loops.
_GD015_CALLS = {"jax.device_get", "device_get"}
_GD015_METHODS = {"item", "block_until_ready"}
_GD015_DEVICE_ROOTS = ("jnp", "jax")

# GD016: the itemsize literals that mark byte arithmetic when they
# multiply shape variables. Deliberately ONLY the 4/8 dtype widths — a
# literal 2 multiplying two names (`2 * E * K` doubled-count idioms) is
# everywhere in graph code, and the false-positive cost of the narrower
# net is just that a 2-byte (f16) model ships unflagged until it grows a
# 4-byte term, which every model in this f32/int32 codebase has.
_GD016_ITEMSIZES = {4, 8}

# the sanctioned cost-model homes: byte formulas in these modules are
# (or must be) registered with graftcost's GB102 gate; anywhere else in
# graphdyn/ they are GD016 findings
_GD016_SANCTIONED = ("obs/memband.py", "obs/roofline.py",
                     "parallel/halo.py", "analysis/graftcost.py")

# the wall-clock calls GD011 watches (time.monotonic is exempt: it is the
# bookkeeping clock for queue waits and deadlines, not a timing idiom);
# the bare names cover the `from time import ...` form — a zero-arg call
# of a local named `time` in a driver module is overwhelmingly the clock,
# and the disable hatch covers the exception
_GD011_CALLS = {"time.time", "time.perf_counter", "perf_counter", "time"}

# the jax.profiler surface GD012 watches: matched as the FINAL attribute
# under any parent (jax.profiler.start_trace, an aliased
# `import jax.profiler as jp; jp.start_trace`, or the bare
# `from jax.profiler import ...` names — distinctive enough to carry no
# false-positive risk). `trace` is only matched dotted under `profiler` —
# the bare name is far too common to police syntactically.
_GD012_NAMES = {
    "start_trace", "stop_trace", "TraceAnnotation", "StepTraceAnnotation",
    "annotate_function",
}
_GD012_DOTTED_ONLY = {"trace"}

# host->device crossings GD010 watches (the potentially-aliasing ones;
# jnp.array copies and is the suggested fix)
_GD010_CALLS = {"jnp.asarray", "jax.numpy.asarray"}
# in-place ndarray methods that count as mutation for GD010
_GD010_MUTATORS = {"fill", "sort", "put", "partition", "resize"}

# host->device transfer calls GD008 watches inside host for-loops
_GD008_CALLS = {
    "jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array",
    "jax.device_put", "device_put",
}

# np dtype scalar constructors: trace-time constants, exempt from GD001
_NP_DTYPE_CTORS = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "bool_", "dtype",
}
# jnp array creators that default to a float dtype (GD004 scope)
_DTYPE_DEFAULT_FLOAT = {"ones", "zeros", "arange"}
_LAX_LOOPS = {"while_loop", "fori_loop", "scan"}
_ROLLOUT_NAME = re.compile(r"rollout|scan")

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-next-line|disable-file)=(.*)$"
)
_CODE_TOKEN = re.compile(r"(?i)^(gd\d{3}|all)$")


class Finding(NamedTuple):
    path: str
    line: int
    col: int
    code: str
    message: str


def parse_disable_comments(src: str, disable_re: re.Pattern,
                           code_token: re.Pattern):
    """Generic disable-comment parser shared by the in-package linters
    (graftlint, graftrace — each with its own comment prefix and code
    regex): ``(same_line: {lineno: set}, next_line: {lineno: set},
    file: set)``. Codes are comma-separated and each piece's first
    whitespace token must match ``code_token`` — so a free-text reason
    after a single space never corrupts the list (``disable=GD004 host
    staging`` still disables GD004)."""

    def parse_codes(blob: str) -> set:
        codes = set()
        for piece in blob.split(","):
            tok = piece.split()[0] if piece.split() else ""
            if code_token.match(tok):
                codes.add(tok.upper())
        return codes

    same, nxt, whole = {}, {}, set()
    for i, text in enumerate(src.splitlines(), start=1):
        m = disable_re.search(text)
        if not m:
            continue
        kind = m.group(1)
        codes = parse_codes(m.group(2))
        if kind == "disable":
            same.setdefault(i, set()).update(codes)
        elif kind == "disable-next-line":
            nxt.setdefault(i + 1, set()).update(codes)
        else:
            whole.update(codes)
    return same, nxt, whole


def _parse_disables(src: str):
    """(same_line: {lineno: set}, next_line: {lineno: set}, file: set)."""
    return parse_disable_comments(src, _DISABLE_RE, _CODE_TOKEN)


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote jax.jit (Name `jit` or `*.jit`)?"""
    d = _dotted(node)
    return d == "jit" or d.endswith(".jit")


def _param_names(fn) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _JitInfo(NamedTuple):
    static: frozenset       # static parameter names
    has_donate: bool
    decorated: bool         # jit via decorator (vs loop body / jit(f) call)


def _jit_kwargs(call: ast.Call) -> dict:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _static_names(fn, kwargs: dict) -> frozenset:
    """Resolve static_argnames/static_argnums decorator kwargs to names."""
    names = set()
    params = _param_names(fn)
    v = kwargs.get("static_argnames")
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        names.add(v.value)
    elif isinstance(v, (ast.Tuple, ast.List)):
        names.update(
            e.value for e in v.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    v = kwargs.get("static_argnums")
    idxs = []
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        idxs = [v.value]
    elif isinstance(v, (ast.Tuple, ast.List)):
        idxs = [e.value for e in v.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    for i in idxs:
        if 0 <= i < len(params):
            names.add(params[i])
    return frozenset(names)


def _jit_decorator_info(fn) -> _JitInfo | None:
    """_JitInfo if ``fn`` carries a jit decorator (plain, called, or via
    functools.partial)."""
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return _JitInfo(frozenset(), False, True)
        if isinstance(dec, ast.Call):
            kwargs = _jit_kwargs(dec)
            if _is_jit_expr(dec.func):
                return _JitInfo(
                    _static_names(fn, kwargs),
                    "donate_argnums" in kwargs or "donate_argnames" in kwargs,
                    True,
                )
            d = _dotted(dec.func)
            if (d == "partial" or d.endswith(".partial")) and any(
                _is_jit_expr(a) for a in dec.args
            ):
                return _JitInfo(
                    _static_names(fn, kwargs),
                    "donate_argnums" in kwargs or "donate_argnames" in kwargs,
                    True,
                )
    return None


class _FileLinter:
    def __init__(self, path: str, src: str, enum_names: frozenset):
        self.path = path
        self.src = src
        self.enum_names = enum_names
        self.findings: list[Finding] = []
        norm = path.replace("\\", "/")
        self.dtype_strict = "/ops/" in norm or "/parallel/" in norm
        # utils/io.py is the one module allowed to touch raw write APIs —
        # it IS the atomic-write implementation
        self.persist_strict = not norm.endswith("utils/io.py")
        # GD008 scope: the experiment drivers — where a per-repetition host
        # loop shipping arrays to the device is the throughput anti-pattern
        # the ensemble pipeline removes
        self.driver_mod = (
            "/models/" in norm or "/pipeline/" in norm
            or norm.endswith("cli.py")
        )
        # GD011 scope: drivers plus the benchmark harness — everywhere a
        # measurement should land in the obs event ledger. graphdyn/obs/
        # and utils/profiling.py are the implementation/shim layer.
        self.timing_strict = self.driver_mod or norm.endswith("bench.py")
        # GD012 scope: everywhere EXCEPT graphdyn/obs/ — the obs layer IS
        # the profiling implementation (aligned capture + span-named
        # TraceAnnotations); a bare jax.profiler call anywhere else forks
        # the device-timeline vocabulary away from the ledger's
        self.profiler_strict = "/obs/" not in norm
        # GD013 scope: the mesh-parallel layer — where a shard-mapped body
        # gathering the full node axis silently reverts the halo exchange's
        # boundary-words-only contract
        self.parallel_mod = "/parallel/" in norm
        # GD014 scope: the search drivers — where a per-chunk host
        # materialization would serialize the ladder/sweep loop
        self.search_mod = "/search/" in norm
        # GD015 scope: the solver layer — where an anneal/sweep drive loop
        # reading a device value back per temperature step caps
        # time-to-target regardless of kernel speed (the fused annealer
        # exists to remove exactly this round-trip)
        self.models_mod = "/models/" in norm
        # GD016 scope: the graphdyn package OUTSIDE the sanctioned
        # cost-model homes — byte formulas live where graftcost's GB102
        # can gate them against the HLO-derived models, nowhere else
        self.byte_model_strict = (
            "graphdyn/" in norm
            and not any(norm.endswith(s) for s in _GD016_SANCTIONED)
            and "ops/pallas_" not in norm
        )
        # GD017 scope: the graphdyn package OUTSIDE graphs.py — the one
        # sanctioned home of node-table layout construction (the padded
        # builders AND their degree-bucketed replacement live there)
        self.node_table_strict = (
            "graphdyn/" in norm and not norm.endswith("graphdyn/graphs.py")
        )

    def emit(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, code, message)
        )

    # -- jit-context discovery ------------------------------------------

    def _collect(self, tree: ast.Module):
        """(all function nodes by name, jit entries, loop-body names)."""
        by_name: dict[str, list] = {}
        entries: dict[int, _JitInfo] = {}       # id(node) -> info
        nodes: dict[int, ast.AST] = {}
        loop_body_names: set[str] = set()
        loop_body_lambdas: list[ast.Lambda] = []

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
                info = _jit_decorator_info(node)
                if info:
                    entries[id(node)] = info
                    nodes[id(node)] = node
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                base = d.rsplit(".", 1)[-1]
                if base in _LAX_LOOPS:
                    # while_loop(cond, body, init) / fori_loop(lo, hi, body,
                    # init) / scan(f, ...): every function-typed positional
                    # arg is traced
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            loop_body_names.add(arg.id)
                        elif isinstance(arg, ast.Lambda):
                            loop_body_lambdas.append(arg)
                elif _is_jit_expr(node.func) and node.args:
                    # jit(f, ...) call form
                    if isinstance(arg := node.args[0], ast.Name):
                        loop_body_names.add(arg.id)  # treated as jit context

        for name in loop_body_names:
            for fn in by_name.get(name, []):
                if id(fn) not in entries:
                    entries[id(fn)] = _JitInfo(frozenset(), False, False)
                    nodes[id(fn)] = fn
        return nodes, entries, loop_body_lambdas

    # -- checks ---------------------------------------------------------

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.src, filename=self.path)
        except SyntaxError as e:
            self.findings.append(
                Finding(self.path, e.lineno or 1, 0, "GD000",
                        f"syntax error: {e.msg}")
            )
            return self.findings

        nodes, entries, lambdas = self._collect(tree)
        seen: set[int] = set()
        for key, fn in nodes.items():
            info = entries[key]
            if info.decorated:
                self._check_jit_signature(fn, info)
                self._check_donation(fn, info)
            traced = frozenset(_param_names(fn)) - info.static
            self._check_body(fn, traced, info.static, seen)
        for lam in lambdas:
            self._check_body(lam, frozenset(_param_names(lam)), frozenset(),
                             seen)
        self._check_dtypes(tree)
        self._check_persistence(tree)
        self._check_host_loop_transfers(tree, seen)
        self._check_vmap_pallas(tree)
        self._check_alias_crossings(tree)
        self._check_bare_timing(tree)
        self._check_bare_profiler(tree)
        self._check_shardmap_full_gather(tree)
        self._check_search_loop_sync(tree, seen)
        self._check_anneal_loop_sync(tree, seen)
        self._check_byte_model_arith(tree)
        self._check_padded_table_full(tree)
        self.findings.sort(key=lambda f: (f.line, f.col, f.code))
        return self.findings

    def _check_body(self, fn, traced: frozenset, static: frozenset,
                    seen: set):
        """GD001/GD002/GD003 inside one jit-context function, recursing into
        nested function definitions (their bodies trace too; their params
        join the traced set *for their own subtree only* — they never leak
        to sibling statements; closures keep the outer static set)."""
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            self._visit(stmt, traced, static, seen)

    def _visit(self, node, traced: frozenset, static: frozenset, seen: set):
        if id(node) in seen:
            return
        seen.add(id(node))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            inner = traced | (frozenset(_param_names(node)) - static)
            for child in ast.iter_child_nodes(node):
                self._visit(child, inner, static, seen)
            return
        if isinstance(node, ast.Call):
            self._check_call(node, static)
        elif isinstance(node, (ast.If, ast.While)):
            self._check_branch(node, node.test, traced)
        elif isinstance(node, ast.For):
            if isinstance(node.iter, ast.Name) and node.iter.id in traced:
                self.emit(
                    node, "GD002",
                    f"Python for-loop iterates over traced value "
                    f"{node.iter.id!r} (use lax.fori_loop/scan, or "
                    f"declare it static)",
                )
        for child in ast.iter_child_nodes(node):
            self._visit(child, traced, static, seen)

    def _check_call(self, node: ast.Call, static: frozenset = frozenset()):
        d = _dotted(node.func)
        if d.startswith(("np.", "numpy.")):
            attr = d.split(".", 1)[1]
            if attr == "asarray":
                self.emit(node, "GD003",
                          "np.asarray inside a jitted/loop body forces a "
                          "device->host transfer")
            elif attr.split(".")[0] not in _NP_DTYPE_CTORS:
                self.emit(node, "GD001",
                          f"host-NumPy call {d}(...) inside a jitted/loop "
                          f"body (runs on host at trace time)")
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self.emit(node, "GD003",
                      ".item() inside a jitted/loop body blocks on a "
                      "device->host transfer")
        elif (isinstance(node.func, ast.Name)
              and node.func.id in ("float", "int")
              and node.args and not isinstance(node.args[0], ast.Constant)
              # float()/int() of a *static* parameter is trace-time by
              # construction — no device value involved
              and not (isinstance(node.args[0], ast.Name)
                       and node.args[0].id in static)):
            self.emit(node, "GD003",
                      f"{node.func.id}(...) inside a jitted/loop body "
                      f"materializes a host scalar")

    def _check_branch(self, node, test: ast.expr, traced: frozenset):
        hits = sorted(
            n.id for n in ast.walk(test)
            if isinstance(n, ast.Name) and n.id in traced
        )
        if hits:
            kw = "if" if isinstance(node, ast.If) else "while"
            self.emit(
                node, "GD002",
                f"Python `{kw}` on traced value(s) {', '.join(hits)} (use "
                f"lax.cond/lax.select, or declare them static)",
            )

    def _check_jit_signature(self, fn, info: _JitInfo):
        a = fn.args
        params = a.posonlyargs + a.args + a.kwonlyargs
        pos_defaults = dict(
            zip([p.arg for p in (a.posonlyargs + a.args)[-len(a.defaults):]],
                a.defaults)
        ) if a.defaults else {}
        kw_defaults = {
            p.arg: d for p, d in zip(a.kwonlyargs, a.kw_defaults) if d
        }
        defaults = {**pos_defaults, **kw_defaults}
        for p in params:
            default = defaults.get(p.arg)
            ann = _dotted(p.annotation).rsplit(".", 1)[-1] if p.annotation \
                else ""
            # `Rule | str`-style unions: look at every referenced name
            ann_names = {ann} | {
                n.id for n in ast.walk(p.annotation)
                if isinstance(n, ast.Name)
            } if p.annotation else {ann}
            stringy = (
                isinstance(default, ast.Constant)
                and isinstance(default.value, str)
            ) or "str" in ann_names or bool(
                ann_names & self.enum_names
            ) or any(n.endswith("Config") for n in ann_names if n)
            if stringy and p.arg not in info.static:
                self.emit(
                    p, "GD005",
                    f"string/enum/config parameter {p.arg!r} of jitted "
                    f"function {fn.name!r} is not in static_argnames "
                    f"(each value retraces, unhashable values fail)",
                )
            if p.arg in info.static and isinstance(
                default, (ast.List, ast.Dict, ast.Set)
            ):
                self.emit(
                    p, "GD005",
                    f"static parameter {p.arg!r} of jitted function "
                    f"{fn.name!r} has an unhashable default",
                )

    def _check_donation(self, fn, info: _JitInfo):
        if info.has_donate:
            return
        has_loop = any(
            isinstance(n, ast.Call)
            and _dotted(n.func).rsplit(".", 1)[-1] in _LAX_LOOPS
            for n in ast.walk(fn)
        )
        if has_loop or _ROLLOUT_NAME.search(fn.name):
            self.emit(
                fn, "GD006",
                f"rollout-shaped jitted entry point {fn.name!r} has no "
                f"donate_argnums/donate_argnames — the state buffer is "
                f"double-buffered in HBM",
            )

    def _check_dtypes(self, tree: ast.Module):
        """GD004: float64 literals (everywhere), dtype-less float-defaulting
        creators (ops/ + parallel/ only)."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                d = _dotted(node)
                if d in ("np.float64", "numpy.float64", "jnp.float64",
                         "jax.numpy.float64"):
                    self.emit(
                        node, "GD004",
                        f"{d} literal: the device dtype contract is "
                        f"int8 spins / int32 sums / f32 messages "
                        f"(ARCHITECTURE.md dtype table)",
                    )
            elif self.dtype_strict and isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d.startswith(("jnp.", "jax.numpy.")):
                    attr = d.rsplit(".", 1)[-1]
                    if attr in _DTYPE_DEFAULT_FLOAT:
                        has_dtype = any(
                            kw.arg == "dtype" for kw in node.keywords
                        ) or len(node.args) >= (4 if attr == "arange" else 2)
                        if not has_dtype:
                            self.emit(
                                node, "GD004",
                                f"dtype-less {d}(...) takes an ambient-"
                                f"dependent dtype (f32, or int64 under "
                                f"x64) — pass the contract dtype "
                                f"explicitly (int8/int32/f32)",
                            )


    def _check_host_loop_transfers(self, tree: ast.Module, jit_seen: set):
        """GD008: host→device transfers inside host-side ``for``-loops of
        driver modules — the serial-ensemble anti-pattern (one transfer per
        repetition while the device idles). ``jit_seen`` holds every node
        already visited inside a jit context: a ``for`` there unrolls at
        trace time, so no per-iteration transfer exists and it is exempt."""
        if not self.driver_mod:
            return
        flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.For) or id(node) in jit_seen:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or id(sub) in flagged:
                    continue
                d = _dotted(sub.func)
                if d in _GD008_CALLS:
                    flagged.add(id(sub))
                    self.emit(
                        sub, "GD008",
                        f"{d}(...) inside a host for-loop ships one buffer "
                        f"per iteration while the device idles — stack the "
                        f"per-iteration tables and run one batched program "
                        f"(see graphdyn.pipeline), or hoist the transfer "
                        f"out of the loop",
                    )

    def _check_alias_crossings(self, tree: ast.Module):
        """GD010: ``jnp.asarray(x)`` in a driver module where the SAME
        function mutates ``x`` in place (``x[...] = ...``, ``x[...] += ...``
        or an in-place ndarray method).  On CPU the device array may alias
        the numpy buffer for its whole lifetime, so the mutation races the
        asynchronous device reads — the PR-4 nondeterminism class; the fix
        is an explicit copy (``jnp.array``) at the crossing."""
        if not self.driver_mod:
            return

        def own_nodes(fn):
            # the function's OWN statements only: nested defs/lambdas are
            # separate scopes analyzed on their own walk — a shadowed local
            # mutated in an inner function must not flag the outer one
            stack = list(ast.iter_child_nodes(fn))
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                yield node
                stack.extend(ast.iter_child_nodes(node))

        flagged: set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            mutated: set[str] = set()
            for node in own_nodes(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Name
                    ):
                        mutated.add(t.value.id)
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _GD010_MUTATORS
                    and isinstance(node.func.value, ast.Name)
                ):
                    mutated.add(node.func.value.id)
            if not mutated:
                continue
            for node in own_nodes(fn):
                if (
                    isinstance(node, ast.Call)
                    and id(node) not in flagged
                    and _dotted(node.func) in _GD010_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in mutated
                ):
                    flagged.add(id(node))
                    self.emit(
                        node, "GD010",
                        f"jnp.asarray({node.args[0].id}) may ALIAS a host "
                        f"buffer this function mutates — on CPU the "
                        f"mutation races the device array's async reads "
                        f"(PR-4 class); copy at the crossing with "
                        f"jnp.array({node.args[0].id}) or drop the device "
                        f"array before mutating",
                    )

    def _check_bare_timing(self, tree: ast.Module):
        """GD011: bare ``time.time()``/``time.perf_counter()`` in a driver
        module — timing outside the obs API never reaches the event ledger
        (one timing idiom: :func:`graphdyn.obs.timed` /
        :func:`graphdyn.obs.span`). ``time.monotonic`` is exempt
        (bookkeeping clock, not a measurement idiom)."""
        if not self.timing_strict:
            return
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and _dotted(node.func) in _GD011_CALLS
                and not node.args and not node.keywords
            ):
                self.emit(
                    node, "GD011",
                    f"bare {_dotted(node.func)}() timing in a driver "
                    f"module bypasses the obs event ledger — use "
                    f"graphdyn.obs.timed(name) (always measures; records "
                    f"when a ledger is active) or obs.span(name); "
                    f"time.monotonic is the allowed bookkeeping clock",
                )

    def _check_bare_profiler(self, tree: ast.Module):
        """GD012: bare ``jax.profiler`` capture/annotation calls outside
        ``graphdyn/obs/``. One profiling idiom
        (:func:`graphdyn.obs.trace.profiling` + span-named annotations) —
        a privately started trace forks the device-timeline vocabulary
        away from the event ledger's, and a second ``start_trace`` inside
        an already-profiling run crashes the process-global profiler."""
        if not self.profiler_strict:
            return

        def _profiler_name(expr: ast.expr) -> str | None:
            d = _dotted(expr)
            parts = d.split(".")
            base = parts[-1]
            # the capture/annotation names are distinctive enough to match
            # as the final attribute under ANY parent — an aliased module
            # (`import jax.profiler as jp; jp.start_trace(...)`) is the
            # same private capture as the fully-dotted form
            if base in _GD012_NAMES:
                return d
            if (base in _GD012_DOTTED_ONLY and len(parts) >= 2
                    and parts[-2] == "profiler"):
                return d
            return None

        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                # `from jax.profiler import trace` would make every later
                # bare `trace(...)` call invisible to the name matching
                # below (the bare name is deliberately not policed) — flag
                # the import itself; module == 'jax.profiler' carries zero
                # false-positive risk
                if node.module == "jax.profiler" and any(
                        a.name in _GD012_DOTTED_ONLY for a in node.names):
                    self.emit(
                        node, "GD012",
                        "from jax.profiler import trace outside "
                        "graphdyn/obs/ — use graphdyn.obs.trace.profiling"
                        "(dir) (CLI --profile / GRAPHDYN_PROFILE); the "
                        "bare `trace` name cannot be policed at call "
                        "sites, so the import is the gate",
                    )
                continue
            if isinstance(node, ast.Call):
                d = _profiler_name(node.func)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the bare decorator form: @jax.profiler.annotate_function
                # (no parentheses) is an Attribute in decorator_list, not a
                # Call — the called form is caught by the branch above
                d = next(
                    (n for n in map(_profiler_name, node.decorator_list)
                     if n is not None),
                    None,
                )
            else:
                continue
            if d is None:
                continue
            self.emit(
                node, "GD012",
                f"bare {d}() outside graphdyn/obs/ — use "
                f"graphdyn.obs.trace.profiling(dir) (CLI --profile / "
                f"GRAPHDYN_PROFILE) for capture; span-aligned "
                f"TraceAnnotations come from obs.span/obs.timed, so the "
                f"device timeline and the event ledger share one "
                f"vocabulary",
            )

    def _check_shardmap_full_gather(self, tree: ast.Module):
        """GD013: ``lax.all_gather`` (or a ``jnp.take`` over its result)
        inside a shard-mapped body of a ``graphdyn/parallel/`` module.  A
        node-sharded synchronous step must move only BOUNDARY spin words
        (the halo exchange's ``ppermute`` schedule); an ``all_gather``
        re-ships the whole state to every device every step — O(n)
        collective bytes where the partition's edge cut would do.  Scope is
        resolved syntactically like GD009: the functions passed (by name)
        as the first argument to ``shard_map``, plus module-local functions
        they call, to a fixpoint; nested defs (loop bodies) are walked with
        their enclosing scoped function."""
        if not self.parallel_mod:
            return

        def base(expr: ast.expr) -> str:
            return _dotted(expr).rsplit(".", 1)[-1]

        # collect all functions by name + their module-local callee names
        fn_nodes: dict[str, list] = {}
        fn_calls: dict[str, set] = {}
        roots: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_nodes.setdefault(node.name, []).append(node)
                called = {
                    base(sub.func) for sub in ast.walk(node)
                    if isinstance(sub, ast.Call)
                }
                fn_calls.setdefault(node.name, set()).update(called - {""})
            elif isinstance(node, ast.Call) and base(node.func) == "shard_map":
                if node.args and isinstance(node.args[0], ast.Name):
                    roots.add(node.args[0].id)

        scoped = set(roots)
        changed = True
        while changed:
            changed = False
            for name in list(scoped):
                for callee in fn_calls.get(name, ()):
                    if callee in fn_nodes and callee not in scoped:
                        scoped.add(callee)
                        changed = True

        flagged: set[int] = set()
        for name in sorted(scoped):
            for fn in fn_nodes.get(name, []):
                tainted: set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call
                    ) and base(node.value.func) == "all_gather":
                        tainted.update(
                            t.id for t in node.targets
                            if isinstance(t, ast.Name)
                        )
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call) or id(node) in flagged:
                        continue
                    d = _dotted(node.func)
                    if base(node.func) == "all_gather":
                        flagged.add(id(node))
                        self.emit(
                            node, "GD013",
                            f"{d}(...) inside a shard-mapped body gathers "
                            f"the FULL node axis every step — ship only the "
                            f"partition's boundary words instead "
                            f"(graphdyn.parallel.halo: ppermute over the "
                            f"static shard-neighbor schedule)",
                        )
                    elif d in ("jnp.take", "jax.numpy.take") and node.args \
                            and isinstance(node.args[0], ast.Name) \
                            and node.args[0].id in tainted:
                        flagged.add(id(node))
                        self.emit(
                            node, "GD013",
                            f"jnp.take over {node.args[0].id!r} (an "
                            f"all_gather result) reads the full node axis "
                            f"inside a shard-mapped body — gather from the "
                            f"local block + halo ghost rows instead "
                            f"(graphdyn.parallel.halo)",
                        )

    def _check_search_loop_sync(self, tree: ast.Module, jit_seen: set):
        """GD014: device→host materialization inside a host ``for``/
        ``while`` loop of a ``graphdyn/search/`` module — the swap/sweep
        drive loop must stay one device program per chunk, with results
        read back once after the loop.  ``jit_seen`` holds nodes already
        visited inside jit contexts (loops there unroll at trace time)."""
        if not self.search_mod:
            return
        flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.While)) \
                    or id(node) in jit_seen:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or id(sub) in flagged:
                    continue
                d = _dotted(sub.func)
                is_method = (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _GD014_METHODS
                )
                # int()/float() of a non-literal in the drive loop is the
                # same blocking readback with different spelling (e.g. a
                # per-chunk int(state.sweeps) budget check — plan the
                # chunk sizes host-side instead)
                is_coerce = (
                    isinstance(sub.func, ast.Name)
                    and sub.func.id in ("int", "float")
                    and sub.args
                    and not isinstance(sub.args[0], ast.Constant)
                )
                if d in _GD014_CALLS or is_method or is_coerce:
                    what = d or (sub.func.attr if isinstance(
                        sub.func, ast.Attribute) else sub.func.id)
                    flagged.add(id(sub))
                    self.emit(
                        sub, "GD014",
                        f"{what}(...) inside a search drive loop "
                        f"materializes device values every chunk — the "
                        f"ladder/sweep must stay one device program per "
                        f"chunk (the sanctioned per-chunk sync is the "
                        f"bool(jnp.any(...)) stop test); read results "
                        f"back once after the loop, and derive chunk "
                        f"budgets host-side",
                    )

    def _check_byte_model_arith(self, tree: ast.Module):
        """GD016: a hand-rolled byte model outside the sanctioned cost
        modules — an itemsize literal (4/8) multiplying two or more shape
        variables (``4 * n * W``), or ``.nbytes`` aggregated through
        ``sum()`` or arithmetic. Byte formulas must live where graftcost's
        GB102 gates them against the HLO-derived models (the
        ``HAND_MODELS`` adapter table); anywhere else they are the hand
        transcription that goes silently stale when the program changes.
        One finding per multiplication chain (the flagged node is the
        outermost ``Mult``)."""
        if not self.byte_model_strict:
            return

        def flatten_mult(node):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
                yield from flatten_mult(node.left)
                yield from flatten_mult(node.right)
            else:
                yield node

        # only outermost Mult chains: children of a Mult are part of
        # their parent's chain, never their own finding
        inner: set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
                for side in (node.left, node.right):
                    if isinstance(side, ast.BinOp) \
                            and isinstance(side.op, ast.Mult):
                        inner.add(id(side))
        for node in ast.walk(tree):
            if not isinstance(node, ast.BinOp) \
                    or not isinstance(node.op, ast.Mult) \
                    or id(node) in inner:
                continue
            factors = list(flatten_mult(node))
            sizes = [
                f for f in factors
                if isinstance(f, ast.Constant)
                and isinstance(f.value, int) and f.value in _GD016_ITEMSIZES
            ]
            names = [
                f for f in factors
                if isinstance(f, (ast.Name, ast.Attribute))
            ]
            if sizes and len(names) >= 2:
                self.emit(
                    node, "GD016",
                    f"byte-size arithmetic ({sizes[0].value} * "
                    f"{len(names)} shape variables) outside the "
                    "sanctioned cost modules — hand byte models go "
                    "stale silently; register the formula as a "
                    "graphdyn.analysis.graftcost.HAND_MODELS adapter "
                    "(GB102 then gates it against the HLO-derived model) "
                    "or move it into obs/memband.py / obs/roofline.py / "
                    "parallel/halo.py / ops/pallas_*.py",
                )
        for node in ast.walk(tree):
            is_agg = False
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "sum":
                is_agg = True
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Add, ast.Mult)):
                # direct operands only — a nested chain flags once at its
                # own BinOp, and .nbytes deeper inside a call argument is
                # that call's business
                for side in (node.left, node.right):
                    if isinstance(side, ast.Attribute) \
                            and side.attr == "nbytes":
                        is_agg = True
            if is_agg and any(
                isinstance(sub, ast.Attribute) and sub.attr == "nbytes"
                for sub in ast.walk(node)
            ):
                self.emit(
                    node, "GD016",
                    ".nbytes aggregation builds a hand byte model outside "
                    "the sanctioned cost modules — register a "
                    "graphdyn.analysis.graftcost.HAND_MODELS adapter so "
                    "GB102 gates the model against the derived one, or "
                    "move it into a sanctioned cost module",
                )

    def _check_padded_table_full(self, tree: ast.Module):
        """GD017: a ``full`` call building a ghost-padded node table
        outside ``graphs.py`` — shape a ≥2-element tuple, fill a
        non-constant expression syntactically identical to one of the
        shape dimensions (``np.full((n, dmax), n)``: the dimension-sized
        ghost id as fill is the signature of the padded neighbor-table
        layout, which one power-law hub inflates for every node). The
        single-ghost-ROW extension ``full((1, dmax), n)`` matches no
        dimension and stays legal; a constant fill (``-1``, a pad
        sentinel) is bookkeeping, not a layout."""
        if not self.node_table_strict:
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func).rsplit(".", 1)[-1] != "full":
                continue
            if len(node.args) < 2:
                continue
            shape, fill = node.args[0], node.args[1]
            if not isinstance(shape, ast.Tuple) or len(shape.elts) < 2:
                continue
            if isinstance(fill, ast.Constant):
                continue
            fill_dump = ast.dump(fill)
            if any(ast.dump(e) == fill_dump for e in shape.elts):
                self.emit(
                    node, "GD017",
                    "ghost-padded node-table construction (the fill value "
                    "is one of the shape dimensions — the np.full((n, "
                    "dmax), n) padded-layout idiom) outside graphs.py; "
                    "node layouts come from the graphs.py builders, and "
                    "power-law degree sequences route through "
                    "graphs.degree_buckets instead of paying dmax per "
                    "node (ROADMAP item 3)",
                )

    def _check_anneal_loop_sync(self, tree: ast.Module, jit_seen: set):
        """GD015: device→host materialization per temperature step — a
        host ``for``/``while`` loop in a ``graphdyn/models/`` module that
        calls ``.item()``/``.block_until_ready()``/``jax.device_get`` or
        coerces a ``jnp.``/``jax.``-rooted call through ``bool()``. The
        anneal schedules of every solver advance INSIDE their device
        loops (``metropolis_anneal_update``; the fused annealer pins the
        whole run on device), so a per-step readback in the drive loop
        caps time-to-target on the host link no matter how fast the
        kernel runs. Loops inside jit contexts unroll at trace time and
        are exempt (``jit_seen``)."""
        if not self.models_mod:
            return
        flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.While)) \
                    or id(node) in jit_seen:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or id(sub) in flagged:
                    continue
                d = _dotted(sub.func)
                is_method = (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _GD015_METHODS
                )
                # bool(jnp.any(x)) / int(jnp.sum(x)) / float(jnp.max(x)):
                # per-step coercions reading the device back. Matched
                # only on jnp./jax.-rooted CALL arguments — models/ drive
                # loops are full of host bookkeeping (`float(lmbd)`,
                # `bool(meta["failed"])`) that a GD014-style
                # any-non-literal net would drown in disables; the direct
                # device-attribute form (`float(state.m_final)`) is
                # uncheckable syntactically and `.item()` covers its
                # common spelling
                is_bool_sync = (
                    isinstance(sub.func, ast.Name)
                    and sub.func.id in ("bool", "int", "float")
                    and sub.args
                    and isinstance(sub.args[0], ast.Call)
                    and _dotted(sub.args[0].func).split(".")[0]
                    in _GD015_DEVICE_ROOTS
                )
                if d in _GD015_CALLS or is_method or is_bool_sync:
                    what = d or (sub.func.attr if isinstance(
                        sub.func, ast.Attribute) else sub.func.id)
                    flagged.add(id(sub))
                    self.emit(
                        sub, "GD015",
                        f"{what}(...) inside a models/ anneal drive loop "
                        f"reads the device back every temperature step — "
                        f"the schedule advances inside the device program "
                        f"(metropolis_anneal_update; the fused annealer, "
                        f"graphdyn.ops.pallas_anneal, keeps the whole run "
                        f"on device); poll at chunk boundaries only and "
                        f"read results back once after the loop",
                    )

    def _check_vmap_pallas(self, tree: ast.Module):
        """GD009: ``jax.vmap`` over a ``pallas_call``-backed callable.
        ``vmap`` has no batching rule for a custom kernel — it lowers to a
        serial Python loop of per-element kernel launches, not a batched
        grid.  'Backed' is resolved syntactically within the module:
        functions whose body calls ``pallas_call`` (transitively through
        module-local calls), names assigned from ``pl.pallas_call(...)``,
        and ``partial(...)`` wrappers of either."""

        def is_pallas_call(call: ast.Call) -> bool:
            return _dotted(call.func).rsplit(".", 1)[-1] == "pallas_call"

        def is_partial(call: ast.Call) -> bool:
            d = _dotted(call.func)
            return d == "partial" or d.endswith(".partial")

        # module-local call graph + direct pallas_call containment
        fn_calls: dict[str, set] = {}
        backed: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                called = set()
                direct = False
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        if is_pallas_call(sub):
                            direct = True
                        base = _dotted(sub.func).rsplit(".", 1)[-1]
                        if base:
                            called.add(base)
                fn_calls.setdefault(node.name, set()).update(called)
                if direct:
                    backed.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                # f = pl.pallas_call(...) / f = partial(backed, ...) are
                # resolved below once `backed` is complete; record the
                # direct pallas_call binding here
                if is_pallas_call(node.value):
                    backed.update(
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    )
        # propagate through module-local calls to a fixpoint (a wrapper of
        # a kernel-backed function is itself kernel-backed)
        changed = True
        while changed:
            changed = False
            for name, called in fn_calls.items():
                if name not in backed and called & backed:
                    backed.add(name)
                    changed = True
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and is_partial(node.value):
                if any(
                    isinstance(a, ast.Name) and a.id in backed
                    for a in node.value.args
                ):
                    backed.update(
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    )

        def arg_is_backed(arg: ast.expr) -> bool:
            if isinstance(arg, ast.Name):
                return arg.id in backed
            if isinstance(arg, ast.Call):
                if is_pallas_call(arg):
                    return True
                if is_partial(arg):
                    return any(arg_is_backed(a) for a in arg.args)
            if isinstance(arg, ast.Lambda):
                return any(
                    isinstance(sub, ast.Call) and (
                        is_pallas_call(sub)
                        or (isinstance(sub.func, ast.Name)
                            and sub.func.id in backed)
                    )
                    for sub in ast.walk(arg)
                )
            return False

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if not (d == "vmap" or d.endswith(".vmap")):
                continue
            if node.args and arg_is_backed(node.args[0]):
                self.emit(
                    node, "GD009",
                    "jax.vmap over a pallas_call-backed callable lowers to "
                    "a SERIAL loop of kernel launches — make the batch "
                    "axis a Pallas grid dimension instead (cf. "
                    "ops/pallas_bdcm.dp_contract_grouped)",
                )

    def _check_persistence(self, tree: ast.Module):
        """GD007: direct durable writes outside utils/io.py. A torn npz/json
        from a preemption mid-write poisons the next resume; the atomic
        writers (temp + ``os.replace``) exist so this can never happen."""
        if not self.persist_strict:
            return

        def is_temp_token(blob: str) -> bool:
            # token-boundary match, not substring: 'attempt_path' and
            # 'template' contain 'temp' but are NOT temp paths. A token is
            # temp-ish when it is exactly tmp/temp/temporary/tempfile or
            # starts with tmp (tmpfile, tmp2) / mkstemp-style names.
            for tok in re.split(r"[^a-z0-9]+", blob.lower()):
                if tok in ("temp", "temporary", "tempfile") or tok.startswith(
                    ("tmp", "mkstemp", "mkdtemp")
                ):
                    return True
            return False

        def looks_temp(node: ast.expr | None) -> bool:
            # a temp-ish token anywhere in the path expression (a literal
            # fragment, a variable named tmp_path, tempfile.* calls):
            # writing the temp half of the atomic discipline is the point
            if node is None:
                return False
            for n in ast.walk(node):
                blob = ""
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    blob = n.value
                elif isinstance(n, ast.Name):
                    blob = n.id
                elif isinstance(n, ast.Attribute):
                    blob = n.attr
                if blob and is_temp_token(blob):
                    return True
            return False

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d in ("np.savez", "numpy.savez", "np.savez_compressed",
                     "numpy.savez_compressed", "np.save", "numpy.save"):
                if node.args and looks_temp(node.args[0]):
                    continue
                self.emit(
                    node, "GD007",
                    f"direct {d}(...) to a non-temp path: a preemption "
                    f"mid-write leaves a torn file — use graphdyn.utils.io "
                    f"(save_results_npz/Checkpoint: temp + os.replace)",
                )
            elif d == "open":
                mode = None
                if len(node.args) >= 2:
                    mode = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                if (
                    isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and mode.value.startswith(("w", "a", "x"))
                    and not (node.args and looks_temp(node.args[0]))
                ):
                    self.emit(
                        node, "GD007",
                        "open(..., for write) to a non-temp path: persist "
                        "through graphdyn.utils.io (write_json_atomic / "
                        "temp file + os.replace) so a preemption cannot "
                        "tear the file",
                    )


def _collect_enum_names(sources: list[tuple[str, str]]) -> frozenset:
    """Names of Enum-derived classes across every linted file (so GD005
    recognizes `rule: Rule` without semantic imports)."""
    names = set()
    for _, src in sources:
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and any(
                "Enum" in _dotted(b) for b in node.bases
            ):
                names.add(node.name)
    return frozenset(names)


def lint_sources(sources: list[tuple[str, str]]) -> list[Finding]:
    """Lint (path, source) pairs; disable comments already honored."""
    enum_names = _collect_enum_names(sources)
    out = []
    for path, src in sources:
        same, nxt, whole = _parse_disables(src)
        for f in _FileLinter(path, src, enum_names).run():
            disabled = (
                f.code in whole or "ALL" in whole
                or f.code in same.get(f.line, ())
                or "ALL" in same.get(f.line, ())
                or f.code in nxt.get(f.line, ())
                or "ALL" in nxt.get(f.line, ())
            )
            if not disabled:
                out.append(f)
    return out


def iter_python_files(paths: Iterable[str]):
    for p in paths:
        path = Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    sources = []
    unreadable = []
    for f in iter_python_files(paths):
        try:
            sources.append((str(f), f.read_text()))
        except OSError as e:
            # fail CLOSED: a file the gate could not inspect is a finding,
            # not a skip — otherwise a permission-broken checkout passes
            unreadable.append(
                Finding(str(f), 1, 0, "GD000", f"cannot read file: {e}")
            )
    return unreadable + lint_sources(sources)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.analysis",
        description="graftlint: JAX/TPU-invariant linter "
                    "(exit code = number of findings)",
    )
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    findings = lint_paths(args.paths)
    if args.format == "json":
        # exactly ONE JSON document on stdout (CI pipes it); the summary —
        # like every other diagnostic — goes to stderr only
        print(json.dumps([f._asdict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col}: {f.code} {f.message}")
    if findings:
        print(f"graftlint: {len(findings)} finding(s)", file=sys.stderr)
    # exit code = findings, clamped to the 8-bit exit-status range
    return min(len(findings), 125)


if __name__ == "__main__":
    sys.exit(main())
