"""``python -m graphdyn.analysis`` — run graftlint from the command line."""

import sys

from graphdyn.analysis.graftlint import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
