"""Trace-time shape/dtype contracts for jitted entry points.

``@contract(s="int8[r,n]", nbr="int32[n,d]", ret="int8[r,n]")`` checks the
arrays flowing through a function against a compact spec language.  Applied
*under* ``jax.jit`` (decorator order: jit outermost), the checks run once per
trace — on abstract values, before any compute — and cost nothing
post-compile; applied to a plain function they run per call on host metadata
only (never touching device data).

This is the runtime half of the dtype contract that ``graftlint`` GD004
enforces statically (ARCHITECTURE.md "Static analysis & contracts"): the
linter catches literal violations in the source, the contract catches the
ones that arrive through an argument — an int64 neighbor table from an
unconverted host build, an f64 chi from an x64-enabled caller, a transposed
state buffer.

Spec grammar (one string per argument; ``ret`` is the return value)::

    spec    := dtypes | dtypes "[" dims "]"
    dtypes  := "*" | name ("|" name)*      # "*" = any dtype
    dims    := ""                          # "[]" = rank-0 scalar
             | dim ("," dim)*
    dim     := INT                         # exact size
             | "_"                         # any size
             | SYMBOL                      # binds; must agree across args

Dtype names accept short aliases (``f32``→float32, ``i8``→int8, ``u32``→
uint32, ``bool``→bool_).  A bare ``dtypes`` spec (no brackets) checks dtype
only and leaves the rank free.  Symbols bind left to right across the
argument list and the return value, so ``s="int8[r,n]", nbr="int32[n,d]"``
enforces that the state's node axis matches the neighbor table's rows.

Tuple/dict returns: give ``ret`` a tuple of specs (checked positionally;
``None`` skips an element) — dict returns are checked per sorted key order
only when a tuple spec is supplied of matching length, otherwise use per-key
checks in the function body.
"""

from __future__ import annotations

import functools
import inspect
import re

__all__ = ["contract", "ContractError"]


class ContractError(TypeError):
    """An argument or return value violated its @contract spec."""


_ALIASES = {
    "f16": "float16", "f32": "float32", "f64": "float64",
    "bf16": "bfloat16",
    "i8": "int8", "i16": "int16", "i32": "int32", "i64": "int64",
    "u8": "uint8", "u16": "uint16", "u32": "uint32", "u64": "uint64",
    "bool": "bool_",
}
_SPEC_RE = re.compile(r"^\s*([^\[\]]+?)\s*(\[(.*)\])?\s*$")
_SYM_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _canon_dtype(name: str) -> str:
    name = name.strip()
    return _ALIASES.get(name, name)


def _parse_spec(spec: str):
    """-> (dtypes: tuple[str] | None, dims: tuple | None).

    dtypes None means any dtype; dims None means any rank, () rank-0.
    """
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(f"malformed contract spec {spec!r}")
    dt_part, has_dims, dims_part = m.group(1), m.group(2), m.group(3)
    if dt_part.strip() == "*":
        dtypes = None
    else:
        dtypes = tuple(_canon_dtype(t) for t in dt_part.split("|"))
        for t in dtypes:
            if not _SYM_RE.match(t.replace("bool_", "bool")):
                raise ValueError(f"bad dtype {t!r} in contract spec {spec!r}")
    if not has_dims:
        return dtypes, None
    dims = []
    if dims_part.strip():
        for tok in dims_part.split(","):
            tok = tok.strip()
            if not tok:
                raise ValueError(f"empty dim in contract spec {spec!r}")
            if tok.isdigit():
                dims.append(int(tok))
            elif tok == "_" or _SYM_RE.match(tok):
                dims.append(tok)
            else:
                raise ValueError(f"bad dim {tok!r} in contract spec {spec!r}")
    return dtypes, tuple(dims)


def _describe(x) -> str:
    dt = getattr(x, "dtype", None)
    sh = getattr(x, "shape", None)
    if dt is None or sh is None:
        return f"{type(x).__name__} (not an array)"
    return f"{dt}[{', '.join(map(str, sh))}]"


def _check_value(fname, where, x, dtypes, dims, env):
    if isinstance(x, (bool, int, float, complex)):
        # Python scalars are weakly typed under jit (a float traces as the
        # ambient float dtype): accept them when any allowed dtype shares
        # their kind, and check rank only
        kind = ("bool" if isinstance(x, bool)
                else "int" if isinstance(x, int)
                else "float" if isinstance(x, float) else "complex")
        if dtypes is not None and not any(kind in t or t == "bool_" and
                                          kind == "bool" for t in dtypes):
            raise ContractError(
                f"{fname}: {where} is a Python {kind} scalar, contract "
                f"requires {'|'.join(dtypes)}"
            )
        if dims not in (None, ()):
            raise ContractError(
                f"{fname}: {where} is a scalar, contract requires rank "
                f"{len(dims)} {dims}"
            )
        return
    dt = getattr(x, "dtype", None)
    sh = getattr(x, "shape", None)
    if dt is None or sh is None:
        raise ContractError(
            f"{fname}: {where} must be an array-like with shape/dtype, got "
            f"{type(x).__name__}"
        )
    if dtypes is not None and str(dt) not in dtypes and getattr(
        dt, "name", None
    ) not in dtypes:
        raise ContractError(
            f"{fname}: {where} has dtype {dt}, contract requires "
            f"{'|'.join(dtypes)} (got {_describe(x)})"
        )
    if dims is None:
        return
    if len(sh) != len(dims):
        raise ContractError(
            f"{fname}: {where} has rank {len(sh)}, contract requires rank "
            f"{len(dims)} {dims} (got {_describe(x)})"
        )
    for axis, (want, got) in enumerate(zip(dims, sh)):
        got = int(got)
        if want == "_":
            continue
        if isinstance(want, int):
            if got != want:
                raise ContractError(
                    f"{fname}: {where} axis {axis} has size {got}, contract "
                    f"requires {want} (got {_describe(x)})"
                )
        else:
            bound = env.setdefault(want, (got, where, axis))
            if bound[0] != got:
                raise ContractError(
                    f"{fname}: {where} axis {axis} has size {got}, but "
                    f"symbol {want!r} was bound to {bound[0]} by {bound[1]} "
                    f"axis {bound[2]}"
                )


def contract(ret=None, **arg_specs):
    """Decorator: check array args/returns against spec strings at trace
    time.  See the module docstring for the grammar.  ``ret`` takes the
    return-value spec (a string, or a tuple of strings/None for tuple
    returns).  Unspecified parameters are unchecked (static/config args need
    no spec)."""
    parsed_args = {k: _parse_spec(v) for k, v in arg_specs.items()}
    if ret is None:
        ret_kind, parsed_ret = None, None
    elif isinstance(ret, (tuple, list)):
        ret_kind = "tuple"
        parsed_ret = tuple(
            None if s is None else _parse_spec(s) for s in ret
        )
    else:
        ret_kind, parsed_ret = "single", _parse_spec(ret)

    def deco(fn):
        sig = inspect.signature(fn)
        unknown = set(parsed_args) - set(sig.parameters)
        if unknown:
            raise ValueError(
                f"@contract on {fn.__qualname__}: specs for unknown "
                f"parameter(s) {sorted(unknown)}"
            )

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            fname = fn.__qualname__
            try:
                bound = sig.bind(*args, **kwargs)
            except TypeError:
                return fn(*args, **kwargs)   # let fn raise its own error
            env: dict = {}
            for name, (dtypes, dims) in parsed_args.items():
                if name in bound.arguments:
                    _check_value(fname, f"argument {name!r}",
                                 bound.arguments[name], dtypes, dims, env)
            out = fn(*args, **kwargs)
            if parsed_ret is not None:
                if ret_kind == "tuple":
                    if not isinstance(out, (tuple, list)) or len(out) != len(
                        parsed_ret
                    ):
                        raise ContractError(
                            f"{fname}: return value is not a {len(parsed_ret)}"
                            f"-tuple (contract gave a tuple of specs)"
                        )
                    for i, spec in enumerate(parsed_ret):
                        if spec is not None:
                            _check_value(fname, f"return[{i}]", out[i],
                                         spec[0], spec[1], env)
                else:
                    _check_value(fname, "return value", out,
                                 parsed_ret[0], parsed_ret[1], env)
            return out

        return wrapper

    return deco
