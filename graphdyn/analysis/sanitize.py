"""Runtime host-aliasing sanitizer — the PR-4 ``jnp.asarray`` race class as
a deterministic failure.

On the CPU backend ``jnp.asarray`` (and ``jax.device_put``) may return a
``jax.Array`` that ALIASES the source numpy buffer for the array's entire
lifetime: any host mutation of that buffer while the device array is alive
races the asynchronous device reads — observed in PR 4 as nondeterministic
entropy-ladder results, fixed there by copying (``jnp.array``) at every
mutated-buffer crossing, and guarded statically by graftlint **GD010**.
Statics can only see syntactic patterns; this module catches the class at
RUN time, deterministically:

- :func:`alias_sanitizer` patches the host→device crossing functions
  (``jnp.asarray`` / ``jnp.array`` with ``copy=False`` semantics left to
  jax, and ``jax.device_put``) for the duration of the context. Every
  crossing whose source is a *writeable* host ``np.ndarray`` snapshots a
  digest of the buffer at dispatch and registers the returned device
  array.
- The digest is re-verified while the device array is alive: at the
  array's finalization (GC), at every explicit :meth:`AliasSanitizer.
  verify` call, and at context exit. A source buffer that changed while
  its device alias lived raises :class:`AliasRaceError` naming the
  crossing site — the race is now a test failure with a file:line, not a
  wrong number three plots later.

The contract is intentionally strict: on CPU the alias persists for the
array's lifetime, so "I mutated after the computation finished" is still
inside the hazard window. The fix is the same as PR 4's — copy at the
crossing (``jnp.array``) or drop the device array before mutating.

Opt-in: ``GRAPHDYN_SANITIZE=alias`` in the environment turns
:func:`maybe_alias_sanitizer` (wrapped around every CLI driver run) into
the real context; otherwise it is a no-op with zero overhead. Tests use
:func:`alias_sanitizer` directly.
"""

from __future__ import annotations

import hashlib
import os
import traceback
import weakref
from contextlib import contextmanager

ENV_VAR = "GRAPHDYN_SANITIZE"
ENV_VALUE = "alias"


class AliasRaceError(RuntimeError):
    """A host buffer was mutated while a device array aliasing it was
    alive — the PR-4 nondeterminism class, caught deterministically."""


def _digest(arr) -> bytes:
    # tobytes() copies, which is exactly what makes the snapshot immune to
    # the mutation it is trying to catch; the sanitizer is opt-in, so the
    # copy cost is a diagnostic-mode price, not a hot-path one
    return hashlib.blake2b(arr.tobytes(), digest_size=16).digest()


def _call_site() -> str:
    """file:line of the crossing, skipping this module and jax frames."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename.replace("\\", "/")
        if "/analysis/sanitize.py" in fn:
            continue
        if "/jax/" in fn or "/jax/_src/" in fn:
            continue
        return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


class _Record:
    __slots__ = ("source", "digest", "site", "dead", "finalizer")

    def __init__(self, source, digest, site):
        self.source = source
        self.digest = digest
        self.site = site
        self.dead = False
        self.finalizer = None


class AliasSanitizer:
    """The active sanitizer: crossing records plus verification. Not
    re-entrant (one active context at a time — :func:`alias_sanitizer`
    enforces it)."""

    def __init__(self):
        self.records: list[_Record] = []
        self.violations: list[str] = []
        self._saved = None

    # -- record / verify -------------------------------------------------

    def _track(self, source, out):
        import numpy as np

        if not isinstance(source, np.ndarray):
            return
        if not source.flags.writeable or source.size == 0:
            return                      # read-only / empty: cannot race
        if source.dtype == object:
            return
        try:
            import jax
            from jax.core import Tracer

            # tracers ARE jax.Array instances, so the exclusion must test
            # Tracer directly: a traced crossing is consumed at trace time
            # (no alias survives into execution) and tracking it would pay
            # digest + stack-walk cost per closure constant for nothing
            if isinstance(out, Tracer) or not isinstance(out, jax.Array):
                return
        except Exception:
            return
        if not self._may_alias(source, out):
            return                      # provably a copy: cannot race
        rec = _Record(source, _digest(source), _call_site())
        # verify at the device array's death: the alias window closes
        # there, and a buffer that already changed inside it is a race
        # regardless of what happens later
        rec.finalizer = weakref.finalize(out, self._on_dead, rec)
        self.records.append(rec)

    @staticmethod
    def _may_alias(source, out) -> bool:
        """Could ``out`` share ``source``'s memory? MAY-alias semantics on
        purpose: whether a same-dtype contiguous crossing actually aliases
        depends on allocator alignment luck (measured: an int8 buffer
        aliased — mutations visible through the device array — while f32
        siblings copied), which is exactly the nondeterminism PR 4
        observed. The sanitizer therefore flags the hazard CLASS
        deterministically and skips only crossings that are *provably*
        copies — a dtype conversion or a non-contiguous source — which
        would otherwise turn legitimate buffer reuse into false
        AliasRaceErrors."""
        if out.dtype != source.dtype:
            return False                # conversion always copies
        if not source.flags.c_contiguous:
            return False                # jax materializes a contiguous copy
        return True

    def _on_dead(self, rec: _Record):
        if not rec.dead:
            rec.dead = True
            self._verify_record(rec)
            # the alias window is closed and the verdict recorded: drop the
            # strong source reference and the record itself, so an
            # hours-long sanitized driver run does not pin every staging
            # buffer it ever crossed
            rec.source = None
            try:
                self.records.remove(rec)
            except ValueError:
                pass

    def _verify_record(self, rec: _Record):
        if rec.source is None:
            return                      # already verified and released
        if _digest(rec.source) != rec.digest:
            msg = (
                f"host buffer mutated while a device array aliasing it "
                f"was alive (crossing at {rec.site}, "
                f"shape={rec.source.shape}, dtype={rec.source.dtype}) — "
                f"copy at the crossing (jnp.array) or drop the device "
                f"array before mutating (graftlint GD010)"
            )
            if msg not in self.violations:
                self.violations.append(msg)

    def verify(self):
        """Re-verify every live crossing now; raise on any violation seen
        so far (including ones collected at array finalization)."""
        # snapshot: a GC triggered mid-loop can run finalizers that prune
        # self.records while we iterate
        for rec in list(self.records):
            if not rec.dead:
                self._verify_record(rec)
        if self.violations:
            raise AliasRaceError(
                "GRAPHDYN_SANITIZE=alias: "
                + "; ".join(self.violations)
            )

    # -- patching --------------------------------------------------------

    def _patch(self):
        import jax
        import jax.numpy as jnp

        saved = {
            "asarray": jnp.asarray,
            "device_put": jax.device_put,
        }
        san = self

        def asarray(a, *args, **kwargs):
            out = saved["asarray"](a, *args, **kwargs)
            san._track(a, out)
            return out

        def device_put(x, *args, **kwargs):
            out = saved["device_put"](x, *args, **kwargs)
            san._track(x, out)
            return out

        jnp.asarray = asarray
        jax.device_put = device_put
        self._saved = saved

    def _unpatch(self):
        import jax
        import jax.numpy as jnp

        jnp.asarray = self._saved["asarray"]
        jax.device_put = self._saved["device_put"]
        self._saved = None


_ACTIVE: list[AliasSanitizer] = []


@contextmanager
def alias_sanitizer():
    """Context manager: patch the crossings, yield the
    :class:`AliasSanitizer`, verify on clean exit (an exception already
    propagating is not masked by a verification failure)."""
    if _ACTIVE:
        raise RuntimeError("alias_sanitizer() is already active "
                           "(not re-entrant)")
    san = AliasSanitizer()
    san._patch()
    _ACTIVE.append(san)
    try:
        yield san
    except BaseException:
        raise
    else:
        san.verify()
    finally:
        _ACTIVE.pop()
        san._unpatch()
        # detach finalizers: verification responsibility ends with the
        # context; late GC of device arrays must not re-verify against
        # legitimately-reused buffers
        for rec in list(san.records):
            rec.dead = True
            if rec.finalizer is not None:
                rec.finalizer.detach()


@contextmanager
def maybe_alias_sanitizer():
    """The env-gated wrapper the CLI drivers run under: the real sanitizer
    when ``GRAPHDYN_SANITIZE=alias``, a zero-overhead no-op otherwise."""
    if os.environ.get(ENV_VAR, "") == ENV_VALUE:
        with alias_sanitizer() as san:
            yield san
    else:
        yield None
