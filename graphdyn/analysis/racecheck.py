"""graftrace — a host-concurrency auditor with a committed shared-state
ledger (ARCHITECTURE.md "Host concurrency model").

The host side of this framework is genuinely concurrent: the prefetch
worker (:class:`graphdyn.pipeline.prefetch.HostPrefetcher`), the
write-behind mirror worker (:mod:`graphdyn.resilience.store`), the watchdog
thread (:mod:`graphdyn.resilience.supervisor`), the flight-recorder ring
(:mod:`graphdyn.obs.flight`) and the journal/heartbeat counters all share
process-global state across threads. PRs 8/9/10 each fixed a real thread
bug (aliased async reads, atexit-stranded mirror writes, a killer thread
firing before its handler installed, watchdog false-preempts) that was
found by accident, not by a gate. graftcheck made *device program
structure* falsifiable in this CPU-only container; this module does the
same for *host concurrency* — two coupled halves sharing one committed
ledger (``CONCURRENCY_LEDGER.json``, the graftcheck bless/update workflow):

**Static half** — an AST pass over ``graphdyn/`` that inventories the
concurrency surface (thread-spawn sites with their targets and daemon
flags; ``Lock``/``RLock``/``Event``/``Condition`` objects at module and
instance scope; the module-global mutables threads share; the static
lock-order graph) and enforces the GT rules:

- **GT001** — a module-global mutable written from a thread-target
  function (the spawn target, or a module-local function it reaches)
  without lexically holding an inventoried lock. Internally-synchronized
  kinds (``queue.Queue``, ``threading.local``) are exempt — they ARE the
  sanctioned sharing idioms.
- **GT002** — lock-order hazard: a cycle in the static acquired-while-
  holding graph (the textbook deadlock shape), or a live edge that
  *inverts* a ledgered pair (the committed order is the contract the
  runtime half asserts too).
- **GT003** — ``Thread.start()`` with no bounded join/close path: no
  ``.join(timeout=...)`` (or ``.join(<bound>)``) on the same thread object
  anywhere in the module. The prefetch/mirror lesson as a rule — a thread
  nobody can bound-join is a thread that wedges process exit or leaks past
  its driver; a daemon loop thread with a *different* bounded close path
  (the mirror's ``flush_mirror(timeout_s=...)``) documents itself with a
  reasoned disable naming that invariant.
- **GT004** — concurrency growth undeclared: a thread-spawn site, sync
  object, shared global, or lock-order edge absent from the committed
  ledger (or a stale ledger row with no live site). Exactly like a new
  HLO op category in graftcheck: the surface may grow, but only
  *declared* (``--update-ledger``, reviewed like any committed artifact).
- **GT005** — ``time.sleep``-based synchronization in non-test code.
  Sleeping is never a happens-before edge; every legitimate sleep (an
  injected-fault primitive, a bounded drain poll against an API with no
  timed join, the fuzzer's own jitter) carries a reasoned disable, so the
  exceptions are enumerable.

Escape hatches mirror graftlint (explicit code list, reason expected)::

    # graftrace: disable=GT005  <reason>
    # graftrace: disable-next-line=GT003  <reason>
    # graftrace: disable-file=GT001  <reason>

**Runtime half** — opt-in via ``GRAPHDYN_RACECHECK=1`` (the CLI installs
it before the driver runs): every inventoried *module-scope* ``Lock``/
``RLock`` is wrapped in a :class:`TracedLock` proxy that

- records per-thread acquisition sequences and emits one
  ``racecheck.acquire`` counter per acquire (lock name, thread name, the
  held stack) — the null recorder forwards these into the bounded flight
  ring, so a post-mortem names the lock a wedged run died holding;
- asserts the *observed* lock order against the ledger: acquiring ``B``
  while holding ``A`` when the ledger commits the pair ``[B, A]`` raises
  :class:`LockOrderError` naming both locks and the thread — the runtime
  complement of GT002;
- with ``GRAPHDYN_RACEFUZZ=<seed>`` additionally injects **deterministic
  per-seed jitter** at the wrapped acquire/release points: the delay is a
  pure function of ``(seed, lock, thread name, op)`` (constant per site
  per seed, ``GRAPHDYN_RACEFUZZ_MAX_MS`` caps it), so a schedule that
  loses a race loses it reproducibly. The fuzzer rides the existing
  fault-injection plumbing for thread-side delays the lock proxy cannot
  reach (the ``mirror.copy`` stall site in the write-behind worker); the
  ``race_mirror_exit`` / ``race_prefetch_close`` scenarios in
  :mod:`graphdyn.resilience.soak` drive it, and the mirror scenario
  proves the harness detects the historical bug class: reverting the
  atexit ``flush_mirror`` registration goes red at a pinned seed.

Racecheck OFF is the default and costs nothing per acquire: the module
locks stay the plain ``threading`` objects (no proxy exists at all — the
only cost is one env check at CLI start; regression-tested).

CLI, mirroring graftlint/graftcheck (exit code = number of findings)::

    python -m graphdyn.analysis.racecheck [--format=text|json]
        [--update-ledger] [--ledger PATH] [paths...]

The static half is stdlib-only (``ast`` + ``json``); the runtime half
imports only the modules whose locks it wraps. Heuristic by design —
scope expansion is module-local (a cross-module call chain into another
module's writes is that module's audit), and the disable hatch with a
written reason is the intended pressure valve.
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import importlib
import json
import os
import re
import sys
import threading
import time
from pathlib import Path
from typing import Iterable, NamedTuple

RULES = {
    "GT001": "module-global mutable written from a thread target without an inventoried lock held",
    "GT002": "lock-order hazard: static acquisition cycle, or an edge inverting a ledgered pair",
    "GT003": "Thread.start() without a bounded join/close path in the module",
    "GT004": "undeclared concurrency growth: thread/sync/global/lock-order site absent from the ledger (or stale ledger row)",
    "GT005": "time.sleep-based synchronization in non-test code (sleep is never a happens-before edge)",
}

LEDGER_NAME = "CONCURRENCY_LEDGER.json"

ENV_VAR = "GRAPHDYN_RACECHECK"
FUZZ_ENV = "GRAPHDYN_RACEFUZZ"
FUZZ_MAX_ENV = "GRAPHDYN_RACEFUZZ_MAX_MS"
#: default jitter cap (milliseconds) when the fuzzer is armed
FUZZ_MAX_MS_DEFAULT = 20.0

#: threading constructors that create sync objects, -> inventory kind
_SYNC_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
    "Event": "event", "Barrier": "barrier",
}
#: kinds that participate in lock ordering / can guard a GT001 write
_GUARD_KINDS = frozenset({"lock", "rlock", "condition", "semaphore"})

#: module-level constructors/literals that create shared mutable state,
#: -> inventory kind. "queue" and "threadlocal" are internally
#: synchronized / per-thread by construction: inventoried (the ledger is
#: the full sharing surface) but exempt from GT001.
_MUTABLE_CTORS = {
    "dict": "dict", "list": "list", "set": "set",
    "OrderedDict": "dict", "defaultdict": "dict", "Counter": "dict",
    "deque": "deque",
    "Queue": "queue", "LifoQueue": "queue", "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "local": "threadlocal",
}
_GT001_EXEMPT_KINDS = frozenset({"queue", "threadlocal"})

#: in-place mutator method names that count as a write for GT001
_MUTATORS = frozenset({
    "append", "appendleft", "add", "update", "clear", "pop", "popleft",
    "remove", "discard", "extend", "extendleft", "insert", "setdefault",
    "sort", "reverse", "rotate",
})

_DISABLE_RE = re.compile(
    r"#\s*graftrace:\s*(disable|disable-next-line|disable-file)=(.*)$"
)
_CODE_TOKEN = re.compile(r"(?i)^(gt\d{3}|all)$")


class Finding(NamedTuple):
    path: str
    line: int
    col: int
    code: str
    message: str


def default_ledger_path() -> Path:
    """The committed ledger at the repo root (next to
    ``GRAFTCHECK_FINGERPRINTS.json``)."""
    return Path(__file__).resolve().parents[2] / LEDGER_NAME


def default_paths() -> list[str]:
    """The package itself — the audit scope the committed ledger covers."""
    return [str(Path(__file__).resolve().parents[1])]


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _relkey(path: str) -> str:
    """Stable, cwd-independent file key: posix path relative to the repo
    root when under it, else the path as given."""
    p = Path(path).resolve()
    try:
        return p.relative_to(_repo_root()).as_posix()
    except ValueError:
        return Path(path).as_posix()


# ---------------------------------------------------------------------------
# disable comments (graftlint's hatch machinery, graftrace-prefixed) and
# shared AST helpers — one implementation for all in-package linters
# ---------------------------------------------------------------------------

from graphdyn.analysis.graftlint import (  # noqa: E402
    _dotted,
    iter_python_files,
    parse_disable_comments,
)


def _parse_disables(src: str):
    return parse_disable_comments(src, _DISABLE_RE, _CODE_TOKEN)


def _base(node: ast.AST) -> str:
    """The final attribute / bare name of a dotted chain ('' if neither)."""
    d = _dotted(node)
    return d.rsplit(".", 1)[-1] if d else ""


# ---------------------------------------------------------------------------
# inventory model
# ---------------------------------------------------------------------------


class ThreadSite(NamedTuple):
    path: str           # repo-relative file key
    line: int
    col: int
    key: str            # stable ledger key (name const, else target)
    target: str         # target base name ('' when unresolvable)
    name: str | None    # name= kwarg when a constant
    daemon: bool | None  # daemon= kwarg when a constant
    assigned: str | None  # base name/attr the Thread object is bound to


class SyncSite(NamedTuple):
    path: str
    line: int
    col: int
    name: str           # module global name, or "Class.attr" / "<fn>.attr"
    kind: str           # lock | rlock | condition | event | ...
    scope: str          # "module" | "instance"


class GlobalSite(NamedTuple):
    path: str
    line: int
    col: int
    name: str
    kind: str           # dict | list | set | deque | queue | threadlocal | rebound


class LockEdge(NamedTuple):
    outer: str          # qualified "path::name"
    inner: str
    path: str
    line: int
    col: int


class Inventory(NamedTuple):
    threads: list[ThreadSite]
    sync: list[SyncSite]
    globals_: list[GlobalSite]
    edges: list[LockEdge]


def _is_thread_ctor(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d == "Thread" or d.endswith(".Thread")


def _const_kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _target_base(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "target":
            return _base(kw.value)
    return ""


class _FileAudit:
    """Per-file inventory extraction + the single-file GT checks."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.key = _relkey(path)
        self.src = src
        self.findings: list[Finding] = []
        self.threads: list[ThreadSite] = []
        self.sync: list[SyncSite] = []
        self.globals_: list[GlobalSite] = []
        self.edges: list[LockEdge] = []
        self.tree: ast.Module | None = None
        # module-level sync names that can guard writes (Name -> kind)
        self.module_guards: dict[str, str] = {}
        self.module_globals: dict[str, str] = {}       # name -> kind
        self.fn_nodes: dict[str, list] = {}            # base name -> defs
        self.fn_calls: dict[int, set] = {}             # id(fn) -> callee bases
        self.fn_acquires: dict[int, set] = {}          # id(fn) -> lock names
        self.has_sleep_import = False

    def emit(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(Finding(
            self.key, getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0), code, message))

    # -- collection -----------------------------------------------------

    def collect(self) -> None:
        try:
            self.tree = ast.parse(self.src, filename=self.path)
        except SyntaxError as e:
            self.findings.append(Finding(
                self.key, e.lineno or 1, 0, "GT000",
                f"syntax error: {e.msg}"))
            return
        self._collect_imports()
        self._collect_module_state()
        self._collect_functions()
        self._filter_unwritten_globals()
        self._collect_threads_and_instance_sync()

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                if any(a.name == "sleep" for a in node.names):
                    self.has_sleep_import = True

    def _ctor_kind(self, value: ast.expr) -> tuple[str, str] | None:
        """('sync'|'mutable', kind) when ``value`` constructs shared
        state, else None."""
        if isinstance(value, ast.Call):
            b = _base(value.func)
            if b in _SYNC_CTORS and (
                "threading" in _dotted(value.func) or _dotted(value.func) == b
            ):
                return ("sync", _SYNC_CTORS[b])
            if b in _MUTABLE_CTORS:
                return ("mutable", _MUTABLE_CTORS[b])
            return None
        if isinstance(value, (ast.Dict, ast.DictComp)):
            return ("mutable", "dict")
        if isinstance(value, (ast.List, ast.ListComp)):
            return ("mutable", "list")
        if isinstance(value, (ast.Set, ast.SetComp)):
            return ("mutable", "set")
        return None

    def _collect_module_state(self) -> None:
        """Module-level sync objects and mutable globals; plus every name a
        function rebinds through a ``global`` declaration (a shared scalar
        slot is shared state even when its initializer is immutable)."""
        assert self.tree is not None
        for stmt in self.tree.body:
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is None:
                continue
            ck = self._ctor_kind(value)
            if ck is None:
                continue
            what, kind = ck
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if what == "sync":
                    self.sync.append(SyncSite(
                        self.key, stmt.lineno, stmt.col_offset,
                        t.id, kind, "module"))
                    if kind in _GUARD_KINDS:
                        self.module_guards[t.id] = kind
                else:
                    self.globals_.append(GlobalSite(
                        self.key, stmt.lineno, stmt.col_offset, t.id, kind))
                    self.module_globals[t.id] = kind
        # names rebound via `global` in any function
        module_names = {
            t.id for stmt in self.tree.body
            for t in (stmt.targets if isinstance(stmt, ast.Assign)
                      else [stmt.target] if isinstance(stmt, ast.AnnAssign)
                      else [])
            if isinstance(t, ast.Name)
        }
        seen = set(self.module_globals) | {s.name for s in self.sync}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Global):
                for name in node.names:
                    if name in module_names and name not in seen:
                        seen.add(name)
                        self.globals_.append(GlobalSite(
                            self.key, node.lineno, node.col_offset,
                            name, "rebound"))
                        self.module_globals[name] = "rebound"

    def _collect_functions(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self.fn_nodes.setdefault(node.name, []).append(node)
            called, acquires = set(), set()
            for sub in self._own_nodes(node):
                if isinstance(sub, ast.Call):
                    b = _base(sub.func)
                    if b:
                        called.add(b)
                elif isinstance(sub, ast.With):
                    for item in sub.items:
                        b = _base(item.context_expr)
                        if b in self.module_guards:
                            acquires.add(b)
            self.fn_calls[id(node)] = called
            self.fn_acquires[id(node)] = acquires

    def _filter_unwritten_globals(self) -> None:
        """Drop module-level containers no function ever writes: a
        read-only constant table (a rule set, a byte-model dict) is not
        *shared mutable state*, and inventorying it would make the ledger
        churn on every new constant. Kept unconditionally: ``queue`` /
        ``threadlocal`` kinds (the deliberate sharing idioms) and
        ``rebound`` slots (a ``global`` declaration IS a write)."""
        written: set[str] = set()
        for node in ast.walk(self.tree):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Name):
                    written.add(t.value.id)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                    and isinstance(node.func.value, ast.Name)):
                written.add(node.func.value.id)

        def keep(g: GlobalSite) -> bool:
            return (g.kind in ("queue", "threadlocal", "rebound")
                    or g.name in written)

        self.globals_ = [g for g in self.globals_ if keep(g)]
        self.module_globals = {
            n: k for n, k in self.module_globals.items()
            if k in ("queue", "threadlocal", "rebound") or n in written
        }

    @staticmethod
    def _own_nodes(fn) -> Iterable[ast.AST]:
        """The function's own statements — nested defs/lambdas are separate
        scopes audited on their own walk."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _collect_threads_and_instance_sync(self) -> None:
        # enclosing-scope names for instance sync sites ("Class.attr")
        parents: dict[int, str] = {}

        def walk(node, scope):
            for child in ast.iter_child_nodes(node):
                s = scope
                if isinstance(child, ast.ClassDef):
                    s = child.name
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    parents[id(child)] = scope
                    s = scope
                walk(child, s)

        walk(self.tree, "")
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                ck = self._ctor_kind(node.value)
                if ck and ck[0] == "sync":
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            scope = self._class_of(t) or "<instance>"
                            self.sync.append(SyncSite(
                                self.key, node.lineno, node.col_offset,
                                f"{scope}.{t.attr}", ck[1], "instance"))
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                name = _const_kwarg(node, "name")
                daemon = _const_kwarg(node, "daemon")
                target = _target_base(node)
                assigned = self._assigned_base(node)
                key = str(name) if isinstance(name, str) else (
                    f"target={target}" if target else f"line@{node.lineno}")
                self.threads.append(ThreadSite(
                    self.key, node.lineno, node.col_offset, key, target,
                    name if isinstance(name, str) else None,
                    daemon if isinstance(daemon, bool) else None, assigned))

    def _class_of(self, attr_node: ast.Attribute) -> str | None:
        """The class whose method assigns ``self.<attr>`` (lexical walk)."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if sub is attr_node:
                        return node.name
        return None

    def _assigned_base(self, ctor: ast.Call) -> str | None:
        """The base name/attr the Thread constructor's result is bound to
        (``x = Thread(...)`` -> 'x'; ``self._t = Thread(...)`` -> '_t')."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and node.value is ctor:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        return t.id
                    if isinstance(t, ast.Attribute):
                        return t.attr
        return None

    # -- threaded-scope resolution (GD013-style module-local fixpoint) --

    def threaded_scope(self) -> list[ast.AST]:
        """Function nodes reachable from any thread target in this module
        (by base name, through module-local calls, to a fixpoint)."""
        roots = {t.target for t in self.threads if t.target}
        scoped: set[str] = {r for r in roots if r in self.fn_nodes}
        changed = True
        while changed:
            changed = False
            for name in list(scoped):
                for fn in self.fn_nodes.get(name, []):
                    for callee in self.fn_calls.get(id(fn), ()):
                        if callee in self.fn_nodes and callee not in scoped:
                            scoped.add(callee)
                            changed = True
        out = []
        for name in sorted(scoped):
            out.extend(self.fn_nodes[name])
        return out

    # -- GT001 ----------------------------------------------------------

    def check_unguarded_writes(self) -> None:
        for fn in self.threaded_scope():
            globals_decl = {
                n for node in self._own_nodes(fn)
                if isinstance(node, ast.Global) for n in node.names
            }
            for stmt in fn.body:
                self._scan_writes(fn, stmt, [], globals_decl)

    def _scan_writes(self, fn, node, held: list[str],
                     globals_decl: set) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                  # separate scope; audited via its own root
        if isinstance(node, ast.With):
            locks = [
                _base(item.context_expr) for item in node.items
                if _base(item.context_expr) in self.module_guards
            ]
            inner = held + locks
            # the with-statement's own item expressions run unguarded
            for item in node.items:
                self._scan_writes(fn, item.context_expr, held, globals_decl)
            for b in node.body:
                self._scan_writes(fn, b, inner, globals_decl)
            return
        self._write_at(fn, node, held, globals_decl)
        for child in ast.iter_child_nodes(node):
            self._scan_writes(fn, child, held, globals_decl)

    def _write_at(self, fn, node, held: list[str],
                  globals_decl: set) -> None:
        target_name = None
        what = None
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Name) and t.id in globals_decl \
                        and t.id in self.module_globals:
                    target_name, what = t.id, "rebinds"
                elif isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Name) \
                        and t.value.id in self.module_globals:
                    target_name, what = t.value.id, "subscript-writes"
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATORS
              and isinstance(node.func.value, ast.Name)
              and node.func.value.id in self.module_globals):
            target_name, what = node.func.value.id, \
                f".{node.func.attr}()-mutates"
        if target_name is None:
            return
        kind = self.module_globals[target_name]
        if kind in _GT001_EXEMPT_KINDS:
            return
        if held:
            return
        self.emit(
            node, "GT001",
            f"thread-target scope {fn.name!r} {what} module global "
            f"{target_name!r} ({kind}) without holding an inventoried "
            f"lock — wrap the access in `with <lock>:` (and declare the "
            f"pairing in {LEDGER_NAME}), or route through an internally "
            f"synchronized container (queue.Queue / threading.local)",
        )

    # -- GT002 edges (local collection; graph checks are package-wide) --

    def collect_edges(self) -> None:
        acq_star: dict[str, set] = {
            name: set().union(*[self.fn_acquires[id(fn)]
                                for fn in fns]) if fns else set()
            for name, fns in self.fn_nodes.items()
        }
        changed = True
        while changed:
            changed = False
            for name, fns in self.fn_nodes.items():
                for fn in fns:
                    for callee in self.fn_calls.get(id(fn), ()):
                        extra = acq_star.get(callee, set()) - acq_star[name]
                        if extra:
                            acq_star[name] |= extra
                            changed = True

        def qual(lock: str) -> str:
            return f"{self.key}::{lock}"

        def visit(node, held: list[str]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.With):
                locks = [
                    _base(item.context_expr) for item in node.items
                    if _base(item.context_expr) in self.module_guards
                ]
                for lk in locks:
                    for h in held:
                        if h != lk:
                            self.edges.append(LockEdge(
                                qual(h), qual(lk), self.key,
                                node.lineno, node.col_offset))
                for item in node.items:
                    visit(item.context_expr, held)
                for b in node.body:
                    visit(b, held + locks)
                return
            if isinstance(node, ast.Call) and held:
                callee = _base(node.func)
                for lk in acq_star.get(callee, ()):
                    for h in held:
                        if h != lk:
                            self.edges.append(LockEdge(
                                qual(h), qual(lk), self.key,
                                node.lineno, node.col_offset))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for fns in self.fn_nodes.values():
            for fn in fns:
                for stmt in fn.body:
                    visit(stmt, [])

    # -- GT003 ----------------------------------------------------------

    def check_unjoined_threads(self) -> None:
        bounded: set[str] = set()
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and (node.args or any(kw.arg == "timeout"
                                          for kw in node.keywords))):
                b = _base(node.func.value)
                if b:
                    bounded.add(b)
        started: set[str] = set()
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start"):
                b = _base(node.func.value)
                if b:
                    started.add(b)
        for t in self.threads:
            if t.assigned is None or (t.assigned in started
                                      and t.assigned not in bounded):
                site = ast.parse("0").body[0]       # placeholder w/ lineno
                site.lineno, site.col_offset = t.line, t.col
                self.emit(
                    site, "GT003",
                    f"thread {t.key!r} is started but the module has no "
                    f"bounded `.join(timeout=...)` for "
                    f"{t.assigned or 'its (unbound) object'} — a thread "
                    f"nobody can bound-join wedges exit or outlives its "
                    f"driver (the prefetch/mirror lesson); add a bounded "
                    f"join/close path, or disable with the invariant that "
                    f"bounds it",
                )

    # -- GT005 ----------------------------------------------------------

    def check_sleep_sync(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d == "time.sleep" or (self.has_sleep_import and d == "sleep"):
                self.emit(
                    node, "GT005",
                    "time.sleep used as synchronization — a sleep is never "
                    "a happens-before edge: wait on an Event/Condition/"
                    "queue with a timeout instead, or disable with the "
                    "reason this sleep is not synchronization (injected "
                    "fault primitive, bounded drain poll, fuzzer jitter)",
                )


# ---------------------------------------------------------------------------
# package-wide analysis
# ---------------------------------------------------------------------------


def collect_inventory(paths: Iterable[str] | None = None,
                      sources: list[tuple[str, str]] | None = None
                      ) -> tuple[Inventory, list[Finding]]:
    """Parse every file and return ``(inventory, rule_findings)`` — the
    findings cover GT001/GT003/GT005 plus the GT002 *cycle* check; ledger
    diffs (GT004 + GT002 inversions) happen in :func:`check_ledger`.
    Disable comments are already honored."""
    if sources is None:
        sources = []
        for f in iter_python_files(paths or default_paths()):
            try:
                sources.append((str(f), f.read_text()))
            except OSError as e:
                # fail CLOSED, like graftlint: an uninspectable file is a
                # finding, not a skip
                return (Inventory([], [], [], []),
                        [Finding(_relkey(str(f)), 1, 0, "GT000",
                                 f"cannot read file: {e}")])
    audits = []
    findings: list[Finding] = []
    for path, src in sources:
        a = _FileAudit(path, src)
        a.collect()
        if a.tree is not None:
            a.check_unguarded_writes()
            a.collect_edges()
            a.check_unjoined_threads()
            a.check_sleep_sync()
        audits.append((a, src))
        findings.extend(a.findings)
    inv = Inventory(
        threads=[t for a, _ in audits for t in a.threads],
        sync=[s for a, _ in audits for s in a.sync],
        globals_=[g for a, _ in audits for g in a.globals_],
        edges=[e for a, _ in audits for e in a.edges],
    )
    findings.extend(_check_cycles(inv.edges))
    # honor disable comments
    out: list[Finding] = []
    disables = {}
    for a, src in audits:
        disables[a.key] = _parse_disables(src)
    for f in findings:
        same, nxt, whole = disables.get(f.path, ({}, {}, set()))
        disabled = (
            f.code in whole or "ALL" in whole
            or f.code in same.get(f.line, ()) or "ALL" in same.get(f.line, ())
            or f.code in nxt.get(f.line, ()) or "ALL" in nxt.get(f.line, ())
        )
        if not disabled:
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return inv, out


def _check_cycles(edges: list[LockEdge]) -> list[Finding]:
    """GT002: a cycle in the acquired-while-holding digraph is the textbook
    deadlock shape — two threads walking the cycle from different entry
    points block forever."""
    graph: dict[str, set] = {}
    where: dict[tuple, LockEdge] = {}
    for e in edges:
        graph.setdefault(e.outer, set()).add(e.inner)
        where.setdefault((e.outer, e.inner), e)
    findings = []
    seen_cycles: set = set()
    state: dict[str, int] = {}          # 0 unvisited, 1 on stack, 2 done

    def dfs(node, stack):
        state[node] = 1
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if state.get(nxt, 0) == 1:
                cyc = tuple(stack[stack.index(nxt):]) + (nxt,)
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    e = where.get((node, nxt)) or next(iter(where.values()))
                    findings.append(Finding(
                        e.path, e.line, e.col, "GT002",
                        "lock-order CYCLE: " + " -> ".join(cyc)
                        + " — two threads entering this cycle at different "
                        "locks deadlock; impose one global order (and "
                        f"commit it to {LEDGER_NAME})",
                    ))
            elif state.get(nxt, 0) == 0:
                dfs(nxt, stack)
        stack.pop()
        state[node] = 2

    for node in sorted(graph):
        if state.get(node, 0) == 0:
            dfs(node, [])
    return findings


# ---------------------------------------------------------------------------
# the ledger (CONCURRENCY_LEDGER.json)
# ---------------------------------------------------------------------------


def inventory_to_ledger(inv: Inventory) -> dict:
    threads = {
        f"{t.path}::{t.key}": {
            "target": t.target or None,
            "daemon": t.daemon,
        }
        for t in inv.threads
    }
    locks = {
        f"{s.path}::{s.name}": {"kind": s.kind, "scope": s.scope}
        for s in inv.sync
    }
    globals_ = {
        f"{g.path}::{g.name}": {"kind": g.kind}
        for g in inv.globals_
    }
    lock_order = sorted({(e.outer, e.inner) for e in inv.edges})
    return {
        "version": 1,
        "threads": dict(sorted(threads.items())),
        "locks": dict(sorted(locks.items())),
        "globals": dict(sorted(globals_.items())),
        "lock_order": [list(p) for p in lock_order],
    }


def load_ledger(path: Path | str | None = None) -> dict | None:
    p = Path(path) if path else default_ledger_path()
    if not p.exists():
        return None
    with open(p) as fh:
        return json.load(fh)


def write_ledger(inv: Inventory, path: Path | str | None = None) -> Path:
    from graphdyn.utils.io import write_json_atomic

    p = Path(path) if path else default_ledger_path()
    write_json_atomic(str(p), inventory_to_ledger(inv), indent=2,
                      sort_keys=True)
    return p


def check_ledger(inv: Inventory, ledger: dict | None,
                 ledger_path: str | None = None) -> list[Finding]:
    """GT004 (+ GT002 inversions): diff the live inventory against the
    committed ledger. A missing ledger is a finding per live section —
    the gate fails until ``--update-ledger`` commits the contract."""
    lpath = _relkey(str(ledger_path or default_ledger_path()))
    live = inventory_to_ledger(inv)
    if ledger is None:
        return [Finding(
            lpath, 1, 0, "GT004",
            f"no concurrency ledger found ({LEDGER_NAME}) — run `python -m "
            "graphdyn.analysis.racecheck --update-ledger` and commit it",
        )]
    findings: list[Finding] = []
    sites = {
        **{f"{t.path}::{t.key}": (t.path, t.line, t.col)
           for t in inv.threads},
        **{f"{s.path}::{s.name}": (s.path, s.line, s.col) for s in inv.sync},
        **{f"{g.path}::{g.name}": (g.path, g.line, g.col)
           for g in inv.globals_},
    }
    for section, noun in (("threads", "thread-spawn site"),
                          ("locks", "sync object"),
                          ("globals", "shared module global")):
        live_keys = set(live[section])
        ledger_keys = set(ledger.get(section, {}))
        for k in sorted(live_keys - ledger_keys):
            path, line, col = sites.get(k, (lpath, 1, 0))
            findings.append(Finding(
                path, line, col, "GT004",
                f"undeclared {noun} {k!r} — concurrency growth must be "
                f"declared: run --update-ledger and commit the new "
                f"{LEDGER_NAME} row (reviewed like a new HLO op category)",
            ))
        for k in sorted(ledger_keys - live_keys):
            findings.append(Finding(
                lpath, 1, 0, "GT004",
                f"stale ledger row: {noun} {k!r} no longer exists in the "
                f"code — run --update-ledger so the ledger matches the "
                f"shipped surface",
            ))
    live_edges = {tuple(p) for p in live["lock_order"]}
    ledger_edges = {tuple(p) for p in ledger.get("lock_order", [])}
    for a, b in sorted(live_edges - ledger_edges):
        e = next(e for e in inv.edges if (e.outer, e.inner) == (a, b))
        if (b, a) in ledger_edges:
            findings.append(Finding(
                e.path, e.line, e.col, "GT002",
                f"lock-order INVERSION: acquiring {b!r} while holding "
                f"{a!r}, but the ledger commits the order [{b}, {a}] — "
                "two threads obeying the two orders deadlock; restore the "
                "committed order or deliberately re-bless with "
                "--update-ledger",
            ))
        else:
            findings.append(Finding(
                e.path, e.line, e.col, "GT004",
                f"undeclared lock-order edge [{a}, {b}] — declare the "
                "acquired-while-holding pair via --update-ledger so the "
                "runtime half can assert the observed order against it",
            ))
    for a, b in sorted(ledger_edges - live_edges):
        findings.append(Finding(
            lpath, 1, 0, "GT004",
            f"stale ledger lock-order edge [{a}, {b}] — no live "
            "acquisition site implies it; run --update-ledger",
        ))
    return findings


def analyze_sources(sources: list[tuple[str, str]],
                    ledger: dict | None = None,
                    check_declarations: bool = False) -> list[Finding]:
    """Test-facing entry: rule findings for in-memory sources; pass a
    ledger dict (with ``check_declarations=True``) to also run the GT004/
    GT002-inversion diff."""
    inv, findings = collect_inventory(sources=sources)
    if check_declarations:
        findings = findings + check_ledger(inv, ledger)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


# ---------------------------------------------------------------------------
# runtime half: the traced-lock proxy, order assertion, schedule fuzzer
# ---------------------------------------------------------------------------


class LockOrderError(RuntimeError):
    """The observed acquisition order inverts a ledgered pair — the runtime
    complement of GT002, raised at the acquire that closes the inversion."""


_tl = threading.local()                 # per-thread held stack + hook guard
_book_lock = threading.Lock()           # guards _observed/_held_registry (never wrapped)
_observed: set = set()                  # observed (outer, inner) edges
#: registration seq -> (thread name, that thread's live held-stack LIST —
#: the same object the thread mutates). Keyed by a monotonic registration
#: id and NEVER overwritten: thread names repeat (every prefetch worker
#: is "graphdyn-prefetch") and CPython recycles thread idents after
#: exit, so either as a key would let a replacement thread silently
#: shadow what a dead/wedged thread still holds — the exact evidence the
#: crash stamp exists to keep. Dead threads with EMPTY stacks are pruned
#: at registration time (bounds growth); a dead thread holding a lock is
#: kept — that IS the post-mortem.
_held_registry: dict = {}
_reg_next: list = [1]                   # monotonic seq (under _book_lock)
_runtime: dict = {"installed": False, "wrapped": [], "pairs": frozenset(),
                  "fuzz": None}


def _held_stack() -> list:
    st = getattr(_tl, "held", None)
    if st is None:
        st = _tl.held = []
        t = threading.current_thread()
        with _book_lock:
            if len(_held_registry) > 64:
                live = {th.ident for th in threading.enumerate()}
                for k in [k for k, (_, ident, s) in _held_registry.items()
                          if not s and ident not in live]:
                    del _held_registry[k]
            seq = _reg_next[0]
            _reg_next[0] += 1
            _held_registry[seq] = (t.name, t.ident, st)
    return st


def held_locks() -> dict[str, list[str]]:
    """Snapshot of every registered thread's currently held wrapped locks
    (non-empty stacks only, keyed ``name#seq``) — the flight recorder's
    crash path stamps this into ``obs.crash`` so a post-mortem names the
    lock a wedged run died holding even after the ring rotated the
    acquire events out. Cross-thread reads are GIL-atomic list copies of
    live stacks: a racing acquire/release can shear the snapshot by one
    entry, which is exactly the precision a crash dump needs."""
    with _book_lock:
        return {f"{name}#{seq}": list(st)
                for seq, (name, _, st) in _held_registry.items() if st}


def _in_hook() -> bool:
    return getattr(_tl, "in_hook", False)


def _fuzz_delay_s(seed: int, lock: str, thread: str, op: str,
                  max_ms: float) -> float:
    """The fuzzer's seeding contract: the jitter at a given (lock, thread,
    op) site is a pure function of the seed — constant across the run, so
    a schedule that loses a race loses it reproducibly per seed."""
    h = int.from_bytes(hashlib.blake2s(
        f"{seed}:{lock}:{thread}:{op}".encode(), digest_size=4,
    ).digest(), "big")
    return (h % 1000) / 1000.0 * max_ms / 1000.0


def _jitter(lock: str, op: str) -> None:
    cfg = _runtime.get("fuzz")
    if not cfg:
        return
    delay = _fuzz_delay_s(cfg["seed"], lock,
                          threading.current_thread().name, op,
                          cfg["max_ms"])
    if delay > 0:
        # graftrace: disable-next-line=GT005  the fuzzer IS the jitter primitive — this sleep exists to perturb schedules, not to synchronize
        time.sleep(delay)


class TracedLock:
    """A ``Lock``/``RLock`` proxy recording per-thread acquisition
    sequences (into the flight ring via the obs counter), asserting the
    observed lock order against the ledgered pairs, and injecting the
    seeded schedule jitter. Installed only under ``GRAPHDYN_RACECHECK=1``
    — racecheck-off code never sees this class."""

    __slots__ = ("_inner", "name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _in_hook():
            return self._inner.acquire(blocking, timeout)
        # order check + event + jitter all happen BEFORE blocking on the
        # inner lock: an inversion is detected without deadlocking on it,
        # and the flight-ring event for a lock the run then wedges on says
        # what it was WAITING FOR and what it already held — exactly the
        # post-mortem question. (Emitting while holding would also
        # self-deadlock when the acquired lock IS the flight ring's own.)
        _tl.in_hook = True
        try:
            self._note_acquire_attempt()
            _jitter(self.name, "acquire")
        finally:
            _tl.in_hook = False
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        if not _in_hook():
            st = _held_stack()
            for i in range(len(st) - 1, -1, -1):
                if st[i] == self.name:
                    del st[i]
                    break
        self._inner.release()
        if _in_hook():
            return
        _tl.in_hook = True
        try:
            _jitter(self.name, "release")
        finally:
            _tl.in_hook = False

    def _note_acquire_attempt(self) -> None:
        st = _held_stack()
        held = [h for h in st if h != self.name]
        pairs = _runtime["pairs"]
        for h in held:
            if (self.name, h) in pairs and (h, self.name) not in pairs:
                raise LockOrderError(
                    f"lock-order inversion on thread "
                    f"{threading.current_thread().name!r}: acquiring "
                    f"{self.name!r} while holding {h!r}, but "
                    f"{LEDGER_NAME} commits the order "
                    f"[{self.name}, {h}] — the GT002 contract, observed "
                    f"live"
                )
        if held:
            with _book_lock:
                for h in held:
                    _observed.add((h, self.name))
        from graphdyn import obs

        obs.counter(
            "racecheck.acquire", lock=self.name,
            thread=threading.current_thread().name,
            depth=len(held) + 1, held="|".join(held) or None,
        )

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def _module_name(relkey: str) -> str:
    return relkey[:-3].replace("/", ".") if relkey.endswith(".py") else ""


_SELF_KEY = "graphdyn/analysis/racecheck.py"


def install(ledger_path: Path | str | None = None, *,
            fuzz_seed: int | None = None,
            fuzz_max_ms: float | None = None) -> list[str]:
    """Wrap every inventoried *module-scope* ``Lock``/``RLock`` in a
    :class:`TracedLock` (this module's own bookkeeping lock excluded).
    Idempotent; returns the wrapped qualified names. The ledger's
    ``lock_order`` pairs become the runtime assertion set."""
    if _runtime["installed"]:
        return [name for name, *_ in _runtime["wrapped"]]
    ledger = load_ledger(ledger_path)
    _runtime["pairs"] = frozenset(
        tuple(p) for p in (ledger or {}).get("lock_order", []))
    if fuzz_seed is None:
        raw = os.environ.get(FUZZ_ENV, "").strip()
        if raw:
            try:
                fuzz_seed = int(raw)
            except ValueError:
                fuzz_seed = None
    if fuzz_seed is not None:
        if fuzz_max_ms is None:
            try:
                fuzz_max_ms = float(
                    os.environ.get(FUZZ_MAX_ENV, "") or FUZZ_MAX_MS_DEFAULT)
            except ValueError:
                fuzz_max_ms = FUZZ_MAX_MS_DEFAULT
        _runtime["fuzz"] = {"seed": int(fuzz_seed),
                            "max_ms": float(fuzz_max_ms)}
    inv, _ = collect_inventory(default_paths())
    wrapped = []
    for s in inv.sync:
        if s.scope != "module" or s.kind not in ("lock", "rlock"):
            continue
        if s.path == _SELF_KEY:
            continue                    # never wrap our own bookkeeping
        modname = _module_name(s.path)
        if not modname:
            continue
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        obj = getattr(mod, s.name, None)
        if obj is None or isinstance(obj, TracedLock):
            continue
        if not (hasattr(obj, "acquire") and hasattr(obj, "release")):
            continue
        qual = f"{s.path}::{s.name}"
        proxy = TracedLock(obj, qual)
        setattr(mod, s.name, proxy)
        wrapped.append((qual, mod, s.name, obj))
    _runtime["wrapped"] = wrapped
    _runtime["installed"] = True
    return [name for name, *_ in wrapped]


def uninstall() -> None:
    """Restore the plain lock objects and reset the runtime state
    (tests; a real run just exits)."""
    for _, mod, attr, obj in _runtime["wrapped"]:
        setattr(mod, attr, obj)
    _runtime.update(installed=False, wrapped=[], pairs=frozenset(),
                    fuzz=None)
    with _book_lock:
        _observed.clear()
        # clear IN PLACE: the registry and each thread's _tl.held point at
        # the same list object — rebinding would orphan the registry view
        for _, _, st in _held_registry.values():
            st.clear()


def installed() -> bool:
    return bool(_runtime["installed"])


def observed_order() -> list[tuple[str, str]]:
    """The observed acquired-while-holding edges so far (sorted)."""
    with _book_lock:
        return sorted(_observed)


def assert_observed_against_ledger(ledger_path=None) -> list[str]:
    """Post-run check: every observed edge must not invert a ledgered
    pair. (Install-time acquisition already raises on the closing acquire;
    this surfaces the full list for harnesses.) Returns problem strings."""
    pairs = _runtime["pairs"] or frozenset(
        tuple(p) for p in (load_ledger(ledger_path) or {}).get(
            "lock_order", []))
    problems = []
    for a, b in observed_order():
        if (b, a) in pairs and (a, b) not in pairs:
            problems.append(
                f"observed edge [{a}, {b}] inverts ledgered pair [{b}, {a}]")
    return problems


def maybe_install() -> list[str]:
    """CLI hook: install the runtime proxies when ``GRAPHDYN_RACECHECK=1``.
    With the env unset this is ONE dict lookup — racecheck-off runs keep
    the plain ``threading`` locks (no proxy exists, zero per-acquire
    cost)."""
    if os.environ.get(ENV_VAR) != "1":
        return []
    return install()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.analysis.racecheck",
        description="graftrace: host-concurrency auditor over the "
                    "committed shared-state ledger (exit code = number of "
                    "findings)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to audit (default: the "
                    "graphdyn package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--ledger", default=None,
                    help=f"ledger path (default: repo-root {LEDGER_NAME})")
    ap.add_argument("--update-ledger", action="store_true",
                    help="rewrite the declaration ledger from the live "
                         "inventory (GT001/GT002-cycle/GT003/GT005 rule "
                         "findings still gate)")
    args = ap.parse_args(argv)

    paths = args.paths or default_paths()
    inv, findings = collect_inventory(paths)
    if args.update_ledger:
        if args.paths:
            ap.error("--update-ledger declares the WHOLE package surface; "
                     "it cannot be combined with explicit paths")
        path = write_ledger(inv, args.ledger)
        print(
            f"graftrace: wrote {len(inv.threads)} thread(s), "
            f"{len(inv.sync)} sync object(s), {len(inv.globals_)} shared "
            f"global(s), {len({(e.outer, e.inner) for e in inv.edges})} "
            f"lock-order edge(s) to {path}", file=sys.stderr)
    elif not args.paths:
        # the declaration diff (GT004) only means something over the full
        # default scope the ledger declares — a partial path list would
        # read every undiffed module as a stale row
        findings = findings + check_ledger(inv, load_ledger(args.ledger),
                                           args.ledger)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    else:
        print("graftrace: explicit paths — rule findings only, ledger "
              "diff skipped (it covers the whole package scope)",
              file=sys.stderr)

    if args.format == "json":
        # exactly ONE JSON document on stdout (CI pipes it); diagnostics
        # stay on stderr — the graftlint/graftcheck contract
        print(json.dumps({
            "findings": [f._asdict() for f in findings],
            "inventory": inventory_to_ledger(inv),
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col}: {f.code} {f.message}")
    if findings:
        print(f"graftrace: {len(findings)} finding(s)", file=sys.stderr)
    else:
        print(
            f"graftrace: concurrency surface clean ({len(inv.threads)} "
            f"thread(s), {len(inv.sync)} sync object(s), "
            f"{len(inv.globals_)} shared global(s))", file=sys.stderr)
    return min(len(findings), 125)


if __name__ == "__main__":
    sys.exit(main())
