"""graftcheck — a jaxpr/HLO program auditor with a fingerprint ledger.

graftlint (:mod:`graphdyn.analysis.graftlint`) reads *source text*; it
cannot see what XLA actually builds. But the compiled program's *structure*
— which ops appear, how they fuse, whether donations were honored, how many
while-loops survive, what constants got baked in — IS the perf contract:
fusion shapes and donation are exactly where the TPU-cluster Ising work
(arXiv:1903.11714) locates its throughput, and a structural regression
(a new gather, a lost donation, a program that recompiles per call) costs
throughput *silently* while results stay correct. Three of five bench
rounds ran with no TPU at all, so trace-time structure is the only perf
signal that is always available: this module makes every headline program's
structure a committed, diffable artifact.

Three pieces (ARCHITECTURE.md "Program-structure contracts"):

1. **Program fingerprinter.** Each headline entry point (the packed
   rollout, the BDCM sweep XLA core behind ``dp_contract``/
   ``dp_contract_grouped``, the ``EntropyCellExec`` chunk program, the
   ``HPRGroupExec`` sweep loop, the grouped SA rollout, and the mesh
   rollout) lowers at a small canonical shape and yields a stable
   fingerprint: HLO **op-category** counts (opcodes bucketed into
   elementwise / layout / gather / scatter / dot / reduce / control /
   fusion / … so benign instruction-selection jitter does not alias real
   drift), fusion count and root shapes, the donated (input/output-aliased)
   parameter set, the largest baked-in constant, and the while-loop count.
   Fingerprints persist to ``GRAFTCHECK_FINGERPRINTS.json`` (the ledger,
   committed); :func:`check_ledger` diffs live traces against it with
   per-field tolerance bands and fails tier-1 on structural drift.

2. **jaxpr/HLO-level rules** the AST linter cannot express:

   - **GC001** — donation declared but not honored: the entry point
     declares ``donate_argnums`` but the compiled executable carries no
     input/output alias (the state buffer is silently double-buffered).
   - **GC002** — unintended f32→f64 promotion inside a jitted graph: the
     inputs are ≤32-bit but the traced program contains float64 values
     (under x64 a stray Python float or ``np.float64`` scalar widens a
     whole chain — doubling message HBM traffic, invisible to GD004 when
     it arrives through an argument).
   - **GC003** — a large (> 1 MiB) host constant baked into the program:
     a closed-over table that should be an argument gets embedded per
     compilation, bloating executables and defeating compile-cache reuse.
   - **GC004** — recompile budget exceeded: :class:`RecompileWatch` counts
     *distinct compiled signatures* per entry point across a driver run;
     grouped executors must compile once per shape class, so a G-extent or
     weak-shape cache miss (every group recompiling) is caught here.

3. **Runtime host-aliasing sanitizer** (:mod:`graphdyn.analysis.sanitize`,
   opt-in via ``GRAPHDYN_SANITIZE=alias``): wraps host→device crossings,
   digests source buffers at dispatch and verifies them while the device
   array is alive — the PR-4 ``jnp.asarray`` aliasing race class as a
   deterministic failure instead of observed nondeterminism.

CLI, mirroring graftlint (exit code = number of findings)::

    python -m graphdyn.analysis.graftcheck [--format=text|json]
        [--update-ledger] [--ledger PATH] [--entries a,b,...]

JSON mode emits exactly ONE JSON document on stdout; all diagnostics
(progress, backend notes) go to stderr, so CI can pipe the output.

Tolerance bands (per field; "informational" = recorded, never gated):

====================  =====================================================
field                 band
====================  =====================================================
op_categories         a category present live but absent from the ledger
                      fails (GC101); per-category count drift beyond
                      max(4, 50% of ledger) fails (GC102)
fusion_count          drift beyond max(2, 25% of ledger) fails (GC103)
donated_params        ledger's aliased set must be a subset of live —
                      any lost donation fails (GC104)
largest_const_bytes   live > max(4× ledger, 1 MiB) fails (GC105)
while_loop_count      any change fails (GC106)
fusion_root_shapes    informational (shape text tracks workload tweaks)
opcode_counts         informational (instruction selection jitters)
====================  =====================================================

The ledger records the backend and jax version it was built on; the checker
diffs only when the live backend matches (the gate runs ``JAX_PLATFORMS=
cpu``, so the committed ledger is the CPU-container contract — exactly the
hardware-free signal ROADMAP item 5 asks for). A pure refactor that
preserves program structure passes without touching the ledger; a
deliberate structural change updates it via ``--update-ledger`` (reviewed
like any other committed artifact).
"""

from __future__ import annotations

import argparse
import json
import logging
import re
import sys
from pathlib import Path
from typing import Callable, NamedTuple

RULES = {
    "GC001": "donation declared but not honored by the compiled executable",
    "GC002": "unintended f32->f64 promotion inside a jitted graph",
    "GC003": "large host constant baked into the program",
    "GC004": "compile-signature budget exceeded (recompile guard)",
    "GC100": "entry point missing from the fingerprint ledger",
    "GC101": "new HLO op category vs the ledger",
    "GC102": "HLO op-category count drift beyond the tolerance band",
    "GC103": "fusion-count jump beyond the tolerance band",
    "GC104": "donation lost vs the ledger",
    "GC105": "baked-constant size blowup vs the ledger",
    "GC106": "while-loop count change vs the ledger",
}

#: live-rule threshold: constants above this are GC003 findings
LARGE_CONSTANT_BYTES = 1 << 20

LEDGER_NAME = "GRAFTCHECK_FINGERPRINTS.json"


def default_ledger_path() -> Path:
    """The committed ledger at the repo root (next to ROADMAP.md)."""
    return Path(__file__).resolve().parents[2] / LEDGER_NAME


class Finding(NamedTuple):
    entry: str
    code: str
    message: str


class UnsupportedEntry(RuntimeError):
    """An entry point whose canonical program cannot build in THIS
    environment (e.g. the halo-exchange rollout needs a 2-device mesh and
    the process sees one device). Distinct from a build *failure*: the
    collector records ``{"unsupported": reason}`` for the entry and every
    consumer skips it with a notice instead of reporting structural drift.
    The gate environments (lint.sh hlocheck, the test harness) force an
    8-device CPU host platform, so the skip only fires in genuinely
    single-device processes (e.g. a 1-chip bench run)."""


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------

# `%name = <shape> opcode(...)` — shape is either an array type
# (`f32[2,3]{1,0}`) or a tuple type (`(f32[2]{0}, s32[])`). A tuple type
# never contains parentheses, but past ~5 elements XLA interleaves
# `/*index=N*/` comments (which contain `=`), so the tuple alternative
# matches on paren balance, NOT on `=`-freedom — the old `\([^=]*?\)`
# silently missed every op whose result tuple carried such a comment,
# which is exactly the big-carry while loops GC106 exists to pin (a
# 14-field chunk carry was invisible; found by the fused-anneal row,
# whose ONE while loop fingerprinted as zero). One nesting level is
# allowed (a tuple element that is itself a flat tuple) so a future
# nested-tuple result type degrades the count visibly rather than
# silently re-opening the same gap.
_OP_RE = re.compile(
    r"=\s+((?:[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?"
    r"|\((?:[^()]|\([^()]*\))*\)))\s+"
    r"([a-z][a-z0-9-]*)\("
)
_CONST_RE = re.compile(
    r"=\s+([a-z0-9]+)\[([0-9,]*)\](?:\{[^}]*\})?\s+constant\("
)
_LAYOUT_RE = re.compile(r"\{[0-9,]*\}")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1,
    "f8e5m2": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# opcode -> structural category. Anything unlisted is "elementwise" — the
# default absorbs XLA's per-version instruction-selection jitter (add vs
# and vs select swaps) while a *new category* (a gather appearing in a
# program that had none, a custom-call, a collective) stays a hard signal.
_CATEGORY = {
    "while": "control", "conditional": "control", "call": "control",
    "fusion": "fusion",
    "constant": "constant",
    "gather": "gather", "dynamic-slice": "gather",
    "scatter": "scatter", "dynamic-update-slice": "scatter",
    "dot": "dot", "convolution": "dot",
    "reduce": "reduce", "reduce-window": "reduce",
    "sort": "sort",
    "rng": "rng", "rng-bit-generator": "rng",
    "rng-get-and-update-state": "rng",
    "custom-call": "custom-call",
    "all-reduce": "collective", "all-gather": "collective",
    "all-to-all": "collective", "collective-permute": "collective",
    "reduce-scatter": "collective", "collective-broadcast": "collective",
    "infeed": "hostio", "outfeed": "hostio",
    "send": "hostio", "recv": "hostio",
    "send-done": "hostio", "recv-done": "hostio",
    # data movement / shape plumbing
    "bitcast": "layout", "bitcast-convert": "layout", "broadcast": "layout",
    "reshape": "layout", "transpose": "layout", "copy": "layout",
    "copy-start": "layout", "copy-done": "layout", "pad": "layout",
    "slice": "layout", "concatenate": "layout", "reverse": "layout",
    "iota": "layout", "get-tuple-element": "layout", "tuple": "layout",
    "parameter": "layout", "convert": "layout", "after-all": "layout",
    "optimization-barrier": "layout",
}


def _find_alias_blob(txt: str) -> str | None:
    """The brace-balanced body of ``input_output_alias={...}`` in the
    module header, or None when the program aliases nothing."""
    key = "input_output_alias={"
    start = txt.find(key)
    if start < 0:
        return None
    i = start + len(key)
    depth = 1
    while i < len(txt) and depth:
        if txt[i] == "{":
            depth += 1
        elif txt[i] == "}":
            depth -= 1
        i += 1
    return txt[start + len(key):i - 1]


def fingerprint_text(hlo_text: str) -> dict:
    """Fingerprint one compiled-HLO module text (see module docstring for
    the field semantics and which fields the checker gates on)."""
    opcode_counts: dict[str, int] = {}
    fusion_shapes: list[str] = []
    for m in _OP_RE.finditer(hlo_text):
        shape, op = m.group(1), m.group(2)
        opcode_counts[op] = opcode_counts.get(op, 0) + 1
        if op == "fusion":
            fusion_shapes.append(_LAYOUT_RE.sub("", shape))

    categories: dict[str, int] = {}
    for op, cnt in opcode_counts.items():
        cat = _CATEGORY.get(op, "elementwise")
        categories[cat] = categories.get(cat, 0) + cnt

    largest_const = 0
    for m in _CONST_RE.finditer(hlo_text):
        dt, dims = m.group(1), m.group(2)
        size = _DTYPE_BYTES.get(dt, 8)
        for d in dims.split(","):
            if d.strip():
                size *= int(d)
        largest_const = max(largest_const, size)

    alias = _find_alias_blob(hlo_text)
    donated = sorted(
        {int(p) for p in re.findall(r"\(\s*(\d+)\s*,", alias)}
    ) if alias else []

    # the declared input dtypes, from the entry computation layout — used
    # by the GC002 live rule (f64 in the graph but not in the inputs)
    mlay = re.search(r"entry_computation_layout=\{\((.*?)\)->", hlo_text)
    input_dtypes = sorted(
        set(re.findall(r"([a-z0-9]+)\[", mlay.group(1)))
    ) if mlay else []

    return {
        "op_categories": dict(sorted(categories.items())),
        "opcode_counts": dict(sorted(opcode_counts.items())),
        "fusion_count": opcode_counts.get("fusion", 0),
        "fusion_root_shapes": sorted(fusion_shapes),
        "while_loop_count": opcode_counts.get("while", 0),
        "donated_params": donated,
        "largest_constant_bytes": largest_const,
        "input_dtypes": input_dtypes,
        "has_f64": bool(re.search(r"\bf64\[", hlo_text)),
    }


def fingerprint_lowered(lowered) -> dict:
    """Compile a ``jax.stages.Lowered`` and fingerprint the optimized HLO."""
    return fingerprint_text(lowered.compile().as_text())


# ---------------------------------------------------------------------------
# canonical entry points
# ---------------------------------------------------------------------------


class EntrySpec(NamedTuple):
    """One fingerprinted entry point: a builder returning the canonical
    ``jax.stages.Lowered`` (the ``lower_*`` surfaces live next to the code
    they lower — ops/pipeline/parallel — so refactors update them in
    place), whether the program declares buffer donation (the GC001
    contract), and a human note on the canonical shape."""

    build: Callable[..., object]
    donates: bool
    canon: str


def _canon_rrg(n: int, d: int, seed: int):
    from graphdyn.graphs import random_regular_graph

    return random_regular_graph(n, d, seed=seed)


def _build_packed_rollout(steps: int = 4, n: int = 256, R: int = 128):
    import jax.numpy as jnp
    import numpy as np

    from graphdyn.ops.packed import pack_spins, packed_rollout

    g = _canon_rrg(n, 3, 0)
    rng = np.random.default_rng(0)
    s = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    return packed_rollout.lower(
        jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(pack_spins(s)),
        steps=steps,
    )


def _build_bdcm_sweep(n: int = 64):
    from graphdyn.ops.bdcm import BDCMData, lower_sweep

    data = BDCMData(_canon_rrg(n, 3, 1), p=1, c=1)
    return lower_sweep(data, damp=0.9)


def _entropy_config():
    from graphdyn.config import DynamicsConfig, EntropyConfig

    return EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1), max_sweeps=50, eps=1e-4,
    )


def _build_entropy_cell_chunk(G: int = 2, n: int = 48):
    import jax.numpy as jnp

    from graphdyn.ops.bdcm import BDCMData
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cells = [
        (BDCMData(_canon_rrg(n, 3, k), p=1, c=1), n, 0) for k in range(G)
    ]
    ex = EntropyCellExec(
        cells, _entropy_config(), group_size=G, chunk_sweeps=8, kernel="xla"
    )
    chi = ex.stack_chi([c[0].init_messages(k) for k, c in enumerate(cells)])
    return ex.lower_chunk(
        chi,
        jnp.zeros(G, jnp.float32),
        jnp.ones(G, bool),
        jnp.full(G, jnp.inf, jnp.float32),
        jnp.zeros(G, jnp.int32),
    )


def _hpr_config():
    from graphdyn.config import DynamicsConfig, HPRConfig

    return HPRConfig(dynamics=DynamicsConfig(p=1, c=1), max_sweeps=20)


def _build_hpr_group_loop(G: int = 2, n: int = 24):
    from graphdyn.pipeline.hpr_group import HPRGroupExec, _build_rep

    config = _hpr_config()
    items = [_build_rep(n, 3, config, k, "pairing") for k in range(G)]
    ex = HPRGroupExec(items, config, group_size=G, kernel="xla")
    state = ex.init_state(
        [it[2] for it in items], [it[3] for it in items],
        [it[4] for it in items], list(range(G)),
    )
    return ex.lower_loop(state, 5)


def _build_sa_group_loop(G: int = 2, n: int = 32):
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.models.sa import prepare_sa_inputs
    from graphdyn.pipeline.sa_group import lower_group_loop

    config = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    graphs = [_canon_rrg(n, 3, k) for k in range(G)]
    preps = [
        prepare_sa_inputs(g, config, n_replicas=1, seed=k, max_steps=50)
        for k, g in enumerate(graphs)
    ]
    return lower_group_loop(
        graphs, preps, list(range(G)), config, group_size=G, chunk_steps=10,
    )


def _build_sharded_rollout(n: int = 64):
    import jax

    from graphdyn.parallel.mesh import make_mesh
    from graphdyn.parallel.sharded import lower_sharded_rollout

    # a 1-device mesh: the canonical mesh-path program must fingerprint
    # identically under the test harness's 8 simulated host devices and a
    # bare 1-device CLI run (the partitioned program depends only on the
    # mesh SHAPE, and (1, 1) exists in both environments)
    mesh = make_mesh((1, 1), ("replica", "node"), devices=jax.devices()[:1])
    return lower_sharded_rollout(mesh, _canon_rrg(n, 3, 0), 8, steps=2)


def _build_halo_rollout(n: int = 128):
    from graphdyn.graphs import partition_graph
    from graphdyn.parallel.halo import lower_halo_rollout
    from graphdyn.parallel.mesh import device_pool, make_mesh

    # the halo exchange only EXISTS at P >= 2 (a 1-device mesh has no
    # ppermute to pin), so this entry needs two devices; the gate
    # environments force an 8-device CPU host platform. The fingerprint
    # pins the exchange structure: one collective-permute slab per
    # schedule offset and NO all-gather — the regression this ledger row
    # exists to catch is the exchange silently deoptimizing into a
    # full-state gather.
    try:
        devices = device_pool(2)
    except RuntimeError as e:
        raise UnsupportedEntry(
            f"halo_rollout needs a 2-device mesh: {e} (force a simulated "
            "host platform: XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        ) from e
    mesh = make_mesh((2,), ("node",), devices=devices[:2])
    g = _canon_rrg(n, 3, 0)
    return lower_halo_rollout(
        mesh, g, partition_graph(g, 2, seed=0), W=4, steps=2
    )


def _build_bucketed_rollout(n: int = 256, W: int = 4, steps: int = 4):
    from graphdyn.graphs import degree_buckets, powerlaw_graph
    from graphdyn.ops.bucketed import lower_bucketed_rollout

    # canonical POWER-LAW family (the graph class the layout exists for:
    # the bucket schedule is degree-sequence-dependent, so the seeded
    # generator pins it); the fingerprint pins the one-program contract —
    # a single fused loop over the static bucket schedule with a donated
    # carry, no per-bucket dispatch and no dmax-padded gather
    g = powerlaw_graph(n, gamma=2.5, dmin=2, seed=0)
    return lower_bucketed_rollout(degree_buckets(g), W=W, steps=steps)


def _build_streamed_chunk(n: int = 256, W: int = 4, n_chunks: int = 3):
    from graphdyn.graphs import powerlaw_graph
    from graphdyn.ops.streamed import build_stream_plan, lower_streamed_chunk

    # canonical power-law family (the out-of-core layout exists for graphs
    # whose resident form does NOT fit); the fingerprinted program is the
    # per-chunk device step at the LAST chunk's shapes — degree-ascending
    # chunk order makes that the wide (hub) chunk, the shape regime whose
    # deoptimization (comparator route flipping, a dmax-padded gather
    # sneaking in) this row exists to catch. The degree cutoff is pinned
    # at 64 so the padded hub width is the SAME power of two (64 >
    # UNROLL_MAX — the wide route) at every graftcost calibration size:
    # uncapped, the hub width grows ~n^(1/(γ−1)) and the cost rows stop
    # being affine in n
    g = powerlaw_graph(n, gamma=2.5, dmin=2, dmax=64, seed=0)
    plan = build_stream_plan(g, W=W, n_chunks=n_chunks)
    return lower_streamed_chunk(plan.chunks[-1], W=W)


def _build_streamed_halo(n: int = 200):
    from graphdyn.graphs import partition_graph, powerlaw_graph
    from graphdyn.parallel.mesh import device_pool, make_mesh
    from graphdyn.parallel.stream import lower_stream_exchange

    # the composed streamed x sharded exchange program (the per-step slab
    # the chunk walk hands the mesh): like halo_rollout it only EXISTS at
    # P >= 2, so this entry needs two devices. The canonical graph is a
    # hub-split power-law partition — hubs vertex-cut at threshold 12 —
    # so the fingerprint pins BOTH collective legs: the hub bit-plane
    # ring and one collective-permute slab per schedule offset, with the
    # previous hub state donated into the carry. The regression this
    # ledger row exists to catch is the exchange silently deoptimizing
    # into a full-state all-gather (GD013).
    try:
        devices = device_pool(2)
    except RuntimeError as e:
        raise UnsupportedEntry(
            f"streamed_halo needs a 2-device mesh: {e} (force a simulated "
            "host platform: XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        ) from e
    mesh = make_mesh((2,), ("node",), devices=devices[:2])
    g = powerlaw_graph(n, gamma=2.3, dmin=2, seed=0)
    part = partition_graph(g, 2, seed=0, hub_threshold=12)
    return lower_stream_exchange(mesh, g, part, W=4)


def _temper_config():
    from graphdyn.config import DynamicsConfig, SAConfig

    return SAConfig(dynamics=DynamicsConfig(p=1, c=1))


def _build_temper_chunk(K: int = 4, n: int = 48):
    from graphdyn.search.tempering import lower_temper_chunk

    return lower_temper_chunk(
        _canon_rrg(n, 3, 0), _temper_config(), n_lanes=K, seed=0,
        max_steps=200, swap_interval=16,
    )


def _build_fused_chunk(R: int = 32, n: int = 48):
    from graphdyn.search.fused import lower_fused_chunk

    return lower_fused_chunk(
        _canon_rrg(n, 3, 0), _temper_config(), n_replicas=R, seed=0,
        m_target=0.9, chunk_sweeps=4,
    )


ENTRIES: dict[str, EntrySpec] = {
    "packed_rollout": EntrySpec(
        _build_packed_rollout, donates=False,
        canon="RRG n=256 d=3, R=128 packed (W=4), steps=4",
    ),
    "bdcm_sweep": EntrySpec(
        _build_bdcm_sweep, donates=False,
        canon="RRG n=64 d=3, p=c=1, damp=0.9, XLA core (use_pallas=False)",
    ),
    "entropy_cell_chunk": EntrySpec(
        _build_entropy_cell_chunk, donates=False,
        canon="G=2 cells, RRG n=48 d=3, p=c=1, chunk_sweeps=8, kernel=xla",
    ),
    "hpr_group_loop": EntrySpec(
        _build_hpr_group_loop, donates=True,
        canon="G=2 reps, RRG n=24 d=3, p=c=1, t_end=5, kernel=xla",
    ),
    "sa_group_loop": EntrySpec(
        _build_sa_group_loop, donates=True,
        canon="G=2 reps, RRG n=32 d=3, p=c=1, max_steps=50, chunk_steps=10",
    ),
    "sharded_rollout": EntrySpec(
        _build_sharded_rollout, donates=False,
        canon="1-device (replica, node) mesh, RRG n=64 d=3, R=8, steps=2",
    ),
    "bucketed_rollout": EntrySpec(
        _build_bucketed_rollout, donates=True,
        canon="power-law n=256 gamma=2.5 dmin=2 seed=0, degree-bucketed "
              "layout, W=4, steps=4, comparator route",
    ),
    # the out-of-core per-chunk step: donates=False is the CONTRACT here —
    # the [M+1, W] gathered slab can never alias the [C, W] chunk output,
    # and a donation annotation would only buy spurious "donated buffer
    # not usable" warnings on every host round-trip (GD006 at the jit)
    "streamed_rollout": EntrySpec(
        _build_streamed_chunk, donates=False,
        canon="power-law n=256 gamma=2.5 dmin=2 dmax=64 seed=0, stream "
              "plan K=3, last (hub) chunk's device step, W=4",
    ),
    "halo_rollout": EntrySpec(
        _build_halo_rollout, donates=True,
        canon="2-device node mesh, RRG n=128 d=3, P=2 partition, W=4, "
              "steps=2",
    ),
    # the composed streamed x sharded exchange (PR 20): boundary words +
    # hub bit-plane partial popcounts riding the ppermute slab / hub-ring
    # schedule between chunk walks — donates=True pins the hub carry,
    # and the op-category band pins "collective-permute only, never an
    # all-gather" for the composed engine's per-step device program
    "streamed_halo": EntrySpec(
        _build_streamed_halo, donates=True,
        canon="2-device node mesh, power-law n=200 gamma=2.3 dmin=2 "
              "seed=0, P=2 hub-split partition (threshold 12), W=4",
    ),
    # the swap-move program: the while-count band pins "ONE Metropolis
    # while-loop then the swap as straight-line ops" (a host round-trip or
    # a second loop sneaking into the swap fails GC106), and donates=True
    # pins the chunk-to-chunk in-place carry (GC001)
    "tempering_ladder": EntrySpec(
        _build_temper_chunk, donates=True,
        canon="K=4 drive ladder, RRG n=48 d=3, p=c=1, max_steps=200, "
              "swap_interval=16",
    ),
    # the one-kernel annealer's XLA twin (the CPU-container contract; the
    # Pallas kernel shares the loop body verbatim): the while-count band
    # pins ONE while loop over flat class steps — a scan over classes, a
    # second loop, or a host round-trip sneaking into the schedule advance
    # fails GC106 — donates=True pins the chunk-to-chunk in-place carry
    # (GC001), and the constant bands keep the LUT/coloring tables
    # arriving as arguments, never baked in (GC003/GC105)
    "fused_anneal": EntrySpec(
        _build_fused_chunk, donates=True,
        canon="R=32 packed replicas (W=1), RRG n=48 d=3, p=c=1, "
              "m_target=0.9, chunk_sweeps=4",
    ),
}

# fingerprint fields gated by the ledger diff (everything else is
# informational — see the band table in the module docstring)
_COMPACT_FIELDS = (
    "op_categories", "fusion_count", "while_loop_count", "donated_params",
    "largest_constant_bytes",
)


def lower_entry(name: str, **overrides):
    """The canonical ``jax.stages.Lowered`` for one entry point
    (``overrides`` reach the builder — e.g. ``G=8`` on the grouped
    entries, for the fingerprint-invariance tests)."""
    return ENTRIES[name].build(**overrides)


def collect_fingerprints(
    entries=None, *, compact: bool = False, diag=None, **overrides
) -> dict[str, dict]:
    """Fingerprints for ``entries`` (default: all). ``compact`` keeps only
    the ledger-gated fields (the bench summary row); ``diag`` is an
    optional progress sink (called with one string per entry — stderr in
    the CLI, so stdout stays a single JSON document)."""
    out = {}
    for name in entries or sorted(ENTRIES):
        if diag:
            diag(f"graftcheck: lowering + compiling {name} "
                 f"({ENTRIES[name].canon})")
        try:
            fp = fingerprint_lowered(lower_entry(name, **overrides))
        except UnsupportedEntry as e:
            # environment limitation, not drift: record the reason so
            # every consumer (ledger diff, bench diff, audit) can skip
            # the entry with a notice instead of mis-reading absence
            if diag:
                diag(f"graftcheck: {name} unsupported here: {e}")
            out[name] = {"unsupported": str(e)}
            continue
        if compact:
            fp = {k: fp[k] for k in _COMPACT_FIELDS}
        out[name] = fp
    return out


# ---------------------------------------------------------------------------
# live rules (no ledger needed): GC001 / GC002 / GC003
# ---------------------------------------------------------------------------


def audit_fingerprint(name: str, fp: dict, *, donates: bool) -> list[Finding]:
    """The ledger-free structural rules on one live fingerprint."""
    findings = []
    if donates and not fp["donated_params"]:
        findings.append(Finding(
            name, "GC001",
            "declares donate_argnums but the compiled executable carries "
            "NO input/output alias — the donated state buffer is silently "
            "double-buffered (backend dropped the donation, or an "
            "input/output shape-dtype mismatch made it unusable)",
        ))
    if fp.get("has_f64") and "f64" not in fp.get("input_dtypes", ()):
        findings.append(Finding(
            name, "GC002",
            "compiled program contains float64 values but no input is "
            "float64 — an implicit f32->f64 promotion inside the jitted "
            "graph (a Python float or np.float64 scalar under x64 widens "
            "the chain and doubles its HBM traffic)",
        ))
    if fp["largest_constant_bytes"] > LARGE_CONSTANT_BYTES:
        findings.append(Finding(
            name, "GC003",
            f"a {fp['largest_constant_bytes']} B constant is baked into "
            f"the program (> {LARGE_CONSTANT_BYTES} B) — a closed-over "
            "host table that should be a traced argument (it re-embeds "
            "per compile and defeats compile-cache sharing)",
        ))
    return findings


def check_no_f64(fn, *args, **kwargs) -> list[Finding]:
    """GC002 at the jaxpr level: trace ``fn`` and report every equation
    that *produces* a float64 value from non-float64 inputs. Usable on any
    callable (jitted or not) — complements the HLO-level scan inside
    :func:`audit_fingerprint` with primitive names for the report."""
    import jax
    import numpy as np

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    in_f64 = any(
        # graftlint: disable-next-line=GD004  dtype *guard*, no f64 created
        getattr(v.aval, "dtype", None) == np.float64
        for v in closed.jaxpr.invars
    )
    if in_f64:
        return []

    hits: list[str] = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                # graftlint: disable-next-line=GD004  dtype *guard* only
                if getattr(v.aval, "dtype", None) == np.float64:
                    hits.append(eqn.primitive.name)
            for val in eqn.params.values():
                for sub in _subjaxprs(val):
                    walk(sub)

    def _subjaxprs(val):
        import jax.extend.core as jex_core

        if isinstance(val, jex_core.ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, jex_core.Jaxpr):
            yield val
        elif isinstance(val, (tuple, list)):
            for v in val:
                yield from _subjaxprs(v)

    walk(closed.jaxpr)
    if not hits:
        return []
    uniq = sorted(set(hits))
    return [Finding(
        getattr(fn, "__name__", repr(fn)), "GC002",
        f"{len(hits)} equation(s) produce float64 from non-float64 inputs "
        f"(primitives: {', '.join(uniq[:8])}) — unintended f32->f64 "
        "promotion inside the traced graph",
    )]


# ---------------------------------------------------------------------------
# GC004 — the recompile guard
# ---------------------------------------------------------------------------


class RecompileWatch:
    """Counts distinct compiled signatures per jitted function across a
    driver run, via ``jax_log_compiles`` (the compile path logs one
    "Compiling <name> with global shapes and types [...]" line per cache
    miss — a cache hit logs nothing, so hits are free and misses are
    exact). Use as a context manager::

        with RecompileWatch() as watch:
            run_driver(...)
        findings = check_recompiles(watch, {"_sa_group_loop": 1})

    Grouped executors must compile once per shape class: a G-extent or
    weak-shape mismatch (every group recompiling) shows up as multiple
    distinct signatures for one entry-point name.
    """

    _COMPILE_RE = re.compile(r"^Compiling\s+(\S+)")

    def __init__(self):
        self.events: list[tuple[str, str]] = []   # (name, signature)
        self._handler = None
        self._prev_flag = None

    # the compile log line is emitted by the lowering machinery; hook the
    # jax logger subtree so a module rename inside jax keeps working
    _LOGGER = "jax"

    def __enter__(self):
        import jax

        watch = self

        class _Handler(logging.Handler):
            def emit(self, record):
                try:
                    msg = record.getMessage()
                except Exception:
                    return
                m = watch._COMPILE_RE.match(msg)
                if m:
                    watch.events.append((m.group(1), msg))

        self._handler = _Handler(level=logging.DEBUG)
        self._prev_flag = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        logging.getLogger(self._LOGGER).addHandler(self._handler)
        return self

    def __exit__(self, *exc):
        import jax

        logging.getLogger(self._LOGGER).removeHandler(self._handler)
        jax.config.update("jax_log_compiles", self._prev_flag)
        return False

    def signatures(self, name_pattern: str) -> set:
        """Distinct compile signatures whose function name matches the
        (substring or regex) pattern."""
        pat = re.compile(name_pattern)
        return {sig for name, sig in self.events if pat.search(name)}

    def counts(self) -> dict[str, int]:
        """Distinct-signature count per compiled function name."""
        per: dict[str, set] = {}
        for name, sig in self.events:
            per.setdefault(name, set()).add(sig)
        return {name: len(sigs) for name, sigs in sorted(per.items())}


def check_recompiles(
    watch: RecompileWatch, budgets: dict[str, int]
) -> list[Finding]:
    """GC004: each ``budgets`` pattern's distinct-signature count must not
    exceed its budget (budget = expected shape classes; 1 for a
    fixed-shape driver run)."""
    findings = []
    for pattern, budget in budgets.items():
        sigs = watch.signatures(pattern)
        if len(sigs) > budget:
            findings.append(Finding(
                pattern, "GC004",
                f"{len(sigs)} distinct compiled signatures (budget "
                f"{budget}) — the entry point recompiles across the run "
                "(G-extent / weak-shape cache miss: pad the group or make "
                "the varying value a traced argument). Signatures: "
                + " | ".join(sorted(s[:120] for s in sigs)),
            ))
    return findings


# ---------------------------------------------------------------------------
# ledger
# ---------------------------------------------------------------------------


def load_ledger(path: Path | str | None = None) -> dict | None:
    p = Path(path) if path else default_ledger_path()
    if not p.exists():
        return None
    with open(p) as fh:
        return json.load(fh)


def write_ledger(fingerprints: dict, path: Path | str | None = None) -> Path:
    """Persist the ledger atomically (the GD007 discipline — a torn ledger
    would fail every subsequent gate run)."""
    import jax

    from graphdyn.utils.io import write_json_atomic

    p = Path(path) if path else default_ledger_path()
    write_json_atomic(str(p), {
        "version": 1,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "canon": {name: ENTRIES[name].canon for name in sorted(ENTRIES)},
        "entries": fingerprints,
    }, indent=2, sort_keys=True)
    return p


def diff_fingerprints(entry: str, ledger_fp: dict, live_fp: dict) -> list[Finding]:
    """Per-field tolerance-band diff of one live fingerprint against its
    ledger row (band table in the module docstring)."""
    findings = []
    lcat = ledger_fp.get("op_categories", {})
    vcat = live_fp.get("op_categories", {})
    for cat, cnt in sorted(vcat.items()):
        if cnt and cat not in lcat:
            findings.append(Finding(
                entry, "GC101",
                f"new HLO op category {cat!r} ({cnt} op(s)) absent from "
                "the ledger — the program gained a structurally new kind "
                "of operation (e.g. a gather/scatter/custom-call that was "
                "never there). If intentional, re-run with --update-ledger",
            ))
    for cat in sorted(set(lcat) | set(vcat)):
        want, got = lcat.get(cat, 0), vcat.get(cat, 0)
        if cat not in lcat:
            continue                      # already a GC101 finding
        band = max(4, int(0.5 * want))
        if abs(got - want) > band:
            findings.append(Finding(
                entry, "GC102",
                f"op category {cat!r}: {want} -> {got} ops "
                f"(band ±{band}) — structural drift beyond benign "
                "instruction-selection jitter",
            ))
    want_f = ledger_fp.get("fusion_count", 0)
    got_f = live_fp.get("fusion_count", 0)
    band_f = max(2, int(0.25 * want_f))
    if abs(got_f - want_f) > band_f:
        findings.append(Finding(
            entry, "GC103",
            f"fusion count {want_f} -> {got_f} (band ±{band_f}) — XLA "
            "now builds a structurally different program (a fused loop "
            "body split apart, or new unfused HBM round-trips)",
        ))
    lost = sorted(
        set(ledger_fp.get("donated_params", ()))
        - set(live_fp.get("donated_params", ()))
    )
    if lost:
        findings.append(Finding(
            entry, "GC104",
            f"donation LOST: input parameter(s) {lost} were input/output-"
            "aliased in the ledger but the live program no longer donates "
            "them — the state buffer is double-buffered in HBM every call. "
            "If intentional, re-run with --update-ledger",
        ))
    want_c = ledger_fp.get("largest_constant_bytes", 0)
    got_c = live_fp.get("largest_constant_bytes", 0)
    if got_c > max(4 * want_c, LARGE_CONSTANT_BYTES):
        findings.append(Finding(
            entry, "GC105",
            f"largest baked-in constant {want_c} B -> {got_c} B — a host "
            "table is being embedded into the program instead of passed "
            "as an argument",
        ))
    want_w = ledger_fp.get("while_loop_count", 0)
    got_w = live_fp.get("while_loop_count", 0)
    if got_w != want_w:
        findings.append(Finding(
            entry, "GC106",
            f"while-loop count {want_w} -> {got_w} — loop structure "
            "changed (a fused while-loop split, a scan unrolled, or a "
            "loop disappeared into host Python). If intentional, re-run "
            "with --update-ledger",
        ))
    return findings


def check_ledger(
    live: dict[str, dict], ledger: dict | None, *, diag=None
) -> list[Finding]:
    """Diff live fingerprints against the ledger. A missing ledger (or a
    missing entry) is a finding — the gate must fail until
    ``--update-ledger`` commits the contract, not silently pass."""
    import jax

    if ledger is None:
        return [
            Finding(name, "GC100",
                    f"no ledger found ({LEDGER_NAME}) — run `python -m "
                    "graphdyn.analysis.graftcheck --update-ledger` and "
                    "commit it")
            for name in sorted(live)
        ]
    backend = jax.default_backend()
    if ledger.get("backend") != backend:
        if diag:
            diag(
                f"graftcheck: ledger was built on backend="
                f"{ledger.get('backend')!r}, live backend is {backend!r} — "
                "skipping the structural diff (fingerprints are backend-"
                "specific; the gate runs on JAX_PLATFORMS=cpu)"
            )
        return []
    if ledger.get("jax") != jax.__version__ and diag:
        diag(
            f"graftcheck: ledger jax={ledger.get('jax')} != live "
            f"jax={jax.__version__} — diffing anyway (bands absorb minor "
            "drift; re-run --update-ledger after a jax upgrade if the "
            "diff fails)"
        )
    findings = []
    entries = ledger.get("entries", {})
    for name in sorted(live):
        if "unsupported" in live[name]:
            # the entry could not build in THIS environment (e.g. a
            # single-device process and the halo entry's 2-device mesh):
            # a notice, not drift — the gate environments force enough
            # simulated devices that this never silently hides a check
            if diag:
                diag(f"graftcheck: skipping {name} diff — "
                     f"{live[name]['unsupported']}")
            continue
        if name not in entries:
            findings.append(Finding(
                name, "GC100",
                "entry point not in the fingerprint ledger — run "
                "--update-ledger and commit the new row",
            ))
            continue
        findings.extend(diff_fingerprints(name, entries[name], live[name]))
    return findings


def diff_bench_fingerprints(prev_row: dict, new_row: dict) -> list[Finding]:
    """Round-over-round structural diff for ``bench.py``'s persisted
    fingerprint summaries (the benchcheck hook): same band policy as the
    ledger diff, applied between two BENCH_*.json rows. Rows from
    different backends — or rounds predating the fingerprint column —
    produce no findings (nothing comparable)."""
    prev = prev_row or {}
    new = new_row or {}
    if not prev.get("entries") or not new.get("entries"):
        return []
    if prev.get("backend") != new.get("backend"):
        return []
    findings = []
    for name, new_fp in sorted(new["entries"].items()):
        old_fp = prev["entries"].get(name)
        if "unsupported" in new_fp or (old_fp and "unsupported" in old_fp):
            continue                      # environment skip, not drift
        if old_fp:
            findings.extend(diff_fingerprints(name, old_fp, new_fp))
    return findings


def bench_drift_blessed(new_row: dict, ledger: dict | None = None) -> bool:
    """Whether a bench fingerprint row that drifted from the *previous
    round* agrees with the committed LEDGER — i.e. the structural change
    was deliberately blessed via ``--update-ledger`` in a reviewed PR.
    This is benchcheck's update path: round artifacts (``BENCH_r*.json``)
    are immutable history, so after a blessed change the round-over-round
    diff stays red only until the checker sees the new row matches the
    ledger; the comparison baseline then refreshes when the next round
    persists its row."""
    ledger = ledger if ledger is not None else load_ledger()
    if not ledger or not new_row or not new_row.get("entries"):
        return False
    if ledger.get("backend") != new_row.get("backend"):
        return False
    entries = ledger.get("entries", {})
    for name, fp in new_row["entries"].items():
        if "unsupported" in fp:
            continue                      # environment skip, not drift
        old = entries.get(name)
        if old is None or diff_fingerprints(name, old, fp):
            return False
    return True


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _diag(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m graphdyn.analysis.graftcheck",
        description="graftcheck: jaxpr/HLO program auditor over the "
                    "fingerprint ledger (exit code = number of findings)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--ledger", default=None,
                    help=f"ledger path (default: repo-root {LEDGER_NAME})")
    ap.add_argument("--update-ledger", action="store_true",
                    help="recompute every entry and rewrite the ledger "
                         "(live GC001-GC003 rules still gate)")
    ap.add_argument("--entries", default=None,
                    help="comma-separated subset of entry points "
                         f"(default: all of {', '.join(sorted(ENTRIES))})")
    args = ap.parse_args(argv)

    names = sorted(ENTRIES)
    if args.entries:
        names = [e.strip() for e in args.entries.split(",") if e.strip()]
        unknown = [e for e in names if e not in ENTRIES]
        if unknown:
            ap.error(f"unknown entries: {', '.join(unknown)} "
                     f"(known: {', '.join(sorted(ENTRIES))})")

    live = collect_fingerprints(names, diag=_diag)
    findings: list[Finding] = []
    for name in names:
        if "unsupported" in live[name]:
            continue                      # skipped with a diag by the collector
        findings.extend(
            audit_fingerprint(name, live[name], donates=ENTRIES[name].donates)
        )
    if args.update_ledger:
        if set(names) != set(ENTRIES):
            ap.error("--update-ledger rewrites the WHOLE ledger; it cannot "
                     "be combined with --entries")
        unsupported = sorted(
            n for n, fp in live.items() if "unsupported" in fp
        )
        if unsupported:
            ap.error(
                "--update-ledger refuses to write a degraded ledger — "
                f"unsupported here: {', '.join(unsupported)} (run under "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
        path = write_ledger(live, args.ledger)
        _diag(f"graftcheck: wrote {len(live)} fingerprint(s) to {path}")
    else:
        findings.extend(
            check_ledger(live, load_ledger(args.ledger), diag=_diag)
        )

    if args.format == "json":
        # exactly ONE JSON document on stdout; diagnostics live on stderr
        print(json.dumps({
            "findings": [f._asdict() for f in findings],
            "fingerprints": live,
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.entry}: {f.code} {f.message}")
    if findings:
        _diag(f"graftcheck: {len(findings)} finding(s)")
    else:
        _diag(f"graftcheck: {len(live)} entry point(s) clean")
    return min(len(findings), 125)


if __name__ == "__main__":
    sys.exit(main())
