"""Roofline-anchored CPU-proxy perf bands (ARCHITECTURE.md "Runtime
telemetry" → roofline band table).

Three of five bench rounds ran with no TPU (ROADMAP item 5): a runtime
regression in a headline program — an extra HBM-sized copy, a gather
falling out of its fused form, a kernel silently scalarizing — would be
invisible most rounds. This module makes the *CPU container* carry an
absolute perf anchor: for each headline program it derives an expected
streaming rate from ARCHITECTURE.md's byte model and a **bandwidth proxy
measured on the host at check time** (so the anchor moves with the machine,
not with the calendar), measures the real program at a smoke shape, and
asserts the measured/model fraction sits inside a committed band.

The bands are deliberately **decade-wide** (table below): a CPU proxy
cannot hold chip-grade tolerances across container load, but an
order-of-magnitude collapse — the class of regression that silently ate
rounds r01/r03/r04's signal — cannot hide inside a decade. The tight
instrument is the round-over-round trend gate (:mod:`graphdyn.obs.trend`);
this module is the absolute sanity anchor underneath it.

Byte models (f32; K = 2**T, M = (d+1)**T):

- **packed rollout** — the ARCHITECTURE.md streaming minimum: per
  spin-update, ``d·4W`` gathered + ``4W`` written bytes across ``32·W``
  replicas → ``(d+1)/8`` B/update (d=3 → 0.5 B).
- **BDCM sweep core** (XLA path) — per directed edge per sweep the DP
  lattice dominates: d accumulation rounds, each reading the ``[K, M]``
  lattice K times (shifted) and writing it once → ``4·d·(K+1)·K·M``; plus
  the factor contraction (``4·K²·M``) and the chi rows themselves
  (``4·(d+2)·K²``). This is exactly the traffic the Pallas kernel keeps
  in VMEM (ARCHITECTURE.md VMEM byte model) — on the CPU proxy it is also
  FLOP-heavy, which the band's low anchor absorbs.
- **entropy cell chunk** — the BDCM model per lane; the grouped executor
  adds the per-lane freeze selects, absorbed by the same band.

``run_obscheck`` is wired into ``scripts/lint.sh`` (the ``obscheck`` step,
``GRAPHDYN_SKIP_OBSCHECK=1`` to skip); when a recorder is active each
measured rate is also emitted as an ``obs.roofline.<program>`` gauge.

On a TPU backend the check switches anchors: :data:`CHIP_BANDS` pins the
chip's published HBM bandwidth (v5e: 819 GB/s) as the model divisor —
fixed by the part number, not measured — against the same byte models,
with its own committed bands. Inert on this container (CPU), live the
first chip round, no code change in between (ROADMAP item 5 remainder).
"""

from __future__ import annotations

import time
from typing import NamedTuple

import numpy as np

# measured/model bands per program: (lo_frac, hi_frac). Calibrated on the
# tier-1 CPU container (packed ≈ 0.29, bdcm ≈ 0.06, entropy ≈ bdcm) with
# about a decade of margin on each side; update workflow in ARCHITECTURE.md
# ("Runtime telemetry" → obscheck update workflow).
BANDS: dict[str, tuple[float, float]] = {
    "packed_rollout": (0.02, 4.0),
    "bdcm_sweep": (0.004, 1.0),
    "entropy_cell_chunk": (0.002, 1.0),
}

#: chip-roofline anchors keyed by TPU device kind (substring match against
#: ``Device.device_kind``) — the ROADMAP item 5 remainder: the per-segment
#: rate gauges grow chip bands the moment a chip round runs this check,
#: with no code change. Each entry pins the chip's published HBM stream
#: bandwidth as the model divisor (v5e: 819 GB/s — the anchor does NOT
#: move with the machine, unlike the CPU proxy's measured host bandwidth:
#: on a chip the part number pins the roof) against the SAME byte models.
#: PROVISIONAL seeds, inert until a chip round persists rows: lo is set
#: where an HBM-streaming kernel cannot honestly fall below (the packed
#: kernel measured 0.11 of the v4 HBM roof in round r02 — v5e lo keeps a
#: decade under that), hi > 1 because the BDCM Pallas kernel holds its DP
#: lattice in VMEM and legitimately beats the HBM streaming model. The
#: first chip round re-centers them (update workflow: ARCHITECTURE.md).
_V5E_PROFILE: dict = {
    "hbm_bytes_per_s": 819e9,
    "bands": {
        "packed_rollout": (0.01, 2.0),
        "bdcm_sweep": (0.002, 4.0),
        "entropy_cell_chunk": (0.001, 4.0),
    },
}

CHIP_BANDS: dict[str, dict] = {
    "v5e": _V5E_PROFILE,
    # v5 lite is the device_kind string some runtimes report for v5e —
    # same physical part, ONE shared profile (a recalibration edit cannot
    # fork the two keys)
    "v5 lite": _V5E_PROFILE,
}


def chip_profile() -> tuple[str, float, dict] | None:
    """``(kind_key, hbm_bytes_per_s, bands)`` for the current backend's
    :data:`CHIP_BANDS` entry, or None when the backend has no chip anchor
    (CPU container: the measured-host-bandwidth proxy bands apply). A TPU
    backend whose device kind has no committed entry also returns None —
    an uncalibrated chip must not borrow another part's roof, and
    ``run_obscheck`` passes it STRUCTURALLY (the host-proxy bands are
    calibrated for host rates; gating chip rates against them would go
    red on every uncalibrated part with no blessing path)."""
    import jax

    if jax.default_backend() != "tpu":
        return None
    kind = jax.local_devices()[0].device_kind.lower()
    for key, prof in CHIP_BANDS.items():
        if key in kind:
            return key, prof["hbm_bytes_per_s"], prof["bands"]
    return None


def packed_bytes_per_update(d: int) -> float:
    """Streaming bytes per spin-update of the packed kernel (word width
    cancels: ``(d·4W + 4W) / 32W``)."""
    return (d + 1) / 8.0


def bdcm_bytes_per_edge_sweep(d: int, T: int) -> float:
    """CPU-proxy traffic per directed edge per sweep of the XLA sweep core
    (module docstring; DP-lattice dominated)."""
    K = 2 ** T
    M = (d + 1) ** T
    return 4.0 * (d * (K + 1) * K * M + K * K * M + (d + 2) * K * K)


def host_stream_bandwidth(nbytes: int = 1 << 26, iters: int = 3) -> float:
    """Measured host copy bandwidth (read+write B/s, best of ``iters``) —
    the machine-local divisor that anchors every model rate, so the bands
    track the container the check runs on instead of a hardcoded GB/s."""
    # graftlint: disable-next-line=GD004  host-only bandwidth probe buffer, never shipped to a device
    src = np.ones(nbytes // 8, np.float64)
    dst = np.empty_like(src)
    best = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        dt = time.perf_counter() - t0
        best = max(best, 2 * nbytes / max(dt, 1e-9))
    return best


class RooflineRow(NamedTuple):
    program: str
    measured: float         # updates/s (packed) or edge-sweeps/s (BDCM)
    model: float            # bandwidth / bytes-per-unit
    frac: float             # measured / model
    lo: float
    hi: float
    unit: str

    @property
    def ok(self) -> bool:
        return self.lo <= self.frac <= self.hi


def _row(program: str, measured: float, model: float, unit: str,
         bands: dict | None = None) -> RooflineRow:
    lo, hi = (bands or BANDS)[program]
    return RooflineRow(program, measured, model,
                       measured / model if model else 0.0, lo, hi, unit)


def _packed_smoke(*, n: int = 32768, d: int = 3, W: int = 8,
                  steps: int = 8):
    """``(f, sp)``: the jit-donated packed-rollout smoke program + its
    initial packed state — ONE builder, shared with
    :mod:`graphdyn.obs.memband` so the rate rows and the memory rows
    measure the same program."""
    import jax
    import jax.numpy as jnp

    from graphdyn.graphs import random_regular_graph
    from graphdyn.ops.packed import packed_rollout

    g = random_regular_graph(n, d, seed=0)
    nbr = jnp.asarray(g.nbr)
    deg = jnp.asarray(g.deg)
    rng = np.random.default_rng(0)
    sp = jnp.array(rng.integers(0, 2 ** 32, (n, W), dtype=np.uint32))
    f = jax.jit(lambda s: packed_rollout(nbr, deg, s, steps),
                donate_argnums=0)
    return f, sp


def measure_packed(bw: float, *, n: int = 32768, d: int = 3, W: int = 8,
                   steps: int = 8, iters: int = 3,
                   bands: dict | None = None) -> RooflineRow:
    """The packed-rollout CPU proxy at a smoke shape (chained, donated —
    the ``bench.py`` timing discipline)."""
    from graphdyn import obs

    f, sp = _packed_smoke(n=n, d=d, W=W, steps=steps)
    sp = f(sp)
    sp.block_until_ready()
    with obs.timed("obs.roofline.packed_rollout", n=n, d=d, W=W) as sw:
        for _ in range(iters):
            sp = f(sp)
        sp.block_until_ready()
    rate = n * W * 32 * steps * iters / sw.wall_s
    return _row("packed_rollout", rate, bw / packed_bytes_per_update(d),
                "spin-updates/s", bands)


def _bdcm_instance(n: int, c: float, seed: int):
    from graphdyn.models.entropy import remove_isolates
    from graphdyn.graphs import erdos_renyi_graph
    from graphdyn.ops.bdcm import BDCMData

    g = erdos_renyi_graph(n, c / (n - 1), seed=seed)
    sub, n_iso = remove_isolates(g)
    return BDCMData(sub, p=1, c=1), n, n_iso


def _bdcm_model_rate(data, bw: float) -> float:
    """Model edge-sweeps/s: bandwidth over the class-population-weighted
    per-edge byte cost."""
    total = sum(
        len(ec.idx) * bdcm_bytes_per_edge_sweep(ec.d, data.T)
        for ec in data.edge_classes
    )
    return bw / (total / max(data.num_directed, 1))


def measure_bdcm(bw: float, *, n: int = 2048, c: float = 3.0,
                 sweeps: int = 20, bands: dict | None = None) -> RooflineRow:
    """The serial XLA sweep core at a smoke ER instance."""
    import jax.numpy as jnp

    from graphdyn.ops.bdcm import make_sweep

    from graphdyn import obs

    data, _, _ = _bdcm_instance(n, c, seed=1)
    sweep = make_sweep(data, damp=0.1, use_pallas=False)
    chi = data.init_messages(0)
    lm = jnp.asarray(0.3, data.dtype)
    chi = sweep(chi, lm)
    chi.block_until_ready()
    with obs.timed("obs.roofline.bdcm_sweep", twoE=data.num_directed) as sw:
        for _ in range(sweeps):
            chi = sweep(chi, lm)
        chi.block_until_ready()
    rate = data.num_directed * sweeps / sw.wall_s
    return _row("bdcm_sweep", rate, _bdcm_model_rate(data, bw),
                "edge-sweeps/s", bands)


def _entropy_smoke_exec(*, n: int = 1024, c: float = 3.0, G: int = 4,
                        chunk_sweeps: int = 16):
    """``(ex, cells)``: the grouped entropy smoke program
    (``EntropyCellExec`` at the roofline shapes) — ONE builder, shared with
    :mod:`graphdyn.obs.memband` so the rate rows and the memory rows
    measure the same program."""
    from graphdyn.config import DynamicsConfig, EntropyConfig
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = EntropyConfig(dynamics=DynamicsConfig(p=1, c=1), eps=0.0,
                        max_sweeps=10 ** 9, damp=0.1)
    cells = [_bdcm_instance(n, c, seed=10 + k) for k in range(G)]
    ex = EntropyCellExec(cells, cfg, group_size=G,
                         chunk_sweeps=chunk_sweeps, kernel="xla")
    return ex, cells


def _entropy_smoke_state(ex, cells, G: int):
    """The chunk-loop initial carry for :func:`_entropy_smoke_exec`'s
    program: ``(chi, lm, active, delta, t)``."""
    import jax.numpy as jnp

    chi = ex.stack_chi([cell[0].init_messages(k) for k, cell in
                        enumerate(cells)])
    lm = jnp.full((G,), 0.3, ex.dtype)
    active = jnp.ones((G,), bool)
    delta = jnp.full((G,), jnp.inf, ex.dtype)
    t = jnp.zeros((G,), jnp.int32)
    return chi, lm, active, delta, t


def measure_entropy_chunk(bw: float, *, n: int = 1024, c: float = 3.0,
                          G: int = 4, chunk_sweeps: int = 16,
                          chunks: int = 2,
                          bands: dict | None = None) -> RooflineRow:
    """The grouped entropy cell chunk (``EntropyCellExec``) at a smoke
    cell group — the program the grouped ``entropy_grid`` default runs."""
    import jax.numpy as jnp

    from graphdyn import obs

    ex, cells = _entropy_smoke_exec(n=n, c=c, G=G,
                                    chunk_sweeps=chunk_sweeps)
    chi, lm, active, delta, t = _entropy_smoke_state(ex, cells, G)
    chi, t, delta = ex.fixed_point_chunk(chi, lm, active, delta, t)  # warm
    np.asarray(t)
    t = jnp.zeros((G,), jnp.int32)
    delta = jnp.full((G,), jnp.inf, ex.dtype)
    with obs.timed("obs.roofline.entropy_cell_chunk", G=G,
                   twoE_max=int(chi.shape[1])) as sw:
        for _ in range(chunks):
            chi, t, delta = ex.fixed_point_chunk(chi, lm, active, delta, t)
        np.asarray(t)
    # work = Σ_g (cell g's real edges) · (sweeps it advanced) — pad rows
    # past a cell's own 2E are inert and must not count as work
    work = float(np.sum(np.asarray(ex.stk.twoE)[:G] * np.asarray(t)))
    rate = work / sw.wall_s
    model = _bdcm_model_rate(cells[0][0], bw)
    return _row("entropy_cell_chunk", rate, model, "edge-sweeps/s", bands)


def run_obscheck(*, diag=None) -> list[RooflineRow]:
    """Measure every headline program against its band; emits one
    ``obs.roofline.<program>`` gauge per row when recording. Returns the
    rows — callers gate on ``row.ok``."""
    import jax

    from graphdyn import obs

    chip = chip_profile()
    if chip is not None:
        kind, bw, bands = chip
        anchor = f"chip:{kind}"
        if diag:
            diag(f"obscheck: chip roofline {kind}: HBM {bw / 1e9:.0f} GB/s "
                 "(committed anchor)")
    elif jax.default_backend() == "tpu":
        # a TPU kind with no committed CHIP_BANDS entry: the host-proxy
        # bandwidth + CPU-calibrated bands are meaningless for chip rates
        # (frac would blow past hi on every uncalibrated part, red gate,
        # no blessing path) — pass STRUCTURALLY with an explicit reason,
        # the memcheck null+reason contract; seed CHIP_BANDS to go live
        kind = jax.local_devices()[0].device_kind
        obs.gauge("obs.roofline.uncalibrated", 1, device_kind=kind)
        if diag:
            diag(f"obscheck: TPU device_kind {kind!r} has no committed "
                 "chip anchor (CHIP_BANDS) — structural pass; seed bands "
                 "for this part to go live")
        return []
    else:
        bands = None
        anchor = "host-proxy"
        bw = host_stream_bandwidth()
        if diag:
            diag(f"obscheck: host stream bandwidth {bw / 1e9:.2f} GB/s")
    rows = [measure_packed(bw, bands=bands), measure_bdcm(bw, bands=bands),
            measure_entropy_chunk(bw, bands=bands)]
    for row in rows:
        obs.gauge(f"obs.roofline.{row.program}", row.measured,
                  model=row.model, frac=row.frac, unit=row.unit,
                  ok=row.ok, anchor=anchor)
        if diag:
            verdict = "ok" if row.ok else "OUT OF BAND"
            diag(
                f"obscheck: {row.program}: measured {row.measured:.3e} "
                f"{row.unit}, model {row.model:.3e} → frac {row.frac:.3f} "
                f"(band [{row.lo:g}, {row.hi:g}]) {verdict}"
            )
    return rows
