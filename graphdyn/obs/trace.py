"""Aligned device-profiler capture (ARCHITECTURE.md "Runtime telemetry" →
device-side eyes).

The JSONL event ledger (:mod:`graphdyn.obs.recorder`) answers *where host
time went*; a ``jax.profiler`` trace answers *what the device was doing*.
Separately they cannot be joined — a chunk span whose ``wall_s ≫ cpu_s``
says "the span waited on the device", and the device timeline says "some
ops ran", but nothing ties the two together. This module makes them share
ONE vocabulary:

- :func:`profiling` starts/stops a ``jax.profiler`` trace around a scope
  (CLI ``--profile DIR`` on every command, ``GRAPHDYN_PROFILE=DIR`` env —
  mirroring ``--obs-ledger``/``GRAPHDYN_OBS``). The capture lands under
  ``DIR/plugins/profile/<ts>/`` (TensorBoard profile tab / Perfetto /
  the ``*.trace.json.gz`` chrome-trace dump).
- While profiling is active, every :class:`graphdyn.obs.recorder.Span`
  additionally opens a ``jax.profiler.TraceAnnotation`` named with the
  span's ledger **name path** (the ``report.py`` vocabulary:
  ``"run > pipeline.entropy.chunk"``) — so a ledger span and its slice of
  the device timeline carry the SAME name, and a chunk's wall≫cpu gap can
  be attributed to the actual device ops under the like-named annotation.
- The name path comes from a thread-local name stack maintained here
  (pushed/popped by ``Span.start``/``Span.stop``), so it works with or
  without a recorder installed: profiling without a ledger still names
  the timeline, and profiling + ledger yields matching vocabularies
  (tested against the profiler's trace-event output).

When profiling is OFF (the default), the hot path pays one module-global
``is None`` check per span and allocates nothing — the null-recorder
contract is untouched (regression-tested). graftlint **GD012** keeps bare
``jax.profiler`` calls out of the rest of the repo so this alignment is
the one profiling idiom.
"""

from __future__ import annotations

import contextlib
import os
import threading

ENV_VAR = "GRAPHDYN_PROFILE"

#: separator joining span names into a path — MUST match the ledger
#: report's aggregation key (graphdyn.obs.report.summarize)
PATH_SEP = " > "

_DIR: str | None = None
_local = threading.local()


def active() -> bool:
    """True while a :func:`profiling` scope is capturing — span sites open
    trace annotations only then (one global check otherwise)."""
    return _DIR is not None


def trace_dir() -> str | None:
    """The active capture directory (None when not profiling)."""
    return _DIR


def _stack() -> list:
    st = getattr(_local, "names", None)
    if st is None:
        st = _local.names = []
    return st


def current_path(name: str) -> str:
    """The annotation name ``name`` would get right now on this thread —
    the enclosing span names joined the way the ledger report joins them."""
    return PATH_SEP.join([*_stack(), name])


def push(name: str):
    """Open a ``TraceAnnotation`` for a span entering ``name`` (called by
    ``Span.start`` when :func:`active`). Returns the annotation handle for
    :func:`pop`."""
    import jax

    path = current_path(name)
    _stack().append(name)
    ann = jax.profiler.TraceAnnotation(path)
    ann.__enter__()
    return ann


def pop(ann) -> None:
    """Close a span's annotation (called by ``Span.stop``). LIFO by
    construction for ``with``-block spans; an abandoned imperative child
    (stop skipped by an exception) costs at worst a mislabeled path suffix
    on this thread's remaining annotations, never a crash."""
    st = _stack()
    if st:
        st.pop()
    ann.__exit__(None, None, None)


@contextlib.contextmanager
def profiling(logdir: str | None = None):
    """Capture a ``jax.profiler`` trace of the scope into ``logdir``.

    ``logdir=None`` falls back to the ``GRAPHDYN_PROFILE`` environment
    variable; when that is unset too the scope is a no-op (the common
    case — zero cost). Yields the active directory or None.

    Nested ``profiling`` scopes are an error only when both name a
    directory (one device trace per run — the profiler is a process-global
    singleton); re-entering with no directory inside an active scope keeps
    the outer capture, mirroring :func:`graphdyn.obs.recording`.
    """
    global _DIR
    if logdir is None and _DIR is None:
        # the env fallback applies only when nothing is capturing yet: a
        # dir-less re-entry inside an active scope must keep the outer
        # capture even when GRAPHDYN_PROFILE is set (it named the OUTER
        # trace), not trip the two-directory error below
        logdir = os.environ.get(ENV_VAR) or None
    if logdir is None or _DIR is not None:
        if logdir is not None and _DIR is not None:
            raise RuntimeError(
                "nested obs.trace.profiling() with an explicit directory — "
                f"one device trace per run (active: {_DIR!r})"
            )
        yield _DIR
        return
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    _DIR = logdir
    try:
        yield logdir
    finally:
        _DIR = None
        jax.profiler.stop_trace()
