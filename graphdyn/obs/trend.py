"""Cross-round bench **rate** trend gating (ARCHITECTURE.md "Runtime
telemetry" → trend gate).

PR 6's benchcheck diffs program *structure* (op/fusion fingerprints)
round-over-round; this module extends the same discipline to *rates*: the
new round's measured rows diff against the latest **comparable** committed
``BENCH_r*.json`` (same backend, same metric — a smoke row never compares
against a full-shape row, and a TPU row never against a CPU fallback), with
per-row tolerance bands. A silent runtime regression — the packed kernel
slowing 4× while its HLO fingerprint stays identical — fails the gate with
a pointed message naming the row, the ratio, and the band.

Update path, mirroring the fingerprint ledger's: round artifacts are
immutable history, so a **deliberate** rate change (new kernel default,
changed shapes) is blessed by committing the new row's rates to
``OBS_TREND.json`` (``python -m graphdyn.obs trend ROW.json --bless``) in
the reviewed PR; a drifted row matching the blessed ledger within band
passes (``trend_drift_blessed``), and the round-over-round baseline
refreshes when the next round persists its row.

Bands are intentionally loose (default: fail below ¼× or above 20× the
previous round) — container-load noise is real; the decade-scale absolute
anchor is :mod:`graphdyn.obs.roofline`.
"""

from __future__ import annotations

import glob
import json
import os
from typing import NamedTuple

#: rate rows diffed round-over-round: name -> (lo_frac, hi_frac) of the
#: previous round's value. Rows absent from either round (or null — an
#: explicit backend skip) are not comparable and produce no finding.
TREND_ROWS: dict[str, tuple[float, float]] = {
    "value": (0.25, 20.0),
    "packed_rate_natural_order": (0.25, 20.0),
    "packed_rate_bfs_order": (0.25, 20.0),
    "packed_rate_wide": (0.25, 20.0),
    "packed_rate_pallas": (0.25, 20.0),
    "int8_rate": (0.25, 20.0),
    "ensemble_rate": (0.25, 20.0),
    "ensemble_rate_serial": (0.25, 20.0),
    "entropy_cell_rate": (0.25, 20.0),
    "powerlaw_rate": (0.25, 20.0),
    "torch_cpu_rate": (0.25, 20.0),
}

LEDGER_NAME = "OBS_TREND.json"


class TrendFinding(NamedTuple):
    row: str
    code: str           # OBS201 regression | OBS202 implausible jump
    message: str


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _rate(row: dict, name: str):
    v = row.get(name)
    return v if isinstance(v, (int, float)) and v > 0 else None


def comparable(prev_row: dict, new_row: dict) -> bool:
    """Rows compare only within one (backend, metric) class: the metric
    string carries the workload shape (``..._n100000`` smoke vs
    ``..._n1000000`` full), and rates are backend-specific."""
    return bool(
        prev_row and new_row
        and prev_row.get("backend") == new_row.get("backend")
        and prev_row.get("metric") == new_row.get("metric")
    )


def diff_bench_rates(prev_row: dict, new_row: dict) -> list[TrendFinding]:
    """Per-row tolerance diff between two comparable bench rows. An error
    round (``value`` 0/absent — the wedged-relay artifacts r01/r03/r04) is
    not a baseline; incomparable rows return no findings."""
    if not comparable(prev_row, new_row) or not _rate(prev_row, "value"):
        return []
    findings = []
    for name, (lo, hi) in sorted(TREND_ROWS.items()):
        prev, new = _rate(prev_row, name), _rate(new_row, name)
        if prev is None or new is None:
            continue
        ratio = new / prev
        if ratio < lo:
            findings.append(TrendFinding(
                name, "OBS201",
                f"rate regressed {1 / ratio:.2f}x vs the previous round "
                f"({prev:.3e} -> {new:.3e}; band floor {lo:g}x). If "
                f"deliberate, bless the new rates: python -m graphdyn.obs "
                f"trend <row.json> --bless",
            ))
        elif ratio > hi:
            findings.append(TrendFinding(
                name, "OBS202",
                f"rate jumped {ratio:.2f}x vs the previous round "
                f"({prev:.3e} -> {new:.3e}; band ceiling {hi:g}x) — "
                f"implausible for an unchanged measurement; check the "
                f"timing fence / workload shape. If deliberate, bless "
                f"with --bless",
            ))
    return findings


def latest_comparable_round(new_row: dict, root: str | None = None,
                            pattern: str = "BENCH_r*.json"):
    """``(path, row)`` of the most recent committed round comparable to
    ``new_row`` (same backend + metric, non-error), or ``(None, None)``."""
    root = root or _repo_root()
    best = (None, None)
    for p in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(p) as fh:
                row = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        # round artifacts wrap the bench row under "parsed" (the capture
        # driver records cmd/rc/tail alongside); a bare row is accepted too
        if isinstance(row, dict) and isinstance(row.get("parsed"), dict):
            row = row["parsed"]
        if comparable(row, new_row) and _rate(row, "value"):
            best = (p, row)
    return best


def load_trend_ledger(path: str | None = None) -> dict | None:
    path = path or os.path.join(_repo_root(), LEDGER_NAME)
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def write_trend_ledger(row: dict, path: str | None = None) -> str:
    """Bless ``row``'s rates: commit them as the deliberate baseline. The
    ledger stores one entry per (backend, metric) class, so blessing a CPU
    smoke row never touches the chip row's baseline."""
    path = path or os.path.join(_repo_root(), LEDGER_NAME)
    ledger = load_trend_ledger(path) or {"classes": {}}
    key = f"{row.get('backend')}|{row.get('metric')}"
    ledger["classes"][key] = {
        "backend": row.get("backend"),
        "metric": row.get("metric"),
        "rates": {name: row[name] for name in TREND_ROWS
                  if _rate(row, name) is not None},
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ledger, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def trend_drift_blessed(new_row: dict, ledger: dict | None = None) -> bool:
    """Whether a row that drifted from the previous round matches the
    committed blessed baseline within band — i.e. the change was deliberate
    and reviewed (the rate analogue of graftcheck's
    ``bench_drift_blessed``)."""
    ledger = ledger if ledger is not None else load_trend_ledger()
    if not ledger or not new_row:
        return False
    entry = ledger.get("classes", {}).get(
        f"{new_row.get('backend')}|{new_row.get('metric')}"
    )
    if not entry:
        return False
    synthetic_prev = {"backend": new_row.get("backend"),
                      "metric": new_row.get("metric"), **entry["rates"]}
    return not diff_bench_rates(synthetic_prev, new_row)


def check_trend(new_row: dict, root: str | None = None,
                ledger: dict | None = None, diag=None):
    """The full gate: find the latest comparable round, diff, consult the
    blessing ledger. Returns ``(findings, status)`` where ``status`` is one
    of ``no_baseline`` / ``stable`` / ``blessed`` / ``drift`` — callers
    (benchcheck) fail only on ``drift`` but must assert the gate RAN."""
    path, prev = latest_comparable_round(new_row, root)
    if prev is None:
        if diag:
            diag(
                "trend gate: no comparable committed round "
                f"(backend={new_row.get('backend')}, "
                f"metric={new_row.get('metric')}) — baseline starts when "
                "such a round persists"
            )
        return [], "no_baseline"
    findings = diff_bench_rates(prev, new_row)
    if not findings:
        if diag:
            diag(f"trend gate: rates stable vs {os.path.basename(path)}")
        return [], "stable"
    if trend_drift_blessed(new_row, ledger):
        if diag:
            diag(
                f"trend gate: rate drift vs {os.path.basename(path)} is "
                f"LEDGER-BLESSED (row matches the committed {LEDGER_NAME})"
            )
        return findings, "blessed"
    if diag:
        for f in findings:
            diag(f"trend gate: RATE DRIFT vs {os.path.basename(path)}: "
                 f"{f.row}: {f.code} {f.message}")
    return findings, "drift"
