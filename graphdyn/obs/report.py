"""Ledger rendering: ``python -m graphdyn.obs report LEDGER``.

Aggregates a JSONL event ledger (:mod:`graphdyn.obs.recorder`) into a
span-tree / counter / gauge summary. Spans aggregate by their *name path*
(the chain of enclosing span names, e.g. ``run > pipeline.sa.chunk``), so a
span name reused under different parents reports separately; counters sum
``inc`` per name; gauges keep count/last/min/max/mean per name.

Output contract (PR-6): ``--format=json`` prints exactly ONE JSON document
on stdout; every diagnostic (torn-line notices etc.) goes to stderr.
"""

from __future__ import annotations

import sys

from graphdyn.obs.recorder import read_ledger


def summarize(events: list[dict]) -> dict:
    """The aggregate document: ``{"manifest", "spans", "counters",
    "gauges", "events"}`` (spans keyed by name path, parent-first)."""
    manifest = None
    by_id: dict[int, dict] = {}
    spans: dict[tuple, dict] = {}
    counters: dict[str, dict] = {}
    gauges: dict[str, dict] = {}

    span_events = [e for e in events if e.get("ev") == "span"]
    for e in span_events:
        if e.get("id") is not None:
            by_id[e["id"]] = e

    def path_of(e: dict) -> tuple:
        parts = [e.get("name", "?")]
        seen = set()
        parent = e.get("parent")
        while parent is not None and parent not in seen:
            seen.add(parent)
            pe = by_id.get(parent)
            if pe is None:
                break
            parts.append(pe.get("name", "?"))
            parent = pe.get("parent")
        return tuple(reversed(parts))

    for e in events:
        kind = e.get("ev")
        if kind == "manifest" and manifest is None:
            manifest = e.get("run", {})
        elif kind == "span":
            key = path_of(e)
            row = spans.setdefault(key, {
                "count": 0, "wall_s": 0.0, "cpu_s": 0.0, "max_wall_s": 0.0,
            })
            row["count"] += 1
            row["wall_s"] += float(e.get("wall_s", 0.0))
            row["cpu_s"] += float(e.get("cpu_s", 0.0))
            row["max_wall_s"] = max(row["max_wall_s"],
                                    float(e.get("wall_s", 0.0)))
        elif kind == "counter":
            row = counters.setdefault(e.get("name", "?"), {"total": 0,
                                                           "events": 0})
            row["total"] += int(e.get("inc", 1))
            row["events"] += 1
        elif kind == "gauge":
            v = e.get("value")
            row = gauges.setdefault(e.get("name", "?"), {
                "count": 0, "last": None, "min": None, "max": None,
                "sum": 0.0,
            })
            row["count"] += 1
            row["last"] = v
            if isinstance(v, (int, float)):
                row["min"] = v if row["min"] is None else min(row["min"], v)
                row["max"] = v if row["max"] is None else max(row["max"], v)
                row["sum"] += v
    for row in gauges.values():
        row["mean"] = (row["sum"] / row["count"]
                       if row["count"] and row["max"] is not None else None)
        del row["sum"]
    return {
        "manifest": manifest,
        "spans": {" > ".join(k): v
                  for k, v in sorted(spans.items())},
        "counters": counters,
        "gauges": gauges,
        "events": len(events),
    }


def render_text(doc: dict, out=sys.stdout) -> None:
    man = doc.get("manifest") or {}
    if man:
        ident = ", ".join(
            f"{k}={man[k]}" for k in
            ("cmd", "backend", "jax", "git_sha") if man.get(k) is not None
        )
        print(f"manifest: {ident or man}", file=out)
    if doc["spans"]:
        print(f"spans ({doc['events']} events):", file=out)
        for path, row in doc["spans"].items():
            depth = path.count(" > ")
            name = path.rsplit(" > ", 1)[-1]
            print(
                f"  {'  ' * depth}{name:<32} n={row['count']:<6} "
                f"wall={row['wall_s']:.3f}s cpu={row['cpu_s']:.3f}s "
                f"max={row['max_wall_s']:.3f}s",
                file=out,
            )
    if doc["counters"]:
        print("counters:", file=out)
        for name, row in sorted(doc["counters"].items()):
            print(f"  {name:<34} total={row['total']} "
                  f"(events={row['events']})", file=out)
    if doc["gauges"]:
        print("gauges:", file=out)
        for name, row in sorted(doc["gauges"].items()):
            stats = (f"last={row['last']!r}" if row["max"] is None else
                     f"last={row['last']:.4g} min={row['min']:.4g} "
                     f"max={row['max']:.4g} mean={row['mean']:.4g}")
            print(f"  {name:<34} n={row['count']} {stats}", file=out)


def load_summary(path: str, diag=None) -> dict:
    """``summarize`` over a ledger file; torn-final-line notices go through
    ``diag`` (stderr in the CLI), never stdout."""
    events, torn = read_ledger(path)
    if torn and diag:
        diag(f"obs report: {path} ends in a torn line (process died "
             "mid-write) — ignored, the prefix is the ledger")
    doc = summarize(events)
    doc["torn_lines"] = torn
    return doc
