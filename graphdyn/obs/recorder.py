"""The event recorder behind :mod:`graphdyn.obs` (ARCHITECTURE.md "Runtime
telemetry").

One run → one append-only **JSONL event ledger**: every line is a complete
JSON object, written and flushed atomically per event, so a preemption
(SIGTERM → exit 75) or even a hard kill leaves a parseable prefix — at worst
the final line is torn, and :func:`read_ledger` tolerates exactly that (plus
the sealed seam a requeued run leaves when it reopens the same path: the
torn fragment gets its own line, followed by the new run's manifest).

Event kinds (the ``ev`` field; ``schema`` is stamped in the manifest):

``manifest``
    One per run, first: ``{"ev": "manifest", "t": 0.0, "run": {...}}`` —
    backend, jax/python versions, git sha, argv, config, pid, wall-clock
    epoch. Everything needed to interpret the rest of the file offline.
``span``
    Emitted when a span *closes*: ``{"ev": "span", "name", "id", "parent",
    "t0", "t", "wall_s", "cpu_s", "attrs"}``. ``t0``/``t`` are
    monotonic-clock offsets from the recorder's start (ordering-safe across
    system clock steps), ``wall_s`` is the monotonic duration, ``cpu_s``
    the process-CPU time consumed inside the span (wall ≫ cpu = the span
    waited — on the device, the disk, or a lock). ``parent`` is the id of
    the enclosing span on the same thread (spans nest via a thread-local
    stack), or null at top level.
``counter``
    ``{"ev": "counter", "name", "inc", "attrs"}`` — monotonically
    accumulating occurrences (retry attempts, compile misses, fault hits).
    The report CLI sums ``inc`` per name.
``gauge``
    ``{"ev": "gauge", "name", "value", "attrs"}`` — point-in-time
    measurements (rates, utilization, latencies). The report CLI keeps
    last/min/max/mean per name.

The default recorder is :data:`NULL` — every method is a no-op and
``span()`` returns one shared, preallocated context manager, so an
uninstrumented run pays **one attribute check per site and allocates
nothing** (regression-tested). A real :class:`Recorder` is installed for a
scope by :func:`graphdyn.obs.recording` (CLI ``--obs-ledger`` /
``GRAPHDYN_OBS=PATH``).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

from graphdyn.obs import flight as _flight
from graphdyn.obs import trace as _trace

_MONO = time.monotonic
_CPU = time.process_time

#: ledger schema version, stamped in the manifest event
SCHEMA = 1

EVENT_KINDS = ("manifest", "span", "counter", "gauge")


class _NullSpan:
    """The shared no-op span: one instance serves every ``span()`` call on
    the null recorder (no per-call allocation), and its timing surface reads
    zero — callers that need real measurements regardless of recording use
    :func:`graphdyn.obs.timed`, which always measures."""

    __slots__ = ()

    wall_s = 0.0
    cpu_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def start(self):
        return self

    def stop(self):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """A measuring span. As a context manager it times its block; the
    imperative ``start()``/``stop()`` surface serves call sites that cannot
    be restructured into a ``with`` block (``stop()`` is idempotent).
    ``set(**attrs)`` attaches attributes any time before the span closes.
    When ``rec`` is None the span measures but emits nothing — the
    always-measuring :func:`graphdyn.obs.timed` handle."""

    __slots__ = ("rec", "name", "attrs", "id", "parent", "t0",
                 "_c0", "wall_s", "cpu_s", "_open", "_ann")

    def __init__(self, rec, name: str, attrs: dict):
        self.rec = rec
        self.name = name
        self.attrs = attrs
        self.id = None
        self.parent = None
        self.t0 = 0.0
        self._c0 = 0.0
        self.wall_s = 0.0
        self.cpu_s = 0.0
        self._open = False
        self._ann = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def start(self) -> "Span":
        if self.rec is not None:
            self.id, self.parent = self.rec._push_span()
        # device-profiler alignment: while a jax.profiler trace is being
        # captured (obs.trace.profiling), the span also opens a
        # TraceAnnotation named with its ledger name PATH, so the device
        # timeline and the JSONL ledger share one vocabulary
        if _trace.active():
            self._ann = _trace.push(self.name)
        self._open = True
        self._c0 = _CPU()
        self.t0 = _MONO()
        return self

    def stop(self) -> "Span":
        if not self._open:
            return self
        self.wall_s = _MONO() - self.t0
        self.cpu_s = _CPU() - self._c0
        self._open = False
        if self._ann is not None:
            _trace.pop(self._ann)
            self._ann = None
        if self.rec is not None:
            self.rec._pop_span(self)
        return self

    def __enter__(self) -> "Span":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class NullRecorder:
    """The default: does nothing, costs (almost) nothing. Hot paths hold the
    module-level accessor and pay one attribute check (``rec.enabled``) plus
    — for ``span`` — one shared-object return per site.

    Two always-on device-side hooks live *behind* the null object (both
    off the hot path's allocation budget):

    - while a :func:`graphdyn.obs.trace.profiling` capture is active,
      ``span()`` returns a measuring (non-emitting) :class:`Span` so the
      device timeline still gets the ledger-vocabulary trace annotations;
    - counter/gauge events are forwarded into the bounded flight-recorder
      ring (:mod:`graphdyn.obs.flight`) so a crash without a ledger is
      still diagnosable post-mortem. ``GRAPHDYN_FLIGHT=0`` disarms it.
    """

    enabled = False

    def span(self, name: str, **attrs):
        if _trace.active():
            return Span(None, name, attrs)
        return NULL_SPAN

    def counter(self, name: str, inc: int = 1, **attrs) -> None:
        if _flight.armed():
            _flight.record_counter(name, inc, attrs)
        return None

    def gauge(self, name: str, value, **attrs) -> None:
        if _flight.armed():
            _flight.record_gauge(name, value, attrs)
        return None

    def manifest(self, **fields):
        return None

    def event(self, doc: dict) -> None:
        return None

    def close(self) -> None:
        return None


NULL = NullRecorder()


class Recorder(NullRecorder):
    """Appends one JSON line per event to ``path``, flushed per event.

    Thread-safe (prefetch threads emit too): writes serialize on an RLock
    and the span stack is thread-local, so spans nest per thread. Attribute
    values that are not JSON types serialize via ``str`` — an attrs dict can
    carry numpy scalars or paths without the emit raising."""

    enabled = True

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # seal a torn tail before appending: a hard-killed prior run (same
        # GRAPHDYN_OBS path across a requeue) may have died mid-line, and
        # appending straight onto the fragment would glue this run's first
        # event to it — destroying the event and turning a tolerated
        # final-line tear into mid-file corruption
        sealed = False
        try:
            with open(path, "rb") as prev:
                prev.seek(-1, os.SEEK_END)
                sealed = prev.read(1) != b"\n"
        except (OSError, ValueError):
            pass                        # absent or empty file: nothing to seal
        # graftlint: disable-next-line=GD007  append-only JSONL ledger: each event is one flushed line, a torn final line is the designed failure mode (read_ledger tolerates it) — atomic-replace would destroy the append-per-event contract
        self._f = open(path, "a", encoding="utf-8")
        if sealed:
            self._f.write("\n")
            self._f.flush()
        self._lock = threading.RLock()
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._t0 = _MONO()

    # -- span bookkeeping (thread-local nesting) ------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "spans", None)
        if st is None:
            st = self._local.spans = []
        return st

    def _push_span(self):
        st = self._stack()
        parent = st[-1] if st else None
        sid = next(self._ids)
        st.append(sid)
        return sid, parent

    def _pop_span(self, span: Span) -> None:
        st = self._stack()
        # tolerate non-LIFO stops: truncate from this span's position, so a
        # descendant whose stop() was skipped (an exception unwound past an
        # imperative start()) is cleaned up when its enclosing span closes
        # instead of misparenting every later span on the thread
        if span.id in st:
            del st[st.index(span.id):]
        self.event({
            "ev": "span",
            "t": round(_MONO() - self._t0, 6),
            "name": span.name,
            "id": span.id,
            "parent": span.parent,
            "t0": round(span.t0 - self._t0, 6),
            "wall_s": round(span.wall_s, 6),
            "cpu_s": round(span.cpu_s, 6),
            **({"attrs": span.attrs} if span.attrs else {}),
        })

    # -- public surface -------------------------------------------------

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def counter(self, name: str, inc: int = 1, **attrs) -> None:
        self.event({
            "ev": "counter",
            "t": round(_MONO() - self._t0, 6),
            "name": name,
            "inc": inc,
            **({"attrs": attrs} if attrs else {}),
        })

    def gauge(self, name: str, value, **attrs) -> None:
        self.event({
            "ev": "gauge",
            "t": round(_MONO() - self._t0, 6),
            "name": name,
            "value": value,
            **({"attrs": attrs} if attrs else {}),
        })

    def manifest(self, **fields) -> dict:
        """Emit the per-run manifest event and return the ``run`` dict (the
        caller may hash it — ``bench.py`` persists that hash in its row)."""
        run = {
            "schema": SCHEMA,
            "pid": os.getpid(),
            "time_unix": time.time(),
            **fields,
        }
        self.event({"ev": "manifest", "t": round(_MONO() - self._t0, 6),
                    "run": run})
        return run

    def event(self, doc: dict) -> None:
        """Append one event: one complete JSON line, flushed — the
        truncation-safety unit of the ledger."""
        line = json.dumps(doc, separators=(",", ":"), default=str)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def read_ledger(path: str) -> tuple[list[dict], int]:
    """Parse a JSONL ledger into ``(events, torn_lines)``.

    Every well-formed line yields one event dict. A torn line (the process
    died mid-write) is counted, not fatal, in the two places a crash can
    legitimately leave one: the FINAL line, and a line immediately followed
    by a ``manifest`` event — the seam a requeued run seals when it reopens
    the same ledger path after a hard kill (``Recorder.__init__``) before
    stamping its manifest. A torn line anywhere else means the file is not
    append-only JSONL and raises. Events whose ``ev`` kind is unknown are
    kept (forward compatibility) — validators that want strictness filter
    on :data:`EVENT_KINDS`."""
    events: list[dict] = []
    torn = 0
    with open(path, encoding="utf-8") as f:
        lines = f.read().split("\n")
    if lines and lines[-1] == "":
        lines.pop()

    def _is_manifest(line: str) -> bool:
        try:
            return json.loads(line).get("ev") == "manifest"
        except (json.JSONDecodeError, AttributeError):
            return False

    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            events.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1 or _is_manifest(lines[i + 1]):
                torn += 1
                continue
            raise ValueError(
                f"{path}:{i + 1}: torn JSON line in the middle of the "
                f"ledger — not an append-only JSONL file"
            )
    return events, torn
