"""Device-memory telemetry + model bands (ARCHITECTURE.md "Runtime
telemetry" → memory bands).

:mod:`graphdyn.obs.roofline` anchors *rates* to the byte model; this module
does the same for *residency*. The TPU Ising literature (PAPERS.md
arXiv:1903.11714, arXiv:2110.02481) reports device-memory occupancy as a
first-class result next to the step rate — and our own ARCHITECTURE.md
derives exact byte models for the packed spin state, the stacked-BDCM
lattice (including the group-resident tilted ``A`` stack the Pallas kernel
holds in VMEM), and the entropy chunk working set. Nothing in the repo
previously *measured* any of them: a 2× residency regression (a lost
donation, an accidental f64 promotion, a materialized gather intermediate)
would surface only as an OOM at the full shape, in scarce chip time.

Two consumers:

- **Per-chunk gauges** (:func:`emit_memory_gauges`): the three grouped
  pipeline loops and the sharded rollout drivers emit
  ``obs.mem.bytes_in_use`` / ``obs.mem.peak`` gauges from
  ``Device.memory_stats()`` at every chunk boundary while recording. On
  backends whose devices expose no usable stats (the CPU container:
  ``memory_stats()`` exists but returns None) ONE ``obs.mem.unavailable``
  gauge per recording scope carries the reason — never silence, never a
  fake 0.
- **The memcheck gate** (:func:`run_memcheck`, ``python -m graphdyn.obs
  memcheck``, the ``scripts/lint.sh`` memcheck step,
  ``GRAPHDYN_SKIP_MEMCHECK=1`` to skip): measured peak bytes against the
  byte models, the way roofline treats rates. On a stats-less backend
  every row reports an explicit ``null`` + reason and the gate passes
  *structurally* — the committed bands go live the first chip round, no
  code change needed.

Bands are deliberately wide (the measured peak includes XLA temp buffers,
warmup double-buffering, and whatever else the process allocated first);
like the roofline bands they catch multiples, not percents, and a
deliberate model change updates :data:`MEM_BANDS` and the ARCHITECTURE.md
table in the same reviewed PR.
"""

from __future__ import annotations

from typing import NamedTuple

#: peak-bytes / model-bytes bands per program. PROVISIONAL seeds: the CPU
#: container cannot calibrate them (no usable memory_stats), so lo/hi are
#: set from the model's construction — the measured peak must at least
#: cover the modeled resident state (lo) and a >16x blowup means a
#: duplicated state class, not allocator slop (hi). The first chip round
#: that runs memcheck re-centers them (update workflow: ARCHITECTURE.md).
MEM_BANDS: dict[str, tuple[float, float]] = {
    "packed_state": (0.5, 16.0),
    "bdcm_stack": (0.5, 16.0),
    "entropy_cell_chunk": (0.25, 16.0),
    # per-shard halo layout: the band divisor is the WIDEST shard's model
    # (devices hold one shard each; the peak is per-device); lo is loose —
    # the shard state is a fraction of the process peak when the P=1
    # baseline ran first in the same process
    "halo_shard": (0.25, 16.0),
    # degree-bucketed layout: lo is loose for the same shared-process
    # reason as halo_shard (the padded baseline usually ran first)
    "bucketed_state": (0.25, 16.0),
    # out-of-core streamed layout: the model charges only the two
    # resident chunks, so lo is very loose (resident baselines usually
    # ran first in the same process and dominate the peak) and hi is wide
    # until the first chip round calibrates it
    "streamed_chunk": (0.05, 64.0),
}


# ---------------------------------------------------------------------------
# byte models (ARCHITECTURE.md derivations)
# ---------------------------------------------------------------------------


def packed_state_bytes(n: int, d: int, W: int) -> int:
    """Resident device state of the packed rollout: the ``uint32[n, W]``
    spin words (32 replicas/word), the ``int32[n, d]`` neighbor table, and
    the ``int32[n]`` degree vector."""
    return 4 * n * W + 4 * n * d + 4 * n


def halo_shard_bytes(n_local: int, n_ghost: int, W: int) -> int:
    """Resident packed spin words of ONE halo shard
    (:mod:`graphdyn.parallel.halo`): the owned rows plus the ghost rows it
    refreshes each step — ``4·n_local·W + 4·n_ghost·W`` bytes (the trash/
    zero bookkeeping rows are two rows, noise at any real shape; the
    neighbor table adds ``4·n_local·dmax`` exactly as in
    :func:`packed_state_bytes` and is charged there). The GHOST term is
    also the shard's per-step exchange traffic — residency and DCN bytes
    share one model (``HaloTables.halo_bytes_per_step``)."""
    return 4 * n_local * W + 4 * n_ghost * W


def bucketed_state_bytes(n: int, W: int, table_entries: int) -> int:
    """Resident device state of the degree-bucketed rollout
    (:mod:`graphdyn.ops.bucketed`): the ``uint32[n, W]`` spin words, the
    bucketed neighbor blocks (``table_entries = Σ_b n_b·2^b`` int32 slots
    — :attr:`graphdyn.graphs.DegreeBuckets.table_entries`), and the
    per-bucket degree vectors (``n`` int32 total). The padded model
    charges ``4·n·dmax`` for the table; this one charges the tight
    blocks, which :func:`bucketed_table_entries_bound` caps at
    ``4E + n`` — edge-count proportional, the whole point of the layout.
    Serve admission prices ``solver='bucketed'`` jobs with THIS model —
    and ONLY those: this formula describes the bucketed rollout's
    resident set, not the fused annealer's (whose padded-dmax/χ tables
    are labeling-invariant), so pricing a fused job with it would
    under-admit by the hub factor."""
    return 4 * n * W + 4 * table_entries + 4 * n


def bucketed_table_entries_bound(n: int, n_edges: int) -> int:
    """Upper bound on :attr:`DegreeBuckets.table_entries` from the edge
    count alone (what admission has before any layout exists): each node's
    block row rounds its degree up to a power of two, at most doubling it
    except degree-0/1 rows which cost one slot — so
    ``Σ_b n_b·2^b ≤ Σ_v max(2·deg(v), 1) ≤ 4·E + n``."""
    return 4 * n_edges + n


def streamed_chunk_bytes(C: int, M: int, width: int, W: int) -> int:
    """Device-resident bytes of ONE streamed chunk's step
    (:mod:`graphdyn.ops.streamed`): the gathered state slab
    ``uint32[M+1, W]`` (owned ∪ neighbor rows + the ghost zero row), the
    slab-local neighbor table ``int32[C, width]``, the degree/self-row
    vectors (``8·C``), and the ``uint32[C, W]`` output block. The ONLY
    term that scales with the whole graph is host RAM — this is the
    formula that deletes the device-memory cliff."""
    return 4 * (M + 1) * W + 4 * C * width + 8 * C + 4 * C * W


def streamed_state_bytes(n: int, W: int, n_edges: int, chunks: int) -> int:
    """Modeled peak DEVICE bytes of the streamed rollout at ``chunks``
    chunks: two chunks resident at once (active + prefetched) under the
    double-buffered lane, each charged :func:`streamed_chunk_bytes` at
    the balanced per-chunk shape — ``C = ⌈n/K⌉`` owned rows, table slots
    ``e_c = ⌈(4E+n)/K⌉`` (the :func:`bucketed_table_entries_bound` split
    across chunks; the degree-ascending chunk walk keeps the power-of-two
    row padding within the same 2× the bucketed layout pays), slab rows
    ``M ≤ C + e_c`` (every gathered neighbor row is some table slot).
    Serve admission prices ``solver='streamed'`` jobs with THIS model —
    the per-chunk device term is what turns "refused: oversized" into
    "admitted: streamed"."""
    if chunks < 1:
        raise ValueError(f"chunks must be >= 1, got {chunks}")
    C = -(-n // chunks)
    e_c = -(-bucketed_table_entries_bound(n, n_edges) // chunks)
    return 2 * streamed_chunk_bytes(C, C + e_c, 1, W) + 4 * e_c - 4 * C
    # NOTE on the width term: streamed_chunk_bytes charges 4·C·width for
    # the table; at the balanced shape that term IS 4·e_c, so the call
    # above passes width=1 and the correction re-prices it exactly.


def streamed_min_bytes(dmax: int, W: int) -> int:
    """The feasibility floor of the streamed layout: the device bytes of
    a single-node chunk holding the worst declared hub (slab of ``2 +
    dmax`` rows, one power-of-two padded table row). Double-buffered,
    ``2×`` this must fit the budget or no chunking can help — the check
    admission runs before sizing the chunk count."""
    width = 1 << max(int(dmax) - 1, 0).bit_length()
    return streamed_chunk_bytes(1, 1 + dmax, width, W)


def streamed_chunk_count(n: int, W: int, n_edges: int,
                         budget_bytes: int) -> int | None:
    """The smallest chunk count whose :func:`streamed_state_bytes` fits
    ``budget_bytes`` — or None when even one-node chunks cannot (the
    caller refuses with the modeled floor). Monotone in K, so a doubling
    walk + binary search."""
    if streamed_state_bytes(n, W, n_edges, max(n, 1)) > budget_bytes:
        return None
    lo, hi = 1, 1
    while streamed_state_bytes(n, W, n_edges, hi) > budget_bytes:
        lo, hi = hi, min(hi * 2, max(n, 1))
    while lo < hi:
        mid = (lo + hi) // 2
        if streamed_state_bytes(n, W, n_edges, mid) > budget_bytes:
            lo = mid + 1
        else:
            hi = mid
    return hi


def stacked_bdcm_bytes(stk) -> int:
    """Resident bytes of a :class:`graphdyn.ops.bdcm.StackedBDCM` cell
    group on device: the ``[G, 2E_max+1, K, K]`` chi stack (ghost row
    included), the group-resident tilted ``A`` stack (``G·K²·M_d`` per
    union degree class — the same term the VMEM model charges the Pallas
    kernel, ``4·G·K²·M``), and the int64 index tables."""
    import numpy as np

    G, K = stk.G, stk.K
    itemsize = np.dtype(stk.dtype).itemsize
    chi = G * (stk.twoE_max + 1) * K * K * itemsize
    a_stack = sum(
        G * K * K * A.shape[-1] * itemsize
        for (_, _, _, A) in stk.edge_classes
    )
    tables = sum(
        8 * (idx.size + in_edges.size)
        for (_, idx, in_edges, _) in stk.edge_classes
    ) + 8 * stk.leaf_idx.size
    return chi + a_stack + tables


def entropy_chunk_bytes(stk) -> int:
    """Working set of one grouped entropy chunk
    (``EntropyCellExec.fixed_point_chunk``): the chi stack double-buffered
    (the chunk donates its carry, so old + new are both live at the swap),
    the resident stack above, plus the widest degree class's DP scratch
    ``[G, Ed, K, M]`` (classes run sequentially inside a sweep, so the
    scratch peak is the max over classes, not the sum)."""
    import numpy as np

    G, K = stk.G, stk.K
    itemsize = np.dtype(stk.dtype).itemsize
    chi = G * (stk.twoE_max + 1) * K * K * itemsize
    scratch = max(
        (G * idx.shape[1] * K * A.shape[-1] * itemsize
         for (_, idx, _, A) in stk.edge_classes),
        default=0,
    )
    return stacked_bdcm_bytes(stk) + chi + scratch


# ---------------------------------------------------------------------------
# device stats
# ---------------------------------------------------------------------------


def device_memory_stats(device=None) -> tuple[dict | None, str | None]:
    """``(stats, None)`` from ``device.memory_stats()``, or ``(None,
    reason)`` when the backend exposes none — the CPU container's devices
    HAVE the method but return None, and both shapes get an explicit
    reason (the null+reason contract: a skip must be unmistakable from a
    measured 0)."""
    import jax

    device = device or jax.local_devices()[0]
    fn = getattr(device, "memory_stats", None)
    if fn is None:
        return None, (
            f"backend {device.platform!r} devices expose no memory_stats()"
        )
    try:
        stats = fn()
    except Exception as e:  # noqa: BLE001 — telemetry must not kill the run
        return None, (
            f"memory_stats() failed on backend {device.platform!r}: "
            f"{str(e)[:120]}"
        )
    if not stats:
        return None, (
            f"backend {device.platform!r} memory_stats() returned none "
            "(host-memory backend)"
        )
    return stats, None


def emit_memory_gauges(**attrs) -> None:
    """Emit ``obs.mem.bytes_in_use`` / ``obs.mem.peak`` gauges from the
    default device's memory stats — the per-chunk call of the pipeline
    loops and the sharded rollout drivers. Free when not recording (one
    enabled check); on a stats-less backend emits ONE
    ``obs.mem.unavailable`` gauge per recording scope carrying the
    reason."""
    from graphdyn import obs

    if not obs.enabled():
        return
    stats, reason = device_memory_stats()
    if stats is None:
        # once per recording scope: the marker lives ON the recorder (an
        # id()-keyed module global could alias a later scope's recorder at
        # a recycled address and silently swallow its reason gauge)
        rec = obs.current()
        if not getattr(rec, "_memband_unavailable_warned", False):
            rec._memband_unavailable_warned = True
            obs.gauge("obs.mem.unavailable", 1, reason=reason, **attrs)
        return
    if "bytes_in_use" in stats:
        obs.gauge("obs.mem.bytes_in_use", int(stats["bytes_in_use"]), **attrs)
    if "peak_bytes_in_use" in stats:
        obs.gauge("obs.mem.peak", int(stats["peak_bytes_in_use"]), **attrs)


def peak_hbm_bytes() -> tuple[int | None, str | None]:
    """``(peak_bytes_in_use, None)`` or ``(None, reason)`` — the bench.py
    row column (null + reason on CPU, never silent)."""
    stats, reason = device_memory_stats()
    if stats is None:
        return None, reason
    peak = stats.get("peak_bytes_in_use")
    if peak is None:
        return None, "memory_stats() carries no peak_bytes_in_use"
    return int(peak), None


# ---------------------------------------------------------------------------
# memcheck
# ---------------------------------------------------------------------------


class MemRow(NamedTuple):
    program: str
    measured: int | None    # peak bytes (None: stats unavailable + reason)
    model: float            # modeled bytes
    frac: float | None      # measured / model
    lo: float
    hi: float
    reason: str | None      # why measured is None (the structural pass)

    @property
    def ok(self) -> bool:
        # a stats-less backend passes STRUCTURALLY: the row exists, names
        # its reason, and the band goes live the first round with stats
        if self.frac is None:
            return self.reason is not None
        return self.lo <= self.frac <= self.hi


def _bands(program: str) -> tuple[float, float]:
    """Band lookup: the hand-model bands here, the ``derived:*``
    cross-check rows' bands next to the models they evaluate
    (:data:`graphdyn.analysis.graftcost.DERIVED_MEM_BANDS`)."""
    if program in MEM_BANDS:
        return MEM_BANDS[program]
    from graphdyn.analysis import graftcost

    return graftcost.DERIVED_MEM_BANDS[program]


def _row(program: str, measured: int | None, model: float,
         reason: str | None = None) -> MemRow:
    lo, hi = _bands(program)
    frac = (measured / model) if (measured is not None and model) else None
    return MemRow(program, measured, model, frac, lo, hi, reason)


def _smoke_exec(n: int = 1024, c: float = 3.0, G: int = 4):
    """The grouped entropy smoke program, built by roofline's SHARED
    builder (so the rate rows and these memory rows measure the same
    program) and run for one chunk so the peak includes it."""
    import numpy as np

    from graphdyn.obs.roofline import _entropy_smoke_exec, _entropy_smoke_state

    ex, cells = _entropy_smoke_exec(n=n, c=c, G=G, chunk_sweeps=8)
    chi, lm, active, delta, t = _entropy_smoke_state(ex, cells, G)
    chi, t, delta = ex.fixed_point_chunk(chi, lm, active, delta, t)
    np.asarray(t)                       # drain: the peak includes the chunk
    return ex


def run_memcheck(*, diag=None) -> list[MemRow]:
    """Measure every modeled program's device-memory peak against its band
    — or, on a stats-less backend, emit the structural null+reason rows
    without running anything (the models still evaluate, so a model-code
    regression fails here even on CPU). Returns the rows; callers gate on
    ``row.ok``."""
    stats, reason = device_memory_stats()
    if stats is None:
        # structural pass: models evaluated at the smoke shapes, measured
        # explicitly unavailable with the backend's reason
        from graphdyn.ops.bdcm import stack_bdcm
        from graphdyn.obs.roofline import _bdcm_instance

        n, d, W = 32768, 3, 8
        stk = stack_bdcm([
            _bdcm_instance(1024, 3.0, seed=10 + k)[0] for k in range(4)
        ])
        rows = [
            _row("packed_state", None, packed_state_bytes(n, d, W), reason),
            _row("bdcm_stack", None, stacked_bdcm_bytes(stk), reason),
            _row("entropy_cell_chunk", None, entropy_chunk_bytes(stk),
                 reason),
            _row("halo_shard", None, _halo_smoke_model(W=W), reason),
            _row("bucketed_state", None, _bucketed_smoke_model(W=W),
                 reason),
            _row("streamed_chunk", None, _streamed_smoke_model(W=W),
                 reason),
            *_derived_rows(reason),
        ]
    else:
        rows = [_measure_packed(), *_measure_bdcm_rows(), _measure_halo(),
                _measure_bucketed(), _measure_streamed(),
                *_derived_rows(None)]
    from graphdyn import obs

    for row in rows:
        obs.gauge(f"obs.memband.{row.program}", row.measured,
                  model=row.model, frac=row.frac, ok=row.ok,
                  **({"reason": row.reason} if row.reason else {}))
        if diag:
            if row.measured is None:
                diag(f"memcheck: {row.program}: model {row.model:.3e} B, "
                     f"measured null ({row.reason}) — structural pass")
            else:
                verdict = "ok" if row.ok else "OUT OF BAND"
                diag(f"memcheck: {row.program}: measured peak "
                     f"{row.measured:.3e} B, model {row.model:.3e} B -> "
                     f"frac {row.frac:.3f} (band [{row.lo:g}, {row.hi:g}]) "
                     f"{verdict}")
    return rows


def _derived_rows(reason: str | None) -> list[MemRow]:
    """Cross-check rows against the HLO-*derived* peak models of
    :mod:`graphdyn.analysis.graftcost` (ARCHITECTURE.md "Cost-model
    contracts"): the committed ``COST_LEDGER.json`` fit, evaluated at a
    canonical-family shape well beyond the calibration points — so the
    hand bands above and the derived bands must BOTH hold on a chip.
    ``reason`` is the stats-unavailability reason (the structural pass);
    when stats are live the packed row runs the canonical program and
    measures its peak, while the fused chunk (whose carry has no
    standalone runtime harness) stays a structural row with its reason."""
    from graphdyn.analysis import graftcost

    rows = []
    for program, entry, n in (
        ("derived:packed_rollout", "packed_rollout", 32768),
        ("derived:bucketed_rollout", "bucketed_rollout", 32768),
        ("derived:fused_anneal", "fused_anneal", 4096),
    ):
        model, mreason = graftcost.derived_peak_bytes(entry, n)
        if model is None:
            rows.append(_row(program, None, 0.0, mreason))
            continue
        if reason is not None:
            rows.append(_row(program, None, model, reason))
            continue
        if entry == "packed_rollout":
            measured, why = _measure_derived_packed(n)
            rows.append(_row(program, measured, model, why))
        elif entry == "bucketed_rollout":
            measured, why = _measure_derived_bucketed(n)
            rows.append(_row(program, measured, model, why))
        else:
            rows.append(_row(
                program, None, model,
                "the canonical fused chunk's loop carry has no standalone "
                "runtime harness — structural check only",
            ))
    return rows


def _measure_derived_packed(n: int) -> tuple[int | None, str | None]:
    """Peak bytes through the CANONICAL packed-rollout family (R=128 →
    W=4, steps=4 — the exact program graftcost's models are fitted on,
    at a size far outside the fit range)."""
    import jax.numpy as jnp
    import numpy as np

    from graphdyn.graphs import random_regular_graph
    from graphdyn.ops.packed import pack_spins, packed_rollout

    g = random_regular_graph(n, 3, seed=0)
    rng = np.random.default_rng(0)
    s = (2 * rng.integers(0, 2, size=(128, g.n)) - 1).astype(np.int8)
    out = packed_rollout(
        jnp.asarray(g.nbr), jnp.asarray(g.deg), jnp.asarray(pack_spins(s)),
        steps=4,
    )
    np.asarray(out)                     # drain: the peak includes the run
    return peak_hbm_bytes()


def _measure_derived_bucketed(n: int) -> tuple[int | None, str | None]:
    """Peak bytes through the CANONICAL bucketed-rollout family (power-law
    γ=2.5 dmin=2 seed=0, W=4, steps=4 — the exact program graftcost's
    models are fitted on, at a size far outside the fit range)."""
    import numpy as np

    from graphdyn.graphs import degree_buckets, powerlaw_graph
    from graphdyn.ops.bucketed import bucketed_rollout

    b = degree_buckets(powerlaw_graph(n, gamma=2.5, dmin=2, seed=0))
    out = bucketed_rollout(b, np.zeros((n, 4), np.uint32), 4)
    np.asarray(out)                     # drain: the peak includes the run
    return peak_hbm_bytes()


def _measure_packed(*, n: int = 32768, d: int = 3, W: int = 8,
                    steps: int = 8) -> MemRow:
    """Peak bytes through the packed-rollout smoke (roofline's SHARED
    builder — same program as the rate row)."""
    from graphdyn.obs.roofline import _packed_smoke

    f, sp = _packed_smoke(n=n, d=d, W=W, steps=steps)
    sp = f(sp)
    sp.block_until_ready()
    peak, reason = peak_hbm_bytes()
    return _row("packed_state", peak, packed_state_bytes(n, d, W), reason)


def _halo_smoke_tables(n: int = 8192, P: int = 2):
    """The halo smoke partition's tables (d=3 RRG — the headline degree)."""
    from graphdyn.graphs import partition_graph, random_regular_graph
    from graphdyn.parallel.halo import build_halo_tables

    g = random_regular_graph(n, 3, seed=0)
    part = partition_graph(g, P, seed=0)
    return g, part, build_halo_tables(g, part)


def _halo_smoke_model(*, W: int, n: int = 8192, P: int = 2) -> float:
    """The widest shard's ``halo_shard`` model bytes at the smoke shape."""
    _, _, tables = _halo_smoke_tables(n, P)
    return float(max(
        halo_shard_bytes(int(tables.counts[p]),
                         int(tables.ghost_counts[p]), W)
        for p in range(tables.P)
    ))


def _measure_halo(*, n: int = 8192, P: int = 2, W: int = 8,
                  steps: int = 8) -> MemRow:
    """Peak bytes through a 2-shard halo rollout against the widest
    shard's model. Needs a 2-device mesh; a single-device process emits
    the null+reason row (structural pass) instead of borrowing the packed
    program's peak."""
    from graphdyn.parallel.mesh import device_pool

    try:
        device_pool(P)
    except RuntimeError as e:
        return _row("halo_shard", None, _halo_smoke_model(W=W, n=n, P=P),
                    f"halo_shard needs {P} devices: {e}")
    import numpy as np

    from graphdyn.parallel.halo import HaloProgram

    g, part, tables = _halo_smoke_tables(n, P)
    prog = HaloProgram(g, part, steps=steps, tables=tables)
    out = prog.advance(prog.place(np.zeros((n, W), np.uint32)))
    np.asarray(out)                     # drain: the peak includes the run
    peak, reason = peak_hbm_bytes()
    model = max(
        halo_shard_bytes(int(tables.counts[p]),
                         int(tables.ghost_counts[p]), W)
        for p in range(tables.P)
    )
    return _row("halo_shard", peak, model, reason)


def _bucketed_smoke_buckets(n: int = 4096):
    """The bucketed smoke layout: a seeded power-law graph (the family the
    layout exists for) at a shape small enough for the structural pass."""
    from graphdyn.graphs import degree_buckets, powerlaw_graph

    g = powerlaw_graph(n, gamma=2.5, dmin=2, seed=0)
    return g, degree_buckets(g)


def _bucketed_smoke_model(*, W: int, n: int = 4096) -> float:
    """``bucketed_state`` model bytes at the smoke shape."""
    _, b = _bucketed_smoke_buckets(n)
    return float(bucketed_state_bytes(b.n, W, b.table_entries))


def _measure_bucketed(*, n: int = 4096, W: int = 8, steps: int = 8) -> MemRow:
    """Peak bytes through the bucketed rollout on the power-law smoke."""
    import numpy as np

    from graphdyn.ops.bucketed import bucketed_rollout

    g, b = _bucketed_smoke_buckets(n)
    out = bucketed_rollout(b, np.zeros((n, W), np.uint32), steps)
    np.asarray(out)                     # drain: the peak includes the run
    peak, reason = peak_hbm_bytes()
    return _row("bucketed_state", peak,
                bucketed_state_bytes(b.n, W, b.table_entries), reason)


def _streamed_smoke_plan(n: int = 4096, chunks: int = 8):
    """The streamed smoke layout: the SAME seeded power-law family as the
    bucketed smoke (the workload class the streaming path serves), split
    into a fixed chunk count."""
    from graphdyn.ops.streamed import build_stream_plan

    g, _ = _bucketed_smoke_buckets(n)
    return g, build_stream_plan(g, W=8, n_chunks=chunks)


def _streamed_smoke_model(*, W: int, n: int = 4096, chunks: int = 8) -> float:
    """``streamed_chunk`` model bytes at the smoke shape: the two largest
    REAL chunks of the smoke plan (the admission-side
    :func:`streamed_state_bytes` models the balanced split; memcheck
    holds the band against the plan that actually ran)."""
    from graphdyn.ops.streamed import plan_device_bytes

    _, plan = _streamed_smoke_plan(n, chunks)
    return float(plan_device_bytes(plan, W))


def _measure_streamed(*, n: int = 4096, chunks: int = 8, W: int = 8,
                      steps: int = 8) -> MemRow:
    """Peak bytes through the streamed rollout on the power-law smoke."""
    import numpy as np

    from graphdyn.ops.streamed import plan_device_bytes, streamed_rollout

    g, plan = _streamed_smoke_plan(n, chunks)
    streamed_rollout(g, np.zeros((n, W), np.uint32), steps, plan=plan)
    peak, reason = peak_hbm_bytes()
    return _row("streamed_chunk", peak, plan_device_bytes(plan, W), reason)


def _measure_bdcm_rows() -> list[MemRow]:
    """Peak bytes through the grouped entropy chunk, against both BDCM
    models (resident stack floor AND chunk working set — one program, two
    calibration anchors)."""
    ex = _smoke_exec()
    peak, reason = peak_hbm_bytes()
    return [
        _row("bdcm_stack", peak, stacked_bdcm_bytes(ex.stk), reason),
        _row("entropy_cell_chunk", peak, entropy_chunk_bytes(ex.stk),
             reason),
    ]
